// Standalone ASan/UBSan harness for the shm object store — the
// build:asan/build:tsan analog for this repo's native layer (reference:
// .bazelrc build:asan + src/ray/object_manager plasma store tests run
// under sanitizers in CI). Built by native/build.py with
// -fsanitize=address,undefined and run as a subprocess by
// tests/test_sanitizers.py; any heap-buffer-overflow / UB aborts the
// process with a nonzero exit.
//
// Exercises: create/seal/get/release/delete round trips, abort of
// unsealed objects, LRU eviction under pressure, cross-handle open,
// multi-threaded hammering of a single-stripe arena (the v1 regime),
// concurrent create/seal/get/evict/stats across >=4 stripes (the
// lock-striped regime: lock-free seal + seqlock stats under fire),
// round-robin fallback when a home stripe is pinned full, and — when
// invoked as its own crash child — SIGKILL mid-rt_create while holding a
// stripe mutex, which the parent must repair via EOWNERDEAD.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <sys/wait.h>
#include <unistd.h>

extern "C" {
void* rt_store_create(const char* path, uint64_t size, int stripes);
void* rt_store_open(const char* path);
void rt_store_close(void* hs);
uint8_t* rt_store_base(void* hs);
uint32_t rt_num_stripes(void* hs);
int64_t rt_create(void* hs, const uint8_t* id, uint64_t data_size,
                  uint64_t meta_size, int evictable);
int rt_seal(void* hs, const uint8_t* id);
int64_t rt_get(void* hs, const uint8_t* id, uint64_t* data_size,
               uint64_t* meta_size, int pin);
int rt_release(void* hs, const uint8_t* id);
int rt_contains(void* hs, const uint8_t* id);
int rt_delete(void* hs, const uint8_t* id);
int rt_abort(void* hs, const uint8_t* id);
uint64_t rt_evict(void* hs, uint64_t bytes);
uint64_t rt_evict_stripe(void* hs, uint32_t stripe, uint64_t bytes);
void rt_stats(void* hs, uint64_t* out);
void rt_stripe_stats(void* hs, uint32_t stripe, uint64_t* out);
uint64_t rt_list_stripe(void* hs, uint32_t stripe, uint8_t* out,
                        uint64_t max_n);
void rt_write_parallel(void* dst, const void* src, uint64_t n, int threads);
uint64_t rt_gc_unsealed(void* hs, uint64_t max_age_sec);
uint64_t rt_max_alloc_bytes(void* hs);
int64_t rt_create_spanning(void* hs, const uint8_t* id, uint64_t data_size,
                           uint64_t meta_size, int evictable);
int rt_is_span(void* hs, const uint8_t* id);
void rt_span_stats(void* hs, uint64_t* out);
}

static constexpr int kIdLen = 20;

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,         \
              __LINE__, #cond);                                        \
      return 1;                                                        \
    }                                                                  \
  } while (0)

static void make_id(uint8_t* id, uint64_t n) {
  memset(id, 0, kIdLen);
  memcpy(id, &n, sizeof(n));
}

// Crash-child mode: open the store and put objects until the chaos hook
// (RAY_TPU_TESTING_SHM_FAILURE=shm_create=N, armed by the parent) SIGKILLs
// this process inside rt_create with the stripe mutex held.
static int crash_child(const char* path) {
  void* h = rt_store_open(path);
  if (!h) return 7;
  uint8_t* b = rt_store_base(h);
  for (uint64_t n = 0; n < 1000; n++) {
    uint8_t id[kIdLen];
    make_id(id, 900000 + n);
    int64_t o = rt_create(h, id, 4096, 0, 1);
    if (o > 0) {
      memset(b + o, 0x5a, 4096);
      rt_seal(h, id);
    }
  }
  return 8;  // survived 1000 creates: the chaos hook never fired
}

// Span crash-child mode: attempt a spanning create with the
// shm_span_create chaos hook armed — dies holding the span mutex AND a
// member stripe's mutex, mid-claim. Parent repairs via EOWNERDEAD on
// both levels.
static int span_crash_child(const char* path) {
  void* h = rt_store_open(path);
  if (!h) return 7;
  uint8_t id[kIdLen];
  make_id(id, 950001);
  rt_create_spanning(h, id, 6 << 20, 0, 1);
  return 8;  // survived: the chaos hook never fired
}

int main(int argc, char** argv) {
  std::string path = argc > 1 ? argv[1] : "/dev/shm/rt_selftest";
  if (argc > 2 && strcmp(argv[2], "crashchild") == 0)
    return crash_child(path.c_str());
  if (argc > 2 && strcmp(argv[2], "spancrashchild") == 0)
    return span_crash_child(path.c_str());

  const uint64_t kArena = 4 << 20;  // 4 MiB
  void* s = rt_store_create(path.c_str(), kArena, 1);  // v1 regime
  CHECK(s != nullptr);
  CHECK(rt_num_stripes(s) == 1);

  // --- round trip -------------------------------------------------------
  uint8_t id[kIdLen];
  make_id(id, 1);
  int64_t off = rt_create(s, id, 1024, 16, 1);
  CHECK(off > 0);
  uint8_t* base = rt_store_base(s);
  memset(base + off, 0xAB, 1024 + 16);  // fill data+meta exactly
  CHECK(rt_seal(s, id) == 0);
  uint64_t dsz = 0, msz = 0;
  int64_t goff = rt_get(s, id, &dsz, &msz, 1);
  CHECK(goff == off && dsz == 1024 && msz == 16);
  for (int i = 0; i < 1024; i++) CHECK(base[goff + i] == 0xAB);
  CHECK(rt_release(s, id) == 0);
  CHECK(rt_contains(s, id) == 1);

  // --- abort of an unsealed object -------------------------------------
  uint8_t id2[kIdLen];
  make_id(id2, 2);
  CHECK(rt_create(s, id2, 256, 0, 1) > 0);
  CHECK(rt_abort(s, id2) == 0);
  CHECK(rt_contains(s, id2) == 0);

  // --- delete-pending while pinned --------------------------------------
  make_id(id2, 3);
  CHECK(rt_create(s, id2, 128, 0, 1) > 0);
  CHECK(rt_seal(s, id2) == 0);
  CHECK(rt_get(s, id2, &dsz, &msz, 1) > 0);
  CHECK(rt_delete(s, id2) == 0);       // pinned: becomes delete-pending
  CHECK(rt_release(s, id2) == 0);      // release completes the delete
  CHECK(rt_contains(s, id2) == 0);

  // --- eviction under pressure ------------------------------------------
  // fill beyond capacity with 64 KiB objects; creates must keep
  // succeeding via LRU eviction of sealed, unpinned entries
  for (uint64_t n = 100; n < 100 + 128; n++) {
    uint8_t eid[kIdLen];
    make_id(eid, n);
    int64_t o = rt_create(s, eid, 64 << 10, 0, 1);
    CHECK(o > 0);
    memset(base + o, (int)(n & 0xff), 64 << 10);
    CHECK(rt_seal(s, eid) == 0);
  }
  uint64_t st[17];
  rt_stats(s, st);
  CHECK(st[3] > 0);       // evictions happened
  CHECK(st[8] == 0);      // not poisoned
  CHECK(st[9] == 1);      // single stripe

  // --- cross-handle open -------------------------------------------------
  void* s2 = rt_store_open(path.c_str());
  CHECK(s2 != nullptr);
  CHECK(rt_contains(s2, id) == rt_contains(s, id));

  // --- concurrent hammering (single stripe) ------------------------------
  std::atomic<int> failures{0};
  auto worker = [&](int tid) {
    void* h = rt_store_open(path.c_str());
    if (!h) { failures++; return; }
    uint8_t* b = rt_store_base(h);
    for (uint64_t n = 0; n < 200; n++) {
      uint8_t wid[kIdLen];
      make_id(wid, 10000 + tid * 1000 + n);
      int64_t o = rt_create(h, wid, 4096, 0, 1);
      if (o <= 0) continue;  // ENOMEM under pressure is legal
      memset(b + o, tid, 4096);
      if (rt_seal(h, wid) != 0) { failures++; continue; }
      uint64_t d, m;
      int64_t g = rt_get(h, wid, &d, &m, 1);
      if (g > 0) {
        if (b[g] != (uint8_t)tid || d != 4096) failures++;
        rt_release(h, wid);
      }
      if (n % 3 == 0) rt_delete(h, wid);
    }
    rt_store_close(h);
  };
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; t++) ts.emplace_back(worker, t);
  for (auto& t : ts) t.join();
  CHECK(failures.load() == 0);

  rt_stats(s, st);
  CHECK(st[8] == 0);

  // --- parallel chunked copies (the off-loop put data path) --------------
  // correctness across split shapes (1 thread = plain memcpy; >1 exercises
  // the pool, odd sizes exercise the tail chunk), then 4 caller threads
  // hammering rt_write_parallel concurrently INTO the arena while others
  // create/seal — the data race surface the tsan wiring exists to watch.
  {
    const uint64_t kN = (3 << 20) + 137;  // odd size: tail chunk
    std::vector<uint8_t> src(kN), dst(kN);
    for (uint64_t i = 0; i < kN; i++) src[i] = (uint8_t)(i * 31 + 7);
    for (int threads : {1, 2, 4, 7}) {
      memset(dst.data(), 0, kN);
      rt_write_parallel(dst.data(), src.data(), kN, threads);
      CHECK(memcmp(dst.data(), src.data(), kN) == 0);
    }

    // payloads above the 1 MiB split threshold so concurrent callers
    // genuinely share the pool (queue + per-batch completion handshake);
    // a separate 32 MiB arena keeps this from thrashing the tiny store
    // the eviction section above sized deliberately small
    std::string cpath = path + ".copy";
    void* cs = rt_store_create(cpath.c_str(), 32 << 20, 0);
    CHECK(cs != nullptr);
    std::atomic<int> copy_failures{0};
    auto copier = [&](int tid) {
      void* h = rt_store_open(cpath.c_str());
      if (!h) { copy_failures++; return; }
      uint8_t* b = rt_store_base(h);
      std::vector<uint8_t> payload((3 << 20) + 64 * tid);
      for (size_t i = 0; i < payload.size(); i++)
        payload[i] = (uint8_t)(tid * 13 + i);
      for (uint64_t n = 0; n < 20; n++) {
        uint8_t wid[kIdLen];
        make_id(wid, 50000 + tid * 1000 + n);
        int64_t o = rt_create(h, wid, payload.size(), 0, 1);
        if (o <= 0) continue;  // ENOMEM under pressure is legal
        rt_write_parallel(b + o, payload.data(), payload.size(), 4);
        if (rt_seal(h, wid) != 0) { copy_failures++; continue; }
        uint64_t d, m;
        int64_t g = rt_get(h, wid, &d, &m, 1);
        if (g > 0) {
          if (memcmp(b + g, payload.data(), payload.size()) != 0)
            copy_failures++;
          rt_release(h, wid);
        }
        rt_delete(h, wid);
      }
      rt_store_close(h);
    };
    std::vector<std::thread> cts;
    for (int t = 0; t < 4; t++) cts.emplace_back(copier, t);
    for (auto& t : cts) t.join();
    CHECK(copy_failures.load() == 0);
    rt_store_close(cs);
    remove(cpath.c_str());
  }

  rt_stats(s, st);
  CHECK(st[8] == 0);
  rt_store_close(s2);
  rt_store_close(s);
  remove(path.c_str());

  // ===================== lock-striped arena sections =====================
  std::string mpath = path + ".striped";
  const uint64_t kStripedArena = 16 << 20;  // 4 MiB per stripe
  void* ms = rt_store_create(mpath.c_str(), kStripedArena, 4);
  CHECK(ms != nullptr);
  CHECK(rt_num_stripes(ms) == 4);

  // --- concurrent create/seal/get/evict/stats across 4 stripes ----------
  // 4 writer threads + an evictor hammering rt_evict_stripe + a lock-free
  // stats poller. The sealed-put path (create+copy+seal) runs against
  // concurrent eviction sweeps: zero seal/create/readback errors allowed.
  {
    std::atomic<int> mfail{0};
    std::atomic<bool> stop{false};
    auto mworker = [&](int tid) {
      void* h = rt_store_open(mpath.c_str());
      if (!h) { mfail++; return; }
      uint8_t* b = rt_store_base(h);
      for (uint64_t n = 0; n < 300; n++) {
        uint8_t wid[kIdLen];
        make_id(wid, 100000 + tid * 10000 + n);
        int64_t o = rt_create(h, wid, 32 << 10, 8, 1);
        if (o <= 0) continue;  // ENOMEM under pressure is legal
        memset(b + o, tid + 1, (32 << 10) + 8);
        if (rt_seal(h, wid) != 0) { mfail++; continue; }
        uint64_t d, m;
        int64_t g = rt_get(h, wid, &d, &m, 1);
        if (g > 0) {
          if (b[g] != (uint8_t)(tid + 1) || d != (32 << 10) || m != 8)
            mfail++;
          rt_release(h, wid);
        }
        if (n % 5 == 0) rt_delete(h, wid);
      }
      rt_store_close(h);
    };
    auto evictor = [&] {
      void* h = rt_store_open(mpath.c_str());
      if (!h) { mfail++; return; }
      uint32_t nstripes = rt_num_stripes(h);
      uint64_t sst[8];
      while (!stop.load()) {
        for (uint32_t i = 0; i < nstripes; i++) {
          rt_stripe_stats(h, i, sst);
          if (sst[0] > sst[1] / 2) rt_evict_stripe(h, i, sst[1] / 4);
        }
      }
      rt_store_close(h);
    };
    auto poller = [&] {
      void* h = rt_store_open(mpath.c_str());
      if (!h) { mfail++; return; }
      uint64_t pst[17];
      uint64_t polls = 0;
      while (!stop.load()) {
        rt_stats(h, pst);
        if (pst[8] != 0) mfail++;         // never poisoned
        if (pst[0] > pst[1]) mfail++;     // in_use can't exceed capacity
        polls++;
      }
      if (polls == 0) mfail++;
      rt_store_close(h);
    };
    std::vector<std::thread> mts;
    for (int t = 0; t < 4; t++) mts.emplace_back(mworker, t);
    std::thread ev(evictor), po(poller);
    for (auto& t : mts) t.join();
    stop.store(true);
    ev.join();
    po.join();
    CHECK(mfail.load() == 0);
  }

  // --- round-robin fallback when the home stripe is pinned full ----------
  // ids 200001 and 200002 hash to the SAME home stripe (deterministic:
  // fixed ids, fixed hash). Pinning the first at 0.7x stripe size leaves
  // no room for the second in its home, so its create must re-home to the
  // next stripe — and still succeed without evicting the pinned object.
  {
    uint64_t big = (kStripedArena / 4) * 7 / 10;
    for (uint64_t n = 200001; n <= 200002; n++) {
      uint8_t bid[kIdLen];
      make_id(bid, n);
      int64_t o = rt_create(ms, bid, big, 0, 1);
      CHECK(o > 0);
      CHECK(rt_seal(ms, bid) == 0);
      CHECK(rt_get(ms, bid, &dsz, &msz, 1) > 0);  // hold the pin
    }
    uint64_t fst[17];
    rt_stats(ms, fst);
    CHECK(fst[11] >= 1);   // create_fallbacks
    CHECK(fst[8] == 0);
    for (uint64_t n = 200001; n <= 200002; n++) {
      uint8_t bid[kIdLen];
      make_id(bid, n);
      CHECK(rt_contains(ms, bid) == 1);
      CHECK(rt_release(ms, bid) == 0);
      CHECK(rt_delete(ms, bid) == 0);
    }
  }

  // --- robust-mutex crash repair (EOWNERDEAD mid-create) -----------------
  // re-exec ourselves as a crash child armed to SIGKILL itself inside its
  // 3rd rt_create while holding a stripe mutex; survivors must observe
  // EOWNERDEAD, repair the poisoned stripe, and keep serving puts.
  // (fork+exec, not fork: the chaos env is parsed once per process.)
  {
    pid_t pid = fork();
    if (pid == 0) {
      setenv("RAY_TPU_TESTING_SHM_FAILURE", "shm_create=3", 1);
      execl(argv[0], argv[0], mpath.c_str(), "crashchild", (char*)nullptr);
      _exit(9);
    }
    CHECK(pid > 0);
    int wstatus = 0;
    CHECK(waitpid(pid, &wstatus, 0) == pid);
    CHECK(WIFSIGNALED(wstatus) && WTERMSIG(wstatus) == SIGKILL);

    // survivors keep serving puts on every stripe
    uint8_t* mb = rt_store_base(ms);
    for (uint64_t n = 0; n < 64; n++) {
      uint8_t rid[kIdLen];
      make_id(rid, 300000 + n);
      int64_t o = rt_create(ms, rid, 4096, 0, 1);
      CHECK(o > 0);
      memset(mb + o, 0x77, 4096);
      CHECK(rt_seal(ms, rid) == 0);
      int64_t g = rt_get(ms, rid, &dsz, &msz, 0);
      CHECK(g > 0 && mb[g] == 0x77);
    }
    uint64_t rst[17];
    rt_stats(ms, rst);
    CHECK(rst[10] >= 1);   // the poisoned stripe was repaired
    CHECK(rst[8] == 0);    // and is healthy again
  }

  // --- per-stripe list + aggregate coherence ----------------------------
  {
    uint64_t total = 0;
    std::vector<uint8_t> ids(4096 * kIdLen);
    for (uint32_t i = 0; i < rt_num_stripes(ms); i++)
      total += rt_list_stripe(ms, i, ids.data(), 4096);
    uint64_t lst[17];
    rt_stats(ms, lst);
    CHECK(total <= lst[2]);  // sealed <= all live objects
  }

  // ===================== spanning-object sections ========================
  // 4 MiB stripes: a 6 MiB object cannot exist in any one stripe, so
  // rt_create must route it to the spanning path transparently.
  {
    uint8_t* mb = rt_store_base(ms);
    const uint64_t kSpanSz = 6 << 20;
    CHECK(rt_max_alloc_bytes(ms) < kSpanSz);
    uint8_t sid[kIdLen];
    make_id(sid, 400001);
    int64_t so = rt_create(ms, sid, kSpanSz, 32, 1);
    CHECK(so > 0);
    CHECK(rt_is_span(ms, sid) == 1);
    // fill data+meta across the stripe boundary with a position pattern
    for (uint64_t i = 0; i < kSpanSz + 32; i += 4096)
      mb[so + i] = (uint8_t)(i >> 12);
    mb[so + kSpanSz + 31] = 0xEE;
    CHECK(rt_seal(ms, sid) == 0);
    CHECK(rt_contains(ms, sid) == 1);
    uint64_t sd = 0, sm = 0;
    int64_t sg = rt_get(ms, sid, &sd, &sm, 1);  // pin
    CHECK(sg == so && sd == kSpanSz && sm == 32);
    for (uint64_t i = 0; i < kSpanSz; i += 4096)
      CHECK(mb[sg + i] == (uint8_t)(i >> 12));
    CHECK(mb[sg + kSpanSz + 31] == 0xEE);

    uint64_t sps[8];
    rt_span_stats(ms, sps);
    CHECK(sps[0] == 1);                 // one live span
    CHECK(sps[1] == kSpanSz + 32);
    CHECK(sps[2] == 2);                 // 6 MiB claims two 4 MiB stripes
    uint64_t ast[17];
    rt_stats(ms, ast);
    CHECK(ast[13] == 1);                // surfaced in aggregate stats

    // --- LRU pressure never half-frees a pinned span -------------------
    // hammer normal puts well past remaining capacity: creates re-home
    // and evict around the span; the span's bytes stay intact
    for (uint64_t n = 0; n < 64; n++) {
      uint8_t pid[kIdLen];
      make_id(pid, 410000 + n);
      int64_t o = rt_create(ms, pid, 1 << 20, 0, 1);
      if (o <= 0) continue;
      memset(mb + o, 0x33, 1 << 20);
      CHECK(rt_seal(ms, pid) == 0);
    }
    rt_span_stats(ms, sps);
    CHECK(sps[0] == 1 && sps[2] == 2);  // still whole
    for (uint64_t i = 0; i < kSpanSz; i += 4096)
      CHECK(mb[sg + i] == (uint8_t)(i >> 12));

    // --- delete-pending while pinned, then whole-span reclaim ----------
    CHECK(rt_delete(ms, sid) == 0);     // pinned: deferred
    CHECK(rt_contains(ms, sid) == 1 || rt_is_span(ms, sid) == 1);
    CHECK(rt_release(ms, sid) == 0);    // completes the delete
    CHECK(rt_contains(ms, sid) == 0);
    rt_span_stats(ms, sps);
    CHECK(sps[0] == 0 && sps[2] == 0);  // every member stripe returned

    // reclaimed stripes serve normal creates again
    for (uint64_t n = 0; n < 16; n++) {
      uint8_t pid[kIdLen];
      make_id(pid, 420000 + n);
      int64_t o = rt_create(ms, pid, 1 << 20, 0, 1);
      CHECK(o > 0);
      CHECK(rt_seal(ms, pid) == 0);
    }
  }

  // --- explicit span path + eviction under whole-arena pressure ---------
  {
    uint8_t sid[kIdLen];
    make_id(sid, 400002);
    // force the span path for a small object (claims one whole stripe)
    int64_t so = rt_create_spanning(ms, sid, 64 << 10, 0, 1);
    CHECK(so > 0);
    CHECK(rt_is_span(ms, sid) == 1);
    memset(rt_store_base(ms) + so, 0x44, 64 << 10);
    CHECK(rt_seal(ms, sid) == 0);
    // rt_evict reclaims the unpinned span atomically when stripes alone
    // can't satisfy the request
    uint64_t freed = rt_evict(ms, (uint64_t)16 << 20);
    CHECK(freed > 0);
    CHECK(rt_contains(ms, sid) == 0);
    uint64_t sps[8];
    rt_span_stats(ms, sps);
    CHECK(sps[0] == 0 && sps[2] == 0);
    CHECK(sps[4] >= 1);                 // span_evictions counted
  }

  // --- EOWNERDEAD repair with a RESIDENT span ----------------------------
  // a client SIGKILLed mid-rt_create holds a NORMAL stripe's mutex
  // (creates skip span-owned stripes), so the resident span must survive
  // the poisoned stripe's repair untouched.
  {
    uint8_t* mb = rt_store_base(ms);
    uint8_t sid[kIdLen];
    make_id(sid, 400003);
    int64_t so = rt_create(ms, sid, 6 << 20, 0, 1);
    CHECK(so > 0);
    for (uint64_t i = 0; i < (6ULL << 20); i += 4096)
      mb[so + i] = (uint8_t)(0x50 + (i >> 20));
    CHECK(rt_seal(ms, sid) == 0);
    uint64_t sd = 0, sm = 0;
    CHECK(rt_get(ms, sid, &sd, &sm, 1) == so);  // hold a pin through it

    pid_t pid = fork();
    if (pid == 0) {
      setenv("RAY_TPU_TESTING_SHM_FAILURE", "shm_create=3", 1);
      execl(argv[0], argv[0], mpath.c_str(), "crashchild", (char*)nullptr);
      _exit(9);
    }
    CHECK(pid > 0);
    int wstatus = 0;
    CHECK(waitpid(pid, &wstatus, 0) == pid);
    CHECK(WIFSIGNALED(wstatus) && WTERMSIG(wstatus) == SIGKILL);

    // survivors trigger the repair; the span is untouched
    for (uint64_t n = 0; n < 64; n++) {
      uint8_t rid[kIdLen];
      make_id(rid, 430000 + n);
      int64_t o = rt_create(ms, rid, 4096, 0, 1);
      if (o > 0) {
        memset(mb + o, 0x77, 4096);
        CHECK(rt_seal(ms, rid) == 0);
      }
    }
    CHECK(rt_contains(ms, sid) == 1);
    for (uint64_t i = 0; i < (6ULL << 20); i += 4096)
      CHECK(mb[so + i] == (uint8_t)(0x50 + (i >> 20)));
    CHECK(rt_release(ms, sid) == 0);
    CHECK(rt_delete(ms, sid) == 0);
  }

  // --- crash mid-SPAN-create: two-level EOWNERDEAD repair ---------------
  // the child dies inside span_create holding the span mutex and a
  // member stripe's mutex; survivors must free/invalidate the WHOLE
  // half-claimed span deterministically and keep both planes serving.
  {
    pid_t pid = fork();
    if (pid == 0) {
      setenv("RAY_TPU_TESTING_SHM_FAILURE", "shm_span_create=1", 1);
      execl(argv[0], argv[0], mpath.c_str(), "spancrashchild",
            (char*)nullptr);
      _exit(9);
    }
    CHECK(pid > 0);
    int wstatus = 0;
    CHECK(waitpid(pid, &wstatus, 0) == pid);
    CHECK(WIFSIGNALED(wstatus) && WTERMSIG(wstatus) == SIGKILL);

    // gc sweep runs the span-mutex EOWNERDEAD repair path
    rt_gc_unsealed(ms, 0);
    uint64_t sps[8];
    rt_span_stats(ms, sps);
    CHECK(sps[0] == 0);                 // no live span leaked
    CHECK(sps[2] == 0);                 // no stripe left claimed
    CHECK(sps[6] == 0);                 // no broken slot left behind

    // both planes keep serving: a fresh span and fresh normal puts
    uint8_t* mb = rt_store_base(ms);
    uint8_t sid[kIdLen];
    make_id(sid, 400004);
    int64_t so = rt_create(ms, sid, 6 << 20, 0, 1);
    CHECK(so > 0);
    memset(mb + so, 0x66, 6 << 20);
    CHECK(rt_seal(ms, sid) == 0);
    uint64_t sd = 0, sm = 0;
    CHECK(rt_get(ms, sid, &sd, &sm, 0) == so && sd == (6ULL << 20));
    CHECK(rt_delete(ms, sid) == 0);
    uint64_t hst[17];
    rt_stats(ms, hst);
    CHECK(hst[8] == 0);                 // healthy
    CHECK(hst[16] >= 1);                // span repair counted
  }

  rt_store_close(ms);
  remove(mpath.c_str());
  printf("shm_store_selftest: OK\n");
  return 0;
}
