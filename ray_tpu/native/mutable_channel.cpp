// mutable_channel.cpp — zero-copy mutable shared-memory channels.
//
// TPU-native re-design of the reference's experimental mutable objects
// (reference: src/ray/core_worker/experimental_mutable_object_manager.h:48,
// the compiled-graph channel substrate). One writer, N readers, version-
// gated: the writer publishes version v+1 only after every reader acked
// version v; readers block for a version newer than the last they consumed.
// Unlike the reference (plasma objects + header seals + raylet push), a
// channel here is a standalone file-backed mapping with a process-shared
// mutex/condvar pair — create/open by path, no daemon involvement.
//
// Layout: [Header | payload arena (max_size bytes)]

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5250554348414e4cULL;  // "RPUCHANL"

struct Header {
  uint64_t magic;
  uint64_t max_size;
  pthread_mutex_t mu;
  pthread_cond_t cv;
  uint64_t version;        // last published version (0 = nothing yet)
  uint64_t data_size;      // payload size of current version
  uint32_t num_readers;    // required acks per version
  uint32_t acks;           // readers that consumed current version
  uint32_t closed;
  uint32_t error;
};

struct Chan {
  Header* hdr;
  uint8_t* payload;
  uint64_t map_size;
  int fd;
};

int64_t now_plus_ms(timespec* ts, int64_t timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
  return 0;
}

int lock_robust(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    h->error = 1;
    pthread_mutex_consistent(&h->mu);
    rc = 0;
  }
  return rc;
}

}  // namespace

extern "C" {

void* rtc_create(const char* path, uint64_t max_size, uint32_t num_readers) {
  unlink(path);
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(Header) + max_size;
  if (ftruncate(fd, (off_t)total) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  madvise(mem, total, MADV_HUGEPAGE);

  Header* h = static_cast<Header*>(mem);
  memset(h, 0, sizeof(Header));
  h->max_size = max_size;
  h->num_readers = num_readers;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->cv, &ca);
  h->magic = kMagic;
  msync(mem, sizeof(Header), MS_SYNC);

  Chan* c = new Chan{h, static_cast<uint8_t*>(mem) + sizeof(Header), total, fd};
  return c;
}

void* rtc_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  Header* h = static_cast<Header*>(mem);
  if (h->magic != kMagic) {
    munmap(mem, (size_t)st.st_size);
    close(fd);
    return nullptr;
  }
  Chan* c = new Chan{h, static_cast<uint8_t*>(mem) + sizeof(Header),
                     (uint64_t)st.st_size, fd};
  return c;
}

void rtc_close(void* hc) {
  Chan* c = static_cast<Chan*>(hc);
  munmap(c->hdr, c->map_size);
  close(c->fd);
  delete c;
}

uint8_t* rtc_payload(void* hc) { return static_cast<Chan*>(hc)->payload; }
uint64_t rtc_max_size(void* hc) { return static_cast<Chan*>(hc)->hdr->max_size; }

// Begin a write: waits until all readers acked the previous version (or
// timeout). Returns 0 on success (payload may then be filled), -1 timeout,
// -2 closed.
int rtc_write_acquire(void* hc, int64_t timeout_ms) {
  Header* h = static_cast<Chan*>(hc)->hdr;
  timespec ts;
  now_plus_ms(&ts, timeout_ms);
  if (lock_robust(h) != 0) return -3;
  while (h->version != 0 && h->acks < h->num_readers && !h->closed) {
    int rc = pthread_cond_timedwait(&h->cv, &h->mu, &ts);
    if (rc == ETIMEDOUT) { pthread_mutex_unlock(&h->mu); return -1; }
  }
  if (h->closed) { pthread_mutex_unlock(&h->mu); return -2; }
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Publish data_size bytes already written into the payload arena.
int rtc_write_publish(void* hc, uint64_t data_size) {
  Header* h = static_cast<Chan*>(hc)->hdr;
  if (lock_robust(h) != 0) return -3;
  h->data_size = data_size;
  h->version += 1;
  h->acks = 0;
  pthread_cond_broadcast(&h->cv);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Block until a version newer than last_version exists; returns the new
// version (>0), 0 on timeout, -2 closed. data_size written through.
int64_t rtc_read_acquire(void* hc, uint64_t last_version, int64_t timeout_ms,
                         uint64_t* data_size) {
  Header* h = static_cast<Chan*>(hc)->hdr;
  timespec ts;
  now_plus_ms(&ts, timeout_ms);
  if (lock_robust(h) != 0) return -3;
  while (h->version <= last_version && !h->closed) {
    int rc = pthread_cond_timedwait(&h->cv, &h->mu, &ts);
    if (rc == ETIMEDOUT) { pthread_mutex_unlock(&h->mu); return 0; }
  }
  if (h->closed && h->version <= last_version) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  int64_t v = (int64_t)h->version;
  *data_size = h->data_size;
  pthread_mutex_unlock(&h->mu);
  return v;
}

// Ack the given version (reader finished with the buffer).
int rtc_read_release(void* hc, uint64_t version) {
  Header* h = static_cast<Chan*>(hc)->hdr;
  if (lock_robust(h) != 0) return -3;
  if (h->version == version) {
    h->acks += 1;
    if (h->acks >= h->num_readers) pthread_cond_broadcast(&h->cv);
  }
  pthread_mutex_unlock(&h->mu);
  return 0;
}

int rtc_set_closed(void* hc) {
  Header* h = static_cast<Chan*>(hc)->hdr;
  if (lock_robust(h) != 0) return -3;
  h->closed = 1;
  pthread_cond_broadcast(&h->cv);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

uint64_t rtc_version(void* hc) {
  return static_cast<Chan*>(hc)->hdr->version;
}

}  // extern "C"
