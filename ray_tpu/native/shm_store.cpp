// shm_store.cpp — node-local shared-memory object store (lock-striped).
//
// TPU-native re-design of the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.cc, plasma_allocator.h).
// Unlike plasma (a store *server* that clients reach over a unix socket with
// fd-passing), the entire store — allocator, object table, LRU — lives in one
// file-backed shared-memory arena that every process on the node maps at a
// known path. create/seal/get/release are direct shared-memory operations:
// no socket round trip, no fd passing. The node daemon only coordinates
// spill-to-disk, eviction sweeps and cross-node transfer.
//
// Concurrency model (v2): the arena is striped into independently locked
// sub-heaps so N same-node clients putting in parallel never rendezvous on
// one mutex:
//
//   - Each stripe owns a contiguous heap slice AND a contiguous segment of
//     the object table, protected by its own robust process-shared mutex.
//     An object's entry and its payload always live in the SAME stripe, so
//     crash repair of one stripe never chases pointers into another.
//   - Allocation hashes the object id to a home stripe; when the home heap
//     is full the create falls back round-robin to the next stripe (the
//     object is re-homed there entirely). The sealed-put fast path
//     (create + copy + seal) takes exactly ONE stripe lock: the create.
//   - rt_seal is a lock-free atomic entry-state transition
//     (CREATED -> SEALED via CAS); the payload copy between create and
//     seal never held a lock to begin with.
//   - rt_stats / rt_stripe_stats read a seqlock-style per-stripe snapshot
//     (lockseq is odd while a locked mutation is open) and acquire a mutex
//     only if a writer appears stuck — which doubles as the robust-mutex
//     recovery probe for holders that died mid-mutation.
//   - LRU is a per-entry sequence stamp, not a linked list: eviction scans
//     the stripe's table segment and frees lowest-seq sealed unpinned
//     entries. Sweeps are driven by the node manager per stripe; the
//     in-create eviction fallback only ever locks the one stripe it is
//     allocating from, so one client's arena pressure cannot stall every
//     other client's create.
//   - A client killed while holding a stripe mutex poisons only that
//     stripe: the next locker gets EOWNERDEAD, marks the mutex consistent
//     and rebuilds the stripe (table segment + heap reset; resident objects
//     there are lost, equivalent to eviction). The other stripes keep
//     serving throughout.
//
// Spanning objects (v3): an object larger than one stripe's heap cannot
// live in any stripe, so weight-sized blobs (sharded checkpoints, RL
// weight pushes, cold-start attach) take the SPANNING path instead:
//
//   - The span claims m = ceil(need / stripe_bytes) physically
//     CONTIGUOUS whole stripes (stripe i+1's heap starts exactly where
//     stripe i's ends, so the payload is one contiguous region). Claimed
//     stripes are marked span_owner and excluded from normal creates,
//     per-stripe eviction and segment probing; their resident objects
//     are LRU-evicted during the claim (pinned residents fail the
//     window and the claim slides to the next one).
//   - Span descriptors live in a small header-level table guarded by
//     their own robust process-shared mutex (spans are few and huge; a
//     single lock is never the bottleneck). The entry/payload
//     colocation rule extends naturally: the descriptor IS the entry,
//     and crash repair frees or invalidates the WHOLE span atomically —
//     a poisoned member stripe marks the span broken, and broken spans
//     are reclaimed (all member stripes at once) by the span-mutex
//     repair path, the gc sweep and allocation pressure. LRU pressure
//     can evict a whole unpinned span but can never half-free one.
//   - rt_create routes by size (need > one stripe -> span path), so the
//     Python client and every put/transfer path gains multi-GB objects
//     transparently; rt_create_spanning forces the path for tests.
//
// Layout:
//   [Header incl. Stripe[] + SpanDesc[] | ObjectTable | striped arena]
//
// Object lifecycle: CREATED (writer owns buffer) -> SEALED (immutable,
// readable by all) -> deleted (deferred until pin_count drops to zero).

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <utility>
#include <vector>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5250555453544f52ULL;  // "RPUTSTOR"
constexpr uint32_t kVersion = 3;
constexpr uint32_t kIdLen = 20;
constexpr uint32_t kTableCapacity = 1 << 16;  // 65536 entries total
constexpr uint64_t kAlign = 64;
constexpr uint32_t kNil = 0xffffffffu;
constexpr uint32_t kMaxStripes = 16;
// Auto-striping floor: a stripe must comfortably hold the largest common
// object (64 MiB bench blobs, multi-MB KV blocks) with room to recycle.
constexpr uint64_t kMinStripeBytes = 128ULL << 20;

// Object states.
enum : uint32_t { kEmpty = 0, kCreated = 1, kSealed = 2, kTombstone = 3 };

// Span descriptor states. kSpanClaiming is only ever observed by crash
// repair: a live claim holds the span mutex for its whole duration, so
// any claiming slot seen by a span-mutex holder belongs to a dead writer.
constexpr uint32_t kMaxSpans = 16;
enum : uint32_t {
  kSpanEmpty = 0,
  kSpanClaiming = 1,
  kSpanCreated = 2,
  kSpanSealed = 3,
  kSpanBroken = 4,
};

// --------------------------------------------------------------- atomics
// Shared-memory fields are plain integers accessed through __atomic
// builtins (std::atomic members are not guaranteed address-free across
// processes by the standard; the builtins are, on this ABI, and tsan
// models them).

// ThreadSanitizer annotations for the seqlock protocol (build:tsan
// analog — tests/test_sanitizers.py runs the striped hammer under
// -fsanitize=thread). Every seqlock-covered field is itself accessed
// through the __atomic builtins above, so tsan already derives the
// happens-before edges from the atomics; these annotations make the
// publication edge EXPLICIT at the protocol level (writer's closing
// lockseq bump releases, reader's validated snapshot acquires), so a
// future relaxation of a field load to a plain read is still anchored
// to the seqlock rather than silently racing.
#if defined(__SANITIZE_THREAD__)
#define RT_TSAN 1
#elif defined(__has_feature)
#if __has_feature(thread_sanitizer)
#define RT_TSAN 1
#endif
#endif
#ifdef RT_TSAN
extern "C" void __tsan_acquire(void* addr);
extern "C" void __tsan_release(void* addr);
#define RT_TSAN_ACQUIRE(p) __tsan_acquire((void*)(p))
#define RT_TSAN_RELEASE(p) __tsan_release((void*)(p))
#else
#define RT_TSAN_ACQUIRE(p) ((void)0)
#define RT_TSAN_RELEASE(p) ((void)0)
#endif
inline uint32_t ld32(const uint32_t* p, int mo = __ATOMIC_ACQUIRE) {
  return __atomic_load_n(p, mo);
}
inline uint64_t ld64(const uint64_t* p, int mo = __ATOMIC_ACQUIRE) {
  return __atomic_load_n(p, mo);
}
inline void st32(uint32_t* p, uint32_t v, int mo = __ATOMIC_RELEASE) {
  __atomic_store_n(p, v, mo);
}
inline void st64(uint64_t* p, uint64_t v, int mo = __ATOMIC_RELEASE) {
  __atomic_store_n(p, v, mo);
}
inline uint64_t add64(uint64_t* p, uint64_t v, int mo = __ATOMIC_ACQ_REL) {
  return __atomic_fetch_add(p, v, mo);
}
inline bool cas32(uint32_t* p, uint32_t expected, uint32_t desired) {
  return __atomic_compare_exchange_n(p, &expected, desired, false,
                                     __ATOMIC_ACQ_REL, __ATOMIC_ACQUIRE);
}

struct Entry {
  uint8_t id[kIdLen];
  uint32_t state;      // atomic; publishes the entry (release on CREATED)
  uint32_t stripe;     // owning stripe == segment holding this slot
  uint64_t offset;     // payload offset, relative to the stripe's heap base
  uint64_t data_size;
  uint64_t meta_size;
  uint32_t pin_count;  // mutated under the stripe lock (seal resets it
                       // lock-free BEFORE the SEALED transition publishes)
  uint32_t flags;      // bit0: delete-pending, bit1: not-evictable
  uint64_t seq;        // LRU stamp (stripe lru_clock value at last touch)
  uint64_t ctime_sec;  // CLOCK_MONOTONIC seconds at creation
};

struct alignas(64) Stripe {
  pthread_mutex_t mutex;     // robust, process-shared
  uint32_t mutating;         // a locked mutation is in progress
  uint32_t poisoned;         // set transiently when a holder died mid-mutation
  uint32_t span_owner;       // 0 = none, else owning span slot + 1: the whole
                             // heap slice belongs to that spanning object
  uint32_t _pad1;
  uint64_t lockseq;          // seqlock: odd while a locked section is open
  uint64_t arena_off;        // base-relative start of this stripe's heap
  uint64_t arena_size;
  uint64_t free_head;        // stripe-relative offset of first free block
  uint64_t bytes_in_use;     // allocated bytes incl. block headers
  uint64_t num_objects;
  uint64_t lru_clock;        // atomic (lock-free seal stamps through it)
  uint64_t num_evictions;
  uint64_t bytes_evicted;
  uint64_t create_count;
  uint64_t seal_count;       // atomic (lock-free seal)
  uint64_t get_hits;
  uint64_t get_misses;       // atomic (lock-free miss path)
  uint64_t repairs;          // robust-mutex crash repairs of this stripe
  uint32_t seg_start, seg_len;  // entry-table segment [start, start+len)
};

// Descriptor for one spanning object: the payload occupies the whole
// contiguous heap slices of stripes [first_stripe, first_stripe +
// n_stripes). Descriptors mutate only under the header's robust span
// mutex; `state` is the atomic publication field (release-stored so a
// lock-free reader that observes CREATED/SEALED sees consistent fields).
struct SpanDesc {
  uint8_t id[kIdLen];
  uint32_t state;        // atomic (see enum above)
  uint32_t first_stripe;
  uint32_t n_stripes;
  uint32_t pin_count;    // mutated under the span mutex
  uint32_t flags;        // bit0: delete-pending, bit1: not-evictable
  uint32_t _pad;
  uint64_t data_size;
  uint64_t meta_size;
  uint64_t seq;          // LRU stamp (header span_clock value at last touch)
  uint64_t ctime_sec;    // CLOCK_MONOTONIC seconds at creation
};

struct Header {
  uint64_t magic;
  uint32_t version;
  uint32_t table_capacity;
  uint64_t total_size;       // whole mapping size
  uint64_t arena_offset;     // start of heap area (base-relative)
  uint64_t arena_size;       // raw heap area size (>= sum of stripe slices)
  uint32_t num_stripes;
  uint32_t _pad0;
  uint64_t fallback_count;   // atomic: creates re-homed off their hash stripe
  // ------------------------------------------------ spanning allocation
  pthread_mutex_t span_mutex;  // robust, process-shared; guards spans[]
  uint32_t span_mutating;      // a locked span mutation is in progress
  uint32_t _pad2;
  uint64_t span_clock;         // LRU clock for spans (under span mutex)
  uint64_t span_creates;       // lifetime spans successfully created
  uint64_t span_evictions;     // whole spans reclaimed under LRU pressure
  uint64_t span_repairs;       // atomic: crash repairs that broke a span
  SpanDesc spans[kMaxSpans];
  Stripe stripes[kMaxStripes];
};

// Boundary-tag heap block, located in a stripe's heap slice. Offsets in
// the free list are stripe-relative. Size includes the header.
struct Block {
  uint64_t size;       // total block size incl. header; low bit = free flag
  uint64_t prev_size;  // size of physically-previous block (0 if first)
  // free blocks only:
  uint64_t next_free;  // stripe-relative offset or ~0
  uint64_t prev_free;  // stripe-relative offset or ~0
};

constexpr uint64_t kBlockHeader = 16;  // size + prev_size (used blocks)
constexpr uint64_t kMinBlock = 64;
constexpr uint64_t kNone = ~0ULL;

struct Store {
  Header* hdr;
  uint8_t* base;     // mapping base
  Entry* table;
  uint64_t map_size;
  int fd;
};

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }
inline bool blk_free(Block* b) { return b->size & 1; }
inline uint64_t blk_size(Block* b) { return b->size & ~1ULL; }
inline void set_size(Block* b, uint64_t s, bool f) { b->size = s | (f ? 1 : 0); }

inline Block* at(Store* s, Stripe* sp, uint64_t off) {
  return reinterpret_cast<Block*>(s->base + sp->arena_off + off);
}
inline uint64_t off_of(Store* s, Stripe* sp, Block* b) {
  return reinterpret_cast<uint8_t*>(b) - (s->base + sp->arena_off);
}

void free_list_push(Store* s, Stripe* sp, Block* b) {
  uint64_t off = off_of(s, sp, b);
  b->next_free = sp->free_head;
  b->prev_free = kNone;
  if (sp->free_head != kNone) at(s, sp, sp->free_head)->prev_free = off;
  sp->free_head = off;
}

void free_list_remove(Store* s, Stripe* sp, Block* b) {
  if (b->prev_free != kNone)
    at(s, sp, b->prev_free)->next_free = b->next_free;
  else
    sp->free_head = b->next_free;
  if (b->next_free != kNone) at(s, sp, b->next_free)->prev_free = b->prev_free;
}

Block* phys_next(Store* s, Stripe* sp, Block* b) {
  uint64_t off = off_of(s, sp, b) + blk_size(b);
  if (off >= sp->arena_size) return nullptr;
  return at(s, sp, off);
}

Block* phys_prev(Store* s, Stripe* sp, Block* b) {
  if (b->prev_size == 0) return nullptr;
  return at(s, sp, off_of(s, sp, b) - b->prev_size);
}

// Allocate `need` payload bytes from one stripe's heap; returns
// stripe-relative offset of payload or kNone. Caller holds the stripe lock.
uint64_t heap_alloc(Store* s, Stripe* sp, uint64_t need) {
  uint64_t want = align_up(need + kBlockHeader, kAlign);
  if (want < kMinBlock) want = kMinBlock;
  // first-fit
  uint64_t off = sp->free_head;
  while (off != kNone) {
    Block* b = at(s, sp, off);
    uint64_t bs = blk_size(b);
    if (bs >= want) {
      free_list_remove(s, sp, b);
      if (bs - want >= kMinBlock) {
        // split
        Block* rest = at(s, sp, off + want);
        set_size(rest, bs - want, true);
        rest->prev_size = want;
        Block* nxt = phys_next(s, sp, rest);
        if (nxt) nxt->prev_size = blk_size(rest);
        free_list_push(s, sp, rest);
        set_size(b, want, false);
      } else {
        set_size(b, bs, false);
      }
      sp->bytes_in_use += blk_size(b);
      return off + kBlockHeader;
    }
    off = b->next_free;
  }
  return kNone;
}

void heap_free(Store* s, Stripe* sp, uint64_t payload_off) {
  Block* b = at(s, sp, payload_off - kBlockHeader);
  sp->bytes_in_use -= blk_size(b);
  set_size(b, blk_size(b), true);
  // coalesce with next
  Block* n = phys_next(s, sp, b);
  if (n && blk_free(n)) {
    free_list_remove(s, sp, n);
    set_size(b, blk_size(b) + blk_size(n), true);
  }
  // coalesce with prev
  Block* p = phys_prev(s, sp, b);
  if (p && blk_free(p)) {
    free_list_remove(s, sp, p);
    set_size(p, blk_size(p) + blk_size(b), true);
    b = p;
  }
  Block* after = phys_next(s, sp, b);
  if (after) after->prev_size = blk_size(b);
  free_list_push(s, sp, b);
}

inline uint64_t hash_id(const uint8_t* id) {
  // Mix all 20 bytes: ids that share a task prefix differ only in the
  // trailing index word, so the tail must feed the hash.
  uint64_t a, b;
  uint32_t c;
  memcpy(&a, id, 8);
  memcpy(&b, id + 8, 8);
  memcpy(&c, id + 16, 4);
  uint64_t h = a ^ (b * 0x9e3779b97f4a7c15ULL) ^ ((uint64_t)c << 17);
  h ^= h >> 33; h *= 0xff51afd7ed558ccdULL; h ^= h >> 33;
  return h;
}

inline uint32_t stripe_of(Store* s, uint64_t h) {
  // high bits pick the stripe; low bits pick the slot within the segment
  return (uint32_t)((h >> 40) % s->hdr->num_stripes);
}

inline uint32_t segment_of(Store* s, uint32_t idx) {
  return idx / s->hdr->stripes[0].seg_len;
}

// Probe one stripe's table segment for id. Safe WITHOUT the stripe lock:
// entries publish via a release-store of state, ids are immutable while an
// entry is live, and a concurrent tombstone compaction can at worst cause
// a spurious miss (callers confirm misses under the lock). Returns entry
// index or kNil.
uint32_t probe_segment(Store* s, uint32_t si, const uint8_t* id, uint64_t h) {
  Stripe* sp = &s->hdr->stripes[si];
  uint32_t start = sp->seg_start, len = sp->seg_len;
  uint32_t i = start + (uint32_t)h % len;
  for (uint32_t probe = 0; probe < len; ++probe) {
    Entry* e = &s->table[i];
    uint32_t st = ld32(&e->state);
    if (st == kEmpty) return kNil;
    if (st != kTombstone && memcmp(e->id, id, kIdLen) == 0) return i;
    if (++i == start + len) i = start;
  }
  return kNil;
}

// Find a free slot in a stripe's segment for id (caller holds the stripe
// lock and has verified id is absent). kNil if the segment is full.
uint32_t segment_slot(Store* s, uint32_t si, uint64_t h) {
  Stripe* sp = &s->hdr->stripes[si];
  uint32_t start = sp->seg_start, len = sp->seg_len;
  uint32_t i = start + (uint32_t)h % len;
  for (uint32_t probe = 0; probe < len; ++probe) {
    uint32_t st = ld32(&s->table[i].state, __ATOMIC_RELAXED);
    if (st == kEmpty || st == kTombstone) return i;
    if (++i == start + len) i = start;
  }
  return kNil;
}

// Lock-free find across stripes: home segment first, then — only when any
// create has ever been re-homed — the remaining segments in fallback order.
uint32_t find_lockfree(Store* s, const uint8_t* id, uint64_t h,
                       uint32_t home) {
  uint32_t idx = probe_segment(s, home, id, h);
  if (idx != kNil) return idx;
  if (ld64(&s->hdr->fallback_count, __ATOMIC_RELAXED) == 0) return kNil;
  uint32_t n = s->hdr->num_stripes;
  for (uint32_t k = 1; k < n; ++k) {
    idx = probe_segment(s, (home + k) % n, id, h);
    if (idx != kNil) return idx;
  }
  return kNil;
}

// ----------------------------------------------------- stripe lock guard
void repair_stripe_locked(Store* s, uint32_t si);
class StripeGuard;
template <typename F>
int64_t with_entry_locked(Store* s, const uint8_t* id, F&& fn);

class StripeGuard {
 public:
  StripeGuard(Store* s, uint32_t si) : sp_(&s->hdr->stripes[si]) {
    int rc = pthread_mutex_lock(&sp_->mutex);
    bool dead = rc == EOWNERDEAD;
    if (dead) pthread_mutex_consistent(&sp_->mutex);
    bool need_repair = dead && ld32(&sp_->mutating);
    st32(&sp_->mutating, 1);
    // open the seqlock window (odd) BEFORE any mutation — including the
    // repair below — so seqlock readers can never accept a torn snapshot.
    // A dead holder may have left lockseq odd already; don't double-bump.
    if (!(ld64(&sp_->lockseq) & 1)) add64(&sp_->lockseq, 1);
    if (need_repair) {
      // the dead holder was mid-mutation: heap/table invariants for THIS
      // stripe are suspect — rebuild it instead of walking corrupt
      // structures. Other stripes are untouched.
      st32(&sp_->poisoned, 1);
      repair_stripe_locked(s, si);
      st32(&sp_->poisoned, 0);
    }
  }
  ~StripeGuard() {
    st32(&sp_->mutating, 0);
    // everything mutated in this window is published to seqlock readers
    // by the closing (even) bump — release BEFORE it so the reader's
    // paired acquire in snapshot_stripe() covers the whole window
    RT_TSAN_RELEASE(&sp_->lockseq);
    add64(&sp_->lockseq, 1);  // even: snapshot stable
    pthread_mutex_unlock(&sp_->mutex);
  }

 private:
  Stripe* sp_;
};

// Reset one stripe's heap to a single free block (fresh-store state).
// Caller holds the stripe mutex. Used by crash repair and by span
// claim/free, whose payload writes overwrite the heap's block headers.
void reset_stripe_heap_locked(Store* s, Stripe* sp) {
  sp->free_head = kNone;
  Block* b = at(s, sp, 0);
  set_size(b, sp->arena_size, true);
  b->prev_size = 0;
  b->next_free = kNone;
  b->prev_free = kNone;
  sp->free_head = 0;
  sp->bytes_in_use = 0;
  sp->num_objects = 0;
}

// Rebuild one stripe after its lock holder died mid-mutation: wipe the
// table segment, reset the heap to a single free block. Objects resident
// in the stripe are lost (survivors observe them as evicted — the same
// contract as LRU eviction of an unspilled object). Caller holds the
// (freshly made-consistent) stripe mutex.
void repair_stripe_locked(Store* s, uint32_t si) {
  Stripe* sp = &s->hdr->stripes[si];
  memset(&s->table[sp->seg_start], 0, sizeof(Entry) * (uint64_t)sp->seg_len);
  reset_stripe_heap_locked(s, sp);
  if (sp->span_owner) {
    // the poisoned stripe held part of a spanning object: its payload is
    // gone, so the WHOLE span must die — mark the descriptor broken
    // (lock-free CAS loop: we hold only this stripe's mutex and must not
    // take the span mutex here). The span-mutex repair path, the gc
    // sweep, and allocation pressure all reclaim broken spans' remaining
    // member stripes atomically.
    uint32_t slot = sp->span_owner - 1;
    if (slot < kMaxSpans) {
      SpanDesc* d = &s->hdr->spans[slot];
      for (;;) {
        uint32_t st = ld32(&d->state);
        if (st == kSpanEmpty || st == kSpanBroken) break;
        if (cas32(&d->state, st, kSpanBroken)) {
          add64(&s->hdr->span_repairs, 1, __ATOMIC_RELAXED);
          break;
        }
      }
    }
    sp->span_owner = 0;
  }
  sp->repairs++;
}

// Free an entry's heap block and tombstone its slot. Caller holds the
// stripe lock and has already transitioned state to kTombstone.
void finish_free(Store* s, uint32_t si, uint32_t idx) {
  Stripe* sp = &s->hdr->stripes[si];
  Entry* e = &s->table[idx];
  // Sanity-gate the heap free: a lock-free seal racing a crash repair's
  // segment wipe can leave a resurrected entry with a zeroed offset —
  // freeing that would walk out of the stripe's heap. Such an entry owns
  // no block (the repair rebuilt the heap), so only the slot dies.
  if (e->offset >= kBlockHeader && e->offset < sp->arena_size)
    heap_free(s, sp, e->offset);
  if (sp->num_objects > 0) sp->num_objects--;  // resurrected entries (see
                                               // above) aren't counted
  // Anti-tombstone-exhaustion: if the next probe slot (within the
  // segment) is empty, this tombstone and any run before it can revert
  // to empty without breaking probe chains.
  uint32_t start = sp->seg_start, len = sp->seg_len;
  uint32_t nxt = idx + 1 == start + len ? start : idx + 1;
  if (ld32(&s->table[nxt].state, __ATOMIC_RELAXED) == kEmpty) {
    uint32_t j = idx;
    while (ld32(&s->table[j].state, __ATOMIC_RELAXED) == kTombstone) {
      st32(&s->table[j].state, kEmpty);
      j = j == start ? start + len - 1 : j - 1;
    }
  }
}

// CAS the entry out of `from` and free it. Returns false when the state
// moved under us (e.g. a lock-free seal won the race against gc).
bool entry_free_from(Store* s, uint32_t si, uint32_t idx, uint32_t from) {
  if (!cas32(&s->table[idx].state, from, kTombstone)) return false;
  finish_free(s, si, idx);
  return true;
}

// Run `fn(si, idx)` under the owning stripe's lock for the live entry
// matching id. The lock-free find is only a hint: a hit is re-verified
// under the lock, and a miss is confirmed by locked probes — a lock-free
// probe racing tombstone compaction must never make a mutation (release,
// delete, abort, get-pin) silently no-op, or pins leak and objects turn
// unevictable. Returns fn's result, or -ENOENT if the id is truly absent.
template <typename F>
int64_t with_entry_locked(Store* s, const uint8_t* id, F&& fn) {
  uint64_t h = hash_id(id);
  uint32_t home = stripe_of(s, h);
  for (int attempt = 0; attempt < 4; ++attempt) {
    uint32_t idx = find_lockfree(s, id, h, home);
    if (idx == kNil) break;
    uint32_t si = segment_of(s, idx);
    StripeGuard g(s, si);
    Entry* e = &s->table[idx];
    uint32_t st = ld32(&e->state);
    if ((st != kCreated && st != kSealed) || memcmp(e->id, id, kIdLen) != 0)
      continue;  // entry was freed/reused between probe and lock — retry
    return fn(si, idx);
  }
  // locked confirmation: probe each candidate segment under its lock
  uint32_t n = s->hdr->num_stripes;
  uint32_t scan = ld64(&s->hdr->fallback_count, __ATOMIC_RELAXED) ? n : 1;
  for (uint32_t k = 0; k < scan; ++k) {
    uint32_t si = (home + k) % n;
    StripeGuard g(s, si);
    uint32_t idx = probe_segment(s, si, id, h);
    if (idx != kNil) return fn(si, idx);
  }
  return -ENOENT;
}

// Evict lowest-seq sealed+unpinned+evictable objects from ONE stripe until
// `bytes` are reclaimable. Caller holds the stripe lock. Returns bytes
// freed. Only this stripe's clients can contend with the sweep.
uint64_t evict_stripe_locked(Store* s, uint32_t si, uint64_t bytes) {
  Stripe* sp = &s->hdr->stripes[si];
  if (sp->span_owner) return 0;  // span stripes have no per-entry LRU: a
                                 // span is reclaimed whole or not at all
  std::vector<std::pair<uint64_t, uint32_t>> cands;  // (seq, idx)
  for (uint32_t i = sp->seg_start; i < sp->seg_start + sp->seg_len; ++i) {
    Entry* e = &s->table[i];
    if (ld32(&e->state, __ATOMIC_RELAXED) == kSealed &&
        ld32(&e->pin_count, __ATOMIC_RELAXED) == 0 && !(e->flags & 2))
      cands.emplace_back(ld64(&e->seq, __ATOMIC_RELAXED), i);
  }
  std::sort(cands.begin(), cands.end());
  uint64_t freed = 0;
  for (auto& c : cands) {
    if (freed >= bytes) break;
    Entry* e = &s->table[c.second];
    uint64_t sz = e->data_size + e->meta_size;
    if (!entry_free_from(s, si, c.second, kSealed)) continue;
    sp->num_evictions++;
    sp->bytes_evicted += sz;
    freed += sz;
  }
  return freed;
}

// -------------------------------------------------------- chaos injection
// Deterministic crash hook for the robust-mutex recovery tests (the shm
// analog of rpc.py's RAY_TPU_TESTING_RPC_FAILURE): spec
// RAY_TPU_TESTING_SHM_FAILURE="shm_create=N" SIGKILLs this process inside
// its Nth rt_create WHILE HOLDING the stripe mutex mid-mutation — the
// worst-case death a survivor must repair from.
long chaos_crash_create_after() {
  static long n = [] {
    const char* raw = getenv("RAY_TPU_TESTING_SHM_FAILURE");
    if (!raw) return 0L;
    const char* p = strstr(raw, "shm_create=");
    return p ? atol(p + sizeof("shm_create=") - 1) : 0L;
  }();
  return n;
}

void chaos_maybe_crash_in_create() {
  long after = chaos_crash_create_after();
  if (after <= 0) return;
  static std::atomic<long> creates{0};
  if (creates.fetch_add(1) + 1 == after) kill(getpid(), SIGKILL);
}

// Span analog: spec "shm_span_create=N" SIGKILLs this process inside its
// Nth spanning create AFTER at least one member stripe is claimed, while
// holding BOTH the span mutex and that stripe's mutex — the worst-case
// death the two-level repair (stripe EOWNERDEAD -> span broken; span
// EOWNERDEAD -> claiming slots freed) must recover from.
long chaos_crash_span_create_after() {
  static long n = [] {
    const char* raw = getenv("RAY_TPU_TESTING_SHM_FAILURE");
    if (!raw) return 0L;
    const char* p = strstr(raw, "shm_span_create=");
    return p ? atol(p + sizeof("shm_span_create=") - 1) : 0L;
  }();
  return n;
}

void chaos_maybe_crash_in_span_create() {
  long after = chaos_crash_span_create_after();
  if (after <= 0) return;
  static std::atomic<long> creates{0};
  if (creates.fetch_add(1) + 1 == after) kill(getpid(), SIGKILL);
}

// ------------------------------------------------- spanning allocation
// All span-table mutations run under the header's robust span mutex.
// Lock order is span_mutex -> stripe mutex (one stripe at a time);
// nothing takes the span mutex while holding a stripe mutex (stripe
// crash repair only CASes span state lock-free), so the order is
// deadlock-free.

void span_free_locked(Store* s, uint32_t slot);

class SpanGuard {
 public:
  explicit SpanGuard(Store* s) : s_(s) {
    Header* h = s->hdr;
    int rc = pthread_mutex_lock(&h->span_mutex);
    bool dead = rc == EOWNERDEAD;
    if (dead) pthread_mutex_consistent(&h->span_mutex);
    bool need_repair = dead && ld32(&h->span_mutating);
    st32(&h->span_mutating, 1);
    if (dead && !need_repair) {
      // holder died between lock and the mutating publish (or after
      // clearing it): the table itself is consistent, but a claim may
      // still be stranded — the sweep below is idempotent, run it too
      need_repair = true;
    }
    if (need_repair) {
      // a span-mutex holder died: any kSpanClaiming slot belongs to it
      // (live claims hold the mutex end-to-end), and kSpanBroken slots
      // are ownerless — free both, reclaiming ALL member stripes, so
      // repair of a poisoned span is deterministic and whole-span.
      for (uint32_t k = 0; k < kMaxSpans; ++k) {
        uint32_t st = ld32(&h->spans[k].state);
        if (st == kSpanClaiming || st == kSpanBroken) {
          span_free_locked(s, k);
          add64(&h->span_repairs, 1, __ATOMIC_RELAXED);
        }
      }
    }
  }
  ~SpanGuard() {
    st32(&s_->hdr->span_mutating, 0);
    pthread_mutex_unlock(&s_->hdr->span_mutex);
  }

 private:
  Store* s_;
};

// Lock-free span lookup: slot index of a live (claiming excluded) span
// matching id, or -1. Publication via the release-store of state.
int span_find(Store* s, const uint8_t* id) {
  for (uint32_t k = 0; k < kMaxSpans; ++k) {
    SpanDesc* d = &s->hdr->spans[k];
    uint32_t st = ld32(&d->state);
    if ((st == kSpanCreated || st == kSpanSealed) &&
        memcmp(d->id, id, kIdLen) == 0)
      return (int)k;
  }
  return -1;
}

// Free one span: unpublish the descriptor, then release every member
// stripe (identified by span_owner, NOT the descriptor's range — a
// crash mid-claim leaves the range unreliable but span_owner exact),
// rebuilding each heap to fresh-store state. Caller holds the span
// mutex. Idempotent: a stripe already reclaimed by its own crash
// repair (span_owner cleared) is skipped.
void span_free_locked(Store* s, uint32_t slot) {
  Header* h = s->hdr;
  SpanDesc* d = &h->spans[slot];
  st32(&d->state, kSpanBroken);  // unpublish before the stripes die
  for (uint32_t si = 0; si < h->num_stripes; ++si) {
    if (ld32(&h->stripes[si].span_owner, __ATOMIC_RELAXED) != slot + 1)
      continue;
    StripeGuard g(s, si);
    Stripe* sp = &h->stripes[si];
    if (sp->span_owner != slot + 1) continue;  // reclaimed under us
    sp->span_owner = 0;
    reset_stripe_heap_locked(s, sp);
  }
  memset(d->id, 0, kIdLen);
  d->data_size = d->meta_size = 0;
  d->pin_count = 0;
  d->flags = 0;
  st32(&d->state, kSpanEmpty);
}

// A normal create met a span-owned stripe: if the owning span is dead
// (broken/empty — e.g. a crashed claim whose repair ran elsewhere),
// reclaim the stripe for normal allocation. Caller holds the stripe
// mutex (racing span_free_locked serializes on it; both sides re-check
// span_owner under the lock, so the reclaim happens exactly once).
// Returns true when the stripe is usable for normal allocation.
bool reclaim_dead_span_stripe_locked(Store* s, uint32_t si) {
  Stripe* sp = &s->hdr->stripes[si];
  uint32_t slot = sp->span_owner - 1;
  uint32_t st = slot < kMaxSpans
                    ? ld32(&s->hdr->spans[slot].state)
                    : (uint32_t)kSpanEmpty;  // corrupt owner: reclaim
  if (st != kSpanEmpty && st != kSpanBroken) return false;
  sp->span_owner = 0;
  reset_stripe_heap_locked(s, sp);
  return true;
}

// Evict whole LRU spans (sealed + unpinned + evictable) until `bytes`
// are freed; broken slots are reclaimed for free. Returns bytes freed.
uint64_t span_evict_bytes(Store* s, uint64_t bytes) {
  Header* h = s->hdr;
  SpanGuard g(s);
  uint64_t freed = 0;
  for (uint32_t k = 0; k < kMaxSpans; ++k)
    if (ld32(&h->spans[k].state) == kSpanBroken) span_free_locked(s, k);
  for (;;) {
    if (freed >= bytes) break;
    int victim = -1;
    uint64_t best_seq = ~0ULL;
    for (uint32_t k = 0; k < kMaxSpans; ++k) {
      SpanDesc* d = &h->spans[k];
      if (ld32(&d->state) != kSpanSealed || d->pin_count > 0 ||
          (d->flags & 2))
        continue;
      if (d->seq < best_seq) { best_seq = d->seq; victim = (int)k; }
    }
    if (victim < 0) break;
    uint64_t sz = h->spans[victim].data_size + h->spans[victim].meta_size;
    span_free_locked(s, (uint32_t)victim);
    add64(&h->span_evictions, 1, __ATOMIC_RELAXED);
    freed += sz;
  }
  return freed;
}

// Create a spanning object across `m` contiguous whole stripes. Caller
// guarantees need > 0. Returns the base-relative payload offset or a
// negative errno-style code (same contract as rt_create).
int64_t span_create(Store* s, const uint8_t* id, uint64_t data_size,
                    uint64_t meta_size, int evictable) {
  Header* h = s->hdr;
  uint64_t need = data_size + meta_size;
  uint64_t stripe_sz = h->stripes[0].arena_size;
  uint32_t m = (uint32_t)((need + stripe_sz - 1) / stripe_sz);
  if (m == 0) m = 1;
  if (m > h->num_stripes) return -ENOMEM;

  SpanGuard g(s);
  if (span_find(s, id) >= 0) return -EEXIST;
  {  // best-effort dup check against the normal table (same contract as
     // rt_create's lock-free re-home check)
    uint64_t hsh = hash_id(id);
    if (find_lockfree(s, id, hsh, stripe_of(s, hsh)) != kNil)
      return -EEXIST;
  }
  int slot = -1;
  for (int pass = 0; pass < 2 && slot < 0; ++pass) {
    for (uint32_t k = 0; k < kMaxSpans; ++k) {
      uint32_t st = ld32(&h->spans[k].state);
      if (st == kSpanEmpty) { slot = (int)k; break; }
      if (pass && st == kSpanBroken) {  // gc a dead slot and take it
        span_free_locked(s, k);
        slot = (int)k;
        break;
      }
    }
  }
  if (slot < 0) return -ENFILE;

  SpanDesc* d = &h->spans[slot];
  memcpy(d->id, id, kIdLen);
  d->data_size = data_size;
  d->meta_size = meta_size;
  d->pin_count = 0;
  d->flags = evictable ? 0 : 2;
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  d->ctime_sec = (uint64_t)ts.tv_sec;
  // publish CLAIMING before touching any stripe: if we die mid-claim,
  // the span-mutex repair frees exactly this slot's claimed stripes
  st32(&d->state, kSpanClaiming);

  // Two passes over the candidate windows: first claim only windows
  // whose residents LRU-evict cleanly; pinned/unsealed residents fail
  // the window and the claim slides on. (The caller's spill+retry is
  // the pressure valve when every window is blocked.)
  for (uint32_t start = 0; start + m <= h->num_stripes; ++start) {
    uint32_t claimed = 0;
    for (; claimed < m; ++claimed) {
      uint32_t si = start + claimed;
      StripeGuard sg(s, si);
      Stripe* sp = &h->stripes[si];
      if (sp->span_owner && !reclaim_dead_span_stripe_locked(s, si)) break;
      if (sp->bytes_in_use) evict_stripe_locked(s, si, sp->arena_size);
      if (sp->bytes_in_use || sp->num_objects) break;
      sp->span_owner = (uint32_t)slot + 1;
      // claimed stripes read as fully used: stats, spill-pressure
      // probes and sweep targeting all see the span's footprint
      sp->bytes_in_use = sp->arena_size;
      // chaos hook: die HERE — span mutex + this stripe's mutex held,
      // descriptor CLAIMING, stripe marked but span unpublished
      chaos_maybe_crash_in_span_create();
    }
    if (claimed == m) {
      d->first_stripe = start;
      d->n_stripes = m;
      d->seq = ++h->span_clock;
      st32(&d->state, kSpanCreated);  // release: publishes the span
      add64(&h->span_creates, 1, __ATOMIC_RELAXED);
      return (int64_t)h->stripes[start].arena_off;
    }
    // window failed: unwind this window's claims
    for (uint32_t u = 0; u < claimed; ++u) {
      StripeGuard sg(s, start + u);
      Stripe* sp = &h->stripes[start + u];
      if (sp->span_owner == (uint32_t)slot + 1) {
        sp->span_owner = 0;
        reset_stripe_heap_locked(s, sp);
      }
    }
  }
  memset(d->id, 0, kIdLen);
  st32(&d->state, kSpanEmpty);
  return -ENOMEM;
}

// ------------------------------------------------------------ copy pool
// Chunked arena copies for the put hot path: the Python binding (ctypes)
// drops the GIL for the duration of the call, and the pool spreads large
// memcpys across a few threads. Per-call latency on a 1-core host is the
// memcpy itself (nthreads<=1 short-circuits to a plain memcpy, no pool
// wakeup); wider hosts split the copy into near-equal 64B-aligned chunks.
struct CopyBatch {
  std::mutex mu;
  std::condition_variable cv;
  int remaining = 0;
};

struct CopyChunk {
  uint8_t* dst;
  const uint8_t* src;
  uint64_t n;
  CopyBatch* batch;
};

class CopyPool {
 public:
  static CopyPool& Instance() {
    static CopyPool* pool = new CopyPool();  // never destroyed: workers may
    return *pool;                            // outlive static teardown order
  }

  // Copy n bytes dst<-src split across `nchunks` pieces; the calling
  // thread copies the first chunk itself, pool threads do the rest.
  void Run(uint8_t* dst, const uint8_t* src, uint64_t n, int nchunks) {
    if (nchunks > kMaxThreads) nchunks = kMaxThreads;
    // 64B-aligned chunk size so no two threads share a cache line
    uint64_t chunk = (n / nchunks + 63) & ~63ULL;
    int pieces = (int)((n + chunk - 1) / chunk);
    if (pieces <= 1) {
      memcpy(dst, src, n);
      return;
    }
    EnsureThreads(pieces - 1);
    CopyBatch batch;
    batch.remaining = pieces - 1;
    {
      std::lock_guard<std::mutex> g(mu_);
      for (int i = 1; i < pieces; i++) {
        uint64_t off = (uint64_t)i * chunk;
        uint64_t len = off + chunk <= n ? chunk : n - off;
        q_.push_back({dst + off, src + off, len, &batch});
      }
    }
    cv_.notify_all();
    memcpy(dst, src, chunk);  // caller's share overlaps the workers
    std::unique_lock<std::mutex> g(batch.mu);
    batch.cv.wait(g, [&] { return batch.remaining == 0; });
  }

 private:
  static constexpr int kMaxThreads = 16;

  void EnsureThreads(int want) {
    std::lock_guard<std::mutex> g(mu_);
    while ((int)threads_.size() < want && (int)threads_.size() < kMaxThreads)
      threads_.emplace_back([this] { WorkerLoop(); });
  }

  void WorkerLoop() {
    for (;;) {
      CopyChunk c;
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_.wait(g, [this] { return !q_.empty(); });
        c = q_.front();
        q_.pop_front();
      }
      memcpy(c.dst, c.src, c.n);
      {
        // notify while holding the lock: the batch lives on the caller's
        // stack and is destroyed the moment Run() observes remaining==0,
        // so the cv must not be touched after this block releases mu
        std::lock_guard<std::mutex> g(c.batch->mu);
        c.batch->remaining--;
        c.batch->cv.notify_one();
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<CopyChunk> q_;
  std::vector<std::thread> threads_;
};

// How many stripes a new store gets. Explicit request wins; otherwise the
// RAY_TPU_ARENA_STRIPES env var; otherwise size/kMinStripeBytes capped at
// 8 — so small test arenas stay single-stripe (exactly the v1 behavior)
// and production-sized arenas stripe wide enough for node-local clients.
uint32_t resolve_stripes(uint64_t arena_size, int requested) {
  long n = requested;
  if (n <= 0) {
    const char* env = getenv("RAY_TPU_ARENA_STRIPES");
    n = env ? atol(env) : 0;
    if (n <= 0) {
      n = (long)(arena_size / kMinStripeBytes);
      if (n > 8) n = 8;
    }
  }
  if (n < 1) n = 1;
  if (n > (long)kMaxStripes) n = (long)kMaxStripes;
  // hard floor: a stripe smaller than 1 MiB cannot hold real objects
  while (n > 1 && arena_size / (uint64_t)n < (1ULL << 20)) n--;
  return (uint32_t)n;
}

struct StripeSnap {
  uint64_t bytes_in_use, capacity, num_objects, num_evictions,
      bytes_evicted, create_count, get_hits, get_misses, repairs,
      seal_count, poisoned;
};

void read_stripe_fields(Stripe* sp, StripeSnap* o) {
  o->bytes_in_use = ld64(&sp->bytes_in_use, __ATOMIC_RELAXED);
  o->capacity = sp->arena_size;
  o->num_objects = ld64(&sp->num_objects, __ATOMIC_RELAXED);
  o->num_evictions = ld64(&sp->num_evictions, __ATOMIC_RELAXED);
  o->bytes_evicted = ld64(&sp->bytes_evicted, __ATOMIC_RELAXED);
  o->create_count = ld64(&sp->create_count, __ATOMIC_RELAXED);
  o->get_hits = ld64(&sp->get_hits, __ATOMIC_RELAXED);
  o->get_misses = ld64(&sp->get_misses, __ATOMIC_RELAXED);
  o->repairs = ld64(&sp->repairs, __ATOMIC_RELAXED);
  o->seal_count = ld64(&sp->seal_count, __ATOMIC_RELAXED);
  o->poisoned = ld32(&sp->poisoned, __ATOMIC_RELAXED);
}

// Seqlock read of one stripe's counters; never blocks on a healthy store.
// Falls back to the mutex only when a writer looks stuck — which is
// exactly the robust-recovery probe needed if that writer is dead.
void snapshot_stripe(Store* s, uint32_t si, StripeSnap* o) {
  Stripe* sp = &s->hdr->stripes[si];
  for (int spin = 0; spin < 4096; ++spin) {
    uint64_t s0 = ld64(&sp->lockseq);
    if (s0 & 1) continue;
    read_stripe_fields(sp, o);
    __atomic_thread_fence(__ATOMIC_ACQUIRE);
    if (ld64(&sp->lockseq) == s0) {
      // validated: pair with the writer's RT_TSAN_RELEASE in
      // ~StripeGuard — the snapshot happens-after the last closed window
      RT_TSAN_ACQUIRE(&sp->lockseq);
      return;
    }
  }
  StripeGuard g(s, si);
  read_stripe_fields(sp, o);
}

}  // namespace

extern "C" {

// Chunked (optionally multi-threaded) memcpy into the arena. Called via
// ctypes, which releases the GIL for the duration — large put copies no
// longer serialize every Python thread in the process. threads<=1 (or a
// copy too small to split) is a plain memcpy on the calling thread.
void rt_write_parallel(void* dst, const void* src, uint64_t n, int threads) {
  if (n == 0) return;
  if (threads <= 1 || n < (1u << 20)) {
    memcpy(dst, src, n);
    return;
  }
  CopyPool::Instance().Run(static_cast<uint8_t*>(dst),
                           static_cast<const uint8_t*>(src), n, threads);
}

// Create a fresh store. `stripes` <= 0 resolves via RAY_TPU_ARENA_STRIPES
// then size-based auto-striping.
void* rt_store_create(const char* path, uint64_t size, int stripes) {
  // Always create a fresh inode (O_EXCL after unlink): truncating an
  // existing path would SIGBUS any process still mapping the old store.
  unlink(path);
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  uint64_t table_bytes = align_up(sizeof(Entry) * (uint64_t)kTableCapacity, 4096);
  uint64_t header_bytes = align_up(sizeof(Header), 4096);
  uint64_t total = align_up(header_bytes + table_bytes + size, 4096);
  if (ftruncate(fd, (off_t)total) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  // Hugepage-advise the arena: first-touch fault cost dominates large-object
  // writes on virtualized hosts (measured 30x on 4K faults); THP cuts the
  // fault count ~512x.
  madvise(mem, total, MADV_HUGEPAGE);

  Store* s = new Store();
  s->base = static_cast<uint8_t*>(mem);
  s->hdr = reinterpret_cast<Header*>(mem);
  s->table = reinterpret_cast<Entry*>(s->base + header_bytes);
  s->map_size = total;
  s->fd = fd;

  Header* h = s->hdr;
  memset(h, 0, sizeof(Header));
  memset(s->table, 0, sizeof(Entry) * (uint64_t)kTableCapacity);
  h->version = kVersion;
  h->table_capacity = kTableCapacity;
  h->total_size = total;
  h->arena_offset = header_bytes + table_bytes;
  h->arena_size = total - h->arena_offset;
  h->num_stripes = resolve_stripes(h->arena_size, stripes);

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);

  pthread_mutex_init(&h->span_mutex, &attr);

  uint64_t stripe_sz = (h->arena_size / h->num_stripes) & ~(kAlign - 1);
  uint32_t seg_len = kTableCapacity / h->num_stripes;
  for (uint32_t i = 0; i < h->num_stripes; ++i) {
    Stripe* sp = &h->stripes[i];
    pthread_mutex_init(&sp->mutex, &attr);
    sp->arena_off = h->arena_offset + (uint64_t)i * stripe_sz;
    sp->arena_size = stripe_sz;
    sp->seg_start = i * seg_len;
    sp->seg_len = seg_len;
    sp->free_head = kNone;
    Block* b = at(s, sp, 0);
    set_size(b, sp->arena_size, true);
    b->prev_size = 0;
    b->next_free = kNone;
    b->prev_free = kNone;
    sp->free_head = 0;
  }
  pthread_mutexattr_destroy(&attr);

  std::atomic_thread_fence(std::memory_order_seq_cst);
  h->magic = kMagic;  // publish last
  return s;
}

void* rt_store_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  madvise(mem, (size_t)st.st_size, MADV_HUGEPAGE);
  Header* h = reinterpret_cast<Header*>(mem);
  if (h->magic != kMagic || h->version != kVersion) {
    munmap(mem, (size_t)st.st_size);
    close(fd);
    return nullptr;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(mem);
  s->hdr = h;
  uint64_t header_bytes = align_up(sizeof(Header), 4096);
  s->table = reinterpret_cast<Entry*>(s->base + header_bytes);
  s->map_size = h->total_size;
  s->fd = fd;
  return s;
}

void rt_store_close(void* hs) {
  Store* s = static_cast<Store*>(hs);
  munmap(s->base, s->map_size);
  close(s->fd);
  delete s;
}

uint8_t* rt_store_base(void* hs) { return static_cast<Store*>(hs)->base; }

uint32_t rt_num_stripes(void* hs) {
  return static_cast<Store*>(hs)->hdr->num_stripes;
}

uint64_t rt_store_capacity(void* hs) {
  // usable capacity = sum of stripe slices (alignment slack excluded)
  Store* s = static_cast<Store*>(hs);
  return (uint64_t)s->hdr->num_stripes * s->hdr->stripes[0].arena_size;
}

uint64_t rt_store_total_size(void* hs) { return static_cast<Store*>(hs)->hdr->total_size; }

// Create an object buffer. Returns base-relative offset of the payload
// (data followed by metadata), or a negative errno-style code:
//   -EEXIST already exists, -ENOMEM no space even after per-stripe
//   eviction, -ENFILE table full.
//
// Lock discipline: the fast path (home stripe has room) takes exactly one
// stripe lock. Under pressure the create walks the other stripes
// round-robin — sequentially, never holding two locks at once — first
// without eviction, then with per-stripe eviction as a last resort (the
// node manager's sweep keeps stripes below watermark so this stays rare).
int64_t rt_create(void* hs, const uint8_t* id, uint64_t data_size,
                  uint64_t meta_size, int evictable) {
  Store* s = static_cast<Store*>(hs);
  uint64_t need = data_size + meta_size;
  uint64_t h = hash_id(id);
  uint32_t nstripes = s->hdr->num_stripes;
  uint32_t home = stripe_of(s, h);

  // size-aware route: an object no single stripe can hold takes the
  // spanning path (contiguous whole stripes) — the Python client and
  // every transfer path gain multi-GB objects with no API change
  if (align_up(need + kBlockHeader, kAlign) > s->hdr->stripes[0].arena_size)
    return span_create(s, id, data_size, meta_size, evictable);
  if (span_find(s, id) >= 0) return -EEXIST;

  // duplicate check for re-homed objects: best-effort lock-free (exact
  // within the home stripe below; a concurrent same-id double-create is
  // caller misuse and at worst wastes one block until delete)
  if (ld64(&s->hdr->fallback_count, __ATOMIC_RELAXED) != 0 &&
      find_lockfree(s, id, h, home) != kNil)
    return -EEXIST;

  int64_t soft_rc = -ENOMEM;
  // pass 0: no evict; 1: per-stripe LRU evict; 2: only reached when
  // whole-span eviction freed stripes back to the normal allocator
  for (int pass = 0; pass < 3; ++pass) {
    if (pass == 2 && span_evict_bytes(s, need) == 0) break;
    for (uint32_t k = 0; k < nstripes; ++k) {
      uint32_t si = (home + k) % nstripes;
      Stripe* sp = &s->hdr->stripes[si];
      StripeGuard g(s, si);
      if (sp->span_owner && !reclaim_dead_span_stripe_locked(s, si))
        continue;  // the stripe belongs to a live spanning object
      if (probe_segment(s, si, id, h) != kNil) return -EEXIST;
      uint32_t slot = segment_slot(s, si, h);
      if (slot == kNil) { soft_rc = -ENFILE; continue; }
      uint64_t off = heap_alloc(s, sp, need);
      if (off == kNone && pass >= 1) {
        evict_stripe_locked(s, si, need);
        off = heap_alloc(s, sp, need);
      }
      if (off == kNone) continue;
      Entry* e = &s->table[slot];
      memcpy(e->id, id, kIdLen);
      // chaos hook: die HERE — lock held, heap mutated, entry half-written
      chaos_maybe_crash_in_create();
      e->stripe = si;
      e->offset = off;
      e->data_size = data_size;
      e->meta_size = meta_size;
      st32(&e->pin_count, 1, __ATOMIC_RELAXED);  // creator pin until seal
      e->flags = evictable ? 0 : 2;
      struct timespec ts;
      clock_gettime(CLOCK_MONOTONIC, &ts);
      e->ctime_sec = (uint64_t)ts.tv_sec;
      st64(&e->seq, add64(&sp->lru_clock, 1) + 1, __ATOMIC_RELAXED);
      st32(&e->state, kCreated);  // release: publishes the entry
      sp->num_objects++;
      sp->create_count++;
      if (si != home) add64(&s->hdr->fallback_count, 1);
      return (int64_t)(sp->arena_off + off);
    }
  }
  return soft_rc;
}

// Seal: lock-free CREATED -> SEALED transition. Takes no heap lock on the
// fast path; the locked fallback only runs when a concurrent tombstone
// compaction hid the entry from the lock-free probe (vanishingly rare).
int rt_seal(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  uint64_t h = hash_id(id);
  uint32_t home = stripe_of(s, h);
  uint32_t idx = find_lockfree(s, id, h, home);
  if (idx == kNil && span_find(s, id) >= 0) {
    // spanning object: CREATED -> SEALED under the span mutex (the
    // lock cost is nothing next to the multi-GB payload copy)
    SpanGuard g(s);
    int k = span_find(s, id);
    if (k < 0) return -ENOENT;
    SpanDesc* d = &s->hdr->spans[k];
    d->seq = ++s->hdr->span_clock;
    if (!cas32(&d->state, kSpanCreated, kSpanSealed)) {
      uint32_t now = ld32(&d->state);
      return (now == kSpanSealed) ? -EINVAL : -ENOENT;
    }
    return 0;
  }
  if (idx == kNil) {
    // confirm the miss under the locks before failing
    uint32_t n = s->hdr->num_stripes;
    for (uint32_t k = 0; k < n && idx == kNil; ++k) {
      StripeGuard g(s, (home + k) % n);
      idx = probe_segment(s, (home + k) % n, id, h);
    }
    if (idx == kNil) return -ENOENT;
  }
  Entry* e = &s->table[idx];
  Stripe* sp = &s->hdr->stripes[segment_of(s, idx)];
  // Order matters: the creator pin must read 0 and the LRU stamp must be
  // set BEFORE the release-CAS publishes SEALED — a get() that observes
  // SEALED (acquire) then sees a consistent entry. Only the creator can
  // legally seal, so the entry cannot be freed+reused under us (gc only
  // reaps CREATED entries minutes old).
  st32(&e->pin_count, 0, __ATOMIC_RELAXED);
  st64(&e->seq, add64(&sp->lru_clock, 1) + 1, __ATOMIC_RELAXED);
  if (!cas32(&e->state, kCreated, kSealed)) {
    uint32_t now = ld32(&e->state);
    return (now == kEmpty || now == kTombstone) ? -ENOENT : -EINVAL;
  }
  add64(&sp->seal_count, 1, __ATOMIC_RELAXED);
  return 0;
}

// Look up a sealed object. On hit fills sizes, pins if pin!=0, returns
// base-relative payload offset. -ENOENT if absent or not sealed. Takes
// exactly one stripe lock on a hit; a miss confirms under the locks (a
// lock-free probe can race tombstone compaction).
int64_t rt_get(void* hs, const uint8_t* id, uint64_t* data_size,
               uint64_t* meta_size, int pin) {
  Store* s = static_cast<Store*>(hs);
  int64_t rc = with_entry_locked(s, id, [&](uint32_t si, uint32_t idx) {
    Stripe* sp = &s->hdr->stripes[si];
    Entry* e = &s->table[idx];
    if (ld32(&e->state) != kSealed) return (int64_t)-ENOENT;  // unsealed
    *data_size = e->data_size;
    *meta_size = e->meta_size;
    if (pin) st32(&e->pin_count, ld32(&e->pin_count) + 1, __ATOMIC_RELAXED);
    st64(&e->seq, add64(&sp->lru_clock, 1) + 1, __ATOMIC_RELAXED);
    sp->get_hits++;
    return (int64_t)(sp->arena_off + e->offset);
  });
  if (rc < 0 && span_find(s, id) >= 0) {
    SpanGuard g(s);
    int k = span_find(s, id);
    if (k >= 0 && ld32(&s->hdr->spans[k].state) == kSpanSealed) {
      SpanDesc* d = &s->hdr->spans[k];
      *data_size = d->data_size;
      *meta_size = d->meta_size;
      if (pin) d->pin_count++;
      d->seq = ++s->hdr->span_clock;
      // span hits attribute to the head stripe (atomic: no stripe lock)
      add64(&s->hdr->stripes[d->first_stripe].get_hits, 1,
            __ATOMIC_RELAXED);
      return (int64_t)s->hdr->stripes[d->first_stripe].arena_off;
    }
  }
  if (rc < 0) {
    uint32_t home = stripe_of(s, hash_id(id));
    add64(&s->hdr->stripes[home].get_misses, 1, __ATOMIC_RELAXED);
  }
  return rc;
}

int rt_release(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  int rc = (int)with_entry_locked(s, id, [&](uint32_t si, uint32_t idx) {
    Entry* e = &s->table[idx];
    uint32_t st = ld32(&e->state);
    uint32_t pins = ld32(&e->pin_count, __ATOMIC_RELAXED);
    if (pins > 0) st32(&e->pin_count, pins - 1, __ATOMIC_RELAXED);
    if ((e->flags & 1) && pins <= 1) entry_free_from(s, si, idx, st);
    return (int64_t)0;
  });
  if (rc == -ENOENT && span_find(s, id) >= 0) {
    SpanGuard g(s);
    int k = span_find(s, id);
    if (k < 0) return -ENOENT;
    SpanDesc* d = &s->hdr->spans[k];
    if (d->pin_count > 0) d->pin_count--;
    if ((d->flags & 1) && d->pin_count == 0) span_free_locked(s, (uint32_t)k);
    return 0;
  }
  return rc;
}

int rt_contains(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  {
    int k = span_find(s, id);
    if (k >= 0)
      return ld32(&s->hdr->spans[k].state) == kSpanSealed ? 1 : 0;
  }
  uint64_t h = hash_id(id);
  uint32_t home = stripe_of(s, h);
  uint32_t idx = find_lockfree(s, id, h, home);
  if (idx != kNil)
    return ld32(&s->table[idx].state) == kSealed &&
                   memcmp(s->table[idx].id, id, kIdLen) == 0
               ? 1
               : 0;
  // lock-free probes can race tombstone compaction: confirm the miss
  uint32_t n = s->hdr->num_stripes;
  uint32_t scan = ld64(&s->hdr->fallback_count, __ATOMIC_RELAXED) ? n : 1;
  for (uint32_t k = 0; k < scan; ++k) {
    uint32_t si = (home + k) % n;
    StripeGuard g(s, si);
    idx = probe_segment(s, si, id, h);
    if (idx != kNil) return ld32(&s->table[idx].state) == kSealed ? 1 : 0;
  }
  return 0;
}

// Delete (deferred if pinned). -ENOENT if absent.
int rt_delete(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  if (span_find(s, id) >= 0) {
    SpanGuard g(s);
    int k = span_find(s, id);
    if (k >= 0) {
      SpanDesc* d = &s->hdr->spans[k];
      if (d->pin_count > 0)
        d->flags |= 1;  // delete-pending; release completes it
      else
        span_free_locked(s, (uint32_t)k);
      return 0;
    }
  }
  return (int)with_entry_locked(s, id, [&](uint32_t si, uint32_t idx) {
    Entry* e = &s->table[idx];
    uint32_t st = ld32(&e->state);
    if (ld32(&e->pin_count, __ATOMIC_RELAXED) > 0) {
      e->flags |= 1;  // delete-pending
      return (int64_t)0;
    }
    if (entry_free_from(s, si, idx, st)) return (int64_t)0;
    // a lock-free seal raced the CAS: retry from the (now SEALED) state
    if (entry_free_from(s, si, idx, kSealed)) return (int64_t)0;
    return (int64_t)-ENOENT;
  });
}

// Abort an in-progress creation (writer failed before seal).
int rt_abort(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  if (span_find(s, id) >= 0) {
    SpanGuard g(s);
    int k = span_find(s, id);
    if (k >= 0) {
      if (ld32(&s->hdr->spans[k].state) != kSpanCreated) return -EINVAL;
      span_free_locked(s, (uint32_t)k);
      return 0;
    }
  }
  return (int)with_entry_locked(s, id, [&](uint32_t si, uint32_t idx) {
    if (ld32(&s->table[idx].state) != kCreated) return (int64_t)-EINVAL;
    return entry_free_from(s, si, idx, kCreated) ? (int64_t)0
                                                 : (int64_t)-EINVAL;
  });
}

// Reclaim CREATED-but-never-sealed objects older than max_age_sec — their
// writer likely died before sealing. Returns number reclaimed. Called
// periodically by the node daemon's sweep.
uint64_t rt_gc_unsealed(void* hs, uint64_t max_age_sec) {
  Store* s = static_cast<Store*>(hs);
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  uint64_t now = (uint64_t)ts.tv_sec;
  uint64_t n = 0;
  for (uint32_t si = 0; si < s->hdr->num_stripes; ++si) {
    Stripe* sp = &s->hdr->stripes[si];
    StripeGuard g(s, si);
    for (uint32_t i = sp->seg_start; i < sp->seg_start + sp->seg_len; ++i) {
      Entry* e = &s->table[i];
      if (ld32(&e->state, __ATOMIC_RELAXED) == kCreated &&
          now - e->ctime_sec >= max_age_sec &&
          entry_free_from(s, si, i, kCreated))  // CAS guards racing seals
        ++n;
    }
  }
  {
    // span pass: broken spans reclaim unconditionally (deterministic
    // cleanup after a crash repair marked them); CREATED-but-unsealed
    // spans age out exactly like entries. kSpanClaiming slots can only
    // belong to a dead writer once we hold the span mutex — free them.
    SpanGuard g(s);
    for (uint32_t k = 0; k < kMaxSpans; ++k) {
      SpanDesc* d = &s->hdr->spans[k];
      uint32_t st = ld32(&d->state);
      if (st == kSpanBroken || st == kSpanClaiming ||
          (st == kSpanCreated && now - d->ctime_sec >= max_age_sec)) {
        span_free_locked(s, k);
        ++n;
      }
    }
  }
  return n;
}

// Evict up to `bytes` from one stripe (node-manager sweep entry point).
uint64_t rt_evict_stripe(void* hs, uint32_t stripe, uint64_t bytes) {
  Store* s = static_cast<Store*>(hs);
  if (stripe >= s->hdr->num_stripes) return 0;
  StripeGuard g(s, stripe);
  return evict_stripe_locked(s, stripe, bytes);
}

uint64_t rt_evict(void* hs, uint64_t bytes) {
  Store* s = static_cast<Store*>(hs);
  uint64_t freed = 0;
  for (uint32_t si = 0; si < s->hdr->num_stripes && freed < bytes; ++si) {
    StripeGuard g(s, si);
    freed += evict_stripe_locked(s, si, bytes - freed);
  }
  if (freed < bytes)
    freed += span_evict_bytes(s, bytes - freed);  // whole spans, never half
  return freed;
}

// Aggregate store stats, served lock-free from per-stripe seqlock
// snapshots — a stats poll never queues behind a client's create.
// out[17]: bytes_in_use, capacity, num_objects, num_evictions,
// bytes_evicted, create_count, get_hits, get_misses, poisoned,
// num_stripes, stripe_repairs, create_fallbacks, seal_count,
// num_spans, span_creates, span_evictions, span_repairs.
void rt_stats(void* hs, uint64_t* out) {
  Store* s = static_cast<Store*>(hs);
  memset(out, 0, 17 * sizeof(uint64_t));
  for (uint32_t si = 0; si < s->hdr->num_stripes; ++si) {
    StripeSnap sn;
    snapshot_stripe(s, si, &sn);
    out[0] += sn.bytes_in_use;
    out[1] += sn.capacity;
    out[2] += sn.num_objects;
    out[3] += sn.num_evictions;
    out[4] += sn.bytes_evicted;
    out[5] += sn.create_count;
    out[6] += sn.get_hits;
    out[7] += sn.get_misses;
    out[8] += sn.poisoned;
    out[10] += sn.repairs;
    out[12] += sn.seal_count;
  }
  out[9] = s->hdr->num_stripes;
  out[11] = ld64(&s->hdr->fallback_count, __ATOMIC_RELAXED);
  for (uint32_t k = 0; k < kMaxSpans; ++k) {
    uint32_t st = ld32(&s->hdr->spans[k].state);
    if (st == kSpanCreated || st == kSpanSealed) {
      out[13]++;   // live spans
      out[2]++;    // a span is a live object too
    }
  }
  out[14] = ld64(&s->hdr->span_creates, __ATOMIC_RELAXED);
  out[15] = ld64(&s->hdr->span_evictions, __ATOMIC_RELAXED);
  out[16] = ld64(&s->hdr->span_repairs, __ATOMIC_RELAXED);
}

// Per-stripe stats (lock-free snapshot) for sweep targeting and bench
// attribution. out[8]: bytes_in_use, capacity, num_objects,
// num_evictions, bytes_evicted, repairs, poisoned, seal_count.
void rt_stripe_stats(void* hs, uint32_t stripe, uint64_t* out) {
  Store* s = static_cast<Store*>(hs);
  memset(out, 0, 8 * sizeof(uint64_t));
  if (stripe >= s->hdr->num_stripes) return;
  StripeSnap sn;
  snapshot_stripe(s, stripe, &sn);
  out[0] = sn.bytes_in_use;
  out[1] = sn.capacity;
  out[2] = sn.num_objects;
  out[3] = sn.num_evictions;
  out[4] = sn.bytes_evicted;
  out[5] = sn.repairs;
  out[6] = sn.poisoned;
  out[7] = sn.seal_count;
}

// List up to max_n sealed object ids of ONE stripe into out.
uint64_t rt_list_stripe(void* hs, uint32_t stripe, uint8_t* out,
                        uint64_t max_n) {
  Store* s = static_cast<Store*>(hs);
  if (stripe >= s->hdr->num_stripes) return 0;
  Stripe* sp = &s->hdr->stripes[stripe];
  StripeGuard g(s, stripe);
  uint64_t n = 0;
  for (uint32_t i = sp->seg_start; i < sp->seg_start + sp->seg_len && n < max_n;
       ++i) {
    Entry* e = &s->table[i];
    if (ld32(&e->state, __ATOMIC_RELAXED) == kSealed) {
      memcpy(out + n * kIdLen, e->id, kIdLen);
      ++n;
    }
  }
  return n;
}

// List up to max_n sealed object ids into out (max_n * kIdLen bytes).
// Locks stripes one at a time — never the whole store. Sealed spanning
// objects are appended after the per-stripe listings.
uint64_t rt_list(void* hs, uint8_t* out, uint64_t max_n) {
  Store* s = static_cast<Store*>(hs);
  uint64_t n = 0;
  for (uint32_t si = 0; si < s->hdr->num_stripes && n < max_n; ++si)
    n += rt_list_stripe(hs, si, out + n * kIdLen, max_n - n);
  for (uint32_t k = 0; k < kMaxSpans && n < max_n; ++k) {
    SpanDesc* d = &s->hdr->spans[k];
    if (ld32(&d->state) == kSpanSealed) {
      memcpy(out + n * kIdLen, d->id, kIdLen);
      ++n;
    }
  }
  return n;
}

// -------------------------------------------------- observability ABI
// Read-only widening for the object-lifetime ledger and the memory
// observability surface (`ray_tpu memory`): per-object provenance
// probes, a free-list fragmentation walk, and the monotonic clock the
// ctime stamps are taken against (so readers can turn ctime_sec into an
// age without guessing the clock base).

// CLOCK_MONOTONIC seconds — the base of every ctime_sec stamp.
uint64_t rt_now_sec(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec;
}

// Per-object info probe — no pin, no LRU touch, no payload access.
// out[8]: data_size, meta_size, pin_count, stripe (owning stripe, or a
// span's first stripe), ctime_sec, is_span, sealed, flags.
// Returns 0 on a live object, -ENOENT otherwise. Spans are read
// lock-free (advisory snapshot, same contract as rt_span_stats);
// entries confirm under the owning stripe's lock so a racing free can't
// hand back a reused slot's fields.
int64_t rt_object_info(void* hs, const uint8_t* id, uint64_t* out) {
  Store* s = static_cast<Store*>(hs);
  memset(out, 0, 8 * sizeof(uint64_t));
  {
    int k = span_find(s, id);
    if (k >= 0) {
      SpanDesc* d = &s->hdr->spans[k];
      uint32_t st = ld32(&d->state);
      if (st == kSpanCreated || st == kSpanSealed) {
        out[0] = d->data_size;
        out[1] = d->meta_size;
        out[2] = ld32(&d->pin_count, __ATOMIC_RELAXED);
        out[3] = d->first_stripe;
        out[4] = d->ctime_sec;
        out[5] = 1;
        out[6] = st == kSpanSealed ? 1 : 0;
        out[7] = d->flags;
        return 0;
      }
    }
  }
  return with_entry_locked(s, id, [&](uint32_t si, uint32_t idx) {
    Entry* e = &s->table[idx];
    out[0] = e->data_size;
    out[1] = e->meta_size;
    out[2] = ld32(&e->pin_count, __ATOMIC_RELAXED);
    out[3] = si;
    out[4] = e->ctime_sec;
    out[5] = 0;
    out[6] = ld32(&e->state) == kSealed ? 1 : 0;
    out[7] = e->flags;
    return (int64_t)0;
  });
}

// Fragmentation walk of ONE stripe's free list (under its lock — this
// is a diagnostic path polled at census cadence, not a hot path).
// out[4]: free_bytes (sum of free block sizes incl. headers),
// largest_hole (largest free block, i.e. the biggest single allocation
// the stripe could serve +/- header/alignment), free_blocks,
// bytes_in_use. A stripe claimed by a spanning object reports zero
// free bytes — its heap belongs to the span wholesale.
void rt_stripe_frag(void* hs, uint32_t stripe, uint64_t* out) {
  Store* s = static_cast<Store*>(hs);
  memset(out, 0, 4 * sizeof(uint64_t));
  if (stripe >= s->hdr->num_stripes) return;
  Stripe* sp = &s->hdr->stripes[stripe];
  StripeGuard g(s, stripe);
  if (sp->span_owner) {
    out[3] = sp->arena_size;
    return;
  }
  uint64_t off = sp->free_head;
  while (off != kNone) {
    Block* b = at(s, sp, off);
    uint64_t sz = blk_size(b);
    out[0] += sz;
    if (sz > out[1]) out[1] = sz;
    out[2]++;
    off = b->next_free;
  }
  out[3] = sp->bytes_in_use;
}

// ------------------------------------------------- spanning-object ABI

// Largest payload (data+meta) the per-stripe allocator can hold; one
// byte more routes to the spanning path. Lets clients and benches pick
// sizes that deterministically exercise either side.
uint64_t rt_max_alloc_bytes(void* hs) {
  Store* s = static_cast<Store*>(hs);
  uint64_t sz = s->hdr->stripes[0].arena_size;
  return (sz & ~(kAlign - 1)) - kBlockHeader;
}

// Force the spanning path regardless of size (tests exercise span
// machinery without multi-GB arenas). Same contract as rt_create.
int64_t rt_create_spanning(void* hs, const uint8_t* id, uint64_t data_size,
                           uint64_t meta_size, int evictable) {
  Store* s = static_cast<Store*>(hs);
  if (data_size + meta_size == 0) return -EINVAL;
  if (rt_contains(hs, id)) return -EEXIST;
  return span_create(s, id, data_size, meta_size, evictable);
}

// 1 when id names a live spanning object (created or sealed).
int rt_is_span(void* hs, const uint8_t* id) {
  return span_find(static_cast<Store*>(hs), id) >= 0 ? 1 : 0;
}

// Span-plane snapshot (lock-free reads; counters are advisory).
// out[8]: live_spans, span_bytes (data+meta of live spans),
// stripes_claimed, span_creates, span_evictions, span_repairs,
// broken_slots, max_span_bytes (whole-arena ceiling for one object).
void rt_span_stats(void* hs, uint64_t* out) {
  Store* s = static_cast<Store*>(hs);
  Header* h = s->hdr;
  memset(out, 0, 8 * sizeof(uint64_t));
  for (uint32_t k = 0; k < kMaxSpans; ++k) {
    SpanDesc* d = &h->spans[k];
    uint32_t st = ld32(&d->state);
    if (st == kSpanCreated || st == kSpanSealed) {
      out[0]++;
      out[1] += d->data_size + d->meta_size;
    } else if (st == kSpanBroken) {
      out[6]++;
    }
  }
  for (uint32_t si = 0; si < h->num_stripes; ++si)
    if (ld32(&h->stripes[si].span_owner, __ATOMIC_RELAXED)) out[2]++;
  out[3] = ld64(&h->span_creates, __ATOMIC_RELAXED);
  out[4] = ld64(&h->span_evictions, __ATOMIC_RELAXED);
  out[5] = ld64(&h->span_repairs, __ATOMIC_RELAXED);
  out[7] = (uint64_t)h->num_stripes * h->stripes[0].arena_size;
}

}  // extern "C"
