// shm_store.cpp — node-local shared-memory object store.
//
// TPU-native re-design of the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.cc, plasma_allocator.h).
// Unlike plasma (a store *server* that clients reach over a unix socket with
// fd-passing), the entire store — allocator, object table, LRU — lives in one
// file-backed shared-memory arena that every process on the node maps at a
// known path. create/seal/get/release are direct shared-memory operations
// under a robust process-shared mutex: no socket round trip, no fd passing.
// The node daemon only coordinates eviction-to-remote and cross-node transfer.
//
// Layout:
//   [Header | ObjectTable (open-addressed) | data arena (boundary-tag heap)]
//
// Object lifecycle: CREATED (writer owns buffer) -> SEALED (immutable,
// readable by all) -> deleted (deferred until pin_count drops to zero).
// Eviction: LRU over sealed, unpinned, evictable objects.

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5250555453544f52ULL;  // "RPUTSTOR"
constexpr uint32_t kVersion = 1;
constexpr uint32_t kIdLen = 20;
constexpr uint32_t kTableCapacity = 1 << 16;  // 65536 entries, power of two
constexpr uint64_t kAlign = 64;
constexpr uint32_t kNil = 0xffffffffu;

// Object states.
enum : uint32_t { kEmpty = 0, kCreated = 1, kSealed = 2, kTombstone = 3 };

struct Entry {
  uint8_t id[kIdLen];
  uint32_t state;
  uint64_t offset;     // offset of payload (data then metadata) from arena base
  uint64_t data_size;
  uint64_t meta_size;
  uint32_t pin_count;
  uint32_t flags;      // bit0: delete-pending, bit1: not-evictable
  uint64_t seq;        // LRU clock value at last touch
  uint64_t ctime_sec;  // CLOCK_MONOTONIC seconds at creation
  uint32_t lru_prev, lru_next;  // doubly-linked LRU list (entry indices)
};

struct Header {
  uint64_t magic;
  uint32_t version;
  uint32_t table_capacity;
  uint64_t total_size;      // whole mapping size
  uint64_t arena_offset;    // start of heap area
  uint64_t arena_size;
  pthread_mutex_t mutex;
  // heap state
  uint64_t free_head;       // offset of first free block (arena-relative), or ~0
  uint64_t bytes_in_use;    // allocated payload bytes (incl. block headers)
  uint64_t num_objects;
  uint64_t lru_clock;
  uint32_t lru_head, lru_tail;  // head = most recent
  uint64_t num_evictions;
  uint64_t bytes_evicted;
  uint64_t create_count;
  uint64_t seal_count;
  uint64_t get_hits;
  uint64_t get_misses;
  uint32_t mutating;   // a mutation is in progress under the lock
  uint32_t poisoned;   // a lock holder died mid-mutation; store is suspect
};

// Boundary-tag heap block. Located in the arena. Size includes the header.
struct Block {
  uint64_t size;       // total block size incl. header; low bit = free flag
  uint64_t prev_size;  // size of physically-previous block (0 if first)
  // free blocks only:
  uint64_t next_free;  // arena offset or ~0
  uint64_t prev_free;  // arena offset or ~0
};

constexpr uint64_t kBlockHeader = 16;  // size + prev_size (used blocks)
constexpr uint64_t kMinBlock = 64;
constexpr uint64_t kNone = ~0ULL;

struct Store {
  Header* hdr;
  uint8_t* base;     // mapping base
  uint8_t* arena;    // heap base
  Entry* table;
  uint64_t map_size;
  int fd;
};

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }
inline bool blk_free(Block* b) { return b->size & 1; }
inline uint64_t blk_size(Block* b) { return b->size & ~1ULL; }
inline void set_size(Block* b, uint64_t s, bool f) { b->size = s | (f ? 1 : 0); }

inline Block* at(Store* s, uint64_t off) {
  return reinterpret_cast<Block*>(s->arena + off);
}
inline uint64_t off_of(Store* s, Block* b) {
  return reinterpret_cast<uint8_t*>(b) - s->arena;
}

void free_list_push(Store* s, Block* b) {
  uint64_t off = off_of(s, b);
  b->next_free = s->hdr->free_head;
  b->prev_free = kNone;
  if (s->hdr->free_head != kNone) at(s, s->hdr->free_head)->prev_free = off;
  s->hdr->free_head = off;
}

void free_list_remove(Store* s, Block* b) {
  if (b->prev_free != kNone)
    at(s, b->prev_free)->next_free = b->next_free;
  else
    s->hdr->free_head = b->next_free;
  if (b->next_free != kNone) at(s, b->next_free)->prev_free = b->prev_free;
}

Block* phys_next(Store* s, Block* b) {
  uint64_t off = off_of(s, b) + blk_size(b);
  if (off >= s->hdr->arena_size) return nullptr;
  return at(s, off);
}

Block* phys_prev(Store* s, Block* b) {
  if (b->prev_size == 0) return nullptr;
  return at(s, off_of(s, b) - b->prev_size);
}

// Allocate `need` payload bytes; returns arena offset of payload or kNone.
uint64_t heap_alloc(Store* s, uint64_t need) {
  uint64_t want = align_up(need + kBlockHeader, kAlign);
  if (want < kMinBlock) want = kMinBlock;
  // first-fit
  uint64_t off = s->hdr->free_head;
  while (off != kNone) {
    Block* b = at(s, off);
    uint64_t bs = blk_size(b);
    if (bs >= want) {
      free_list_remove(s, b);
      if (bs - want >= kMinBlock) {
        // split
        Block* rest = at(s, off + want);
        set_size(rest, bs - want, true);
        rest->prev_size = want;
        Block* nxt = phys_next(s, rest);
        if (nxt) nxt->prev_size = blk_size(rest);
        free_list_push(s, rest);
        set_size(b, want, false);
      } else {
        set_size(b, bs, false);
      }
      s->hdr->bytes_in_use += blk_size(b);
      return off + kBlockHeader;
    }
    off = b->next_free;
  }
  return kNone;
}

void heap_free(Store* s, uint64_t payload_off) {
  Block* b = at(s, payload_off - kBlockHeader);
  s->hdr->bytes_in_use -= blk_size(b);
  set_size(b, blk_size(b), true);
  // coalesce with next
  Block* n = phys_next(s, b);
  if (n && blk_free(n)) {
    free_list_remove(s, n);
    set_size(b, blk_size(b) + blk_size(n), true);
  }
  // coalesce with prev
  Block* p = phys_prev(s, b);
  if (p && blk_free(p)) {
    free_list_remove(s, p);
    set_size(p, blk_size(p) + blk_size(b), true);
    b = p;
  }
  Block* after = phys_next(s, b);
  if (after) after->prev_size = blk_size(b);
  free_list_push(s, b);
}

inline uint64_t hash_id(const uint8_t* id) {
  // Mix all 20 bytes: ids that share a task prefix differ only in the
  // trailing index word, so the tail must feed the hash.
  uint64_t a, b;
  uint32_t c;
  memcpy(&a, id, 8);
  memcpy(&b, id + 8, 8);
  memcpy(&c, id + 16, 4);
  uint64_t h = a ^ (b * 0x9e3779b97f4a7c15ULL) ^ ((uint64_t)c << 17);
  h ^= h >> 33; h *= 0xff51afd7ed558ccdULL; h ^= h >> 33;
  return h;
}

// Find entry index for id; returns kNil if absent.
uint32_t table_find(Store* s, const uint8_t* id) {
  uint32_t mask = s->hdr->table_capacity - 1;
  uint32_t i = static_cast<uint32_t>(hash_id(id)) & mask;
  for (uint32_t probe = 0; probe <= mask; ++probe, i = (i + 1) & mask) {
    Entry* e = &s->table[i];
    if (e->state == kEmpty) return kNil;
    if (e->state != kTombstone && memcmp(e->id, id, kIdLen) == 0) return i;
  }
  return kNil;
}

// Find slot to insert id (assumes not present); kNil if table full.
uint32_t table_slot(Store* s, const uint8_t* id) {
  uint32_t mask = s->hdr->table_capacity - 1;
  uint32_t i = static_cast<uint32_t>(hash_id(id)) & mask;
  for (uint32_t probe = 0; probe <= mask; ++probe, i = (i + 1) & mask) {
    Entry* e = &s->table[i];
    if (e->state == kEmpty || e->state == kTombstone) return i;
  }
  return kNil;
}

void lru_unlink(Store* s, uint32_t i) {
  Entry* e = &s->table[i];
  if (e->lru_prev != kNil) s->table[e->lru_prev].lru_next = e->lru_next;
  else if (s->hdr->lru_head == i) s->hdr->lru_head = e->lru_next;
  if (e->lru_next != kNil) s->table[e->lru_next].lru_prev = e->lru_prev;
  else if (s->hdr->lru_tail == i) s->hdr->lru_tail = e->lru_prev;
  e->lru_prev = e->lru_next = kNil;
}

void lru_push_front(Store* s, uint32_t i) {
  Entry* e = &s->table[i];
  e->lru_prev = kNil;
  e->lru_next = s->hdr->lru_head;
  if (s->hdr->lru_head != kNil) s->table[s->hdr->lru_head].lru_prev = i;
  s->hdr->lru_head = i;
  if (s->hdr->lru_tail == kNil) s->hdr->lru_tail = i;
  e->seq = ++s->hdr->lru_clock;
}

void entry_free(Store* s, uint32_t i) {
  Entry* e = &s->table[i];
  lru_unlink(s, i);
  heap_free(s, e->offset);
  e->state = kTombstone;
  s->hdr->num_objects--;
  // Anti-tombstone-exhaustion: if the next probe slot is empty, this
  // tombstone (and any run of tombstones before it) can revert to empty
  // without breaking probe chains.
  uint32_t mask = s->hdr->table_capacity - 1;
  if (s->table[(i + 1) & mask].state == kEmpty) {
    uint32_t j = i;
    while (s->table[j].state == kTombstone) {
      s->table[j].state = kEmpty;
      j = (j - 1) & mask;
    }
  }
}

class Guard {
 public:
  explicit Guard(Store* s) : h_(s->hdr), m_(&s->hdr->mutex) {
    int rc = pthread_mutex_lock(m_);
    if (rc == EOWNERDEAD) {
      pthread_mutex_consistent(m_);
      // If the dead holder was mid-mutation, heap/table invariants may be
      // broken: poison the store instead of walking corrupt structures.
      if (h_->mutating) h_->poisoned = 1;
    }
    h_->mutating = 1;
  }
  ~Guard() {
    h_->mutating = 0;
    pthread_mutex_unlock(m_);
  }
  bool poisoned() const { return h_->poisoned != 0; }

 private:
  Header* h_;
  pthread_mutex_t* m_;
};

// Evict LRU sealed+unpinned+evictable objects until `bytes` are reclaimable.
// Called with lock held. Returns bytes freed.
uint64_t evict_locked(Store* s, uint64_t bytes) {
  uint64_t freed = 0;
  uint32_t i = s->hdr->lru_tail;
  while (freed < bytes && i != kNil) {
    uint32_t prev = s->table[i].lru_prev;
    Entry* e = &s->table[i];
    if (e->state == kSealed && e->pin_count == 0 && !(e->flags & 2)) {
      uint64_t sz = e->data_size + e->meta_size;
      entry_free(s, i);
      s->hdr->num_evictions++;
      s->hdr->bytes_evicted += sz;
      freed += sz;
    }
    i = prev;
  }
  return freed;
}

// ------------------------------------------------------------ copy pool
// Chunked arena copies for the put hot path: the Python binding (ctypes)
// drops the GIL for the duration of the call, and the pool spreads large
// memcpys across a few threads. Per-call latency on a 1-core host is the
// memcpy itself (nthreads<=1 short-circuits to a plain memcpy, no pool
// wakeup); wider hosts split the copy into near-equal 64B-aligned chunks.
struct CopyBatch {
  std::mutex mu;
  std::condition_variable cv;
  int remaining = 0;
};

struct CopyChunk {
  uint8_t* dst;
  const uint8_t* src;
  uint64_t n;
  CopyBatch* batch;
};

class CopyPool {
 public:
  static CopyPool& Instance() {
    static CopyPool* pool = new CopyPool();  // never destroyed: workers may
    return *pool;                            // outlive static teardown order
  }

  // Copy n bytes dst<-src split across `nchunks` pieces; the calling
  // thread copies the first chunk itself, pool threads do the rest.
  void Run(uint8_t* dst, const uint8_t* src, uint64_t n, int nchunks) {
    if (nchunks > kMaxThreads) nchunks = kMaxThreads;
    // 64B-aligned chunk size so no two threads share a cache line
    uint64_t chunk = (n / nchunks + 63) & ~63ULL;
    int pieces = (int)((n + chunk - 1) / chunk);
    if (pieces <= 1) {
      memcpy(dst, src, n);
      return;
    }
    EnsureThreads(pieces - 1);
    CopyBatch batch;
    batch.remaining = pieces - 1;
    {
      std::lock_guard<std::mutex> g(mu_);
      for (int i = 1; i < pieces; i++) {
        uint64_t off = (uint64_t)i * chunk;
        uint64_t len = off + chunk <= n ? chunk : n - off;
        q_.push_back({dst + off, src + off, len, &batch});
      }
    }
    cv_.notify_all();
    memcpy(dst, src, chunk);  // caller's share overlaps the workers
    std::unique_lock<std::mutex> g(batch.mu);
    batch.cv.wait(g, [&] { return batch.remaining == 0; });
  }

 private:
  static constexpr int kMaxThreads = 16;

  void EnsureThreads(int want) {
    std::lock_guard<std::mutex> g(mu_);
    while ((int)threads_.size() < want && (int)threads_.size() < kMaxThreads)
      threads_.emplace_back([this] { WorkerLoop(); });
  }

  void WorkerLoop() {
    for (;;) {
      CopyChunk c;
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_.wait(g, [this] { return !q_.empty(); });
        c = q_.front();
        q_.pop_front();
      }
      memcpy(c.dst, c.src, c.n);
      {
        // notify while holding the lock: the batch lives on the caller's
        // stack and is destroyed the moment Run() observes remaining==0,
        // so the cv must not be touched after this block releases mu
        std::lock_guard<std::mutex> g(c.batch->mu);
        c.batch->remaining--;
        c.batch->cv.notify_one();
      }
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<CopyChunk> q_;
  std::vector<std::thread> threads_;
};

}  // namespace

extern "C" {

// Chunked (optionally multi-threaded) memcpy into the arena. Called via
// ctypes, which releases the GIL for the duration — large put copies no
// longer serialize every Python thread in the process. threads<=1 (or a
// copy too small to split) is a plain memcpy on the calling thread.
void rt_write_parallel(void* dst, const void* src, uint64_t n, int threads) {
  if (n == 0) return;
  if (threads <= 1 || n < (1u << 20)) {
    memcpy(dst, src, n);
    return;
  }
  CopyPool::Instance().Run(static_cast<uint8_t*>(dst),
                           static_cast<const uint8_t*>(src), n, threads);
}

void* rt_store_create(const char* path, uint64_t size) {
  // Always create a fresh inode (O_EXCL after unlink): truncating an
  // existing path would SIGBUS any process still mapping the old store.
  unlink(path);
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  uint64_t table_bytes = align_up(sizeof(Entry) * (uint64_t)kTableCapacity, 4096);
  uint64_t header_bytes = align_up(sizeof(Header), 4096);
  uint64_t total = align_up(header_bytes + table_bytes + size, 4096);
  if (ftruncate(fd, (off_t)total) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  // Hugepage-advise the arena: first-touch fault cost dominates large-object
  // writes on virtualized hosts (measured 30x on 4K faults); THP cuts the
  // fault count ~512x.
  madvise(mem, total, MADV_HUGEPAGE);

  Store* s = new Store();
  s->base = static_cast<uint8_t*>(mem);
  s->hdr = reinterpret_cast<Header*>(mem);
  s->table = reinterpret_cast<Entry*>(s->base + header_bytes);
  s->arena = s->base + header_bytes + table_bytes;
  s->map_size = total;
  s->fd = fd;

  Header* h = s->hdr;
  memset(h, 0, sizeof(Header));
  memset(s->table, 0, sizeof(Entry) * (uint64_t)kTableCapacity);
  h->version = kVersion;
  h->table_capacity = kTableCapacity;
  h->total_size = total;
  h->arena_offset = header_bytes + table_bytes;
  h->arena_size = total - h->arena_offset;
  h->free_head = kNone;
  h->lru_head = h->lru_tail = kNil;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  // one giant free block
  Block* b = at(s, 0);
  set_size(b, h->arena_size, true);
  b->prev_size = 0;
  free_list_push(s, b);

  std::atomic_thread_fence(std::memory_order_seq_cst);
  h->magic = kMagic;  // publish last
  return s;
}

void* rt_store_open(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) { close(fd); return nullptr; }
  madvise(mem, (size_t)st.st_size, MADV_HUGEPAGE);
  Header* h = reinterpret_cast<Header*>(mem);
  if (h->magic != kMagic || h->version != kVersion) {
    munmap(mem, (size_t)st.st_size);
    close(fd);
    return nullptr;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(mem);
  s->hdr = h;
  uint64_t header_bytes = align_up(sizeof(Header), 4096);
  s->table = reinterpret_cast<Entry*>(s->base + header_bytes);
  s->arena = s->base + h->arena_offset;
  s->map_size = h->total_size;
  s->fd = fd;
  return s;
}

void rt_store_close(void* hs) {
  Store* s = static_cast<Store*>(hs);
  munmap(s->base, s->map_size);
  close(s->fd);
  delete s;
}

uint8_t* rt_store_base(void* hs) { return static_cast<Store*>(hs)->base; }
uint64_t rt_store_capacity(void* hs) { return static_cast<Store*>(hs)->hdr->arena_size; }
uint64_t rt_store_total_size(void* hs) { return static_cast<Store*>(hs)->hdr->total_size; }

// Create an object buffer. Returns base-relative offset of the payload
// (data followed by metadata), or a negative errno-style code:
//   -EEXIST already exists, -ENOMEM no space even after eviction,
//   -ENFILE table full.
int64_t rt_create(void* hs, const uint8_t* id, uint64_t data_size,
                  uint64_t meta_size, int evictable) {
  Store* s = static_cast<Store*>(hs);
  uint64_t need = data_size + meta_size;
  Guard g(s);
  if (g.poisoned()) return -EIO;
  if (table_find(s, id) != kNil) return -EEXIST;
  uint32_t slot = table_slot(s, id);
  if (slot == kNil) return -ENFILE;
  uint64_t off = heap_alloc(s, need);
  if (off == kNone) {
    evict_locked(s, need);
    off = heap_alloc(s, need);
    if (off == kNone) return -ENOMEM;
  }
  Entry* e = &s->table[slot];
  memcpy(e->id, id, kIdLen);
  e->state = kCreated;
  e->offset = off;
  e->data_size = data_size;
  e->meta_size = meta_size;
  e->pin_count = 1;  // creator holds a pin until seal+release
  e->flags = evictable ? 0 : 2;
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  e->ctime_sec = (uint64_t)ts.tv_sec;
  e->lru_prev = e->lru_next = kNil;
  s->hdr->num_objects++;
  s->hdr->create_count++;
  return (int64_t)(s->hdr->arena_offset + off);
}

int rt_seal(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  uint32_t i = table_find(s, id);
  if (i == kNil) return -ENOENT;
  Entry* e = &s->table[i];
  if (e->state != kCreated) return -EINVAL;
  e->state = kSealed;
  e->pin_count = 0;
  lru_push_front(s, i);
  s->hdr->seal_count++;
  return 0;
}

// Look up a sealed object. On hit fills sizes, pins if pin!=0, returns
// base-relative payload offset. -ENOENT if absent or not sealed.
int64_t rt_get(void* hs, const uint8_t* id, uint64_t* data_size,
               uint64_t* meta_size, int pin) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  if (g.poisoned()) return -EIO;
  uint32_t i = table_find(s, id);
  if (i == kNil || s->table[i].state != kSealed) {
    s->hdr->get_misses++;
    return -ENOENT;
  }
  Entry* e = &s->table[i];
  *data_size = e->data_size;
  *meta_size = e->meta_size;
  if (pin) e->pin_count++;
  // touch LRU
  lru_unlink(s, i);
  lru_push_front(s, i);
  s->hdr->get_hits++;
  return (int64_t)(s->hdr->arena_offset + e->offset);
}

int rt_release(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  uint32_t i = table_find(s, id);
  if (i == kNil) return -ENOENT;
  Entry* e = &s->table[i];
  if (e->pin_count > 0) e->pin_count--;
  if ((e->flags & 1) && e->pin_count == 0) entry_free(s, i);
  return 0;
}

int rt_contains(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  uint32_t i = table_find(s, id);
  return (i != kNil && s->table[i].state == kSealed) ? 1 : 0;
}

// Delete (deferred if pinned). -ENOENT if absent.
int rt_delete(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  uint32_t i = table_find(s, id);
  if (i == kNil) return -ENOENT;
  Entry* e = &s->table[i];
  if (e->pin_count > 0) {
    e->flags |= 1;  // delete-pending
    return 0;
  }
  entry_free(s, i);
  return 0;
}

// Abort an in-progress creation (writer failed before seal).
int rt_abort(void* hs, const uint8_t* id) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  uint32_t i = table_find(s, id);
  if (i == kNil) return -ENOENT;
  if (s->table[i].state != kCreated) return -EINVAL;
  entry_free(s, i);
  return 0;
}

// Reclaim CREATED-but-never-sealed objects older than max_age_sec — their
// writer likely died before sealing. Returns number reclaimed. Called
// periodically by the node daemon.
uint64_t rt_gc_unsealed(void* hs, uint64_t max_age_sec) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  uint64_t now = (uint64_t)ts.tv_sec;
  uint64_t n = 0;
  for (uint32_t i = 0; i < s->hdr->table_capacity; ++i) {
    Entry* e = &s->table[i];
    if (e->state == kCreated && now - e->ctime_sec >= max_age_sec) {
      entry_free(s, i);
      ++n;
    }
  }
  return n;
}

uint64_t rt_evict(void* hs, uint64_t bytes) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  return evict_locked(s, bytes);
}

void rt_stats(void* hs, uint64_t* out) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  Header* h = s->hdr;
  out[0] = h->bytes_in_use;
  out[1] = h->arena_size;
  out[2] = h->num_objects;
  out[3] = h->num_evictions;
  out[4] = h->bytes_evicted;
  out[5] = h->create_count;
  out[6] = h->get_hits;
  out[7] = h->get_misses;
  out[8] = h->poisoned;
}

// List up to max_n sealed object ids into out (max_n * kIdLen bytes).
uint64_t rt_list(void* hs, uint8_t* out, uint64_t max_n) {
  Store* s = static_cast<Store*>(hs);
  Guard g(s);
  uint64_t n = 0;
  for (uint32_t i = 0; i < s->hdr->table_capacity && n < max_n; ++i) {
    Entry* e = &s->table[i];
    if (e->state == kSealed) {
      memcpy(out + n * kIdLen, e->id, kIdLen);
      ++n;
    }
  }
  return n;
}

}  // extern "C"
