// C++ API frontend for the ray_tpu runtime.
//
// Counterpart of the reference's C++ API (reference: cpp/include/ray/api.h,
// cpp/src/ray/runtime/abstract_ray_runtime.cc) re-designed for this
// runtime's control plane: one framed-msgpack RPC protocol speaks directly
// to the GCS, node managers and workers (no protobuf/gRPC layer), and
// cross-language task calls name Python functions ("module:attr") with
// msgpack-encoded arguments and results (KIND_MSGPACK on the wire).
//
// Synchronous, dependency-free (C++17, POSIX sockets). Usage:
//
//   rt::Client c;
//   c.Connect("tcp:127.0.0.1:6379");
//   rt::Value out = c.Call("builtins:pow", {rt::Value::Int(2),
//                                           rt::Value::Int(10)});
//
#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <random>
#include <stdexcept>
#include <string>
#include <thread>
#include <chrono>
#include <vector>

namespace rt {

// ----------------------------------------------------------- value model
struct Value {
  enum Type { NIL, BOOL, INT, FLOAT, STR, BIN, ARRAY, MAP };
  Type type = NIL;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;                       // STR
  std::vector<uint8_t> bin;            // BIN
  std::vector<Value> arr;              // ARRAY
  std::map<std::string, Value> obj;    // MAP (string keys)

  static Value Nil() { return Value{}; }
  static Value Bool(bool v) { Value x; x.type = BOOL; x.b = v; return x; }
  static Value Int(int64_t v) { Value x; x.type = INT; x.i = v; return x; }
  static Value Float(double v) { Value x; x.type = FLOAT; x.d = v; return x; }
  static Value Str(std::string v) {
    Value x; x.type = STR; x.s = std::move(v); return x;
  }
  static Value Bin(std::vector<uint8_t> v) {
    Value x; x.type = BIN; x.bin = std::move(v); return x;
  }
  static Value Arr(std::vector<Value> v) {
    Value x; x.type = ARRAY; x.arr = std::move(v); return x;
  }
  static Value Map(std::map<std::string, Value> v) {
    Value x; x.type = MAP; x.obj = std::move(v); return x;
  }

  double AsNumber() const { return type == INT ? double(i) : d; }
};

// ----------------------------------------------------- msgpack encoding
inline void PackTo(const Value& v, std::string* out);

inline void PackU8(std::string* out, uint8_t b) { out->push_back(char(b)); }
inline void PackBe(std::string* out, const void* p, size_t n) {
  const uint8_t* u = static_cast<const uint8_t*>(p);
  for (size_t k = 0; k < n; k++) out->push_back(char(u[n - 1 - k]));
}

inline void PackUint(std::string* out, uint64_t x) {
  if (x < 128) {
    PackU8(out, uint8_t(x));
  } else if (x <= 0xff) {
    PackU8(out, 0xcc); PackU8(out, uint8_t(x));
  } else if (x <= 0xffff) {
    uint16_t v = uint16_t(x); PackU8(out, 0xcd); PackBe(out, &v, 2);
  } else if (x <= 0xffffffffULL) {
    uint32_t v = uint32_t(x); PackU8(out, 0xce); PackBe(out, &v, 4);
  } else {
    PackU8(out, 0xcf); PackBe(out, &x, 8);
  }
}

inline void PackInt(std::string* out, int64_t x) {
  if (x >= 0) { PackUint(out, uint64_t(x)); return; }
  if (x >= -32) { PackU8(out, uint8_t(0xe0 | (x + 32))); return; }
  if (x >= INT8_MIN) { PackU8(out, 0xd0); PackU8(out, uint8_t(x)); return; }
  if (x >= INT16_MIN) {
    int16_t v = int16_t(x); PackU8(out, 0xd1); PackBe(out, &v, 2); return;
  }
  if (x >= INT32_MIN) {
    int32_t v = int32_t(x); PackU8(out, 0xd2); PackBe(out, &v, 4); return;
  }
  PackU8(out, 0xd3); PackBe(out, &x, 8);
}

inline void PackStr(std::string* out, const std::string& s) {
  size_t n = s.size();
  if (n < 32) PackU8(out, uint8_t(0xa0 | n));
  else if (n <= 0xff) { PackU8(out, 0xd9); PackU8(out, uint8_t(n)); }
  else { uint16_t v = uint16_t(n); PackU8(out, 0xda); PackBe(out, &v, 2); }
  out->append(s);
}

inline void PackBin(std::string* out, const uint8_t* p, size_t n) {
  if (n <= 0xff) { PackU8(out, 0xc4); PackU8(out, uint8_t(n)); }
  else if (n <= 0xffff) {
    uint16_t v = uint16_t(n); PackU8(out, 0xc5); PackBe(out, &v, 2);
  } else {
    uint32_t v = uint32_t(n); PackU8(out, 0xc6); PackBe(out, &v, 4);
  }
  out->append(reinterpret_cast<const char*>(p), n);
}

inline void PackTo(const Value& v, std::string* out) {
  switch (v.type) {
    case Value::NIL: PackU8(out, 0xc0); break;
    case Value::BOOL: PackU8(out, v.b ? 0xc3 : 0xc2); break;
    case Value::INT: PackInt(out, v.i); break;
    case Value::FLOAT: {
      PackU8(out, 0xcb); PackBe(out, &v.d, 8); break;
    }
    case Value::STR: PackStr(out, v.s); break;
    case Value::BIN: PackBin(out, v.bin.data(), v.bin.size()); break;
    case Value::ARRAY: {
      size_t n = v.arr.size();
      if (n < 16) PackU8(out, uint8_t(0x90 | n));
      else { uint16_t w = uint16_t(n); PackU8(out, 0xdc); PackBe(out, &w, 2); }
      for (const auto& e : v.arr) PackTo(e, out);
      break;
    }
    case Value::MAP: {
      size_t n = v.obj.size();
      if (n < 16) PackU8(out, uint8_t(0x80 | n));
      else { uint16_t w = uint16_t(n); PackU8(out, 0xde); PackBe(out, &w, 2); }
      for (const auto& kv : v.obj) { PackStr(out, kv.first); PackTo(kv.second, out); }
      break;
    }
  }
}

// ----------------------------------------------------- msgpack decoding
struct Cursor {
  const uint8_t* p;
  size_t n;
  size_t off = 0;
  uint8_t U8() {
    if (off >= n) throw std::runtime_error("msgpack underrun");
    return p[off++];
  }
  const uint8_t* Take(size_t k) {
    if (off + k > n) throw std::runtime_error("msgpack underrun");
    const uint8_t* q = p + off; off += k; return q;
  }
  uint64_t Be(size_t k) {
    const uint8_t* q = Take(k);
    uint64_t x = 0;
    for (size_t j = 0; j < k; j++) x = (x << 8) | q[j];
    return x;
  }
};

inline Value Unpack(Cursor* c) {
  uint8_t t = c->U8();
  if (t < 0x80) return Value::Int(t);
  if (t >= 0xe0) return Value::Int(int8_t(t));
  if ((t & 0xf0) == 0x80) {  // fixmap
    std::map<std::string, Value> m;
    for (int k = t & 0x0f; k > 0; k--) {
      Value key = Unpack(c);
      m[key.s] = Unpack(c);
    }
    return Value::Map(std::move(m));
  }
  if ((t & 0xf0) == 0x90) {  // fixarray
    std::vector<Value> a;
    for (int k = t & 0x0f; k > 0; k--) a.push_back(Unpack(c));
    return Value::Arr(std::move(a));
  }
  if ((t & 0xe0) == 0xa0) {  // fixstr
    size_t k = t & 0x1f;
    const uint8_t* q = c->Take(k);
    return Value::Str(std::string(reinterpret_cast<const char*>(q), k));
  }
  switch (t) {
    case 0xc0: return Value::Nil();
    case 0xc2: return Value::Bool(false);
    case 0xc3: return Value::Bool(true);
    case 0xc4: case 0xc5: case 0xc6: {
      size_t k = c->Be(t == 0xc4 ? 1 : t == 0xc5 ? 2 : 4);
      const uint8_t* q = c->Take(k);
      return Value::Bin(std::vector<uint8_t>(q, q + k));
    }
    case 0xca: {
      uint32_t raw = uint32_t(c->Be(4));
      float f;
      std::memcpy(&f, &raw, 4);
      return Value::Float(f);
    }
    case 0xcb: {
      uint64_t raw = c->Be(8);
      double d;
      std::memcpy(&d, &raw, 8);
      return Value::Float(d);
    }
    case 0xcc: return Value::Int(int64_t(c->Be(1)));
    case 0xcd: return Value::Int(int64_t(c->Be(2)));
    case 0xce: return Value::Int(int64_t(c->Be(4)));
    case 0xcf: return Value::Int(int64_t(c->Be(8)));
    case 0xd0: return Value::Int(int8_t(c->Be(1)));
    case 0xd1: return Value::Int(int16_t(c->Be(2)));
    case 0xd2: return Value::Int(int32_t(c->Be(4)));
    case 0xd3: return Value::Int(int64_t(c->Be(8)));
    case 0xd9: case 0xda: case 0xdb: {
      size_t k = c->Be(t == 0xd9 ? 1 : t == 0xda ? 2 : 4);
      const uint8_t* q = c->Take(k);
      return Value::Str(std::string(reinterpret_cast<const char*>(q), k));
    }
    case 0xdc: case 0xdd: {
      size_t k = c->Be(t == 0xdc ? 2 : 4);
      std::vector<Value> a;
      a.reserve(k);
      for (size_t j = 0; j < k; j++) a.push_back(Unpack(c));
      return Value::Arr(std::move(a));
    }
    case 0xde: case 0xdf: {
      size_t k = c->Be(t == 0xde ? 2 : 4);
      std::map<std::string, Value> m;
      for (size_t j = 0; j < k; j++) {
        Value key = Unpack(c);
        m[key.s] = Unpack(c);
      }
      return Value::Map(std::move(m));
    }
  }
  throw std::runtime_error("msgpack: unsupported tag");
}

// -------------------------------------------------- framed rpc transport
class RpcConn {
 public:
  RpcConn() = default;
  RpcConn(const RpcConn&) = delete;
  RpcConn& operator=(const RpcConn&) = delete;

  // addr: "tcp:host:port" (as advertised by the runtime)
  void Connect(const std::string& addr) {
    std::string a = addr;
    if (a.rfind("tcp:", 0) == 0) a = a.substr(4);
    size_t colon = a.rfind(':');
    if (colon == std::string::npos)
      throw std::runtime_error("bad address " + addr);
    std::string host = a.substr(0, colon);
    std::string port = a.substr(colon + 1);
    if (host == "0.0.0.0") host = "127.0.0.1";
    struct addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0)
      throw std::runtime_error("resolve failed: " + host);
    fd_ = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd_ < 0 || connect(fd_, res->ai_addr, res->ai_addrlen) != 0) {
      freeaddrinfo(res);
      throw std::runtime_error("connect failed: " + addr);
    }
    freeaddrinfo(res);
  }

  Value Call(const std::string& method, const Value& kwargs) {
    // [REQUEST=0, seq, method, kwargs]
    Value frame = Value::Arr({Value::Int(0), Value::Int(++seq_),
                              Value::Str(method), kwargs});
    std::string body;
    PackTo(frame, &body);
    uint32_t len = uint32_t(body.size());
    uint8_t hdr[4] = {uint8_t(len), uint8_t(len >> 8), uint8_t(len >> 16),
                      uint8_t(len >> 24)};
    WriteAll(hdr, 4);
    WriteAll(body.data(), body.size());
    for (;;) {  // responses are in-order for a single-threaded client
      uint8_t rh[4];
      ReadAll(rh, 4);
      uint32_t rlen = uint32_t(rh[0]) | uint32_t(rh[1]) << 8 |
                      uint32_t(rh[2]) << 16 | uint32_t(rh[3]) << 24;
      std::vector<uint8_t> buf(rlen);
      ReadAll(buf.data(), rlen);
      Cursor c{buf.data(), buf.size()};
      Value msg = Unpack(&c);
      if (msg.arr.size() < 4 || msg.arr[0].i != 1) continue;  // not a resp
      if (msg.arr[1].i != seq_) continue;                     // stale
      if (!msg.arr[2].b) {
        const Value& err = msg.arr[3];
        std::string what = "rpc error";
        if (err.type == Value::ARRAY && err.arr.size() >= 2)
          what = err.arr[0].s + ": " + err.arr[1].s;
        throw std::runtime_error(what);
      }
      return msg.arr[3];
    }
  }

  ~RpcConn() {
    if (fd_ >= 0) close(fd_);
  }

 private:
  void WriteAll(const void* p, size_t n) {
    const char* q = static_cast<const char*>(p);
    while (n) {
      ssize_t w = ::write(fd_, q, n);
      if (w <= 0) throw std::runtime_error("rpc write failed");
      q += w;
      n -= size_t(w);
    }
  }
  void ReadAll(void* p, size_t n) {
    char* q = static_cast<char*>(p);
    while (n) {
      ssize_t r = ::read(fd_, q, n);
      if (r <= 0) throw std::runtime_error("rpc read failed");
      q += r;
      n -= size_t(r);
    }
  }
  int fd_ = -1;
  int64_t seq_ = 0;
};

// --------------------------------------------------------------- client
class Client {
 public:
  void Connect(const std::string& gcs_addr) {
    gcs_.Connect(gcs_addr);
    Value nodes = gcs_.Call("get_all_nodes", Value::Map({}));
    for (const auto& n : nodes.arr) {
      auto alive = n.obj.find("alive");
      if (alive != n.obj.end() && !alive->second.b) continue;
      node_address_ = n.obj.at("address").s;
      node_id_ = n.obj.at("node_id").s;
      break;
    }
    if (node_address_.empty())
      throw std::runtime_error("no alive nodes in cluster");
    node_.Connect(node_address_);
    std::mt19937_64 rng(std::random_device{}());
    worker_id_ = "cpp-";
    for (int k = 0; k < 4; k++)
      worker_id_ += "0123456789abcdef"[rng() % 16];
  }

  // Call a Python function by "module:attr" with msgpack args; blocks for
  // the result (one lease per call; idle-lease reuse is the Python
  // submitter's optimization, correctness is identical).
  Value Call(const std::string& func_ref, const std::vector<Value>& args,
             double num_cpus = 1.0) {
    Value lease = RequestLease(num_cpus);
    RpcConn worker;
    worker.Connect(lease.obj.at("worker_address").s);
    const std::string grant_node =
        lease.obj.count("node_address") ? lease.obj.at("node_address").s
                                        : node_address_;

    std::mt19937_64 rng(std::random_device{}());
    std::vector<uint8_t> task_id(16), ret_id;
    for (auto& b : task_id) b = uint8_t(rng());
    ret_id = task_id;
    ret_id.push_back(0);
    ret_id.push_back(0);
    ret_id.push_back(0);
    ret_id.push_back(1);  // return index 1, big-endian

    std::vector<Value> enc_args;
    for (const auto& a : args) {
      std::string payload;
      PackTo(a, &payload);
      enc_args.push_back(Value::Arr(
          {Value::Str("v"), Value::Int(3) /* KIND_MSGPACK */,
           Value::Bin({}),
           Value::Arr({Value::Bin(std::vector<uint8_t>(
               payload.begin(), payload.end()))})}));
    }
    Value spec = Value::Map({
        {"task_id", Value::Bin(task_id)},
        {"job_id", Value::Int(0)},
        {"name", Value::Str(func_ref)},
        {"func_ref", Value::Str(func_ref)},
        {"args", Value::Arr(std::move(enc_args))},
        {"kwargs", Value::Map({})},
        {"return_ids", Value::Arr({Value::Bin(ret_id)})},
        {"owner_address", Value::Str("cpp-client")},
        {"owner_node", Value::Str(node_id_)},
        {"xlang", Value::Bool(true)},
    });
    Value resp;
    try {
      resp = worker.Call("push_task", Value::Map({{"spec", spec}}));
    } catch (...) {
      ReturnLease(grant_node, lease, /*worker_dead=*/true);
      throw;
    }
    ReturnLease(grant_node, lease, false);
    const Value& ret = resp.obj.at("returns").arr.at(0);
    // ["wire", kind, pkl, [payloads]]
    int64_t kind = ret.arr.at(1).i;
    const auto& payload = ret.arr.at(3).arr.at(0).bin;
    Cursor c{payload.data(), payload.size()};
    if (kind == 1) {
      Value msg = Unpack(&c);   // xlang errors arrive as msgpack text
      throw std::runtime_error("remote task failed: " + func_ref + ": " +
                               msg.s);
    }
    return Unpack(&c);
  }

  // ----------------------------------------------------------- actors
  // Cross-language actors: the class is named by an importable
  // "module:Class" reference (reference: cpp/java actor class
  // descriptors); instance state lives in a Python worker, methods are
  // pushed directly to it like the Python ActorTaskSubmitter.
  struct ActorHandle {
    std::string actor_id;
    std::string address;
    std::shared_ptr<RpcConn> conn;   // cached per-handle connection
  };

  ActorHandle CreateActor(const std::string& class_ref,
                          const std::vector<Value>& init_args,
                          double num_cpus = 1.0,
                          double timeout_s = 60.0) {
    std::mt19937_64 rng(std::random_device{}());
    std::string actor_id;
    for (int k = 0; k < 32; k++)
      actor_id += "0123456789abcdef"[rng() % 16];
    std::vector<Value> enc_args;
    for (const auto& a : init_args) enc_args.push_back(EncodeArg(a));
    Value spec = Value::Map({
        {"actor_id", Value::Str(actor_id)},
        {"job_id", Value::Int(0)},
        {"class_ref", Value::Str(class_ref)},
        {"name", Value::Str("")},
        {"namespace", Value::Str("default")},
        {"init_args", Value::Arr(std::move(enc_args))},
        {"init_kwargs", Value::Map({})},
        {"resources",
         Value::Map({{"CPU", Value::Float(num_cpus)}})},
        {"max_restarts", Value::Int(0)},
        {"max_concurrency", Value::Int(1)},
        {"scheduling", Value::Map({})},
        {"owner_address", Value::Str("cpp-client")},
        {"method_names", Value::Arr({})},
    });
    gcs_.Call("create_actor", Value::Map({{"spec", spec}}));
    // wait for placement (reference: actor creation is async; handles
    // resolve the address from the GCS actor table)
    for (int i = 0; i < int(timeout_s / 0.1); i++) {
      Value info = gcs_.Call(
          "get_actor_info",
          Value::Map({{"actor_id", Value::Str(actor_id)}}));
      if (!info.obj.empty()) {
        const std::string& state = info.obj.at("state").s;
        if (state == "ALIVE")
          return ActorHandle{actor_id, info.obj.at("address").s, nullptr};
        if (state == "DEAD") {
          std::string cause;
          auto it = info.obj.find("death_cause");
          if (it != info.obj.end()) cause = ": " + it->second.s;
          throw std::runtime_error("actor creation failed: " + class_ref +
                                   cause);
        }
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    }
    // the registration was accepted; without a kill the GCS would place
    // the actor later and leak its resources with no reachable handle
    try {
      KillActor(ActorHandle{actor_id, "", nullptr});
    } catch (...) {
    }
    throw std::runtime_error("actor never became ALIVE: " + class_ref);
  }

  Value CallActor(ActorHandle& h, const std::string& method,
                  const std::vector<Value>& args) {
    std::mt19937_64 rng(std::random_device{}());
    std::vector<uint8_t> task_id(16), ret_id;
    for (auto& b : task_id) b = uint8_t(rng());
    ret_id = task_id;
    ret_id.push_back(0);
    ret_id.push_back(0);
    ret_id.push_back(0);
    ret_id.push_back(1);
    std::vector<Value> enc_args;
    for (const auto& a : args) enc_args.push_back(EncodeArg(a));
    Value spec = Value::Map({
        {"task_id", Value::Bin(task_id)},
        {"job_id", Value::Int(0)},
        {"name", Value::Str(method)},
        {"actor_id", Value::Str(h.actor_id)},
        {"method", Value::Str(method)},
        {"args", Value::Arr(std::move(enc_args))},
        {"kwargs", Value::Map({})},
        {"return_ids", Value::Arr({Value::Bin(ret_id)})},
        {"owner_address", Value::Str("cpp-client")},
        {"owner_node", Value::Str(node_id_)},
        {"xlang", Value::Bool(true)},
    });
    if (!h.conn) {
      h.conn = std::make_shared<RpcConn>();
      h.conn->Connect(h.address);
    }
    Value resp = h.conn->Call("push_task", Value::Map({{"spec", spec}}));
    const Value& ret = resp.obj.at("returns").arr.at(0);
    int64_t kind = ret.arr.at(1).i;
    const auto& payload = ret.arr.at(3).arr.at(0).bin;
    Cursor c{payload.data(), payload.size()};
    if (kind == 1) {
      // xlang errors arrive as msgpack text
      Value msg = Unpack(&c);
      throw std::runtime_error("actor method failed: " + method + ": " +
                               msg.s);
    }
    return Unpack(&c);
  }

  void KillActor(const ActorHandle& h) {
    gcs_.Call("kill_actor",
              Value::Map({{"actor_id", Value::Str(h.actor_id)},
                          {"no_restart", Value::Bool(true)}}));
  }

 private:
  static Value EncodeArg(const Value& a) {
    std::string payload;
    PackTo(a, &payload);
    return Value::Arr(
        {Value::Str("v"), Value::Int(3) /* KIND_MSGPACK */, Value::Bin({}),
         Value::Arr({Value::Bin(std::vector<uint8_t>(
             payload.begin(), payload.end()))})});
  }

  Value RequestLease(double num_cpus) {
    RpcConn* target = &node_;
    std::unique_ptr<RpcConn> spill_conn;
    for (int hop = 0; hop < 8; hop++) {
      Value resp = target->Call(
          "request_lease",
          Value::Map({{"resources",
                       Value::Map({{"CPU", Value::Float(num_cpus)}})},
                      {"scheduling", Value::Map({})},
                      {"worker_id", Value::Str(worker_id_)},
                      {"spilled", Value::Bool(hop > 0)}}));
      const std::string& status = resp.obj.at("status").s;
      if (status == "ok") return resp;
      if (status == "spill") {
        spill_conn = std::make_unique<RpcConn>();
        spill_conn->Connect(resp.obj.at("spill_to").s);
        target = spill_conn.get();
        continue;
      }
      throw std::runtime_error("lease denied");
    }
    throw std::runtime_error("lease spillback loop");
  }

  void ReturnLease(const std::string& grant_node, const Value& lease,
                   bool worker_dead) {
    try {
      RpcConn conn;
      conn.Connect(grant_node);
      conn.Call("return_lease",
                Value::Map({{"lease_id", lease.obj.at("lease_id")},
                            {"worker_dead", Value::Bool(worker_dead)}}));
    } catch (...) {
    }
  }

  RpcConn gcs_;
  RpcConn node_;
  std::string node_address_;
  std::string node_id_;
  std::string worker_id_;
};

}  // namespace rt
