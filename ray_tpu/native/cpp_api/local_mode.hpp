// In-process ("local mode") C++ runtime for the ray_tpu C++ API.
//
// Counterpart of the reference's local-mode runtime (reference:
// cpp/src/ray/runtime/local_mode_ray_runtime.cc +
// cpp/src/ray/runtime/task/local_mode_task_submitter.cc): tasks and
// actors registered as native C++ functions execute inside the calling
// process on a small thread pool — no cluster, no sockets — while
// keeping the task/actor/object semantics (futures as object refs,
// dependency resolution of ref arguments before execution, serialized
// FIFO actor mailboxes, error capture + rethrow on Get). The remote
// path (ray_tpu_api.hpp Client) and this local path share the same
// Value model, mirroring the reference's AbstractRayRuntime split.
//
// Usage:
//   Value Pow(const std::vector<Value>& a);
//   RT_LOCAL_REMOTE(Pow);
//   ...
//   rt::local::LocalRuntime rt(4);
//   auto ref = rt.Task("Pow", {Value::Int(2), Value::Int(10)});
//   Value v = rt.Get(ref);                      // 1024
//
// Dependency-free C++17; header-only like the client API.
#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <functional>
#include <future>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <utility>
#include <variant>
#include <vector>

#include "ray_tpu_api.hpp"

namespace rt {
namespace local {

using TaskFn = std::function<Value(const std::vector<Value>&)>;

// ------------------------------------------------------- task registry
// RAY_REMOTE analog (reference: cpp/include/ray/api/ray_remote.h):
// static registration of free functions by name.
class FunctionRegistry {
 public:
  static FunctionRegistry& Instance() {
    static FunctionRegistry r;
    return r;
  }
  void Register(const std::string& name, TaskFn fn) {
    std::lock_guard<std::mutex> g(mu_);
    fns_[name] = std::move(fn);
  }
  TaskFn Find(const std::string& name) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = fns_.find(name);
    if (it == fns_.end())
      throw std::runtime_error("no such task function: " + name);
    return it->second;
  }

 private:
  mutable std::mutex mu_;
  std::map<std::string, TaskFn> fns_;
};

struct Registrar {
  Registrar(const std::string& name, TaskFn fn) {
    FunctionRegistry::Instance().Register(name, std::move(fn));
  }
};

#define RT_LOCAL_REMOTE(fn) \
  static ::rt::local::Registrar _rt_local_reg_##fn(#fn, fn)

// ------------------------------------------------------ actor registry
// Actor classes register a factory + named methods; instances live as
// shared_ptr<void> so the runtime is class-agnostic (the reference's
// local mode keeps a map of actor handles to untyped instances).
struct ActorClassInfo {
  std::function<std::shared_ptr<void>(const std::vector<Value>&)> factory;
  std::map<std::string,
           std::function<Value(void*, const std::vector<Value>&)>>
      methods;
};

class ActorRegistry {
 public:
  static ActorRegistry& Instance() {
    static ActorRegistry r;
    return r;
  }
  void RegisterClass(const std::string& name, ActorClassInfo info) {
    std::lock_guard<std::mutex> g(mu_);
    classes_[name] = std::move(info);
  }
  const ActorClassInfo& Find(const std::string& name) const {
    std::lock_guard<std::mutex> g(mu_);
    auto it = classes_.find(name);
    if (it == classes_.end())
      throw std::runtime_error("no such actor class: " + name);
    return it->second;
  }

 private:
  mutable std::mutex mu_;
  std::map<std::string, ActorClassInfo> classes_;
};

// Typed registration helper: methods must have the uniform signature
// Value (T::*)(const std::vector<Value>&) — the same calling convention
// tasks use, keeping the wire/value model single.
template <typename T>
void RegisterActorClass(
    const std::string& name,
    std::map<std::string, Value (T::*)(const std::vector<Value>&)>
        methods) {
  ActorClassInfo info;
  info.factory = [](const std::vector<Value>& args) {
    return std::static_pointer_cast<void>(std::make_shared<T>(args));
  };
  for (auto& kv : methods) {
    auto m = kv.second;
    info.methods[kv.first] = [m](void* self, const std::vector<Value>& a) {
      return (static_cast<T*>(self)->*m)(a);
    };
  }
  ActorRegistry::Instance().RegisterClass(name, std::move(info));
}

// ---------------------------------------------------------- object refs
// A local-mode ObjectRef is a shared future: Put resolves immediately,
// Task/CallActor resolve when the pool executes the work. Errors are
// carried in-band and rethrown at Get (the reference's RayTaskError).
struct ObjectState {
  std::mutex mu;
  std::condition_variable cv;
  bool ready = false;
  Value value;
  std::string error;  // nonempty => Get throws
  std::vector<std::function<void()>> callbacks;  // fired once on ready
};

class LocalObjectRef {
 public:
  LocalObjectRef() : st_(std::make_shared<ObjectState>()) {}
  bool Ready() const {
    std::lock_guard<std::mutex> g(st_->mu);
    return st_->ready;
  }
  // Run fn when the ref resolves (immediately if already resolved).
  // The scheduler uses this to gate dependent work instead of blocking
  // a pool thread in Get — a fixed-size pool + blocking resolution
  // would deadlock on out-of-order dependency chains.
  void OnReady(std::function<void()> fn) const {
    {
      std::lock_guard<std::mutex> g(st_->mu);
      if (!st_->ready) {
        st_->callbacks.push_back(std::move(fn));
        return;
      }
    }
    fn();
  }
  void Resolve(Value v) {
    std::vector<std::function<void()>> cbs;
    {
      std::lock_guard<std::mutex> g(st_->mu);
      st_->value = std::move(v);
      st_->ready = true;
      cbs.swap(st_->callbacks);
    }
    st_->cv.notify_all();
    for (auto& cb : cbs) cb();
  }
  void Fail(std::string err) {
    std::vector<std::function<void()>> cbs;
    {
      std::lock_guard<std::mutex> g(st_->mu);
      st_->error = std::move(err);
      st_->ready = true;
      cbs.swap(st_->callbacks);
    }
    st_->cv.notify_all();
    for (auto& cb : cbs) cb();
  }
  Value Get(int64_t timeout_ms = -1) const {
    std::unique_lock<std::mutex> g(st_->mu);
    if (timeout_ms < 0) {
      st_->cv.wait(g, [&] { return st_->ready; });
    } else if (!st_->cv.wait_for(g, std::chrono::milliseconds(timeout_ms),
                                 [&] { return st_->ready; })) {
      throw std::runtime_error("Get timed out");
    }
    if (!st_->error.empty())
      throw std::runtime_error("task failed: " + st_->error);
    return st_->value;
  }

 private:
  std::shared_ptr<ObjectState> st_;
};

// Task arguments may be plain Values or ObjectRefs; refs are resolved
// (blocking the worker, not the submitter) before the function runs —
// the reference local mode's dependency semantics.
using Arg = std::variant<Value, LocalObjectRef>;

struct MailboxEntry {
  std::function<void()> work;        // runs with deps already resolved
  std::vector<LocalObjectRef> deps;  // ref args this call waits on
};

struct ActorStateBox {
  std::shared_ptr<void> instance;
  const ActorClassInfo* cls = nullptr;
  std::mutex mu;                 // serializes the mailbox
  std::deque<MailboxEntry> mailbox;
  bool draining = false;
};

// ---------------------------------------------------------- the runtime
class LocalRuntime {
 public:
  explicit LocalRuntime(int num_threads = 4) : stop_(false) {
    for (int i = 0; i < num_threads; i++)
      pool_.emplace_back([this] { WorkerLoop(); });
  }
  ~LocalRuntime() {
    {
      std::lock_guard<std::mutex> g(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : pool_) t.join();
  }

  LocalObjectRef Put(Value v) {
    LocalObjectRef ref;
    ref.Resolve(std::move(v));
    return ref;
  }

  Value Get(const LocalObjectRef& ref, int64_t timeout_ms = -1) {
    return ref.Get(timeout_ms);
  }

  // Wait: indices of ready refs once num_ready are ready or timeout.
  // timeout_ms < 0 blocks forever, matching Get's convention.
  std::vector<size_t> Wait(const std::vector<LocalObjectRef>& refs,
                           size_t num_ready, int64_t timeout_ms) {
    const bool forever = timeout_ms < 0;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(forever ? 0 : timeout_ms);
    std::vector<size_t> ready;
    for (;;) {
      ready.clear();
      for (size_t i = 0; i < refs.size(); i++)
        if (refs[i].Ready()) ready.push_back(i);
      if (ready.size() >= num_ready ||
          (!forever && std::chrono::steady_clock::now() >= deadline))
        return ready;
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }

  LocalObjectRef Task(const std::string& name, std::vector<Arg> args) {
    TaskFn fn = FunctionRegistry::Instance().Find(name);  // fail fast
    LocalObjectRef ref;
    auto work = [fn, args, ref]() mutable { RunInto(ref, fn, args); };
    // dependency-gate: enqueue only once every ref arg is resolved, so
    // pool threads never block on unresolved deps (submission order of
    // plain tasks is not an execution-order contract)
    WhenArgsReady(args, [this, work = std::move(work)]() mutable {
      Enqueue(std::move(work));
    });
    return ref;
  }

  // ----------------------------------------------------------- actors
  struct ActorHandle {
    std::shared_ptr<ActorStateBox> box;
  };

  ActorHandle CreateActor(const std::string& cls_name,
                          const std::vector<Value>& args) {
    const ActorClassInfo& cls = ActorRegistry::Instance().Find(cls_name);
    ActorHandle h;
    h.box = std::make_shared<ActorStateBox>();
    h.box->cls = &cls;
    h.box->instance = cls.factory(args);  // synchronous ctor, like ref
    return h;
  }

  LocalObjectRef CallActor(const ActorHandle& h, const std::string& method,
                           std::vector<Arg> args) {
    auto it = h.box->cls->methods.find(method);
    if (it == h.box->cls->methods.end())
      throw std::runtime_error("no such actor method: " + method);
    auto m = it->second;
    LocalObjectRef ref;
    auto box = h.box;
    MailboxEntry entry;
    for (auto& a : args)
      if (std::holds_alternative<LocalObjectRef>(a))
        entry.deps.push_back(std::get<LocalObjectRef>(a));
    entry.work = [box, m, args = std::move(args), ref]() mutable {
      void* self = box->instance.get();
      RunInto(ref,
              [self, m](const std::vector<Value>& a) { return m(self, a); },
              args);
    };
    // FIFO mailbox: enqueue; if no drainer is active, this submission
    // becomes the drainer — actor methods never run concurrently and
    // run in submission order (actor semantics). The drainer yields its
    // pool thread when the front entry's deps are unresolved.
    bool start_drain = false;
    {
      std::lock_guard<std::mutex> g(box->mu);
      box->mailbox.push_back(std::move(entry));
      if (!box->draining) {
        box->draining = true;
        start_drain = true;
      }
    }
    if (start_drain) Enqueue([this, box] { DrainActor(box); });
    return ref;
  }

 private:
  template <typename F>
  static void RunInto(LocalObjectRef& ref, F&& fn, std::vector<Arg>& args) {
    try {
      std::vector<Value> vals;
      vals.reserve(args.size());
      for (auto& a : args) {
        if (std::holds_alternative<Value>(a))
          vals.push_back(std::get<Value>(a));
        else
          vals.push_back(std::get<LocalObjectRef>(a).Get());
      }
      ref.Resolve(fn(vals));
    } catch (const std::exception& e) {
      ref.Fail(e.what());
    }
  }

  // Fire fn once every ref arg in args is resolved (immediately when
  // none are pending). Countdown starts at 1 so fn can't fire before
  // all OnReady registrations are in place.
  template <typename F>
  static void WhenArgsReady(const std::vector<Arg>& args, F fn) {
    auto pending = std::make_shared<std::atomic<int>>(1);
    auto shared_fn = std::make_shared<F>(std::move(fn));
    auto fire = [pending, shared_fn] {
      if (pending->fetch_sub(1) == 1) (*shared_fn)();
    };
    for (const auto& a : args) {
      if (std::holds_alternative<LocalObjectRef>(a)) {
        pending->fetch_add(1);
        std::get<LocalObjectRef>(a).OnReady(fire);
      }
    }
    fire();
  }

  void DrainActor(const std::shared_ptr<ActorStateBox>& box) {
    for (;;) {
      MailboxEntry entry;
      {
        std::lock_guard<std::mutex> g(box->mu);
        if (box->mailbox.empty()) {
          box->draining = false;
          return;
        }
        // front's deps unresolved: keep FIFO order — yield this pool
        // thread and restart the drain when they resolve
        for (const auto& d : box->mailbox.front().deps) {
          if (!d.Ready()) {
            // draining stays true: no second drainer can start
            d.OnReady([this, box] { Enqueue([this, box] {
              DrainActor(box);
            }); });
            return;
          }
        }
        entry = std::move(box->mailbox.front());
        box->mailbox.pop_front();
      }
      entry.work();
    }
  }

  void Enqueue(std::function<void()> work) {
    {
      std::lock_guard<std::mutex> g(mu_);
      queue_.push_back(std::move(work));
    }
    cv_.notify_one();
  }

  void WorkerLoop() {
    for (;;) {
      std::function<void()> work;
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_.wait(g, [&] { return stop_ || !queue_.empty(); });
        if (stop_ && queue_.empty()) return;
        work = std::move(queue_.front());
        queue_.pop_front();
      }
      work();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::function<void()>> queue_;
  std::vector<std::thread> pool_;
  bool stop_;
};

}  // namespace local
}  // namespace rt
