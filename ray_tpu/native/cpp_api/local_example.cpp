// Local-mode C++ runtime example/selftest (reference:
// cpp/src/ray/test/examples + local_mode_ray_runtime tests): native
// task registration, dependency chaining through object refs, error
// propagation, serialized actor mailboxes under concurrent submission,
// and Wait. Run by tests/test_cpp_api.py; prints LOCAL_MODE_OK.
#include <atomic>
#include <cstdio>
#include <stdexcept>
#include <thread>
#include <vector>

#include "local_mode.hpp"

using rt::Value;
using rt::local::Arg;
using rt::local::LocalObjectRef;
using rt::local::LocalRuntime;

static Value Pow(const std::vector<Value>& a) {
  int64_t base = a[0].i, exp = a[1].i, out = 1;
  for (int64_t k = 0; k < exp; k++) out *= base;
  return Value::Int(out);
}
RT_LOCAL_REMOTE(Pow);

static Value AddOne(const std::vector<Value>& a) {
  return Value::Int(a[0].i + 1);
}
RT_LOCAL_REMOTE(AddOne);

static Value Fails(const std::vector<Value>&) {
  throw std::runtime_error("intentional boom");
}
RT_LOCAL_REMOTE(Fails);

static Value SlowEcho(const std::vector<Value>& a) {
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  return a[0];
}
RT_LOCAL_REMOTE(SlowEcho);

// An actor: counter with history-order check.
class Counter {
 public:
  explicit Counter(const std::vector<Value>& args)
      : total_(args.empty() ? 0 : args[0].i) {}
  Value Add(const std::vector<Value>& a) {
    // detect concurrent entry (would corrupt `entered_` discipline)
    if (entered_.exchange(true)) return Value::Str("CONCURRENT!");
    std::this_thread::sleep_for(std::chrono::microseconds(200));
    total_ += a[0].i;
    entered_ = false;
    return Value::Int(total_);
  }
  Value Total(const std::vector<Value>&) { return Value::Int(total_); }

 private:
  int64_t total_;
  std::atomic<bool> entered_{false};
};

int main() {
  rt::local::RegisterActorClass<Counter>(
      "Counter", {{"Add", &Counter::Add}, {"Total", &Counter::Total}});

  LocalRuntime rt(4);

  // task + get
  auto r1 = rt.Task("Pow", {Arg(Value::Int(2)), Arg(Value::Int(10))});
  if (rt.Get(r1).i != 1024) return 1;
  printf("pow=%lld\n", (long long)rt.Get(r1).i);

  // dependency chain: AddOne(AddOne(Pow(2,3))) == 10
  auto c1 = rt.Task("Pow", {Arg(Value::Int(2)), Arg(Value::Int(3))});
  auto c2 = rt.Task("AddOne", {Arg(c1)});
  auto c3 = rt.Task("AddOne", {Arg(c2)});
  if (rt.Get(c3).i != 10) return 2;
  printf("chain=%lld\n", (long long)rt.Get(c3).i);

  // error propagation
  bool threw = false;
  try {
    rt.Get(rt.Task("Fails", {}));
  } catch (const std::exception& e) {
    threw = std::string(e.what()).find("intentional boom") !=
            std::string::npos;
  }
  if (!threw) return 3;
  printf("error propagated\n");

  // unknown function fails fast at submission
  threw = false;
  try {
    rt.Task("Nope", {});
  } catch (const std::exception&) {
    threw = true;
  }
  if (!threw) return 4;

  // Put/Get + Wait
  auto p = rt.Put(Value::Str("hello"));
  std::vector<LocalObjectRef> refs = {
      p, rt.Task("SlowEcho", {Arg(Value::Int(7))})};
  auto ready = rt.Wait(refs, 1, 1000);
  if (ready.empty()) return 5;
  auto all = rt.Wait(refs, 2, 5000);
  if (all.size() != 2) return 6;
  printf("wait ok\n");

  // actor: 64 concurrent Adds from 4 threads must serialize FIFO
  auto h = rt.CreateActor("Counter", {Value::Int(100)});
  std::vector<LocalObjectRef> adds;
  std::mutex addmu;
  std::vector<std::thread> ts;
  for (int t = 0; t < 4; t++) {
    ts.emplace_back([&] {
      for (int i = 0; i < 16; i++) {
        auto r = rt.CallActor(h, "Add", {Arg(Value::Int(1))});
        std::lock_guard<std::mutex> g(addmu);
        adds.push_back(r);
      }
    });
  }
  for (auto& t : ts) t.join();
  for (auto& r : adds) {
    Value v = rt.Get(r);
    if (v.type == Value::STR) {
      printf("CONCURRENT ACTOR ENTRY\n");
      return 7;
    }
  }
  auto total = rt.Get(rt.CallActor(h, "Total", {}));
  if (total.i != 164) return 8;
  printf("actor_total=%lld\n", (long long)total.i);

  // dependency-gating regression: on a 1-thread pool, a task whose dep
  // is unresolved must not occupy the worker (old blocking design
  // deadlocked here); later independent tasks keep flowing, and actor
  // FIFO order is preserved across an unresolved-dep head-of-line
  {
    LocalRuntime rt1(1);
    LocalObjectRef pending;  // resolved manually below
    auto gated = rt1.Task("AddOne", {Arg(pending)});
    auto free1 = rt1.Task("Pow", {Arg(Value::Int(3)), Arg(Value::Int(2))});
    if (rt1.Get(free1, 2000).i != 9) return 9;   // pool not wedged
    auto h1 = rt1.CreateActor("Counter", {Value::Int(0)});
    auto a1 = rt1.CallActor(h1, "Add", {Arg(pending)});
    auto a2 = rt1.CallActor(h1, "Total", {});
    if (!rt1.Wait({a2}, 1, 200).empty()) return 10;  // FIFO held back
    pending.Resolve(Value::Int(41));
    if (rt1.Get(gated, 2000).i != 42) return 11;
    if (rt1.Get(a2, 2000).i != 41) return 12;        // Add ran first
    printf("gating ok\n");
  }

  printf("LOCAL_MODE_OK\n");
  return 0;
}
