// Cross-language smoke: submit Python tasks from C++ (see ray_tpu_api.hpp).
#include <cstdio>
#include <string>

#include "ray_tpu_api.hpp"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <gcs tcp:host:port>\n", argv[0]);
    return 2;
  }
  rt::Client client;
  client.Connect(argv[1]);

  rt::Value p = client.Call("builtins:pow",
                            {rt::Value::Int(2), rt::Value::Int(10)});
  std::printf("pow=%lld\n", static_cast<long long>(p.i));
  if (p.i != 1024) return 1;

  rt::Value ln = client.Call(
      "builtins:len", {rt::Value::Str("hello-cross-language")});
  std::printf("len=%lld\n", static_cast<long long>(ln.i));
  if (ln.i != 20) return 1;

  bool raised = false;
  try {
    client.Call("builtins:int", {rt::Value::Str("not-a-number")});
  } catch (const std::exception& e) {
    raised = true;
    std::printf("error propagated: %s\n", e.what());
  }
  if (!raised) return 1;

  // cross-language actor: create, call methods, observe state, kill
  rt::Client::ActorHandle acc = client.CreateActor(
      "ray_tpu.util.xlang_demo:Accumulator", {rt::Value::Int(100)});
  rt::Value r1 = client.CallActor(acc, "add", {rt::Value::Int(5)});
  rt::Value r2 = client.CallActor(acc, "add", {rt::Value::Int(7)});
  rt::Value r3 = client.CallActor(acc, "get", {});
  std::printf("actor_total=%lld\n", static_cast<long long>(r3.i));
  if (r1.i != 105 || r2.i != 112 || r3.i != 112) return 1;
  bool actor_err = false;
  try {
    client.CallActor(acc, "add", {rt::Value::Str("not-a-number")});
  } catch (const std::exception& e) {
    actor_err = true;
    std::printf("actor error propagated: %s\n", e.what());
  }
  if (!actor_err) return 1;
  client.KillActor(acc);

  // death path: a killed actor's connection drops — the next call must
  // surface an error, never hang (reference: actor death propagation to
  // xlang callers)
  bool dead_err = false;
  for (int attempt = 0; attempt < 50 && !dead_err; attempt++) {
    try {
      acc.conn.reset();   // force a reconnect to the (dead) address
      client.CallActor(acc, "get", {});
      // worker may not have exited yet; retry until the kill lands
      std::this_thread::sleep_for(std::chrono::milliseconds(100));
    } catch (const std::exception& e) {
      dead_err = true;
      std::printf("dead actor error: %s\n", e.what());
    }
  }
  if (!dead_err) return 1;

  // creation failure path: a bogus class ref fails loudly within the
  // timeout instead of hanging
  bool create_err = false;
  try {
    client.CreateActor("nosuch.module:Nope", {}, 1.0, 15.0);
  } catch (const std::exception& e) {
    create_err = true;
    std::printf("create error propagated: %s\n", e.what());
  }
  if (!create_err) return 1;

  std::printf("CPP_API_OK\n");
  return 0;
}
