// Cross-language smoke: submit Python tasks from C++ (see ray_tpu_api.hpp).
#include <cstdio>
#include <string>

#include "ray_tpu_api.hpp"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <gcs tcp:host:port>\n", argv[0]);
    return 2;
  }
  rt::Client client;
  client.Connect(argv[1]);

  rt::Value p = client.Call("builtins:pow",
                            {rt::Value::Int(2), rt::Value::Int(10)});
  std::printf("pow=%lld\n", static_cast<long long>(p.i));
  if (p.i != 1024) return 1;

  rt::Value ln = client.Call(
      "builtins:len", {rt::Value::Str("hello-cross-language")});
  std::printf("len=%lld\n", static_cast<long long>(ln.i));
  if (ln.i != 20) return 1;

  bool raised = false;
  try {
    client.Call("builtins:int", {rt::Value::Str("not-a-number")});
  } catch (const std::exception& e) {
    raised = true;
    std::printf("error propagated: %s\n", e.what());
  }
  if (!raised) return 1;

  std::printf("CPP_API_OK\n");
  return 0;
}
