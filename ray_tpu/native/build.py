"""Build the native components on demand.

The native library is compiled once per source change into
``ray_tpu/native/_build/`` and loaded via ctypes (no pybind11 in this image;
the C ABI + ctypes keeps the binding dependency-free).
"""

from __future__ import annotations

import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()

_SOURCES = {
    "shm_store": ["shm_store.cpp"],
    "mutable_channel": ["mutable_channel.cpp"],
}


def lib_path(name: str) -> str:
    return os.path.join(_BUILD_DIR, f"lib{name}.so")


def build(name: str) -> str:
    """Compile (if stale) and return the path to lib<name>.so."""
    srcs = [os.path.join(_HERE, s) for s in _SOURCES[name]]
    out = lib_path(name)
    with _LOCK:
        src_mtime = max(os.path.getmtime(s) for s in srcs)
        if os.path.exists(out) and os.path.getmtime(out) >= src_mtime:
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = f"{out}.tmp.{os.getpid()}"  # per-process tmp; os.replace is atomic
        cmd = [
            "g++", "-O2", "-g", "-shared", "-fPIC", "-std=c++17",
            "-o", tmp, *srcs, "-lpthread",
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, out)
    return out
