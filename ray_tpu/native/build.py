"""Build the native components on demand.

The native library is compiled once per source change into
``ray_tpu/native/_build/`` and loaded via ctypes (no pybind11 in this image;
the C ABI + ctypes keeps the binding dependency-free).
"""

from __future__ import annotations

import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()

_SOURCES = {
    "shm_store": ["shm_store.cpp"],
    "mutable_channel": ["mutable_channel.cpp"],
}


def lib_path(name: str) -> str:
    return os.path.join(_BUILD_DIR, f"lib{name}.so")


def _compile(srcs, out, flags) -> str:
    """Compile (if stale vs source mtimes) srcs -> out; atomic replace."""
    with _LOCK:
        src_mtime = max(os.path.getmtime(s) for s in srcs)
        if os.path.exists(out) and os.path.getmtime(out) >= src_mtime:
            return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = f"{out}.tmp.{os.getpid()}"  # per-process tmp; os.replace is atomic
        cmd = ["g++", "-std=c++17", *flags, "-o", tmp, *srcs, "-lpthread"]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, out)
    return out


def build(name: str) -> str:
    """Compile (if stale) and return the path to lib<name>.so."""
    srcs = [os.path.join(_HERE, s) for s in _SOURCES[name]]
    return _compile(srcs, lib_path(name),
                    ["-O2", "-g", "-shared", "-fPIC"])


# Standalone sanitizer harnesses (the reference's build:asan/build:ubsan
# CI story, .bazelrc:104-125): each entry is a main() program compiled
# WITH the component sources under -fsanitize and run as a subprocess by
# tests/test_sanitizers.py. The suite runs asan+ubsan plus a
# sanitize="thread" build of the shm store's concurrent sections: the
# off-loop put path (per-stripe allocator + rt_write_parallel copy pool)
# and the lock-striped arena's racy surfaces — lock-free seal CAS,
# seqlock stats reads, and concurrent create/seal/get/evict across >=4
# stripes. The seqlock's publication edge is explicitly annotated for
# tsan (RT_TSAN_ACQUIRE/RT_TSAN_RELEASE in shm_store.cpp, compiled in
# only under -fsanitize=thread), so the reader/writer pairing is checked
# at the protocol level, not just per-field. tsan runs single-process
# multi-thread only — the cross-process robust-mutex EOWNERDEAD repair
# path is exercised by the asan harness via a re-exec'd crash child.
_SELFTESTS = {
    "shm_store_selftest": ["shm_store_selftest.cpp", "shm_store.cpp"],
    "mutable_channel_selftest": ["mutable_channel_selftest.cpp",
                                 "mutable_channel.cpp"],
}


def build_selftest(name: str, sanitize: str = "address,undefined") -> str:
    """Compile (if stale) a sanitizer selftest binary; returns its path."""
    srcs = [os.path.join(_HERE, s) for s in _SELFTESTS[name]]
    out = os.path.join(_BUILD_DIR, f"{name}.{sanitize.replace(',', '_')}")
    # tsan's runtime slowdown (5-15x) is hostile at -O1 on 1-core CI
    # hosts; -O2 keeps the hammer sections inside their test timeouts
    opt = "-O2" if sanitize == "thread" else "-O1"
    return _compile(srcs, out,
                    [opt, "-g", f"-fsanitize={sanitize}",
                     "-fno-omit-frame-pointer"])
