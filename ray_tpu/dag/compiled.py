"""CompiledDAG: static execution over pre-allocated actors + mutable
channels (reference: python/ray/dag/compiled_dag_node.py:549 — compiled
graphs bypass per-call scheduling/serialization; execution schedule:
dag_node_operation.py). Each participating actor runs a long-lived loop
(driven by a built-in actor method) that reads its input channels, applies
the bound methods, and writes output channels; the driver writes the input
channel and reads the terminal channel — the per-call cost is two shm
channel handoffs, no RPC."""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.nodes import (ClassMethodNode, DAGNode, InputNode,
                               MultiOutputNode)
from ray_tpu.experimental.channel import (Channel, ChannelClosed,
                                          ChannelWriter, node_local_path,
                                          open_wait)


def _topo(root: DAGNode) -> List[DAGNode]:
    order: List[DAGNode] = []
    seen = set()

    def visit(n: DAGNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        for up in n._upstream():
            visit(up)
        order.append(n)

    visit(root)
    return order


class CompiledDAG:
    """Cross-node aware: each edge's channel lives on its PRODUCER's
    node; consumer nodes (and the driver's node, for outputs) receive
    published versions as node-manager-pushed mirrors (reference: NCCL
    channels + PushMutableObject; here the transport is shm locally and
    the node managers' RPC plane across nodes)."""

    def __init__(self, root: DAGNode, max_buffer_size: int = 1 << 20):
        import ray_tpu
        self.root = root
        self.dir = f"/tmp/raytpu/channels/{uuid.uuid4().hex[:12]}"
        os.makedirs(self.dir, exist_ok=True)
        nodes = _topo(root)
        self.input_node: Optional[InputNode] = None
        if isinstance(root, MultiOutputNode):
            outputs = root.outputs
        else:
            outputs = [root]

        w = ray_tpu._get_worker()
        driver_node = w.core.node_id
        # actor placement (the GCS actor table knows each actor's node)
        actor_node: Dict[str, str] = {}
        self._actors = {}
        for n in nodes:
            if isinstance(n, ClassMethodNode):
                aid = n.actor._actor_id
                self._actors[aid] = n.actor
                if aid not in actor_node:
                    # compile may race actor creation: wait until the GCS
                    # has placed it (its node decides channel placement)
                    import time as _time
                    deadline = _time.monotonic() + 60.0
                    while True:
                        info = w.gcs_call("get_actor_info", actor_id=aid)
                        if info and info.get("node_id") \
                                and info.get("state") == "ALIVE":
                            actor_node[aid] = info["node_id"]
                            break
                        if info and info.get("state") == "DEAD":
                            raise RuntimeError(
                                f"actor {aid[:12]} died before compile: "
                                f"{info.get('death_cause')}")
                        if _time.monotonic() > deadline:
                            raise RuntimeError(
                                f"actor {aid[:12]} never became ALIVE "
                                f"(state: {info and info.get('state')})")
                        _time.sleep(0.05)

        def node_of(n: DAGNode) -> str:
            if isinstance(n, ClassMethodNode):
                return actor_node[n.actor._actor_id]
            return driver_node       # InputNode: the driver produces

        # per-produced-value reader counts by node; same-actor edges
        # resolve in-process (no channel read), so they don't count
        readers: Dict[int, Dict[str, int]] = {}
        seen_edges = set()
        for n in nodes:
            if isinstance(n, MultiOutputNode):
                continue
            for up in n._upstream():
                if (isinstance(n, ClassMethodNode)
                        and isinstance(up, ClassMethodNode)
                        and n.actor._actor_id == up.actor._actor_id):
                    continue
                if isinstance(n, ClassMethodNode):
                    # an actor's loop reads each input channel ONCE per
                    # iteration no matter how many of its steps consume
                    # it (values cache) — count one reader per actor
                    edge = (n.actor._actor_id, id(up))
                    if edge in seen_edges:
                        continue
                    seen_edges.add(edge)
                by_node = readers.setdefault(id(up), {})
                nn = node_of(n)
                by_node[nn] = by_node.get(nn, 0) + 1
        for out in outputs:
            by_node = readers.setdefault(id(out), {})
            by_node[driver_node] = by_node.get(driver_node, 0) + 1

        # one channel SPEC per produced value: the producer creates its
        # local channel; other reader nodes get pushed mirrors
        self.specs: Dict[int, Dict] = {}
        for n in nodes:
            if isinstance(n, MultiOutputNode):
                continue
            if isinstance(n, InputNode):
                if self.input_node is not None and self.input_node is not n:
                    raise ValueError("only one InputNode supported")
                self.input_node = n
            prod = node_of(n)
            by_node = readers.get(id(n), {})
            self.specs[id(n)] = {
                "path": os.path.join(self.dir, f"ch_{len(self.specs)}"),
                "max_size": max_buffer_size,
                "producer_node": prod,
                "local_readers": by_node.get(prod, 0),
                "remote": {nid: cnt for nid, cnt in by_node.items()
                           if nid != prod},
            }

        # per-actor step plans, in topological order
        plans: Dict[str, Dict] = {}
        for n in nodes:
            if not isinstance(n, ClassMethodNode):
                continue
            plan = plans.setdefault(n.actor._actor_id, {"steps": []})

            def enc(arg):
                if isinstance(arg, DAGNode):
                    return {"chan": self.specs[id(arg)]}
                return {"const": arg}

            plan["steps"].append({
                "method": n.method_name,
                "args": [enc(a) for a in n.args],
                "kwargs": {k: enc(v) for k, v in n.kwargs.items()},
                "out": self.specs[id(n)],
            })

        # launch the loops (each actor creates its own output channels)
        self._loop_refs = []
        for aid, plan in plans.items():
            handle = self._actors[aid]
            from ray_tpu.actor import ActorMethod
            loop_method = ActorMethod(handle, "__rt_dag_loop__")
            self._loop_refs.append(loop_method.remote(plan["steps"]))

        # driver side: writer for the input edge, readers for outputs
        self._in_writer = None
        if self.input_node is not None:
            self._in_writer = ChannelWriter(self.specs[id(self.input_node)])
        self._out_specs = [self.specs[id(o)] for o in outputs]
        self._out_chans = None   # opened lazily (producers create them)
        self._multi = isinstance(root, MultiOutputNode)
        self._destroyed = False

    def _ensure_out_chans(self, timeout_s: float):
        if self._out_chans is None:
            import ray_tpu
            me = ray_tpu._get_worker().core.node_id
            self._out_chans = [
                open_wait(node_local_path(sp["path"], me), timeout_s)
                for sp in self._out_specs]

    def execute(self, *args, timeout_s: float = 60.0):
        if self._in_writer is not None:
            value = args[0] if len(args) == 1 else args
            self._in_writer.write(value, timeout_s=timeout_s)
        self._ensure_out_chans(timeout_s)
        outs = [c.read(timeout_s=timeout_s) for c in self._out_chans]
        return outs if self._multi else outs[0]

    def teardown(self):
        if self._destroyed:
            return
        self._destroyed = True
        import ray_tpu
        w = ray_tpu._get_worker()
        # close every edge everywhere: local channels + pushed mirrors
        for sp in self.specs.values():
            targets = set(sp["remote"])
            targets.add(sp["producer_node"])
            try:
                w.node_call("channel_close", path=sp["path"],
                            targets=list(targets))
            except Exception:
                pass
        if self._in_writer is not None:
            self._in_writer.close()
        try:
            ray_tpu.get(self._loop_refs, timeout=10)
        except Exception:
            pass
        if self._out_chans:
            for ch in self._out_chans:
                try:
                    ch.destroy()
                except Exception:
                    pass
        import shutil
        shutil.rmtree(self.dir, ignore_errors=True)

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


def _dag_actor_loop(instance, steps: List[Dict]):
    """Runs inside the actor (executor thread) until channels close.
    Output channels are CREATED here (the producer's node owns the
    channel); input channels are opened with a wait, since a remote
    producer's mirror only appears on this node at its first push."""
    writers: Dict[str, ChannelWriter] = {}
    for step in steps:
        sp = step["out"]
        if sp["path"] not in writers:
            writers[sp["path"]] = ChannelWriter(sp)
    in_chans: Dict[str, Channel] = {}

    from ray_tpu import _get_worker
    me = _get_worker().core.node_id

    def in_chan(sp) -> Channel:
        ch = in_chans.get(sp["path"])
        if ch is None:
            # the mirror only materializes at the producer's first
            # publish, which may be arbitrarily long after compile —
            # wait like a read would
            ch = open_wait(node_local_path(sp["path"], me),
                           timeout_s=3600.0)
            in_chans[sp["path"]] = ch
        return ch

    try:
        while True:
            values: Dict[str, Any] = {}

            def resolve(a):
                if "const" in a:
                    return a["const"]
                path = a["chan"]["path"]
                if path not in values:
                    values[path] = in_chan(a["chan"]).read(timeout_s=3600.0)
                return values[path]

            for step in steps:
                args = [resolve(a) for a in step["args"]]
                kwargs = {k: resolve(v) for k, v in step["kwargs"].items()}
                out = getattr(instance, step["method"])(*args, **kwargs)
                writers[step["out"]["path"]].write(out)
                values[step["out"]["path"]] = out
    except ChannelClosed:
        return "closed"
    except TimeoutError:
        return "timeout"
