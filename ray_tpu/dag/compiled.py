"""CompiledDAG: static execution over pre-allocated actors + mutable
channels (reference: python/ray/dag/compiled_dag_node.py:549 — compiled
graphs bypass per-call scheduling/serialization; execution schedule:
dag_node_operation.py). Each participating actor runs a long-lived loop
(driven by a built-in actor method) that reads its input channels, applies
the bound methods, and writes output channels; the driver writes the input
channel and reads the terminal channel — the per-call cost is two shm
channel handoffs, no RPC."""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag.nodes import (ClassMethodNode, DAGNode, InputNode,
                               MultiOutputNode)
from ray_tpu.experimental.channel import Channel, ChannelClosed


def _topo(root: DAGNode) -> List[DAGNode]:
    order: List[DAGNode] = []
    seen = set()

    def visit(n: DAGNode):
        if id(n) in seen:
            return
        seen.add(id(n))
        for up in n._upstream():
            visit(up)
        order.append(n)

    visit(root)
    return order


class CompiledDAG:
    def __init__(self, root: DAGNode, max_buffer_size: int = 1 << 20):
        import ray_tpu
        self.root = root
        self.dir = f"/tmp/raytpu/channels/{uuid.uuid4().hex[:12]}"
        os.makedirs(self.dir, exist_ok=True)
        nodes = _topo(root)
        self.input_node: Optional[InputNode] = None
        terminal = root
        if isinstance(root, MultiOutputNode):
            outputs = root.outputs
        else:
            outputs = [root]

        # consumer counts per producing node; same-actor edges resolve
        # in-process (no channel read), so they don't count as readers
        consumers: Dict[int, int] = {}
        for n in nodes:
            if isinstance(n, MultiOutputNode):
                continue
            for up in n._upstream():
                if (isinstance(n, ClassMethodNode)
                        and isinstance(up, ClassMethodNode)
                        and n.actor._actor_id == up.actor._actor_id):
                    continue
                consumers[id(up)] = consumers.get(id(up), 0) + 1
        for out in outputs:
            consumers[id(out)] = consumers.get(id(out), 0) + 1  # driver reads

        # create one channel per produced value
        self.channels: Dict[int, str] = {}
        self._chan_objs: List[Channel] = []
        for n in nodes:
            if isinstance(n, MultiOutputNode):
                continue
            if isinstance(n, InputNode):
                if self.input_node is not None and self.input_node is not n:
                    raise ValueError("only one InputNode supported")
                self.input_node = n
            path = os.path.join(self.dir, f"ch_{len(self.channels)}")
            ch = Channel(path, max_size=max_buffer_size,
                         num_readers=consumers.get(id(n), 1), create=True)
            self._chan_objs.append(ch)
            self.channels[id(n)] = path

        # per-actor step plans, in topological order
        plans: Dict[str, Dict] = {}
        self._actors = {}
        for n in nodes:
            if not isinstance(n, ClassMethodNode):
                continue
            aid = n.actor._actor_id
            self._actors[aid] = n.actor
            plan = plans.setdefault(aid, {"steps": []})

            def enc(arg):
                if isinstance(arg, DAGNode):
                    return {"chan": self.channels[id(arg)]}
                return {"const": arg}

            plan["steps"].append({
                "method": n.method_name,
                "args": [enc(a) for a in n.args],
                "kwargs": {k: enc(v) for k, v in n.kwargs.items()},
                "out": self.channels[id(n)],
            })

        # launch the loops
        self._loop_refs = []
        for aid, plan in plans.items():
            handle = self._actors[aid]
            from ray_tpu.actor import ActorMethod
            loop_method = ActorMethod(handle, "__rt_dag_loop__")
            self._loop_refs.append(loop_method.remote(plan["steps"]))

        self.output_paths = [self.channels[id(o)] for o in outputs]
        self._out_chans = [Channel(p) for p in self.output_paths]
        self._in_chan = (Channel(self.channels[id(self.input_node)])
                         if self.input_node is not None else None)
        self._multi = isinstance(root, MultiOutputNode)
        self._destroyed = False

    def execute(self, *args, timeout_s: float = 60.0):
        if self._in_chan is not None:
            value = args[0] if len(args) == 1 else args
            self._in_chan.write(value, timeout_s=timeout_s)
        outs = [c.read(timeout_s=timeout_s) for c in self._out_chans]
        return outs if self._multi else outs[0]

    def teardown(self):
        if self._destroyed:
            return
        self._destroyed = True
        for ch in self._chan_objs:
            ch.close()
        import ray_tpu
        try:
            ray_tpu.get(self._loop_refs, timeout=10)
        except Exception:
            pass
        for ch in self._chan_objs:
            ch.destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


def _dag_actor_loop(instance, steps: List[Dict]):
    """Runs inside the actor (executor thread) until channels close."""
    in_chans: Dict[str, Channel] = {}
    out_chans: Dict[str, Channel] = {}
    for step in steps:
        for a in list(step["args"]) + list(step["kwargs"].values()):
            if "chan" in a and a["chan"] not in in_chans:
                in_chans[a["chan"]] = Channel(a["chan"])
        if step["out"] not in out_chans:
            out_chans[step["out"]] = Channel(step["out"])
    try:
        while True:
            values: Dict[str, Any] = {}

            def resolve(a):
                if "const" in a:
                    return a["const"]
                path = a["chan"]
                if path not in values:
                    values[path] = in_chans[path].read(timeout_s=3600.0)
                return values[path]

            for step in steps:
                args = [resolve(a) for a in step["args"]]
                kwargs = {k: resolve(v) for k, v in step["kwargs"].items()}
                out = getattr(instance, step["method"])(*args, **kwargs)
                out_chans[step["out"]].write(out)
                values[step["out"]] = out
    except ChannelClosed:
        return "closed"
    except TimeoutError:
        return "timeout"
