"""DAG IR (reference: python/ray/dag/dag_node.py, input_node.py,
class_node.py — InputNode/ClassMethodNode graph captured by .bind())."""

from __future__ import annotations

from typing import Any, List, Optional, Tuple


class DAGNode:
    def __init__(self):
        self._downstream: List["DAGNode"] = []

    def experimental_compile(self, max_buffer_size: int = 1 << 20):
        from ray_tpu.dag.compiled import CompiledDAG
        return CompiledDAG(self, max_buffer_size=max_buffer_size)

    def _upstream(self) -> List["DAGNode"]:
        return []


class InputNode(DAGNode):
    """The driver-supplied input (context-manager idiom like the
    reference's `with InputNode() as inp:`)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __repr__(self):
        return "InputNode()"


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, args: Tuple,
                 kwargs: dict):
        super().__init__()
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs

    def _upstream(self) -> List[DAGNode]:
        ups = [a for a in self.args if isinstance(a, DAGNode)]
        ups += [v for v in self.kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def __repr__(self):
        return f"ClassMethodNode({self.method_name})"


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__()
        self.outputs = list(outputs)

    def _upstream(self) -> List[DAGNode]:
        return list(self.outputs)
