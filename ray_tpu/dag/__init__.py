from ray_tpu.dag.nodes import (ClassMethodNode, DAGNode, InputNode,
                               MultiOutputNode)
from ray_tpu.dag.compiled import CompiledDAG

__all__ = ["InputNode", "DAGNode", "ClassMethodNode", "MultiOutputNode",
           "CompiledDAG"]
