"""Remote client sessions ("Ray Client" equivalent).

The reference runs a gRPC proxy on the head node that muxes remote
interactive drivers into the cluster (reference: python/ray/util/client/ —
server/proxier.py per-job servers, client worker.py, `ray://` addresses;
client_mode_hook wraps the public API). Here the proxy is an asyncio RPC
server (same msgpack transport as the rest of the control plane) hosting
one real in-cluster driver; each connected client gets a session that
ships pickled functions/classes once, submits tasks/actor calls by id,
and fetches results by object id. `ray_tpu.client.connect("host:port")`
flips the public API into client mode.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import cloudpickle

_client: Optional["ClientContext"] = None


def current_client() -> Optional["ClientContext"]:
    return _client


# --------------------------------------------------------------- server side
class ClientProxyServer:
    """Runs inside (or next to) the cluster head: a driver that executes
    API calls on behalf of remote clients."""

    def __init__(self, gcs_address: Optional[str] = None, port: int = 0):
        self.gcs_address = gcs_address
        self.port = port
        self.address: Optional[str] = None
        # Per-CONNECTION sessions (reference: proxier.py runs one server
        # per job; here sessions share the proxy driver process but each
        # client gets its OWN function table / ref table / actor table,
        # and disconnect frees the session's refs and kills its
        # non-detached actors — one client's leaks cannot pin another's
        # objects or grow its tables).
        self._sessions: Dict[int, Dict[str, Dict]] = {}
        self._lock = threading.Lock()
        self._next = 0

    def _session(self, conn) -> Dict[str, Dict]:
        key = conn.peer_info.setdefault("client_session", id(conn))
        st = self._sessions.get(key)
        if st is None:
            st = self._sessions[key] = {"funcs": {}, "objects": {},
                                        "actors": {}}
        return st

    def _on_disconnect(self, conn):
        key = conn.peer_info.get("client_session")
        st = self._sessions.pop(key, None) if key is not None else None
        if not st:
            return
        st["objects"].clear()     # drop the session's ref pins
        detached = st.get("detached") or set()
        victims = [h for aid, h in st["actors"].items()
                   if aid not in detached]
        if victims:
            import asyncio

            import ray_tpu

            def _reap(handles):
                for h in handles:
                    try:
                        ray_tpu.kill(h)
                    except Exception:
                        pass
            asyncio.get_event_loop().run_in_executor(None, _reap, victims)

    def _new_id(self) -> bytes:
        import os
        with self._lock:
            self._next += 1
            return self._next.to_bytes(8, "little") + os.urandom(8)

    def _track(self, conn, ref) -> bytes:
        oid = self._new_id()
        self._session(conn)["objects"][oid] = ref
        return oid

    # -- handlers (run on the proxy's rpc loop; blocking work uses the
    #    driver's own bridge thread through executors)
    async def h_put(self, conn, payload: bytes):
        import asyncio

        import ray_tpu
        value = cloudpickle.loads(payload)
        ref = await asyncio.get_event_loop().run_in_executor(
            None, ray_tpu.put, value)
        return self._track(conn, ref)

    async def h_get(self, conn, oids: List[bytes], timeout=None):
        import asyncio

        import ray_tpu
        objects = self._session(conn)["objects"]
        refs = [objects[o] for o in oids]

        def fetch():
            vals = ray_tpu.get(refs, timeout=timeout)
            return cloudpickle.dumps(vals)
        try:
            return {"ok": True,
                    "payload": await asyncio.get_event_loop()
                    .run_in_executor(None, fetch)}
        except Exception as e:
            return {"ok": False, "error": cloudpickle.dumps(e)}

    async def h_wait(self, conn, oids: List[bytes], num_returns: int,
                     timeout=None):
        import asyncio

        import ray_tpu
        objects = self._session(conn)["objects"]
        refs = [objects[o] for o in oids]
        by_ref = {id(objects[o]): o for o in oids}
        ready, rest = await asyncio.get_event_loop().run_in_executor(
            None, lambda: ray_tpu.wait(refs, num_returns=num_returns,
                                       timeout=timeout))
        return {"ready": [by_ref[id(r)] for r in ready],
                "not_ready": [by_ref[id(r)] for r in rest]}

    def h_register_function(self, conn, func_id: bytes, payload: bytes):
        funcs = self._session(conn)["funcs"]
        if func_id not in funcs:
            funcs[func_id] = cloudpickle.loads(payload)
        return True

    def _decode_args(self, conn, args_payload: bytes):
        args, kwargs = cloudpickle.loads(args_payload)
        objects = self._session(conn)["objects"]

        def resolve(v):
            if isinstance(v, _ServerRefMarker):
                return objects[v.oid]
            return v
        return ([resolve(a) for a in args],
                {k: resolve(v) for k, v in kwargs.items()})

    async def h_submit_task(self, conn, func_id: bytes, args_payload: bytes,
                            opts: Dict):
        import asyncio

        import ray_tpu
        fn = self._session(conn)["funcs"][func_id]
        args, kwargs = self._decode_args(conn, args_payload)
        rf = ray_tpu.remote(fn)
        if opts:
            rf = rf.options(**opts)
        refs = await asyncio.get_event_loop().run_in_executor(
            None, lambda: rf.remote(*args, **kwargs))
        refs = refs if isinstance(refs, list) else [refs]
        return [self._track(conn, r) for r in refs]

    async def h_create_actor(self, conn, func_id: bytes, args_payload: bytes,
                             opts: Dict):
        import asyncio

        import ray_tpu
        cls = self._session(conn)["funcs"][func_id]
        args, kwargs = self._decode_args(conn, args_payload)
        ac = ray_tpu.remote(cls)
        if opts:
            ac = ac.options(**opts)
        handle = await asyncio.get_event_loop().run_in_executor(
            None, lambda: ac.remote(*args, **kwargs))
        actor_id = handle._actor_id
        st = self._session(conn)
        st["actors"][actor_id] = handle
        if (opts or {}).get("lifetime") == "detached":
            # detached actors outlive their creator BY CONTRACT — track
            # for calls but exclude from disconnect reaping
            st.setdefault("detached", set()).add(actor_id)
        return actor_id

    async def h_call_actor(self, conn, actor_id: str, method_name: str,
                           args_payload: bytes):
        import asyncio

        import ray_tpu
        handle = self._session(conn)["actors"][actor_id]
        args, kwargs = self._decode_args(conn, args_payload)
        ref = await asyncio.get_event_loop().run_in_executor(
            None, lambda: getattr(handle, method_name).remote(
                *args, **kwargs))
        return self._track(conn, ref)

    async def h_get_actor(self, conn, name: str, namespace: str = "default"):
        """Look up a (typically detached) named actor and attach its
        handle to THIS session — the path by which a reconnecting client
        regains access to actors that outlived its previous session
        (reference: ray.get_actor through the client proxy)."""
        import asyncio

        import ray_tpu
        handle = await asyncio.get_event_loop().run_in_executor(
            None, lambda: ray_tpu.get_actor(name, namespace))
        st = self._session(conn)
        st["actors"][handle._actor_id] = handle
        # looked-up actors are never reaped on disconnect: this session
        # did not create them
        st.setdefault("detached", set()).add(handle._actor_id)
        return handle._actor_id

    async def h_kill_actor(self, conn, actor_id: str):
        import asyncio

        import ray_tpu
        handle = self._session(conn)["actors"].pop(actor_id, None)
        if handle is not None:
            # blocking bridge must not run on this loop (it IS the
            # driver's loop) — executor thread instead
            await asyncio.get_event_loop().run_in_executor(
                None, ray_tpu.kill, handle)
        return True

    def h_free(self, conn, oids: List[bytes]):
        objects = self._session(conn)["objects"]
        for o in oids:
            objects.pop(o, None)
        return True

    async def h_cluster_resources(self, conn):
        import asyncio

        import ray_tpu
        return await asyncio.get_event_loop().run_in_executor(
            None, ray_tpu.cluster_resources)

    async def start(self) -> str:
        from ray_tpu._private import rpc
        handlers = {
            "put": self.h_put, "get": self.h_get, "wait": self.h_wait,
            "register_function": self.h_register_function,
            "submit_task": self.h_submit_task,
            "create_actor": self.h_create_actor,
            "call_actor": self.h_call_actor,
            "kill_actor": self.h_kill_actor,
            "get_actor": self.h_get_actor,
            "free": self.h_free,
            "cluster_resources": self.h_cluster_resources,
            "ping": lambda conn: "pong",
        }
        self.server = rpc.Server(handlers, name="client-proxy")
        self.server.on_disconnect = self._on_disconnect
        self.address = await self.server.listen_tcp("0.0.0.0", self.port)
        return self.address


def serve_proxy(port: int = 0) -> str:
    """Start a proxy server on the connected cluster; returns its address.
    Runs on the driver's existing event loop thread."""
    import asyncio

    import ray_tpu
    w = ray_tpu._get_worker()
    proxy = ClientProxyServer(port=port)
    return asyncio.run_coroutine_threadsafe(
        proxy.start(), w.core.loop).result(30)


# --------------------------------------------------------------- client side
class _ServerRefMarker:
    """Placeholder for a ClientObjectRef inside pickled task args."""

    def __init__(self, oid: bytes):
        self.oid = oid


class ClientObjectRef:
    __slots__ = ("id", "_ctx")

    def __init__(self, oid: bytes, ctx: "ClientContext"):
        self.id = oid
        self._ctx = ctx

    def __repr__(self):
        return f"ClientObjectRef({self.id.hex()[:16]})"

    def __reduce__(self):
        return (_ServerRefMarker, (self.id,))


class ClientRemoteFunction:
    def __init__(self, ctx: "ClientContext", fn, opts: Optional[Dict] = None):
        self._ctx = ctx
        self._fn = fn
        self._opts = opts or {}
        import hashlib
        self._func_id = hashlib.sha1(
            cloudpickle.dumps(fn)).digest()[:16]

    def options(self, **opts):
        return ClientRemoteFunction(self._ctx, self._fn,
                                    {**self._opts, **opts})

    def remote(self, *args, **kwargs):
        ctx = self._ctx
        ctx._ensure_function(self._func_id, self._fn)
        oids = ctx._call("submit_task", func_id=self._func_id,
                         args_payload=cloudpickle.dumps((args, kwargs)),
                         opts=self._opts)
        refs = [ClientObjectRef(o, ctx) for o in oids]
        return refs[0] if len(refs) == 1 else refs


class ClientActorMethod:
    def __init__(self, ctx, actor_id, name):
        self._ctx = ctx
        self._actor_id = actor_id
        self._name = name

    def remote(self, *args, **kwargs):
        oid = self._ctx._call(
            "call_actor", actor_id=self._actor_id, method_name=self._name,
            args_payload=cloudpickle.dumps((args, kwargs)))
        return ClientObjectRef(oid, self._ctx)


class ClientActorHandle:
    def __init__(self, ctx, actor_id: str):
        self._ctx = ctx
        self._actor_id = actor_id

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ClientActorMethod(self._ctx, self._actor_id, name)


class ClientActorClass:
    def __init__(self, ctx, cls, opts: Optional[Dict] = None):
        self._ctx = ctx
        self._cls = cls
        self._opts = opts or {}
        import hashlib
        self._func_id = hashlib.sha1(cloudpickle.dumps(cls)).digest()[:16]

    def options(self, **opts):
        return ClientActorClass(self._ctx, self._cls,
                                {**self._opts, **opts})

    def remote(self, *args, **kwargs):
        ctx = self._ctx
        ctx._ensure_function(self._func_id, self._cls)
        actor_id = ctx._call(
            "create_actor", func_id=self._func_id,
            args_payload=cloudpickle.dumps((args, kwargs)),
            opts=self._opts)
        return ClientActorHandle(ctx, actor_id)


class ClientContext:
    """One remote session; owns a background event loop + connection."""

    def __init__(self, address: str):
        import asyncio

        from ray_tpu._private import rpc
        self.address = address
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="client-loop", daemon=True)
        self._thread.start()
        self._conn = self._submit(rpc.connect(address, name="client",
                                              retries=10))
        self._shipped: set = set()

    def _submit(self, coro, timeout: float = 600):
        import asyncio
        return asyncio.run_coroutine_threadsafe(
            coro, self._loop).result(timeout)

    def _call(self, _method, **kw):
        return self._submit(self._conn.call(_method, **kw))

    def _ensure_function(self, func_id: bytes, fn):
        if func_id not in self._shipped:
            self._call("register_function", func_id=func_id,
                       payload=cloudpickle.dumps(fn))
            self._shipped.add(func_id)

    # public surface (mirrors ray_tpu.*)
    def remote(self, target=None, **opts):
        import inspect
        if target is None:
            return lambda t: (self.remote(t, **opts))
        if inspect.isclass(target):
            return ClientActorClass(self, target, opts or None)
        return ClientRemoteFunction(self, target, opts or None)

    def put(self, value) -> ClientObjectRef:
        oid = self._call("put", payload=cloudpickle.dumps(value))
        return ClientObjectRef(oid, self)

    def get(self, refs, timeout=None):
        single = isinstance(refs, ClientObjectRef)
        if single:
            refs = [refs]
        resp = self._call("get", oids=[r.id for r in refs],
                          timeout=timeout)
        if not resp["ok"]:
            raise cloudpickle.loads(resp["error"])
        vals = cloudpickle.loads(resp["payload"])
        return vals[0] if single else vals

    def wait(self, refs, num_returns=1, timeout=None):
        by_id = {r.id: r for r in refs}
        resp = self._call("wait", oids=[r.id for r in refs],
                          num_returns=num_returns, timeout=timeout)
        return ([by_id[o] for o in resp["ready"]],
                [by_id[o] for o in resp["not_ready"]])

    def kill(self, actor: ClientActorHandle):
        self._call("kill_actor", actor_id=actor._actor_id)

    def get_actor(self, name: str,
                  namespace: str = "default") -> ClientActorHandle:
        actor_id = self._call("get_actor", name=name, namespace=namespace)
        return ClientActorHandle(self, actor_id)

    def cluster_resources(self):
        return self._call("cluster_resources")

    def disconnect(self):
        global _client
        try:
            self._submit(self._conn.close(), timeout=5)
        except Exception:
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        if _client is self:
            _client = None


def connect(address: str) -> ClientContext:
    """Connect this process to a remote cluster through its client proxy.
    Accepts "host:port" or "ray_tpu://host:port"."""
    global _client
    if address.startswith("ray_tpu://"):
        address = address[len("ray_tpu://"):]
    ctx = ClientContext(address)
    _client = ctx
    return ctx
