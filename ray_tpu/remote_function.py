"""@ray_tpu.remote for functions (reference:
python/ray/remote_function.py:266 — options resolution and submission)."""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional


def _resources_from_options(opts: Dict[str, Any]) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        res["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus") is not None:
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus") is not None:      # accepted for API familiarity
        res["GPU"] = float(opts["num_gpus"])
    if opts.get("memory") is not None:
        res["memory"] = float(opts["memory"])
    if "CPU" not in res and "TPU" not in res and "GPU" not in res:
        res["CPU"] = 1.0
    if "TPU" in res:
        from ray_tpu._private.accelerators import TPUAcceleratorManager
        ok, reason = TPUAcceleratorManager.validate_resource_request_quantity(
            res["TPU"])
        if not ok:
            raise ValueError(f"invalid TPU request {res['TPU']}: {reason}")
    return res


def _scheduling_from_options(opts: Dict[str, Any]) -> Dict[str, Any]:
    strategy = opts.get("scheduling_strategy")
    sched: Dict[str, Any] = {}
    if strategy is None:
        return sched
    if isinstance(strategy, str):
        sched["strategy"] = strategy
        return sched
    # strategy objects from util.scheduling_strategies
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy,
        SpreadSchedulingStrategy)
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        sched["placement_group_id"] = strategy.placement_group.id
        sched["placement_group_bundle_index"] = strategy.placement_group_bundle_index
    elif isinstance(strategy, NodeAffinitySchedulingStrategy):
        sched["strategy"] = "NODE_AFFINITY"
        sched["node_id"] = strategy.node_id
        sched["soft"] = strategy.soft
    elif isinstance(strategy, SpreadSchedulingStrategy):
        sched["strategy"] = "SPREAD"
    return sched


class RemoteFunction:
    def __init__(self, function, options: Optional[Dict[str, Any]] = None):
        self._function = function
        self._options = options or {}
        try:
            functools.update_wrapper(self, function)
        except AttributeError:
            # callables without __name__/__doc__ (e.g. joblib wrappers)
            self.__name__ = type(function).__name__

    def remote(self, *args, **kwargs):
        from ray_tpu.client import current_client
        cc = current_client()
        if cc is not None:   # client-mode hook (reference: client_mode_hook)
            return cc.remote(self._function, **self._options).remote(
                *args, **kwargs)
        from ray_tpu import _get_worker
        w = _get_worker()
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        if num_returns == "streaming":
            # -> ObjectRefGenerator (reference: _raylet.pyx:281)
            return w.submit_streaming(
                self._function, args, kwargs,
                resources=_resources_from_options(opts),
                scheduling=_scheduling_from_options(opts),
                name=opts.get("name") or getattr(
                    self._function, "__name__",
                    type(self._function).__name__),
                runtime_env=opts.get("runtime_env"),
                backpressure=opts.get("_generator_backpressure"))
        refs = w.submit(
            self._function, args, kwargs,
            num_returns=num_returns,
            resources=_resources_from_options(opts),
            max_retries=opts.get("max_retries"),
            scheduling=_scheduling_from_options(opts),
            name=opts.get("name") or getattr(self._function, "__name__",
                                 type(self._function).__name__),
            runtime_env=opts.get("runtime_env"))
        return refs[0] if num_returns == 1 else refs

    def options(self, **new_options) -> "RemoteFunction":
        merged = {**self._options, **new_options}
        return RemoteFunction(self._function, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._function.__name__}' cannot be called "
            "directly; use .remote().")
