"""Mixture-of-Experts feed-forward with expert parallelism.

GShard/Switch-style dense dispatch, designed for the MXU and XLA SPMD:
routing builds one-hot dispatch/combine tensors and the token→expert
shuffle is an einsum — under an `expert`-sharded mesh axis XLA lowers it
to an all-to-all over ICI, with expert FFN weights stacked as one
[E, d, ff] tensor (logical axes ("experts", "embed", "mlp")) so every
expert's matmul runs at full tile size. No counterpart in the reference
(it orchestrates torch processes and ships no MoE, SURVEY §2.4: EP listed
as "absent — must be built natively").

Routing (per batch row as the dispatch group):
- softmax router in fp32, top-k experts per token, gates renormalized;
- per-expert capacity C = ceil(capacity_factor * L * k / E); tokens over
  capacity are dropped (standard Switch behavior, keeps shapes static);
- aux load-balancing loss (Switch eq. 4): E * Σ_e frac_tokens_e · mean_prob_e.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ray_tpu.models.transformer import _p
from ray_tpu.parallel.sharding import constrain


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense MLP block (gate/up/down SwiGLU),
    with `cfg.n_experts` experts and top-`cfg.expert_top_k` routing."""

    cfg: Any

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        B, L, D = x.shape
        E, K = cfg.n_experts, cfg.expert_top_k
        C = max(1, math.ceil(cfg.capacity_factor * L * K / E))

        router = self.param(
            "router", _p(nn.initializers.lecun_normal(), "embed", "experts"),
            (D, E), jnp.float32)
        probs = jax.nn.softmax(
            x.astype(jnp.float32) @ router, axis=-1)           # [B,L,E]

        gate_vals, gate_idx = jax.lax.top_k(probs, K)          # [B,L,K]
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)

        # expert-choice position: for the j-th routing slot, a token's slot
        # in expert e's buffer is the number of earlier (token, slot) picks
        # of e, counting slots in priority order (slot 0 of every token
        # first — standard top-k dispatch priority)
        sel = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)   # [B,L,K,E]
        flat = sel.transpose(0, 2, 1, 3).reshape(B, K * L, E)  # slot-major
        pos_flat = jnp.cumsum(flat, axis=1) - flat             # [B,K*L,E]
        pos = pos_flat.reshape(B, K, L, E).transpose(0, 2, 1, 3)  # [B,L,K,E]
        pos = (pos * sel).sum(-1)                              # [B,L,K]
        keep = (pos < C).astype(gate_vals.dtype)

        # combine[b,l,e,c]: gate weight of token (b,l) at slot c of expert e
        onehot_c = jax.nn.one_hot(pos.astype(jnp.int32), C,
                                  dtype=jnp.float32)           # [B,L,K,C]
        combine = jnp.einsum("blk,blke,blkc->blec",
                             gate_vals * keep, sel, onehot_c)
        dispatch = (combine > 0).astype(x.dtype)

        # token→expert shuffle; sharding the e dim over the expert axis
        # turns this einsum into an all-to-all under SPMD
        expert_in = jnp.einsum("blec,bld->ebcd", dispatch, x)
        expert_in = constrain(expert_in, ("experts", None, None, "embed"))

        dense = lambda feats, axes, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
            kernel_init=_p(nn.initializers.lecun_normal(), *axes))
        # one stacked DenseGeneral per projection: E batched matmuls
        w_gate = self.param(
            "gate", _p(nn.initializers.lecun_normal(),
                       "experts", "embed", "mlp"),
            (E, D, cfg.d_ff), cfg.param_dtype)
        w_up = self.param(
            "up", _p(nn.initializers.lecun_normal(),
                     "experts", "embed", "mlp"),
            (E, D, cfg.d_ff), cfg.param_dtype)
        w_down = self.param(
            "down", _p(nn.initializers.lecun_normal(),
                       "experts", "mlp", "embed"),
            (E, cfg.d_ff, D), cfg.param_dtype)
        h = jnp.einsum("ebcd,edf->ebcf", expert_in,
                       w_gate.astype(cfg.dtype))
        u = jnp.einsum("ebcd,edf->ebcf", expert_in, w_up.astype(cfg.dtype))
        y = nn.silu(h) * u
        expert_out = jnp.einsum("ebcf,efd->ebcd", y,
                                w_down.astype(cfg.dtype))
        expert_out = constrain(expert_out,
                               ("experts", None, None, "embed"))

        out = jnp.einsum("blec,ebcd->bld",
                         combine.astype(x.dtype), expert_out)

        # Switch load-balance loss: encourages uniform routing
        frac_tokens = sel.sum((1, 2)) / (L * K)                # [B,E]
        mean_probs = probs.mean(1)                             # [B,E]
        aux = E * (frac_tokens * mean_probs).sum(-1).mean()
        return out, aux
