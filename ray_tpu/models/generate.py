"""Autoregressive generation over the sharded KV-cache decode path (the
serving counterpart of parallel/train_step.make_train_fns; reference
framework ships no model code — this is the TPU-native inference engine
its Serve story would orchestrate).

Shape: ONE jitted function runs prefill (full-prompt forward seeding the
cache) and then `lax.scan`s single-token decode steps — token selection
(greedy or temperature sampling) happens inside the scan, so the whole
generation is a single XLA program with no host round trips. Params
shard per the megatron rule table; the KV cache shards batch over the
data axes and KV heads over `tensor`, so decode attention reads are
local to each tensor shard and the only cross-device traffic is the
activation all-reduce the matmul shardings already imply.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.sampling import sample_logits
from ray_tpu.models.transformer import init_cache
from ray_tpu.parallel import sharding as sharding_lib
from ray_tpu.parallel.mesh import use_mesh
from ray_tpu.parallel.train_step import (_prune_indivisible,
                                         logical_pspec_to_mesh,
                                         state_shardings)


def make_generate_fn(model: nn.Module, mesh: Mesh, rules=None,
                     batch: int = 8, prompt_len: int = 128,
                     max_new_tokens: int = 128,
                     temperature: float = 0.0,
                     ) -> Tuple[Callable, Callable, Any]:
    """Returns (init_fn(rng) -> params, generate_fn(params, tokens, rng)
    -> [B, max_new_tokens] token ids, param_sharding_tree).

    temperature 0.0 = greedy argmax; >0 = softmax sampling inside the
    decode scan. max_len = prompt_len + max_new_tokens bounds the KV
    cache (static shapes: XLA compiles one prefill + one decode body)."""
    cfg = model.cfg
    rules = rules or sharding_lib.DEFAULT_RULES
    max_len = prompt_len + max_new_tokens
    tokens0 = jnp.zeros((batch, prompt_len), jnp.int32)

    def init_params(rng):
        return model.init(rng, tokens0)["params"]

    abstract = jax.eval_shape(init_params, jax.random.PRNGKey(0))
    param_sh = state_shardings(abstract, mesh, rules)
    init_fn = jax.jit(init_params, out_shardings=param_sh)

    # cache [n_layers, B, M, Hkv, D]: batch over data axes, KV heads
    # over tensor (same split the k/v projection weights carry)
    cache_spec = _prune_indivisible(
        logical_pspec_to_mesh(P(None, "batch", None, "kv_heads", None),
                              rules),
        (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim),
        mesh)
    cache_sh = {"k": NamedSharding(mesh, cache_spec),
                "v": NamedSharding(mesh, cache_spec),
                "idx": NamedSharding(mesh, P())}

    def _pick(logits, rng):
        # shared with the inference engine (models/sampling.py); static
        # temperature=0 compiles to the same bare argmax as before
        return sample_logits(logits, rng, temperature=temperature)

    def generate(params, tokens, rng):
        cache = init_cache(cfg, batch, max_len)
        cache = jax.lax.with_sharding_constraint(cache, cache_sh)
        # prefill: one full-prompt forward seeds every layer's cache
        logits, cache = model.apply({"params": params}, tokens,
                                    cache=cache)
        rng, k0 = jax.random.split(rng)
        first = _pick(logits[:, -1, :], k0).astype(jnp.int32)

        def step(carry, _):
            cache, tok, rng = carry
            logits, cache = model.apply({"params": params}, tok[:, None],
                                        cache=cache)
            rng, k = jax.random.split(rng)
            nxt = _pick(logits[:, -1, :], k).astype(jnp.int32)
            cache = jax.lax.with_sharding_constraint(cache, cache_sh)
            return (cache, nxt, rng), nxt

        (_, _, _), rest = jax.lax.scan(
            step, (cache, first, rng), None, length=max_new_tokens - 1)
        return jnp.concatenate([first[:, None], rest.T], axis=1)

    batch_sh = NamedSharding(
        mesh, _prune_indivisible(
            logical_pspec_to_mesh(P("batch", None), rules),
            (batch, prompt_len), mesh))
    jit_gen = jax.jit(generate,
                      in_shardings=(param_sh, batch_sh, None),
                      out_shardings=NamedSharding(mesh, P()))

    def generate_with_mesh(params, tokens, rng):
        with use_mesh(mesh):
            return jit_gen(params, tokens, rng)

    def init_with_mesh(rng):
        with use_mesh(mesh):
            return init_fn(rng)

    return init_with_mesh, generate_with_mesh, param_sh
