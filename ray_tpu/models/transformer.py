"""Flagship model: Llama-style decoder-only transformer (flax.linen).

TPU-first design choices:
- bfloat16 activations, fp32 params/optimizer (master-weight recipe);
  matmuls hit the MXU at full tile size.
- `lax.scan` over layers (one compiled layer body, fast compiles) with
  `jax.checkpoint` rematerialization per layer.
- Every parameter is annotated with *logical* axes via flax partitioning
  metadata; ray_tpu.parallel.sharding maps them to the dp/fsdp/tp/sp mesh.
- Attention dispatches to the Pallas flash kernel on one device or to
  ring attention over the `seq` mesh axis when sequence parallelism is on.

The reference framework ships no model implementations (it orchestrates
torch code); this model exists as the framework's flagship train/serve
workload and benchmark subject.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from ray_tpu.ops.dispatch import attention as attention_dispatch


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    tie_embeddings: bool = False
    remat: bool = True
    # checkpoint policy: "nothing" (recompute all), "dots" (save matmul
    # outputs — usually fastest on TPU: backward reuses MXU results and
    # recomputes only cheap elementwise), "dots_no_batch"
    remat_policy: str = "dots"
    scan_layers: bool = True
    # keep logits in bf16 and let the loss upcast inside its reductions —
    # avoids materializing a [B,L,vocab] fp32 buffer (HBM traffic)
    logits_fp32: bool = False
    # "auto": flash kernel on 1 seq shard, ring attention when seq axis > 1
    attention_impl: str = "auto"
    seq_axis: str = "seq"
    # Mixture-of-Experts: n_experts=0 means dense MLP in every block;
    # n_experts>0 replaces every MLP with a top-k-routed expert layer
    # (models/moe.py) sharded over the mesh's `expert` axis
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


_PARTITION_OFF = __import__("threading").local()


def _p(init, *logical_axes):
    """Attach logical-axis metadata to a param initializer (suppressed
    inside `unpartitioned_params`, e.g. for shard_map pipeline stages
    where logical names must not reach the physical mesh)."""
    if getattr(_PARTITION_OFF, "off", False):
        return init
    return nn.with_partitioning(init, logical_axes)


class unpartitioned_params:
    """Context: create/apply model params without flax partitioning boxes.
    Used by pipeline-parallel stages (parallel/pipeline.py), whose params
    are sharded explicitly over the `stage` axis by shard_map in_specs."""

    def __enter__(self):
        _PARTITION_OFF.off = True
        return self

    def __exit__(self, *exc):
        _PARTITION_OFF.off = False


class RMSNorm(nn.Module):
    eps: float
    dtype: Any

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", _p(nn.initializers.ones, "embed"),
                           (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True)
                                + self.eps)
        return (y * scale).astype(self.dtype)


def rope(x, positions, theta: float):
    """Rotary embeddings. x[B,L,H,D], positions[B,L]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,L,D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)
    return out.astype(x.dtype)


def _cached_attention(q, k_cache, v_cache, q_pos0):
    """Decode-path attention against a padded KV cache.

    q [B,S,H,D] are the S newest positions (absolute start q_pos0);
    caches [B,M,Hkv,D] already contain the new keys/values written at
    [q_pos0, q_pos0+S). q_pos0 is a scalar (shared start, the
    make_generate_fn shape) or a [B] vector (per-slot starts — the
    continuous-batching slot pool, where every sequence sits at its own
    length). Mask: query i attends cache slots j <= q_pos0+i (causal
    over absolute positions; padded tail masked out). Plain dot-product
    in fp32 — decode is bandwidth-bound on the cache read, not
    MXU-bound, so there is nothing for the flash kernel to win here."""
    B, S, H, D = q.shape
    M, Hkv = k_cache.shape[1], k_cache.shape[2]
    # GQA via grouped einsum against the UNEXPANDED cache: a repeat of
    # k/v would multiply exactly the HBM read this path is bound by
    G = H // Hkv
    qg = q.reshape(B, S, Hkv, G, D).astype(jnp.float32)
    scores = jnp.einsum("bshgd,bmhd->bhgsm", qg,
                        k_cache.astype(jnp.float32)) / jnp.sqrt(float(D))
    # [1,S] (scalar start) or [B,S] (per-slot starts)
    qpos = jnp.reshape(q_pos0, (-1, 1)) + jnp.arange(S)[None, :]
    mask = jnp.arange(M)[None, None, :] <= qpos[:, :, None]  # [B|1,S,M]
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgsm,bmhd->bshgd", probs,
                     v_cache.astype(jnp.float32))
    return out.reshape(B, S, H, D).astype(q.dtype)


def _cache_write(cache, new, idx):
    """Write `new` [B,L,Hkv,D] into `cache` [B,M,Hkv,D] at position
    `idx`: a scalar (all rows share one write offset) or a [B] vector
    (per-slot offsets — each row lands at its own length). XLA clamps
    out-of-range starts, so a full/free slot writes at M-L harmlessly."""
    new = new.astype(cache.dtype)
    if jnp.ndim(idx) == 0:
        return jax.lax.dynamic_update_slice(cache, new, (0, idx, 0, 0))
    return jax.vmap(
        lambda c, u, i: jax.lax.dynamic_update_slice(c, u, (i, 0, 0))
    )(cache, new, idx)


class Attention(nn.Module):
    cfg: TransformerConfig
    # static: route L>1 cache writes through _cached_attention (prefill
    # CONTINUES an occupied cache — chunked prefill) instead of assuming
    # an empty cache and using the fused kernel
    chunked: bool = False

    @nn.compact
    def __call__(self, x, positions, cache=None):
        """cache=None: training/prefill forward (flash/ring dispatch),
        returns out. cache=(k_cache, v_cache, idx): serving decode —
        writes this call's K/V at [idx, idx+L) (idx scalar or per-slot
        [B] vector), attends against the cache, returns
        (out, (k_cache', v_cache'))."""
        cfg = self.cfg
        B, L, E = x.shape
        H, Hkv, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        dense = lambda feats, axes, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
            kernel_init=_p(nn.initializers.lecun_normal(), *axes))
        q = dense((H, D), ("embed", "heads", "head_dim"), "q")(x)
        k = dense((Hkv, D), ("embed", "kv_heads", "head_dim"), "k")(x)
        v = dense((Hkv, D), ("embed", "kv_heads", "head_dim"), "v")(x)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        proj = nn.DenseGeneral(
            E, axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="o",
            kernel_init=_p(nn.initializers.lecun_normal(),
                           "heads", "head_dim", "embed"))
        if cache is None:
            out = attention_dispatch(q, k, v, causal=True,
                                     impl=cfg.attention_impl)
            return proj(out)
        k_cache, v_cache, idx = cache
        k_cache = _cache_write(k_cache, k, idx)
        v_cache = _cache_write(v_cache, v, idx)
        if L > 1 and not self.chunked:
            # one-shot prefill (L is static): the block attends only
            # within itself, so the fused flash/ring kernel computes it
            # — the cache is just written, never read. This assumes
            # prefill starts from an EMPTY cache (idx==0, the
            # make_generate_fn contract); chunked prefill (idx>0) sets
            # `chunked` and takes the cached path below, which attends
            # the earlier chunks at the correct causal offset.
            out = attention_dispatch(q, k, v, causal=True,
                                     impl=cfg.attention_impl)
        else:
            out = _cached_attention(q, k_cache, v_cache, idx)
        return proj(out), (k_cache, v_cache)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, axes, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
            kernel_init=_p(nn.initializers.lecun_normal(), *axes))
        gate = dense(cfg.d_ff, ("embed", "mlp"), "gate")(x)
        up = dense(cfg.d_ff, ("embed", "mlp"), "up")(x)
        y = nn.silu(gate) * up
        return dense(cfg.d_model, ("mlp", "embed"), "down")(y)


class Block(nn.Module):
    cfg: TransformerConfig
    chunked: bool = False

    @nn.compact
    def __call__(self, x, positions, cache=None):
        cfg = self.cfg
        att = Attention(cfg, self.chunked, name="attn")(
            RMSNorm(cfg.norm_eps, cfg.dtype, name="attn_norm")(x),
            positions, cache)
        new_cache = None
        if cache is not None:
            att, new_cache = att
        h = x + att
        normed = RMSNorm(cfg.norm_eps, cfg.dtype, name="mlp_norm")(h)
        if cfg.n_experts > 0:
            from ray_tpu.models.moe import MoEMLP
            y, aux = MoEMLP(cfg, name="moe")(normed)
        else:
            y, aux = MLP(cfg, name="mlp")(normed), jnp.zeros((), jnp.float32)
        if cache is not None:
            return h + y, aux, new_cache
        return h + y, aux


class ScanBlock(nn.Module):
    """Block with a scan-compatible (carry, ys) signature; ys carries the
    per-layer MoE aux loss. The carry is PINNED to the canonical
    activation sharding (batch over dp axes, seq over sp, d_model
    replicated) on entry and exit: without the pin, GSPMD picks its own
    layout for the while-loop carry in the backward pass and bridges to
    it with an involuntary full rematerialization (a per-step all-gather
    — round-4 verdict weak #5)."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        from ray_tpu.parallel.sharding import constrain
        x = constrain(x, ("batch", "seq", None))
        out, aux = Block(self.cfg, name="block")(x, positions)
        out = constrain(out, ("batch", "seq", None))
        return out, aux


class DecodeScanBlock(nn.Module):
    """Scan body for the serving decode path: the layer's KV cache
    rides as a scanned input (axis 0 = layers) and the updated cache
    comes back in the ys. Param names mirror ScanBlock ('block' under
    the scan) so the SAME trained/stacked params apply."""
    cfg: TransformerConfig
    chunked: bool = False

    @nn.compact
    def __call__(self, carry, cache_kv):
        x, positions, idx = carry
        out, _aux, new_cache = Block(self.cfg, self.chunked, name="block")(
            x, positions, (cache_kv[0], cache_kv[1], idx))
        return (out, positions, idx), new_cache


def init_cache(cfg: TransformerConfig, batch: int, max_len: int,
               dtype=None):
    """Fresh KV cache pytree: {'k','v': [n_layers,B,max_len,Hkv,D],
    'idx': next write position (scalar int32)}."""
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "idx": jnp.zeros((), jnp.int32)}


class TransformerLM(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions=None, return_hidden=False,
                 cache=None, chunked_prefill=False):
        """return_hidden=True skips the unembed projection and returns the
        final-norm hidden states [B,L,d] — callers (train_step's chunked
        cross-entropy) then compute logits a block at a time so the
        [B,L,vocab] buffer never exists in HBM.

        chunked_prefill=True (static; needs cache): this L>1 forward
        CONTINUES a partially-filled cache — attention runs against the
        cache with the causal offset cache["idx"] instead of assuming
        idx==0 (the inference engine's budgeted prompt chunks).
        cache["idx"] may be a scalar or a per-row [B] vector (slot pool:
        every row decodes at its own length)."""
        cfg = self.cfg
        B, L = tokens.shape
        if positions is None:
            if cache is not None:
                # decode: tokens continue at the cache's write position
                # (scalar idx, or [B] per-slot write positions)
                positions = jnp.broadcast_to(
                    jnp.reshape(cache["idx"], (-1, 1))
                    + jnp.arange(L)[None, :], (B, L))
            else:
                positions = jnp.broadcast_to(jnp.arange(L)[None, :],
                                             (B, L))
        embed = self.param(
            "embed",
            _p(nn.initializers.normal(0.02), "vocab", "embed_lookup"),
            (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        x = embed.astype(cfg.dtype)[tokens]
        # canonical activation layout from the very first op: the embed
        # table's own layout (vocab@tensor, d@fsdp) must not leak into x
        # — fsdp is already spent on the batch dim, and GSPMD bridges the
        # conflict with an involuntary full rematerialization
        from ray_tpu.parallel.sharding import constrain
        x = constrain(x, ("batch", "seq", None))
        if cache is not None:
            return self._decode(x, positions, cache, embed, return_hidden,
                                chunked_prefill)

        # (training/prefill path continues below)

        policies = {
            "nothing": jax.checkpoint_policies.nothing_saveable,
            "dots": jax.checkpoint_policies.checkpoint_dots,
            "dots_no_batch":
                jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        }
        if cfg.remat and cfg.remat_policy not in policies:
            raise ValueError(
                f"remat_policy={cfg.remat_policy!r}; expected one of "
                f"{sorted(policies)}")
        remat_policy = policies.get(cfg.remat_policy)
        if cfg.scan_layers:
            scan_target = ScanBlock
            if cfg.remat:
                scan_target = nn.remat(
                    ScanBlock, prevent_cse=False, policy=remat_policy)
            stack = nn.scan(
                scan_target,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, name="layers")
            x, aux_per_layer = stack(x, positions)
            aux_total = jnp.sum(aux_per_layer)
        else:
            block = Block
            if cfg.remat:
                block = nn.remat(
                    Block, prevent_cse=False, policy=remat_policy)
            aux_total = jnp.zeros((), jnp.float32)
            for i in range(cfg.n_layers):
                x, aux_i = block(cfg, name=f"layer_{i}")(x, positions)
                aux_total = aux_total + aux_i
        if cfg.n_experts > 0:
            # surfaced to the train step via mutable=["losses"]; a no-op
            # for callers that apply without that collection
            self.sow("losses", "moe_aux", aux_total,
                     reduce_fn=lambda a, b: a + b,
                     init_fn=lambda: jnp.zeros((), jnp.float32))
        x = RMSNorm(cfg.norm_eps, cfg.dtype, name="final_norm")(x)
        x = constrain(x, ("batch", "seq", None))
        unembed = None if cfg.tie_embeddings else self._unembed_param()
        if return_hidden:
            return x
        return self._logits(x, embed, unembed)

    def _unembed_param(self):
        cfg = self.cfg
        return self.param(
            "unembed",
            _p(nn.initializers.normal(0.02), "embed_lookup", "vocab"),
            (cfg.d_model, cfg.vocab_size), cfg.param_dtype)

    def _logits(self, x, embed, unembed):
        """Shared output head (training/prefill AND decode): final-norm
        hidden -> vocab logits, honoring tie_embeddings/logits_fp32."""
        cfg = self.cfg
        if cfg.tie_embeddings:
            logits = jnp.einsum("bld,vd->blv", x, embed.astype(cfg.dtype))
        else:
            logits = jnp.einsum("bld,dv->blv", x,
                                unembed.astype(cfg.dtype))
        return logits.astype(jnp.float32) if cfg.logits_fp32 else logits

    def _decode(self, x, positions, cache, embed, return_hidden,
                chunked_prefill=False):
        """Serving decode forward: applies every layer against the KV
        cache and returns (logits|hidden, new_cache). Shares the
        training param tree — the decode scan mirrors ScanBlock's
        naming ('layers'/'block')."""
        cfg = self.cfg
        L = x.shape[1]
        idx = cache["idx"]
        if cfg.scan_layers:
            stack = nn.scan(
                DecodeScanBlock,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=0,
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, chunked_prefill, name="layers")
            (x, _, _), (k_new, v_new) = stack((x, positions, idx),
                                              (cache["k"], cache["v"]))
        else:
            ks, vs = [], []
            for i in range(cfg.n_layers):
                x, _aux, (k_i, v_i) = Block(
                    cfg, chunked_prefill, name=f"layer_{i}")(
                    x, positions, (cache["k"][i], cache["v"][i], idx))
                ks.append(k_i)
                vs.append(v_i)
            k_new = jnp.stack(ks)
            v_new = jnp.stack(vs)
        new_cache = {"k": k_new, "v": v_new, "idx": idx + L}
        x = RMSNorm(cfg.norm_eps, cfg.dtype, name="final_norm")(x)
        if return_hidden:
            return x, new_cache
        unembed = None if cfg.tie_embeddings else self._unembed_param()
        return self._logits(x, embed, unembed), new_cache


def count_params(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))
