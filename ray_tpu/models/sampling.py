"""Token selection shared by every generation path: make_generate_fn's
in-scan `_pick` and the continuous-batching engine's prefill/decode
steps (ray_tpu/inference/engine.py) call the same functions, so greedy
decoding is bit-identical across them by construction.

Two entry points for the two shapes of temperature:

- ``sample_logits``: temperature is a *static* Python float (compiled
  into the program). temperature<=0 short-circuits to a pure
  ``jnp.argmax`` — no masking, no division — which is exactly the op the
  pre-refactor ``_pick`` compiled, keeping temperature=0 outputs
  bit-identical.
- ``sample_logits_dynamic``: temperature is a *traced* per-row [B]
  vector (the slot pool mixes requests with different temperatures in
  one decode step). Greedy rows (temperature<=0) select the same argmax
  as the static path via ``jnp.where``.

top-k / top-p (nucleus) filtering are static knobs applied before
sampling; both default off (top_k=0, top_p=1.0).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _apply_top_k(logits, top_k: int):
    """Keep the top_k highest logits per row; mask the rest."""
    if not top_k or top_k >= logits.shape[-1]:
        return logits
    kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
    return jnp.where(logits >= kth, logits, _NEG_INF)


def _apply_top_p(logits, top_p: float):
    """Nucleus filtering: keep the smallest prefix of the
    probability-sorted vocab whose cumulative mass reaches top_p (the
    first token is always kept)."""
    if top_p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # exclusive cumsum: a token is kept while the mass BEFORE it < top_p
    keep_sorted = (cum - probs) < top_p
    # threshold = smallest kept logit; everything below it is masked
    thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where(logits >= thresh, logits, _NEG_INF)


def _filtered(logits, top_k: int, top_p: float):
    logits = logits.astype(jnp.float32)
    logits = _apply_top_k(logits, top_k)
    return _apply_top_p(logits, top_p)


def sample_logits(logits, rng, temperature: float = 0.0, top_k: int = 0,
                  top_p: float = 1.0):
    """logits [..., V] -> token ids [...]. Static temperature:
    temperature<=0 is greedy argmax (bit-identical to the historical
    `_pick`); otherwise softmax sampling at the given temperature after
    static top-k/top-p filtering."""
    if not temperature or temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(
        rng, _filtered(logits, top_k, top_p) / temperature, axis=-1)


def sample_logits_dynamic(logits, rng, temperature, top_k: int = 0,
                          top_p: float = 1.0):
    """logits [B, V], temperature [B] (traced) -> token ids [B]. Rows
    with temperature<=0 take the greedy argmax; the rest sample at their
    own temperature. One program serves every mix of per-request
    sampling settings, so the decode step never recompiles."""
    greedy = jnp.argmax(logits, axis=-1)
    temp = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    sampled = jax.random.categorical(
        rng, _filtered(logits, top_k, top_p) / temp[:, None], axis=-1)
    return jnp.where(jnp.asarray(temperature) > 0.0, sampled, greedy)
