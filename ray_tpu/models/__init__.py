from ray_tpu.models.transformer import (TransformerConfig, TransformerLM,
                                        count_params, init_cache)

MODEL_REGISTRY = {
    "llama-debug": TransformerConfig(
        vocab_size=1024, d_model=256, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=512, max_seq_len=512),
    "llama-125m": TransformerConfig(
        vocab_size=32000, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12,
        d_ff=2048, max_seq_len=2048),
    "llama-350m": TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=24, n_heads=16,
        n_kv_heads=16, d_ff=2816, max_seq_len=2048),
    "llama-1b": TransformerConfig(
        vocab_size=32000, d_model=2048, n_layers=16, n_heads=32, n_kv_heads=8,
        d_ff=5632, max_seq_len=4096),
    "llama-7b": TransformerConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=32,
        d_ff=11008, max_seq_len=4096),
    # TPU-native flagship geometry: 128-lane heads (head_dim=128) fill the
    # MXU's 128-wide systolic tiles; the classic hd=64 llama layout leaves
    # half the array idle on QK^T/PV. Measured +10pts MFU on v5e
    # (reports/mfu_ablation.jsonl: 42.8% vs 32.1% for the same 350m FLOPs)
    "tpu-125m": TransformerConfig(
        vocab_size=32000, d_model=768, n_layers=12, n_heads=6, n_kv_heads=6,
        d_ff=2048, max_seq_len=2048),
    "tpu-350m": TransformerConfig(
        vocab_size=32000, d_model=1024, n_layers=24, n_heads=8, n_kv_heads=8,
        d_ff=2816, max_seq_len=2048),
    "tpu-1b": TransformerConfig(
        vocab_size=32000, d_model=2048, n_layers=16, n_heads=16,
        n_kv_heads=16, d_ff=5632, max_seq_len=4096),
    # Larger rungs keep hd=128 and add GQA (4:1) — KV projections are
    # bandwidth, not FLOPs, and 8 KV heads shard cleanly over an 8-way
    # tensor axis. tpu-3b is the largest single-v5e-chip (16 GB) rung:
    # it needs bf16 params + adafactor + chunked cross-entropy to fit
    # (see reports/MFU_ABLATION.md OOM table); tpu-7b (llama-7b-class
    # FLOPs, MXU-aligned d_ff) is the multi-chip FSDP flagship.
    "tpu-3b": TransformerConfig(
        vocab_size=32000, d_model=3072, n_layers=24, n_heads=24,
        n_kv_heads=8, d_ff=8192, max_seq_len=4096),
    "tpu-7b": TransformerConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=11264, max_seq_len=4096),
    # MoE family (models/moe.py): expert-parallel over the mesh `expert` axis
    "moe-debug": TransformerConfig(
        vocab_size=1024, d_model=256, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=512, max_seq_len=512, n_experts=4, expert_top_k=2),
    "mixtral-8x7b": TransformerConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq_len=4096, n_experts=8, expert_top_k=2),
}

from ray_tpu.models.generate import make_generate_fn
from ray_tpu.models.sampling import sample_logits, sample_logits_dynamic

__all__ = ["TransformerConfig", "TransformerLM", "MODEL_REGISTRY",
           "count_params", "init_cache", "make_generate_fn",
           "sample_logits", "sample_logits_dynamic"]
