"""Job submission: run driver scripts on the cluster (reference:
python/ray/dashboard/modules/job/ — JobManager :59, JobSupervisor actor
:53 runs the entrypoint as a subprocess and streams logs; SDK sdk.py).
The manager is a detached named actor; the REST surface lives in
ray_tpu.dashboard."""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from typing import Dict, List, Optional

JOB_MANAGER_NAME = "_JOB_MANAGER"

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


class JobSupervisor:
    """One actor per submitted job: runs the entrypoint subprocess."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[Dict], gcs_address: str):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.status = PENDING
        self.logs: List[str] = []
        self.returncode: Optional[int] = None
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = gcs_address
        env.update((runtime_env or {}).get("env_vars", {}))
        cwd = (runtime_env or {}).get("working_dir")
        self._proc = subprocess.Popen(
            entrypoint, shell=True, env=env, cwd=cwd,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        self.status = RUNNING
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self):
        for line in self._proc.stdout:
            self.logs.append(line.rstrip("\n"))
        self.returncode = self._proc.wait()
        if self.status != STOPPED:
            self.status = SUCCEEDED if self.returncode == 0 else FAILED

    def get_status(self) -> Dict:
        return {"job_id": self.job_id, "status": self.status,
                "returncode": self.returncode,
                "entrypoint": self.entrypoint}

    def get_logs(self, offset: int = 0) -> List[str]:
        return self.logs[offset:]

    def stop(self):
        self.status = STOPPED
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self._proc.kill()
        return True


class JobManager:
    """Named detached actor tracking all submitted jobs."""

    def __init__(self):
        self.jobs: Dict[str, Dict] = {}   # job_id -> {supervisor, meta}

    def submit(self, entrypoint: str, runtime_env: Optional[Dict] = None,
               submission_id: Optional[str] = None,
               metadata: Optional[Dict] = None) -> str:
        import ray_tpu
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        sup_cls = ray_tpu.remote(JobSupervisor)
        sup = sup_cls.options(max_concurrency=4, num_cpus=0.1).remote(
            job_id, entrypoint, runtime_env, ray_tpu.get_gcs_address())
        self.jobs[job_id] = {"supervisor": sup,
                             "metadata": metadata or {},
                             "submitted_at": time.time()}
        return job_id

    def status(self, job_id: str) -> Optional[Dict]:
        import ray_tpu
        info = self.jobs.get(job_id)
        if info is None:
            return None
        try:
            st = ray_tpu.get(info["supervisor"].get_status.remote(),
                             timeout=30)
        except Exception as e:
            st = {"job_id": job_id, "status": FAILED,
                  "error": f"supervisor lost: {e}"}
        return {**st, **info["metadata"],
                "submitted_at": info["submitted_at"]}

    def logs(self, job_id: str, offset: int = 0) -> List[str]:
        import ray_tpu
        info = self.jobs.get(job_id)
        if info is None:
            return []
        try:
            return ray_tpu.get(info["supervisor"].get_logs.remote(offset),
                               timeout=30)
        except Exception:
            return []

    def stop(self, job_id: str) -> bool:
        import ray_tpu
        info = self.jobs.get(job_id)
        if info is None:
            return False
        return ray_tpu.get(info["supervisor"].stop.remote(), timeout=30)

    def list(self) -> List[Dict]:
        return [self.status(j) for j in list(self.jobs)]


def _get_manager():
    import ray_tpu
    try:
        return ray_tpu.get_actor(JOB_MANAGER_NAME, namespace="_internal")
    except ValueError:
        cls = ray_tpu.remote(JobManager)
        return cls.options(name=JOB_MANAGER_NAME, namespace="_internal",
                           lifetime="detached", max_concurrency=4,
                           num_cpus=0.1).remote()


class JobSubmissionClient:
    """Reference: python/ray/dashboard/modules/job/sdk.py — here speaking
    actor RPC instead of REST (the dashboard exposes the same over HTTP)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        self._mgr = _get_manager()

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict] = None) -> str:
        import ray_tpu
        return ray_tpu.get(self._mgr.submit.remote(
            entrypoint, runtime_env, submission_id, metadata), timeout=60)

    def get_job_status(self, job_id: str) -> str:
        import ray_tpu
        st = ray_tpu.get(self._mgr.status.remote(job_id), timeout=30)
        return st["status"] if st else "NOT_FOUND"

    def get_job_info(self, job_id: str) -> Optional[Dict]:
        import ray_tpu
        return ray_tpu.get(self._mgr.status.remote(job_id), timeout=30)

    def get_job_logs(self, job_id: str) -> str:
        import ray_tpu
        return "\n".join(ray_tpu.get(self._mgr.logs.remote(job_id),
                                     timeout=30))

    def stop_job(self, job_id: str) -> bool:
        import ray_tpu
        return ray_tpu.get(self._mgr.stop.remote(job_id), timeout=30)

    def list_jobs(self) -> List[Dict]:
        import ray_tpu
        return ray_tpu.get(self._mgr.list.remote(), timeout=60)

    def wait_until_finished(self, job_id: str, timeout: float = 300) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = self.get_job_status(job_id)
            if st in (SUCCEEDED, FAILED, STOPPED):
                return st
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still {st} after {timeout}s")
