"""Multi-node-on-one-machine test cluster (reference:
python/ray/cluster_utils.py:135 `Cluster`).

Starts one GCS plus N node managers as separate local processes, each with
its own shm store and arbitrary fake resources (e.g. {"TPU": 4} on a CPU
box) — the fixture that lets all distributed scheduling, placement-group,
and failover logic be exercised hermetically (reference conftest pattern:
python/ray/tests/conftest.py:500 ray_start_cluster)."""

from __future__ import annotations

import time
import uuid
from typing import Dict, List, Optional

from ray_tpu._private import node as node_mod


class ClusterNode:
    def __init__(self, local: node_mod.LocalNode):
        self._local = local
        self.node_id = local.node_id
        self.address = local.node_address
        self.store_path = local.store_path

    def kill(self):
        self._local.kill()


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None):
        self.session_name = f"c{uuid.uuid4().hex[:8]}"
        self.gcs_address: Optional[str] = None
        self.nodes: List[ClusterNode] = []
        self._gcs_handle = None
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def address(self) -> str:
        return self.gcs_address

    def add_node(self, num_cpus: float = 1,
                 resources: Optional[Dict[str, float]] = None,
                 object_store_memory: int = 128 * 1024 * 1024,
                 labels: Optional[Dict[str, str]] = None) -> ClusterNode:
        if self.gcs_address is None:
            head = node_mod.start_head(
                num_cpus=num_cpus, resources=resources,
                object_store_memory=object_store_memory, labels=labels,
                session_name=self.session_name)
            self.gcs_address = head.gcs_address
            self._gcs_handle = head.gcs_handle
            head.gcs_handle = None   # node.kill() must not take GCS down
            node = ClusterNode(head)
        else:
            ln = node_mod.start_node(
                self.gcs_address, num_cpus=num_cpus, resources=resources,
                object_store_memory=object_store_memory, labels=labels,
                session_name=self.session_name)
            node = ClusterNode(ln)
        self.nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode):
        node.kill()
        self.nodes = [n for n in self.nodes if n is not node]

    def wait_for_nodes(self, timeout: float = 15.0):
        """Block until every added node is alive in the GCS view."""
        import ray_tpu
        deadline = time.monotonic() + timeout
        want = {n.node_id for n in self.nodes}
        while time.monotonic() < deadline:
            alive = {n["node_id"] for n in ray_tpu.nodes() if n["alive"]}
            if want <= alive:
                return
            time.sleep(0.1)
        raise TimeoutError(f"nodes never came up: {want - alive}")

    def shutdown(self):
        for node in self.nodes:
            node.kill()
        self.nodes = []
        if self._gcs_handle is not None:
            self._gcs_handle.kill()
            self._gcs_handle = None
