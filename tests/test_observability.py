"""OTLP trace export + cluster stack dump (reference:
python/ray/util/tracing/tracing_helper.py:34 OTLP hooks; `ray stack`).
"""

import http.server
import json
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util.tracing import (cluster_stacks, export_otlp,
                                  format_cluster_stacks,
                                  task_events_to_otlp)

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_otlp_mapping_unit():
    rows = [
        {"task_id": "ab" * 12, "name": "f", "trace_id": "11" * 16,
         "span_id": "22" * 8, "parent_span_id": "33" * 8,
         "state_times": {"RUNNING": 10.0, "FINISHED": 10.5},
         "type": "NORMAL_TASK", "node_id": "n", "worker_id": "w",
         "state": "FINISHED"},
        {"task_id": "cd" * 12, "name": "g",
         "state_times": {"RUNNING": 11.0, "FAILED": 11.2},
         "state": "FAILED"},
        {"task_id": "ee" * 12, "name": "never-ran", "state_times": {}},
    ]
    payload = task_events_to_otlp(rows)
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert len(spans) == 2              # never-ran is dropped
    s0 = spans[0]
    assert s0["traceId"] == "11" * 16 and len(s0["traceId"]) == 32
    assert s0["spanId"] == "22" * 8 and len(s0["spanId"]) == 16
    assert s0["parentSpanId"] == "33" * 8
    assert int(s0["endTimeUnixNano"]) - int(s0["startTimeUnixNano"]) == \
        int(0.5e9)
    assert s0["status"]["code"] == 1
    assert spans[1]["status"]["code"] == 2      # FAILED -> error status
    # ids derived from task_id when no trace ctx, still fixed-width hex
    assert len(spans[1]["traceId"]) == 32 and len(spans[1]["spanId"]) == 16


def test_export_otlp_file_and_http(ray_start, tmp_path):
    @ray_tpu.remote
    def traced(x):
        return x + 1

    assert ray_tpu.get([traced.remote(i) for i in range(3)],
                       timeout=60) == [1, 2, 3]

    received = []

    class _Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            received.append((self.path, json.loads(self.rfile.read(n))))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), _Collector)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        out = str(tmp_path / "traces.json")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            payload = export_otlp(
                filename=out,
                endpoint=f"http://127.0.0.1:{srv.server_port}")
            spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
            if len([s for s in spans if s["name"] == "traced"]) >= 3:
                break
            time.sleep(0.5)     # task events flush asynchronously
        named = [s for s in spans if s["name"] == "traced"]
        assert len(named) >= 3, [s["name"] for s in spans]
        with open(out) as f:
            on_disk = json.load(f)
        assert on_disk["resourceSpans"][0]["resource"]["attributes"][0] \
            == {"key": "service.name", "value": {"stringValue": "ray_tpu"}}
        path, posted = received[-1]
        assert path == "/v1/traces" and "resourceSpans" in posted
    finally:
        srv.shutdown()


def test_cluster_stack_dump(ray_start):
    @ray_tpu.remote
    class Sleeper:
        def nap(self, s):
            import time as _t
            _t.sleep(s)
            return True

    a = Sleeper.remote()
    ref = a.nap.remote(20.0)
    # retry until the nap frame is visible: under load the actor may
    # take several seconds to construct and enter the method
    text = ""
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        dump = cluster_stacks()
        assert dump, "no nodes in stack dump"
        text = format_cluster_stacks(dump)
        if "nap" in text and "_t.sleep(s)" in text:
            break
        time.sleep(1.0)
    # the actor's sleeping frame is visible somewhere in the cluster
    assert "nap" in text and "_t.sleep(s)" in text
    # the node manager's own threads are present
    assert "node_manager" in text
    assert ray_tpu.get(ref, timeout=60) is True
    ray_tpu.kill(a)
