"""Sanitizer subsystem (SURVEY §5.2 — the reference's build:asan/tsan CI
configs, thread_checker.h single-thread assertions, and
instrumented_io_context event stats / lag probes, src/ray/common/asio/).

- Native: shm_store_selftest compiled with -fsanitize=address,undefined
  runs the arena through round trips / eviction / 4-thread hammering; any
  heap overflow or UB fails the subprocess.
- asyncio: the loop sanitizer times every callback the loop runs,
  aggregates per-handler event stats, rings slow callbacks, and probes
  scheduling lag; SingleLoopChecker pins components to one loop.
"""

import asyncio
import json
import subprocess
import sys
import textwrap

import pytest


@pytest.mark.slow
@pytest.mark.parametrize("name,shm", [
    ("shm_store_selftest", "/dev/shm/rt_selftest_pytest"),
    ("mutable_channel_selftest", "/dev/shm/rtc_selftest_pytest"),
])
def test_native_asan_selftest(name, shm):
    """Native components under ASan+UBSan: build the standalone harness
    and run it; sanitizer findings abort with nonzero exit + report on
    stderr."""
    from ray_tpu.native.build import build_selftest
    binary = build_selftest(name)
    r = subprocess.run([binary, shm],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr[-4000:])
    assert "OK" in r.stdout


@pytest.mark.slow
def test_native_tsan_concurrent_puts():
    """The put path's native surface under ThreadSanitizer: the
    selftest's concurrent sections run 4 caller threads through
    create/rt_write_parallel/seal/get on one arena plus the shared copy
    pool (queue + per-batch completion handshake), then hammer the
    lock-striped arena — concurrent create/seal/get against a
    per-stripe evictor and a lock-free rt_stats poller on a 4-stripe
    store (the lock-free seal CAS and seqlock snapshot reads are the
    racy surfaces this build exists to watch). The seqlock's
    publication edge carries explicit __tsan_acquire/__tsan_release
    annotations (shm_store.cpp RT_TSAN_*, compiled in only under this
    build): the stats reader's validated snapshot is anchored to the
    writer's closing lockseq bump at the protocol level, so a future
    relaxation of a per-field atomic to a plain load still trips tsan
    instead of silently racing. Single-process multi-thread is the
    regime tsan models well; cross-process robust-mutex EOWNERDEAD
    repair stays with the asan harness above (re-exec'd crash child).
    Any data race aborts with a nonzero exit."""
    from ray_tpu.native.build import build_selftest
    binary = build_selftest("shm_store_selftest", sanitize="thread")
    r = subprocess.run([binary, "/dev/shm/rt_selftest_tsan_pytest"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr[-4000:])
    assert "OK" in r.stdout


_LOOP_SCRIPT = textwrap.dedent("""
    import asyncio, json, os, time
    os.environ["RAY_TPU_LOOP_SANITIZER"] = "1"
    os.environ["RAY_TPU_SLOW_CALLBACK_S"] = "0.05"
    from ray_tpu.util import sanitizers

    def blocker():
        time.sleep(0.12)   # blocks the loop: the asyncio "data race"

    async def main():
        assert sanitizers.maybe_install()
        loop = asyncio.get_running_loop()
        loop.call_soon(blocker)
        # let the lag probe observe the stall the blocker causes
        await asyncio.sleep(0.3)
        print(json.dumps(sanitizers.stats_snapshot()))

    asyncio.run(main())
""")


def test_loop_sanitizer_records_slow_callbacks_and_lag():
    """Runs in a subprocess: the sanitizer patches Handle._run process-
    wide, and the suite must not run instrumented."""
    r = subprocess.run([sys.executable, "-c", _LOOP_SCRIPT],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    snap = json.loads(r.stdout.strip().splitlines()[-1])
    slow = snap["slow_callbacks"]
    assert any("blocker" in s["callback"] and s["duration_s"] >= 0.1
               for s in slow), slow
    # the 120ms block showed up as scheduling lag for the probe
    assert snap["loop_lag"]["max_s"] >= 0.05, snap["loop_lag"]
    # event stats aggregated the handler
    assert any("blocker" in name for name in snap["handlers"]), \
        snap["handlers"]


def test_single_loop_checker(monkeypatch):
    from ray_tpu.util.sanitizers import SingleLoopChecker
    monkeypatch.setenv("RAY_TPU_LOOP_SANITIZER", "1")
    chk = SingleLoopChecker("comp")

    async def touch():
        chk.check()

    asyncio.run(touch())          # pins loop 1
    with pytest.raises(AssertionError, match="single-loop"):
        asyncio.run(touch())      # fresh loop -> violation

    # disabled -> no-op even across loops
    monkeypatch.setenv("RAY_TPU_LOOP_SANITIZER", "0")
    chk2 = SingleLoopChecker("comp2")
    asyncio.run(_noop(chk2))
    asyncio.run(_noop(chk2))


async def _noop(chk):
    chk.check()


def test_stats_snapshot_none_when_inactive():
    from ray_tpu.util import sanitizers
    # this pytest process never installed the patch
    assert sanitizers.stats_snapshot() is None


_CLUSTER_SCRIPT = textwrap.dedent("""
    import json, os, time
    os.environ["RAY_TPU_LOOP_SANITIZER"] = "1"
    os.environ["RAY_TPU_SLOW_CALLBACK_S"] = "0.05"
    import ray_tpu

    @ray_tpu.remote
    class Blocker:
        async def block(self):
            # an async actor method doing sync sleep blocks the worker
            # loop — the exact bug class the sanitizer exists to catch
            time.sleep(0.2)
            return os.getpid()

    ray_tpu.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    try:
        a = Blocker.remote()
        ray_tpu.get(a.block.remote())
        from ray_tpu.util.tracing import cluster_stacks
        dump = cluster_stacks()
        found = []
        for node in dump.values():
            nm = node.get("node_manager") or {}
            if nm.get("loop_stats"):
                found.append("node_manager")
            for w in (node.get("workers") or {}).values():
                ls = w.get("loop_stats")
                if ls and ls["slow_callbacks"]:
                    found.append("worker_slow")
        print("FOUND:" + json.dumps(sorted(set(found))))
    finally:
        ray_tpu.shutdown()
""")


@pytest.mark.slow
def test_cluster_loop_stats_via_stack_dump():
    """e2e: daemons inherit the sanitizer env, a loop-blocking task is
    visible in the worker's loop stats through `ray_tpu stack`'s RPC."""
    r = subprocess.run([sys.executable, "-c", _CLUSTER_SCRIPT],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    line = [ln for ln in r.stdout.splitlines() if ln.startswith("FOUND:")]
    assert line, r.stdout[-2000:]
    found = json.loads(line[0][len("FOUND:"):])
    assert "node_manager" in found, found
    assert "worker_slow" in found, found
