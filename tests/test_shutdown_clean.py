"""Shutdown hygiene: ray_tpu.shutdown() must cancel-and-await every
background asyncio task — nothing may survive to spew "Task was destroyed
but it is pending!" (the asyncio analogue of the reference's sanitizer-clean
shutdown discipline, reference: .bazelrc tsan/asan configs)."""

import ray_tpu


def test_shutdown_leaves_no_pending_tasks():
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)

    @ray_tpu.remote
    def f(x):
        return x + 1

    @ray_tpu.remote(num_cpus=0.1)
    class A:
        def m(self):
            return 2

    # drive every background-task family: dispatchers (plain tasks),
    # actor senders (actor calls), event flusher, borrow/free paths
    assert ray_tpu.get([f.remote(i) for i in range(20)]) == \
        [i + 1 for i in range(20)]
    a = A.remote()
    assert ray_tpu.get([a.m.remote() for _ in range(20)]) == [2] * 20
    ref = ray_tpu.put(list(range(100)))
    assert len(ray_tpu.get(ref)) == 100

    import ray_tpu._private.worker as worker_mod
    w = worker_mod.global_worker
    assert w is not None
    ray_tpu.shutdown()
    assert w.leaked_tasks == [], \
        f"pending tasks leaked through shutdown: {w.leaked_tasks}"


def test_double_shutdown_is_safe():
    ray_tpu.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    ray_tpu.shutdown()
    ray_tpu.shutdown()   # no-op, must not raise
