"""Multi-model fleet plane (serve/fleet.py; ROADMAP item 3):
scale-to-zero with pre-warmed shells, per-tenant fair-share admission,
and burn-aware shedding.

Hermetic tier (no cluster, any interpreter):
- idle reaper thresholds (decide_scale_to_zero) and the controller's
  autoscale floor at one replica;
- shell pool checkout/return/discard/replenish;
- DRR fairness under zipf tenants, asserted NUMERICALLY: a hot tenant
  cannot push a quota-respecting tenant's service share below its
  weight;
- TenantAdmission quota 429s (shed + Retry-After, queued grant order);
- fallback shedding order (handle ladder, burn-loop suppression, demand
  rows);
- anti-affinity placement (plan_spread);
- revival through the shell pool with cold-start accounting, incl. the
  ShellAttachKiller chaos path: a shell killed mid-attach is discarded
  and the revival lands on a fresh shell / cold replica, exactly one
  replica published;
- prefix-summary push over the long-poll plane (controller bump +
  router apply + pull suppression);
- rtlint RT001 pass over the fleet module's hold-queue paths.

Cluster tier (Python >= 3.12): scale-to-zero -> cold-start revival
through a pre-warmed shell with exactly-once request delivery and a
reported cold-start p99.
"""

import collections
import itertools
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.fleet import (DeficitRoundRobin, FleetManager,
                                 ShellPool, TenantAdmission,
                                 TenantQuotaExceeded, decide_scale_to_zero,
                                 fallback_has_headroom, plan_spread)

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")


# --------------------------------------------------------------------------
# fakes (the test_serve_preemption idiom: controller drives fake replicas
# through monkeypatched ray primitives)
# --------------------------------------------------------------------------

class _FakeRef:
    _ids = itertools.count()

    def __init__(self, resolve):
        self.id = f"fakeref-{next(self._ids)}"
        self._resolve = resolve


class _FakeMethod:
    def __init__(self, fn):
        self._fn = fn

    def remote(self, *a, **kw):
        return _FakeRef(lambda: self._fn(*a, **kw))


class _FakeShell:
    _ids = itertools.count()

    def __init__(self, fail_attach=False):
        self._actor_id = f"shell-{next(self._ids)}"
        self.fail_attach = fail_attach
        self.attaches = 0

    def __getattr__(self, name):
        if name == "attach":
            return _FakeMethod(self._attach)
        if name == "get_queue_len":
            return _FakeMethod(lambda: 0)
        if name == "get_runtime_state":
            return _FakeMethod(
                lambda: {"queue_len": 0, "draining": False})
        if name == "check_health":
            return _FakeMethod(lambda: True)
        raise AttributeError(name)

    def _attach(self, *a, **kw):
        self.attaches += 1
        if self.fail_attach:
            raise RuntimeError("shell died mid-attach (chaos)")
        return True


@pytest.fixture
def fake_ray(monkeypatch):
    killed = []

    def fake_get(obj, timeout=None):
        if isinstance(obj, list):
            return [fake_get(o, timeout=timeout) for o in obj]
        return obj._resolve()

    def fake_wait(refs, num_returns=None, timeout=None):
        return list(refs), []

    monkeypatch.setattr(ray_tpu, "get", fake_get)
    monkeypatch.setattr(ray_tpu, "wait", fake_wait)
    monkeypatch.setattr(ray_tpu, "kill", killed.append)
    return killed


@pytest.fixture
def ctrl():
    from ray_tpu.serve.controller import ServeController

    class _QuietController(ServeController):
        def _reconcile_loop(self):   # tests drive ticks by hand
            return

    c = _QuietController()
    c._stop = True
    return c


def _mk_dep(ctrl, replicas, auto=None, name="m", app="default",
            extra_cfg=None):
    cfg = {"num_replicas": max(1, len(replicas)),
           "max_ongoing_requests": 4,
           "graceful_shutdown_timeout_s": 5.0,
           "preempt_grace_s": 2.0,
           "resumable_streams": False}
    if auto is not None:
        cfg["autoscaling_config"] = auto
    cfg.update(extra_cfg or {})
    dep = {"spec": {"name": name, "app_name": app, "config": cfg,
                    "callable": b"", "init_args": [], "init_kwargs": {},
                    "is_function": False},
           "replicas": list(replicas),
           "replica_gens": [0] * len(replicas),
           "version": 0, "target": max(1, len(replicas))}
    ctrl.apps.setdefault(app, {})[name] = dep
    return dep


# ==========================================================================
# idle reaper thresholds
# ==========================================================================

AUTO_S2Z = {"min_replicas": 0, "max_replicas": 2,
            "target_ongoing_requests": 2.0, "idle_scale_to_zero_s": 10.0,
            "look_back_period_s": 1.0, "downscale_delay_s": 0.0,
            "upscale_delay_s": 0.0}


def test_idle_reaper_waits_full_window():
    z, since = decide_scale_to_zero(AUTO_S2Z, None, 100.0, 1, 0.0)
    assert not z and since == 100.0
    z, since = decide_scale_to_zero(AUTO_S2Z, since, 105.0, 1, 0.0)
    assert not z and since == 100.0
    z, _ = decide_scale_to_zero(AUTO_S2Z, since, 110.0, 1, 0.0)
    assert z


def test_idle_reaper_load_resets_window():
    _, since = decide_scale_to_zero(AUTO_S2Z, None, 100.0, 1, 0.0)
    z, since = decide_scale_to_zero(AUTO_S2Z, since, 109.0, 1, 3.0)
    assert not z and since is None     # traffic: idle window restarts
    z, since = decide_scale_to_zero(AUTO_S2Z, since, 112.0, 1, 0.0)
    assert not z and since == 112.0


def test_idle_reaper_requires_opt_in_and_not_reviving():
    # min_replicas >= 1 never reaps, idle_scale_to_zero_s unset never
    # reaps, a revival in flight pins the deployment up
    a1 = {**AUTO_S2Z, "min_replicas": 1}
    assert decide_scale_to_zero(a1, 0.0, 1e6, 1, 0.0) == (False, None)
    a2 = {k: v for k, v in AUTO_S2Z.items() if k != "idle_scale_to_zero_s"}
    assert decide_scale_to_zero(a2, 0.0, 1e6, 1, 0.0) == (False, None)
    assert decide_scale_to_zero(AUTO_S2Z, 0.0, 1e6, 1, 0.0,
                                reviving=True) == (False, None)
    assert decide_scale_to_zero(None, 0.0, 1e6, 1, 0.0) == (False, None)


def test_autoscale_floors_at_one_replica_for_min_zero(ctrl, fake_ray):
    """The ordinary autoscaling policy never takes the last step to
    zero — only the fleet reaper does (after the FULL idle window)."""
    dep = _mk_dep(ctrl, [_FakeShell()], auto=AUTO_S2Z)
    for _ in range(8):
        ctrl._autoscale("default", "m", dep, [0])
    assert dep["target"] == 1


def test_controller_reaps_after_idle_window(ctrl, fake_ray):
    dep = _mk_dep(ctrl, [_FakeShell()], auto=AUTO_S2Z)
    clock = {"t": 1000.0}
    ctrl._fleet = FleetManager(ctrl, spawn_shell=_FakeShell,
                               clock=lambda: clock["t"])
    assert not ctrl._fleet.note_load("default", "m", dep, 0.0)
    clock["t"] += 5.0
    assert not ctrl._fleet.note_load("default", "m", dep, 0.0)
    clock["t"] += 6.0
    assert ctrl._fleet.note_load("default", "m", dep, 0.0)
    assert dep["target"] == 0
    # the ordinary reconcile path drains the last replica to zero
    ctrl._reconcile_deployment(dep)
    assert dep["replicas"] == [] and dep.get("draining")


# ==========================================================================
# shell pool
# ==========================================================================

def test_shell_pool_checkout_discard_replenish(fake_ray):
    spawned = []

    def spawn():
        s = _FakeShell()
        spawned.append(s)
        return s

    pool = ShellPool(spawn, size=2)
    pool.ensure()
    assert pool.idle() == 2 and pool.spawned_total == 2
    s1 = pool.checkout()
    assert s1 in spawned and pool.idle() == 1
    pool.discard(s1)
    assert fake_ray == [s1] and pool.discarded_total == 1
    pool.ensure()
    assert pool.idle() == 2 and pool.spawned_total == 3
    assert pool.checkout() and pool.checkout()
    assert pool.checkout() is None          # empty pool: cold build path
    st = pool.stats()
    assert st["checked_out_total"] == 3 and st["target"] == 2


def test_shell_pool_spawn_failure_is_contained():
    def bad_spawn():
        raise RuntimeError("no resources")

    pool = ShellPool(bad_spawn, size=2)
    pool.ensure()                            # must not raise
    assert pool.idle() == 0


# ==========================================================================
# DRR fairness (the acceptance criterion: numeric, zipf-hot tenants)
# ==========================================================================

def test_drr_equal_weights_split_service_equally():
    d = DeficitRoundRobin()
    for i in range(10_000):
        d.push("hot", i)
    for i in range(500):
        d.push("quiet", i)
    served = collections.Counter()
    for _ in range(800):
        t, _ = d.pop()
        served[t] += 1
    # both backlogged throughout: exactly half each under weight 1:1
    assert served["quiet"] == 400 and served["hot"] == 400


def test_drr_weighted_shares_are_proportional():
    d = DeficitRoundRobin()
    d.set_weight("a", 3.0)
    d.set_weight("b", 1.0)
    for i in range(2000):
        d.push("a", i)
        d.push("b", i)
    served = collections.Counter()
    for _ in range(1000):
        t, _ = d.pop()
        served[t] += 1
    assert served["a"] == 750 and served["b"] == 250


def test_drr_fractional_weight_banks_credit():
    d = DeficitRoundRobin()
    d.set_weight("slow", 0.25)
    for i in range(100):
        d.push("slow", i)
        d.push("fast", i)
    served = collections.Counter()
    for _ in range(100):
        t, _ = d.pop()
        served[t] += 1
    # 0.25 vs 1.0 -> 1:4 service ratio
    assert served["slow"] == 20 and served["fast"] == 80


def test_drr_hot_zipf_tenants_cannot_starve_anyone():
    """THE fairness assertion: 8 tenants with zipf-skewed backlogs and
    equal weights each get an equal service share while backlogged — a
    hot tenant's queue depth buys it nothing."""
    import numpy as np
    rng = np.random.default_rng(0)
    d = DeficitRoundRobin()
    tenants = [f"t{i}" for i in range(8)]
    # zipf arrivals: tenant 0 floods, the tail trickles — but everyone
    # stays backlogged over the service window we measure
    zipf = (1.0 / np.arange(1, 9)) ** 1.2
    arrivals = (4000 * zipf / zipf[-1]).astype(int)
    for t, n in zip(tenants, arrivals):
        for i in range(int(n)):
            d.push(t, i)
    order = list(rng.permutation(len(tenants)))  # arrival order irrelevant
    assert order                                  # (zipf used for queues)
    served = collections.Counter()
    rounds = 2000
    for _ in range(rounds):
        t, _ = d.pop()
        served[t] += 1
    share = {t: served[t] / rounds for t in tenants}
    for t in tenants:
        # weight share is 1/8; nobody dips below it (exact under DRR)
        assert share[t] == pytest.approx(1 / 8), (t, share)


# ==========================================================================
# TenantAdmission: quotas, 429s, grant order
# ==========================================================================

def test_quota_429_with_retry_after():
    adm = TenantAdmission(default_quota=2, queue_max=0)
    l1 = adm.acquire("a")
    l2 = adm.acquire("a")
    with pytest.raises(TenantQuotaExceeded) as ei:
        adm.acquire("a")
    assert ei.value.retry_after_s > 0 and ei.value.tenant == "a"
    assert adm.stats()["shed_total"]["a"] == 1
    l1.release()
    l3 = adm.acquire("a")                  # freed capacity admits again
    l2.release()
    l3.release()


def test_quota_zero_means_unlimited():
    adm = TenantAdmission(default_quota=0, queue_max=0)
    leases = [adm.acquire("anyone") for _ in range(64)]
    for l in leases:
        l.release()
    assert adm.stats()["admitted_total"]["anyone"] == 64


def test_queued_waiter_granted_on_release_fifo():
    adm = TenantAdmission(default_quota=1, queue_max=4)
    lease = adm.acquire("a")
    got = []

    def waiter(tag):
        l = adm.acquire("a", timeout_s=10)
        got.append(tag)
        l.release()

    t1 = threading.Thread(target=waiter, args=("first",))
    t1.start()
    time.sleep(0.1)
    t2 = threading.Thread(target=waiter, args=("second",))
    t2.start()
    time.sleep(0.1)
    assert got == []                       # both parked behind the quota
    lease.release()
    t1.join(timeout=5)
    t2.join(timeout=5)
    assert got == ["first", "second"]      # FIFO within one tenant


def test_queue_full_sheds_and_timeout_sheds():
    adm = TenantAdmission(default_quota=1, queue_max=1)
    lease = adm.acquire("a")
    shed = []

    def waiter():
        try:
            adm.acquire("a", timeout_s=0.2)
        except TenantQuotaExceeded:
            shed.append("timeout")

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with pytest.raises(TenantQuotaExceeded):
        adm.acquire("a", timeout_s=0.1)    # queue already holds 1
    t.join(timeout=5)
    assert shed == ["timeout"]
    lease.release()


def test_hot_tenant_cannot_push_quiet_share_below_weight():
    """Fairness through the FULL admission gate (quota + DRR + total
    concurrency): a flooding tenant and a quota-respecting tenant share
    a 2-slot ingress at >= the quiet tenant's weight share."""
    adm = TenantAdmission(default_quota=2, queue_max=10_000, total_limit=2)
    counts = collections.Counter()
    stop = threading.Event()

    def client(tenant):
        while not stop.is_set():
            try:
                lease = adm.acquire(tenant, timeout_s=5)
            except TenantQuotaExceeded:
                continue
            counts[tenant] += 1
            time.sleep(0.0005)
            lease.release()

    # BOTH tenants keep more threads than the 2-slot ingress, so both
    # stay backlogged in the DRR queue — the hot tenant merely floods 3x
    # harder. Fair share under equal weights is then 1/2 regardless.
    threads = [threading.Thread(target=client, args=("hot",), daemon=True)
               for _ in range(6)]
    threads += [threading.Thread(target=client, args=("quiet",),
                                 daemon=True) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    total = counts["hot"] + counts["quiet"]
    assert total > 50
    quiet_share = counts["quiet"] / total
    # equal weights -> fair share is 1/2; allow scheduling noise but the
    # hot tenant's 3x thread flood must not push quiet below ~40%
    assert quiet_share >= 0.4, counts


def test_apply_quotas_rows_and_default_row():
    adm = TenantAdmission(default_quota=0, queue_max=0)
    adm.apply_quotas([{"tenant": "a", "quota": 1, "weight": 2.0},
                      {"tenant": "__default__", "quota": 3}])
    assert adm.quota("a") == 1
    assert adm.quota("someone-else") == 3
    assert adm._drr.weight("a") == 2.0
    lease = adm.acquire("a")
    with pytest.raises(TenantQuotaExceeded):
        adm.acquire("a")
    lease.release()


def test_gcs_tenant_quota_table_merge_and_bound():
    from ray_tpu._private.gcs import GcsServer
    g = GcsServer.__new__(GcsServer)
    g.tenant_quotas = {}
    assert g.h_set_tenant_quota(None, "a", quota=4)
    assert g.h_set_tenant_quota(None, "a", weight=2.0)   # merges
    row = {r["tenant"]: r for r in g.h_get_tenant_quotas(None)}["a"]
    assert row["quota"] == 4 and row["weight"] == 2.0
    assert not g.h_set_tenant_quota(None, "")


# ==========================================================================
# fallback shedding order
# ==========================================================================

class _ShedRouter:
    """Just enough router surface for _maybe_shed."""

    def __init__(self, fallback=None, overloaded=False,
                 scale_to_zero=False, replicas=(1,)):
        self.fallback = fallback
        self._over = overloaded
        self.scale_to_zero = scale_to_zero
        self.replicas = list(replicas)
        self.revives = 0

    def refresh(self, force=False):
        pass

    def overloaded(self):
        return self._over

    def _request_revive(self):
        self.revives += 1


def _shed_handle(router):
    from ray_tpu.serve.handle import DeploymentHandle
    h = DeploymentHandle.__new__(DeploymentHandle)
    h.deployment_name = "big"
    h.app_name = "default"
    h._router = router
    return h


def test_handle_sheds_to_fallback_when_overloaded(monkeypatch):
    h = _shed_handle(_ShedRouter(fallback="small", overloaded=True))
    calls = []

    class _FB:
        def _invoke(self, method, args, kwargs, retry=2, shed_depth=0):
            calls.append((method, args, shed_depth))
            return "shed-response"

    monkeypatch.setattr(type(h), "_fallback_handle", lambda self: _FB())
    out = h._invoke("__call__", ("x",), {})
    assert out == "shed-response"
    assert calls == [("__call__", ("x",), 1)]


def test_handle_serves_locally_when_not_overloaded(monkeypatch):
    h = _shed_handle(_ShedRouter(fallback="small", overloaded=False))
    assert h._maybe_shed("__call__", (), {}, 2, 0) is None
    h2 = _shed_handle(_ShedRouter(fallback=None, overloaded=True))
    assert h2._maybe_shed("__call__", (), {}, 2, 0) is None


def test_shed_depth_caps_the_fallback_ladder():
    h = _shed_handle(_ShedRouter(fallback="small", overloaded=True))
    from ray_tpu.serve.handle import DeploymentHandle
    assert h._maybe_shed("__call__", (), {}, 2,
                         DeploymentHandle.MAX_SHED_DEPTH) is None


def test_shed_from_zero_replicas_kicks_revival(monkeypatch):
    r = _ShedRouter(fallback="small", overloaded=True,
                    scale_to_zero=True, replicas=())
    h = _shed_handle(r)

    class _FB:
        def _invoke(self, *a, **kw):
            return "fb"

    monkeypatch.setattr(type(h), "_fallback_handle", lambda self: _FB())
    assert h._invoke("__call__", (), {}) == "fb"
    assert r.revives == 1   # fallback absorbs WHILE the primary warms


def test_burn_loop_prefers_shedding_over_new_slices(ctrl, fake_ray,
                                                    monkeypatch):
    """Burn-violating deployment with a fallback that has headroom:
    target stays put, shed_active set, demand rows stay empty."""
    big = _mk_dep(ctrl, [_FakeShell()], name="big",
                  auto={"min_replicas": 1, "max_replicas": 4,
                        "target_ongoing_requests": 2.0},
                  extra_cfg={"fallback_model": "small",
                             "slo_config": {"p95_ttft_ms": 100.0}})
    small = _mk_dep(ctrl, [_FakeShell()], name="small")
    small["loads"] = [0]

    class _Scaler:
        def decide(self, auto, rows, target, load, now):
            return target + 1          # burn says: upscale

    ctrl._burn_scalers[("default", "big")] = _Scaler()
    rows = [{"objective": "latency", "violating": True,
             "burn_fast": 3.0, "burn_slow": 3.0}]
    with ctrl._lock:
        ctrl._burn_autoscale("default", "big", big, rows, [8])
    assert big["target"] == 1 and big["shed_active"]
    assert ctrl.get_replica_demand() == []     # no slice bids while shedding

    # fallback saturated -> shedding stops, the upscale goes through
    small["loads"] = [100]
    with ctrl._lock:
        ctrl._burn_autoscale("default", "big", big, rows, [8])
    assert big["target"] == 2 and not big["shed_active"]
    assert len(ctrl.get_replica_demand()) == 1


def test_fallback_headroom_predicate():
    dep = {"spec": {"config": {"max_ongoing_requests": 4}},
           "replicas": [object(), object()], "loads": [1, 1]}
    assert fallback_has_headroom(dep)
    dep["loads"] = [4, 4]
    assert not fallback_has_headroom(dep)          # >= 80% of 8
    assert not fallback_has_headroom(
        {"spec": {"config": {}}, "replicas": [], "loads": []})


# ==========================================================================
# anti-affinity placement
# ==========================================================================

def _node(nid, cpu=8.0, alive=True):
    return {"node_id": nid, "alive": alive, "available": {"CPU": cpu}}


def test_plan_spread_picks_least_loaded_distinct_node():
    nodes = [_node("a"), _node("b"), _node("c")]
    assert plan_spread(nodes, ["a", "b"]) == "c"
    assert plan_spread(nodes, ["a", "a", "b", "c"]) in ("b", "c")
    # ties break to the most available CPU
    nodes2 = [_node("a", cpu=2.0), _node("b", cpu=16.0)]
    assert plan_spread(nodes2, []) == "b"


def test_plan_spread_skips_dead_nodes_and_single_node():
    nodes = [_node("a"), _node("b", alive=False)]
    assert plan_spread(nodes, []) is None           # one alive node: moot
    nodes = [_node("a"), _node("b", alive=False), _node("c")]
    assert plan_spread(nodes, ["a"]) == "c"


def test_controller_records_spread_assignment(ctrl, fake_ray, monkeypatch):
    dep = _mk_dep(ctrl, [], name="spread")
    dep["target"] = 2
    monkeypatch.setattr(
        ray_tpu, "nodes",
        lambda: [_node("n1"), _node("n2")], raising=False)
    built = []

    def fake_build(spec, spread_node=None):
        built.append(spread_node)
        return _FakeShell(), None

    monkeypatch.setattr(ctrl, "_build_replica", fake_build)
    ctrl._create_replicas(dep, 2)
    assert len(dep["replicas"]) == 2
    # second build must land on the OTHER node (anti-affinity)
    assert set(built) == {"n1", "n2"}
    assert set(dep["replica_nodes"].values()) == {"n1", "n2"}


# ==========================================================================
# revival through the shell pool (+ chaos)
# ==========================================================================

def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def test_revive_attaches_shell_and_records_cold_start(ctrl, fake_ray):
    dep = _mk_dep(ctrl, [], auto=AUTO_S2Z)
    dep["target"] = 0
    fm = FleetManager(ctrl, spawn_shell=_FakeShell)
    ctrl._fleet = fm
    fm.pool.ensure()
    v0 = dep["version"]
    assert ctrl.revive_deployment("default", "m")
    assert _wait(lambda: len(dep["replicas"]) == 1)
    assert dep["target"] == 1 and dep["version"] > v0
    assert dep["replicas"][0].attaches == 1
    assert _wait(lambda: not dep.get("_creating", True))
    stats = fm.cold_start_stats()["default/m"]
    assert stats["count"] == 1 and stats["p99_ms"] >= 0
    assert fm.revivals_total == 1 and fm.cold_builds_total == 0
    # idempotent once replicas exist
    assert ctrl.revive_deployment("default", "m")
    time.sleep(0.05)
    assert len(dep["replicas"]) == 1
    st = ctrl.get_fleet_status()
    assert st["deployments"]["default"]["m"]["scale_to_zero"]
    assert st["fleet"]["cold_starts"]["default/m"]["count"] == 1


def test_revive_unknown_deployment_is_false(ctrl, fake_ray):
    ctrl._fleet = FleetManager(ctrl, spawn_shell=_FakeShell)
    assert not ctrl.revive_deployment("default", "nope")


def test_chaos_shell_attach_failure_falls_to_fresh_shell(ctrl, fake_ray):
    """ShellAttachKiller shape: the first shell dies mid-attach; the
    fleet manager discards it and the revival lands on the next pooled
    shell — EXACTLY one replica published (held requests dispatch once,
    to a replica that exists)."""
    dep = _mk_dep(ctrl, [], auto=AUTO_S2Z)
    dep["target"] = 0
    shells = [_FakeShell(fail_attach=True), _FakeShell()]
    spawned = iter(shells + [_FakeShell() for _ in range(8)])
    fm = FleetManager(ctrl, spawn_shell=lambda: next(spawned))
    fm.pool.size = 2
    ctrl._fleet = fm
    fm.pool.ensure()
    assert ctrl.revive_deployment("default", "m")
    assert _wait(lambda: len(dep["replicas"]) == 1)
    assert _wait(lambda: not dep.get("_creating", True))
    assert len(dep["replicas"]) == 1                     # exactly once
    assert dep["replicas"][0] is shells[0] or dep["replicas"][0].attaches
    assert dep["replicas"][0].fail_attach is False
    assert shells[0] in fake_ray                         # poisoned: killed
    assert fm.pool.discarded_total == 1


def test_chaos_all_shells_poisoned_falls_back_to_cold_build(
        ctrl, fake_ray, monkeypatch):
    dep = _mk_dep(ctrl, [], auto=AUTO_S2Z)
    dep["target"] = 0
    bad = iter([_FakeShell(fail_attach=True) for _ in range(8)])
    fm = FleetManager(ctrl, spawn_shell=lambda: next(bad))
    fm.pool.size = 1
    ctrl._fleet = fm
    fm.pool.ensure()
    cold = _FakeShell()
    monkeypatch.setattr(ctrl, "_build_replica",
                        lambda spec, spread_node=None: (cold, None))
    assert ctrl.revive_deployment("default", "m")
    assert _wait(lambda: len(dep["replicas"]) == 1)
    assert dep["replicas"] == [cold]
    assert fm.cold_builds_total == 1


def test_shell_attach_killer_spec_and_arming():
    import os

    from ray_tpu._private import rpc
    from ray_tpu.util.chaos import ShellAttachKiller
    k = ShellAttachKiller(0.5)
    assert k.spec() == "shell_attach=0.5"
    env = k.env({"RAY_TPU_TESTING_RPC_FAILURE": "push_chunk=0.1"})
    assert env["RAY_TPU_TESTING_RPC_FAILURE"] == \
        "push_chunk=0.1,shell_attach=0.5"
    with pytest.raises(ValueError):
        ShellAttachKiller(0.0)
    k2 = ShellAttachKiller(1.0)
    k2.arm_local()
    try:
        assert os.environ["RAY_TPU_TESTING_RPC_FAILURE"] == \
            "shell_attach=1.0"
        with pytest.raises(rpc.RpcError):
            rpc._maybe_inject_failure("shell_attach")
    finally:
        ShellAttachKiller.disarm_local()
    rpc._maybe_inject_failure("shell_attach")   # disarmed: no-op


def test_replica_shell_guards_until_attached(fake_ray):
    import cloudpickle

    from ray_tpu.serve.fleet import ReplicaShell
    shell = ReplicaShell()
    assert shell.check_health() is True       # idle shell is healthy
    with pytest.raises(RuntimeError):
        shell.handle_request("__call__", (), {})

    class _Target:
        def __init__(self):
            self.attached_hook = 0

        def on_shell_attach(self):
            self.attached_hook += 1

        def __call__(self, x):
            return x * 2

    assert shell.attach(cloudpickle.dumps(_Target), (), {}, False)
    assert shell._callable.attached_hook == 1  # warm hook ran pre-ready
    assert shell.handle_request("__call__", (3,), {}) == 6


# ==========================================================================
# hold queue (handle-level submit(hold=) shape)
# ==========================================================================

def _hold_router():
    from ray_tpu.serve.handle import _Router
    r = _Router.__new__(_Router)
    r.deployment_name = "m"
    r.app_name = "default"
    r.replicas = []
    r.inflight = {}
    r.shared_load = {}
    r.version = 0
    r.scale_to_zero = True
    r.fallback = None
    r.max_ongoing = 4
    r._revive_t = 0.0
    r.lock = threading.Lock()
    r.model_map = {}
    return r


def test_hold_for_revival_parks_until_replicas_appear(monkeypatch):
    from ray_tpu._private.config import cfg as rt_cfg
    r = _hold_router()
    revives = []

    def fake_refresh(force=False):
        if len(revives) >= 1:
            with r.lock:
                r.replicas = [object()]
                r.inflight = {0: 0}

    monkeypatch.setattr(r, "refresh", fake_refresh, raising=False)
    monkeypatch.setattr(r, "_request_revive",
                        lambda: revives.append(1), raising=False)
    t0 = time.monotonic()
    r._hold_for_revival()
    assert revives and r.replicas           # parked, revived, released
    assert time.monotonic() - t0 < rt_cfg.fleet_cold_start_timeout_s


def test_hold_for_revival_times_out_to_error_path(monkeypatch):
    r = _hold_router()
    monkeypatch.setattr(r, "refresh", lambda force=False: None,
                        raising=False)
    monkeypatch.setattr(r, "_request_revive", lambda: None, raising=False)
    from ray_tpu._private.config import cfg as rt_cfg
    rt_cfg.set("fleet_cold_start_timeout_s", 0.3)
    try:
        t0 = time.monotonic()
        r._hold_for_revival()               # returns (pick raises after)
        assert 0.2 < time.monotonic() - t0 < 5.0
    finally:
        rt_cfg.reset("fleet_cold_start_timeout_s")


def test_router_overloaded_predicate():
    r = _hold_router()
    assert r.overloaded()                    # zero replicas
    with r.lock:
        r.replicas = [object(), object()]
        r.shared_load = {0: 4, 1: 3}
        r.inflight = {0: 0, 1: 1}
    assert r.overloaded()                    # 8 >= 2 * 4
    with r.lock:
        r.shared_load = {0: 1, 1: 1}
        r.inflight = {0: 0, 1: 0}
    assert not r.overloaded()
    with r.lock:
        r.max_ongoing = 0                    # unknown capacity
        r.shared_load = {0: 99, 1: 99}
    assert not r.overloaded()


# ==========================================================================
# prefix-summary push over long-poll (ROADMAP item 1 satellite)
# ==========================================================================

def _summary_rows():
    return [{"replica_id": "r1", "fps": [11, 22], "chunk": 4,
             "deployment": "d", "ts": 1.0},
            {"replica_id": "r2", "fps": [33], "chunk": 4,
             "deployment": "d", "ts": 1.0}]


def test_controller_pushes_summaries_on_change(ctrl, fake_ray,
                                               monkeypatch):
    dep = _mk_dep(ctrl, [_FakeShell()], name="d",
                  extra_cfg={"prefix_routed": True})
    rows = {"v": _summary_rows()}

    class _W:
        def gcs_call(self, method, **kw):
            assert method == "get_prefix_summaries"
            return rows["v"]

    monkeypatch.setattr(ray_tpu, "_get_worker", lambda: _W(),
                        raising=False)
    items = [("default", "d", dep)]
    ctrl._push_prefix_summaries(items)
    assert ctrl._versions.get("prefix_summaries") == 1
    assert ctrl._key_data("prefix_summaries") == {"rows": _summary_rows()}
    # unchanged table -> no bump
    ctrl._push_prefix_summaries(items)
    assert ctrl._versions.get("prefix_summaries") == 1
    # changed fingerprints -> bump
    rows["v"] = [{"replica_id": "r1", "fps": [11], "chunk": 4,
                  "deployment": "d", "ts": 2.0}]
    ctrl._push_prefix_summaries(items)
    assert ctrl._versions.get("prefix_summaries") == 2


def test_controller_push_skips_without_prefix_routed_deployments(
        ctrl, fake_ray, monkeypatch):
    dep = _mk_dep(ctrl, [_FakeShell()], name="plain")

    def boom():
        raise AssertionError("must not query the GCS")

    monkeypatch.setattr(ray_tpu, "_get_worker", boom, raising=False)
    ctrl._push_prefix_summaries([("default", "plain", dep)])
    assert "prefix_summaries" not in ctrl._versions


def test_router_summary_push_applies_and_suppresses_pull(monkeypatch):
    from ray_tpu.serve.handle import _Router
    r = _Router.__new__(_Router)
    r.lock = threading.Lock()
    r.replica_ids = ["r1", "r2"]
    r._summaries = {}
    r._summary_chunk = None
    r._last_summary_refresh = 0.0
    r._apply_summary_push(_summary_rows())
    assert r._summaries == {"r1": {11, 22}, "r2": {33}}
    assert r._summary_chunk == 4

    def boom():
        raise AssertionError("push is fresh: pull must be suppressed")

    monkeypatch.setattr(ray_tpu, "_get_worker", boom, raising=False)
    r._refresh_summaries()          # early-returns before any GCS call

    # rows for replicas outside this deployment are filtered out
    r.replica_ids = ["r2"]
    r._apply_summary_push(_summary_rows())
    assert set(r._summaries) == {"r2"}


def test_longpoll_client_dispatches_summary_key():
    from ray_tpu.serve.handle import _LongPollClient
    client = _LongPollClient.__new__(_LongPollClient)
    client._routers = {}
    client._summary_routers = {}
    client._versions = {}
    client._reg_lock = threading.Lock()
    r = _hold_router()
    r._summaries = {}
    r._summary_chunk = None
    client.watch_summaries(r)
    client.watch_summaries(r)       # idempotent
    assert client._versions["prefix_summaries"] == -1
    assert client._summary_routers["prefix_summaries"] == [r]


# ==========================================================================
# deployment info carries the fleet fields
# ==========================================================================

def test_deployment_info_fleet_fields(ctrl, fake_ray):
    _mk_dep(ctrl, [_FakeShell()], name="m", auto=AUTO_S2Z,
            extra_cfg={"fallback_model": "small"})
    info = ctrl.get_deployment_info("default", "m")
    assert info["scale_to_zero"] is True
    assert info["fallback"] == "small"
    assert info["max_ongoing"] == 4
    _mk_dep(ctrl, [_FakeShell()], name="plain")
    info2 = ctrl.get_deployment_info("default", "plain")
    assert info2["scale_to_zero"] is False and info2["fallback"] is None


# ==========================================================================
# rtlint: RT001 pass over the fleet module's hold-queue paths
# ==========================================================================

def test_rtlint_rt001_clean_on_fleet_hold_paths():
    """The fleet plane's hold/queue code must never block the
    controller reconcile loop or any async handler: RT001
    (loop-blocking) over serve/fleet.py reports zero findings."""
    import os

    from ray_tpu.devtools.lint import run_lint
    from ray_tpu.devtools.lint.config import LintConfig
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    target = os.path.join(repo, "ray_tpu", "serve", "fleet.py")
    r = run_lint([target], config=LintConfig(root=repo),
                 enable=["RT001"], use_baseline=False)
    assert r.findings == [], [str(f) for f in r.findings]


# ==========================================================================
# cluster tier (Python >= 3.12)
# ==========================================================================

@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=6)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


@needs_cluster
def test_scale_to_zero_and_shell_revival_exactly_once(ray_start):
    """Acceptance: a deployment scales to zero after its idle window,
    then concurrent first requests revive it through a pre-warmed shell
    — every held request answered exactly once, cold-start p99
    reported by the fleet view."""
    import dataclasses

    class Echo:
        def __call__(self, payload):
            import os
            return {"pid": os.getpid(), "echo": payload}

    dep = serve.deployment(
        Echo, num_replicas=1,
        autoscaling_config={"min_replicas": 0, "max_replicas": 1,
                            "target_ongoing_requests": 2.0,
                            "look_back_period_s": 1.0,
                            "downscale_delay_s": 0.5,
                            "idle_scale_to_zero_s": 2.0})
    assert dataclasses.asdict(
        dep.config.autoscaling_config)["idle_scale_to_zero_s"] == 2.0
    handle = serve.run(dep.bind(), name="fleet-acc")
    try:
        assert handle.remote("warm").result(timeout=30)["echo"] == "warm"
        # idle past the window: the reaper takes the last replica
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = serve.status()["fleet-acc"]["Echo"]
            if st["running"] == 0 and st["target"] == 0:
                break
            time.sleep(0.5)
        else:
            pytest.fail(f"never scaled to zero: {serve.status()}")
        fs = serve.fleet_status()
        assert fs["deployments"]["fleet-acc"]["Echo"]["scaled_to_zero"]

        # concurrent first requests: all held, all answered exactly once
        results = {}

        def one(i):
            results[i] = handle.remote({"i": i}).result(timeout=90)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(4)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        wall = time.monotonic() - t0
        assert len(results) == 4
        assert sorted(r["echo"]["i"] for r in results.values()) == \
            [0, 1, 2, 3]                            # exactly once each
        pids = {r["pid"] for r in results.values()}
        assert len(pids) == 1                       # one revived replica

        fs = serve.fleet_status()
        cold = fs["fleet"]["cold_starts"]["fleet-acc/Echo"]
        assert cold["count"] >= 1
        assert 0 < cold["p99_ms"] < wall * 1e3 + 60_000
        assert fs["fleet"]["revivals_total"] >= 1
    finally:
        serve.shutdown()


@needs_cluster
def test_tenant_quota_429_through_http_proxy(ray_start):
    """Per-tenant admission at the ingress: a tenant with quota 1 gets
    429 + Retry-After on its second concurrent request; untagged
    traffic is untouched."""
    import json as _json
    import urllib.error
    import urllib.request

    class Slow:
        def __call__(self, payload):
            time.sleep(1.0)
            return {"ok": True}

    serve.run(serve.deployment(Slow, num_replicas=1).bind(),
              name="tenants", route_prefix="/t")
    try:
        serve.set_tenant_quota("metered", max_concurrent=1)
        from ray_tpu._private.config import cfg as rt_cfg
        rt_cfg.set("tenant_queue_max", 0)
        serve.start(http_port=0, wait=True)
        addr = next(iter(serve.proxies().values()))["http"]
        time.sleep(6.0)        # let the proxy's quota refresh land

        def post(tenant):
            req = urllib.request.Request(
                f"http://{addr}/t", method="POST",
                data=_json.dumps({"x": 1}).encode(),
                headers={"Content-Type": "application/json",
                         **({"X-RayTPU-Tenant": tenant} if tenant
                            else {})})
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return resp.status, dict(resp.headers)
            except urllib.error.HTTPError as e:
                return e.code, dict(e.headers)

        codes = {}

        def run_one(tag, tenant):
            codes[tag] = post(tenant)

        threads = [threading.Thread(target=run_one, args=(i, "metered"))
                   for i in range(3)]
        threads.append(threading.Thread(target=run_one,
                                        args=("free", "")))
        for t in threads:
            t.start()
            time.sleep(0.1)
        for t in threads:
            t.join(timeout=60)
        metered = [codes[i][0] for i in range(3)]
        assert 200 in metered and 429 in metered, codes
        shed = next(codes[i] for i in range(3) if codes[i][0] == 429)
        assert "Retry-After" in shed[1]
        assert codes["free"][0] == 200              # untagged: untouched
    finally:
        from ray_tpu._private.config import cfg as rt_cfg
        rt_cfg.reset("tenant_queue_max")
        serve.shutdown()


# --------------------------------------------------- weight-source attach
# ROADMAP item 3 leftover: shell revivals attach weights from the PR 11
# arena (serve/weights.py resolve_weight_source) instead of re-running
# params_fn — KV-recorded broadcast ref, put-fallback, loader fallback.

class _FakeKV:
    def __init__(self):
        self.store = {}

    def gcs_call(self, method, ns=None, key=None, value=None):
        if method == "kv_put":
            self.store[(ns, key)] = value
            return None
        if method == "kv_get":
            return self.store.get((ns, key))
        if method == "kv_del":
            self.store.pop((ns, key), None)
            return None
        raise AssertionError(method)


@pytest.fixture()
def fake_weight_plane(monkeypatch):
    """serve/weights.py wired to an in-memory KV + object store."""
    from ray_tpu.serve import weights as W
    kv = _FakeKV()
    objects = {}
    counter = itertools.count()

    class Ref:
        def __init__(self, n):
            self.n = n

    def broadcast(tree, node_ids=None, **kw):
        ref = Ref(next(counter))
        objects[ref.n] = tree
        return ref

    def put(tree):
        ref = Ref(next(counter))
        objects[ref.n] = tree
        return ref

    def get(ref, timeout=None):
        if ref.n not in objects:
            raise RuntimeError("object lost")
        return objects[ref.n]

    monkeypatch.setattr(W, "_connected", lambda: True)
    monkeypatch.setattr(W, "_worker", lambda: kv)
    monkeypatch.setattr(ray_tpu, "broadcast_weights", broadcast)
    monkeypatch.setattr(ray_tpu, "put", put)
    monkeypatch.setattr(ray_tpu, "get", get)
    return {"kv": kv, "objects": objects}


def test_weight_source_loader_runs_once(fake_weight_plane):
    from ray_tpu.serve import weights as W
    calls = []

    def loader():
        calls.append(1)
        return {"w": 1.0}

    first = W.resolve_weight_source("llm/m/0", loader, enabled=True)
    assert first == {"w": 1.0} and len(calls) == 1
    # second attach (the shell-revival shape): arena ref, no loader
    second = W.resolve_weight_source("llm/m/0", loader, enabled=True)
    assert second == {"w": 1.0} and len(calls) == 1


def test_weight_source_put_fallback(fake_weight_plane, monkeypatch):
    from ray_tpu.serve import weights as W

    def broken_broadcast(tree, **kw):
        raise RuntimeError("no data plane")
    monkeypatch.setattr(ray_tpu, "broadcast_weights", broken_broadcast)
    calls = []
    out = W.resolve_weight_source("k2", lambda: calls.append(1)
                                  or {"w": 2.0}, enabled=True)
    assert out == {"w": 2.0} and calls == [1]
    # the put-fallback still recorded a usable ref
    out2 = W.resolve_weight_source("k2", lambda: calls.append(1)
                                   or {"w": 2.0}, enabled=True)
    assert out2 == {"w": 2.0} and len(calls) == 1


def test_weight_source_stale_ref_reloads(fake_weight_plane):
    from ray_tpu.serve import weights as W
    calls = []

    def loader():
        calls.append(1)
        return {"w": 3.0}

    W.resolve_weight_source("k3", loader, enabled=True)
    # the broadcast object dies (node loss); the recorded ref goes stale
    fake_weight_plane["objects"].clear()
    out = W.resolve_weight_source("k3", loader, enabled=True)
    assert out == {"w": 3.0} and len(calls) == 2
    # ...and the reload re-published: next attach is arena again
    W.resolve_weight_source("k3", loader, enabled=True)
    assert len(calls) == 2


def test_weight_source_disabled_reruns_loader(fake_weight_plane):
    from ray_tpu.serve import weights as W
    calls = []
    for _ in range(2):
        W.resolve_weight_source("k4", lambda: calls.append(1) or {},
                                enabled=False)
    assert len(calls) == 2
    assert fake_weight_plane["kv"].store == {}


def test_llm_deployment_auto_weights_key(monkeypatch):
    """LLMDeployment derives the arena key from (model, seed) for
    registry models and routes params_fn through the resolver."""
    from ray_tpu.inference import api as api_mod
    from ray_tpu.serve import weights as W
    seen = {}

    def fake_resolve(key, loader, **kw):
        seen["key"] = key
        return loader()
    monkeypatch.setattr(W, "resolve_weight_source", fake_resolve)

    import jax
    import jax.numpy as jnp
    from ray_tpu.models import MODEL_REGISTRY, TransformerLM
    m = TransformerLM(MODEL_REGISTRY["llama-debug"])

    def pf():
        t0 = jnp.zeros((1, 8), jnp.int32)
        return m.init(jax.random.PRNGKey(0), t0)["params"]

    api_mod.LLMDeployment("llama-debug", n_slots=2, max_len=32,
                          params_fn=pf, seed=7)
    assert seen["key"] == "llm/llama-debug/7"
