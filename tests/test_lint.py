"""rtlint — the runtime-aware static analysis gate (ISSUE 8).

Three tiers here:

- rule semantics against the fixture corpus (`tests/lint_fixtures/`):
  every rule detects its bad fixtures and stays silent on its clean
  fixture; suppressions and the baseline behave as documented;
- the SELF-GATE: `ray_tpu lint ray_tpu/ --format json` over the real
  package exits 0 with zero unsuppressed findings, in under 10 s (the
  CI wall-clock guard);
- the compile-once invariant covered by BOTH layers: RT002 flags the
  retrace-inducing scalar pattern statically, and the same class of
  bug monkeypatched into the live decode step is caught dynamically by
  `decode_compile_count` — the two layers watch the same failure.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu.devtools.lint import run_lint
from ray_tpu.devtools.lint.baseline import Baseline
from ray_tpu.devtools.lint.config import LintConfig, load_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


def lint_fixture(*names, enable=None):
    paths = [os.path.join(FIXTURES, n) for n in names]
    return run_lint(paths, config=LintConfig(root=REPO), enable=enable,
                    use_baseline=False)


def rules_hit(result):
    return sorted({f.rule for f in result.findings})


# ------------------------------------------------------------- rule corpus

@pytest.mark.parametrize("bad,rule,min_hits", [
    ("rt001_bad_sleep.py", "RT001", 3),
    ("rt001_bad_handler.py", "RT001", 3),
    ("rt002_bad_coerce.py", "RT002", 3),
    ("rt002_bad_spec_accept.py", "RT002", 3),
    ("rt002_bad_donate.py", "RT002", 2),
    ("rt002_bad_donate_apply.py", "RT002", 2),
    ("rt003_bad_unlocked.py", "RT003", 3),
    ("rt003_bad_wrong_lock.py", "RT003", 1),
    ("_private/rt004_bad_daemon.py", "RT004", 2),
    ("rt005_bad_returns.py", "RT005", 4),
])
def test_bad_fixture_detected(bad, rule, min_hits):
    r = lint_fixture(bad)
    hits = [f for f in r.findings if f.rule == rule]
    assert len(hits) >= min_hits, [f.format() for f in r.findings]
    # findings carry usable locations
    assert all(f.line > 0 and f.path.endswith(bad.split("/")[-1])
               for f in hits)


@pytest.mark.parametrize("clean", [
    "rt001_clean.py", "rt002_clean.py", "rt003_clean.py",
    "_private/rt004_clean.py", "rt005_clean.py",
])
def test_clean_fixture_not_flagged(clean):
    r = lint_fixture(clean)
    assert r.findings == [], [f.format() for f in r.findings]


def test_rt004_scoped_to_private_paths(tmp_path):
    """The same daemon-swallow pattern outside a _private/ path is out
    of RT004's scope (the rule's path_filter)."""
    src = open(os.path.join(FIXTURES, "_private",
                            "rt004_bad_daemon.py")).read()
    p = tmp_path / "userland.py"
    p.write_text(src)
    r = run_lint([str(p)], config=LintConfig(root=str(tmp_path)),
                 use_baseline=False)
    assert not [f for f in r.findings if f.rule == "RT004"]


def test_rt002_branch_allows_is_none():
    src = textwrap.dedent("""
        import jax
        @jax.jit
        def f(x, temp):
            if temp is None:          # trace-time Python: allowed
                return x
            return x * temp
    """)
    import ast as ast_mod
    from ray_tpu.devtools.lint.registry import FileContext
    from ray_tpu.devtools.lint.rules.rt002_jit_retrace import JitRetraceRule
    ctx = FileContext("mod.py", src, ast_mod.parse(src))
    assert list(JitRetraceRule().check(ctx)) == []


# ---------------------------------------------------- suppression semantics

def test_suppressions_trailing_standalone_and_def_scope():
    r = lint_fixture("suppressed.py")
    assert r.findings == [], [f.format() for f in r.findings]
    assert r.suppressed == 4      # 2 inline + 2 under the def-line pragma


def test_suppression_only_silences_named_rule():
    r = lint_fixture("suppressed.py", enable=["RT001"])
    assert r.findings == []
    # a pragma naming RT001 must not hide other rules on the same line —
    # check the suppression map directly
    from ray_tpu.devtools.lint.suppress import (is_suppressed,
                                                parse_suppressions)
    src = open(os.path.join(FIXTURES, "suppressed.py")).read()
    per_line, file_wide = parse_suppressions(src)
    some_line = next(iter(per_line))
    assert is_suppressed("RT001", some_line, [], per_line, file_wide)
    assert not is_suppressed("RT004", some_line, [], per_line, file_wide)


# ----------------------------------------------------------- baseline gate

def test_baseline_passes_known_and_fails_new(tmp_path):
    # without a baseline the legacy finding fails the gate
    r = lint_fixture("baselined.py")
    assert len(r.findings) == 1 and r.findings[0].rule == "RT001"

    # register it with a justification -> gate passes, finding reported
    # as baselined with the justification attached
    bpath = tmp_path / "bl.json"
    bl = Baseline()
    bl.update(r.findings, str(bpath))
    doc = json.loads(bpath.read_text())
    doc["entries"][0]["justification"] = "legacy sleep; tracked in #42"
    bpath.write_text(json.dumps(doc))

    r2 = run_lint([os.path.join(FIXTURES, "baselined.py")],
                  config=LintConfig(root=REPO),
                  baseline_path=str(bpath))
    assert r2.ok and r2.findings == []
    assert len(r2.baselined) == 1
    assert r2.baselined[0].justification == "legacy sleep; tracked in #42"

    # a NEW finding alongside the baselined one still fails
    r3 = run_lint([os.path.join(FIXTURES, "baselined.py"),
                   os.path.join(FIXTURES, "rt001_bad_sleep.py")],
                  config=LintConfig(root=REPO), baseline_path=str(bpath))
    assert not r3.ok and len(r3.findings) >= 3


def test_baseline_update_preserves_justifications_and_reports_stale(
        tmp_path):
    r = lint_fixture("baselined.py")
    bpath = tmp_path / "bl.json"
    Baseline().update(r.findings, str(bpath))
    doc = json.loads(bpath.read_text())
    doc["entries"][0]["justification"] = "keep me"
    # plus a stale entry for code that no longer exists
    doc["entries"].append({"fingerprint": "feedfacedeadbeef",
                           "rule": "RT001", "path": "gone.py",
                           "symbol": "x", "snippet": "gone()",
                           "justification": "obsolete"})
    bpath.write_text(json.dumps(doc))

    r2 = run_lint([os.path.join(FIXTURES, "baselined.py")],
                  config=LintConfig(root=REPO), baseline_path=str(bpath))
    assert r2.stale_baseline == ["feedfacedeadbeef"]

    bl = Baseline.load(str(bpath))
    bl.update(r2.findings + r2.baselined, str(bpath))
    doc2 = json.loads(bpath.read_text())
    assert len(doc2["entries"]) == 1            # stale entry pruned
    assert doc2["entries"][0]["justification"] == "keep me"

    # fingerprints survive the finding moving to another line (same
    # repo-relative path, edits above the finding)
    src = open(os.path.join(FIXTURES, "baselined.py")).read()
    moved = tmp_path / "tests" / "lint_fixtures" / "baselined.py"
    moved.parent.mkdir(parents=True)
    moved.write_text("# pushed down\n\n" + src)
    r3 = run_lint([str(moved)], config=LintConfig(root=str(tmp_path)),
                  use_baseline=False)
    assert r3.findings[0].line != r2.baselined[0].line
    # same (rule, path, symbol, snippet) -> same fingerprint
    assert r3.findings[0].fingerprint == r2.baselined[0].fingerprint


# ------------------------------------------------------- config resolution

def test_tool_rtlint_config_block(tmp_path):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [project]
        name = "x"

        [tool.rtlint]
        paths = ["pkg"]
        exclude = ["__pycache__", "pkg/vendor"]
        enable = ["RT001", "RT004"]
        baseline = "custom-baseline.json"
    """))
    cfg = load_config(str(tmp_path))
    assert cfg.paths == ["pkg"]
    assert cfg.enable == ["RT001", "RT004"]
    assert cfg.exclude[-1] == "pkg/vendor"
    assert cfg.baseline_path == str(tmp_path / "custom-baseline.json")

    # enabled-rule subset is honored end to end
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "import time\nasync def f():\n    time.sleep(1)\n")
    r = run_lint(config=load_config(str(tmp_path)), use_baseline=False)
    assert rules_hit(r) == ["RT001"]
    assert r.rules_run == ["RT001", "RT004"]


def test_unknown_rule_rejected():
    with pytest.raises(ValueError, match="unknown rule"):
        run_lint([FIXTURES], config=LintConfig(root=REPO),
                 enable=["RT999"], use_baseline=False)


# ------------------------------------------------------------ CLI contract

def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "lint", *args],
        capture_output=True, text=True, cwd=cwd, timeout=120)


def test_cli_exit_codes_and_json():
    bad = _cli(os.path.join("tests", "lint_fixtures",
                            "rt001_bad_sleep.py"), "--format", "json")
    assert bad.returncode == 1, bad.stderr[-1000:]
    doc = json.loads(bad.stdout)
    assert not doc["ok"] and len(doc["findings"]) >= 3
    assert {"rule", "path", "line", "message", "fingerprint"} <= \
        set(doc["findings"][0])

    clean = _cli(os.path.join("tests", "lint_fixtures", "rt001_clean.py"))
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "0 new finding(s)" in clean.stdout


def test_cli_self_gate_package_clean_and_fast():
    """THE acceptance gate: `ray_tpu lint ray_tpu/ --format json` over
    the whole package — zero unsuppressed findings, exit 0, < 10 s
    wall clock (tier-1 box guard)."""
    t0 = time.monotonic()
    r = _cli("ray_tpu", "--format", "json")
    wall = time.monotonic() - t0
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]
    doc = json.loads(r.stdout)
    assert doc["ok"] and doc["findings"] == []
    assert doc["files_scanned"] > 100          # really saw the package
    assert doc["errors"] == []
    # every baselined finding carries a real justification
    for f in doc["baselined"]:
        assert f.get("justification"), f
        assert "TODO" not in f["justification"], f
    assert wall < 10.0, f"lint self-gate took {wall:.1f}s (budget 10s)"


# --------------------------------------------------- off_loop marker plumb

def test_off_loop_marker_is_pure_annotation():
    from ray_tpu._private.markers import off_loop

    class C:
        @off_loop(lock="_mu")
        def m(self):
            return 41

    assert C().m() == 41
    assert C.m.__rt_off_loop__ == {"lock": "_mu"}


@pytest.mark.skipif(sys.version_info < (3, 12),
                    reason="object_store requires 3.12 (PEP 688)")
def test_arena_client_methods_are_marked():
    from ray_tpu._private.object_store import ObjectStoreClient
    for name in ("create", "get", "put_bytes", "_release", "close"):
        fn = getattr(ObjectStoreClient, name)
        assert getattr(fn, "__rt_off_loop__", None) == \
            {"lock": "_pins_lock"}, name


# ------------------------------------- compile-once invariant, both layers

_RETRACE_SNIPPET = textwrap.dedent("""
    import jax

    def build(model):
        def decode(params, pk, pv, lengths, toks, rng, temps):
            cur = int(lengths)         # host coercion of traced state
            if lengths > 0:            # data-dependent Python branch
                toks = toks + cur
            return toks
        return jax.jit(decode)
""")


def test_compile_once_static_layer_flags_retrace_pattern(tmp_path):
    p = tmp_path / "decode_like.py"
    p.write_text(_RETRACE_SNIPPET)
    r = run_lint([str(p)], config=LintConfig(root=str(tmp_path)),
                 use_baseline=False)
    msgs = [f.message for f in r.findings if f.rule == "RT002"]
    assert any("concretizes" in m for m in msgs), msgs
    assert any("branch" in m for m in msgs), msgs


def test_compile_once_dynamic_layer_catches_retrace():
    """The runtime side of the same invariant: a retrace-inducing
    wrapper monkeypatched into the decode step drives
    decode_compile_count past 1 within a few steps — the dynamic check
    (engine.compile instants + the ==1 assertions in
    test_inference_engine.py) covers exactly the failure RT002 flags
    statically."""
    jax = pytest.importorskip("jax")
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import numpy as np

    from ray_tpu.inference import EngineConfig, InferenceEngine
    from ray_tpu.models.transformer import TransformerConfig, TransformerLM
    import jax.numpy as jnp

    tcfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, max_seq_len=64, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)
    model = TransformerLM(tcfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    eng = InferenceEngine(model, params, EngineConfig(
        n_slots=2, max_len=32, prefill_chunk=4, prefill_budget=8))

    # healthy engine: decode compiles exactly once over several steps
    h = eng.submit(np.arange(1, 6), max_new_tokens=8)
    for _ in range(4):
        eng.step()
    assert eng.decode_compile_count == 1

    # the bug RT002 models: a host-coerced scalar folded into the
    # program's cache identity — here the current max sequence length
    # rides in as a STATIC arg, so every step's new value is a cache
    # miss that re-traces the decode body (and bumps the trace counter)
    raw = eng._decode_fn.__wrapped__

    def decode_with_scalar(cur_len, *args):
        return raw(*args)

    bad_jit = jax.jit(decode_with_scalar, static_argnums=(0,))

    def retracing_decode(*args):
        cur_len = int(np.asarray(eng._lengths).max())   # the coercion
        return bad_jit(cur_len, *args)

    eng._decode_fn = retracing_decode
    before = eng.decode_compile_count
    for _ in range(3):
        eng.step()
    assert h is not None
    assert eng.decode_compile_count >= before + 2, (
        "dynamic layer failed to observe the retrace",
        eng.decode_compile_count)
