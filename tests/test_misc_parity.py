"""Parity utilities: ActorPool, Queue, cancel, runtime_env, timeline,
workflow, spilling, autoscaler (reference: python/ray/tests/test_actor_pool,
test_queue, test_cancel, test_runtime_env, workflow tests, autoscaler
fake-provider tests)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Queue


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_actor_pool(ray_start):
    @ray_tpu.remote
    class Sq:
        def sq(self, x):
            return x * x

    pool = ActorPool([Sq.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.sq.remote(v), range(8)))
    assert out == [i * i for i in range(8)]
    out2 = sorted(pool.map_unordered(lambda a, v: a.sq.remote(v), range(5)))
    assert out2 == [i * i for i in range(5)]


def test_queue(ray_start):
    q = Queue(maxsize=3)
    for i in range(3):
        q.put(i)
    assert q.qsize() == 3
    with pytest.raises(Exception):
        q.put(99, block=False)
    assert [q.get() for _ in range(3)] == [0, 1, 2]
    with pytest.raises(Exception):
        q.get(block=False)

    q2 = Queue()   # unbounded: producer must not block on a full queue
    @ray_tpu.remote
    def producer(q):
        for i in range(5):
            q.put(i * 10)

    ray_tpu.get(producer.remote(q2), timeout=30)
    assert [q2.get(timeout=10) for _ in range(5)] == [0, 10, 20, 30, 40]


def test_cancel_queued_task(ray_start):
    @ray_tpu.remote
    def blocker():
        import time
        time.sleep(5)
        return "done"

    @ray_tpu.remote
    def victim():
        return "ran"

    # fill all 4 CPUs, then queue a victim and cancel it
    blockers = [blocker.remote() for _ in range(4)]
    time.sleep(0.5)
    v = victim.remote()
    time.sleep(0.3)
    ray_tpu.cancel(v)
    with pytest.raises((ray_tpu.TaskCancelledError, Exception)):
        ray_tpu.get(v, timeout=30)
    assert ray_tpu.get(blockers, timeout=30) == ["done"] * 4


def test_runtime_env_env_vars(ray_start):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "yes_hello"}})
    def read_env():
        import os
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_env.remote()) == "yes_hello"

    @ray_tpu.remote
    def read_env2():
        import os
        return os.environ.get("MY_FLAG")

    assert ray_tpu.get(read_env2.remote()) is None   # restored after task


def test_timeline_export(ray_start, tmp_path):
    @ray_tpu.remote
    def traced():
        return 1

    ray_tpu.get([traced.remote() for _ in range(2)])
    time.sleep(1.5)
    out = str(tmp_path / "trace.json")
    ray_tpu.timeline(out)
    import json
    with open(out) as f:
        events = json.load(f)
    assert any(e["name"] == "traced" for e in events)


def test_workflow_resume(ray_start, tmp_path):
    from ray_tpu import workflow

    counter_file = str(tmp_path / "exec_count")

    def bump_counter():
        n = int(open(counter_file).read()) if os.path.exists(counter_file) \
            else 0
        with open(counter_file, "w") as f:
            f.write(str(n + 1))

    @workflow.step
    def load():
        bump_counter()
        return 10

    @workflow.step
    def double(x):
        return x * 2

    @workflow.step
    def add(a, b):
        return a + b

    dag = add.bind(double.bind(load.bind()), load.bind())
    out = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path / "wf"))
    assert out == 30
    runs_first = int(open(counter_file).read())
    # resume: all steps checkpointed, nothing re-executes
    out2 = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path / "wf"))
    assert out2 == 30
    assert int(open(counter_file).read()) == runs_first
    workflow.delete("wf1", storage=str(tmp_path / "wf"))


def test_object_spill_and_restore(ray_start):
    """Fill the 64MB store past its spill threshold; earlier objects spill
    to disk and must still be readable."""
    import numpy as np
    refs = [ray_tpu.put(np.full(8 * 1024 * 1024 // 8, i, np.float64))
            for i in range(12)]   # 96MB total in a 64MB store
    time.sleep(5)   # spill loop cadence
    for i, r in enumerate(refs):
        arr = ray_tpu.get(r, timeout=30)
        assert arr[0] == i, f"object {i} corrupted/lost"


def test_runtime_env_working_dir_and_py_modules(ray_start, tmp_path):
    """Local dirs ship as content-addressed zips through the GCS KV and
    materialize on workers (reference: runtime-env packaging — GCS zips,
    packaging.py; URI-cached)."""
    wd = tmp_path / "proj"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-42")
    mod = tmp_path / "mylib"
    mod.mkdir()
    (mod / "__init__.py").write_text("MAGIC = 1234\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd),
                                 "py_modules": [str(mod)]})
    def probe():
        import os
        with open("data.txt") as f:
            content = f.read()
        import mylib
        return content, mylib.MAGIC, os.getcwd()

    content, magic, cwd = ray_tpu.get(probe.remote(), timeout=60)
    assert content == "payload-42"
    assert magic == 1234
    # ran in the EXTRACTED copy, not the source dir
    assert cwd != str(wd) and "runtime_envs" in cwd


def test_multiprocessing_pool(ray_start):
    """Pool shim (reference: ray.util.multiprocessing.Pool)."""
    from ray_tpu.util.multiprocessing import Pool

    def sq(x):
        return x * x

    def addmul(a, b):
        return a * 10 + b

    with Pool(processes=2) as p:
        assert p.map(sq, range(8)) == [i * i for i in range(8)]
        assert p.apply(sq, (7,)) == 49
        ar = p.apply_async(sq, (9,))
        assert ar.get(timeout=30) == 81 and ar.successful()
        assert list(p.imap(sq, range(4))) == [0, 1, 4, 9]
        assert sorted(p.imap_unordered(sq, range(4))) == [0, 1, 4, 9]
        assert p.starmap(addmul, [(1, 2), (3, 4)]) == [12, 34]


def test_workflow_events_and_virtual_actors(ray_start, tmp_path):
    """Workflow event steps block durably until send_event; virtual
    actors persist state per method call (reference: ray.workflow events
    + virtual actors)."""
    import threading

    from ray_tpu import workflow

    @workflow.step
    def before():
        return "ready"

    @workflow.step
    def combine(a, ev):
        return f"{a}:{ev}"

    node = combine.bind(before.bind(), workflow.wait_for_event("go"))

    out = {}

    def runner():
        out["v"] = workflow.run(node, workflow_id="ev-wf",
                                storage=str(tmp_path))

    t = threading.Thread(target=runner)
    t.start()
    time.sleep(0.5)
    assert t.is_alive()            # blocked on the event
    workflow.send_event("ev-wf", "go", "signal", storage=str(tmp_path))
    t.join(timeout=60)
    assert out["v"] == "ready:signal"
    # resume consumes the checkpoint, not the event again
    assert workflow.run(node, workflow_id="ev-wf",
                        storage=str(tmp_path)) == "ready:signal"

    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    a = workflow.get_actor(Counter, "c1", storage=str(tmp_path))
    assert a.add(2) == 2
    assert a.add(3) == 5
    # a fresh handle (fresh process in real life) sees durable state
    b = workflow.get_actor(Counter, "c1", storage=str(tmp_path))
    assert b.add(1) == 6
