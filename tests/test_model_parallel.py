"""Model + sharding tests on the 8-device virtual CPU mesh: ring attention
exactness, flash kernel (interpret mode), sharded train step convergence
across dp/fsdp/tp/sp layouts."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from ray_tpu.models import MODEL_REGISTRY, TransformerLM
from ray_tpu.ops.attention import flash_attention, mha_reference
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.parallel import MeshConfig, make_mesh
from ray_tpu.parallel.mesh import AXIS_SEQ, use_mesh
from ray_tpu.parallel.train_step import make_train_fns


def test_devices():
    assert len(jax.devices()) == 8


def test_flash_attention_interpret_matches_reference():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    B, L, H, D = 2, 256, 2, 128
    q = jax.random.normal(k1, (B, L, H, D), jnp.float32)
    k = jax.random.normal(k2, (B, L, H, D), jnp.float32)
    v = jax.random.normal(k3, (B, L, H, D), jnp.float32)
    ref = mha_reference(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ring_attention_exact():
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, seq=8, tensor=1))
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    B, L, H, D = 2, 128, 4, 32
    q = jax.random.normal(k1, (B, L, H, D), jnp.float32)
    k = jax.random.normal(k2, (B, L, H, D), jnp.float32)
    v = jax.random.normal(k3, (B, L, H, D), jnp.float32)
    ref = mha_reference(q, k, v, causal=True)
    spec = P(None, AXIS_SEQ, None, None)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name=AXIS_SEQ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_ring_attention_gqa():
    mesh = make_mesh(MeshConfig(data=2, fsdp=1, seq=4, tensor=1))
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(2), 3)
    B, L, H, Hkv, D = 1, 64, 8, 2, 16
    q = jax.random.normal(k1, (B, L, H, D), jnp.float32)
    k = jax.random.normal(k2, (B, L, Hkv, D), jnp.float32)
    v = jax.random.normal(k3, (B, L, Hkv, D), jnp.float32)
    ref = mha_reference(q, k, v, causal=True)
    spec = P(None, AXIS_SEQ, None, None)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name=AXIS_SEQ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    out = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


MESH_LAYOUTS = [
    MeshConfig(data=8, fsdp=1, seq=1, tensor=1),
    MeshConfig(data=1, fsdp=8, seq=1, tensor=1),
    MeshConfig(data=1, fsdp=1, seq=1, tensor=8),
    MeshConfig(data=2, fsdp=2, seq=1, tensor=2),
    MeshConfig(data=1, fsdp=2, seq=2, tensor=2),
]


@pytest.mark.parametrize("layout", MESH_LAYOUTS,
                         ids=lambda c: f"d{c.data}f{c.fsdp}s{c.seq}t{c.tensor}")
def test_sharded_train_step(layout):
    mesh = make_mesh(layout)
    cfg = MODEL_REGISTRY["llama-debug"]
    model = TransformerLM(cfg)
    opt = optax.adamw(1e-3)
    B, L = 8, 64
    init_fn, step_fn, _ = make_train_fns(model, opt, mesh,
                                         batch_shape=(B, L + 1))
    state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, L + 1), 0,
                                cfg.vocab_size)
    losses = []
    for _ in range(5):
        state, metrics = step_fn(state, tokens)
        losses.append(float(metrics["loss"]))
    # memorizing one batch: loss must drop
    assert losses[-1] < losses[0] - 0.1, losses
    assert int(jax.device_get(state.step)) == 5


def test_layouts_agree():
    """Same data, two different shardings → same loss trajectory."""
    cfg = MODEL_REGISTRY["llama-debug"]
    model = TransformerLM(cfg)
    B, L = 8, 64
    tokens = jax.random.randint(jax.random.PRNGKey(7), (B, L + 1), 0,
                                cfg.vocab_size)
    results = []
    for layout in [MeshConfig(data=8, fsdp=1, seq=1, tensor=1),
                   MeshConfig(data=1, fsdp=2, seq=2, tensor=2)]:
        mesh = make_mesh(layout)
        opt = optax.adamw(1e-3)
        init_fn, step_fn, _ = make_train_fns(model, opt, mesh,
                                             batch_shape=(B, L + 1))
        state = init_fn(jax.random.PRNGKey(0))
        tr = []
        for _ in range(3):
            state, m = step_fn(state, tokens)
            tr.append(float(m["loss"]))
        results.append(tr)
    np.testing.assert_allclose(results[0], results[1], rtol=2e-2)


def test_flash_attention_grad_matches_reference():
    """The custom_vjp backward kernels (dq, dk, dv) must match XLA AD
    through the reference implementation, including GQA summing and
    head-dim padding (D=64 -> 128 lanes)."""
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(3), 4)
    B, L, H, Hkv, D = 2, 256, 4, 2, 64
    q = jax.random.normal(k1, (B, L, H, D), jnp.float32)
    k = jax.random.normal(k2, (B, L, Hkv, D), jnp.float32)
    v = jax.random.normal(k3, (B, L, Hkv, D), jnp.float32)
    dout = jax.random.normal(k4, (B, L, H, D), jnp.float32)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) * dout)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=128,
                                       block_k=128, interpret=True) * dout)

    ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    out_grads = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_out, name in zip(ref_grads, out_grads, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g_out), np.asarray(g_ref), rtol=2e-2, atol=2e-2,
            err_msg=f"d{name} mismatch")


def test_flash_attention_noncausal_grad():
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(5), 3)
    B, L, H, D = 1, 128, 2, 128
    q = jax.random.normal(k1, (B, L, H, D), jnp.float32)
    k = jax.random.normal(k2, (B, L, H, D), jnp.float32)
    v = jax.random.normal(k3, (B, L, H, D), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    ref = jax.grad(loss(lambda q, k, v: mha_reference(
        q, k, v, causal=False)), argnums=(0, 1, 2))(q, k, v)
    out = jax.grad(loss(lambda q, k, v: flash_attention(
        q, k, v, causal=False, block_q=128, block_k=128, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_out in zip(ref, out):
        np.testing.assert_allclose(np.asarray(g_out), np.asarray(g_ref),
                                   rtol=2e-2, atol=2e-2)
