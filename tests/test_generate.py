"""KV-cache decode + autoregressive generation (the serving inference
engine; reference ships no model code — parity target is the decode
correctness contract every inference stack owes: cached stepwise logits
must equal the full causal forward).

CPU-pinned: the axon TPU plugin overrides JAX_PLATFORMS, and its bf16
default matmuls would turn exactness checks into noise comparisons."""

import dataclasses

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax_cpu():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return jax


@pytest.fixture(scope="module")
def debug_model(jax_cpu):
    import jax.numpy as jnp

    from ray_tpu.models import MODEL_REGISTRY, TransformerLM
    cfg = dataclasses.replace(MODEL_REGISTRY["llama-debug"],
                              dtype=jnp.float32, param_dtype=jnp.float32,
                              remat=False)
    model = TransformerLM(cfg)
    tokens = jax_cpu.random.randint(jax_cpu.random.PRNGKey(1), (2, 12), 0,
                                    cfg.vocab_size)
    params = model.init(jax_cpu.random.PRNGKey(0), tokens)["params"]
    return cfg, model, params, tokens


def test_cached_decode_matches_full_forward(jax_cpu, debug_model):
    """Prefill + single-token decode steps reproduce the full causal
    forward's logits at every position (scanned-layer layout)."""
    import jax.numpy as jnp

    from ray_tpu.models import init_cache
    cfg, model, params, tokens = debug_model
    full = model.apply({"params": params}, tokens)
    cache = init_cache(cfg, 2, 12, dtype=jnp.float32)
    lg, cache = model.apply({"params": params}, tokens[:, :8],
                            cache=cache)
    np.testing.assert_allclose(np.asarray(lg), np.asarray(full[:, :8]),
                               rtol=2e-4, atol=2e-4)
    for t in range(8, 12):
        lg, cache = model.apply({"params": params}, tokens[:, t:t + 1],
                                cache=cache)
        np.testing.assert_allclose(np.asarray(lg[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-4, atol=2e-4)
    assert int(cache["idx"]) == 12


def test_cached_decode_matches_unrolled_layers(jax_cpu, debug_model):
    """Same contract on the scan_layers=False param layout."""
    import jax.numpy as jnp

    from ray_tpu.models import TransformerLM, init_cache
    cfg, _, _, tokens = debug_model
    cfg2 = dataclasses.replace(cfg, scan_layers=False)
    model = TransformerLM(cfg2)
    params = model.init(jax_cpu.random.PRNGKey(0), tokens)["params"]
    full = model.apply({"params": params}, tokens)
    cache = init_cache(cfg2, 2, 12, dtype=jnp.float32)
    _, cache = model.apply({"params": params}, tokens[:, :5], cache=cache)
    lg = None
    for t in range(5, 12):
        lg, cache = model.apply({"params": params}, tokens[:, t:t + 1],
                                cache=cache)
    np.testing.assert_allclose(np.asarray(lg[:, 0]),
                               np.asarray(full[:, 11]),
                               rtol=2e-4, atol=2e-4)


def test_chunked_prefill_matches_one_shot(jax_cpu, debug_model):
    """Prefill split into budgeted chunks through the cached-attention
    path (chunked_prefill=True, idx>0) reproduces the one-shot prefill
    logits at every position — the empty-cache restriction is lifted."""
    import jax.numpy as jnp

    from ray_tpu.models import init_cache
    cfg, model, params, tokens = debug_model
    full = model.apply({"params": params}, tokens)
    one_shot = init_cache(cfg, 2, 12, dtype=jnp.float32)
    lg_one, one_shot = model.apply({"params": params}, tokens,
                                   cache=one_shot)
    cache = init_cache(cfg, 2, 12, dtype=jnp.float32)
    lgs = []
    for lo, hi in [(0, 5), (5, 9), (9, 12)]:      # uneven chunks
        lg, cache = model.apply({"params": params}, tokens[:, lo:hi],
                                cache=cache, chunked_prefill=True)
        lgs.append(lg)
    chunked = jnp.concatenate(lgs, axis=1)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(full),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(lg_one),
                               rtol=2e-4, atol=2e-4)
    assert int(cache["idx"]) == 12
    # caches agree -> subsequent decode steps agree
    np.testing.assert_allclose(np.asarray(cache["k"]),
                               np.asarray(one_shot["k"]),
                               rtol=2e-4, atol=2e-4)


def test_per_slot_decode_positions(jax_cpu, debug_model):
    """cache['idx'] as a per-row vector: each row decodes at its own
    length (the slot-pool contract). Row parity against independent
    scalar-idx decodes at different lengths."""
    import jax.numpy as jnp

    from ray_tpu.models import init_cache
    cfg, model, params, tokens = debug_model
    lens = [7, 4]
    # reference: each row prefilled alone to its own length, one decode
    want = []
    for b, ln in enumerate(lens):
        c = init_cache(cfg, 1, 12, dtype=jnp.float32)
        _, c = model.apply({"params": params}, tokens[b:b + 1, :ln],
                           cache=c)
        lg, _ = model.apply({"params": params}, tokens[b:b + 1, ln:ln + 1],
                            cache=c)
        want.append(np.asarray(lg[0, 0]))
    # slot pool: both rows in one cache at different idx
    pool = {"k": jnp.zeros((cfg.n_layers, 2, 12, cfg.n_kv_heads,
                            cfg.head_dim), jnp.float32),
            "v": jnp.zeros((cfg.n_layers, 2, 12, cfg.n_kv_heads,
                            cfg.head_dim), jnp.float32),
            "idx": jnp.zeros((), jnp.int32)}
    for b, ln in enumerate(lens):
        c = init_cache(cfg, 1, 12, dtype=jnp.float32)
        _, c = model.apply({"params": params}, tokens[b:b + 1, :ln],
                           cache=c)
        pool["k"] = pool["k"].at[:, b:b + 1].set(c["k"])
        pool["v"] = pool["v"].at[:, b:b + 1].set(c["v"])
    pool["idx"] = jnp.asarray(lens, jnp.int32)
    step_tok = jnp.stack([tokens[b, ln] for b, ln in enumerate(lens)])
    lg, new = model.apply({"params": params}, step_tok[:, None],
                          cache=pool)
    for b in range(2):
        np.testing.assert_allclose(np.asarray(lg[b, 0]), want[b],
                                   rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(np.asarray(new["idx"]),
                                  np.asarray(lens) + 1)


def test_generate_greedy_matches_stepwise_argmax(jax_cpu, debug_model):
    """make_generate_fn's one-program generation equals a hand loop of
    full forwards + argmax."""
    from ray_tpu.models import make_generate_fn
    from ray_tpu.parallel import MeshConfig, make_mesh
    cfg, model, params, tokens = debug_model
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, seq=1, tensor=1),
                     devices=jax_cpu.devices()[:1])
    B, P, N = 2, 12, 6
    _, gen_fn, _ = make_generate_fn(model, mesh, batch=B, prompt_len=P,
                                    max_new_tokens=N)
    out = np.asarray(gen_fn(params, tokens, jax_cpu.random.PRNGKey(7)))
    # reference: repeated full forwards (no cache), greedy
    cur = np.asarray(tokens)
    want = []
    for _ in range(N):
        logits = model.apply({"params": params},
                             jax_cpu.numpy.asarray(cur))
        nxt = np.asarray(logits[:, -1, :]).argmax(-1)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, np.stack(want, axis=1))


def test_generate_sharded_mesh(jax_cpu):
    """Generation jitted over an fsdp x tensor mesh: sharded params +
    sharded KV cache, replicated output tokens, deterministic greedy."""
    from ray_tpu.models import MODEL_REGISTRY, TransformerLM, \
        make_generate_fn
    from ray_tpu.parallel import MeshConfig, make_mesh
    if len(jax_cpu.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = MODEL_REGISTRY["llama-debug"]
    model = TransformerLM(cfg)
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, seq=1, tensor=2),
                     devices=jax_cpu.devices()[:8])
    B, P, N = 8, 16, 8
    init_fn, gen_fn, _ = make_generate_fn(model, mesh, batch=B,
                                          prompt_len=P, max_new_tokens=N)
    params = init_fn(jax_cpu.random.PRNGKey(0))
    prompt = jax_cpu.random.randint(jax_cpu.random.PRNGKey(1), (B, P), 0,
                                    cfg.vocab_size)
    out = np.asarray(gen_fn(params, prompt, jax_cpu.random.PRNGKey(2)))
    assert out.shape == (B, N)
    assert out.min() >= 0 and out.max() < cfg.vocab_size
    out2 = np.asarray(gen_fn(params, prompt, jax_cpu.random.PRNGKey(9)))
    np.testing.assert_array_equal(out, out2)     # greedy ignores rng
    _, gen_t, _ = make_generate_fn(model, mesh, batch=B, prompt_len=P,
                                   max_new_tokens=N, temperature=1.0)
    a = np.asarray(gen_t(params, prompt, jax_cpu.random.PRNGKey(3)))
    b = np.asarray(gen_t(params, prompt, jax_cpu.random.PRNGKey(4)))
    assert (a != b).any()
