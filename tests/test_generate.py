"""KV-cache decode + autoregressive generation (the serving inference
engine; reference ships no model code — parity target is the decode
correctness contract every inference stack owes: cached stepwise logits
must equal the full causal forward).

CPU-pinned: the axon TPU plugin overrides JAX_PLATFORMS, and its bf16
default matmuls would turn exactness checks into noise comparisons."""

import dataclasses

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax_cpu():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return jax


@pytest.fixture(scope="module")
def debug_model(jax_cpu):
    import jax.numpy as jnp

    from ray_tpu.models import MODEL_REGISTRY, TransformerLM
    cfg = dataclasses.replace(MODEL_REGISTRY["llama-debug"],
                              dtype=jnp.float32, param_dtype=jnp.float32,
                              remat=False)
    model = TransformerLM(cfg)
    tokens = jax_cpu.random.randint(jax_cpu.random.PRNGKey(1), (2, 12), 0,
                                    cfg.vocab_size)
    params = model.init(jax_cpu.random.PRNGKey(0), tokens)["params"]
    return cfg, model, params, tokens


def test_cached_decode_matches_full_forward(jax_cpu, debug_model):
    """Prefill + single-token decode steps reproduce the full causal
    forward's logits at every position (scanned-layer layout)."""
    import jax.numpy as jnp

    from ray_tpu.models import init_cache
    cfg, model, params, tokens = debug_model
    full = model.apply({"params": params}, tokens)
    cache = init_cache(cfg, 2, 12, dtype=jnp.float32)
    lg, cache = model.apply({"params": params}, tokens[:, :8],
                            cache=cache)
    np.testing.assert_allclose(np.asarray(lg), np.asarray(full[:, :8]),
                               rtol=2e-4, atol=2e-4)
    for t in range(8, 12):
        lg, cache = model.apply({"params": params}, tokens[:, t:t + 1],
                                cache=cache)
        np.testing.assert_allclose(np.asarray(lg[:, 0]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-4, atol=2e-4)
    assert int(cache["idx"]) == 12


def test_cached_decode_matches_unrolled_layers(jax_cpu, debug_model):
    """Same contract on the scan_layers=False param layout."""
    import jax.numpy as jnp

    from ray_tpu.models import TransformerLM, init_cache
    cfg, _, _, tokens = debug_model
    cfg2 = dataclasses.replace(cfg, scan_layers=False)
    model = TransformerLM(cfg2)
    params = model.init(jax_cpu.random.PRNGKey(0), tokens)["params"]
    full = model.apply({"params": params}, tokens)
    cache = init_cache(cfg2, 2, 12, dtype=jnp.float32)
    _, cache = model.apply({"params": params}, tokens[:, :5], cache=cache)
    lg = None
    for t in range(5, 12):
        lg, cache = model.apply({"params": params}, tokens[:, t:t + 1],
                                cache=cache)
    np.testing.assert_allclose(np.asarray(lg[:, 0]),
                               np.asarray(full[:, 11]),
                               rtol=2e-4, atol=2e-4)


def test_generate_greedy_matches_stepwise_argmax(jax_cpu, debug_model):
    """make_generate_fn's one-program generation equals a hand loop of
    full forwards + argmax."""
    from ray_tpu.models import make_generate_fn
    from ray_tpu.parallel import MeshConfig, make_mesh
    cfg, model, params, tokens = debug_model
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, seq=1, tensor=1),
                     devices=jax_cpu.devices()[:1])
    B, P, N = 2, 12, 6
    _, gen_fn, _ = make_generate_fn(model, mesh, batch=B, prompt_len=P,
                                    max_new_tokens=N)
    out = np.asarray(gen_fn(params, tokens, jax_cpu.random.PRNGKey(7)))
    # reference: repeated full forwards (no cache), greedy
    cur = np.asarray(tokens)
    want = []
    for _ in range(N):
        logits = model.apply({"params": params},
                             jax_cpu.numpy.asarray(cur))
        nxt = np.asarray(logits[:, -1, :]).argmax(-1)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, np.stack(want, axis=1))


def test_generate_sharded_mesh(jax_cpu):
    """Generation jitted over an fsdp x tensor mesh: sharded params +
    sharded KV cache, replicated output tokens, deterministic greedy."""
    from ray_tpu.models import MODEL_REGISTRY, TransformerLM, \
        make_generate_fn
    from ray_tpu.parallel import MeshConfig, make_mesh
    if len(jax_cpu.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = MODEL_REGISTRY["llama-debug"]
    model = TransformerLM(cfg)
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, seq=1, tensor=2),
                     devices=jax_cpu.devices()[:8])
    B, P, N = 8, 16, 8
    init_fn, gen_fn, _ = make_generate_fn(model, mesh, batch=B,
                                          prompt_len=P, max_new_tokens=N)
    params = init_fn(jax_cpu.random.PRNGKey(0))
    prompt = jax_cpu.random.randint(jax_cpu.random.PRNGKey(1), (B, P), 0,
                                    cfg.vocab_size)
    out = np.asarray(gen_fn(params, prompt, jax_cpu.random.PRNGKey(2)))
    assert out.shape == (B, N)
    assert out.min() >= 0 and out.max() < cfg.vocab_size
    out2 = np.asarray(gen_fn(params, prompt, jax_cpu.random.PRNGKey(9)))
    np.testing.assert_array_equal(out, out2)     # greedy ignores rng
    _, gen_t, _ = make_generate_fn(model, mesh, batch=B, prompt_len=P,
                                   max_new_tokens=N, temperature=1.0)
    a = np.asarray(gen_t(params, prompt, jax_cpu.random.PRNGKey(3)))
    b = np.asarray(gen_t(params, prompt, jax_cpu.random.PRNGKey(4)))
    assert (a != b).any()
