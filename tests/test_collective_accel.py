"""Collective API (store backend) between actors + TPU accelerator
resources/isolation (reference tests: python/ray/util/collective/tests/,
python/ray/tests/accelerators/)."""

import os
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_start():
    os.environ["RAY_TPU_FAKE_CHIPS"] = "4"
    ctx = ray_tpu.init(num_cpus=4, resources={"TPU": 4.0},
                       object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_FAKE_CHIPS", None)


def test_collective_allreduce_between_actors(ray_start):
    @ray_tpu.remote
    class Peer:
        def __init__(self, rank, world):
            from ray_tpu.util import collective
            collective.init_collective_group(world, rank, backend="store",
                                             group_name="g1")
            self.rank = rank

        def do_allreduce(self):
            from ray_tpu.util import collective
            import numpy as np
            out = collective.allreduce(np.ones(8) * (self.rank + 1),
                                       group_name="g1")
            return out

        def do_broadcast(self):
            from ray_tpu.util import collective
            import numpy as np
            return collective.broadcast(np.arange(4) * (self.rank + 10),
                                        src_rank=0, group_name="g1")

    world = 3
    peers = [Peer.remote(r, world) for r in range(world)]
    outs = ray_tpu.get([p.do_allreduce.remote() for p in peers], timeout=60)
    for out in outs:
        np.testing.assert_array_equal(out, np.ones(8) * 6)   # 1+2+3
    outs = ray_tpu.get([p.do_broadcast.remote() for p in peers], timeout=60)
    for out in outs:
        np.testing.assert_array_equal(out, np.arange(4) * 10)


def test_collective_send_recv(ray_start):
    @ray_tpu.remote
    class P2P:
        def __init__(self, rank):
            from ray_tpu.util import collective
            collective.init_collective_group(2, rank, backend="store",
                                             group_name="p2p")
            self.rank = rank

        def run(self):
            from ray_tpu.util import collective
            import numpy as np
            if self.rank == 0:
                collective.send(np.full(4, 7.0), dst_rank=1,
                                group_name="p2p")
                return None
            return collective.recv(src_rank=0, group_name="p2p")

    a, b = P2P.remote(0), P2P.remote(1)
    _, got = ray_tpu.get([a.run.remote(), b.run.remote()], timeout=60)
    np.testing.assert_array_equal(got, np.full(4, 7.0))


def test_tpu_chip_isolation(ray_start):
    @ray_tpu.remote(num_tpus=2)
    def visible():
        import os
        return os.environ.get("TPU_VISIBLE_CHIPS")

    v = ray_tpu.get(visible.remote())
    assert v is not None and len(v.split(",")) == 2


def test_tpu_actor_chips(ray_start):
    @ray_tpu.remote(num_tpus=1)
    class TpuActor:
        def chips(self):
            import os
            return os.environ.get("TPU_VISIBLE_CHIPS")

    actors = [TpuActor.remote() for _ in range(2)]
    got = ray_tpu.get([a.chips.remote() for a in actors], timeout=60)
    assert all(g is not None for g in got)
    assert got[0] != got[1]   # distinct chips


def test_tpu_resource_accounting(ray_start):
    assert ray_tpu.cluster_resources().get("TPU") == 4.0

    @ray_tpu.remote(num_tpus=4)
    def hold():
        import time
        time.sleep(3.0)
        return True

    r = hold.remote()
    # heartbeats propagate availability every ~0.5s
    deadline = time.monotonic() + 2.5
    seen = 4.0
    while time.monotonic() < deadline:
        seen = ray_tpu.available_resources().get("TPU", 0)
        if seen < 4.0:
            break
        time.sleep(0.2)
    assert seen < 4.0
    assert ray_tpu.get(r) is True
