"""Autoscaler loop against the fake provider: unmet demand launches real
local nodes; idle launched nodes terminate (reference hermetic pattern:
python/ray/tests/autoscaler + FakeMultiNodeProvider)."""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (Autoscaler, AutoscalerConfig,
                                FakeMultiNodeProvider)
from ray_tpu.autoscaler.autoscaler import NodeTypeConfig


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_scale_up_then_down(ray_start):
    provider = FakeMultiNodeProvider(ray_tpu.get_gcs_address())
    config = AutoscalerConfig(
        node_types={"cpu4": NodeTypeConfig(resources={"CPU": 4.0},
                                           max_workers=2)},
        idle_timeout_s=4.0)
    scaler = Autoscaler(config, provider)

    @ray_tpu.remote(num_cpus=2)
    def big():
        import time
        time.sleep(3)
        return 1

    # 1-CPU head can't run a 2-CPU task: demand appears in heartbeats
    ref = big.remote()
    launched = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not launched:
        time.sleep(1.0)
        launched = scaler.step()["launched"]
    assert launched == ["cpu4"]
    assert ray_tpu.get(ref, timeout=60) == 1

    # idle node terminates after the timeout
    deadline = time.monotonic() + 40
    terminated = []
    while time.monotonic() < deadline and not terminated:
        time.sleep(1.0)
        terminated = scaler.step()["terminated"]
    assert terminated
    assert provider.non_terminated_nodes() == []
