"""Env-to-module connector pipeline (reference: rllib/connectors/ —
frame stacking and mean/std observation filters between env and module).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import AlgorithmConfig
from ray_tpu.rl.connectors import (FrameStack, NormalizeObs, apply_pipeline,
                                   build_pipeline, pipeline_output_shape)


def test_frame_stack_shapes_and_history():
    fs = FrameStack(k=3)
    assert fs.output_shape((4,)) == (12,)
    o1 = np.ones((2, 4), np.float32)
    fs.reset(o1)
    out = fs(o1)
    assert out.shape == (2, 12)
    o2 = 2 * np.ones((2, 4), np.float32)
    out = fs(o2)
    # newest frame last; history shifts left
    assert np.allclose(out[:, -4:], 2.0) and np.allclose(out[:, :4], 1.0)


def test_normalize_obs_converges():
    norm = NormalizeObs()
    rng = np.random.default_rng(0)
    out = None
    for _ in range(50):
        out = norm(rng.normal(5.0, 2.0, size=(32, 3)).astype(np.float32))
    assert abs(float(out.mean())) < 0.5
    assert 0.5 < float(out.std()) < 1.5


def test_pipeline_build_and_shape():
    specs = [("frame_stack", {"k": 2}), ("normalize_obs", {})]
    assert pipeline_output_shape(specs, (4,)) == (8,)
    pipe = build_pipeline(specs)
    obs = np.ones((3, 4), np.float32)
    out = apply_pipeline(pipe, obs, is_reset=True)
    assert out.shape == (3, 8)
    with pytest.raises(ValueError):
        build_pipeline([("nope", {})])


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_ppo_with_connectors_learns(ray_start):
    """CartPole through frame_stack(2)+normalize: the module input is
    8-dim, batches carry connected obs, and learning still works."""
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(train_batch_size=512, minibatch_size=128,
                        num_epochs=6, lr=3e-4, entropy_coeff=0.01,
                        connectors=(("frame_stack", {"k": 2}),
                                    ("normalize_obs", {}))))
    algo = config.build()
    try:
        assert algo.learner_group.local.module.obs_dim == 8
        best, first = -np.inf, None
        for _ in range(18):
            r = algo.train()["episode_return_mean"]
            if r is None:
                continue
            first = r if first is None else first
            best = max(best, r)
            if best > 80:
                break
        assert best > first + 15 and best > 60, (first, best)
    finally:
        algo.stop()
