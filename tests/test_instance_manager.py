"""Autoscaler v2 instance manager + GCE VM provider (reference:
python/ray/autoscaler/v2/instance_manager/instance_manager.py:29 state
machine; _private/gcp/node_provider.py compute-engine half)."""

import pytest

from ray_tpu.autoscaler.instance_manager import (Instance, InstanceManager,
                                                 Status)
from ray_tpu.autoscaler.node_provider import GceVmNodeProvider


class FakeCloudProvider:
    """In-memory provider whose 'cloud' the test scripts directly."""

    def __init__(self):
        self.cloud = set()
        self.fail_next_create = False
        self.n = 0

    def create_node(self, node_type, resources, labels):
        if self.fail_next_create:
            self.fail_next_create = False
            raise RuntimeError("quota exceeded")
        self.n += 1
        pid = f"vm-{self.n}"
        self.cloud.add(pid)
        return pid

    def terminate_node(self, pid):
        self.cloud.discard(pid)

    def non_terminated_nodes(self):
        return list(self.cloud)


NODE_TYPES = {"cpu_worker": {"resources": {"CPU": 8.0},
                             "labels": {"team": "infra"}}}


def _ray_node(pid):
    return {"node_id": pid, "alive": True, "total": {"CPU": 8.0}}


def test_scale_up_through_states():
    prov = FakeCloudProvider()
    im = InstanceManager(prov, NODE_TYPES)
    im.set_target("cpu_worker", 2)
    acts = im.reconcile([])
    assert len(acts["launched"]) == 2
    sts = [i.status for i in im.instances.values()]
    assert sts.count(Status.REQUESTED) == 2
    # next step: cloud lists them -> ALLOCATED
    im.reconcile([])
    assert all(i.status == Status.ALLOCATED
               for i in im.instances.values())
    # nodes register with the GCS -> RAY_RUNNING
    im.reconcile([_ray_node(i.provider_id)
                  for i in im.instances.values()])
    assert all(i.status == Status.RAY_RUNNING
               for i in im.instances.values())
    # steady state: nothing more to do
    acts = im.reconcile([_ray_node(i.provider_id)
                         for i in im.instances.values()])
    assert acts == {"launched": [], "terminated": [], "failed": []}


def test_allocation_failure_retries_and_history():
    prov = FakeCloudProvider()
    prov.fail_next_create = True
    im = InstanceManager(prov, NODE_TYPES)
    im.set_target("cpu_worker", 1)
    acts = im.reconcile([])
    assert len(acts["failed"]) == 1
    failed = next(i for i in im.instances.values()
                  if i.status == Status.ALLOCATION_FAILED)
    assert any("create failed" in h[2] for h in failed.history)
    # failed instance is terminal; the deficit relaunches a NEW instance
    acts = im.reconcile([])
    assert len(acts["launched"]) == 1
    assert len(im.instances) == 2


def test_vanished_instance_marked_failed():
    prov = FakeCloudProvider()
    im = InstanceManager(prov, NODE_TYPES)
    im.set_target("cpu_worker", 1)
    im.reconcile([])
    im.reconcile([])    # ALLOCATED
    inst = next(iter(im.instances.values()))
    prov.cloud.clear()  # preempted / deleted out of band
    acts = im.reconcile([])
    assert inst.status == Status.ALLOCATION_FAILED
    assert acts["failed"] == [inst.instance_id]


def test_scale_down_prefers_not_yet_running():
    prov = FakeCloudProvider()
    im = InstanceManager(prov, NODE_TYPES)
    im.set_target("cpu_worker", 3)
    im.reconcile([])
    im.reconcile([])            # all ALLOCATED
    insts = list(im.instances.values())
    # only the first registers with ray
    im.reconcile([_ray_node(insts[0].provider_id)])
    assert insts[0].status == Status.RAY_RUNNING
    im.set_target("cpu_worker", 1)
    acts = im.reconcile([_ray_node(insts[0].provider_id)])
    assert len(acts["terminated"]) == 2
    assert insts[0].status == Status.RAY_RUNNING   # survivor = running one
    # delete confirmed next step
    im.reconcile([_ray_node(insts[0].provider_id)])
    sts = sorted(i.status for i in im.instances.values())
    assert sts.count(Status.TERMINATED) == 2
    assert im.summary()["cpu_worker"][Status.RAY_RUNNING] == 1


class FakeGceApi:
    def __init__(self):
        self.instances = {}
        self.calls = []

    def __call__(self, method, path, body=None):
        self.calls.append((method, path))
        if method == "POST":
            assert body["machineType"].endswith("n2-standard-8")
            assert body["labels"]["ray-tpu-node-type"] == "cpu-worker"
            assert "startup-script" in body["metadata"]["items"][0]["key"]
            self.instances[body["name"]] = "PROVISIONING"
            return {}
        if method == "GET":
            return {"items": [{"name": n, "status": st}
                              for n, st in self.instances.items()]}
        if method == "DELETE":
            self.instances.pop(path.rsplit("/", 1)[1], None)
            return {}
        raise AssertionError(method)


def test_gce_vm_provider_lifecycle():
    api = FakeGceApi()
    p = GceVmNodeProvider("proj", "us-central1-a", "10.0.0.1:6379", api=api)
    name = p.create_node("cpu_worker", {"CPU": 8}, {"team": "ml"})
    assert name in api.instances
    assert p.non_terminated_nodes() == [name]
    api.instances[name] = "RUNNING"
    assert p.non_terminated_nodes() == [name]
    api.instances[name] = "TERMINATED"   # preempted
    assert p.non_terminated_nodes() == []
    p.terminate_node(name)
    assert name not in api.instances


def test_instance_manager_with_gce_provider_end_to_end():
    api = FakeGceApi()
    p = GceVmNodeProvider("proj", "us-central1-a", "10.0.0.1:6379", api=api)
    im = InstanceManager(p, NODE_TYPES)
    im.set_target("cpu_worker", 2)
    im.reconcile([])
    assert len(api.instances) == 2
    for n in api.instances:
        api.instances[n] = "RUNNING"
    im.reconcile([])
    assert im.summary()["cpu_worker"][Status.ALLOCATED] == 2
    im.set_target("cpu_worker", 0)
    im.reconcile([])
    im.reconcile([])
    assert not api.instances
    assert im.summary()["cpu_worker"][Status.TERMINATED] == 2
