"""Runtime-env plugin protocol + container env kind (reference:
python/ray/_private/runtime_env/plugin.py — plugin-dispatched setup;
runtime_env/image_uri.py — worker under `podman run`).

The container e2e runs against a stub container runtime (a script that
parses `podman run` flags, applies --env, and execs the worker command)
injected via RAY_TPU_CONTAINER_RUNTIME — the standard way to test
container integration without a container daemon: every line of OUR
plumbing (lease proc_env, worker-pool isolation, spawn wrapper, env
forwarding) runs for real; only the containerization syscall layer is
simulated."""

import os
import stat
import sys
import textwrap

import pytest

import ray_tpu
from ray_tpu._private.runtime_env_plugins import (RuntimeEnvContext,
                                                  apply_worker_plugins,
                                                  container_command,
                                                  proc_env_of,
                                                  register_plugin,
                                                  unregister_plugin)

pytestmark = pytest.mark.slow


# ------------------------------------------------------------ unit tests
def test_proc_env_extraction():
    assert proc_env_of(None) is None
    assert proc_env_of({"pip": ["x"]}) is None
    assert proc_env_of({"container": "img:1"}) == \
        {"container": {"image": "img:1"}}
    assert proc_env_of({"image_uri": "img:2"}) == \
        {"container": {"image": "img:2"}}
    assert proc_env_of({"container": {"image": "img:3",
                                      "run_options": ["--gpus all"]}}) \
        == {"container": {"image": "img:3", "run_options": ["--gpus all"]}}


def test_container_command_shape():
    cmd = container_command(
        {"container": {"image": "img:1", "run_options": ["--shm-size 1g"]}},
        ["python", "-m", "w"],
        {"RAY_TPU_NODE_ID": "n1", "HOME": "/root", "PATH": "/usr/bin"})
    runtime = os.environ.get("RAY_TPU_CONTAINER_RUNTIME", "podman")
    assert cmd[0] == runtime and cmd[1] == "run"
    assert "--network=host" in cmd and "--rm" in cmd
    assert "-v" in cmd
    assert "--env" in cmd
    envs = [cmd[i + 1] for i, a in enumerate(cmd) if a == "--env"]
    assert "RAY_TPU_NODE_ID=n1" in envs
    assert not any(e.startswith("HOME=") for e in envs)   # no host leakage
    img = cmd.index("img:1")
    assert cmd[img - 2:img] == ["--shm-size", "1g"]
    assert cmd[img + 1:] == ["python", "-m", "w"]


def test_plugin_priority_and_dispatch():
    calls = []

    class A:
        name, priority = "aaa", 60

        def setup(self, value, renv, ctx, worker):
            calls.append(("aaa", value))

    class B:
        name, priority = "bbb", 1

        def setup(self, value, renv, ctx, worker):
            calls.append(("bbb", value))
            ctx.env_vars["BBB"] = str(value)

    register_plugin(A())
    register_plugin(B())
    try:
        ctx = apply_worker_plugins({"aaa": 1, "bbb": 2, "unknown": 3},
                                   worker=None)
        assert calls == [("bbb", 2), ("aaa", 1)]   # priority order
        assert ctx.env_vars["BBB"] == "2"
        assert isinstance(ctx, RuntimeEnvContext)
    finally:
        unregister_plugin("aaa")
        unregister_plugin("bbb")


# ------------------------------------------------------------- e2e tests
@pytest.fixture()
def plugin_cluster(tmp_path, monkeypatch):
    """Cluster whose workers load the TokenPlugin and whose node manager
    spawns container workers through the stub runtime."""
    stub = tmp_path / "fake-podman"
    stub.write_text(textwrap.dedent(f"""\
        #!{sys.executable}
        import os, sys
        args = sys.argv[1:]
        assert args[0] == "run", args
        i, envs, mounts = 1, [], []
        while i < len(args):
            a = args[i]
            if a in ("--rm", "--network=host"):
                i += 1
            elif a == "-v":
                mounts.append(args[i + 1]); i += 2
            elif a == "--env":
                envs.append(args[i + 1]); i += 2
            else:
                break
        image, cmd = args[i], args[i + 1:]
        for e in envs:
            k, _, v = e.partition("=")
            os.environ[k] = v
        os.environ["IN_FAKE_CONTAINER"] = image
        os.environ["FAKE_MOUNTS"] = ";".join(mounts)
        os.execvp(cmd[0], cmd)
        """))
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("RAY_TPU_CONTAINER_RUNTIME", str(stub))
    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_PLUGINS",
                       "ray_tpu.util.testing_plugins:TokenPlugin")
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_user_plugin_dispatched_in_worker(plugin_cluster):
    @ray_tpu.remote
    def probe():
        return (os.environ.get("TOKEN_PLUGIN_VALUE"),
                os.environ.get("TOKEN_PLUGIN_SAW_KEYS"),
                os.environ.get("EXPLICIT"))

    got = ray_tpu.get(probe.options(runtime_env={
        "token": "t-42", "env_vars": {"EXPLICIT": "yes"}}).remote(),
        timeout=60)
    assert got == ("t-42", "env_vars,token", "yes")
    # restored after the task: a plain task on the same pool sees nothing
    got2 = ray_tpu.get(probe.remote(), timeout=60)
    assert got2 == (None, None, None)


def test_container_worker_e2e(plugin_cluster):
    @ray_tpu.remote
    def where():
        return (os.environ.get("IN_FAKE_CONTAINER"), os.getpid(),
                os.environ.get("FAKE_MOUNTS"))

    image, pid_c, mounts = ray_tpu.get(
        where.options(runtime_env={"container": {"image": "tpu/img:9"}})
        .remote(), timeout=120)
    assert image == "tpu/img:9"
    assert "/tmp/raytpu:/tmp/raytpu" in (mounts or "")
    # plain tasks stay on uncontained workers (pool isolation both ways)
    image2, pid_p, _ = ray_tpu.get(where.remote(), timeout=60)
    assert image2 is None and pid_p != pid_c
    # same container env reuses the pooled containered worker
    image3, pid_c2, _ = ray_tpu.get(
        where.options(runtime_env={"container": {"image": "tpu/img:9"}})
        .remote(), timeout=120)
    assert image3 == "tpu/img:9" and pid_c2 == pid_c
    # a different image is a different process
    image4, pid_c3, _ = ray_tpu.get(
        where.options(runtime_env={"container": {"image": "tpu/img:10"}})
        .remote(), timeout=120)
    assert image4 == "tpu/img:10" and pid_c3 not in (pid_c, pid_p)


def test_container_actor_e2e(plugin_cluster):
    @ray_tpu.remote
    class Boxed:
        def image(self):
            return os.environ.get("IN_FAKE_CONTAINER")

    a = Boxed.options(
        runtime_env={"container": {"image": "tpu/actor-img:1"}}).remote()
    assert ray_tpu.get(a.image.remote(), timeout=120) == "tpu/actor-img:1"
    ray_tpu.kill(a)
