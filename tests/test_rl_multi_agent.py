"""Multi-agent PPO + offline BC (reference: multi_agent_env_runner.py,
rllib/offline/). The toy cooperative env rewards both agents when they
pick matching actions — learnable only if each policy adapts to the
other's behavior through the shared reward."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl.multi_agent import (MultiAgentConfig, MultiAgentEnv,
                                    MultiAgentPPO)


class _Box:
    def __init__(self, shape):
        self.shape = shape


class _Disc:
    def __init__(self, n):
        self.n = n


class MatchEnv(MultiAgentEnv):
    """Two agents each see one random bit; +1 to both when their actions
    agree with the OTHER agent's observed bit (cooperative coordination)."""

    agents = ["a0", "a1"]

    def __init__(self, episode_len=16):
        self._len = episode_len
        self._t = 0
        self._rng = np.random.default_rng(0)
        self._bits = None

    def observation_space(self, agent_id):
        return _Box((2,))

    def action_space(self, agent_id):
        return _Disc(2)

    def _obs(self):
        # each agent sees its own bit one-hot; the optimal policy copies
        # its own bit (reward checks action == own bit)
        return {aid: np.eye(2, dtype=np.float32)[self._bits[i]]
                for i, aid in enumerate(self.agents)}

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._bits = self._rng.integers(0, 2, size=2)
        return self._obs(), {}

    def step(self, actions):
        rew_each = float(actions["a0"] == self._bits[0]) \
            + float(actions["a1"] == self._bits[1])
        rewards = {aid: rew_each / 2.0 for aid in self.agents}
        self._t += 1
        self._bits = self._rng.integers(0, 2, size=2)
        done = self._t >= self._len
        terms = {aid: done for aid in self.agents}
        terms["__all__"] = done
        truncs = {"__all__": False}
        return self._obs(), rewards, terms, truncs, {}


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_multi_agent_ppo_learns_cooperative_env(ray_start):
    algo = MultiAgentPPO(MultiAgentConfig(
        env_maker=MatchEnv,
        policy_mapping_fn=lambda aid: aid,      # independent policies
        num_env_runners=2, rollout_fragment_length=64,
        num_epochs=4, minibatch_size=64, lr=3e-3, entropy_coeff=0.0))
    assert sorted(algo.learners) == ["a0", "a1"]
    first = None
    result = None
    for _ in range(12):
        result = algo.train()
        if first is None and result["episode_return_mean"] is not None:
            first = result["episode_return_mean"]
    # perfect coordination = 16 steps * 1.0; random ~8. Require clear
    # improvement over the starting return
    assert result["episode_return_mean"] is not None
    assert result["episode_return_mean"] > first + 2.0, \
        (first, result["episode_return_mean"])


def test_multi_agent_shared_policy(ray_start):
    algo = MultiAgentPPO(MultiAgentConfig(
        env_maker=MatchEnv,
        policy_mapping_fn=lambda aid: "shared",
        num_env_runners=1, rollout_fragment_length=32, num_epochs=2,
        minibatch_size=32))
    assert list(algo.learners) == ["shared"]
    out = algo.training_step()
    assert "shared" in out


def test_bc_trains_from_recorded_dataset(ray_start):
    import ray_tpu.data as rd
    from ray_tpu.rl.offline import BC, BCConfig, record_experiences

    # expert on CartPole-ish synthetic: obs 4-dim random, action = obs[0]>0
    rng = np.random.default_rng(1)
    rows = [{"obs": (o := rng.standard_normal(4).astype(np.float32)).tolist(),
             "action": int(o[0] > 0), "reward": 1.0, "done": False}
            for _ in range(2000)]
    ds = rd.from_items(rows)
    bc = BC(BCConfig(dataset=ds, obs_dim=4, action_dim=2,
                     num_epochs=4, lr=5e-3))
    for _ in range(3):
        out = bc.train()
    assert out["loss"] is not None and out["loss"] < 0.3
    acc = bc.action_accuracy()
    assert acc > 0.9, acc
