"""Streaming deployment responses (reference: serve streaming handles —
DeploymentResponseGenerator): generator methods stream chunks over the
core streaming-generator protocol (ObjectRefGenerator items with
backpressure); errors mid-stream surface to the consumer with their
original type."""

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=4)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


def test_streaming_handle(ray_start):
    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                yield {"token": i}

        def fail_midway(self, n):
            for i in range(n):
                if i == 3:
                    raise ValueError("midstream boom")
                yield i

    serve.run(Streamer.bind(), name="stream-app")
    h = serve.get_app_handle("stream-app").options(stream=True)
    chunks = list(h.remote(5))
    assert chunks == [{"token": i} for i in range(5)]

    gen = h.fail_midway.remote(10)
    got = []
    with pytest.raises(ValueError, match="midstream boom"):
        for c in gen:
            got.append(c)
    assert got == [0, 1, 2]
