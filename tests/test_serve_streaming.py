"""Streaming deployment responses (reference: serve streaming handles —
DeploymentResponseGenerator): generator methods stream chunks over the
core streaming-generator protocol (ObjectRefGenerator items with
backpressure); errors mid-stream surface to the consumer with their
original type. Cancellation: a client that drops/closes the iterator
mid-generation must run the replica-side generator's finally path NOW
(freeing inference-engine slots etc.), and a replica killed mid-stream
must come back with a clean slot pool."""

import sys
import time

import pytest

import ray_tpu
from ray_tpu import serve

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=4)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


@needs_cluster
def test_streaming_handle(ray_start):
    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                yield {"token": i}

        def fail_midway(self, n):
            for i in range(n):
                if i == 3:
                    raise ValueError("midstream boom")
                yield i

    serve.run(Streamer.bind(), name="stream-app")
    h = serve.get_app_handle("stream-app").options(stream=True)
    chunks = list(h.remote(5))
    assert chunks == [{"token": i} for i in range(5)]

    gen = h.fail_midway.remote(10)
    got = []
    with pytest.raises(ValueError, match="midstream boom"):
        for c in gen:
            got.append(c)
    assert got == [0, 1, 2]


# --------------------------------------------------------------------------
# cancellation: replica-side finally must run when the client walks away
# --------------------------------------------------------------------------

class _Tracker:
    """Counts generator entry/exit so tests can see whether the
    replica-side finally ran."""

    def __init__(self):
        self.active = 0
        self.closed = 0

    def stream(self, n):
        self.active += 1
        try:
            for i in range(n):
                yield i
        finally:
            self.active -= 1
            self.closed += 1

    def state(self):
        return (self.active, self.closed)


def _tiny_llm_config():
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig
    return TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=512, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)


def test_replica_stream_close_runs_user_finally():
    """No cluster needed: closing the replica's handle_stream generator
    mid-iteration must close the USER generator (GeneratorExit through
    its finally) and release the ongoing count."""
    import cloudpickle

    from ray_tpu.serve.replica import Replica
    r = Replica(cloudpickle.dumps(_Tracker), (), {}, False)
    g = r.handle_stream("stream", (1000,), {})
    assert next(g) == 0
    assert next(g) == 1
    assert r.handle_request("state", (), {}) == (1, 0)
    g.close()
    assert r.handle_request("state", (), {}) == (0, 1)
    assert r.get_queue_len() == 0


def test_llm_deployment_generator_exit_frees_slot():
    """No cluster needed: dropping LLMDeployment's streaming generator
    mid-generation cancels the engine request — the slot returns to the
    pool and the queue drains (the contract the Serve path relies on)."""
    from ray_tpu.inference import LLMDeployment
    dep = LLMDeployment(_tiny_llm_config(), n_slots=2, max_len=256,
                        prefill_chunk=8, prefill_budget=16)
    try:
        gen = dep([1, 2, 3, 4], max_new_tokens=200)
        got = [next(gen) for _ in range(3)]
        assert len(got) == 3
        assert dep.stats()["slots_occupied"] == 1
        gen.close()                      # GeneratorExit -> cancel
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            st = dep.stats()
            if st["slots_free"] == 2 and st["queue_depth"] == 0:
                break
            time.sleep(0.02)
        st = dep.stats()
        assert st["slots_free"] == 2 and st["queue_depth"] == 0, st
        # the slot is immediately reusable
        assert len(dep.generate([5, 6], max_new_tokens=4)) == 4
        assert dep.stats()["decode_compile_count"] == 1
    finally:
        dep.engine.stop()


@needs_cluster
def test_stream_cancellation_frees_slot_over_serve(ray_start):
    """Client drops a Serve streaming iterator mid-generation: the
    engine slot frees and the queue metrics decrement."""
    from ray_tpu.inference import LLMDeployment
    dep = serve.deployment(LLMDeployment)
    serve.run(dep.bind(_tiny_llm_config(), n_slots=2, max_len=512,
                       prefill_chunk=8, prefill_budget=16),
              name="llm-cancel")
    h = serve.get_app_handle("llm-cancel")
    stream = h.options(stream=True)
    gen = stream.remote([1, 2, 3, 4], max_new_tokens=400)
    got = []
    for tok in gen:
        got.append(tok)
        if len(got) >= 3:
            break
    gen.close()                          # client walks away mid-stream
    deadline = time.monotonic() + 30
    st = {}
    while time.monotonic() < deadline:
        st = h.stats.remote().result()
        if st["slots_free"] == st["n_slots"] and st["queue_depth"] == 0:
            break
        time.sleep(0.2)
    assert st.get("slots_free") == st.get("n_slots"), st
    assert st.get("queue_depth") == 0, st
    # engine still healthy: a fresh request completes
    out = list(stream.remote([9, 8, 7], max_new_tokens=5))
    assert len(out) == 5
    serve.delete("llm-cancel")


@needs_cluster
def test_kill_replica_mid_stream_reclaims_slots(ray_start):
    """Chaos: a replica killed mid-stream is replaced by the controller
    and the replacement's slot pool is fully free (no leaked slots from
    the severed stream); serving resumes."""
    from ray_tpu.inference import LLMDeployment
    from ray_tpu.util.chaos import ServeReplicaKiller
    dep = serve.deployment(LLMDeployment)
    serve.run(dep.bind(_tiny_llm_config(), n_slots=2, max_len=512,
                       prefill_chunk=8, prefill_budget=16),
              name="llm-chaos")
    h = serve.get_app_handle("llm-chaos")
    gen = h.options(stream=True).remote([1, 2, 3, 4], max_new_tokens=400)
    got = [next(gen) for _ in range(2)]
    assert len(got) == 2
    killer = ServeReplicaKiller("llm-chaos", "LLMDeployment")
    assert killer.kill_one()
    # the severed stream surfaces an error (type depends on where the
    # death lands: mid-item vs between items)
    with pytest.raises(Exception):
        for _ in range(1000):
            next(gen)
    assert killer.wait_for_replacement(timeout_s=90)
    deadline = time.monotonic() + 60
    st = {}
    while time.monotonic() < deadline:
        try:
            st = h.stats.remote().result()
            if st.get("slots_free") == st.get("n_slots"):
                break
        except Exception:
            pass
        time.sleep(0.5)
    assert st.get("slots_free") == st.get("n_slots"), st
    out = list(h.options(stream=True).remote([5, 6], max_new_tokens=4))
    assert len(out) == 4
    serve.delete("llm-chaos")
