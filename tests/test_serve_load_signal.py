"""Shared load signal for handle routing: the controller probes replica
queue depths and pushes them to every router, so a FRESH handle (zero
local in-flight knowledge) avoids a replica another handle has already
buried (reference: pow-2 scheduler queue-length probes,
_private/replica_scheduler/pow_2_scheduler.py:52; round-3 weakness #6 —
client-local counts degrade toward random with many handles and dogpile
cold replicas)."""

import time
import uuid

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_session():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@serve.deployment(num_replicas=2, max_ongoing_requests=16)
class Worker:
    def __init__(self):
        self.uid = uuid.uuid4().hex[:8]

    def __call__(self, payload):
        return self.uid

    def slow(self, t):
        time.sleep(t)
        return self.uid


def test_fresh_handle_avoids_buried_replica(serve_session):
    handle1 = serve.run(Worker.bind(), name="loadsig")
    # bury ONE replica via sticky multiplex routing: every slow call with
    # the same model id pins to the replica that served it first
    sticky = handle1.options(multiplexed_model_id="pin")
    slow_calls = [sticky.slow.remote(20.0) for _ in range(6)]
    time.sleep(1.0)
    busy_uid = sticky.remote("probe").result(timeout=30)

    # wait for the controller's load probe to publish nonzero depths
    deadline = time.time() + 15
    while time.time() < deadline:
        handle1._router.refresh(force=True)
        if any(v >= 5 for v in handle1._router.shared_load.values()):
            break
        time.sleep(0.5)
    else:
        pytest.fail(
            f"controller never published loads: "
            f"{handle1._router.shared_load}")

    # a FRESH handle has no local in-flight history; only the shared
    # signal can warn it off the buried replica
    handle2 = serve.get_app_handle("loadsig")
    assert handle2._router is not handle1._router
    uids = [handle2.remote("x").result(timeout=30) for _ in range(10)]
    n_busy = sum(1 for u in uids if u == busy_uid)
    # client-local P2C would send ~5/10 into the 20s queue; the shared
    # signal must keep nearly all of them on the idle replica
    assert n_busy <= 2, (f"{n_busy}/10 requests dogpiled the buried "
                         f"replica (busy={busy_uid}, uids={uids})")
    for c in slow_calls:
        del c


def test_shared_load_included_in_info(serve_session):
    handle = serve.run(Worker.bind(), name="loadsig2",
                       route_prefix="/loadsig2")
    handle.remote("x").result(timeout=30)
    info = ray_tpu.get(
        serve.api._get_controller().get_deployment_info.remote(
            "loadsig2", "Worker"), timeout=30)
    assert "loads" in info and isinstance(info["loads"], list)
