"""Weight-distribution plane: spanning-stripe arena allocation composed
with the tree-relay `ray_tpu.broadcast_weights()` (shm_store.cpp spans +
data_plane.py planning/striping + node_manager relay + worker retry).

Unit tier (any interpreter): binomial fan-out planning, rebroadcast
sharding across surviving holders, adaptive stream counts for
weight-sized transfers, a weight-sized loopback push through a real
DataPlaneServer/Client pair, relay-subtree failure surfacing at the
root's ack, and the runner-set broadcast helper's fallback. The cluster
tier needs the Python 3.12 store runtime like every other multi-node
suite."""

import asyncio
import sys
import time

import pytest

from ray_tpu._private import data_plane as dp
from ray_tpu._private.config import cfg

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")

OID = b"\x07" * 20


# --------------------------------------------------------- fan-out planning

def test_binomial_split_covers_every_target_once():
    for n in range(0, 33):
        targets = [f"n{i}" for i in range(n)]
        plan = dp.binomial_split(targets)
        seen = [h for h, _rest in plan]
        for _h, rest in plan:
            seen.extend(rest)
        assert sorted(seen) == sorted(targets)
        assert len(set(seen)) == len(seen)    # nobody pushed twice


def test_binomial_split_source_sends_log_n_copies():
    # the source's direct pushes (plan length) stay O(log n)
    plan = dp.binomial_split([f"n{i}" for i in range(64)])
    assert len(plan) == 7      # ceil(log2(64)) + 1
    assert dp.binomial_split([]) == []
    assert dp.binomial_split(["a"]) == [("a", [])]
    # two targets: both direct (no relay hop for a pair)
    assert dp.binomial_split(["a", "b"]) == [("a", []), ("b", [])]


def test_binomial_split_delegates_half():
    plan = dp.binomial_split([f"n{i}" for i in range(8)])
    # first head carries the other 3 nodes of its half as relay
    assert plan[0] == ("n0", ["n1", "n2", "n3"])


def test_plan_rebroadcast_shards_across_survivors():
    missing = [f"m{i}" for i in range(7)]
    holders = ["h0", "h1", "h2"]
    plan = dp.plan_rebroadcast(missing, holders)
    assigned = [t for _h, tgts in plan for t in tgts]
    assert sorted(assigned) == sorted(missing)
    used = {h for h, _t in plan}
    assert used <= set(holders)
    # round-robin: no holder is more than one target heavier
    sizes = [len(t) for _h, t in plan]
    assert max(sizes) - min(sizes) <= 1


def test_plan_rebroadcast_edge_cases():
    assert dp.plan_rebroadcast([], ["h"]) == []
    assert dp.plan_rebroadcast(["m"], []) == []
    assert dp.plan_rebroadcast(["m"], ["", None]) == []
    assert dp.plan_rebroadcast(["m1", "m2"], ["h"]) == [("h", ["m1", "m2"])]


# ------------------------------------------------------- adaptive streaming

@pytest.fixture()
def _stream_knobs():
    cfg.set("transfer_streams", 2)
    cfg.set("transfer_streams_large", 8)
    cfg.set("transfer_large_object_bytes", 1 << 20)
    yield
    for k in ("transfer_streams", "transfer_streams_large",
              "transfer_large_object_bytes"):
        cfg.reset(k)


def test_adaptive_streams_boundaries(_stream_knobs):
    threshold = 1 << 20
    assert dp.adaptive_streams(0) == 2
    assert dp.adaptive_streams(threshold - 1) == 2
    assert dp.adaptive_streams(threshold) == 8       # at the boundary
    assert dp.adaptive_streams(threshold + 1) == 8
    assert dp.adaptive_streams(100 * threshold) == 8


def test_adaptive_streams_escalation_disabled(_stream_knobs):
    # large <= default disables the escalation entirely
    cfg.set("transfer_streams_large", 2)
    assert dp.adaptive_streams(1 << 30) == 2
    cfg.set("transfer_streams_large", 1)
    assert dp.adaptive_streams(1 << 30) == 2


def test_adaptive_stripe_ranges_compose(_stream_knobs):
    # a weight-sized object fans out across the large stream count, but
    # never below stripe_min bytes per stream
    size = 8 << 20
    ranges = dp.stripe_ranges(size, dp.adaptive_streams(size), 1 << 20)
    assert len(ranges) == 8
    assert sum(length for _o, length in ranges) == size
    small = 512 * 1024
    assert len(dp.stripe_ranges(small, dp.adaptive_streams(small),
                                1 << 20)) == 1


# -------------------------------------------- loopback weight-sized pushes

class FakeNM:
    """Duck-typed stand-in for NodeManager receive bookkeeping (the
    data-plane server only touches `_receiving`, `_finish_receive`,
    `_abort_receive`)."""

    def __init__(self):
        self._receiving = {}
        self.finished = []
        self.aborted = []
        self.relay_result = True

    def begin(self, oid: bytes, size: int) -> bytearray:
        buf = bytearray(size)
        self._receiving[oid] = {"data": memoryview(buf), "remaining": size,
                                "relay": [], "t": time.monotonic()}
        return buf

    def _finish_receive(self, oid: bytes):
        self._receiving.pop(oid)
        self.finished.append(oid)
        return self.relay_result

    def _abort_receive(self, oid: bytes, reason: str):
        self._receiving.pop(oid, None)
        self.aborted.append((oid, reason))


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_weight_sized_push_uses_large_stream_count(_stream_knobs):
    """A payload over the large-object threshold stripes across
    transfer_streams_large raw connections and lands byte-exact."""
    cfg.set("transfer_chunk_bytes", 256 * 1024)
    cfg.set("transfer_stripe_min_bytes", 128 * 1024)
    payload = bytes(range(256)) * (2 * 1024 * 1024 // 256)  # 2 MB >= 1 MB

    async def go():
        nm = FakeNM()
        server = dp.DataPlaneServer(nm)
        addr = await server.start("127.0.0.1")
        client = dp.DataPlaneClient()
        try:
            buf = nm.begin(OID, len(payload))
            stripes = await client.push(addr, OID, memoryview(payload),
                                        len(payload))
            assert len(stripes) == 8      # escalated, not the default 2
            assert sum(stripes) == len(payload)
            assert bytes(buf) == payload
            assert nm.finished == [OID]
        finally:
            client.close()
            await server.close()

    try:
        _run(go())
    finally:
        for k in ("transfer_chunk_bytes", "transfer_stripe_min_bytes"):
            cfg.reset(k)


def test_relay_subtree_failure_surfaces_at_root_ack(_stream_knobs):
    """The completing chunk's ack defers past the receiver's relay
    subtree; a failed subtree turns into FINISH_FAILED and the pusher
    (broadcast root) sees a DataPlaneError — partial delivery is never
    silent."""
    payload = b"w" * (256 * 1024)

    async def go():
        nm = FakeNM()

        async def failing_relay():
            raise RuntimeError("relay node died mid-subtree")

        server = dp.DataPlaneServer(nm)
        addr = await server.start("127.0.0.1")
        client = dp.DataPlaneClient()
        try:
            nm.begin(OID, len(payload))
            nm.relay_result = asyncio.ensure_future(failing_relay())
            with pytest.raises(dp.DataPlaneError):
                await client.push(addr, OID, memoryview(payload),
                                  len(payload))
        finally:
            client.close()
            await server.close()

    _run(go())


# ------------------------------------------------- runner-set weight push

def test_runner_set_broadcast_falls_back_to_put(monkeypatch):
    """Driver loops keep training when the broadcast plane is
    unavailable: the helper degrades to a plain put (runners then pull
    point-to-point as before)."""
    import ray_tpu
    from ray_tpu.rl.actor_manager import FaultTolerantRunnerSet

    rs = FaultTolerantRunnerSet(lambda i: object(), 0)
    calls = {}

    def boom(weights, node_ids=None, **kw):
        calls["broadcast"] = weights
        raise RuntimeError("no cluster")

    def fake_put(v):
        calls["put"] = v
        return "REF"

    monkeypatch.setattr(ray_tpu, "broadcast_weights", boom)
    monkeypatch.setattr(ray_tpu, "put", fake_put)
    out = rs.broadcast_weights({"w": 1})
    assert calls["broadcast"] == {"w": 1}
    assert calls["put"] == {"w": 1}
    assert out == "REF"


def test_runner_set_broadcast_prefers_plane(monkeypatch):
    import ray_tpu
    from ray_tpu.rl.actor_manager import FaultTolerantRunnerSet

    rs = FaultTolerantRunnerSet(lambda i: object(), 0)
    monkeypatch.setattr(ray_tpu, "broadcast_weights",
                        lambda w, node_ids=None, **kw: ("REF", w))
    monkeypatch.setattr(
        ray_tpu, "put",
        lambda v: (_ for _ in ()).throw(AssertionError("put used")))
    assert rs.broadcast_weights({"w": 2}) == ("REF", {"w": 2})


# ------------------------------------------ checkpoint broadcast restore

def test_restore_from_broadcast_places_leaves(monkeypatch):
    np = pytest.importorskip("numpy")
    jax = pytest.importorskip("jax")
    import ray_tpu
    from ray_tpu.train import sharded_checkpoint as sc

    tree = {"w": np.ones((4,), np.float32), "b": np.zeros((2,), np.float32)}
    monkeypatch.setattr(ray_tpu, "get", lambda ref: tree)
    # no abstract tree: the raw host arrays come back as-is
    out = sc.restore_from_broadcast("ref")
    assert out is tree
    # with an abstract tree the leaves are cast/placed per-host
    abstract = {"w": jax.ShapeDtypeStruct((4,), "bfloat16"),
                "b": jax.ShapeDtypeStruct((2,), "float32")}
    placed = sc.restore_from_broadcast("ref", abstract)
    assert placed["w"].dtype == jax.numpy.bfloat16
    assert placed["b"].dtype == jax.numpy.float32


# ----------------------------------------------------------- cluster tier

@needs_cluster
def test_broadcast_weights_cluster_delivery_and_arrivals():
    """256 KB blob (small for CI; the spanning path has native selftest
    + store-level coverage) reaches every node via the relay tree; each
    receiver records a store.broadcast.arrival instant with bytes."""
    import numpy as np

    import ray_tpu
    import ray_tpu._private.worker as wm
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    targets = [cluster.add_node(num_cpus=1) for _ in range(3)]
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes()
        blob = np.arange(256 * 1024, dtype=np.uint8)
        ref = ray_tpu.broadcast_weights(blob)
        view = wm.global_worker.gcs_call("get_cluster_view")
        for t in targets:
            r = wm.global_worker._run(wm.global_worker.core.pool.call(
                view[t.node_id]["address"], "has_object", oid=ref.id))
            assert r["in_store"]
        deadline = time.monotonic() + 30
        arrivals = []
        while time.monotonic() < deadline and len(arrivals) < 3:
            rows = wm.global_worker.gcs_call(
                "list_task_events", kind="runtime_event", limit=20000)
            arrivals = [r for r in rows
                        if r.get("name") == "store.broadcast.arrival"
                        and (r.get("attrs") or {}).get("object_id")
                        == ref.id.hex()[:16]]
            time.sleep(0.5)
        assert len(arrivals) >= 3
        assert all((a.get("attrs") or {}).get("bytes") == blob.nbytes
                   for a in arrivals)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@needs_cluster
def test_broadcast_weights_retries_via_surviving_holders(monkeypatch):
    """Relay-death chaos: every relay-carrying push fails (the interior
    of the tree dies), the root's await surfaces the subtree failure,
    and the retry delivers the missing nodes from the surviving holders
    — exactly-once everywhere, retries observable in the result."""
    import numpy as np

    import ray_tpu
    import ray_tpu._private.worker as wm
    from ray_tpu._private import rpc
    from ray_tpu.util.chaos import BroadcastRelayKiller

    killer = BroadcastRelayKiller(probability=1.0)
    monkeypatch.setenv(killer.SPEC_ENV, killer.spec())
    rpc._CHAOS_SPEC = None      # re-parse the spec in THIS process
    from ray_tpu.cluster_utils import Cluster
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    targets = [cluster.add_node(num_cpus=1) for _ in range(3)]
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes()
        blob = np.ones(128 * 1024, dtype=np.uint8)
        ref = ray_tpu.put(blob)
        res = wm.global_worker.broadcast_weights(
            ref, [t.node_id for t in targets], max_retries=3)
        assert res["retries"] >= 1
        view = wm.global_worker.gcs_call("get_cluster_view")
        for t in targets:
            r = wm.global_worker._run(wm.global_worker.core.pool.call(
                view[t.node_id]["address"], "has_object", oid=ref.id))
            assert r["in_store"]
    finally:
        rpc._CHAOS_SPEC = None
        ray_tpu.shutdown()
        cluster.shutdown()
