"""Daemon lifetime invariants (round-4 fix: 131 processes survived a
green suite).

Three layers under test:
1. PR_SET_PDEATHSIG — a SIGKILLed driver reaps its GCS + node manager +
   workers (reference: worker processes die with the raylet via the
   socket + the raylet dies with the GCS via
   gcs_rpc_server_reconnect_timeout_s).
2. SIGTERM on a node manager reaps its worker pool before exiting
   (reference: NodeManager::Stop kills registered workers).
3. A node manager whose GCS stays unreachable past
   cfg.gcs_reconnect_timeout_s exits instead of retrying forever
   (reference: src/ray/raylet/main.cc:123 shutdown path).
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest


def _pids_alive(pids):
    """Live (non-zombie) pids. A daemon our own process spawned shows up
    as a zombie until wait()ed — that's 'exited' for lifetime purposes."""
    out = []
    for p in pids:
        try:
            with open(f"/proc/{p}/stat") as f:
                state = f.read().rsplit(")", 1)[1].split()[0]
            if state != "Z":
                out.append(p)
        except OSError:
            pass
    return out


def _wait_gone(pids, timeout=20):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = _pids_alive(pids)
        if not alive:
            return []
        time.sleep(0.25)
    return alive


DRIVER = textwrap.dedent("""
    import os, sys, time
    import ray_tpu
    ray_tpu.init(num_cpus=1, object_store_memory=32*1024*1024)

    @ray_tpu.remote
    def pid():
        return os.getpid()

    wpid = ray_tpu.get(pid.remote(), timeout=60)
    node = ray_tpu._context.node            # LocalNode handle
    print("GCS_PID", node.gcs_handle.proc.pid, flush=True)
    print("NM_PID", node.nm_handle.proc.pid, flush=True)
    print("W_PID", wpid, flush=True)
    print("READY", flush=True)
    time.sleep(600)
""")


def test_sigkilled_driver_reaps_whole_tree():
    proc = subprocess.Popen([sys.executable, "-c", DRIVER],
                            stdout=subprocess.PIPE, text=True)
    pids = {}
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        parts = line.split()
        if len(parts) == 2 and parts[0].endswith("_PID"):
            pids[parts[0]] = int(parts[1])
        if line.startswith("READY"):
            break
    assert len(pids) == 3, f"driver never announced: {pids}"
    assert set(_pids_alive(pids.values())) == set(pids.values())
    proc.kill()                      # SIGKILL: no cleanup code runs
    proc.wait()
    leftovers = _wait_gone(list(pids.values()))
    assert not leftovers, \
        f"daemons outlived a SIGKILLed driver: {leftovers} of {pids}"


def test_sigterm_node_manager_reaps_workers():
    import ray_tpu
    ray_tpu.init(num_cpus=1, object_store_memory=32 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def pid():
            return os.getpid()

        wpid = ray_tpu.get(pid.remote(), timeout=60)
        nm_pid = ray_tpu._context.node.nm_handle.proc.pid
        os.kill(nm_pid, signal.SIGTERM)
        leftovers = _wait_gone([nm_pid, wpid])
        assert not leftovers, f"SIGTERMed nm left {leftovers} alive"
    finally:
        ray_tpu.shutdown()


@pytest.mark.slow
def test_node_manager_exits_when_gcs_stays_dead(tmp_path):
    """With gcs_reconnect_timeout_s=3, a node manager whose GCS was
    SIGKILLed must exit on its own within the timeout + slack, taking
    its workers along."""
    env = dict(os.environ)
    env["RAY_TPU_GCS_RECONNECT_TIMEOUT_S"] = "3"
    proc = subprocess.Popen([sys.executable, "-c", DRIVER],
                            stdout=subprocess.PIPE, text=True, env=env)
    try:
        pids = {}
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            parts = line.split()
            if len(parts) == 2 and parts[0].endswith("_PID"):
                pids[parts[0]] = int(parts[1])
            if line.startswith("READY"):
                break
        assert len(pids) == 3
        os.kill(pids["GCS_PID"], signal.SIGKILL)
        leftovers = _wait_gone([pids["NM_PID"], pids["W_PID"]], timeout=30)
        assert not leftovers, \
            f"nm/worker kept running with a dead GCS: {leftovers}"
    finally:
        proc.kill()
        proc.wait()
