"""State API + job submission + dashboard REST tests (reference:
python/ray/tests/test_state_api.py, dashboard/modules/job/tests)."""

import json
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_list_nodes(ray_start):
    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]


def test_task_events(ray_start):
    @ray_tpu.remote
    def traced_task():
        return 1

    ray_tpu.get([traced_task.remote() for _ in range(3)])
    time.sleep(1.5)   # event flush interval
    tasks = state.list_tasks()
    mine = [t for t in tasks if t.get("name") == "traced_task"]
    assert len(mine) == 3
    assert all(t["state"] == "FINISHED" for t in mine)
    summ = state.summarize_tasks()
    assert summ.get("traced_task", {}).get("FINISHED") == 3


def test_list_actors(ray_start):
    @ray_tpu.remote
    class Tracked:
        def ping(self):
            return 1

    a = Tracked.remote()
    ray_tpu.get(a.ping.remote())
    actors = state.list_actors()
    assert any(x["state"] == "ALIVE" for x in actors)
    assert state.summarize_actors().get("ALIVE", 0) >= 1


def test_job_submission(ray_start):
    from ray_tpu.job_submission import JobSubmissionClient
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"print('job says hi')\"")
    status = client.wait_until_finished(job_id, timeout=60)
    assert status == "SUCCEEDED"
    assert "job says hi" in client.get_job_logs(job_id)
    jobs = client.list_jobs()
    assert any(j["job_id"] == job_id for j in jobs)


def test_job_failure_status(ray_start):
    from ray_tpu.job_submission import JobSubmissionClient
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"{sys.executable} -c \"import sys; sys.exit(3)\"")
    status = client.wait_until_finished(job_id, timeout=60)
    assert status == "FAILED"


def test_dashboard_rest(ray_start):
    from ray_tpu.dashboard import start_dashboard
    start_dashboard(port=18266)

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:18266{path}", timeout=30) as r:
            return json.loads(r.read())

    nodes = get("/api/nodes")
    assert len(nodes) == 1
    st = get("/api/cluster_status")
    assert st["nodes_alive"] == 1
    # submit a job over REST
    req = urllib.request.Request(
        "http://127.0.0.1:18266/api/jobs",
        data=json.dumps({"entrypoint":
                         f"{sys.executable} -c \"print('rest job')\""}
                        ).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        job_id = json.loads(r.read())["job_id"]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        info = get(f"/api/jobs/{job_id}")
        if info["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.5)
    assert info["status"] == "SUCCEEDED"
    assert "rest job" in get(f"/api/jobs/{job_id}/logs")["logs"]


def test_user_metrics_and_prometheus(ray_start):
    """Counter/Gauge/Histogram push to GCS; /metrics renders Prometheus
    text (reference: ray.util.metrics + metrics agent export)."""
    import time

    import ray_tpu
    from ray_tpu.util.metrics import (Counter, Gauge, Histogram,
                                      render_prometheus)

    c = Counter("test_requests_total", "reqs", tag_keys=("route",))
    c.inc(3, tags={"route": "/a"})
    c.inc(2, tags={"route": "/b"})
    g = Gauge("test_queue_depth", "depth")
    g.set(7)
    h = Histogram("test_latency_s", "lat", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    deadline = time.time() + 15
    snap = {}
    while time.time() < deadline:
        snap = ray_tpu._get_worker().gcs_call("get_metrics")
        if snap:
            break
        time.sleep(0.5)
    assert snap, "metrics never reached GCS"
    text = render_prometheus(snap)
    assert 'test_requests_total{route="/a"} 3.0' in text
    assert "test_queue_depth 7.0" in text
    assert 'test_latency_s_bucket{le="0.1"} 1' in text
    assert "test_latency_s_count 3" in text


def test_worker_logs_reach_driver(ray_start, capfd):
    """print() inside a task is echoed to the driver with a (pid, ip)
    prefix (reference: log_monitor -> pubsub -> driver stdout)."""
    import time

    import ray_tpu

    @ray_tpu.remote
    def chatty():
        print("hello-from-worker-xyz", flush=True)
        return 1

    assert ray_tpu.get(chatty.remote(), timeout=60) == 1
    deadline = time.time() + 10
    seen = False
    while time.time() < deadline and not seen:
        time.sleep(0.7)
        out = capfd.readouterr().out
        seen = "hello-from-worker-xyz" in out
    assert seen, "worker stdout never reached the driver"


def test_dashboard_index_and_timeline(ray_start, tmp_path):
    """Dashboard serves a UI page; the timeline exporter produces a
    chrome trace (reference: dashboard frontend, `ray timeline`)."""
    from ray_tpu.dashboard import start_dashboard
    start_dashboard(port=18266)   # reuses the detached dashboard actor

    @ray_tpu.remote
    def traced_task(x):
        return x + 1

    assert ray_tpu.get(traced_task.remote(1), timeout=30) == 2
    with urllib.request.urlopen(
            "http://127.0.0.1:18266/", timeout=10) as resp:
        body = resp.read().decode()
    assert "ray_tpu dashboard" in body and "/api/" in body

    time.sleep(2.0)        # task-event buffers flush every 1s
    out = str(tmp_path / "trace.json")
    ray_tpu.timeline(out)
    trace = json.loads(open(out).read())
    assert isinstance(trace, list) and trace
    assert any(ev.get("name") == "traced_task" for ev in trace)


def test_list_objects_reports_sizes(ray_start):
    import numpy as np
    ref = ray_tpu.put(np.ones(300_000, dtype=np.uint8))
    rows = state.list_objects()
    shm = [r for r in rows if r.get("kind", "").endswith("shm")]
    assert shm and any(r["size_bytes"] >= 300_000 for r in shm)
    owned = [r for r in rows if "owned" in r.get("kind", "")]
    assert any(r["object_id"] == ref.id.hex() for r in owned)
    del ref


def test_list_objects_respects_limit_and_dedupes(ray_start):
    """An object both shm-resident and owned collapses to ONE
    'owned+shm' row (carrying size AND ownership fields), and the
    result never exceeds `limit` rows."""
    import numpy as np
    refs = [ray_tpu.put(np.ones(200_000, dtype=np.uint8))
            for _ in range(6)]
    rows = state.list_objects()
    ids = [r["object_id"] for r in rows]
    assert len(ids) == len(set(ids)), "duplicate rows for one object"
    merged = {r["object_id"]: r for r in rows}
    for ref in refs:
        row = merged[ref.id.hex()]
        assert row["kind"] == "owned+shm"
        assert row["size_bytes"] >= 200_000
        assert "complete" in row and "borrowers" in row
    assert len(state.list_objects(limit=3)) <= 3
    del refs
