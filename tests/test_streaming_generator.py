"""Streaming generator returns (reference: num_returns="streaming" /
ObjectRefGenerator, python/ray/_raylet.pyx:281, item reporting protocol
core_worker.proto:400 ReportGeneratorItemReturns; tests modeled on
python/ray/tests/test_streaming_generator.py).
"""

import sys
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_basic_stream(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    g = gen.remote(5)
    assert isinstance(g, ray_tpu.ObjectRefGenerator)
    vals = [ray_tpu.get(ref) for ref in g]
    assert vals == [0, 10, 20, 30, 40]
    # the completion ref resolves to the item count
    assert ray_tpu.get(g.completed(), timeout=30) == 5


def test_items_arrive_before_completion(cluster):
    """Consumers see early items while the producer is still running —
    the point of streaming vs. returning a list."""
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(3):
            yield i
            time.sleep(1.0)

    g = slow_gen.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(g.next(timeout=30))
    dt = time.monotonic() - t0
    assert first == 0
    assert dt < 2.5, f"first item took {dt:.1f}s — buffered whole stream?"
    rest = [ray_tpu.get(r) for r in g]
    assert rest == [1, 2]


def test_backpressure_bounds_inflight(cluster):
    """With backpressure K, an unconsumed stream holds <= K+1 items in
    flight; the producer advances only as the consumer acks."""
    K = 4

    @ray_tpu.remote(num_returns="streaming", _generator_backpressure=K)
    def counter(tmp):
        import pathlib
        for i in range(100):
            pathlib.Path(tmp).write_text(str(i + 1))
            yield np.ones(1024, np.uint8) * (i % 256)

    import tempfile
    with tempfile.NamedTemporaryFile() as f:
        g = counter.remote(f.name)
        time.sleep(3.0)     # producer runs free; consumer reads nothing
        produced = int(open(f.name).read())
        assert produced <= K + 1, \
            f"producer ran {produced} items ahead with K={K}"
        # consume everything; the stream completes
        n = sum(1 for _ in g)
        assert n == 100
        assert int(open(f.name).read()) == 100


def test_store_occupancy_stays_bounded(cluster):
    """The verdict's acceptance shape: stream 100 shm-sized blocks with
    backpressure K and assert (via store stats) the object store never
    holds the whole stream — consumed-and-dropped items are freed by the
    owner while the producer keeps going."""
    K = 4
    BLOCK = 2 * 1024 * 1024

    @ray_tpu.remote(num_returns="streaming", _generator_backpressure=K)
    def blocks():
        for i in range(100):
            yield np.full(BLOCK, i % 256, np.uint8)

    w = ray_tpu._get_worker()
    base = w.node_call("get_node_info")["store"]["bytes_in_use"]
    g = blocks.remote()
    peak = 0
    n = 0
    for ref in g:
        arr = ray_tpu.get(ref)
        assert arr[0] == n % 256 and arr.nbytes == BLOCK
        n += 1
        del arr, ref
        if n % 10 == 0:
            used = w.node_call("get_node_info")["store"]["bytes_in_use"]
            peak = max(peak, used - base)
    assert n == 100
    # window K + consumer-held item + freeing slack; far below 100 blocks
    assert peak <= (2 * K + 4) * BLOCK, \
        f"store held {peak / BLOCK:.0f} blocks with K={K}"


def test_midstream_error_surfaces_in_order(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield 1
        yield 2
        raise ValueError("boom at 3")

    g = bad.remote()
    assert ray_tpu.get(next(g)) == 1
    assert ray_tpu.get(next(g)) == 2
    with pytest.raises(Exception, match="boom"):
        ray_tpu.get(next(g))
    with pytest.raises(StopIteration):
        next(g)


def test_actor_streaming_method(cluster):
    @ray_tpu.remote
    class Chunker:
        def stream(self, n):
            for i in range(n):
                yield f"chunk-{i}"

        def ping(self):
            return "pong"

    a = Chunker.remote()
    g = a.stream.options(num_returns="streaming").remote(4)
    assert [ray_tpu.get(r) for r in g] == [f"chunk-{i}" for i in range(4)]
    # the actor still answers ordinary calls afterwards
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"


def test_async_actor_streaming(cluster):
    @ray_tpu.remote
    class AsyncGen:
        async def stream(self, n):
            import asyncio
            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * i

    a = AsyncGen.remote()
    g = a.stream.options(num_returns="streaming").remote(5)
    assert [ray_tpu.get(r) for r in g] == [0, 1, 4, 9, 16]


def test_close_stops_producer(cluster):
    @ray_tpu.remote(num_returns="streaming", _generator_backpressure=2)
    def forever(tmp):
        import pathlib
        i = 0
        while True:
            pathlib.Path(tmp).write_text(str(i))
            yield i
            i += 1

    import tempfile
    with tempfile.NamedTemporaryFile() as f:
        g = forever.remote(f.name)
        assert ray_tpu.get(next(g)) == 0
        g.close()
        time.sleep(1.0)
        after = int(open(f.name).read())
        time.sleep(2.0)
        assert int(open(f.name).read()) <= after + 2, \
            "producer kept running after close()"


def test_consumer_crash_cleans_up(cluster):
    """A driver that dies mid-stream must not leave the producer
    running: the broken connection aborts the generator."""
    import subprocess
    import tempfile
    import textwrap
    with tempfile.NamedTemporaryFile() as f:
        addr = ray_tpu.get_gcs_address()
        script = textwrap.dedent(f"""
            import time
            import ray_tpu
            ray_tpu.init(address={addr!r})

            @ray_tpu.remote(num_returns="streaming",
                            _generator_backpressure=1000)
            def producer():
                import pathlib
                i = 0
                while True:
                    pathlib.Path({f.name!r}).write_text(str(i))
                    yield i
                    i += 1
                    time.sleep(0.01)

            g = producer.remote()
            ray_tpu.get(next(g))     # stream is live
            print("STREAMING", flush=True)
            time.sleep(600)
        """)
        proc = subprocess.Popen([sys.executable, "-c", script],
                                stdout=subprocess.PIPE, text=True)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "STREAMING" in line:
                break
        proc.kill()
        proc.wait()
        time.sleep(3.0)     # connection-loss detection + abort
        n1 = int(open(f.name).read())
        time.sleep(3.0)
        n2 = int(open(f.name).read())
        assert n2 <= n1 + 5, \
            f"producer still streaming after consumer death ({n1}->{n2})"
