"""Joblib backend and usage-stats shims (reference:
python/ray/util/joblib/ and python/ray/_private/usage/usage_lib.py)."""

import math

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=2)
    yield ctx
    ray_tpu.shutdown()


def test_joblib_backend(ray_start):
    import joblib
    from joblib import Parallel, delayed

    from ray_tpu.util.joblib_backend import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu", n_jobs=2):
        out = Parallel()(delayed(math.factorial)(i) for i in range(8))
    assert out == [math.factorial(i) for i in range(8)]


def test_usage_stats(ray_start):
    from ray_tpu._private import usage_stats
    usage_stats.record_library_usage("train")
    usage_stats.record_extra_usage_tag("topology", "v4-8")
    rep = usage_stats.usage_report()
    assert rep.get("library_train") == "1"
    assert rep.get("topology") == "v4-8"
