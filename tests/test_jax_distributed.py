"""The real multi-host substrate, exercised end-to-end on CPU: two worker
processes form a jax.distributed world through the GCS-KV rendezvous and
run XLA collectives across process boundaries (reference:
python/ray/util/collective/collective.py NCCL group init + master
rendezvous; python/ray/train/_internal/backend_executor.py:68,135).

These are the CI stand-ins for multi-host TPU: same code path, CPU
devices (1 per process, Gloo-backed XLA collectives).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig


@ray_tpu.remote(max_concurrency=1, num_cpus=1)
class XlaRank:
    """One process of an xla collective group (CPU backend)."""

    def __init__(self, world_size, rank, group):
        import jax
        jax.config.update("jax_platforms", "cpu")
        from ray_tpu.util import collective
        collective.init_collective_group(world_size, rank, backend="xla",
                                         group_name=group)
        self.rank = rank

    def allreduce_named(self, value, group, op="sum"):
        from ray_tpu.util import collective
        return np.asarray(collective.allreduce(np.asarray(value),
                                               group, op=op))

    def broadcast(self, value, src, group):
        from ray_tpu.util import collective
        return np.asarray(collective.broadcast(np.asarray(value), src,
                                               group))

    def allgather(self, value, group):
        from ray_tpu.util import collective
        return [np.asarray(x) for x in collective.allgather(
            np.asarray(value), group)]

    def world(self):
        import jax
        return [jax.process_count(), jax.local_device_count(),
                len(jax.devices())]


def test_xla_collective_group_two_processes():
    ray_tpu.init(num_cpus=4)
    try:
        group = "xg1"
        actors = [XlaRank.remote(2, r, group) for r in range(2)]
        # the device world spans both processes (each contributes its
        # local CPU devices — 8 under the test XLA_FLAGS)
        worlds = ray_tpu.get([a.world.remote() for a in actors],
                             timeout=180)
        for n_proc, n_local, n_total in worlds:
            assert n_proc == 2 and n_total == 2 * n_local
        # device-native psum across processes (ints stay exact)
        outs = ray_tpu.get(
            [a.allreduce_named.remote(np.array([r + 1, 10], np.int32),
                                      group)
             for r, a in enumerate(actors)], timeout=180)
        for o in outs:
            assert o.tolist() == [3, 20] and o.dtype == np.int32
        # broadcast from rank 1
        outs = ray_tpu.get(
            [a.broadcast.remote(
                np.full(3, 7.0) if r == 1 else np.zeros(3), 1, group)
             for r, a in enumerate(actors)], timeout=180)
        for o in outs:
            assert o.tolist() == [7.0, 7.0, 7.0]
        # allgather returns one entry per process
        outs = ray_tpu.get(
            [a.allgather.remote(np.array([float(r)]), group)
             for r, a in enumerate(actors)], timeout=180)
        for o in outs:
            assert len(o) == 2
            assert sorted(float(x[0]) for x in o) == [0.0, 1.0]
    finally:
        ray_tpu.shutdown()


def _dp_train_fn(config):
    """Data-parallel step over a 2-process global mesh: grads sync via
    sharding-driven psum, each process feeding its own batch shard."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    from ray_tpu import train as rt_train

    devs = np.array(jax.devices())
    assert jax.process_count() == 2, \
        f"expected 2-process world, got {jax.process_count()}"
    mesh = Mesh(devs, ("data",))
    rank = jax.process_index()
    n_total = len(devs)
    n_local = jax.local_device_count()

    w = jnp.zeros((4,))
    # one row per device; this process's rows carry (rank+1)
    local_x = np.full((n_local, 4), float(rank + 1), np.float32)

    def per_shard(w, x):
        # per-shard grad of mean((x@w - 1)^2), psum-averaged over data
        def loss(w):
            pred = x @ w
            return jnp.mean((pred - 1.0) ** 2)
        g = jax.grad(loss)(w)
        return jax.lax.pmean(g, "data")

    f = jax.jit(shard_map(per_shard, mesh=mesh, in_specs=(P(), P("data")),
                          out_specs=P(), check_rep=False))
    # global batch assembled from process-local shards; under
    # multi-process jit each process supplies only its local rows
    sharding = NamedSharding(mesh, P("data"))
    gx = jax.make_array_from_process_local_data(sharding, local_x,
                                                (n_total, 4))
    g = f(w, gx)
    # analytic: grad of mean((c*0 - 1)^2) wrt w at w=0 is -2*mean(x) per dim
    # (x columns are constant c per process: c=1 and c=2, pmean -> -3.0)
    expected = -2.0 * (1.0 + 2.0) / 2.0
    got = np.asarray(jax.device_get(g))
    assert np.allclose(got, expected, atol=1e-5), (got, expected)
    rt_train.report({"grad0": float(got[0]), "rank": rank})


def test_jax_trainer_two_process_world():
    ray_tpu.init(num_cpus=4)
    try:
        trainer = JaxTrainer(
            _dp_train_fn,
            scaling_config=ScalingConfig(num_workers=2,
                                         use_jax_distributed=True),
            run_config=RunConfig(name="jd-e2e"),
        )
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics.get("grad0") == pytest.approx(-3.0)
    finally:
        ray_tpu.shutdown()
