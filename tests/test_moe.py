"""Mixture-of-Experts tests on the 8-device virtual CPU mesh: routing
correctness (1-expert MoE == dense MLP), expert-parallel sharding, aux
load-balance loss, and a sharded train step over the `expert` axis.
(No reference counterpart: SURVEY §2.4 lists EP/MoE as absent upstream —
these follow the sharded-train-step test pattern of test_model_parallel.)"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.models import MODEL_REGISTRY, TransformerLM
from ray_tpu.models.moe import MoEMLP
from ray_tpu.models.transformer import MLP, TransformerConfig
from ray_tpu.parallel import MeshConfig, make_mesh
from ray_tpu.parallel.train_step import make_train_fns


def test_single_expert_equals_dense_mlp():
    """With one expert and top-1 routing, MoE must reproduce the dense MLP
    bit-for-bit (gate weight is exactly 1.0, no drops at cf>=1)."""
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, n_experts=1, expert_top_k=1, capacity_factor=2.0,
        dtype=jnp.float32, param_dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 32), jnp.float32)

    moe = MoEMLP(cfg)
    mvars = moe.init(jax.random.PRNGKey(1), x)
    dense = MLP(cfg)
    dvars = {"params": {
        "gate": {"kernel": mvars["params"]["gate"].value[0]},
        "up": {"kernel": mvars["params"]["up"].value[0]},
        "down": {"kernel": mvars["params"]["down"].value[0]},
    }}
    moe_out, aux = moe.apply(mvars, x)
    dense_out = dense.apply(dvars, x)
    np.testing.assert_allclose(np.asarray(moe_out), np.asarray(dense_out),
                               rtol=1e-5, atol=1e-5)
    assert abs(float(aux) - 1.0) < 1e-5   # 1 expert -> perfectly balanced


def test_topk_routing_respects_capacity():
    """Tokens beyond expert capacity are dropped (output contribution 0),
    never mis-routed."""
    cfg = TransformerConfig(
        vocab_size=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=32, n_experts=2, expert_top_k=1, capacity_factor=0.25,
        dtype=jnp.float32, param_dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 32, 16), jnp.float32)
    moe = MoEMLP(cfg)
    out, _ = moe.apply(moe.init(jax.random.PRNGKey(1), x), x)
    # capacity = ceil(0.25 * 32 * 1 / 2) = 4 per expert -> at most 8 of 32
    # tokens produce nonzero output
    nonzero = (jnp.abs(out).sum(-1) > 1e-6).sum()
    assert int(nonzero) <= 8, int(nonzero)


def test_moe_train_step_expert_parallel():
    cfg = MODEL_REGISTRY["moe-debug"]
    model = TransformerLM(cfg)
    mesh = make_mesh(MeshConfig(data=1, fsdp=2, expert=2, seq=1, tensor=2))
    B, L = 8, 64
    init_fn, step_fn, shardings = make_train_fns(
        model, optax.adamw(1e-3), mesh, batch_shape=(B, L + 1))
    state = init_fn(jax.random.PRNGKey(0))

    # expert weights are sharded over the expert axis
    moe_params = state.params["layers"]["block"]["moe"]
    spec = moe_params["gate"].value.sharding.spec
    assert "expert" in jax.tree.leaves(tuple(spec)), spec

    toks = jax.random.randint(jax.random.PRNGKey(1), (B, L + 1), 0,
                              cfg.vocab_size)
    losses = []
    for _ in range(4):
        state, m = step_fn(state, toks)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    assert float(m["moe_aux"]) > 0.0


def test_moe_output_matches_across_expert_layouts():
    """The same MoE forward must produce identical logits whether experts
    are sharded 1-way or 4-way (SPMD correctness of the all-to-all)."""
    cfg = dataclasses.replace(MODEL_REGISTRY["moe-debug"],
                              dtype=jnp.float32, param_dtype=jnp.float32)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0,
                              cfg.vocab_size)
    outs = []
    for layout in [MeshConfig(data=1, fsdp=8, expert=1, seq=1, tensor=1),
                   MeshConfig(data=1, fsdp=2, expert=4, seq=1, tensor=1)]:
        mesh = make_mesh(layout)
        init_fn, _, _ = make_train_fns(
            model, optax.sgd(0.0), mesh, batch_shape=(4, 33))
        state = init_fn(jax.random.PRNGKey(0))
        logits = model.apply({"params": jax.device_get(state.params)}, toks)
        outs.append(np.asarray(logits))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-4, atol=1e-4)
