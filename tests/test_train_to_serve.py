"""Full ML lifecycle: train -> sharded checkpoint -> multi-host serving.

The composition the framework exists for: a model trained on one mesh
layout is checkpointed with orbax, then a SHARDED serve replica group
(2-process jax.distributed gang) restores it resharded over ITS global
mesh and serves logits — asserted equal to a driver-local forward with
the same trained params (reference: Train checkpointing -> Serve
deployment handoff; reshard-on-restore is the TPU-native part)."""

import dataclasses

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve

pytestmark = pytest.mark.slow

PROMPT = [3, 14, 15, 92, 65, 35, 89, 79]


def _cfg():
    import jax.numpy as jnp

    from ray_tpu.models import MODEL_REGISTRY
    return dataclasses.replace(MODEL_REGISTRY["llama-debug"],
                               dtype=jnp.float32,
                               param_dtype=jnp.float32, remat=False)


class CheckpointedLM:
    """Serve callable: restores the trained params over the replica
    GROUP's global mesh and serves last-position logits."""

    def __init__(self, ckpt_path: str):
        import jax

        from ray_tpu.models import TransformerLM
        from ray_tpu.parallel import MeshConfig, make_mesh
        from ray_tpu.parallel.train_step import make_infer_fns
        from ray_tpu.train.sharded_checkpoint import (abstract_like,
                                                      restore_sharded)
        assert jax.process_count() == 2
        cfg = _cfg()
        model = TransformerLM(cfg)
        mesh = make_mesh(MeshConfig(data=1, fsdp=8, seq=1, tensor=2),
                         devices=jax.devices())
        init_fn, self._infer, _ = make_infer_fns(
            model, mesh, batch_shape=(1, len(PROMPT)))
        # concrete template in the TARGET layout (the proven
        # reshard-on-restore pattern, test_sharded_checkpoint)
        template = init_fn(jax.random.PRNGKey(7))
        self.params = restore_sharded(ckpt_path,
                                      abstract_like(template))

    def __call__(self, tokens):
        import jax
        import jax.numpy as jnp
        logits = self._infer(self.params,
                             jnp.asarray([tokens], jnp.int32))
        return np.asarray(jax.device_get(logits))[0].tolist()


def test_train_checkpoint_serve_roundtrip(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import optax

    from ray_tpu.models import TransformerLM
    from ray_tpu.parallel import MeshConfig, make_mesh
    from ray_tpu.parallel.train_step import make_train_fns
    from ray_tpu.train.sharded_checkpoint import save_sharded

    cfg = _cfg()
    model = TransformerLM(cfg)
    # train on a single-process 8-device mesh (one layout)...
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, seq=1, tensor=2),
                     devices=jax.devices()[:8])
    B, L = 8, 32
    init_fn, step_fn, _ = make_train_fns(model, optax.adamw(1e-3), mesh,
                                         batch_shape=(B, L + 1))
    state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, L + 1), 0,
                                cfg.vocab_size)
    for _ in range(2):
        state, metrics = step_fn(state, tokens)
    save_sharded(state.params, ckpt)

    # driver-local reference logits with the trained params
    import jax.numpy as jnp
    ref = np.asarray(jax.device_get(model.apply(
        {"params": jax.device_get(state.params)},
        jnp.asarray([PROMPT], jnp.int32))))[0, -1]

    # ...serve from the checkpoint on a DIFFERENT layout: a 2-process
    # gang restoring resharded over its 16-device global mesh
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    try:
        app = serve.deployment(
            CheckpointedLM, num_hosts=2,
            ray_actor_options={"num_cpus": 0.5}).bind(ckpt)
        handle = serve.run(app, name="lm", route_prefix=None)
        got = np.asarray(handle.remote(PROMPT).result(timeout=180))
        assert got.shape == ref.shape
        np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
