"""Locality-aware streaming split dealing (reference: OutputSplitter
locality_hints, output_splitter.py — bundles deal to the consumer on the
block's node within a bounded row-imbalance slack)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.split import _QUEUE_CAP, _SplitCoordinator


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _drain(coord, idx):
    rows = []
    while True:
        b = coord.next(idx)
        if b is None:
            return rows
        if b[0] == "__wait__":
            continue
        rows.append(ray_tpu.get(b[0]))


def test_locality_dealing_prefers_hinted_consumer(ray_start, monkeypatch):
    """With locations stubbed to alternate between two nodes, each
    consumer receives (almost) exactly its node's blocks."""
    ds = rd.range(160, parallelism=8).map_batches(lambda b: b)
    locs = {}

    def fake_locate(self, ref):
        # derive a stable fake location from the ref identity
        return locs.setdefault(ref.id, ["nodeA", "nodeB"][len(locs) % 2])

    monkeypatch.setattr(_SplitCoordinator, "_locate", fake_locate)
    coord = _SplitCoordinator(ds._stages, 2, False,
                              locality_hints=["nodeA", "nodeB"])
    got0 = _drain(coord, 0)
    got1 = _drain(coord, 1)
    assert len(got0) + len(got1) == 8
    hits, total = coord.locality_stats()
    assert total == 8 and hits == 8, (hits, total)
    # alternating fake locations -> exact 4/4 block, 80/80 row split
    assert len(got0) == 4 and len(got1) == 4
    assert sum(b.num_rows for b in got0) == 80
    assert sum(b.num_rows for b in got1) == 80


def test_locality_slack_caps_imbalance(ray_start, monkeypatch):
    """All blocks 'live' on node A: locality must yield to row balance
    once consumer 0 runs ahead by the slack — consumer 1 still gets a
    substantial share instead of starving."""
    ds = rd.range(400, parallelism=16).map_batches(lambda b: b)
    monkeypatch.setattr(_SplitCoordinator, "_locate",
                        lambda self, ref: "nodeA")
    coord = _SplitCoordinator(ds._stages, 2, False,
                              locality_hints=["nodeA", "nodeB"])
    got0 = _drain(coord, 0)
    got1 = _drain(coord, 1)
    rows0 = sum(b.num_rows for b in got0)
    rows1 = sum(b.num_rows for b in got1)
    assert rows0 + rows1 == 400
    assert rows1 > 0, "remote consumer starved"
    # slack = 4 bundles of 25 rows: consumer 0 may lead by <= ~125 rows
    assert rows0 - rows1 <= 4 * 25 + 25, (rows0, rows1)


def test_streaming_split_e2e_with_hints(ray_start):
    """Public API: hints flow through, stream completes, rows conserved
    (single node: every hint matches, pure smoke for the real _locate)."""
    me = ray_tpu._get_worker().core.node_id
    ds = rd.range(100, parallelism=4)
    shards = ds.streaming_split(2, locality_hints=[me, me])
    total = 0
    for sh in shards:
        for batch in sh.iter_batches(batch_size=None):
            total += len(batch["id"])
    assert total == 100