"""Cluster serving edge (ROADMAP item 2): shared tenant-quota leases
across proxies, the decode→decode KV fabric with its fallback ladder,
batched hot-prefix export coalescing, and per-tenant SLO burn.

Everything here is hermetic: the GCS lease handlers run on a bare
GcsServer instance, the lease client gets a fake clock + in-process
call shim, and the fabric tests wire DisaggLLMDeployment peers as
direct objects (the same injection seams the cluster path uses)."""

import threading

import numpy as np
import pytest

from ray_tpu._private.config import cfg as rt_cfg
from ray_tpu.serve.fleet import (QuotaLeaseClient, TenantAdmission,
                                 TenantQuotaExceeded, TenantTokenBucket)


# ==========================================================================
# TenantTokenBucket: leased-share refill arithmetic (fake clock)
# ==========================================================================

def test_bucket_burst_drain_refill_and_deficit():
    b = TenantTokenBucket(rate=2.0, burst=4.0, now=0.0)
    assert [b.take(0.0) for _ in range(4)] == [True] * 4
    assert not b.take(0.0)                   # burst exhausted
    # the honest Retry-After: (1 - tokens) / rate
    assert b.wait_s(0.0) == pytest.approx(0.5)
    assert b.take(0.5)                       # exactly one token refilled
    assert not b.take(0.5)
    b2 = TenantTokenBucket(rate=2.0, burst=4.0, now=0.0)
    for _ in range(4):
        b2.take(0.0)
    assert b2.take(10.0)                     # refill caps at burst
    assert b2.tokens == pytest.approx(3.0)


def test_bucket_unlimited_and_set_params_clamp():
    b = TenantTokenBucket(rate=0.0, burst=1.0)
    assert all(b.take(0.0) for _ in range(100))   # rate<=0 = unlimited
    assert b.wait_s(0.0) == 0.0
    b = TenantTokenBucket(rate=4.0, burst=8.0, now=0.0)
    b.set_params(1.0, 2.0)                   # re-split shrank the share
    assert b.tokens == 2.0                   # banked tokens clamp to burst
    assert b.burst == 2.0 and b.rate == 1.0


# ==========================================================================
# GCS lease handlers: split, epoch, escrow
# ==========================================================================

def _gcs():
    from ray_tpu._private.gcs import GcsServer
    g = GcsServer.__new__(GcsServer)
    g.tenant_quotas = {}
    g.quota_leases = {}
    g.quota_lease_epoch = 1
    g.tenant_burn = {}
    return g


def _call(g):
    return lambda method, **kw: getattr(g, "h_" + method)(None, **kw)


def test_gcs_lease_acquire_splits_rate_and_bumps_epoch():
    g = _gcs()
    assert g.h_set_tenant_quota(None, "a", rate=10.0, burst=10.0)
    e0 = g.quota_lease_epoch
    out1 = g.h_quota_lease_acquire(None, "p1")
    assert out1["epoch"] == e0 + 1 and out1["n_proxies"] == 1
    assert out1["shares"]["a"]["rate"] == pytest.approx(10.0)
    out2 = g.h_quota_lease_acquire(None, "p2")
    assert out2["epoch"] == e0 + 2 and out2["n_proxies"] == 2
    assert out2["shares"]["a"]["rate"] == pytest.approx(5.0)
    assert out2["shares"]["a"]["cluster_rate"] == pytest.approx(10.0)
    # stale-epoch renew gets the fresh split piggybacked; current-epoch
    # renew stays lean (no shares payload)
    r = g.h_quota_lease_renew(None, "p1", epoch=out1["epoch"])
    assert not r["revoked"] and r["shares"]["a"]["rate"] == \
        pytest.approx(5.0)
    r2 = g.h_quota_lease_renew(None, "p1", epoch=r["epoch"])
    assert "shares" not in r2
    # a rate change bumps the epoch so proxies re-split on next renew
    g.h_set_tenant_quota(None, "a", rate=20.0)
    assert g.quota_lease_epoch == out2["epoch"] + 1


def test_gcs_lease_revoke_escrows_share():
    g = _gcs()
    g.h_set_tenant_quota(None, "a", rate=10.0, burst=10.0)
    g.h_quota_lease_acquire(None, "p1")
    e = g.h_quota_lease_acquire(None, "p2")["epoch"]
    assert g.h_quota_lease_revoke(None, "p1")
    assert not g.h_quota_lease_revoke(None, "nobody")
    # the ESCROW property: p1 still counts in the denominator, so p2's
    # share must NOT grow while p1 may still be admitting
    r = g.h_quota_lease_renew(None, "p2", epoch=e)   # stale → shares
    assert r["shares"]["a"]["rate"] == pytest.approx(5.0)
    # the revoked proxy learns on its renew and must degrade
    assert g.h_quota_lease_renew(None, "p1", epoch=e)["revoked"]
    # re-acquire clears the revocation and restores the full share
    out = g.h_quota_lease_acquire(None, "p1")
    assert out["shares"]["a"]["rate"] == pytest.approx(5.0)
    st = g.h_quota_lease_status(None)
    assert all(not row["revoked"] for row in st["leases"])


def test_gcs_lease_release_prune_and_burn_fold():
    g = _gcs()
    g.h_set_tenant_quota(None, "a", rate=8.0)
    g.h_quota_lease_acquire(None, "p1")
    e = g.h_quota_lease_acquire(None, "p2")["epoch"]
    g.h_quota_lease_renew(None, "p1", epoch=e, burn={"a": 3})
    g.h_quota_lease_renew(None, "p2", epoch=e, burn={"a": 2, "b": 1})
    st = g.h_quota_lease_status(None)
    assert st["tenant_burn"] == {"a": 5, "b": 1}
    assert g.h_quota_lease_release(None, "p2")
    assert g.quota_lease_epoch > e
    # an expired lease prunes out (and bumps the epoch) on any touch
    g.quota_leases["p1"]["ts"] -= rt_cfg.quota_lease_ttl_s + 1
    st = g.h_quota_lease_status(None)
    assert st["leases"] == []


# ==========================================================================
# QuotaLeaseClient against the real handlers (fake clock)
# ==========================================================================

class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture()
def fast_renew():
    rt_cfg.set("quota_lease_interval_s", 0.0)
    try:
        yield
    finally:
        rt_cfg.reset("quota_lease_interval_s")


def test_lease_client_admit_burn_and_retry_hint(fast_renew):
    g = _gcs()
    g.h_set_tenant_quota(None, "a", rate=2.0, burst=2.0)
    clk = _Clock()
    c = QuotaLeaseClient("p1", _call(g), clock=clk)
    assert c.acquire()
    assert c.admit("a", clk()) is None
    assert c.admit("a", clk()) is None       # burst of 2
    wait = c.admit("a", clk())
    assert wait is not None and wait == pytest.approx(0.5, abs=0.01)
    assert c.retry_hint("a") == pytest.approx(wait, abs=0.01)
    assert c.retry_hint("unrated") is None
    # burn deltas reach the GCS cluster totals via renew
    clk.t += 0.01
    c.maybe_renew(clk())
    assert g.tenant_burn.get("a") == 2
    assert c.stats()["pending_burn"] == {}


def test_lease_client_adopts_resplit_on_epoch_move(fast_renew):
    g = _gcs()
    g.h_set_tenant_quota(None, "a", rate=10.0, burst=10.0)
    clk = _Clock()
    c1 = QuotaLeaseClient("p1", _call(g), clock=clk)
    assert c1.acquire()
    assert c1.stats()["rates"]["a"] == pytest.approx(10.0)
    g.h_quota_lease_acquire(None, "p2")      # second proxy joins
    clk.t += 0.01
    c1.maybe_renew(clk())                    # stale epoch → re-split
    assert c1.stats()["rates"]["a"] == pytest.approx(5.0)


def test_lease_client_revoked_degrades_then_reacquires(fast_renew):
    g = _gcs()
    g.h_set_tenant_quota(None, "a", rate=8.0, burst=8.0)
    clk = _Clock()
    c1 = QuotaLeaseClient("p1", _call(g), clock=clk)
    c2 = QuotaLeaseClient("p2", _call(g), clock=clk)
    assert c1.acquire() and c2.acquire()
    clk.t += 0.01
    c1.maybe_renew(clk())                    # adopt the 2-proxy re-split
    share = c1.stats()["rates"]["a"]
    assert share == pytest.approx(4.0)
    g.h_quota_lease_revoke(None, "p1")
    clk.t += 0.01
    c1.maybe_renew(clk())                    # learns the revocation
    assert c1.revoked
    frac = rt_cfg.quota_lease_conservative_frac
    assert c1.stats()["rates"]["a"] == pytest.approx(share * frac)
    # survivor's share is UNCHANGED (escrow): degraded + survivor stays
    # strictly under the cluster rate → no over-admission window
    clk.t += 0.01
    c2.maybe_renew(clk())
    assert c2.stats()["rates"]["a"] == pytest.approx(4.0)
    assert c1.stats()["rates"]["a"] + c2.stats()["rates"]["a"] < 8.0
    # next tick re-acquires and restores the full split
    clk.t += 0.01
    c1.maybe_renew(clk())
    assert not c1.revoked
    assert c1.stats()["rates"]["a"] == pytest.approx(4.0)


def test_lease_client_renew_failure_rebanks_burn_and_degrades(fast_renew):
    g = _gcs()
    g.h_set_tenant_quota(None, "a", rate=4.0, burst=4.0)
    clk = _Clock()
    state = {"fail": False}
    real = _call(g)

    def call(method, **kw):
        if state["fail"] and method == "quota_lease_renew":
            raise ConnectionError("gcs away")
        return real(method, **kw)

    c = QuotaLeaseClient("p1", call, clock=clk)
    assert c.acquire()
    assert c.admit("a", clk()) is None
    state["fail"] = True
    clk.t += 0.01
    c.maybe_renew(clk())                     # renew fails → burn re-banked
    assert c.stats()["pending_burn"] == {"a": 1}
    assert not c.revoked                     # inside the TTL: full share
    clk.t += rt_cfg.quota_lease_ttl_s + 1.0
    c.maybe_renew(clk())                     # past TTL: degrade
    assert c.revoked
    frac = rt_cfg.quota_lease_conservative_frac
    assert c.stats()["rates"]["a"] == pytest.approx(4.0 * frac)


# ==========================================================================
# Chaos: QuotaLeaseRevoker round-trip (satellite 6)
# ==========================================================================

def test_quota_lease_revoker_no_over_admission(fast_renew):
    from ray_tpu.util.chaos import QuotaLeaseRevoker
    g = _gcs()
    g.h_set_tenant_quota(None, "hot", rate=10.0, burst=10.0)
    clk = _Clock()
    clients = {p: QuotaLeaseClient(p, _call(g), clock=clk)
               for p in ("p1", "p2")}
    for c in clients.values():
        assert c.acquire()
    clk.t += 0.01
    for c in clients.values():
        c.maybe_renew(clk())                 # both adopt the 2-way split
    rev = QuotaLeaseRevoker(_call(g), seed=7)
    assert sorted(rev.lease_ids()) == ["p1", "p2"]
    pid = rev.revoke_one()
    assert pid in clients and rev.revoked == [pid]
    victim, survivor = clients[pid], \
        clients[{"p1": "p2", "p2": "p1"}[pid]]

    def poke():
        clk.t += 0.01
        victim.maybe_renew(clk())
        survivor.maybe_renew(clk())

    assert rev.wait_for_degraded(victim, timeout_s=5.0, poke=poke)
    frac = rt_cfg.quota_lease_conservative_frac
    # the invariant: degraded victim + escrow-frozen survivor admit
    # strictly under the cluster rate throughout the window
    assert victim.stats()["rates"]["hot"] == pytest.approx(5.0 * frac)
    assert survivor.stats()["rates"]["hot"] == pytest.approx(5.0)
    assert (victim.stats()["rates"]["hot"]
            + survivor.stats()["rates"]["hot"]) < 10.0
    # and the round-trip: the victim re-leases back to a full share
    assert rev.wait_for_release(victim, timeout_s=5.0, poke=poke)
    assert victim.stats()["rates"]["hot"] == pytest.approx(5.0)


# ==========================================================================
# Satellite 1: Retry-After derives from the bucket deficit
# ==========================================================================

def test_shed_retry_after_uses_bucket_deficit():
    adm = TenantAdmission(default_quota=1, queue_max=0)
    adm.retry_hint = lambda t: 2.5           # the lease client's deficit
    lease = adm.acquire("a")
    with pytest.raises(TenantQuotaExceeded) as ei:
        adm.acquire("a")
    assert ei.value.retry_after_s == pytest.approx(2.5)
    lease.release()
    # a broken/None hint falls back to the fixed constant
    adm.retry_hint = lambda t: (_ for _ in ()).throw(RuntimeError())
    lease = adm.acquire("a")
    with pytest.raises(TenantQuotaExceeded) as ei:
        adm.acquire("a")
    assert ei.value.retry_after_s == pytest.approx(
        rt_cfg.tenant_retry_after_s)
    lease.release()


# ==========================================================================
# Per-tenant SLO burn rows (ROADMAP item 2d)
# ==========================================================================

def test_evaluate_tenant_slo_rows_and_unseen_skip():
    from ray_tpu.serve.slo import evaluate_tenant_slo
    samples = {"a": 0.2, "b": None}          # b: no observations at all

    def query(metric, window=60.0, agg="avg", tags=None, threshold=None):
        assert metric == "serve_tenant_ttft_ms" and agg == "frac_over"
        return {"value": samples[tags["tenant"]]}

    slo = {"p95_ttft_ms": 100.0, "budget_fraction": 0.05}
    rows = evaluate_tenant_slo(slo, query, ["a", "b"])
    assert len(rows) == 1                    # absent != violating
    row = rows[0]
    assert row["tenant"] == "a" and row["objective"] == "tenant_latency"
    assert row["burn_fast"] == pytest.approx(0.2 / 0.05)
    assert row["violating"]
    assert evaluate_tenant_slo({}, query, ["a"]) == []
    assert evaluate_tenant_slo(slo, query, []) == []


# ==========================================================================
# KV fabric: decode→decode hand-off + fallback ladder (engine-backed)
# ==========================================================================

@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig, TransformerLM
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _mk_dep(tiny_fixture, **kw):
    from ray_tpu.serve.disagg import DisaggLLMDeployment
    cfg, _model, params = tiny_fixture
    args = dict(n_slots=2, max_len=64, prefill_chunk=4, prefill_budget=8,
                prefix_cache_slots=2, params_fn=lambda: params)
    args.update(kw)
    return DisaggLLMDeployment(cfg, **args)


def _oracle(tiny_fixture, prompt, n=10, **kw):
    from ray_tpu.inference import LLMDeployment
    cfg, _model, params = tiny_fixture
    args = dict(n_slots=2, max_len=64, prefill_chunk=4,
                prefill_budget=8, prefix_cache_slots=0,
                params_fn=lambda: params)
    args.update(kw)
    dep = LLMDeployment(cfg, **args)
    try:
        return dep.generate(prompt, max_new_tokens=n)
    finally:
        dep.engine.stop()


def _rows(rid, dep):
    return lambda: [{"replica_id": rid,
                     **dep.engine.prefix_cache.summary()}]


PROMPT = list(range(50, 67))                 # 17 tokens: 4 full chunks


def test_fabric_peer_import_bit_identical_and_compile_once(tiny):
    a = _mk_dep(tiny)
    b = _mk_dep(tiny, peers={"A": a}, summaries_fn=_rows("A", a))
    try:
        want = _oracle(tiny, PROMPT)
        a.generate(PROMPT, max_new_tokens=2)   # warm the peer's trie
        got = b.generate(PROMPT, max_new_tokens=10)
        assert got == want                     # greedy bit-identical
        assert b.engine.kv_imports == 1
        assert b.engine.remote_prefix_tokens == 16
        assert b.engine.decode_compile_count == 1
        assert a._singleflight.exports == 1
        # second request: local radix hit, no new fabric pull
        assert b.generate(PROMPT, max_new_tokens=10) == want
        assert b.engine.kv_imports == 1
        assert b.engine.sched.queue_depth() == 0
    finally:
        a.engine.stop()
        b.engine.stop()


def test_fabric_peer_dead_mid_export_lands_on_local_prefill(tiny):
    from ray_tpu.util.chaos import PeerExportKiller
    a = _mk_dep(tiny)
    b = _mk_dep(tiny, peers={"A": a}, summaries_fn=_rows("A", a))
    killer = PeerExportKiller(1.0)
    try:
        want = _oracle(tiny, PROMPT, n=8)
        a.generate(PROMPT, max_new_tokens=2)
        killer.arm_local()
        with pytest.raises(Exception):
            a.peer_export(PROMPT)              # the injection really fires
        got = b.generate(PROMPT, max_new_tokens=8)
        assert got == want                     # rung 5, exactly-once
        assert b.engine.kv_imports == 0
        assert b.engine.sched.queue_depth() == 0
    finally:
        killer.disarm_local()
        a.engine.stop()
        b.engine.stop()


def test_fabric_stale_fingerprint_lands_on_local_prefill(tiny):
    from ray_tpu.inference.prefix_cache import chunk_fingerprints
    a = _mk_dep(tiny)
    # the summary CLAIMS coverage the live trie never had — the shape of
    # "summary newer than evicted blocks": the exporter must refuse
    fake = [{"replica_id": "A", "chunk": 4,
             "fps": chunk_fingerprints(PROMPT, 4, max_chunks=4)}]
    b = _mk_dep(tiny, peers={"A": a}, summaries_fn=lambda: fake)
    try:
        want = _oracle(tiny, PROMPT, n=8)
        got = b.generate(PROMPT, max_new_tokens=8)
        assert got == want
        assert b.engine.kv_imports == 0
        # and the explicit proof path: a cached prefix with the WRONG
        # requested fingerprint refuses with the stale diagnosis
        a.generate(PROMPT, max_new_tokens=2)
        with pytest.raises(LookupError, match="stale fingerprint"):
            a.peer_export(PROMPT, max_chunks=4, want_fp=0x1234)
    finally:
        a.engine.stop()
        b.engine.stop()


def test_fabric_quant_mismatch_refuses_lossy_direction(tiny):
    # int8 wire -> fp pool is the one LOSSY direction; the fabric must
    # refuse it and land on local prefill so greedy stays bit-identical
    a = _mk_dep(tiny, kv_quant="int8")
    b = _mk_dep(tiny, peers={"A": a}, summaries_fn=_rows("A", a))
    try:
        want = _oracle(tiny, PROMPT, n=8)
        a.generate(PROMPT, max_new_tokens=2)
        got = b.generate(PROMPT, max_new_tokens=8)
        assert got == want
        assert b.engine.kv_imports == 0        # refused, not imported
    finally:
        a.engine.stop()
        b.engine.stop()


def test_fabric_fp_wire_into_int8_pool_imports_exactly(tiny):
    # fp wire -> int8 pool quantizes with the save-path math: the import
    # is exact vs what the int8 engine would have produced locally
    a = _mk_dep(tiny)
    b = _mk_dep(tiny, kv_quant="int8", peers={"A": a},
                summaries_fn=_rows("A", a))
    try:
        want = _oracle(tiny, PROMPT, n=8, kv_quant="int8",
                       prefix_cache_slots=2)
        a.generate(PROMPT, max_new_tokens=2)
        got = b.generate(PROMPT, max_new_tokens=8)
        assert got == want
        assert b.engine.kv_imports == 1
    finally:
        a.engine.stop()
        b.engine.stop()


def test_batched_export_single_flight_coalesces(tiny):
    a = _mk_dep(tiny)
    try:
        a.generate(PROMPT, max_new_tokens=2)
        fp = a.engine.prefix_cache.covered_fp(PROMPT, 4)
        assert fp is not None
        exports0 = a.engine.kv_exports
        barrier = threading.Barrier(8)
        outs, errs = [], []

        def hit(i):
            barrier.wait()
            try:
                outs.append(a.peer_export(PROMPT, max_chunks=4,
                                          want_fp=fp, node_id=f"n{i}"))
            except Exception as e:           # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=hit, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert not errs
        # the acceptance bound: 8 concurrent misses, exactly 1 export
        assert len(outs) == 8
        assert all(o["covered"] == 16 for o in outs)
        assert a._singleflight.exports == 1
        assert a._singleflight.coalesced == 7
        assert a.engine.kv_exports == exports0 + 1
    finally:
        a.engine.stop()


# ==========================================================================
# serve_million_sessions smoke (scaled down; full scale lives in bench.py)
# ==========================================================================

@pytest.mark.slow
def test_serve_million_sessions_smoke():
    """O(1k)-session edge_probe pass through 2 real proxies: exercises
    the full wiring of the serve_million_sessions bench entry (quota
    leases + revocation, KV fabric vs local-only baseline, coalesced
    batched export) without the 100k-session figure run."""
    import os
    import sys
    reports = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "reports")
    if reports not in sys.path:
        sys.path.insert(0, reports)
    import edge_probe
    # cluster rate scales down with the session count so the buckets
    # actually constrain (at the default 2000/s a 1k run never sheds
    # and the raw zipf draw leaks past the fairness bound) and so the
    # revoked proxy's degrade->restore round trip lands inside the run
    res = edge_probe.run({"n_sessions": 1000, "proxies": 2, "seed": 0,
                          "cluster_rate_rps": 10.0})
    assert res["sessions"] == 1000
    assert res["proxies"] == 2
    assert res["fairness_ok"]
    assert res["over_admission_total"] == 0
    edge = res["edge"]
    assert edge["degraded_after_sessions"] is not None
    assert edge["restored_after_sessions"] is not None
    fab = res["fabric"]
    assert fab["hit_rate_improved"]
    assert fab["bit_identical"]
    assert all(c == 1 for c in fab["decode_compile_count"].values())
    bat = res["batched_export"]
    assert bat["export_runs"] == 1
    assert bat["coalesced"] == 7
    assert bat["relay_within_bound"]
    assert not bat["errors"]


# ==========================================================================
# rtlint: self-gate over the cluster-edge modules
# ==========================================================================

def test_rtlint_clean_on_edge_modules():
    """The edge stack (quota leases, KV fabric, chaos, edge probe)
    ships lint-clean: a full rtlint pass — all rules, NO baseline —
    over every module this plane touches reports zero findings."""
    import os

    from ray_tpu.devtools.lint import run_lint
    from ray_tpu.devtools.lint.config import LintConfig
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = [os.path.join(repo, *p.split("/")) for p in (
        "ray_tpu/serve/fleet.py", "ray_tpu/serve/proxy.py",
        "ray_tpu/serve/disagg.py", "ray_tpu/serve/slo.py",
        "ray_tpu/util/chaos.py", "reports/edge_probe.py")]
    r = run_lint(targets, config=LintConfig(root=repo),
                 use_baseline=False)
    assert r.findings == [], [str(f) for f in r.findings]
