"""Object-lifetime ledger (ray_tpu/_private/ledger.py + the GCS
object_ledger table): ring discipline, per-node delta/census merge,
leak-detector sweep thresholds, the list_objects join, and the
`ray_tpu memory` CLI helpers. Unit tier runs on any interpreter (no
store import); the cluster tier (synthetic leak flagged within one
sweep, arena-full fragmentation breakdown) is 3.12-gated."""

import asyncio
import sys
import time

import pytest

from ray_tpu._private import ledger
from ray_tpu._private.config import cfg
from ray_tpu._private.gcs import GcsServer
from ray_tpu.util.state import _merge_object_rows

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")

OID = bytes(range(20))
OID_HEX = OID.hex()


@pytest.fixture(autouse=True)
def _clean_ledger():
    ledger.set_enabled(True)
    ledger.configure(capacity=4096)
    ledger.drain()
    yield
    ledger.drain()
    ledger.set_enabled(True)
    cfg.reset("ledger_leak_after_s")
    cfg.reset("ledger_max_entries")


# ------------------------------------------------------------- record ring
def test_record_put_shape_and_drain():
    ledger.record_put(OID, size=1234, meta_size=5, owner="w:addr",
                      owner_worker="w1", node_id="n1", task_id="t1",
                      is_span=True)
    batch = ledger.drain()
    assert len(batch) == 1
    rec = batch[0]
    assert rec["object_id"] == OID_HEX
    assert rec["event"] == "created" and rec["sealed"] is True
    assert rec["size"] == 1234 and rec["meta_size"] == 5
    assert rec["is_span"] is True and rec["owner_worker"] == "w1"
    assert rec["seq"] > 0
    assert ledger.drain() == []


def test_disabled_ledger_records_nothing():
    ledger.set_enabled(False)
    ledger.record_put(OID, size=10)
    ledger.record(OID, "freed")
    assert ledger.drain() == []


def test_seq_is_monotonic_per_process():
    ledger.record(OID, "created", size=1)
    ledger.record(OID, "sealed")
    ledger.record(OID, "freed")
    seqs = [r["seq"] for r in ledger.drain()]
    assert seqs == sorted(seqs) and len(set(seqs)) == 3


def test_ring_drops_oldest_and_reports_in_band():
    ledger.configure(capacity=4)
    for i in range(10):
        ledger.record(OID, "refs", refs=i)
    st = ledger.stats()
    assert st["buffered"] == 4 and st["dropped_total"] >= 6
    batch = ledger.drain()
    # drops ride the first record of the next flushed batch
    assert batch[0]["dropped"] >= 6
    assert [r["refs"] for r in batch] == [6, 7, 8, 9]
    # counter reset after a reporting drain
    ledger.record(OID, "refs", refs=42)
    assert "dropped" not in ledger.drain()[0]


# ---------------------------------------------------------- GCS row merge
def _created(seq=1, ts=100.0, **kw):
    rec = {"object_id": OID_HEX, "event": "created", "ts": ts, "seq": seq,
           "size": 1000, "meta_size": 0, "owner": "w:1",
           "owner_worker": "w1", "node_id": "n1", "task_id": "t1",
           "is_span": False, "sealed": True}
    rec.update(kw)
    return rec


def test_gcs_merge_lifecycle():
    g = GcsServer()
    g.h_update_object_ledger(None, records=[_created()], worker_id="w1")
    row = g.object_ledger[OID_HEX]
    assert row["owner"] == "w:1" and row["creator_worker"] == "w1"
    assert row["creator_task"] == "t1"
    assert row["created_ts"] == 100.0 and row["sealed_ts"] == 100.0
    assert list(row["locations"]) == ["n1"]
    # census updates pins + placement
    g.h_update_object_ledger(None, census={"objects": {
        OID_HEX: {"pins": 3, "size": 1000, "is_span": False,
                  "stripe": 2, "age_s": 1.0}}}, node_id="n1")
    assert row["locations"]["n1"]["pins"] == 3
    assert row["stripe"] == 2
    # transfer arrival on a second node, then spill there
    g.h_update_object_ledger(None, records=[
        {"object_id": OID_HEX, "event": "location_add", "ts": 101.0,
         "seq": 1, "node_id": "n2"},
        {"object_id": OID_HEX, "event": "spilled", "ts": 102.0, "seq": 2,
         "node_id": "n2", "size": 1000}])
    assert set(row["locations"]) == {"n1"}
    assert row["spilled_ts"] == 102.0 and row["spilled_on"] == ["n2"]
    g.h_update_object_ledger(None, records=[
        {"object_id": OID_HEX, "event": "restored", "ts": 103.0,
         "seq": 3, "node_id": "n2"}])
    assert set(row["locations"]) == {"n1", "n2"}
    assert row["spilled_on"] == []
    # owner frees: row closes
    g.h_update_object_ledger(None, records=[
        {"object_id": OID_HEX, "event": "freed", "ts": 104.0, "seq": 4,
         "node_id": "n1"}])
    assert row["freed_ts"] == 104.0


def test_census_reconciles_silent_eviction_and_discovery():
    g = GcsServer()
    g.h_update_object_ledger(None, records=[_created()], worker_id="w1")
    other = ("ff" * 20)
    # census: OID vanished (LRU eviction emitted no event), `other`
    # appeared (pre-ledger object discovered by first sighting)
    g.h_update_object_ledger(None, census={"objects": {
        other: {"pins": 1, "size": 77, "is_span": True, "stripe": 0,
                "age_s": 5.0}}}, node_id="n1")
    row = g.object_ledger[OID_HEX]
    assert row["locations"] == {} and row["evicted_ts"] is not None
    drow = g.object_ledger[other]
    assert drow["size"] == 77 and drow["is_span"] is True
    assert drow["locations"]["n1"]["pins"] == 1
    assert drow["sealed_ts"] is not None  # age anchored at sighting


def test_ledger_table_bounded_retires_freed_rows_first():
    g = GcsServer()
    cfg.set("ledger_max_entries", 4)
    try:
        for i in range(4):
            oid = f"{i:02x}" * 20
            g.h_update_object_ledger(None, records=[
                _created(**{"object_id": oid})])
        # free row 2: it should be the eviction victim, not row 0
        g.h_update_object_ledger(None, records=[
            {"object_id": "02" * 20, "event": "freed", "ts": 1.0,
             "seq": 9}])
        g.h_update_object_ledger(None, records=[
            _created(**{"object_id": "aa" * 20})])
        assert "02" * 20 not in g.object_ledger
        assert "00" * 20 in g.object_ledger
        assert len(g.object_ledger) == 4
    finally:
        cfg.reset("ledger_max_entries")


# ------------------------------------------------------------- leak sweep
class _FakeConn:
    closed = False

    def __init__(self):
        self.notifies = []

    async def notify(self, method, **kw):
        self.notifies.append((method, kw))


def _sweep(g, now):
    async def run():
        out = await g.h_ledger_sweep(None, now=now)
        await asyncio.sleep(0)   # let evict-hint notifies run
        return out
    return asyncio.run(run())


def test_sweep_flags_only_past_threshold():
    cfg.set("ledger_leak_after_s", 30.0)
    g = GcsServer()
    g.h_update_object_ledger(None, records=[_created(ts=100.0)])
    g.h_update_object_ledger(None, records=[
        {"object_id": None, "event": "worker_exit", "worker_id": "w1",
         "ts": 100.0, "seq": 2}])
    # too young at t=120
    out = _sweep(g, now=120.0)
    assert out["leaked_objects"] == 0 and not out["newly_flagged"]
    # flagged at t=200 (one sweep)
    out = _sweep(g, now=200.0)
    assert out["leaked_objects"] == 1
    assert out["newly_flagged"] == [OID_HEX]
    assert out["leaked_bytes"] == 1000
    row = g.object_ledger[OID_HEX]
    assert row["leaked"] and row["leak_ts"] == 200.0
    # idempotent: second sweep counts it but doesn't re-flag
    out = _sweep(g, now=210.0)
    assert out["leaked_objects"] == 1 and not out["newly_flagged"]


def test_sweep_exports_gauge_and_leak_instant():
    cfg.set("ledger_leak_after_s", 10.0)
    g = GcsServer()
    g.h_update_object_ledger(None, records=[_created(ts=0.0)])
    g._ledger_exited.add("w1")
    _sweep(g, now=100.0)
    q = g.h_query_metrics(None, "store_leaked_bytes", window=1e9,
                          agg="latest", now=100.0)
    assert q["value"] == 1000.0
    q = g.h_query_metrics(None, "store_leaked_objects", window=1e9,
                          agg="latest", now=100.0)
    assert q["value"] == 1.0
    leaks = [r for r in g.h_list_task_events(None, kind="runtime_event",
                                             category="store")
             if r["name"] == "store.leak"]
    assert len(leaks) == 1
    assert leaks[0]["attrs"]["object_id"] == OID_HEX
    assert leaks[0]["attrs"]["bytes"] == 1000


def test_sweep_sends_eviction_hint_to_holding_node():
    cfg.set("ledger_leak_after_s", 1.0)
    g = GcsServer()
    conn = _FakeConn()
    g.node_conns["n1"] = conn
    g.h_update_object_ledger(None, records=[_created(ts=0.0)])
    g._ledger_exited.add("w1")
    _sweep(g, now=100.0)
    assert conn.notifies == [("ledger_evict_hint",
                              {"oids": [OID_HEX]})]


def test_pins_and_live_owner_protect_from_sweep():
    cfg.set("ledger_leak_after_s", 1.0)
    g = GcsServer()
    # pinned object of a dead owner: protected
    g.h_update_object_ledger(None, records=[_created(ts=0.0)])
    g.h_update_object_ledger(None, census={"objects": {
        OID_HEX: {"pins": 2, "size": 1000, "is_span": False,
                  "stripe": 0, "age_s": 1.0}}}, node_id="n1")
    g._ledger_exited.add("w1")
    assert _sweep(g, now=100.0)["leaked_objects"] == 0
    # unpinned object of a LIVE owner with unknown refs: protected
    other = "bb" * 20
    g.h_update_object_ledger(None, records=[
        _created(ts=0.0, **{"object_id": other,
                            "owner_worker": "alive"})])
    assert _sweep(g, now=100.0)["leaked_objects"] == 0
    # ...until the owner reports zero references
    g.h_update_object_ledger(None, records=[
        {"object_id": other, "event": "refs", "refs": 0, "ts": 1.0,
         "seq": 5}])
    out = _sweep(g, now=100.0)
    assert out["newly_flagged"] == [other]


def test_freed_and_evicted_rows_never_flag():
    cfg.set("ledger_leak_after_s", 1.0)
    g = GcsServer()
    g.h_update_object_ledger(None, records=[_created(ts=0.0)])
    g._ledger_exited.add("w1")
    _sweep(g, now=50.0)
    assert g.object_ledger[OID_HEX]["leaked"]
    # the holding node reclaims it (hint consumed): census drops it
    g.h_update_object_ledger(None, census={"objects": {}}, node_id="n1")
    out = _sweep(g, now=60.0)
    assert out["leaked_objects"] == 0
    assert g.object_ledger[OID_HEX]["leaked"] is False


# -------------------------------------------------------- list_objects join
def _shm_row(hexid, **kw):
    row = {"object_id": hexid, "node_id": "n1", "size_bytes": 100,
           "kind": "shm", "pins": 1, "is_span": False, "stripe": 0,
           "age_s": 5, "sealed": True}
    row.update(kw)
    return row


def test_merge_rows_join_and_order_is_deterministic():
    shm = [_shm_row("aa" * 20)]
    owned = {bytes.fromhex("aa" * 20): {"complete": True,
                                        "location": "n1",
                                        "borrowers": set(),
                                        "submitted": 0}}
    led = [{"object_id": "aa" * 20, "owner": "w:1", "creator_task": "t1",
            "created_ts": 1.0, "sealed_ts": 1.0, "size": 100,
            "locations": {"n1": {"pins": 9}}, "leaked": False},
           {"object_id": "bb" * 20, "owner": "w:2", "created_ts": 2.0,
            "sealed_ts": 2.0, "size": 999, "meta_size": 1,
            "is_span": True, "locations": {"n2": {"pins": 0}},
            "leaked": True}]
    a = _merge_object_rows(shm, owned, led, 10, node_id="n1", now=50.0)
    b = _merge_object_rows(shm, owned, led, 10, node_id="n1", now=50.0)
    assert a == b
    # shm+owned row keeps live truth (pins=1 from the arena, NOT the
    # ledger's 9) and gains provenance
    r0 = a[0]
    assert r0["kind"] == "owned+shm" and r0["pins"] == 1
    assert r0["owner"] == "w:1" and r0["creator_task"] == "t1"
    assert r0["age_s"] == 5       # live age wins
    # ledger-only row: provenance-derived columns
    r1 = a[1]
    assert r1["kind"] == "ledger" and r1["is_span"] is True
    assert r1["size_bytes"] == 1000 and r1["leaked"] is True
    assert r1["age_s"] == 48.0 and r1["node_id"] == "n2"


def test_merge_rows_every_row_has_new_columns():
    shm = [_shm_row("aa" * 20)]
    owned = {bytes.fromhex("cc" * 20): {"complete": False,
                                        "location": None,
                                        "borrowers": set(),
                                        "submitted": 1}}
    out = _merge_object_rows(shm, owned, [], 10, node_id="n1", now=1.0)
    for row in out:
        assert "is_span" in row and "pins" in row and "age_s" in row


def test_merge_rows_respects_limit_shm_first():
    shm = [_shm_row(f"{i:02x}" * 20) for i in range(5)]
    led = [{"object_id": "ee" * 20, "size": 1, "locations": {},
            "created_ts": 1.0, "sealed_ts": 1.0}]
    out = _merge_object_rows(shm, {}, led, 3, now=2.0)
    assert len(out) == 3
    assert all(r["kind"] == "shm" for r in out)


# ------------------------------------------------------------- CLI helpers
def test_cli_memory_sort_group_format():
    from ray_tpu.scripts.cli import (_format_memory_rows, _memory_grouped,
                                     _memory_sorted)
    rows = [
        {"object_id": "a" * 40, "kind": "owned+shm", "size_bytes": 10,
         "pins": 0, "age_s": 100.0, "is_span": False, "owner": "w:1",
         "node_id": "n1", "locations": ["n1"]},
        {"object_id": "b" * 40, "kind": "ledger", "size_bytes": 999,
         "pins": 2, "age_s": 1.0, "is_span": True, "owner": "w:2",
         "node_id": "n2", "locations": ["n1", "n2"], "leaked": True},
    ]
    assert [r["size_bytes"] for r in _memory_sorted(rows, "size")] \
        == [999, 10]
    assert [r["age_s"] for r in _memory_sorted(rows, "age")] \
        == [100.0, 1.0]
    assert [r["node_id"] for r in _memory_sorted(rows, "node")] \
        == ["n1", "n2"]
    groups = {g["group"]: g for g in _memory_grouped(rows, "owner")}
    assert groups["w:2"]["leaked_bytes"] == 999
    assert groups["w:1"]["bytes"] == 10
    text = _format_memory_rows(rows)
    assert "LEAK" in text and "yes" in text and "w:1" in text


def test_cli_memory_pane_renders_available_metrics():
    from ray_tpu.scripts import cli as cli_mod

    class FakeState:
        @staticmethod
        def query_metrics(name, window, agg):
            if name == "store_bytes_in_use":
                return {"value": 12345.0}
            if name == "data_plane_bytes_in_total":
                return {"value": 1e6}
            return {"value": None}
    pane = cli_mod._memory_pane(FakeState, 30.0)
    assert "arena bytes in use" in pane
    assert "data-plane B/s in" in pane
    assert "leaked" not in pane   # no value pushed -> row omitted


# ------------------------------------------------------------ cluster tier
@needs_cluster
def test_arena_full_error_carries_fragmentation_breakdown(tmp_path):
    from ray_tpu._private import events
    from ray_tpu._private.object_store import ObjectStoreClient
    store = ObjectStoreClient(str(tmp_path / "frag_store"), create=True,
                              size=4 * 1024 * 1024, stripes=1)
    try:
        # unevictable objects so the create cannot make room
        for i in range(3):
            bufs = store.create(bytes([i]) * 20, 1024 * 1024,
                                evictable=False)
            assert bufs is not None
            store.seal(bytes([i]) * 20)
        events.drain()
        with pytest.raises(MemoryError) as ei:
            store.create(b"Z" * 20, 64 * 1024 * 1024)
        msg = str(ei.value)
        assert "requested=" in msg and "live=" in msg \
            and "hole=" in msg
        recs = [r for r in events.drain()
                if r["name"] == "store.arena_full"]
        assert recs and "stripes" in recs[0]["attrs"]
        # live arena truth probes
        info = store.object_info(bytes([0]) * 20)
        assert info["sealed"] and info["data_size"] == 1024 * 1024
        frag = store.fragmentation()
        assert frag["stripes"][0]["live"] >= 3 * 1024 * 1024
    finally:
        store.close()


@needs_cluster
def test_node_manager_consumes_evict_hints():
    from ray_tpu._private.node_manager import NodeManager
    from ray_tpu._private.object_store import ObjectStoreClient
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        store = ObjectStoreClient(d + "/hint_store", create=True,
                                  size=4 * 1024 * 1024, stripes=1)
        try:
            oid = b"L" * 20
            store.put_bytes(oid, b"x" * 4096)

            class Stub:
                pass
            stub = Stub()
            stub.store = store
            stub._evict_hints = set()
            NodeManager.h_ledger_evict_hint(stub, None, [oid.hex()])
            assert oid in stub._evict_hints
            freed = NodeManager._consume_evict_hints(stub, {0}, False)
            assert freed >= 4096
            assert not store.contains(oid)
            assert oid not in stub._evict_hints
        finally:
            store.close()


@needs_cluster
def test_cluster_synthetic_leak_flagged_within_one_sweep():
    """Acceptance: a sealed object whose owner (an actor worker) was
    killed with no pins outstanding is flagged by ONE explicit ledger
    sweep, and its bytes land in store_leaked_bytes."""
    import os

    import numpy as np
    os.environ["RAY_TPU_LEDGER_LEAK_AFTER_S"] = "1"
    import ray_tpu
    from ray_tpu.util import state
    cfg.set("ledger_leak_after_s", 1.0)
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    try:
        @ray_tpu.remote
        class Leaker:
            def leak(self):
                # owner keeps the ref alive so it is never freed; the
                # ref dies WITH the worker -> classic leak shape
                self.ref = ray_tpu.put(np.ones(300_000, dtype=np.uint8))
                return self.ref.id.hex()

        a = Leaker.remote()
        leaked_hex = ray_tpu.get(a.leak.remote())
        time.sleep(2.5)    # ledger flush (1s cadence) + census tick
        ray_tpu.kill(a)
        deadline = time.time() + 30
        flagged = None
        while time.time() < deadline:
            time.sleep(1.0)
            out = state.ledger_sweep()
            if leaked_hex in (out.get("newly_flagged") or ()) \
                    or any(r["object_id"] == leaked_hex
                           for r in state.list_object_ledger(leaked=True)):
                flagged = out
                break
        assert flagged is not None, "synthetic leak never flagged"
        q = state.query_metrics("store_leaked_bytes", window=120,
                                agg="latest")
        assert (q["value"] or 0) >= 300_000
        rows = [r for r in state.list_objects(limit=2000)
                if r.get("object_id") == leaked_hex]
        assert rows and rows[0]["leaked"]
        assert rows[0].get("size_bytes", 0) >= 300_000
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RAY_TPU_LEDGER_LEAK_AFTER_S", None)
        cfg.reset("ledger_leak_after_s")
