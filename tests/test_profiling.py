"""Step profiler (ray_tpu/util/profiling.py): phase attribution,
cost_analysis via the AOT wrap, gauge emission (the acceptance "CPU
train loop emits per-step MFU gauges with compute/host-gap
attribution"), and the engine decode / RL learner / make_train_fns
wiring. CPU-only, no cluster."""

import time

import numpy as np
import pytest

from ray_tpu._private import events
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util import profiling


@pytest.fixture(autouse=True)
def _clean_recorder():
    events.drain()
    yield
    events.drain()


def _snap(prefix):
    return {m["name"]: m for m in metrics_mod.registry_snapshot()
            if m["name"].startswith(prefix)}


def test_attribution_phases_split_sanely():
    prof = profiling.StepProfiler("probe_attrib", emit_span=False,
                                  peak_flops=1e9, peak_bytes_per_s=1e9)
    prof.set_cost(flops=1e6, bytes_accessed=1e6)
    for _ in range(2):
        with prof.step(tokens=100) as s:
            time.sleep(0.02)            # data wait
            s.data_ready()
            time.sleep(0.04)            # compute
        time.sleep(0.01)                # host gap (before next step)
    rec = prof.last
    assert rec["data_wait_ms"] >= 15.0
    assert rec["compute_ms"] >= 30.0
    assert rec["host_gap_ms"] >= 5.0
    assert rec["wall_ms"] == pytest.approx(
        rec["compute_ms"] + rec["data_wait_ms"] + rec["host_gap_ms"],
        abs=0.01)
    # mfu over wall < mfu over compute alone; roofline from intensity
    assert 0 < rec["mfu"] < rec["mfu_compute"]
    assert rec["roofline_bound"] == 1.0     # intensity == machine balance
    assert rec["tokens_per_s"] > 0


def test_wrap_jit_cost_analysis_and_result_parity():
    import jax
    import jax.numpy as jnp
    prof = profiling.StepProfiler("probe_wrap", emit_span=False)

    def f(x, y):
        return x @ y

    j = jax.jit(f)
    wrapped = prof.wrap_jit(j)
    x = jnp.ones((64, 32))
    y = jnp.ones((32, 16))
    out = wrapped(x, y)
    assert out.shape == (64, 16)
    assert np.allclose(np.asarray(out), np.asarray(j(x, y)))
    assert prof.flops > 0               # cost analysis landed
    # second shape compiles its own entry with its own cost
    first = prof.flops
    wrapped(jnp.ones((8, 32)), y)
    assert prof.flops != first


def test_cpu_train_loop_emits_mfu_gauges_with_attribution():
    """Acceptance slice: a CPU train loop (make_train_fns + profiler)
    emits runtime_train_step_mfu and per-phase attribution gauges."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import TransformerLM
    from ray_tpu.models.transformer import TransformerConfig
    from ray_tpu.parallel import MeshConfig, make_mesh
    from ray_tpu.parallel.train_step import make_train_fns

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                            n_heads=2, n_kv_heads=2, d_ff=64,
                            max_seq_len=32)
    mesh = make_mesh(MeshConfig(data=1))
    prof = profiling.StepProfiler("train_step", emit_span=True)
    init, step, _ = make_train_fns(TransformerLM(cfg), optax.adam(1e-3),
                                   mesh, batch_shape=(2, 16),
                                   profiler=prof)
    state = init(jax.random.PRNGKey(0))
    toks = jnp.asarray(np.random.default_rng(0).integers(
        0, 64, (2, 16)), jnp.int32)
    losses = []
    for _ in range(3):
        state, m = step(state, toks)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert prof.flops > 0, "cost_analysis did not land"
    rec = prof.last
    assert rec["mfu"] > 0 and "compute_ms" in rec and "host_gap_ms" in rec
    snap = _snap("runtime_train_step")
    assert snap["runtime_train_step_mfu"]["samples"][0][1] == rec["mfu"]
    phases = {dict(k)["phase"]: v for k, v in
              snap["runtime_train_step_phase_ms"]["samples"]}
    assert set(phases) == {"compute", "data_wait", "host_gap"}
    # and the per-step spans landed on the flight recorder
    names = [r["name"] for r in events.drain()
             if r.get("state") == "RUNNING"]
    assert names.count("train_step.step") == 3


def test_engine_decode_emits_mfu_and_span_attribution():
    import jax

    from ray_tpu.inference.engine import EngineConfig, InferenceEngine
    from ray_tpu.models import TransformerLM
    from ray_tpu.models.transformer import TransformerConfig
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                            n_heads=2, n_kv_heads=2, d_ff=64,
                            max_seq_len=32)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    eng = InferenceEngine(model, params,
                          EngineConfig(n_slots=2, max_len=32,
                                       prefill_chunk=8,
                                       prefill_budget=16))
    h = eng.submit([1, 2, 3, 4], max_new_tokens=6)
    while eng.step():
        pass
    assert len(h.tokens()) == 6
    assert eng.decode_compile_count == 1    # profiler must not retrace
    assert eng.profiler is not None and eng.profiler.last["mfu"] > 0
    decs = [r for r in events.drain()
            if r.get("state") == "RUNNING" and r["name"] == "engine.decode"]
    assert decs, "no decode spans"
    assert all("mfu" in d["attrs"] and "compute_ms" in d["attrs"]
               and "host_gap_ms" in d["attrs"] for d in decs)
    snap = _snap("runtime_decode_step")
    assert snap["runtime_decode_step_mfu"]["samples"][0][1] > 0

    # step_profile=False keeps the old behavior (the bench baseline)
    eng2 = InferenceEngine(model, params,
                           EngineConfig(n_slots=2, max_len=32,
                                        prefill_chunk=8,
                                        prefill_budget=16,
                                        step_profile=False))
    h2 = eng2.submit([1, 2, 3], max_new_tokens=2)
    while eng2.step():
        pass
    assert len(h2.tokens()) == 2
    assert eng2.profiler is None
    decs2 = [r for r in events.drain()
             if r.get("state") == "RUNNING"
             and r["name"] == "engine.decode"]
    assert decs2 and all("mfu" not in d["attrs"] for d in decs2)


def test_rl_learner_emits_update_mfu():
    from ray_tpu.rl.learner import JaxLearner
    cfg = {"lr": 3e-4, "clip_param": 0.2, "vf_loss_coeff": 0.5,
           "entropy_coeff": 0.01, "minibatch_size": 16, "num_epochs": 1,
           "grad_clip": 0.5}
    learner = JaxLearner(cfg, obs_dim=4, action_dim=2)
    n = 64
    rng = np.random.default_rng(0)
    batch = {"obs": rng.standard_normal((n, 4)).astype(np.float32),
             "actions": rng.integers(0, 2, n),
             "logp": np.zeros(n, np.float32),
             "advantages": rng.standard_normal(n).astype(np.float32),
             "value_targets": rng.standard_normal(n).astype(np.float32)}
    m = learner.update_from_batch(batch)
    assert np.isfinite(m["total_loss"])
    assert learner.profiler.flops > 0
    assert learner.profiler.last["compute_ms"] > 0
    snap = _snap("runtime_rl_update")
    assert "runtime_rl_update_mfu" in snap


def test_decode_flops_and_bytes_estimates():
    flops = profiling.decode_step_flops(
        n_params=1000, n_layers=2, n_heads=4, head_dim=8,
        kv_lens=[10, 20])
    # 2*1000 per token + 4*2*kv*4*8 attention
    assert flops == 2 * (2 * 1000) + 4 * 2 * (10 + 20) * 4 * 8
    nbytes = profiling.decode_step_bytes(
        param_bytes=4000, n_layers=2, n_kv_heads=4, head_dim=8,
        kv_lens=[10], elt_bytes=4)
    assert nbytes == 4000 + 2 * 2 * 10 * 4 * 8 * 4


def test_peak_flops_env_override(monkeypatch):
    monkeypatch.setenv("RAY_TPU_PEAK_FLOPS", "123e9")
    assert profiling.detect_peak_flops() == 123e9
    monkeypatch.setenv("RAY_TPU_PEAK_BYTES_PER_S", "7e9")
    assert profiling.detect_peak_bytes_per_s() == 7e9
