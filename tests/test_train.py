"""Train library tests: JaxTrainer end-to-end on a local cluster —
worker group, sessions/report, checkpointing, failure restart
(reference: python/ray/train/tests/test_data_parallel_trainer.py shape)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (Checkpoint, CheckpointConfig, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig)


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=6, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_trainer_basic(ray_start, tmp_path):
    def loop(config):
        ctx = train.get_context()
        for step in range(3):
            train.report({"step": step, "rank": ctx.get_world_rank(),
                          "world": ctx.get_world_size()})

    trainer = JaxTrainer(
        loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path), name="basic"))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert result.metrics["world"] == 2
    assert len(result.metrics_history) == 3


def test_trainer_checkpointing(ray_start, tmp_path):
    def loop(config):
        ctx = train.get_context()
        for step in range(4):
            ckpt = None
            if ctx.get_world_rank() == 0:
                ckpt = Checkpoint.from_dict({"model": step * 10})
            train.report({"step": step, "loss": 10.0 - step},
                         checkpoint=ckpt)

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=str(tmp_path), name="ckpt",
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="loss",
                checkpoint_score_order="min")))
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    data = result.checkpoint.to_dict()
    assert data["model"] == 30   # best (lowest loss) = last step
    ckpt_dir = os.path.join(str(tmp_path), "ckpt", "checkpoints")
    assert len(os.listdir(ckpt_dir)) == 2   # top-k retention


def test_trainer_failure_restart(ray_start, tmp_path):
    marker = str(tmp_path / "crashed_once")

    def loop(config):
        ctx = train.get_context()
        start = 0
        if train.session.get_checkpoint() is not None:
            start = train.session.get_checkpoint().to_dict()["step"] + 1
        for step in range(start, 4):
            ckpt = None
            if ctx.get_world_rank() == 0:
                ckpt = Checkpoint.from_dict({"step": step})
            train.report({"step": step}, checkpoint=ckpt)
            if step == 1 and ctx.get_world_rank() == 0 and \
                    not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                time.sleep(0.3)   # let the report drain
                os._exit(1)       # simulate worker crash

    trainer = JaxTrainer(
        loop, train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            storage_path=str(tmp_path), name="restart",
            failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["step"] == 3
    assert os.path.exists(marker)
