"""Elastic MPMD pipeline training (train/mpmd.py): per-stage programs,
1F1B/GPipe host schedules, bounded replay, and stage-level preemption
recovery.

Unit tier (any interpreter, in-process LocalStageHandle):
  - schedule generation + dependency-order simulation (pipeline.py)
  - replay-buffer determinism + bounded eviction + gap detection
  - stage kill mid-step → park → restore → replay → BIT-IDENTICAL
    optimizer state vs the uninterrupted baseline, compile counts ==1
  - barrier deadline miss / exhausted budget → controlled degrade
    (PipelineDegradedError), never a hang
  - graceful preemption-notice migration at a step boundary
  - FailureConfig restart_policy plumbing + BackendExecutor
    supports_worker_replace gating
  - StageKiller chaos spec + stage shard save/restore helpers

Cluster tier (Python >= 3.12): a real PipelineStageActor gang with a
stage actor killed mid-step, and JaxTrainer per-worker replace under
restart_policy="stage".
"""

import os
import sys
import time

import numpy as np
import pytest

from ray_tpu.parallel import pipeline as plib
from ray_tpu.train.config import FailureConfig
from ray_tpu.train.mpmd import (LocalStageHandle, MicrobatchReplayBuffer,
                                MPMDConfig, MPMDPipelineTrainer,
                                PipelineDegradedError, StageDefinition,
                                StageLostError)
from ray_tpu.util.chaos import StageKiller

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")

D, MB, M, S = 8, 4, 4, 3


# ------------------------------------------------------------- schedules

def test_1f1b_counts_and_order():
    for s_n, m_n in [(2, 2), (3, 4), (4, 8), (2, 1), (5, 3)]:
        sched = plib.schedule_1f1b(s_n, m_n)
        assert len(sched) == s_n
        for ops in sched:
            fwd = [mb for op, mb in ops if op == plib.OP_FWD]
            bwd = [mb for op, mb in ops if op == plib.OP_BWD]
            assert fwd == list(range(m_n))     # F in microbatch order
            assert bwd == list(range(m_n))     # B in microbatch order


def test_1f1b_peak_live_below_gpipe():
    s_n, m_n = 4, 8
    f1b = plib.schedule_1f1b(s_n, m_n)
    gp = plib.schedule_gpipe(s_n, m_n)
    for s in range(s_n):
        # gpipe peaks at M stashes during warmup (grad buffer only
        # becomes live once stashes are draining, so M stays the peak)
        assert plib.peak_live_activations(gp[s]) == m_n
        # 1f1b steady state: min(S-s, M) stashes + the grad-accumulation
        # buffer held from first backward to the apply
        assert plib.peak_live_activations(f1b[s]) == min(s_n - s, m_n) + 1
        # legacy activation-only count (what the buffer sizing used to
        # be computed from — one short per stage)
        assert plib.peak_live_activations(f1b[s], grad_buffers=False) == \
            min(s_n - s, m_n)


def test_peak_live_pinned_s4_m8_interleaved():
    """Satellite pin: steady-state buffer peaks for (S=4, M=8) at
    v=1 and v=2 — the numbers MicrobatchReplayBuffer.budget() sizes
    peak_microbatch_buffers from."""
    assert [plib.peak_live_activations(ops)
            for ops in plib.make_schedule("1f1b", 4, 8)] == [5, 4, 3, 2]
    assert [plib.peak_live_activations(ops)
            for ops in plib.make_schedule("1f1b", 4, 8, virtual=2)] == \
        [13, 11, 9, 7]


def test_schedules_simulate_without_deadlock():
    for kind in ("1f1b", "gpipe"):
        for s_n, m_n in [(2, 2), (3, 4), (4, 8)]:
            order = plib.simulate_schedule(
                plib.make_schedule(kind, s_n, m_n))
            assert len(order) == 2 * s_n * m_n
            done = set()
            for _tick, s, op, mb, _chunk in order:
                if op == plib.OP_FWD:
                    assert s == 0 or (s - 1, "F", mb) in done
                else:
                    assert (s, "F", mb) in done
                    assert s == s_n - 1 or (s + 1, "B", mb) in done
                done.add((s, op, mb))


def test_interleaved_schedule_simulates_without_deadlock():
    """Deadlock-freedom for v in {2, 3} across divisible and
    non-divisible M (closed form and greedy fallback paths)."""
    for v in (2, 3):
        for s_n, m_n in [(2, 4), (4, 8), (3, 4), (4, 6), (2, 8)]:
            sched = plib.schedule_interleaved_1f1b(s_n, m_n, v)
            order = plib.simulate_schedule(sched)
            assert len(order) == 2 * v * s_n * m_n
            done = set()
            for _tick, s, op, mb, chunk in order:
                vs = chunk * s_n + s
                if op == plib.OP_FWD:
                    assert vs == 0 or (vs - 1, "F", mb) in done
                else:
                    assert (vs, "F", mb) in done
                    assert vs == v * s_n - 1 or (vs + 1, "B", mb) in done
                done.add((vs, op, mb))
            # each chunk's F and B streams stay in microbatch order —
            # the bit-identity invariant replay depends on
            for s in range(s_n):
                for c in range(v):
                    for kind in (plib.OP_FWD, plib.OP_BWD):
                        mbs = [op[1] for op in sched[s]
                               if op[0] == kind and plib.op_chunk(op) == c]
                        assert mbs == list(range(m_n))


def test_interleaved_closed_form_meets_analytic_bound():
    """When M % S == 0 the Megatron closed form must hit
    (S-1)/(v*M+S-1) exactly under the unit-time event model."""
    for v in (2, 3):
        for s_n, m_n in [(2, 4), (4, 8), (3, 6)]:
            sched = plib.schedule_interleaved_1f1b(s_n, m_n, v)
            tl = plib.simulate_timeline(sched, lambda s, k, c: 1.0)
            ideal = 2.0 * v * m_n  # per-stage busy ticks
            bound = plib.pipeline_bubble_fraction(s_n, m_n, virtual=v)
            assert tl["span"] == pytest.approx(ideal / (1.0 - bound),
                                               rel=1e-9)


def test_simulate_schedule_detects_deadlock():
    # backward before its own forward can never become ready
    bad = [[(plib.OP_BWD, 0), (plib.OP_FWD, 0)],
           [(plib.OP_FWD, 0), (plib.OP_BWD, 0)]]
    with pytest.raises(ValueError, match="deadlock"):
        plib.simulate_schedule(bad)


def test_make_schedule_validates():
    with pytest.raises(ValueError):
        plib.make_schedule("zigzag", 2, 2)
    with pytest.raises(ValueError):
        plib.schedule_1f1b(0, 4)
    assert plib.pipeline_bubble_fraction(4, 8) == pytest.approx(3 / 11)


# ---------------------------------------------------------- replay buffer

def test_replay_buffer_bounded_oldest_first():
    buf = MicrobatchReplayBuffer(depth=2)
    for t in range(1, 5):
        buf.record(t, [np.full((2,), t)], [np.full((2,), -t)])
    assert buf.steps() == [3, 4]
    ins, tgts = buf.get(3)
    np.testing.assert_array_equal(ins[0], np.full((2,), 3))
    np.testing.assert_array_equal(tgts[0], np.full((2,), -3))
    with pytest.raises(KeyError):
        buf.get(2)


def test_replay_buffer_snapshots_inputs():
    buf = MicrobatchReplayBuffer(depth=2)
    x = np.zeros((3,))
    buf.record(1, [x], [x])
    x[:] = 99.0                      # caller mutation after record
    ins, _ = buf.get(1)
    np.testing.assert_array_equal(ins[0], np.zeros((3,)))


def test_replay_buffer_gap_detection():
    buf = MicrobatchReplayBuffer(depth=2)
    buf.record(5, [np.zeros(1)], [np.zeros(1)])
    buf.record(6, [np.zeros(1)], [np.zeros(1)])
    assert buf.replayable_from(4) == [5, 6]
    assert buf.replayable_from(5) == [6]
    with pytest.raises(KeyError, match="gap"):
        buf.replayable_from(2)      # steps 3..4 already evicted


# ----------------------------------------------------------- local gangs

def _builder(stage_idx):
    import jax
    import jax.numpy as jnp
    import optax
    k = jax.random.PRNGKey(stage_idx)
    params = {"w": jax.random.normal(k, (D, D)) * 0.3,
              "b": jnp.zeros((D,))}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    loss_fn = None
    if stage_idx == S - 1:
        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)
    return StageDefinition(stage_fn=stage_fn, params=params,
                           optimizer=optax.adamw(1e-2), loss_fn=loss_fn)


def _data_fn(step):
    rng = np.random.RandomState(step)
    ins = [rng.randn(MB, D).astype(np.float32) for _ in range(M)]
    tgts = [rng.randn(MB, D).astype(np.float32) for _ in range(M)]
    return ins, tgts


def _trainer(max_failures=2, **cfg_kw):
    cfg_kw.setdefault("n_microbatches", M)
    return MPMDPipelineTrainer(
        [_builder] * S, MPMDConfig(**cfg_kw),
        FailureConfig(max_failures=max_failures, restart_policy="stage",
                      restart_backoff_s=0.0))


def test_local_pipeline_trains_and_compiles_once():
    tr = _trainer()
    out = tr.fit(_data_fn, 5)
    assert out["steps"] == 5
    losses = [h["loss"] for h in out["history"] if "loss" in h]
    assert len(losses) == 5 and all(np.isfinite(losses))
    for counts in tr.compile_counts():
        assert counts == {"fwd": 1, "bwd": 1, "apply": 1}
    # per-stage bubble + peak-live bookkeeping present
    assert out["peak_live_activations"] == [min(S - s, M) + 1
                                            for s in range(S)]
    assert 0.0 < out["bubble_fraction_analytic"] < 1.0
    assert "stage0_bubble_fraction" in out["history"][0]


def test_gpipe_schedule_also_trains():
    tr = _trainer(schedule="gpipe")
    out = tr.fit(_data_fn, 2)
    assert out["steps"] == 2
    assert out["peak_live_activations"] == [M] * S


def test_stage_kill_recovers_bit_identical():
    """The acceptance criterion: a stage killed mid-step recovers
    without restarting survivors, resumes within replay_depth + 1
    steps, and post-replay optimizer state is bit-identical."""
    base = _trainer()
    base.fit(_data_fn, 6)
    base_digests = base.state_digests()

    tr = _trainer(replay_depth=2)
    tr.start()
    survivors_before = [tr.handles[0], tr.handles[2]]
    tr.handles[1]._fail_at = (4, "F")          # dies mid-step 4
    out = tr.fit(_data_fn, 6)
    assert len(out["recoveries"]) == 1
    rec = out["recoveries"][0]
    assert rec["stages"] == [1]
    assert rec["steps_lost"] <= tr.config.replay_depth + 1
    assert rec["boundary"] == 2                # checkpoint_every=replay=2
    # survivors were never re-provisioned
    assert tr.handles[0] is survivors_before[0]
    assert tr.handles[2] is survivors_before[1]
    # state parity with the uninterrupted run, bit for bit
    assert tr.state_digests() == base_digests
    for counts in tr.compile_counts():
        assert counts["fwd"] == 1 and counts["bwd"] == 1


def test_kill_during_backward_also_recovers():
    base = _trainer()
    base.fit(_data_fn, 4)
    tr = _trainer()
    tr.start()
    tr.handles[2]._fail_at = (3, "B")          # last stage, backward
    tr.fit(_data_fn, 4)
    assert tr.recoveries and tr.recoveries[0]["stages"] == [2]
    assert tr.state_digests() == base.state_digests()


def test_failure_budget_exhaustion_degrades():
    tr = _trainer(max_failures=1)
    tr.start()

    # every provisioned replacement for stage 1 dies immediately too
    def chaos_provision(idx, snapshot=None):
        h = tr._default_provision(idx, snapshot)
        if idx == 1:
            h._fail_at = (3, "F")
        return h
    tr._provision_fn = chaos_provision
    tr.handles[1]._fail_at = (3, "F")
    with pytest.raises(PipelineDegradedError, match="budget"):
        tr.fit(_data_fn, 6)


def test_job_policy_refuses_stage_recovery():
    tr = MPMDPipelineTrainer(
        [_builder] * S, MPMDConfig(n_microbatches=M),
        FailureConfig(max_failures=3, restart_policy="job"))
    tr.start()
    tr.handles[1]._fail_at = (1, "F")
    with pytest.raises(PipelineDegradedError, match="job"):
        tr.fit(_data_fn, 2)


def test_barrier_deadline_miss_degrades():
    """A survivor that cannot park within the deadline turns the
    recovery into a controlled job-level degrade instead of a hang."""
    tr = _trainer(barrier_deadline_s=0.2)

    class StuckHandle(LocalStageHandle):
        def abort_step(self, step):
            from ray_tpu.train.mpmd import _Now
            return _Now(error=TimeoutError("survivor wedged"))

    def provision(idx, snapshot=None):
        if idx == 0:
            return StuckHandle(idx, S, M, _builder, snapshot)
        return tr._default_provision(idx, snapshot)
    tr._provision_fn = provision
    tr.start()
    tr.handles[1]._fail_at = (1, "F")
    t0 = time.monotonic()
    with pytest.raises(PipelineDegradedError, match="barrier"):
        tr.fit(_data_fn, 2)
    assert time.monotonic() - t0 < 10.0


def test_preempt_notice_migrates_at_boundary(tmp_path):
    """The graceful path: a notice (marker file) migrates the stage at
    the next step boundary — no replay, no recovery entry, and the
    run's final state matches the unperturbed baseline bit for bit."""
    base = _trainer()
    base.fit(_data_fn, 4)

    tr = MPMDPipelineTrainer(
        [_builder] * S, MPMDConfig(n_microbatches=M),
        FailureConfig(max_failures=2, restart_policy="stage",
                      restart_backoff_s=0.0),
        marker_dir=str(tmp_path))
    tr.start()
    old = tr.handles[1]
    done = []

    def data_fn(step):
        if step == 3 and not done:
            done.append(1)
            StageKiller.preempt_stage(tr.preempt_marker(1))
        return _data_fn(step)

    out = tr.fit(data_fn, 4)
    assert tr.handles[1] is not old           # migrated
    assert old._dead                          # old host reaped
    assert out["recoveries"] == []            # no crash recovery
    assert not os.path.exists(tr.preempt_marker(1))   # notice cleared
    assert tr.state_digests() == base.state_digests()


def test_stage_killer_chaos_spec_degrades_controlled():
    """stage_step=1.0 kills every (re)provisioned stage's first forward:
    recovery burns the budget and must end in PipelineDegradedError —
    the controlled degrade, not a hang or an unhandled crash."""
    killer = StageKiller(probability=1.0)
    assert killer.spec() == "stage_step=1.0"
    env = killer.env({})
    assert env[StageKiller.SPEC_ENV] == "stage_step=1.0"
    tr = _trainer(max_failures=2)
    tr.start()                                 # provision BEFORE arming
    killer.arm_local()
    try:
        with pytest.raises(PipelineDegradedError, match="budget"):
            tr.fit(_data_fn, 3)
    finally:
        StageKiller.disarm_local()


def test_stage_killer_single_shot_recovers():
    """Arm before step 2, disarm when the controller provisions the
    first replacement (the 'node came back clean' shape) — the pipeline
    recovers and finishes training."""
    killer = StageKiller(probability=1.0)
    tr = _trainer(max_failures=3)
    tr.start()

    def provision(idx, snapshot=None):
        StageKiller.disarm_local()      # replacement host is clean
        return tr._default_provision(idx, snapshot)
    tr._provision_fn = provision
    armed = []

    def data_fn(step):
        if step == 2 and not armed:
            armed.append(1)
            killer.arm_local()
        return _data_fn(step)

    try:
        out = tr.fit(data_fn, 4)
    finally:
        StageKiller.disarm_local()
    assert out["recoveries"], "chaos never fired"
    # with p=1 the whole gang died at once; every stage was replaced
    assert out["recoveries"][0]["stages"] == [0, 1, 2]
    assert out["steps"] == 4


# ------------------------------------------------- restore-source ladder

def test_stage_shard_save_restore_roundtrip(tmp_path):
    from ray_tpu.train.sharded_checkpoint import (restore_stage_shard,
                                                  save_stage_shard)
    snap = {"step": 7, "stage": 1,
            "params": {"w": np.arange(6, dtype=np.float32)},
            "opt_state": {"m": np.ones(3)}}
    save_stage_shard(str(tmp_path), 1, snap)
    back = restore_stage_shard(str(tmp_path), 1)
    assert back["step"] == 7
    np.testing.assert_array_equal(back["params"]["w"],
                                  snap["params"]["w"])


def test_recovery_falls_back_to_storage_shard(tmp_path):
    """Snapshot ref lost with the stage's node → the replacement
    restores from the durable storage shard instead."""
    from ray_tpu.train.sharded_checkpoint import save_stage_shard
    base = _trainer()
    base.fit(_data_fn, 4)

    tr = _trainer(storage_path=str(tmp_path))
    tr.start()
    # step-boundary checkpoints: persist each stage's snapshot like the
    # actor's checkpoint() does when storage_path is set
    orig_ckpt = tr._checkpoint_all

    def ckpt_and_persist(step):
        orig_ckpt(step)
        for s in list(tr._snap_refs):
            # async checkpoints park unresolved futures; the durable
            # write needs the sealed snapshot (the actor's shard writer
            # gets it from the on_sealed hook)
            save_stage_shard(str(tmp_path), s, tr._resolve_snap(s))
    tr._checkpoint_all = ckpt_and_persist
    ckpt_and_persist(0)
    tr.handles[1]._fail_at = (3, "F")
    # simulate the in-memory snapshot dying with the stage
    orig_restore = tr._restore_source

    def restore(stage_idx):
        tr._snap_refs.pop(stage_idx, None)
        return orig_restore(stage_idx)
    tr._restore_source = restore
    tr.fit(_data_fn, 4)
    assert tr.recoveries
    assert tr.state_digests() == base.state_digests()


def test_no_restore_source_degrades():
    tr = _trainer()
    tr.start()
    tr.handles[1]._fail_at = (2, "F")
    tr._snap_refs.clear()
    with pytest.raises(PipelineDegradedError, match="restore source"):
        tr.fit(_data_fn, 3)


# ------------------------------------------- interleaved virtual stages

V4 = 4          # virtual stages for the interleaving tests


def _vbuilder(vs):
    """Builder for an n_virtual=4 pipeline: one tanh layer per virtual
    stage, loss on the deepest chunk."""
    import jax
    import jax.numpy as jnp
    import optax
    k = jax.random.PRNGKey(100 + vs)
    params = {"w": jax.random.normal(k, (D, D)) * 0.3,
              "b": jnp.zeros((D,))}

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    loss_fn = None
    if vs == V4 - 1:
        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)
    return StageDefinition(stage_fn=stage_fn, params=params,
                           optimizer=optax.adamw(1e-2), loss_fn=loss_fn)


def _vtrainer(virtual_stages, **cfg_kw):
    cfg_kw.setdefault("n_microbatches", M)
    return MPMDPipelineTrainer(
        [_vbuilder] * V4,
        MPMDConfig(virtual_stages=virtual_stages, **cfg_kw),
        FailureConfig(max_failures=2, restart_policy="stage",
                      restart_backoff_s=0.0))


def test_interleaved_matches_plain_bit_identical():
    """The tentpole invariant: v=2 over 2 hosts runs each chunk's F and
    B streams in strict microbatch order, so optimizer state is
    bit-identical to the SAME 4 virtual stages spread plainly over 4
    hosts — and every chunk still compiles exactly once."""
    plain = _vtrainer(virtual_stages=1)
    out_p = plain.fit(_data_fn, 4)
    inter = _vtrainer(virtual_stages=2)
    out_i = inter.fit(_data_fn, 4)
    assert plain.n_stages == 4 and inter.n_stages == 2
    assert inter.state_digests() == plain.state_digests()
    loss_p = [h["loss"] for h in out_p["history"] if "loss" in h]
    loss_i = [h["loss"] for h in out_i["history"] if "loss" in h]
    assert loss_i == loss_p
    for counts in inter.compile_counts():
        assert counts == {"fwd": 1, "bwd": 1, "apply": 1}
    # interleaving shrinks the analytic bubble
    assert out_i["bubble_fraction_analytic"] < \
        out_p["bubble_fraction_analytic"]


def test_interleaved_kill_recovery_bit_identical():
    """A stage hosting TWO chunks dies mid-step: both chunks restore
    from the same boundary, replay in the interleaved order, and the
    final state matches the uninterrupted run bit-for-bit. The rebuilt
    chunks compile once each (fresh runtimes, no retrace churn)."""
    base = _vtrainer(virtual_stages=2)
    base.fit(_data_fn, 6)

    tr = _vtrainer(virtual_stages=2, replay_depth=2)
    tr.start()
    tr.handles[1]._fail_at = (4, "F")          # chunks 1 and 3 die
    out = tr.fit(_data_fn, 6)
    assert len(out["recoveries"]) == 1
    assert tr.state_digests() == base.state_digests()
    for counts in tr.compile_counts():
        assert counts == {"fwd": 1, "bwd": 1, "apply": 1}


# ------------------------------------------------------ fake stage gangs

def test_local_gang_trains_and_matches_solo():
    """A 2-rank gang on stage 1 (fake: two in-process members) must be
    invisible to training semantics: same digests as the solo run, and
    the gang handle fans every compute op out to both ranks."""
    solo = _trainer()
    solo.fit(_data_fn, 4)

    gang = MPMDPipelineTrainer(
        [_builder] * S, MPMDConfig(n_microbatches=M),
        FailureConfig(max_failures=2, restart_policy="stage",
                      restart_backoff_s=0.0),
        stage_gang_sizes=[1, 2, 1])
    gang.fit(_data_fn, 4)
    h = gang.handles[1]
    assert hasattr(h, "members") and len(h.members) == 2
    assert gang.state_digests() == solo.state_digests()
    # both ranks actually ran the stage program (replicas, not spares)
    for m in h.members:
        for rt in m._rts:
            assert rt.step == 4
            assert rt.compile_counts() == {"fwd": 1, "bwd": 1, "apply": 1}


def test_gang_rank_divergence_detected():
    """The replicated-stage invariant: digests are gathered from every
    rank and must agree bit-for-bit — a silently diverged rank raises
    instead of corrupting the next boundary."""
    import jax
    tr = MPMDPipelineTrainer(
        [_builder] * S, MPMDConfig(n_microbatches=M),
        FailureConfig(max_failures=2, restart_policy="stage",
                      restart_backoff_s=0.0),
        stage_gang_sizes=[1, 2, 1])
    tr.fit(_data_fn, 2)
    rt = tr.handles[1].members[1]._rts[0]
    rt.params = jax.tree.map(lambda x: x + 1.0, rt.params)
    with pytest.raises(RuntimeError, match="diverged"):
        tr.state_digests()


# ------------------------------------- off-step I/O and donation parity

def test_async_checkpoint_and_donation_parity():
    """Async off-step checkpointing and buffer donation are pure
    performance knobs: all three configurations land bit-identical
    optimizer state. The async run must also park its boundary
    snapshots UNRESOLVED (no step-path barrier)."""
    base = _trainer()                                   # async + donate on
    base.fit(_data_fn, 5)
    assert all(hasattr(r, "result") for r in base._snap_refs.values())

    sync = _trainer(async_checkpoint=False)
    sync.fit(_data_fn, 5)
    nodonate = _trainer(donate_buffers=False)
    nodonate.fit(_data_fn, 5)
    assert sync.state_digests() == base.state_digests()
    assert nodonate.state_digests() == base.state_digests()


def test_replay_budget_reports_peak_buffers():
    """Satellite: the replay buffer is sized against the CORRECTED
    peak (grad buffers included), and budget() reports the composite
    microbatch-buffer number the controller reasons about."""
    peaks = [plib.peak_live_activations(ops)
             for ops in plib.make_schedule("1f1b", 4, 8, virtual=2)]
    assert peaks == [13, 11, 9, 7]
    buf = MicrobatchReplayBuffer(depth=2, n_microbatches=8,
                                 peak_live_buffers=peaks)
    buf.record(1, [np.zeros((2, 2))] * 8, [np.zeros((2, 2))] * 8)
    b = buf.budget()
    assert b["replay_microbatches"] == 16
    assert b["peak_live_stage_buffers"] == 13
    assert b["peak_microbatch_buffers"] == 29
    assert b["bytes_held"] == 8 * 2 * (2 * 2 * 8)   # 16 float64 4-elt arrays


# ----------------------------------------------------- config validation

def test_mpmd_config_validation():
    with pytest.raises(ValueError, match="checkpoint_every"):
        MPMDConfig(replay_depth=2, checkpoint_every=3).resolved()
    with pytest.raises(ValueError, match="n_microbatches"):
        MPMDConfig(n_microbatches=0).resolved()
    c = MPMDConfig().resolved()
    assert c.checkpoint_every == c.replay_depth


def test_failure_config_validation():
    with pytest.raises(ValueError, match="restart_policy"):
        FailureConfig(restart_policy="worker")
    with pytest.raises(ValueError, match="backoff"):
        FailureConfig(restart_backoff_s=-1.0)
    fc = FailureConfig(max_failures=2, restart_policy="stage")
    assert fc.restart_policy == "stage"


def test_trainer_requires_two_stages():
    with pytest.raises(ValueError, match="2 physical stages"):
        MPMDPipelineTrainer([_builder], MPMDConfig(n_microbatches=M))


def test_backend_executor_replace_gating():
    from ray_tpu.train.backend_executor import BackendExecutor
    from ray_tpu.train.config import ScalingConfig
    ex = BackendExecutor(ScalingConfig(num_workers=2))
    assert ex.supports_worker_replace()
    ex_jd = BackendExecutor(ScalingConfig(num_workers=2),
                            use_jax_distributed=True)
    assert not ex_jd.supports_worker_replace()
    ex_slice = BackendExecutor(ScalingConfig(num_workers=2))
    ex_slice.slice_pod = "pod-0"       # slice gangs fail as a unit
    assert not ex_slice.supports_worker_replace()


# ------------------------------------------------------- cluster tier

@needs_cluster
def test_actor_gang_stage_kill_bit_identical():
    """Real PipelineStageActor gang: stage 1's actor is SIGKILLed
    mid-run; recovery restores its shard from the object store and the
    final state matches the in-process uninterrupted baseline bit for
    bit (same programs, same data, same schedule)."""
    import ray_tpu
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        base = _trainer()
        base.fit(_data_fn, 5)

        tr = MPMDPipelineTrainer(
            [_builder] * S, MPMDConfig(n_microbatches=M, replay_depth=2),
            FailureConfig(max_failures=2, restart_policy="stage",
                          restart_backoff_s=0.0),
            remote=True)
        tr.start()
        killed = []

        def data_fn(step):
            if step == 3 and not killed:
                killed.append(1)
                import threading

                def kill_soon():
                    time.sleep(0.05)       # land mid-step
                    ray_tpu.kill(tr.handles[1].actor)
                threading.Thread(target=kill_soon, daemon=True).start()
            return _data_fn(step)

        out = tr.fit(data_fn, 5)
        assert out["recoveries"], "kill never surfaced as a stage loss"
        assert out["recoveries"][0]["steps_lost"] <= \
            tr.config.replay_depth + 1
        assert tr.state_digests() == base.state_digests()
        for counts in tr.compile_counts():
            assert counts["fwd"] == 1 and counts["bwd"] == 1
        tr.shutdown()
    finally:
        ray_tpu.shutdown()


@needs_cluster
def test_jax_trainer_per_worker_replace():
    """restart_policy="stage": a worker whose loop raises once is
    replaced in its bundle and resumes from the latest checkpoint; the
    fit completes without surfacing the failure."""
    import ray_tpu
    from ray_tpu.train import (Checkpoint, JaxTrainer, RunConfig,
                               ScalingConfig)
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        import tempfile
        marker = os.path.join(tempfile.mkdtemp(), "died_once")

        def loop(config):
            from ray_tpu import train
            ckpt = train.get_checkpoint()
            start = 0
            if ckpt is not None:
                start = ckpt.to_dict()["step"] + 1
            for step in range(start, 6):
                if step == 3 and not os.path.exists(config["marker"]):
                    with open(config["marker"], "w") as f:
                        f.write("x")
                    raise RuntimeError("injected worker death")
                train.report({"step": step},
                             checkpoint=Checkpoint.from_dict(
                                 {"step": step}))

        trainer = JaxTrainer(
            loop, train_loop_config={"marker": marker},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                failure_config=FailureConfig(
                    max_failures=2, restart_policy="stage",
                    restart_backoff_s=0.1)))
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics["step"] == 5
        steps = [m["step"] for m in result.metrics_history]
        assert steps[-1] == 5
        # the replacement resumed from the checkpoint, not step 0:
        # step 3 appears at most twice (once failed pre-report, once
        # after resume), never the full prefix again
        assert steps.count(0) == 1
    finally:
        ray_tpu.shutdown()
