"""IMPALA / DQN / replay-buffer / V-trace tests (reference: rllib's
vtrace tests and tuned-example regressions; V-trace is checked against a
plain-python recursion, algorithms against CartPole smoke training)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import AlgorithmConfig, ReplayBuffer


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=4)
    yield ctx
    ray_tpu.shutdown()


def test_vtrace_matches_python_recursion():
    import jax.numpy as jnp

    from ray_tpu.rl.vtrace import vtrace
    rng = np.random.default_rng(0)
    T, B = 7, 3
    b_logp = rng.normal(size=(T, B)).astype(np.float32) * 0.3
    t_logp = b_logp + rng.normal(size=(T, B)).astype(np.float32) * 0.2
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    discounts = (0.9 * (rng.random((T, B)) > 0.2)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    boot = rng.normal(size=(B,)).astype(np.float32)

    out = vtrace(jnp.asarray(b_logp), jnp.asarray(t_logp),
                 jnp.asarray(rewards), jnp.asarray(discounts),
                 jnp.asarray(values), jnp.asarray(boot))

    # plain-python reference recursion (IMPALA paper eq. 1)
    rhos = np.minimum(1.0, np.exp(t_logp - b_logp))
    cs = np.minimum(1.0, np.exp(t_logp - b_logp))
    vs = np.zeros((T, B), np.float32)
    acc = np.zeros(B, np.float32)
    for t in reversed(range(T)):
        v_tp1 = values[t + 1] if t + 1 < T else boot
        delta = rhos[t] * (rewards[t] + discounts[t] * v_tp1 - values[t])
        acc = delta + discounts[t] * cs[t] * acc
        vs[t] = acc + values[t]
    pg_adv = np.zeros((T, B), np.float32)
    for t in range(T):
        vs_tp1 = vs[t + 1] if t + 1 < T else boot
        pg_adv[t] = rhos[t] * (rewards[t] + discounts[t] * vs_tp1
                               - values[t])
    np.testing.assert_allclose(np.asarray(out.vs), vs, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out.pg_advantages), pg_adv,
                               rtol=1e-5, atol=1e-5)


def test_replay_buffer_ring_and_sampling():
    buf = ReplayBuffer(capacity=10)
    buf.add({"x": np.arange(6, dtype=np.float32)})
    assert len(buf) == 6
    buf.add({"x": np.arange(6, 14, dtype=np.float32)})   # wraps
    assert len(buf) == 10
    sample = buf.sample(32)["x"]
    # oldest entries (0..3) were overwritten by the wrap
    assert sample.min() >= 4.0
    assert set(np.unique(sample)).issubset(set(range(4, 14)))


def test_impala_cartpole_smoke(ray_start):
    from ray_tpu.rl import IMPALA
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                           rollout_fragment_length=32)
              .training(lr=5e-4))
    algo = IMPALA(config)
    try:
        for _ in range(3):
            out = algo.train()
        assert out["num_env_steps_sampled"] > 0
        assert np.isfinite(out["total_loss"])
        assert out["training_iteration"] == 3
    finally:
        algo.stop()


def test_dqn_cartpole_smoke(ray_start):
    from ray_tpu.rl import DQN
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                           rollout_fragment_length=32)
              .training(lr=1e-3, minibatch_size=64))
    algo = DQN(config)
    try:
        for _ in range(3):
            out = algo.train()
        assert out["replay_size"] > 0
        assert np.isfinite(out["td_loss"])
        assert out["epsilon"] < 1.0
    finally:
        algo.stop()
