"""Radix prefix KV cache (ray_tpu/inference/prefix_cache.py) + coalesced
token streaming (PR 10): trie insert/longest-match/ref-count/LRU units,
greedy bit-exact hit-vs-miss parity through the engine, the compile-once
contract with the cache on, coalesced-stream exactly-once semantics
(including resume mid-coalesced-chunk under replica death), session
affinity routing, and the bench-side decode plausibility guard.

Everything above the `needs_cluster` line is CPU-pinned and cluster-free
(tier-1 on any interpreter)."""

import sys
import time

import numpy as np
import pytest

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")


# --------------------------------------------------------------------------
# trie units (pure host code, no JAX)
# --------------------------------------------------------------------------

def _cache(chunk=4, blocks=8):
    from ray_tpu.inference import RadixPrefixCache
    return RadixPrefixCache(chunk, blocks)


def test_trie_insert_and_longest_match():
    c = _cache(chunk=4, blocks=8)
    toks = list(range(40, 53))              # 13 tokens = 3 full chunks
    created = c.insert(toks)
    assert [off for off, _ in created] == [0, 4, 8]
    # longest match walks the chunk path; capped BELOW the prompt length
    m, nodes = c.match(toks)
    assert m == 12 and len(nodes) == 3      # 13 tokens: last one prefills
    c.release(nodes)
    # a 12-token prompt with the same prefix may match at most 8 (cap)
    m, nodes = c.match(toks[:12])
    assert m == 8
    c.release(nodes)
    # diverging suffix matches only the shared chunks
    m, nodes = c.match(toks[:8] + [99, 98, 97, 96, 95])
    assert m == 8
    c.release(nodes)
    # diverging FIRST chunk matches nothing
    m, nodes = c.match([99] + toks[1:])
    assert m == 0 and nodes == []
    # re-insert of cached chunks allocates nothing new
    assert c.insert(toks) == []
    # extension allocates only the new chunk
    created = c.insert(toks[:12] + [7, 7, 7, 7])
    assert [off for off, _ in created] == [12]


def test_trie_refcount_blocks_eviction_lru_under_pressure():
    c = _cache(chunk=2, blocks=2)
    c.insert([1, 2])                        # block A (oldest stamp)
    c.insert([3, 4])                        # block B
    # pool exhausted: next insert must evict the LRU leaf (A)
    created = c.insert([5, 6])
    assert len(created) == 1 and c.evictions == 1
    assert c.match([1, 2, 9])[0] == 0       # A is gone
    # pin B (an in-flight request matched it): under pressure only the
    # UNPINNED leaves cycle; B survives arbitrarily many evictions
    m, pinned = c.match([3, 4, 9])
    assert m == 2
    c.insert([7, 8])                        # evicts [5,6]
    c.insert([9, 10])                       # evicts [7,8]
    assert c.evictions == 3
    assert c.match([3, 4, 1])[0] == 2       # B still matchable
    c.release(pinned)


def test_trie_pinned_never_evicted_explicitly():
    c = _cache(chunk=2, blocks=1)
    c.insert([1, 2])
    m, nodes = c.match([1, 2, 3])
    assert m == 2
    # the only block is pinned: allocation for a new chunk must fail
    # (insert returns nothing) rather than reuse pinned memory
    assert c.insert([5, 6]) == []
    c.release(nodes)
    assert len(c.insert([5, 6])) == 1       # unpinned -> evictable
    assert c.evictions == 1


def test_trie_interior_nodes_not_evicted_before_leaves():
    c = _cache(chunk=2, blocks=3)
    c.insert([1, 2, 3, 4, 5, 6])            # chain of 3 nodes
    # pressure: the leaf (5,6) must go first, never the root chunk
    created = c.insert([9, 9])
    assert len(created) == 1
    assert c.match([1, 2, 3, 4, 9])[0] == 4  # interior chain survives


# --------------------------------------------------------------------------
# engine integration: parity + compile-once
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig, TransformerLM
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _engine(model, params, **kw):
    from ray_tpu.inference import EngineConfig, InferenceEngine
    cfg = dict(n_slots=2, max_len=48, prefill_chunk=4, prefill_budget=8)
    cfg.update(kw)
    return InferenceEngine(model, params, EngineConfig(**cfg))


def _drain(eng, handle, max_steps=300):
    for _ in range(max_steps):
        eng.step()
        if handle.finish_reason is not None:
            return handle.tokens()
    raise AssertionError("request did not finish")


def test_greedy_bit_exact_hit_vs_miss_vs_uncached(tiny):
    """The acceptance contract: greedy output is bit-identical whether
    the prompt's prefix prefilled from scratch (miss), restored from
    cached blocks (hit), or ran through a cache-disabled engine."""
    _, model, params = tiny
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, 128, 17)
    eng_off = _engine(model, params)
    want = _drain(eng_off, eng_off.submit(prompt, max_new_tokens=10))
    eng = _engine(model, params, prefix_cache_slots=1)
    h_miss = eng.submit(prompt, max_new_tokens=10)
    miss = _drain(eng, h_miss)
    h_hit = eng.submit(prompt, max_new_tokens=10)
    hit = _drain(eng, h_hit)
    assert h_miss.prefix_matched == 0
    assert h_hit.prefix_matched == 16       # 17 tokens, cap leaves 1
    assert miss == want and hit == want
    # a longer prompt sharing the prefix also matches and stays exact
    prompt2 = np.concatenate([prompt, rng.randint(0, 128, 9)])
    eng_off2 = _engine(model, params)
    want2 = _drain(eng_off2, eng_off2.submit(prompt2, max_new_tokens=10))
    h2 = eng.submit(prompt2, max_new_tokens=10)
    assert _drain(eng, h2) == want2
    assert h2.prefix_matched == 16


def test_decode_compiles_exactly_once_with_cache_on(tiny):
    """Hits, misses, evictions and block restores never retrace any of
    the engine's programs — the copy fns are fixed-shape too."""
    _, model, params = tiny
    eng = _engine(model, params, prefix_cache_slots=1)
    rng = np.random.RandomState(8)
    shared = rng.randint(0, 128, 12)
    hs = []
    for i in range(6):
        p = np.concatenate([shared, rng.randint(0, 128, 1 + i)])
        hs.append(eng.submit(p, max_new_tokens=4))
    for _ in range(400):
        eng.step()
        if all(h.finish_reason for h in hs):
            break
    assert all(h.finish_reason for h in hs)
    st = eng.stats()
    assert st["prefix_hits"] >= 4, st
    assert eng.decode_compile_count == 1
    assert eng.prefill_compile_count == 1
    assert eng._decode_fn._cache_size() == 1
    assert eng._load_span_fn._cache_size() == 1
    assert eng._save_span_fn._cache_size() == 1


def test_cache_eviction_under_slot_pressure_keeps_serving(tiny):
    """A block pool much smaller than the working set evicts LRU and
    keeps producing exact output (hits just get rarer)."""
    _, model, params = tiny
    eng = _engine(model, params, prefix_cache_slots=1, max_len=16,
                  prefill_chunk=4)           # 4 blocks total
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, 128, 9) for _ in range(5)]
    for p in prompts + prompts:
        h = eng.submit(p, max_new_tokens=3)
        _drain(eng, h)
    st = eng.stats()
    assert st["prefix_evictions"] > 0
    assert eng.decode_compile_count == 1
    # exactness after heavy eviction churn
    eng_off = _engine(model, params, max_len=16, prefill_chunk=4)
    want = _drain(eng_off, eng_off.submit(prompts[0], max_new_tokens=3))
    assert _drain(eng, eng.submit(prompts[0], max_new_tokens=3)) == want


# --------------------------------------------------------------------------
# coalesced streaming: RequestHandle.next_many + handle-layer unpack
# --------------------------------------------------------------------------

def test_next_many_coalesces_and_never_drops_the_tail(tiny):
    """next_many returns >= 1 token per call, caps at max_tokens, and a
    finish mid-batch delivers the collected tokens NOW with
    StopIteration only on the following call."""
    _, model, params = tiny
    eng = _engine(model, params).start()
    try:
        h = eng.submit(np.arange(1, 6), max_new_tokens=11)
        got = [h.next(timeout=30)]          # eager first token
        batches = []
        while True:
            try:
                b = h.next_many(4, flush_s=0.05, timeout=30)
            except StopIteration:
                break
            assert 1 <= len(b) <= 4
            batches.append(b)
            got.extend(b)
        assert len(got) == 11
        assert h.finish_reason == "length"
    finally:
        eng.stop()


def test_llm_deployment_streams_coalesced_chunks(tiny):
    """Direct-call contract: first chunk is the eager single token; all
    chunks respect stream_coalesce_tokens; flattening equals generate()."""
    cfg, model, params = tiny
    from ray_tpu.inference import LLMDeployment
    dep = LLMDeployment(cfg, n_slots=2, max_len=64, prefill_chunk=4,
                        prefill_budget=8, stream_coalesce_tokens=5,
                        stream_coalesce_ms=15.0,
                        params_fn=lambda: params)
    try:
        chunks = list(dep([1, 2, 3], max_new_tokens=17))
        assert len(chunks[0]) == 1          # TTFT never waits the window
        assert all(len(c) <= 5 for c in chunks)
        flat = [t for c in chunks for t in c]
        assert len(flat) == 17
        assert dep.generate([1, 2, 3], max_new_tokens=17) == flat
        # per-call override down to per-token framing
        singles = list(dep([1, 2, 3], max_new_tokens=5,
                           stream_coalesce_tokens=1))
        assert [len(c) for c in singles] == [1] * 5
    finally:
        dep.engine.stop()


class _StubGen:
    """Stands in for the core ObjectRefGenerator (coalesced frames)."""

    def __init__(self, frames, fail_after_frames=None, error=None):
        self._frames = list(frames)
        self._i = 0
        self._fail = fail_after_frames
        self._error = error
        self.closed = False

    def next(self, timeout=None):
        if self._fail is not None and self._i >= self._fail:
            raise self._error
        if self._i >= len(self._frames):
            raise StopIteration
        v = self._frames[self._i]
        self._i += 1
        return v

    def close(self):
        self.closed = True


def _wrap(stub, **kw):
    from ray_tpu.serve.handle import DeploymentResponseGenerator
    g = DeploymentResponseGenerator(stub, None, 0, **kw)
    g._get = lambda ref: ref
    return g


def test_coalesced_resume_mid_chunk_exactly_once():
    """Replica dies after delivering one full frame and while a second
    is buffered client-side: the resume carries TOKEN-granular state
    (fetched tokens, flattened), the buffered tail still reaches the
    consumer, and the continuation starts at the exact next token —
    zero dropped, zero duplicated."""
    import ray_tpu
    seen = {}

    def resume(fetched, chunks):
        seen["fetched"] = fetched
        seen["chunks"] = list(chunks)
        return _wrap(_StubGen([[50, 60], [70]]), unpack=True), 0

    g = _wrap(_StubGen([[10], [20, 30, 40]], fail_after_frames=2,
                       error=ray_tpu.ActorDiedError("replica gone")),
              unpack=True, resume=resume, record_chunks=True)
    # consume ONE token: [20,30,40] is fetched+buffered when death lands
    assert next(g) == 10
    assert next(g) == 20
    assert list(g) == [30, 40, 50, 60, 70]
    # resume saw every FETCHED token (buffered ones included: they are
    # delivered from the buffer, so the fresh stream continues after)
    assert seen == {"fetched": 4, "chunks": [10, 20, 30, 40]}


def test_coalesced_nonresumable_skip_is_token_granular():
    """Non-resumable restart: the fresh stream re-produces everything
    with DIFFERENT frame boundaries; the wrapper skips exactly the
    fetched token count, keeping a straddling frame's tail."""
    import ray_tpu

    def resume(fetched, chunks):
        assert chunks is None
        return _wrap(_StubGen([[10, 20, 30], [40, 50]]),
                     unpack=True), fetched

    g = _wrap(_StubGen([[10], [20]], fail_after_frames=2,
                       error=ray_tpu.ActorDiedError("gone")),
              unpack=True, resume=resume)
    assert list(g) == [10, 20, 30, 40, 50]


def test_next_batch_drains_frames_without_blocking_per_token():
    g = _wrap(_StubGen([[1, 2, 3], [4]]), unpack=True)
    assert g.next_batch() == [1, 2, 3]
    assert g.next_batch() == [4]
    with pytest.raises(StopIteration):
        g.next_batch()
    # mixed use: __next__ then next_batch drains the remainder
    g = _wrap(_StubGen([[1, 2, 3]]), unpack=True)
    assert next(g) == 1
    assert g.next_batch() == [2, 3]


def test_plain_streams_unchanged_without_unpack():
    """A non-coalesced deployment yielding list VALUES must not be
    unpacked (the flag, not the type, decides)."""
    vals = [{"a": 1}, [9, 9], "x"]
    g = _wrap(_StubGen(vals))
    assert list(g) == vals


# --------------------------------------------------------------------------
# session-affinity routing (ROADMAP 1c first slice)
# --------------------------------------------------------------------------

def _router(n):
    from ray_tpu.serve.handle import _Router
    r = _Router.__new__(_Router)     # skip ctor (no long-poll client)
    import threading
    r.deployment_name = "d"
    r.app_name = "a"
    r.replicas = [object() for _ in range(n)]
    r.inflight = {i: 0 for i in range(n)}
    r.shared_load = {}
    r.version = 0
    r.resumable = False
    r.coalesced = False
    r.prefix_routed = False
    r.replica_ids = []
    r._summaries = {}
    r._summary_chunk = None
    r._last_summary_refresh = time.monotonic() + 1e6
    r.lock = threading.Lock()
    r._last_refresh = time.monotonic() + 1e6   # never refresh
    r.model_map = {}
    return r


def test_session_id_routes_sticky():
    r = _router(4)
    picks = {r.pick(session_id="sess-abc")[0] for _ in range(8)}
    assert len(picks) == 1                  # same session -> same replica
    # sessions spread (crc32 over 64 ids on 4 replicas hits them all)
    spread = {r.pick(session_id=f"s{i}")[0] for i in range(64)}
    assert spread == {0, 1, 2, 3}


def test_session_fallback_least_ongoing_when_sticky_unavailable():
    r = _router(3)
    sticky = r.pick(session_id="user-1")[0]
    r.inflight = {0: 5, 1: 5, 2: 5}
    others = [i for i in range(3) if i != sticky]
    r.inflight[others[0]] = 0               # clearly least-ongoing
    idx, _ = r.pick(session_id="user-1", avoid={sticky})
    assert idx == others[0]


def test_session_rehashes_when_replica_set_shrinks():
    r = _router(4)
    before = r.pick(session_id="sess-x")[0]
    r.replicas = r.replicas[:2]             # detach (drain/preempt)
    r.inflight = {0: 0, 1: 0}
    after = r.pick(session_id="sess-x")[0]
    assert after in (0, 1)
    # deterministic on the new set
    assert r.pick(session_id="sess-x")[0] == after
    assert before in range(4)


# --------------------------------------------------------------------------
# bench-side decode plausibility guard (satellite: r05 runs-list leak)
# --------------------------------------------------------------------------

def test_bench_decode_guard_filters_runs_not_just_median():
    import bench
    r = {"runs": [1514.2, 8500.1, 384000000.0],    # the r05 artifact
         "roofline_tokens_per_s": 50000.0, "e2e_tokens_per_s": 1217.9}
    c = bench._plausible_decode(r)
    assert c["runs"] == [1514.2, 8500.1]           # rejected run GONE
    assert c["decode_tokens_per_s"] == 8500.1
    assert c["rejected_by_bench"] == 1
    assert 0 < c["spread"] < 1.0                   # from accepted only
    assert c["e2e_tokens_per_s"] == 1217.9


def test_bench_decode_guard_rejects_implausible_e2e_and_empty():
    import bench
    r = {"runs": [5000.0], "roofline_tokens_per_s": 50000.0,
         "e2e_tokens_per_s": 9.9e7}
    assert bench._plausible_decode(r)["e2e_tokens_per_s"] is None
    assert bench._plausible_decode(
        {"runs": [384e6], "roofline_tokens_per_s": 5e4}) is None
    # no roofline field (older probe): the absolute cap still holds
    c = bench._plausible_decode({"runs": [8000.0, 384e6]})
    assert c["runs"] == [8000.0]


# --------------------------------------------------------------------------
# cluster tier (Python >= 3.12): coalesced exactly-once under chaos
# --------------------------------------------------------------------------

def _tiny_llm_config():
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig
    return TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=512, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def ray_start():
    import ray_tpu
    from ray_tpu import serve
    ctx = ray_tpu.init(num_cpus=4)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


@needs_cluster
def test_coalesced_stream_exactly_once_under_preempt_chaos(ray_start):
    """PR 9's preempt_one() against PR 10's coalesced streams: a replica
    preempted (and a second one hard-killed) mid-coalesced-chunk must
    deliver every token exactly once, as per-token iteration, matching
    the greedy oracle — the resume path carries token-granular state
    through the chunk envelope."""
    from ray_tpu import serve
    from ray_tpu.inference import LLMDeployment
    from ray_tpu.util.chaos import ServeReplicaKiller
    dep = serve.deployment(LLMDeployment, num_replicas=2,
                           preempt_grace_s=30.0)
    serve.run(dep.bind(_tiny_llm_config(), n_slots=2, max_len=512,
                       prefill_chunk=8, prefill_budget=16,
                       stream_coalesce_tokens=4, stream_coalesce_ms=10.0),
              name="llm-coalesce")
    h = serve.get_app_handle("llm-coalesce")
    oracle = list(h.options(stream=True).remote([5, 6, 7],
                                                max_new_tokens=32))
    assert len(oracle) == 32                # DRG unpacks to tokens
    killer = ServeReplicaKiller("llm-coalesce", "LLMDeployment")

    # graceful preemption mid-stream: drained replica finishes it
    gen = h.options(stream=True).remote([5, 6, 7], max_new_tokens=32)
    got = [next(gen) for _ in range(5)]
    assert killer.preempt_one()
    got.extend(gen)
    assert got == oracle
    assert killer.wait_for_replacement(timeout_s=90, handle=h)

    # hard kill mid-stream: resume_tokens continuation on the survivor
    gen = h.options(stream=True).remote([5, 6, 7], max_new_tokens=32)
    got = [next(gen) for _ in range(5)]     # > one coalesced chunk
    assert killer.kill_one(prefer_busy=True)
    got.extend(gen)
    assert got == oracle
    serve.delete("llm-coalesce")
