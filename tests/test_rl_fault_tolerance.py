"""Env-runner fault tolerance (reference: rllib/utils/actor_manager.py
FaultTolerantActorManager + AlgorithmConfig restart_failed_env_runners):
dead env runners are replaced in-slot mid-training with current weights
re-pushed; the training loop survives on the survivors' data; restarts
are budgeted and disabling them fails fast."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import AlgorithmConfig
from ray_tpu.rl.actor_manager import RunnerSetBroken


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _config(**training):
    return (AlgorithmConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=2,
                         rollout_fragment_length=16)
            .training(**training))


def test_ppo_survives_runner_death(ray_start):
    from ray_tpu.rl import PPO
    algo = PPO(_config())
    try:
        r1 = algo.train()
        assert r1["num_env_steps_sampled"] == 64  # both runners
        victim = algo.env_runners[0]
        ray_tpu.kill(victim)
        r2 = algo.train()  # victim's round drops, slot is refilled
        assert algo.env_runners.num_restarts == 1
        assert len(algo.env_runners) == 2
        assert algo.env_runners[0] is not victim
        # next round: both runners (incl. the replacement) sample again
        r3 = algo.train()
        assert r3["num_env_steps_sampled"] == 64, r3
    finally:
        algo.stop()


def test_impala_survives_runner_death(ray_start):
    from ray_tpu.rl import IMPALA
    algo = IMPALA(_config(lr=1e-3))
    try:
        algo.train()
        victim = algo.env_runners[1]
        ray_tpu.kill(victim)
        algo.train()   # the in-flight fragment surfaces ActorDiedError
        assert algo.env_runners.num_restarts == 1
        assert len(algo.env_runners) == 2
        r3 = algo.train()
        assert r3["num_env_steps_sampled"] > 0
    finally:
        algo.stop()


def test_restarts_disabled_fails_fast(ray_start):
    from ray_tpu.rl import PPO
    algo = PPO(_config(restart_failed_env_runners=False))
    try:
        algo.train()
        ray_tpu.kill(algo.env_runners[0])
        with pytest.raises(RunnerSetBroken, match="disabled"):
            algo.train()
    finally:
        algo.stop()


def test_restart_budget_exhausts(ray_start):
    from ray_tpu.rl import PPO
    algo = PPO(_config(max_env_runner_restarts=1))
    try:
        algo.train()
        ray_tpu.kill(algo.env_runners[0])
        algo.train()                      # consumes the only restart
        assert algo.env_runners.num_restarts == 1
        ray_tpu.kill(algo.env_runners[1])
        with pytest.raises(RunnerSetBroken, match="exhausted"):
            algo.train()
    finally:
        algo.stop()
