"""Actor concurrency groups (reference:
src/ray/core_worker/transport/concurrency_group_manager.h + fibers —
named groups with independent concurrency limits; the default group
keeps its ordered single queue).

Here: per-group asyncio queue + consumer pool on the actor's worker;
methods declare their group with @ray_tpu.method(concurrency_group=...)
or per-call via .options(concurrency_group=...)."""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote(concurrency_groups={"io": 2})
class Groups:
    def __init__(self):
        self.events = []

    def busy(self, t):
        self.events.append(("busy-start", time.monotonic()))
        time.sleep(t)
        self.events.append(("busy-end", time.monotonic()))
        return "busy"

    @ray_tpu.method(concurrency_group="io")
    def ping(self):
        self.events.append(("ping", time.monotonic()))
        return "pong"

    def get_events(self):
        return list(self.events)


def test_io_group_not_blocked_by_default_group(ray_start):
    """A long default-group call must NOT delay io-group methods — the
    whole point of groups (reference: concurrency groups keep health
    checks responsive behind busy user code)."""
    a = Groups.remote()
    ray_tpu.get(a.get_events.remote(), timeout=30)   # actor fully up
    slow = a.busy.remote(4.0)
    time.sleep(0.5)     # busy() is definitely running
    t0 = time.monotonic()
    assert ray_tpu.get(a.ping.remote(), timeout=10) == "pong"
    ping_latency = time.monotonic() - t0
    assert ping_latency < 2.0, \
        f"io-group ping waited {ping_latency:.1f}s behind default group"
    assert ray_tpu.get(slow, timeout=30) == "busy"


def test_per_call_group_override(ray_start):
    """.options(concurrency_group=...) routes a single call into a
    group, overriding the method's declared group."""
    a = Groups.remote()
    ray_tpu.get(a.get_events.remote(), timeout=30)   # actor fully up
    slow = a.busy.remote(3.0)
    time.sleep(0.3)
    t0 = time.monotonic()
    # get_events is default-group by declaration; route it via io
    ev = ray_tpu.get(
        a.get_events.options(concurrency_group="io").remote(), timeout=10)
    assert time.monotonic() - t0 < 2.0
    assert any(k == "busy-start" for k, _ in ev)
    ray_tpu.get(slow, timeout=30)


def test_group_width_limits_parallelism(ray_start):
    """The io group is 2-wide: three concurrent 1s io calls take ~2s
    (2 parallel + 1 queued), not ~1s or ~3s."""

    @ray_tpu.remote(concurrency_groups={"io": 2})
    class Width:
        @ray_tpu.method(concurrency_group="io")
        def io_sleep(self, t):
            time.sleep(t)
            return True

    a = Width.remote()
    ray_tpu.get(a.io_sleep.remote(0.01), timeout=30)   # warm worker
    t0 = time.monotonic()
    refs = [a.io_sleep.remote(1.0) for _ in range(3)]
    assert all(ray_tpu.get(refs, timeout=30))
    dt = time.monotonic() - t0
    assert 1.7 < dt < 3.4, f"3 x 1s on a 2-wide group took {dt:.2f}s"


def test_default_group_stays_ordered(ray_start):
    """Default-group calls from one submitter execute in order even when
    groups exist (the reference's ordered default group)."""

    @ray_tpu.remote(concurrency_groups={"io": 1})
    class Ordered:
        def __init__(self):
            self.seen = []

        def mark(self, i):
            self.seen.append(i)
            return i

        def get(self):
            return list(self.seen)

    a = Ordered.remote()
    refs = [a.mark.remote(i) for i in range(20)]
    ray_tpu.get(refs, timeout=30)
    assert ray_tpu.get(a.get.remote(), timeout=10) == list(range(20))
