"""Trace context propagation (reference:
python/ray/util/tracing/tracing_helper.py:34 — spans wrap remote calls
with the trace context carried in task metadata; here the context is
(trace_id, span_id, parent_span_id) stamped on every task spec and
surfaced via task events / the timeline export)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 1, "resources": {"n1": 1.0}})
    c.add_node(num_cpus=1, resources={"n2": 1.0})
    ray_tpu.init(address=c.address)
    c.wait_for_nodes()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _events_by_name(w, names, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        rows = {r.get("name"): r
                for r in w.gcs_call("list_task_events", limit=10000)}
        if all(n in rows and rows[n].get("span_id") for n in names):
            return rows
        time.sleep(0.5)
    raise AssertionError(f"missing events {names}: have {list(rows)}")


def test_parent_child_linkage_across_nodes(cluster):
    """driver -> outer (node 1) -> inner (node 2): one trace id end to
    end, inner's parent span == outer's span, outer's parent is the
    driver's root context (no parent span)."""

    @ray_tpu.remote(resources={"n2": 0.1}, num_cpus=0.1, name="inner_t")
    def inner(x):
        return x + 1

    @ray_tpu.remote(resources={"n1": 0.1}, num_cpus=0.1, name="outer_t")
    def outer():
        return ray_tpu.get(inner.remote(1))

    assert ray_tpu.get(outer.remote(), timeout=60) == 2
    w = ray_tpu._get_worker()
    rows = _events_by_name(w, ["outer_t", "inner_t"])
    o, i = rows["outer_t"], rows["inner_t"]
    assert o["trace_id"] == i["trace_id"], (o, i)
    assert i["parent_span_id"] == o["span_id"], (o, i)
    assert not o.get("parent_span_id"), o
    assert o["node_id"] != i["node_id"], "tasks did not cross nodes"


def test_actor_calls_carry_trace(cluster):
    @ray_tpu.remote
    class A:
        def m(self):
            return "ok"

    a = A.remote()
    assert ray_tpu.get(a.m.remote(), timeout=60) == "ok"
    w = ray_tpu._get_worker()
    rows = _events_by_name(w, ["m"])
    assert rows["m"].get("trace_id") and rows["m"].get("span_id")


def test_timeline_export_includes_spans(cluster, tmp_path):
    @ray_tpu.remote(name="traced_task")
    def t():
        return 1

    assert ray_tpu.get(t.remote(), timeout=60) == 1
    w = ray_tpu._get_worker()
    _events_by_name(w, ["traced_task"])
    out = ray_tpu.timeline(str(tmp_path / "tl.json"))
    import json
    with open(out) as f:
        events = json.load(f)
    traced = [e for e in events if e["name"] == "traced_task"]
    assert traced and traced[0]["args"]["trace_id"], traced[:1]
