"""Config/flag system: registry, env overrides, propagation.

Mirrors the reference's RAY_CONFIG behavior (reference:
src/ray/common/ray_config_def.h — env-overridable typed flags;
node_manager.proto:432 GetSystemConfig head->node propagation).
"""

import os
import subprocess
import sys

import pytest

from ray_tpu._private.config import Config, cfg, flags


def test_defaults_and_registry():
    assert cfg.lease_idle_timeout_s == 1.0
    assert cfg.task_max_retries == 3
    assert cfg.transfer_chunk_bytes == 8 * 1024 * 1024
    # binary data plane tunables (data_plane.py)
    assert cfg.data_plane_enabled is True
    assert cfg.transfer_streams >= 1
    assert cfg.transfer_stripe_min_bytes > 0
    assert len(flags()) >= 20
    with pytest.raises(AttributeError):
        cfg.no_such_flag


def test_data_plane_env_toggles(monkeypatch):
    c = Config()
    monkeypatch.setenv("RAY_TPU_DATA_PLANE_ENABLED", "0")
    monkeypatch.setenv("RAY_TPU_TRANSFER_STREAMS", "4")
    assert c.data_plane_enabled is False
    assert c.transfer_streams == 4


def test_env_override(monkeypatch):
    c = Config()
    monkeypatch.setenv("RAY_TPU_LEASE_IDLE_TIMEOUT_S", "7.5")
    monkeypatch.setenv("RAY_TPU_TASK_MAX_RETRIES", "9")
    assert c.lease_idle_timeout_s == 7.5
    assert c.task_max_retries == 9
    monkeypatch.setenv("RAY_TPU_TASK_MAX_RETRIES", "not-an-int")
    with pytest.raises(ValueError):
        c.task_max_retries


def test_explicit_beats_env(monkeypatch):
    c = Config()
    monkeypatch.setenv("RAY_TPU_NODE_DEATH_TIMEOUT_S", "99")
    c.set("node_death_timeout_s", 3.0)
    assert c.node_death_timeout_s == 3.0
    c.reset("node_death_timeout_s")
    assert c.node_death_timeout_s == 99.0


def test_snapshot_apply_roundtrip():
    c = Config()
    c.set("heartbeat_interval_s", 0.123)
    snap = c.snapshot()
    c2 = Config()
    c2.apply(snap)
    assert c2.heartbeat_interval_s == 0.123
    # unknown keys ignored (newer head / older node)
    c2.apply({"flag_from_the_future": 1})
    assert "describe" and "heartbeat_interval_s" in c2.describe()


def test_cluster_propagation(tmp_path):
    """_system_config set at init reaches worker processes through the
    GCS snapshot handshake."""
    script = """
import ray_tpu
from ray_tpu._private.config import cfg
ray_tpu.init(num_cpus=2, _system_config={"lease_idle_timeout_s": 4.25})

@ray_tpu.remote
def read_flag():
    from ray_tpu._private.config import cfg
    return cfg.lease_idle_timeout_s

assert cfg.lease_idle_timeout_s == 4.25
got = ray_tpu.get(read_flag.remote(), timeout=60)
assert got == 4.25, got
ray_tpu.shutdown()
print("PROPAGATED")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=180)
    assert "PROPAGATED" in out.stdout, (out.stdout, out.stderr[-2000:])
