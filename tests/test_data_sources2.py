"""Projection pushdown + sql/webdataset sources (reference:
python/ray/data logical/rules projection pushdown,
_internal/datasource/sql_datasource.py, webdataset_datasource.py)."""

import os
import sqlite3
import tarfile

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data import execution as exe


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


@pytest.fixture()
def pq_file(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    t = pa.table({"a": np.arange(100, dtype=np.int64),
                  "b": np.arange(100, dtype=np.float64) * 2.0,
                  "payload": [b"x" * 1000] * 100})
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path, row_group_size=25)
    return path


def test_projection_pushdown_rebinds_read(pq_file):
    """The optimized plan's ReadStage must be rebound to the projected
    columns (plan-level check, no cluster needed)."""
    ds = rd.read_parquet(pq_file).select_columns(["a"])
    optimized = exe.optimize_plan(list(ds._stages))
    read = optimized[0]
    assert isinstance(read, exe.ReadStage)
    # rebound fns read only column "a": execute one locally and check
    blocks = list(read.read_fns[0]())
    assert blocks[0].column_names == ["a"]


def test_projection_pushdown_chained_selects(pq_file):
    """Chained selects: only the FIRST (widest) projection pushes into
    the read — pushing the narrower one would starve the earlier select
    of its columns (round-5 review finding)."""
    ds = rd.read_parquet(pq_file).select_columns(["a", "b"]) \
        .select_columns(["a"])
    optimized = exe.optimize_plan(list(ds._stages))
    blocks = list(optimized[0].read_fns[0]())
    assert set(blocks[0].column_names) == {"a", "b"}


def test_projection_chained_end_to_end(ray_start, pq_file):
    rows = rd.read_parquet(pq_file).select_columns(["a", "b"]) \
        .select_columns(["a"]).take(3)
    assert rows == [{"a": 0}, {"a": 1}, {"a": 2}]


def test_read_sql_sharded_with_order_by(ray_start, tmp_path):
    """Sharded read of a query with ORDER BY (round-5 review: WHERE
    splicing broke on any ORDER BY/GROUP BY/LIMIT suffix)."""
    db = str(tmp_path / "ob.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE kv (k INTEGER, grp TEXT)")
    conn.executemany("INSERT INTO kv VALUES (?, ?)",
                     [(i, "ab"[i % 2]) for i in range(10)])
    conn.commit()
    conn.close()
    ds = rd.read_sql("SELECT k, grp FROM kv WHERE k >= 2 ORDER BY k",
                     lambda: sqlite3.connect(db),
                     shard_column="grp", shard_keys=["a", "b"])
    rows = ds.take_all()
    assert sorted(r["k"] for r in rows) == list(range(2, 10))


def test_projection_pushdown_through_limit(pq_file):
    ds = rd.read_parquet(pq_file).limit(10).select_columns(["b"])
    optimized = exe.optimize_plan(list(ds._stages))
    blocks = list(optimized[0].read_fns[0]())
    assert blocks[0].column_names == ["b"]


def test_projection_not_pushed_past_udf(pq_file):
    """An arbitrary map between read and project may need the dropped
    columns — the read must stay unpruned."""
    ds = rd.read_parquet(pq_file) \
        .map(lambda r: {**r, "c": r["b"] + 1}) \
        .select_columns(["c"])
    optimized = exe.optimize_plan(list(ds._stages))
    blocks = list(optimized[0].read_fns[0]())
    assert set(blocks[0].column_names) == {"a", "b", "payload"}


def test_projection_end_to_end(ray_start, pq_file):
    rows = rd.read_parquet(pq_file).select_columns(["a"]).take(5)
    assert rows == [{"a": i} for i in range(5)]
    # explicit columns= arg works without a projection stage
    rows = rd.read_parquet(pq_file, columns=["b"]).take(2)
    assert rows == [{"b": 0.0}, {"b": 2.0}]


def test_read_sql_single_and_sharded(ray_start, tmp_path):
    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE kv (k INTEGER, grp TEXT, v REAL)")
    conn.executemany("INSERT INTO kv VALUES (?, ?, ?)",
                     [(i, "ab"[i % 2], float(i)) for i in range(20)])
    conn.commit()
    conn.close()

    ds = rd.read_sql("SELECT k, v FROM kv ORDER BY k",
                     lambda: sqlite3.connect(db))
    rows = ds.take_all()
    assert len(rows) == 20 and rows[0] == {"k": 0, "v": 0.0}

    sharded = rd.read_sql("SELECT k, grp FROM kv",
                          lambda: sqlite3.connect(db),
                          shard_column="grp", shard_keys=["a", "b"])
    assert sharded.num_blocks() == 2
    rows = sharded.take_all()
    assert len(rows) == 20
    assert {r["grp"] for r in rows} == {"a", "b"}


def test_read_webdataset(ray_start, tmp_path):
    import io
    import json as json_mod
    shard = str(tmp_path / "shard-000.tar")
    with tarfile.open(shard, "w") as tar:
        for i in range(3):
            for ext, payload in [
                    ("cls", str(i).encode()),
                    ("txt", f"sample {i}".encode()),
                    ("json", json_mod.dumps({"idx": i}).encode())]:
                data = io.BytesIO(payload)
                info = tarfile.TarInfo(f"sample{i:03d}.{ext}")
                info.size = len(payload)
                tar.addfile(info, data)
    ds = rd.read_webdataset(shard)
    rows = ds.take_all()
    assert len(rows) == 3
    r0 = next(r for r in rows if r["__key__"] == "sample000")
    assert r0["cls"] == 0 and r0["txt"] == "sample 0"
    assert r0["json"] == {"idx": 0}


# ------------------------------------------------- mongo / bigquery fakes
class _FakeMongoCollection:
    def __init__(self, docs):
        self._docs = docs

    def aggregate(self, pipeline):
        docs = self._docs
        for stage in pipeline:
            if "$match" in stage:
                m = stage["$match"]
                docs = [d for d in docs
                        if all(d.get(k) == v for k, v in m.items())]
            elif "$project" in stage:
                keep = [k for k, v in stage["$project"].items() if v]
                docs = [{k: d[k] for k in keep if k in d} for d in docs]
            else:
                raise ValueError(f"fake mongo: unsupported stage {stage}")
        return iter(docs)


class _FakeMongoClient:
    """pymongo surface: client[db][coll].aggregate(...)"""

    def __init__(self):
        self.closed = False

    def __getitem__(self, db):
        return {"events": _FakeMongoCollection(
            [{"_id": _FakeObjectId(i), "grp": "ab"[i % 2], "v": i}
             for i in range(10)])}

    def close(self):
        self.closed = True


class _FakeObjectId:
    """Non-arrow-native id type: read_mongo must stringify it."""

    def __init__(self, i):
        self.i = i

    def __str__(self):
        return f"oid-{self.i:04d}"


def test_read_mongo_single_and_sharded(ray_start):
    ds = rd.read_mongo("mongodb://unused", "db", "events",
                       client_factory=_FakeMongoClient)
    rows = ds.take_all()
    assert len(rows) == 10
    assert rows[0]["_id"].startswith("oid-")  # ObjectId stringified

    sharded = rd.read_mongo(
        "mongodb://unused", "db", "events",
        pipeline=[{"$project": {"grp": 1, "v": 1}}],
        shard_match=[{"grp": "a"}, {"grp": "b"}],
        client_factory=_FakeMongoClient)
    assert sharded.num_blocks() == 2
    rows = sharded.take_all()
    assert len(rows) == 10
    assert {r["grp"] for r in rows} == {"a", "b"}
    assert all("_id" not in r for r in rows)  # $project applied


class _FakeBqJob:
    def __init__(self, rows):
        self._rows = rows

    def result(self):
        return iter(self._rows)


class _FakeBqClient:
    """google-cloud-bigquery surface: client.query(sql).result()"""

    def query(self, sql):
        assert "FROM" in sql, sql
        if "`ds.t`" in sql:   # whole-table form built by read_bigquery
            return _FakeBqJob([{"x": i, "name": f"n{i}"} for i in range(5)])
        return _FakeBqJob([{"x": 1}])


def test_read_bigquery_query_and_table(ray_start):
    ds = rd.read_bigquery("SELECT x FROM t", client_factory=_FakeBqClient)
    assert ds.take_all() == [{"x": 1}]

    ds2 = rd.read_bigquery(dataset="ds.t", client_factory=_FakeBqClient)
    rows = ds2.take_all()
    assert len(rows) == 5 and rows[0] == {"x": 0, "name": "n0"}

    with pytest.raises(ValueError, match="query.*or.*dataset"):
        rd.read_bigquery()
