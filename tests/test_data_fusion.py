"""Operator fusion + per-op stats (reference:
python/ray/data/_internal/logical/rules/operator_fusion.py and
_internal/stats.py — fused map chains pay one task per block; ds.stats()
reports tasks/rows/bytes/wall per operator)."""

import pytest

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.data import execution as exe


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_fusion_rule_plan_shape():
    a = exe.MapStage("map", lambda r: r)
    b = exe.MapStage("filter", lambda r: True)
    c = exe.AllToAllStage("repartition", num_blocks=2)
    d = exe.MapStage("map_batches", lambda x: x)
    plan = exe.optimize_plan([exe.InputStage([]), a, b, c, d])
    kinds = [type(s).__name__ for s in plan]
    assert kinds == ["InputStage", "MapStage", "AllToAllStage", "MapStage"]
    assert [k for k, *_ in plan[1].ops] == ["map", "filter"]
    assert plan[1].name == "Map(map->filter)"


def test_actor_pool_is_fusion_barrier():
    a = exe.MapStage("map", lambda r: r)
    pool = exe.ActorPoolMapStage.__new__(exe.ActorPoolMapStage)
    b = exe.MapStage("map", lambda r: r)
    plan = exe.optimize_plan([a, pool, b])
    assert len(plan) == 3


def test_fused_two_maps_half_the_tasks(ray_start):
    n_blocks = 4
    ds = rd.range(400, parallelism=n_blocks) \
        .map(lambda r: {"id": r["id"], "x": r["id"] * 2}) \
        .filter(lambda r: r["x"] % 4 == 0)
    rows = ds.take_all()
    assert len(rows) == 200
    assert all(r["x"] % 4 == 0 and r["x"] == r["id"] * 2 for r in rows)
    stats = ds.stats()
    # one Read op + ONE fused map op, each n_blocks tasks: the unfused
    # plan would show two map operators = 2x the object-store round trips
    lines = [ln for ln in stats.splitlines() if "Map(" in ln]
    assert len(lines) == 1, stats
    assert "Map(map->filter)" in lines[0], stats
    assert f"{n_blocks} tasks" in lines[0], stats


def test_stats_reports_rows_and_bytes(ray_start):
    ds = rd.range(100, parallelism=2).map_batches(lambda b: b)
    rows = ds.take_all()
    assert len(rows) == 100
    s = ds.stats()
    assert "Read" in s and "100 rows" in s and "Total:" in s, s


def test_limit_pushdown_past_map(ray_start):
    """range(10k).map(f).limit(50): the limit moves ahead of the map, so
    only ~1 block's rows are mapped instead of all 10k (reference:
    logical/rules/limit_pushdown.py)."""
    plan = exe.optimize_plan([
        exe.InputStage([]),
        exe.MapStage("map", lambda r: r),
        exe.LimitStage(50),
    ])
    kinds = [type(s).__name__ for s in plan]
    assert kinds == ["InputStage", "LimitStage", "MapStage", "LimitStage"]
    # NOT pushed past cardinality-changing stages
    plan2 = exe.optimize_plan([
        exe.MapStage("filter", lambda r: True), exe.LimitStage(5)])
    assert [type(s).__name__ for s in plan2] == ["MapStage", "LimitStage"]
    # end-to-end correctness
    ds = rd.range(10_000, parallelism=8) \
        .map(lambda r: {"v": r["id"] * 2}).limit(50)
    rows = ds.take_all()
    assert [r["v"] for r in rows] == [i * 2 for i in range(50)]
    stats = ds.stats()
    map_line = next(ln for ln in stats.splitlines() if "Map(" in ln)
    # the pushed-down limit cuts BEFORE the map: 50 rows mapped, not 10k
    assert " 50 rows" in map_line, stats


def test_fused_semantics_match_unfused(ray_start):
    base = rd.range(60, parallelism=3)
    fused = base.map(lambda r: {"v": r["id"] + 1}) \
        .flat_map(lambda r: [r, r]) \
        .filter(lambda r: r["v"] % 2 == 0)
    got = sorted(r["v"] for r in fused.take_all())
    expect = sorted(v for i in range(60) for v in [i + 1, i + 1]
                    if v % 2 == 0)
    assert got == expect
