"""Memory monitor / OOM worker-killing (reference:
src/ray/common/memory_monitor.h:52, raylet worker_killing_policy_*.cc —
retriable task workers die before actors; the node daemon survives).
"""

import os
import subprocess
import sys

from ray_tpu._private.node_manager import NodeManager


class _FakeWorker:
    def __init__(self, state, pid, rss):
        self.state = state
        self.pid = pid
        self.worker_id = f"w{pid}"
        self._rss = rss


def _nm_with_workers(monkeypatch, workers):
    nm = NodeManager.__new__(NodeManager)   # no start(): policy-only test
    nm.workers = {w.worker_id: w for w in workers}
    monkeypatch.setattr(NodeManager, "_proc_rss_bytes",
                        staticmethod(lambda pid: next(
                            w._rss for w in workers if w.pid == pid)))
    return nm


def test_meminfo_fraction_parses():
    frac = NodeManager._system_memory_fraction()
    assert 0.0 < frac < 1.0


def test_victim_prefers_retriable_over_actor(monkeypatch):
    workers = [
        _FakeWorker("actor", 11, rss=9_000_000),
        _FakeWorker("leased", 12, rss=1_000),
        _FakeWorker("leased", 13, rss=5_000),
        _FakeWorker("idle", 14, rss=99_000_000),
    ]
    nm = _nm_with_workers(monkeypatch, workers)
    v = nm._pick_oom_victim()
    assert v.pid == 13          # biggest *leased*, not the bigger actor/idle


def test_victim_falls_back_to_actor(monkeypatch):
    workers = [
        _FakeWorker("actor", 21, rss=10),
        _FakeWorker("actor", 22, rss=20),
        _FakeWorker("idle", 23, rss=999),
    ]
    nm = _nm_with_workers(monkeypatch, workers)
    assert nm._pick_oom_victim().pid == 22


def test_no_victim_when_only_idle(monkeypatch):
    nm = _nm_with_workers(monkeypatch, [_FakeWorker("idle", 31, rss=1)])
    assert nm._pick_oom_victim() is None


def test_oom_kill_e2e():
    """threshold=0 makes every monitor pass fire: the leased worker is
    killed mid-task and the owner surfaces a worker-crash failure."""
    script = """
import ray_tpu
ray_tpu.init(num_cpus=2, _system_config={
    "memory_usage_threshold": 0.0,
    "memory_monitor_interval_s": 0.2,
})

@ray_tpu.remote(max_retries=0)
def hog():
    import time
    time.sleep(30)
    return "survived"

try:
    ray_tpu.get(hog.remote(), timeout=60)
    print("UNEXPECTED-SUCCESS")
except Exception as e:
    print("KILLED:", type(e).__name__)
ray_tpu.shutdown()
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=180)
    assert "KILLED:" in out.stdout, (out.stdout, out.stderr[-2000:])
