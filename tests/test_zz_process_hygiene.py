"""Suite-final process-hygiene gate (zz prefix: pytest collects files
alphabetically, so this runs after every other test file).

Round-4 audit: a green 250-test run left 131 ray_tpu daemons alive —
GCS servers and node managers from crashed fixtures, node managers
retrying a dead GCS forever, workers orphaned by SIGKILLed node
managers. Every daemon spawned during this session carries
RAY_TPU_TEST_SESSION in its environment (tests/conftest.py); here we
assert none survived. The reference enforces the same invariant through
its test fixture teardown (ray.tests.conftest shutdown_only) plus the
raylet's bounded GCS-reconnect exit.
"""

import os
import time

from ray_tpu._private.proc_util import find_session_processes


def _describe(pid: int) -> str:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return f.read().replace(b"\0", b" ").decode()[:160]
    except OSError:
        return "<gone>"


def test_no_daemons_survive_the_suite():
    marker = os.environ.get("RAY_TPU_TEST_SESSION")
    assert marker, "conftest did not set RAY_TPU_TEST_SESSION"
    import ray_tpu
    ray_tpu.shutdown()
    # teardown is asynchronous (SIGTERM -> worker reap, plus the node
    # manager's bounded GCS-reconnect exit): allow a grace period for
    # the tree to drain before calling anything a leak — generous,
    # because at the tail of a 35-minute full-suite run the box is
    # still digesting the last fixtures' teardown. The r4 pathology
    # this gate exists for was daemons alive HOURS later.
    deadline = time.monotonic() + 45
    strays = []
    while time.monotonic() < deadline:
        strays = list(find_session_processes(marker))
        if not strays:
            return
        time.sleep(0.5)
    detail = "\n".join(f"  pid {p}: {_describe(p)}" for p in strays)
    # persist the evidence: the assertion detail is truncated under -q,
    # and the strays are about to be killed
    try:
        with open("/tmp/raytpu/hygiene_strays.log", "a") as f:
            f.write(f"session {marker} at {time.time()}:\n{detail}\n")
    except OSError:
        pass
    # reap them so one leak doesn't poison subsequent runs on this box —
    # but still fail loudly
    for p in strays:
        try:
            os.kill(p, 9)
        except OSError:
            pass
    raise AssertionError(
        f"{len(strays)} ray_tpu daemon(s) outlived the test session "
        f"(killed now):\n{detail}")
