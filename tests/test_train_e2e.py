"""The minimum end-to-end slice (SURVEY.md §7.2 milestone): driver →
JaxTrainer → worker actor → sharded train step on a device mesh, with
Data ingest and checkpointing — loss must drop."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import Checkpoint, JaxTrainer, RunConfig, ScalingConfig


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _loop(config):
    import jax
    import optax
    from ray_tpu import train
    from ray_tpu.models import MODEL_REGISTRY, TransformerLM
    from ray_tpu.parallel import MeshConfig, make_mesh
    from ray_tpu.parallel.train_step import make_train_fns

    jax.config.update("jax_platforms", "cpu")
    cfg = MODEL_REGISTRY["llama-debug"]
    model = TransformerLM(cfg)
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, seq=1, tensor=1),
                     devices=jax.devices()[:1])
    B, L = 4, 32
    init_fn, step_fn, _ = make_train_fns(model, optax.adamw(3e-3), mesh,
                                         batch_shape=(B, L + 1))
    state = init_fn(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, L + 1), 0,
                                cfg.vocab_size)
    first = last = None
    for step in range(6):
        state, metrics = step_fn(state, tokens)
        loss = float(metrics["loss"])
        first = first if first is not None else loss
        last = loss
        ckpt = None
        if train.get_context().get_world_rank() == 0 and step == 5:
            ckpt = Checkpoint.from_dict({"final_loss": loss})
        train.report({"loss": loss, "step": step}, checkpoint=ckpt)
    assert last < first


def test_jax_trainer_transformer(ray_start, tmp_path):
    trainer = JaxTrainer(
        _loop, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path), name="e2e"))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["step"] == 5
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] < losses[0]
    assert result.checkpoint is not None
    assert "final_loss" in result.checkpoint.to_dict()
