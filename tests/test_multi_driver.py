"""Concurrent driver processes against one cluster (reference:
python/ray/tests/test_multi_node.py driver-exit tests and the
multi_client_* rows of release/perf_metrics/microbenchmark.json).

Regression for the round-3 hang: server-side lease requests from a
disconnected driver ("zombie waiters") could win a freed lease after the
driver exited, leaking the CPU slot forever and starving every other
driver (multi_client_tasks_async scored 0.0 via timeout)."""

import subprocess
import sys
import time

import pytest

import ray_tpu

DRIVER = """
import sys, time
import ray_tpu
ray_tpu.init(address=sys.argv[1])
@ray_tpu.remote
def add(a, b):
    return a + b
vals = ray_tpu.get([add.remote(i, i) for i in range(40)])
assert vals == [2 * i for i in range(40)], vals
n, t0 = 0, time.perf_counter()
while time.perf_counter() - t0 < 1.0:
    ray_tpu.get([add.remote(n, 1) for _ in range(50)])
    n += 50
print("OK", n, flush=True)
ray_tpu.shutdown()
"""

CRASHER = """
import os, sys
import ray_tpu
ray_tpu.init(address=sys.argv[1])
@ray_tpu.remote
def nop():
    return None
ray_tpu.get([nop.remote() for _ in range(10)])
print("CRASHING", flush=True)
os._exit(1)   # hard exit WITHOUT returning leases
"""


@pytest.fixture(scope="module")
def head():
    ray_tpu.init(num_cpus=1, object_store_memory=128 * 1024 * 1024)
    yield ray_tpu.get_gcs_address()
    ray_tpu.shutdown()


def _run_drivers(addr, snippet, n, timeout):
    procs = [subprocess.Popen(
        [sys.executable, "-u", "-c", snippet, addr],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for _ in range(n)]
    outs = []
    deadline = time.time() + timeout
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("driver hung: lease starvation across drivers")
        outs.append((p.returncode, out))
    return outs


def test_four_concurrent_drivers_one_cpu(head):
    """4 drivers × (40 verified tasks + 1s of churn) on a 1-CPU node:
    every driver must finish — the freed lease must cycle between LIVE
    drivers, never park on a dead driver's abandoned request."""
    outs = _run_drivers(head, DRIVER, 4, timeout=120)
    for rc, out in outs:
        assert rc == 0, out
        assert "OK" in out, out


def test_driver_hard_crash_releases_lease(head):
    """A driver that os._exit()s while holding a lease must not leak the
    CPU: the next driver has to complete normally."""
    outs = _run_drivers(head, CRASHER, 1, timeout=60)
    assert "CRASHING" in outs[0][1]
    outs = _run_drivers(head, DRIVER, 2, timeout=90)
    for rc, out in outs:
        assert rc == 0, out
        assert "OK" in out, out
