"""Flight recorder (ray_tpu/_private/events.py): ring-buffer drop
accounting, span/instant recording, trace-context chaining through the
inference engine, chrome-trace + OTLP read side, shutdown flush, and
the end-to-end Serve streaming trace (proxy -> replica -> engine-slot
-> first-token under ONE trace id)."""

import json
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private import events
from ray_tpu.util.tracing import task_events_to_chrome, task_events_to_otlp

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")


@pytest.fixture(autouse=True)
def _clean_recorder():
    """Each test starts with an empty ring and default capacity; drops
    accumulated by other tests don't leak into assertions."""
    events.drain()
    events.configure(capacity=8192)
    events.set_enabled(True)
    yield
    events.drain()
    events.configure(capacity=8192)
    events.set_enabled(True)


def _running_rows(rows):
    """Collapse drained GCS rows to their RUNNING entries by name."""
    out = {}
    for r in rows:
        if r.get("state") == "RUNNING":
            out.setdefault(r["name"], []).append(r)
    return out


# ---------------------------------------------------------------- ring unit
def test_ring_overflow_deterministic_drop_accounting():
    events.configure(capacity=16)
    for i in range(50):
        events.record_instant("probe", category="test", i=i)
    st = events.stats()
    assert st["buffered"] == 16
    assert st["dropped_unreported"] == 34         # exactly 50 - 16
    rows = events.drain()
    by_name = _running_rows(rows)
    # the newest records survive and the drop marker carries the count
    kept = sorted(r["attrs"]["i"] for r in by_name["probe"])
    assert kept == list(range(34, 50))
    assert by_name["events.dropped"][0]["attrs"]["count"] == 34
    # drop accounting resets once reported
    events.record_instant("probe2", category="test")
    rows = events.drain()
    assert "events.dropped" not in _running_rows(rows)


def test_disabled_recorder_records_nothing():
    events.set_enabled(False)
    with events.record_span("off", category="test") as sp:
        sp.set(x=1)
    events.record_instant("off2", category="test")
    events.set_enabled(True)
    assert events.drain() == []


def test_span_pairs_merge_shape():
    """A span flushes as a RUNNING/FINISHED pair sharing one task_id —
    the shape the GCS merge folds into a single timeline row."""
    with events.record_span("window", category="test", n=3):
        time.sleep(0.01)
    rows = events.drain()
    assert len(rows) == 2
    running, finished = rows
    assert running["state"] == "RUNNING" and finished["state"] == "FINISHED"
    assert running["task_id"] == finished["task_id"] == running["span_id"]
    assert running["kind"] == "runtime_event"
    assert finished["ts"] >= running["ts"]
    assert running["attrs"] == {"n": 3}


def test_trace_context_nesting():
    root = events.start_span("root", category="test")
    with events.trace_context(root.trace_id, root.span_id):
        assert events.current_context() == (root.trace_id, root.span_id)
        with events.record_span("child", category="test") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_span_id == root.span_id
    root.end()
    assert events.current_context() is None


# ------------------------------------------------------------- read side
def _span_row(name, trace, span, parent, t0, t1, category="engine",
              event_kind="span", **attrs):
    return {"task_id": span, "kind": "runtime_event", "name": name,
            "category": category, "type": "RUNTIME_EVENT",
            "event_kind": event_kind, "trace_id": trace, "span_id": span,
            "parent_span_id": parent, "node_id": "n0", "worker_id": "w0",
            "attrs": attrs, "state": "FINISHED",
            "state_times": {"RUNNING": t0, "FINISHED": t1}}


def _task_row(name, trace, span, parent, t0, t1):
    return {"task_id": "ab" * 12, "name": name, "type": "ACTOR_TASK",
            "trace_id": trace, "span_id": span, "parent_span_id": parent,
            "node_id": "n0", "worker_id": "w0", "state": "FINISHED",
            "state_times": {"RUNNING": t0, "FINISHED": t1}}


def _sample_trace():
    t = "11" * 16
    return [
        _task_row("handle_stream", t, "aa" * 8, "bb" * 8, 10.0, 11.0),
        _span_row("engine.request", t, "cc" * 8, "aa" * 8, 10.1, 10.9,
                  category="serve"),
        _span_row("engine.slot", t, "dd" * 8, "cc" * 8, 10.2, 10.8,
                  slot=0, queue_wait_ms=3.5),
        _span_row("engine.first_token", t, "ee" * 8, "cc" * 8, 10.3, 10.3,
                  category="serve", event_kind="instant", ttft_ms=200.0),
    ]


def test_chrome_trace_runtime_tracks_roundtrip():
    rows = _sample_trace()
    out = json.loads(json.dumps(task_events_to_chrome(rows)))
    assert len(out) == 4
    # monotonic ts, nonnegative dur on every duration event
    ts = [e["ts"] for e in out]
    assert ts == sorted(ts)
    for e in out:
        if e["ph"] == "X":
            assert e["dur"] >= 1.0
        else:
            assert e["ph"] == "i"
    # runtime rows land on per-subsystem tracks; tasks keep node tracks
    pids = {e["name"]: e["pid"] for e in out}
    assert pids["handle_stream"] == "n0"
    assert pids["engine.slot"] == "runtime:engine"
    assert pids["engine.request"] == "runtime:serve"
    slot = next(e for e in out if e["name"] == "engine.slot")
    assert slot["args"]["queue_wait_ms"] == 3.5
    assert slot["args"]["parent_span_id"] == "cc" * 8


def test_otlp_parents_engine_slot_under_request():
    payload = task_events_to_otlp(_sample_trace())
    spans = {s["name"]: s
             for s in payload["resourceSpans"][0]["scopeSpans"][0]["spans"]}
    assert len(spans) == 4
    assert len({s["traceId"] for s in spans.values()}) == 1
    assert spans["engine.request"]["parentSpanId"] == \
        spans["handle_stream"]["spanId"]
    assert spans["engine.slot"]["parentSpanId"] == \
        spans["engine.request"]["spanId"]
    assert spans["engine.first_token"]["parentSpanId"] == \
        spans["engine.request"]["spanId"]
    attrs = {a["key"]: a["value"] for a in spans["engine.slot"]["attributes"]}
    assert attrs["ray_tpu.attr.queue_wait_ms"] == {"doubleValue": 3.5}
    assert attrs["ray_tpu.category"] == {"stringValue": "engine"}


# --------------------------------------------------------- engine spans
def _tiny_engine(n_slots=2, max_len=32):
    import jax
    import numpy as np

    from ray_tpu.inference.engine import EngineConfig, InferenceEngine
    from ray_tpu.models import TransformerLM
    from ray_tpu.models.transformer import TransformerConfig
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                            n_heads=2, n_kv_heads=2, d_ff=64,
                            max_seq_len=max_len)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))["params"]
    return InferenceEngine(model, params,
                           EngineConfig(n_slots=n_slots, max_len=max_len,
                                        prefill_chunk=8,
                                        prefill_budget=16))


def test_engine_spans_one_trace_with_parent_links():
    eng = _tiny_engine()
    root = events.start_span("request.root", category="test")
    with events.trace_context(root.trace_id, root.span_id):
        h = eng.submit([1, 2, 3, 4, 5], max_new_tokens=4)
    while eng.step():
        pass
    assert len(h.tokens()) == 4
    root.end()
    by = _running_rows(events.drain())
    slot = by["engine.slot"][0]
    assert slot["trace_id"] == root.trace_id
    assert slot["parent_span_id"] == root.span_id
    assert slot["attrs"]["prompt_tokens"] == 5
    assert slot["attrs"]["queue_wait_ms"] >= 0
    for pre in by["engine.prefill"]:
        assert pre["parent_span_id"] == slot["span_id"]
    # single-occupancy decode steps adopt the request's trace
    for dec in by["engine.decode"]:
        assert dec["trace_id"] == root.trace_id
        assert dec["parent_span_id"] == slot["span_id"]
        assert dec["attrs"]["slots_active"] == 1
    evict = by["engine.evict"][0]
    assert evict["parent_span_id"] == slot["span_id"]
    # compile ticks surface as instants (decode compiles exactly once)
    fns = [c["attrs"]["fn"] for c in by["engine.compile"]]
    assert "decode" in fns and "prefill" in fns


def test_engine_decode_multi_trace_uses_engine_root():
    eng = _tiny_engine(n_slots=2)
    r1 = events.start_span("req1", category="test")
    r2 = events.start_span("req2", category="test")
    with events.trace_context(r1.trace_id, r1.span_id):
        h1 = eng.submit([1, 2, 3], max_new_tokens=6)
    with events.trace_context(r2.trace_id, r2.span_id):
        h2 = eng.submit([4, 5, 6], max_new_tokens=6)
    while eng.step():
        pass
    h1.tokens(), h2.tokens()
    r1.end(), r2.end()
    by = _running_rows(events.drain())
    both = [d for d in by["engine.decode"]
            if d["attrs"]["slots_active"] == 2]
    assert both, "no decode step saw both requests co-resident"
    for d in both:
        # two distinct traces in one batch -> neutral engine-root trace
        assert d["trace_id"] not in (r1.trace_id, r2.trace_id)


def test_gcs_merge_and_exports_roundtrip():
    """Drained rows fold through the REAL GCS handler (RUNNING/FINISHED
    pairs merge into one row) and both exporters consume the result."""
    from ray_tpu._private.gcs import GcsServer
    g = GcsServer()
    with events.record_span("engine.decode", category="engine", tokens=4):
        pass
    events.record_instant("engine.compile", category="engine", fn="decode")
    g.h_add_task_events(None, events.drain())
    out = g.h_list_task_events(None, limit=100, kind="runtime_event")
    assert len(out) == 2
    span_row = next(r for r in out if r["name"] == "engine.decode")
    assert {"RUNNING", "FINISHED"} <= set(span_row["state_times"])
    assert span_row["attrs"] == {"tokens": 4}
    inst = next(r for r in out if r["name"] == "engine.compile")
    assert inst["event_kind"] == "instant"
    # kind/category filters
    assert g.h_list_task_events(None, kind="task") == []
    assert g.h_list_task_events(None, kind="runtime_event",
                                category="store") == []
    assert len(g.h_list_task_events(None, kind="runtime_event",
                                    category="engine")) == 2
    chrome = task_events_to_chrome(out)
    assert {e["ph"] for e in chrome} == {"X", "i"}
    spans = task_events_to_otlp(
        out)["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert len(spans) == 2


# ------------------------------------------------- prometheus rendering
def test_render_prometheus_escapes_label_values():
    """Backslash / quote / newline in tag values and HELP text emit
    valid exposition format, and the tag value never swaps in for the
    sample value (the shadowed-loop-variable bug class)."""
    from ray_tpu.util.metrics import render_prometheus
    snap = {"w1": [
        {"name": "g", "type": "gauge", "help": "line1\nline2\\x",
         "samples": [[[["zone", 'a"b\\c\nd']], 2.5]]},
        {"name": "c", "type": "counter", "help": "",
         "samples": [[[["t", "v"]], 7.0]]},
        {"name": "h", "type": "histogram", "help": "hh",
         "boundaries": [1.0],
         "samples": [[[["q", 'x"y']], [2, 1], 0.5]]},
    ]}
    text = render_prometheus(snap)
    assert "# HELP g line1\\nline2\\\\x" in text
    assert 'g{zone="a\\"b\\\\c\\nd"} 2.5' in text
    # no raw newline may survive inside a sample line
    for line in text.splitlines():
        assert not line.endswith("\\")
    # sample value stays the metric value, not the tag value
    assert 'c{t="v"} 7.0' in text
    assert 'h_bucket{q="x\\"y",le="1.0"} 2' in text
    assert 'h_bucket{q="x\\"y",le="+Inf"} 3' in text
    assert 'h_count{q="x\\"y"} 3' in text


def test_render_prometheus_aggregates_across_workers():
    from ray_tpu.util.metrics import render_prometheus
    row = {"name": "c", "type": "counter", "help": "",
           "samples": [[[["k", "a"]], 2.0]]}
    text = render_prometheus({"w1": [row], "w2": [row]})
    assert 'c{k="a"} 4.0' in text


# ------------------------------------------------------ cluster-side
@pytest.fixture(scope="module")
def cluster():
    from ray_tpu.cluster_utils import Cluster
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    yield c
    c.shutdown()


@needs_cluster
def test_spans_survive_worker_shutdown_flush(cluster):
    """Spans recorded by a driver that exits before the 1s flusher
    cadence reach the GCS through the stop_async flush."""
    ray_tpu.init(address=cluster.address)
    try:
        marker = f"shutdown-span-{time.monotonic_ns()}"
        with events.record_span(marker, category="test"):
            pass
    finally:
        ray_tpu.shutdown()     # flush happens here, NOT via the flusher
    ray_tpu.init(address=cluster.address)
    try:
        rows = ray_tpu._get_worker().gcs_call(
            "list_task_events", limit=20000, kind="runtime_event")
        names = {r.get("name") for r in rows}
        assert marker in names, sorted(names)[:20]
    finally:
        ray_tpu.shutdown()


@needs_cluster
def test_serve_streaming_end_to_end_trace(cluster, tmp_path):
    """Acceptance: one streaming Serve request through the HTTP proxy
    produces a single trace — proxy, replica task, engine
    prefill/decode/slot, first-token — with correct parent links,
    visible in both the chrome-trace and OTLP exports."""
    import urllib.request

    from ray_tpu import serve
    from ray_tpu.inference import LLMDeployment
    from ray_tpu.models.transformer import TransformerConfig
    ray_tpu.init(address=cluster.address)
    try:
        cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                                n_heads=2, n_kv_heads=2, d_ff=64,
                                max_seq_len=64)
        app = serve.deployment(LLMDeployment).bind(
            cfg, n_slots=2, max_len=32, prefill_chunk=8,
            prefill_budget=16)
        serve.run(app, name="llm", _http=True, http_port=8130)
        addr = next(iter(serve.proxies().values()))["http"]
        body = json.dumps([1, 2, 3, 4]).encode()
        req = urllib.request.Request(
            f"http://{addr}/", data=body,
            headers={"Content-Type": "application/json",
                     "X-RayTPU-Stream": "1"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            chunks = [json.loads(ln) for ln in
                      resp.read().decode().splitlines()]
        assert len(chunks) >= 4 and all(
            isinstance(c, int) for c in chunks), chunks[:8]

        want = ["proxy.request", "handle_stream", "engine.request",
                "engine.slot", "engine.prefill", "engine.decode",
                "engine.first_token"]
        deadline = time.monotonic() + 60
        rows = []
        while time.monotonic() < deadline:
            rows = ray_tpu._get_worker().gcs_call("list_task_events",
                                                  limit=20000)
            have = {r.get("name") for r in rows}
            if all(n in have for n in want):
                break
            time.sleep(0.5)
        by = {}
        for r in rows:
            by.setdefault(r.get("name"), []).append(r)
        missing = [n for n in want if n not in by]
        assert not missing, f"missing {missing}"

        proxy = by["proxy.request"][0]
        trace = proxy["trace_id"]
        replica_task = next(r for r in by["handle_stream"]
                            if r.get("trace_id") == trace)
        request = next(r for r in by["engine.request"]
                       if r.get("trace_id") == trace)
        slot = next(r for r in by["engine.slot"]
                    if r.get("trace_id") == trace)
        first = next(r for r in by["engine.first_token"]
                     if r.get("trace_id") == trace)
        decodes = [r for r in by["engine.decode"]
                   if r.get("trace_id") == trace]
        prefills = [r for r in by["engine.prefill"]
                    if r.get("trace_id") == trace]
        assert replica_task["parent_span_id"] == proxy["span_id"]
        assert request["parent_span_id"] == replica_task["span_id"]
        assert slot["parent_span_id"] == request["span_id"]
        assert first["parent_span_id"] == request["span_id"]
        assert prefills and all(p["parent_span_id"] == slot["span_id"]
                                for p in prefills)
        assert decodes, "no decode spans joined the request trace"

        # same trace visible in both export formats
        chrome = ray_tpu.timeline()
        in_trace = [e for e in chrome
                    if e["args"].get("trace_id") == trace]
        chrome_names = {e["name"] for e in in_trace}
        for n in ("proxy.request", "engine.slot", "engine.first_token"):
            assert n in chrome_names
        otlp = task_events_to_otlp(rows)
        ospans = [s for s in
                  otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
                  if s["traceId"] == trace]
        onames = {s["name"] for s in ospans}
        for n in want:
            assert n in onames, (n, sorted(onames))
        oslot = next(s for s in ospans if s["name"] == "engine.slot")
        orequest = next(s for s in ospans
                        if s["name"] == "engine.request")
        assert oslot["parentSpanId"] == orequest["spanId"]
    finally:
        from ray_tpu import serve as _serve
        try:
            _serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
