"""Lineage reconstruction: lost shm objects are re-created by
re-executing their creating task (reference:
src/ray/core_worker/object_recovery_manager.h:41 — resubmit on loss;
lineage retained by task_manager.h:208 / reference_count.h:64).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


SIZE = 512 * 1024   # well above the inline threshold -> sealed into shm


def _wait_complete(ref, timeout=60):
    """Wait until the owner marks the task's return complete WITHOUT
    fetching it (ray_tpu.wait pulls a local copy, which would defeat a
    loss test — readiness here comes from the ownership table)."""
    w = ray_tpu._get_worker()
    deadline = time.time() + timeout
    while time.time() < deadline:
        entry = w.core.owned.get(ref.id)
        if entry is not None and entry.get("complete"):
            return
        time.sleep(0.05)
    raise TimeoutError("task did not complete")


def _cluster_3():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    n2 = cluster.add_node(num_cpus=2)
    n3 = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    return cluster, n2, n3


def test_shm_result_survives_node_kill():
    """A large task result lives only on the node that ran the task; the
    node dies before the driver fetches; get() still succeeds via
    re-execution (soft affinity falls back to the surviving node)."""
    cluster, n2, n3 = _cluster_3()
    try:
        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
            n2.node_id, soft=True))
        def make_blob(seed):
            return np.full(SIZE // 8, seed, dtype=np.int64)

        ref = make_blob.remote(7)
        _wait_complete(ref)                   # completed, not fetched
        cluster.remove_node(n2)
        time.sleep(1.0)
        out = ray_tpu.get(ref, timeout=120)
        assert out.shape == (SIZE // 8,) and int(out[0]) == 7
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_chained_reconstruction():
    """The recovered task's own argument was also lost with the node:
    recovery recurses through the lineage chain."""
    cluster, n2, n3 = _cluster_3()
    try:
        strat = NodeAffinitySchedulingStrategy(n2.node_id, soft=True)

        @ray_tpu.remote(scheduling_strategy=strat)
        def base():
            return np.arange(SIZE // 8, dtype=np.int64)

        @ray_tpu.remote(scheduling_strategy=strat)
        def double(x):
            return x * 2

        b = base.remote()
        d = double.remote(b)
        _wait_complete(d)
        cluster.remove_node(n2)
        time.sleep(1.0)
        out = ray_tpu.get(d, timeout=180)
        assert int(out[3]) == 6
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_put_objects_are_not_reconstructable():
    """ray_tpu.put has no lineage: losing its only copy surfaces
    ObjectLostError (matches the reference: only task outputs recover)."""
    cluster, n2, n3 = _cluster_3()
    try:
        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
            n2.node_id, soft=True))
        def put_on_node():
            # the worker on n2 owns + stores this object; return the ref
            return [ray_tpu.put(np.ones(SIZE // 8))]

        (inner_ref,) = ray_tpu.get(put_on_node.remote(), timeout=60)
        cluster.remove_node(n2)
        time.sleep(1.0)
        with pytest.raises(Exception) as ei:
            ray_tpu.get(inner_ref, timeout=60)
        assert "lost" in str(ei.value).lower() or "unreachable" in str(
            ei.value).lower()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_reconstruction_attempt_cap():
    """lineage_max_depth bounds repeated reconstruction of one object."""
    from ray_tpu._private.config import cfg
    cluster, n2, n3 = _cluster_3()
    try:
        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
            n2.node_id, soft=True))
        def blob():
            return np.zeros(SIZE // 8)

        ref = blob.remote()
        _wait_complete(ref)
        w = ray_tpu._get_worker()
        entry = w.core.owned.get(ref.id)
        assert entry is not None and entry["lineage"] is not None
        # exhaust the reconstruction budget, then lose the only copy
        entry["lineage"]["attempts"] = cfg.lineage_max_depth
        cluster.remove_node(n2)
        time.sleep(1.0)
        with pytest.raises(Exception) as ei:
            ray_tpu.get(ref, timeout=60)
        assert "lost" in str(ei.value).lower()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
