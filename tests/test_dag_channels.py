"""Mutable-channel + compiled-DAG tests (reference:
python/ray/dag/tests/experimental/test_accelerated_dag.py shapes)."""

import multiprocessing
import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.experimental.channel import Channel, ChannelClosed


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=6, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_channel_same_process():
    ch = Channel("/dev/shm/rt_test_chan1", max_size=1 << 16,
                 num_readers=1, create=True)
    reader = Channel("/dev/shm/rt_test_chan1")
    ch.write({"x": 1})
    assert reader.read() == {"x": 1}
    ch.write([1, 2, 3])
    assert reader.read() == [1, 2, 3]
    ch.destroy()


def _reader_proc(path, out_q):
    ch = Channel(path)
    vals = []
    try:
        while True:
            vals.append(ch.read(timeout_s=10))
    except ChannelClosed:
        pass
    out_q.put(vals)


def test_channel_cross_process_backpressure():
    path = "/dev/shm/rt_test_chan2"
    ch = Channel(path, max_size=1 << 16, num_readers=1, create=True)
    q = multiprocessing.Queue()
    p = multiprocessing.Process(target=_reader_proc, args=(path, q))
    p.start()
    for i in range(20):
        ch.write(i)     # blocks until reader acks previous version
    ch.close()
    vals = q.get(timeout=30)
    p.join(timeout=10)
    assert vals == list(range(20))   # every version seen exactly once
    ch.destroy()


def test_compiled_dag_linear(ray_start):
    @ray_tpu.remote
    class AddOne:
        def add(self, x):
            return x + 1

    @ray_tpu.remote
    class Double:
        def mul(self, x):
            return x * 2

    a, b = AddOne.remote(), Double.remote()
    with InputNode() as inp:
        mid = a.add.bind(inp)
        out = b.mul.bind(mid)
    dag = out.experimental_compile()
    try:
        assert dag.execute(5) == 12
        assert dag.execute(10) == 22
        # repeated execution is the point: run many
        for i in range(50):
            assert dag.execute(i) == (i + 1) * 2
    finally:
        dag.teardown()


def test_compiled_dag_multi_output(ray_start):
    @ray_tpu.remote
    class Worker1:
        def inc(self, x):
            return x + 1

        def dec(self, x):
            return x - 1

    w1, w2 = Worker1.remote(), Worker1.remote()
    with InputNode() as inp:
        o1 = w1.inc.bind(inp)
        o2 = w2.dec.bind(inp)
        dag = MultiOutputNode([o1, o2]).experimental_compile()
    try:
        assert dag.execute(10) == [11, 9]
    finally:
        dag.teardown()


def test_compiled_dag_throughput(ray_start):
    @ray_tpu.remote
    class Echo:
        def ping(self, x):
            return x

    e = Echo.remote()
    with InputNode() as inp:
        dag = e.ping.bind(inp).experimental_compile()
    try:
        for _ in range(5):
            dag.execute(0)
        n = 200
        t0 = time.perf_counter()
        for i in range(n):
            dag.execute(i)
        dt = time.perf_counter() - t0
        per_call_us = dt / n * 1e6
        # must be far below the RPC path (~1ms); expect tens of µs
        assert per_call_us < 2000, per_call_us
    finally:
        dag.teardown()
