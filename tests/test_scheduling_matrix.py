"""Scheduler correctness matrix (reference:
src/ray/raylet/cluster_task_manager_test.cc — infeasible tasks become
feasible on node arrival, infeasible requests eventually fail, remote-only
resources spill back, draining nodes are avoided).
"""

import os
import time
from contextlib import contextmanager

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@contextmanager
def _grace(seconds):
    """Cluster daemons inherit the env override at spawn."""
    os.environ["RAY_TPU_INFEASIBLE_GRACE_S"] = str(seconds)
    try:
        yield
    finally:
        os.environ.pop("RAY_TPU_INFEASIBLE_GRACE_S", None)


def test_infeasible_becomes_feasible_on_node_add():
    with _grace(120):
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2})
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(resources={"accel": 1.0}, num_cpus=0.5)
        def where():
            return ray_tpu.get_runtime_context()["node_id"]

        ref = where.remote()
        ready, rest = ray_tpu.wait([ref], timeout=2.0)
        assert not ready            # no node has "accel" yet: stays queued
        node = cluster.add_node(num_cpus=2, resources={"accel": 2.0})
        assert ray_tpu.get(ref, timeout=90) == node.node_id
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_infeasible_forever_fails_after_grace():
    with _grace(2.0):
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2})
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(resources={"never": 1.0}, max_retries=0)
        def impossible():
            return 1

        with pytest.raises(Exception) as ei:
            ray_tpu.get(impossible.remote(), timeout=60)
        assert "unschedulable" in str(ei.value)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_remote_only_resource_spills_back():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    n2 = cluster.add_node(num_cpus=2, resources={"special": 1.0})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes()
    try:
        @ray_tpu.remote(resources={"special": 0.5}, num_cpus=0.5)
        def where():
            return ray_tpu.get_runtime_context()["node_id"]

        assert ray_tpu.get(where.remote(), timeout=60) == n2.node_id
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_draining_node_receives_no_new_work():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    n2 = cluster.add_node(num_cpus=4)
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes()
    try:
        @ray_tpu.remote(num_cpus=1)
        def where():
            time.sleep(0.1)
            return ray_tpu.get_runtime_context()["node_id"]

        # sanity: with 4 free CPUs, n2 takes work
        spots = set(ray_tpu.get([where.remote() for _ in range(6)],
                                timeout=60))
        assert n2.node_id in spots
        ray_tpu._get_worker().gcs_call("drain_node", node_id=n2.node_id)
        time.sleep(1.5)   # view refresh
        spots = set(ray_tpu.get(
            [where.options(scheduling_strategy="SPREAD").remote()
             for _ in range(6)], timeout=90))
        assert n2.node_id not in spots, spots
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
