"""Core API integration tests: tasks, objects, actors on a local cluster
(reference test model: python/ray/tests/test_basic.py on ray_start_regular)."""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_put_get_small(ray_start):
    ref = ray_tpu.put({"a": 1, "b": [1, 2, 3]})
    assert ray_tpu.get(ref) == {"a": 1, "b": [1, 2, 3]}


def test_put_get_large_zero_copy(ray_start):
    arr = np.arange(1_000_000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)
    # second get works too (buffer stays pinned/readable)
    out2 = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out2)


def test_simple_task(ray_start):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_ref_args(ray_start):
    @ray_tpu.remote
    def double(x):
        return x * 2

    ref = ray_tpu.put(21)
    assert ray_tpu.get(double.remote(ref)) == 42


def test_task_large_return(ray_start):
    @ray_tpu.remote
    def make_array(n):
        return np.ones(n, dtype=np.float64)

    out = ray_tpu.get(make_array.remote(500_000))
    assert out.shape == (500_000,)
    assert out.sum() == 500_000


def test_task_chain(ray_start):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(10):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 11


def test_many_parallel_tasks(ray_start):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(50)]
    assert ray_tpu.get(refs) == [i * i for i in range(50)]


def test_task_exception(ray_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(ValueError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_multiple_returns(ray_start):
    @ray_tpu.remote(num_returns=2)
    def pair():
        return 1, 2

    r1, r2 = pair.remote()
    assert ray_tpu.get(r1) == 1
    assert ray_tpu.get(r2) == 2


def test_wait(ray_start):
    @ray_tpu.remote
    def sleepy(t):
        time.sleep(t)
        return t

    fast = sleepy.remote(0.01)
    slow = sleepy.remote(2.0)
    ready, not_ready = ray_tpu.wait([fast, slow], num_returns=1, timeout=1.0)
    assert ready == [fast]
    assert not_ready == [slow]


def test_actor_basic(ray_start):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, k=1):
            self.n += k
            return self.n

        def get(self):
            return self.n

    c = Counter.remote(10)
    assert ray_tpu.get(c.inc.remote()) == 11
    assert ray_tpu.get(c.inc.remote(5)) == 16
    assert ray_tpu.get(c.get.remote()) == 16


def test_actor_ordering(ray_start):
    @ray_tpu.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)

        def get(self):
            return self.items

    a = Appender.remote()
    for i in range(20):
        a.add.remote(i)
    assert ray_tpu.get(a.get.remote()) == list(range(20))


def test_async_actor(ray_start):
    @ray_tpu.remote
    class AsyncActor:
        async def work(self, x):
            import asyncio
            await asyncio.sleep(0.01)
            return x * 2

    a = AsyncActor.remote()
    refs = [a.work.remote(i) for i in range(10)]
    assert ray_tpu.get(refs) == [i * 2 for i in range(10)]


def test_named_actor(ray_start):
    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    s = Store.options(name="kvstore").remote()
    ray_tpu.get(s.set.remote("x", 1))
    h = ray_tpu.get_actor("kvstore")
    assert ray_tpu.get(h.get.remote("x")) == 1


def test_actor_handle_passing(ray_start):
    @ray_tpu.remote
    class Counter2:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    @ray_tpu.remote
    def bump(c):
        return ray_tpu.get(c.inc.remote())

    c = Counter2.remote()
    assert ray_tpu.get(bump.remote(c)) == 1
    assert ray_tpu.get(bump.remote(c)) == 2


def test_kill_actor(ray_start):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote()) == "pong"
    ray_tpu.kill(v)
    time.sleep(0.3)
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(v.ping.remote())


def test_nested_tasks(ray_start):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 10

    assert ray_tpu.get(outer.remote(0)) == 11


def test_cluster_resources(ray_start):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU") == 4.0


def test_large_task_fan(ray_start):
    """A 1000-task fan must complete promptly: submissions pipeline onto
    a bounded set of leases instead of issuing 1000 lease requests
    (reference: NormalTaskSubmitter lease pipelining)."""
    import time

    @ray_tpu.remote
    def inc(x):
        return x + 1

    t0 = time.monotonic()
    out = ray_tpu.get([inc.remote(i) for i in range(1000)], timeout=120)
    assert out == [i + 1 for i in range(1000)]
    assert time.monotonic() - t0 < 60


def test_actor_restart_preserves_call_order(ray_start):
    """Calls racing an actor kill+restart are resent in submission order
    (reference: SequentialActorSubmitQueue seq-nos — ordered delivery
    survives restarts; VERDICT round-1 weak item 6)."""
    @ray_tpu.remote(max_restarts=1, max_task_retries=-1)
    class Journal:
        def __init__(self):
            self.log = []

        def append(self, i):
            time.sleep(0.05)      # keep a pipeline in flight at the kill
            self.log.append(i)
            return i

        def get_log(self):
            return list(self.log)

    j = Journal.remote()
    assert ray_tpu.get(j.append.remote(-1), timeout=60) == -1
    refs = [j.append.remote(i) for i in range(40)]
    time.sleep(0.4)           # several appends done, many in flight
    ray_tpu.kill(j, no_restart=False)
    out = ray_tpu.get(refs, timeout=120)
    assert out == list(range(40))
    log = ray_tpu.get(j.get_log.remote(), timeout=60)
    # the restarted actor's journal is a CONTIGUOUS ASCENDING suffix:
    # resends jumped ahead of later submissions, preserving order
    assert log, "kill landed after all appends; nothing exercised"
    assert log == list(range(log[0], 40)), log
    assert log[0] > 0, "kill landed before any append completed"


def test_fast_method_using_sync_api_never_double_executes(ray_start):
    """A quick actor method that calls a blocking sync API (ray_tpu.get)
    must stay on the thread pool (inline execution would deadlock or
    double-run side effects): the bridge marks it inline-unsafe during
    its first pool runs and every call executes exactly once."""
    @ray_tpu.remote(num_cpus=0.1)
    class G:
        def __init__(self):
            self.count = 0

        def bump_and_get(self, refs):
            # nested refs stay unresolved (top-level args resolve to
            # values), so the method itself must call the blocking get
            self.count += 1
            return self.count, ray_tpu.get(refs[0])

    g = G.remote()
    ref = ray_tpu.put(7)
    outs = ray_tpu.get([g.bump_and_get.remote([ref]) for _ in range(30)],
                       timeout=60)
    assert [c for c, _ in outs] == list(range(1, 31))
    assert all(v == 7 for _, v in outs)
