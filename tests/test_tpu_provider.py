"""GCE TPU queued-resources provider state machine, driven through a fake
transport (reference: python/ray/autoscaler/_private/gcp/ node provider +
v2 instance manager reconciliation; no credentials or egress needed).
"""

import pytest

from ray_tpu.autoscaler.node_provider import GcpTpuNodeProvider


class FakeTpuApi:
    """Simulates the Cloud TPU queued-resources API surface."""

    def __init__(self):
        self.resources = {}      # name -> state
        self.calls = []

    def __call__(self, method, path, body=None):
        self.calls.append((method, path))
        if method == "POST":
            name = path.split("queuedResourceId=")[1]
            self.resources[name] = "WAITING_FOR_RESOURCES"
            assert body["tpu"]["nodeSpec"][0]["node"]["acceleratorType"]
            assert "startup-script" in \
                body["tpu"]["nodeSpec"][0]["node"]["metadata"]
            return {"name": name}
        if method == "GET":
            # LIST endpoint
            return {"queuedResources": [
                {"name": f"{path}/{n}", "state": {"state": st}}
                for n, st in self.resources.items()]}
        if method == "DELETE":
            name = path.rsplit("/", 1)[1].split("?")[0]
            self.resources.pop(name, None)
            return {}
        raise AssertionError(method)


@pytest.fixture
def provider():
    api = FakeTpuApi()
    p = GcpTpuNodeProvider("proj", "us-central2-b", "10.0.0.1:6379",
                           accelerator_type="v4-32", api=api)
    return p, api


def test_queued_resource_lifecycle(provider):
    p, api = provider
    name = p.create_node("tpu_slice", {"TPU": 16}, {"team": "ml"})
    assert name in api.resources
    # queued: counted as in-flight capacity, reported as pending
    assert p.non_terminated_nodes() == [name]
    assert p.pending_nodes() == [name]

    api.resources[name] = "PROVISIONING"
    p.non_terminated_nodes()
    assert p.pending_nodes() == [name]

    api.resources[name] = "ACTIVE"
    p.non_terminated_nodes()
    assert p.pending_nodes() == []                 # slice is up
    assert p.non_terminated_nodes() == [name]

    p.terminate_node(name)
    assert p.non_terminated_nodes() == []
    assert name not in api.resources


def test_failed_queued_resource_drops_out(provider):
    p, api = provider
    name = p.create_node("tpu_slice", {"TPU": 16}, {})
    api.resources[name] = "FAILED"
    assert p.non_terminated_nodes() == []          # pruned
    # a fresh demand pass may create a new request
    name2 = p.create_node("tpu_slice", {"TPU": 16}, {})
    assert name2 != name


def test_api_outage_keeps_last_known_state(provider):
    p, api = provider
    name = p.create_node("tpu_slice", {"TPU": 16}, {})

    def broken(method, path, body=None):
        raise OSError("no egress")

    p.api = broken
    # can't verify -> keep the node rather than double-launching
    assert p.non_terminated_nodes() == [name]
    # ...and a failed DELETE must not forget a live billing slice
    with pytest.raises(OSError):
        p.terminate_node(name)
    p.api = api
    assert p.non_terminated_nodes() == [name]


def test_out_of_band_deletion_marks_dead(provider):
    p, api = provider
    name = p.create_node("tpu_slice", {"TPU": 16}, {})
    del api.resources[name]          # deleted via gcloud
    assert p.non_terminated_nodes() == []


def test_node_ids_are_gce_safe(provider):
    p, api = provider
    name = p.create_node("Tpu_Slice.v4", {"TPU": 16}, {"Team": "ML_infra"})
    assert name == name.lower()
    assert all(c.isalnum() or c == "-" for c in name)
