"""Rolling redeploys (reference: serve _private/deployment_state.py —
code/config changes replace replicas GRADUALLY, surging new-version
replicas before retiring old ones, so capacity never drops to zero)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_session():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@serve.deployment(num_replicas=2)
class Tagged:
    def __init__(self, tag):
        self.tag = tag

    def __call__(self, payload):
        return self.tag


def test_rolling_update_no_downtime(serve_session):
    handle = serve.run(Tagged.bind("v1"), name="roll")
    assert handle.remote("x").result(timeout=60) == "v1"

    # redeploy with new code/config -> rolling replacement
    handle = serve.run(Tagged.bind("v2"), name="roll")

    # during the roll EVERY request must succeed (old or new version);
    # eventually only v2 answers
    deadline = time.time() + 120
    seen = set()
    consecutive_v2 = 0
    while time.time() < deadline:
        tags = [handle.remote("x").result(timeout=30) for _ in range(6)]
        seen.update(tags)
        consecutive_v2 = consecutive_v2 + 1 if set(tags) == {"v2"} else 0
        if consecutive_v2 >= 3:    # roll definitely finished
            break
        time.sleep(1.0)
    assert consecutive_v2 >= 3, f"never converged to v2: {seen}"
    # steady state
    for _ in range(4):
        assert handle.remote("x").result(timeout=30) == "v2"
