"""Data adoption of core streaming generators (round-5): generator
map_batches UDFs fan one block into many without buffering the
expansion, and parquet reads stream per row group (reference:
map_transformer generator UDFs; parquet fragment reads)."""

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def test_generator_udf_streams_chunks(cluster):
    """A map_batches UDF that yields K chunks per input block produces
    K output blocks, in order."""
    def expand(batch):
        n = len(batch["id"])
        for k in range(3):
            yield {"id": batch["id"] * 10 + k, "chunk": np.full(n, k)}

    ds = rd.range(40, parallelism=4).map_batches(expand)
    rows = ds.take_all()
    assert len(rows) == 120
    chunks = [r["chunk"] for r in rows]
    assert set(chunks) == {0, 1, 2}
    ids = sorted(r["id"] for r in rows if r["chunk"] == 1)
    assert ids == [i * 10 + 1 for i in range(40)]


def test_generator_udf_fuses_with_downstream_map(cluster):
    """Fusion across a generator UDF: each streamed chunk flows through
    the fused downstream op inside the same task."""
    def expand(batch):
        yield {"v": batch["id"]}
        yield {"v": batch["id"] + 100}

    ds = (rd.range(10, parallelism=2)
          .map_batches(expand)
          .map(lambda r: {"v2": r["v"] * 2}))
    vals = sorted(r["v2"] for r in ds.take_all())
    expect = sorted([i * 2 for i in range(10)]
                    + [(i + 100) * 2 for i in range(10)])
    assert vals == expect


def test_parquet_row_groups_stream_as_blocks(cluster, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    table = pa.table({"x": list(range(1000))})
    path = str(tmp_path / "rg.parquet")
    pq.write_table(table, path, row_group_size=100)    # 10 row groups
    ds = rd.read_parquet(path)
    assert sorted(r["x"] for r in ds.take_all()) == list(range(1000))
    # one block per row group (the stream fanned the file out)
    assert ds.num_blocks() == 10


def test_stats_cover_streamed_stages(cluster):
    def expand(batch):
        yield {"a": batch["id"]}
        yield {"a": batch["id"]}

    ds = rd.range(20, parallelism=2).map_batches(expand)
    ds.take_all()
    # per-op stats still render for streamed stages
    assert "Map" in ds.stats()
