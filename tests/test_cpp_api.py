"""C++ API frontend test (reference: cpp/include/ray/api.h + the C++
runtime): compile the example against ray_tpu_api.hpp, run it against a
live cluster, and check cross-language task calls (msgpack args/results,
error propagation)."""

import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

HOST_SCRIPT = """
import time
import ray_tpu
ray_tpu.init(num_cpus=2)
print(f"GCS={ray_tpu.get_gcs_address()}", flush=True)
while True:
    time.sleep(1)
"""


def test_cpp_local_mode(tmp_path):
    """Local-mode C++ runtime (reference: cpp local_mode_ray_runtime):
    native tasks/actors execute in-process — no cluster. Covers task
    registration, ref-dependency chaining, error propagation, FIFO actor
    serialization under 4-thread submission, Put/Get/Wait."""
    binary = str(tmp_path / "cpp_local")
    build = subprocess.run(
        ["g++", "-std=c++17", "-O2", "-o", binary,
         os.path.join(ROOT, "ray_tpu/native/cpp_api/local_example.cpp"),
         "-I", os.path.join(ROOT, "ray_tpu/native/cpp_api"),
         "-lpthread"],
        capture_output=True, text=True, timeout=120)
    assert build.returncode == 0, build.stderr
    out = subprocess.run([binary], capture_output=True, text=True,
                         timeout=120)
    assert out.returncode == 0, (out.returncode, out.stdout, out.stderr)
    assert "LOCAL_MODE_OK" in out.stdout
    assert "pow=1024" in out.stdout
    assert "chain=10" in out.stdout
    assert "actor_total=164" in out.stdout


def test_cpp_client_cross_language(tmp_path):
    binary = str(tmp_path / "cpp_example")
    build = subprocess.run(
        ["g++", "-std=c++17", "-O2", "-o", binary,
         os.path.join(ROOT, "ray_tpu/native/cpp_api/example.cpp"),
         "-I", os.path.join(ROOT, "ray_tpu/native/cpp_api")],
        capture_output=True, text=True, timeout=120)
    assert build.returncode == 0, build.stderr

    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT
    host = subprocess.Popen([sys.executable, "-c", HOST_SCRIPT],
                            stdout=subprocess.PIPE, text=True, env=env)
    try:
        gcs = None
        deadline = time.time() + 60
        while time.time() < deadline:
            if host.poll() is not None:
                break          # host died: readline() would spin on ''
            line = host.stdout.readline()
            if line.startswith("GCS="):
                gcs = line.strip().split("=", 1)[1]
                break
        assert gcs, "cluster did not start"
        out = subprocess.run([binary, gcs], capture_output=True, text=True,
                             timeout=120)
        assert "CPP_API_OK" in out.stdout, out.stdout + out.stderr
        assert "pow=1024" in out.stdout
        assert "error propagated" in out.stdout
        assert "actor_total=112" in out.stdout
        assert "dead actor error" in out.stdout
        assert "create error propagated" in out.stdout
    finally:
        host.terminate()
        host.wait(timeout=10)
