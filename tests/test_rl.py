"""RL tests: PPO on CartPole improves reward (reference regression-test
pattern: rllib/tuned_examples as threshold tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import AlgorithmConfig


@pytest.fixture(scope="module")
def ray_start():
    import os
    # worker processes must run jax on CPU (the axon TPU tunnel would be
    # contended by every runner/learner actor at once)
    saved = {k: os.environ.pop(k, None)
             for k in ("PALLAS_AXON_POOL_IPS",)}
    os.environ["JAX_PLATFORMS"] = "cpu"
    ctx = ray_tpu.init(num_cpus=6, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()
    for k, v in saved.items():
        if v is not None:
            os.environ[k] = v


def test_ppo_cartpole_learns(ray_start):
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(train_batch_size=512, minibatch_size=128,
                        num_epochs=4, lr=3e-4, entropy_coeff=0.01))
    algo = config.build()
    first_return = None
    best = -np.inf
    for i in range(20):
        result = algo.train()
        r = result["episode_return_mean"]
        if r is not None:
            if first_return is None:
                first_return = r
            best = max(best, r)
    algo.stop()
    assert first_return is not None
    # CartPole starts ~15-25; PPO should clearly improve within 12 iters
    assert best > first_return + 20, (first_return, best)
    assert best > 50


def test_ppo_multi_learner_smoke(ray_start):
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=1, num_envs_per_env_runner=2,
                           rollout_fragment_length=32)
              .training(train_batch_size=64, minibatch_size=32,
                        num_epochs=1)
              .learners(num_learners=2))
    algo = config.build()
    result = algo.train()
    assert result["num_env_steps_sampled"] == 64
    assert "total_loss" in result
    algo.stop()
