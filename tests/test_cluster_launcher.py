"""Cluster launcher e2e on the fake provider: `up(cluster.yaml)` brings up
head + min_workers and a task runs on every node (reference:
`ray up` commands.py + FakeMultiNodeProvider hermetic loop)."""

import textwrap
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import launcher


def test_up_runs_tasks_on_every_node(tmp_path):
    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(textwrap.dedent("""\
        cluster_name: launcher-e2e
        provider:
          type: fake
        head:
          num_cpus: 1
        available_node_types:
          cpu_worker:
            resources: {CPU: 1}
            min_workers: 2
            max_workers: 4
        idle_timeout_s: 300
    """))
    handle = launcher.up(str(cfg))
    try:
        ray_tpu.init(address=handle.gcs_address)
        deadline = time.time() + 60
        while time.time() < deadline:
            if sum(1 for n in ray_tpu.nodes() if n["alive"]) >= 3:
                break
            time.sleep(0.5)
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        assert len(alive) == 3    # head + 2 min_workers from YAML

        @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
        def where():
            import time as _t
            _t.sleep(1)      # hold the CPU so peers must serve the rest
            return ray_tpu.get_runtime_context()["node_id"]

        spots = ray_tpu.get([where.remote() for _ in range(6)], timeout=120)
        assert len(set(spots)) == 3, "tasks must have spread to every node"
    finally:
        ray_tpu.shutdown()
        handle.down()


def test_config_validation(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("provider: {type: nope}\n")
    with pytest.raises(ValueError):
        launcher.load_config(str(bad))
    bad2 = tmp_path / "bad2.yaml"
    bad2.write_text(textwrap.dedent("""\
        provider: {type: fake}
        available_node_types:
          w: {min_workers: 1}
    """))
    with pytest.raises(ValueError):
        launcher.load_config(str(bad2))
