"""Pipeline-parallel tests on the 8-device virtual CPU mesh: GPipe
schedule exactness vs sequential execution, gradient equivalence, and a
pipelined transformer-block stack with a training step (reference
counterpart: compiled-DAG pipelines, python/ray/dag/compiled_dag_node.py:549;
here the schedule is a lax.scan + ppermute inside one SPMD program)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ray_tpu.models.transformer import (Block, TransformerConfig,
                                        unpartitioned_params)
from ray_tpu.parallel import MeshConfig, make_mesh
from ray_tpu.parallel.pipeline import (pipeline_apply, stack_stage_params,
                                       stage_param_specs)

S, M, MB, D = 4, 8, 2, 16


def _mlp_stage(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _mlp_params():
    ks = jax.random.split(jax.random.PRNGKey(0), S)
    return [{"w": jax.random.normal(k, (D, D)) * 0.5, "b": jnp.zeros((D,))}
            for k in ks]


def test_pipeline_forward_matches_sequential():
    mesh = make_mesh(MeshConfig(data=1, fsdp=2, stage=S))
    per_stage = _mlp_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (M, MB, D))
    y = pipeline_apply(_mlp_stage, stack_stage_params(per_stage), x, mesh)
    ref = x
    for p in per_stage:
        ref = jax.vmap(lambda xx, p=p: _mlp_stage(p, xx))(ref)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_grads_match_sequential():
    mesh = make_mesh(MeshConfig(data=1, fsdp=2, stage=S))
    per_stage = _mlp_params()
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(1), (M, MB, D))

    g_pp = jax.grad(
        lambda p: pipeline_apply(_mlp_stage, p, x, mesh).sum())(stacked)

    def seq_loss(params_list):
        r = x
        for p in params_list:
            r = jax.vmap(lambda xx, p=p: _mlp_stage(p, xx))(r)
        return r.sum()

    g_seq = stack_stage_params(jax.grad(seq_loss)(per_stage))
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_pipelined_transformer_blocks_train_step():
    """2-stage pipeline of real transformer Blocks + embed/unembed outside;
    one adamw step must run and reduce loss over a few iterations."""
    cfg = TransformerConfig(
        vocab_size=128, d_model=32, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=64, dtype=jnp.float32, param_dtype=jnp.float32,
        scan_layers=False, remat=False)
    n_stage, n_mb, mb, L = 2, 4, 2, 16
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, stage=n_stage))
    block = Block(cfg)
    positions = jnp.broadcast_to(jnp.arange(L)[None, :], (mb, L))

    def stage_fn(p, x):
        with unpartitioned_params():   # trace-time: no logical-axis boxes
            out, _aux = block.apply({"params": p}, x, positions)
        return out

    x0 = jnp.zeros((mb, L, cfg.d_model), jnp.float32)
    with unpartitioned_params():
        stages = [block.init(jax.random.PRNGKey(i), x0, positions)["params"]
                  for i in range(n_stage)]
    params = {
        "embed": jax.random.normal(jax.random.PRNGKey(9),
                                   (cfg.vocab_size, cfg.d_model)) * 0.02,
        "stages": stack_stage_params(stages),
    }
    tokens = jax.random.randint(jax.random.PRNGKey(10),
                                (n_mb, mb, L + 1), 0, cfg.vocab_size)
    opt = optax.adamw(3e-3)
    opt_state = opt.init(params)

    def loss_fn(params):
        inp, tgt = tokens[..., :-1], tokens[..., 1:]
        h = params["embed"][inp]                       # [M, mb, L, D]
        h = pipeline_apply(stage_fn, params["stages"], h, mesh)
        logits = jnp.einsum("mbld,vd->mblv", h, params["embed"])
        logz = jax.nn.logsumexp(logits, -1)
        gold = jnp.take_along_axis(logits, tgt[..., None], -1)[..., 0]
        return (logz - gold).mean()

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params=params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
