"""GCS fault tolerance: kill -9 the GCS, restart from snapshot, cluster
recovers (reference: gcs_init_data.cc restart rebuild, NotifyGCSRestart
node_manager.proto:383, gcs_client_reconnection_test.cc).
"""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

import ray_tpu


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_gcs(port, persist, session):
    # child_env arms PDEATHSIG: a restarted GCS dies with this pytest
    # process even if the test aborts before its finally/fixture teardown
    # (round-4 leak: test_gcs_ft GCS processes survived for hours)
    from ray_tpu._private.proc_util import child_env
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.gcs", "--port", str(port),
         "--session-name", session, "--persist-path", persist],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=child_env())
    deadline = time.time() + 30
    while time.time() < deadline:
        line = proc.stdout.readline()
        if "GCS_ADDRESS" in line:
            return proc, line.split("GCS_ADDRESS=", 1)[1].strip()
    raise TimeoutError("GCS did not announce")


@pytest.fixture
def gcs_restart_cluster(tmp_path):
    from ray_tpu._private import node as node_mod
    port = _free_port()
    persist = str(tmp_path / "gcs_snapshot.bin")
    session = f"ft{os.getpid()}"
    gcs_proc, gcs_addr = _spawn_gcs(port, persist, session)
    node = node_mod.start_node(gcs_addr, num_cpus=2, session_name=session)
    ray_tpu.init(address=gcs_addr)
    ctx = {"port": port, "persist": persist, "session": session,
           "gcs_proc": gcs_proc, "addr": gcs_addr}
    yield ctx
    ray_tpu.shutdown()
    node.kill()
    # kill the CURRENT GCS from ctx, not the local from setup: tests
    # restart the GCS and reassign ctx["gcs_proc"] — killing the stale
    # local leaked every restarted instance until pytest itself exited
    # (round-5 hygiene-gate evidence: exactly one stray per fixture test)
    cur = ctx["gcs_proc"]
    if cur.poll() is None:
        cur.kill()
    cur.wait()


def test_gcs_restart_recovers_state(gcs_restart_cluster):
    ctx = gcs_restart_cluster

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    @ray_tpu.remote
    def f(x):
        return x + 1

    w = ray_tpu._get_worker()
    # durable state: KV + named actor
    w.gcs_call("kv_put", ns="user", key=b"k1", value=b"v1")
    counter = Counter.options(name="survivor").remote()
    assert ray_tpu.get(counter.inc.remote(), timeout=30) == 1
    time.sleep(2.5)          # > gcs_snapshot_interval_s: state on disk

    # hard-kill the GCS and restart it on the same port + snapshot
    ctx["gcs_proc"].send_signal(signal.SIGKILL)
    ctx["gcs_proc"].wait()
    time.sleep(0.5)
    new_gcs, _ = _spawn_gcs(ctx["port"], ctx["persist"], ctx["session"])
    ctx["gcs_proc"] = new_gcs

    # driver buffers through: KV survives, named actor resolvable, the
    # existing handle keeps working, node re-registers, new tasks run
    assert w.gcs_call("kv_get", ns="user", key=b"k1") == b"v1"
    assert ray_tpu.get(counter.inc.remote(), timeout=60) == 2

    deadline = time.time() + 20
    while time.time() < deadline:
        nodes = [n for n in w.gcs_call("get_all_nodes") if n["alive"]]
        if nodes:
            break
        time.sleep(0.5)
    assert nodes, "node manager did not re-register after GCS restart"

    again = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(again.inc.remote(), timeout=60) == 3
    assert ray_tpu.get(f.remote(41), timeout=60) == 42


def test_gcs_restart_on_different_port(tmp_path):
    """Store-client GCS-FT: kill the GCS, restart it from the same store
    on a NEW port. Node managers re-discover the published address and
    re-register; in-flight tasks complete; new work runs against the
    restarted GCS (reference: Redis-backed GCS-FT — raylets re-resolve
    the GCS address from the store, redis_store_client.h:106,
    python/ray/tests/test_gcs_fault_tolerance.py)."""
    from ray_tpu._private import node as node_mod
    persist = str(tmp_path / "gcs_store.bin")
    session = f"ftmove{os.getpid()}"
    port1 = _free_port()
    gcs_proc, gcs_addr = _spawn_gcs(port1, persist, session)
    node = node_mod.start_node(gcs_addr, num_cpus=2, session_name=session,
                               gcs_address_source=persist)
    ray_tpu.init(address=gcs_addr)
    try:
        w = ray_tpu._get_worker()
        w.gcs_call("kv_put", ns="user", key=b"moved", value=b"yes")

        @ray_tpu.remote
        def slow(x):
            time.sleep(1.5)
            return x * 10

        # warm the worker pool + ship the function while the GCS lives:
        # in-flight completion during an outage is a data-plane property
        # of EXISTING workers (fresh spawns need the GCS for function
        # fetch, same as the reference)
        assert ray_tpu.get(slow.remote(0), timeout=60) == 0
        refs = [slow.remote(i) for i in range(4)]
        gcs_proc.send_signal(signal.SIGKILL)
        gcs_proc.wait()

        port2 = _free_port()
        assert port2 != port1
        gcs_proc, new_addr = _spawn_gcs(port2, persist, session)
        assert new_addr != gcs_addr

        # in-flight tasks complete (data plane never touches the GCS)
        assert ray_tpu.get(refs, timeout=90) == [0, 10, 20, 30]

        # the node manager re-reads the published address and
        # re-registers with the NEW GCS
        import subprocess as sp
        deadline = time.time() + 60
        nodes = []
        while time.time() < deadline:
            out = sp.run(
                [sys.executable, "-c",
                 "import sys, ray_tpu\n"
                 "ray_tpu.init(address=sys.argv[1])\n"
                 "w = ray_tpu._get_worker()\n"
                 "ns = [n for n in w.gcs_call('get_all_nodes')"
                 " if n['alive']]\n"
                 "print('ALIVE', len(ns))\n"
                 "print('KV', w.gcs_call('kv_get', ns='user',"
                 " key=b'moved'))\n"
                 "import ray_tpu as r\n"
                 "@r.remote\n"
                 "def f(x): return x + 1\n"
                 "print('TASK', r.get(f.remote(41), timeout=60))\n"
                 "r.shutdown()\n", new_addr],
                capture_output=True, text=True, timeout=120)
            if "ALIVE 1" in out.stdout and "TASK 42" in out.stdout:
                nodes = [1]
                break
            time.sleep(2)
        assert nodes, f"node never re-registered with moved GCS: {out.stdout}\n{out.stderr}"
        assert "KV b'yes'" in out.stdout
    finally:
        ray_tpu.shutdown()
        node.kill()
        if gcs_proc.poll() is None:
            gcs_proc.kill()


def test_gcs_restart_while_tasks_inflight(gcs_restart_cluster):
    ctx = gcs_restart_cluster

    @ray_tpu.remote
    def slow(x):
        time.sleep(1.5)
        return x * 10

    refs = [slow.remote(i) for i in range(4)]
    ctx["gcs_proc"].send_signal(signal.SIGKILL)
    ctx["gcs_proc"].wait()
    new_gcs, _ = _spawn_gcs(ctx["port"], ctx["persist"], ctx["session"])
    ctx["gcs_proc"] = new_gcs
    # in-flight work (already-pushed tasks) completes: the data plane is
    # worker<->worker and never touches the GCS
    assert ray_tpu.get(refs, timeout=90) == [0, 10, 20, 30]


def test_wal_closes_snapshot_window(gcs_restart_cluster):
    """A mutation made moments before a GCS kill -9 (inside the periodic
    snapshot interval) survives restart via the write-ahead log
    (reference: synchronous Redis store writes, redis_store_client.h:106)."""
    ctx = gcs_restart_cluster
    import ray_tpu._private.worker as wm
    w = wm.global_worker
    # register state and kill IMMEDIATELY — no snapshot tick can run
    w.gcs_call("kv_put", ns="walns", key=b"k1", value=b"v1")

    @ray_tpu.remote(name="wal_actor", lifetime="detached")
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    ctx["gcs_proc"].kill()
    ctx["gcs_proc"].wait()

    proc2, addr2 = _spawn_gcs(ctx["port"], ctx["persist"], ctx["session"])
    ctx["gcs_proc"] = proc2
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            assert w.gcs_call("kv_get", ns="walns", key=b"k1") == b"v1"
            break
        except Exception:
            time.sleep(0.5)
    else:
        raise AssertionError("kv entry lost across restart")
    # the actor's registration survived the restart too
    info = w.gcs_call("get_actor_info",
                      actor_id=a._actor_id)
    assert info is not None and info.get("state") is not None
