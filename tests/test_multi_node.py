"""Multi-node scheduling / placement-group / failover tests on a local
multi-raylet cluster (reference: python/ray/tests/test_placement_group*.py,
test_actor_failures.py over the ray_start_cluster fixture)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import (NodeAffinitySchedulingStrategy,
                          PlacementGroupSchedulingStrategy,
                          placement_group, remove_placement_group)


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 2,
                                "resources": {"head": 1.0}})
    c.add_node(num_cpus=2, resources={"worker1": 1.0, "TPU": 4.0})
    c.add_node(num_cpus=2, resources={"worker2": 1.0})
    ray_tpu.init(address=c.address)
    c.wait_for_nodes()
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_cluster_resources(cluster):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 6.0
    assert total["TPU"] == 4.0


def test_custom_resource_scheduling(cluster):
    @ray_tpu.remote(resources={"worker2": 1.0}, num_cpus=1)
    def where():
        import ray_tpu
        return ray_tpu.get_runtime_context()["node_id"]

    node_id = ray_tpu.get(where.remote())
    w2 = [n for n in ray_tpu.nodes() if "worker2" in n["total"]][0]
    assert node_id == w2["node_id"]


def test_tpu_resource_task(cluster):
    @ray_tpu.remote(num_tpus=2)
    def tpu_task():
        import ray_tpu
        return ray_tpu.get_runtime_context()["node_id"]

    nid = ray_tpu.get(tpu_task.remote())
    tpu_node = [n for n in ray_tpu.nodes() if "TPU" in n["total"]][0]
    assert nid == tpu_node["node_id"]


def test_node_affinity(cluster):
    target = [n for n in ray_tpu.nodes() if "worker1" in n["total"]][0]

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=target["node_id"]))
    def pinned():
        import ray_tpu
        return ray_tpu.get_runtime_context()["node_id"]

    assert ray_tpu.get(pinned.remote()) == target["node_id"]


def test_cross_node_object_transfer(cluster):
    @ray_tpu.remote(resources={"worker1": 0.01})
    def produce():
        return np.arange(500_000, dtype=np.float32)

    @ray_tpu.remote(resources={"worker2": 0.01})
    def consume(arr):
        return float(arr.sum())

    ref = produce.remote()
    total = ray_tpu.get(consume.remote(ref))
    assert total == float(np.arange(500_000, dtype=np.float32).sum())
    # driver-side get pulls to the driver's node too
    arr = ray_tpu.get(ref)
    assert arr.shape == (500_000,)


def test_placement_group_spread(cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}, {"CPU": 1}],
                         strategy="STRICT_SPREAD")
    assert pg.wait(timeout=15)
    nodes = pg.node_ids()
    assert len(set(nodes)) == 3

    @ray_tpu.remote(num_cpus=1)
    def where():
        import ray_tpu
        return ray_tpu.get_runtime_context()["node_id"]

    refs = [where.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            pg, placement_group_bundle_index=i)).remote()
        for i in range(3)]
    got = ray_tpu.get(refs)
    assert got == nodes
    remove_placement_group(pg)


def test_placement_group_pack_actor(cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout=15)

    @ray_tpu.remote
    class A:
        def node(self):
            import ray_tpu
            return ray_tpu.get_runtime_context()["node_id"]

    actors = [A.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            pg, placement_group_bundle_index=i)).remote()
        for i in range(2)]
    got = ray_tpu.get([a.node.remote() for a in actors])
    assert got == pg.node_ids()
    del actors
    remove_placement_group(pg)


def test_spillback_when_local_full(cluster):
    """More parallel tasks than any single node's CPUs: they must land on
    several nodes (hybrid policy spillback). A rendezvous barrier makes
    the requirement deterministic — 3 tasks must run CONCURRENTLY, which
    the 2-CPU head alone cannot do, so spillback has to happen (serial
    reuse of local leases would deadlock the barrier, not flake)."""
    @ray_tpu.remote(num_cpus=0.1)
    class Barrier:
        def __init__(self, n):
            self.n = n
            self.count = 0

        def arrive(self):
            self.count += 1
            return self.count

        def ready(self):
            return self.count >= self.n

    bar = Barrier.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            cluster.nodes[0].node_id)).remote(3)

    @ray_tpu.remote(num_cpus=1)
    def hold(bar):
        import time

        import ray_tpu
        ray_tpu.get(bar.arrive.remote(), timeout=30)
        deadline = time.time() + 45
        while time.time() < deadline:
            if ray_tpu.get(bar.ready.remote(), timeout=30):
                return ray_tpu.get_runtime_context()["node_id"]
            time.sleep(0.05)
        raise TimeoutError("fewer than 3 tasks ran concurrently "
                           "(no spillback happened)")

    refs = [hold.remote(bar) for _ in range(6)]
    got = ray_tpu.get(refs, timeout=90)
    assert len(set(got)) >= 2


def test_node_death_actor_restart(cluster):
    node = cluster.add_node(num_cpus=1, resources={"doomed": 1.0})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_restarts=1, resources={"doomed": 0.01}, num_cpus=0.1)
    class Pinned:
        def node(self):
            import ray_tpu
            return ray_tpu.get_runtime_context()["node_id"]

    # not enough "doomed" elsewhere → after node death actor must report DEAD
    a = Pinned.remote()
    assert ray_tpu.get(a.node.remote(), timeout=30) == node.node_id
    cluster.remove_node(node)
    time.sleep(6.5)   # heartbeat timeout
    with pytest.raises((ray_tpu.ActorDiedError, TimeoutError)):
        ray_tpu.get(a.node.remote(), timeout=15)


def test_actor_restart_on_other_node(cluster):
    node = cluster.add_node(num_cpus=1, resources={"flaky": 1.0})
    cluster.wait_for_nodes()

    @ray_tpu.remote(max_restarts=2, num_cpus=0.5)
    class Roamer:
        def node(self):
            import ray_tpu
            return ray_tpu.get_runtime_context()["node_id"]

    # schedule with affinity to the doomed node, soft so it can move
    a = Roamer.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=node.node_id, soft=True)).remote()
    first = ray_tpu.get(a.node.remote(), timeout=30)
    assert first == node.node_id
    cluster.remove_node(node)
    time.sleep(6.5)
    second = ray_tpu.get(a.node.remote(), timeout=30)
    assert second != node.node_id
