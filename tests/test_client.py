"""Remote client sessions (reference: python/ray/util/client/ — the
`ray://` proxy). A separate process hosts the cluster + client proxy;
this process connects WITHOUT ray_tpu.init and drives tasks, actors,
puts and waits over the single proxy connection."""

import os
import subprocess
import sys
import time

import pytest

HOST_SCRIPT = """
import sys, time
import ray_tpu
from ray_tpu.client import serve_proxy
ray_tpu.init(num_cpus=2, object_store_memory=64*1024*1024)
addr = serve_proxy()
print(f"PROXY_ADDR={addr}", flush=True)
while True:
    time.sleep(1)
"""


@pytest.fixture(scope="module")
def proxy_addr():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    proc = subprocess.Popen([sys.executable, "-c", HOST_SCRIPT],
                            stdout=subprocess.PIPE, text=True, env=env)
    addr = None
    deadline = time.time() + 60
    while time.time() < deadline:
        if proc.poll() is not None:
            break              # host died: readline() would spin on ''
        line = proc.stdout.readline()
        if line.startswith("PROXY_ADDR="):
            addr = line.strip().split("=", 1)[1]
            break
    assert addr, "proxy did not start"
    yield addr
    proc.terminate()
    proc.wait(timeout=10)


def test_session_isolation(proxy_addr):
    """Per-connection sessions (reference: proxier.py one-server-per-job
    isolation): another connection can NEITHER read nor free a
    session's refs, and the proxy survives a session's disconnect."""
    import asyncio

    from ray_tpu import client as rc
    from ray_tpu._private import rpc

    ctx = rc.connect(proxy_addr)
    try:
        import ray_tpu
        ref = ray_tpu.put({"secret": 42})
        oid = ref.id

        # a SECOND connection probing the first session's oid must fail
        # (its session tables are its own), while the owner still reads
        async def probe():
            conn = await rpc.connect(proxy_addr, name="intruder")
            try:
                out = await conn.call("get", oids=[oid], timeout=30)
                return out
            except rpc.RpcError as e:
                return ("denied", str(e))
            finally:
                await conn.close()

        out = asyncio.run(probe())
        assert out[0] == "denied" and "KeyError" in out[1], out
        assert ray_tpu.get(ref, timeout=60) == {"secret": 42}
    finally:
        ctx.disconnect()

    # proxy stays healthy for fresh sessions after a disconnect
    ctx2 = rc.connect(proxy_addr)
    try:
        import ray_tpu

        @ray_tpu.remote
        def ping():
            return "alive"

        assert ray_tpu.get(ping.remote(), timeout=60) == "alive"
    finally:
        ctx2.disconnect()


def test_detached_actor_survives_and_reattaches(proxy_addr):
    """Detached actors outlive the creating session; a reconnecting
    client reattaches by name via get_actor (reference: ray.get_actor
    through the client proxy; proxier session isolation)."""
    from ray_tpu import client as rc

    ctx = rc.connect(proxy_addr)
    try:
        import ray_tpu

        @ray_tpu.remote
        class Keeper:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        k = Keeper.options(lifetime="detached", name="keeper").remote()
        assert ray_tpu.get(k.incr.remote(), timeout=60) == 1
    finally:
        ctx.disconnect()

    time.sleep(1.0)   # let disconnect reaping (of non-detached) run
    ctx2 = rc.connect(proxy_addr)
    try:
        import ray_tpu
        k2 = ray_tpu.get_actor("keeper")
        # state survived the session that created it
        assert ray_tpu.get(k2.incr.remote(), timeout=60) == 2
        ray_tpu.kill(k2)
    finally:
        ctx2.disconnect()


def test_client_tasks_actors_objects(proxy_addr):
    from ray_tpu import client as rc
    ctx = rc.connect(proxy_addr)
    try:
        import ray_tpu

        @ray_tpu.remote
        def add(a, b):
            return a + b

        ref = add.remote(2, 3)
        assert ray_tpu.get(ref, timeout=60) == 5

        # object refs as args resolve server-side
        big = ray_tpu.put(list(range(100)))
        @ray_tpu.remote
        def total(xs):
            return sum(xs)
        assert ray_tpu.get(total.remote(big), timeout=60) == 4950

        # wait
        refs = [add.remote(i, i) for i in range(4)]
        ready, rest = ray_tpu.wait(refs, num_returns=4, timeout=60)
        assert len(ready) == 4 and not rest
        assert sorted(ray_tpu.get(ready, timeout=60)) == [0, 2, 4, 6]

        # actors
        @ray_tpu.remote
        class Counter:
            def __init__(self, start):
                self.n = start

            def incr(self, k=1):
                self.n += k
                return self.n

        c = Counter.remote(10)
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 11
        assert ray_tpu.get(c.incr.remote(5), timeout=60) == 16
        ray_tpu.kill(c)

        # errors propagate
        @ray_tpu.remote
        def boom():
            raise ValueError("client boom")

        with pytest.raises(Exception, match="client boom"):
            ray_tpu.get(boom.remote(), timeout=60)

        assert ctx.cluster_resources().get("CPU") == 2.0
    finally:
        ctx.disconnect()
