"""Chaos tests: workloads complete while a node dies mid-run and while
RPCs randomly fail (reference: python/ray/tests/chaos/ + release chaos
suites — setup_chaos.py kills nodes during Data/Train workloads)."""

import subprocess
import sys
import time

import pytest


def test_tasks_survive_node_kill():
    """Retriable tasks spread over 3 nodes; one node dies mid-flight; all
    results still arrive via task retry (owner-side resubmission)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.chaos import NodeKiller

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    w1 = cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(max_retries=5, scheduling_strategy="SPREAD")
        def slow_square(x):
            time.sleep(0.4)
            return x * x

        refs = [slow_square.remote(i) for i in range(24)]
        killer = NodeKiller(cluster, interval_s=1.0,
                            protected_node_ids=[cluster.nodes[0].node_id],
                            max_kills=1).start()
        try:
            out = ray_tpu.get(refs, timeout=180)
        finally:
            killer.stop()
        assert out == [i * i for i in range(24)]
        assert killer.killed, "no node was killed — chaos did not fire"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


CHAOS_RPC_SCRIPT = """
import ray_tpu
ray_tpu.init(num_cpus=2)

@ray_tpu.remote(max_retries=10)
def f(x):
    return x + 1

out = ray_tpu.get([f.remote(i) for i in range(40)], timeout=120)
assert out == [i + 1 for i in range(40)], out
print("RPC_CHAOS_OK", flush=True)
"""


def test_rpc_failure_injection():
    """5% of pull_object/request_lease RPCs raise injected errors; the
    retry paths absorb them (reference: RAY_testing_rpc_failure)."""
    import os
    env = dict(os.environ)
    env["RAY_TPU_TESTING_RPC_FAILURE"] = \
        "request_lease=0.05,pull_object=0.05"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", CHAOS_RPC_SCRIPT],
                         capture_output=True, text=True, timeout=180,
                         env=env)
    assert "RPC_CHAOS_OK" in out.stdout, out.stdout + out.stderr
