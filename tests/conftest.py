"""Test env: force an 8-device virtual CPU mesh (multi-chip sharding is
tested hermetically on CPU — real TPU hardware is exercised by bench.py /
__graft_entry__.py instead).

Set via jax.config (not env vars): pytest plugins may import jax before this
conftest runs, but the backend only initializes on first device use, so the
config route still wins."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# worker processes pin themselves through worker_main (the axon
# sitecustomize overrides the env var with jax.config at startup, so the
# env alone doesn't stick in children)
os.environ["RAY_TPU_JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:   # backend already initialized (env vars took effect)
    pass
