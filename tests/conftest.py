"""Test env: force an 8-device virtual CPU mesh (multi-chip sharding is
tested hermetically on CPU — real TPU hardware is exercised by bench.py /
__graft_entry__.py instead).

Set via jax.config (not env vars): pytest plugins may import jax before this
conftest runs, but the backend only initializes on first device use, so the
config route still wins."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# worker processes pin themselves through worker_main (the axon
# sitecustomize overrides the env var with jax.config at startup, so the
# env alone doesn't stick in children)
os.environ["RAY_TPU_JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except Exception:   # backend already initialized (env vars took effect)
    pass

import uuid  # noqa: E402

# Every daemon spawned during this pytest session inherits this marker in
# its environment; the suite-final hygiene check (test_zz_process_hygiene)
# scans /proc for survivors carrying it and fails the run if any daemon
# outlived its test (round-4 audit: 131 leaked processes after a green
# suite).
os.environ.setdefault("RAY_TPU_TEST_SESSION", uuid.uuid4().hex)

import pytest  # noqa: E402

# Two tiers (suite wall-clock grows ~6 min/round; the full matrix is for
# rounds/CI, the fast tier for inner-loop dev):
#   fast:  python -m pytest tests/ -m 'not slow'   (~1/3 of the time)
#   full:  python -m pytest tests/
_SLOW_FILES = {
    "test_chaos.py", "test_cluster_launcher.py", "test_data_shuffle.py",
    "test_data_ingest.py", "test_gcs_ft.py", "test_jax_distributed.py",
    "test_multi_node.py", "test_object_transfer.py",
    "test_rl_regression.py", "test_rl_algos.py", "test_rl_multi_agent.py",
    "test_runtime_env_pip.py", "test_serve_harden.py", "test_serve.py",
    "test_slice_gang.py", "test_train_e2e.py", "test_tune.py",
    "test_view_sync.py", "test_sharded_checkpoint.py",
}


def pytest_collection_modifyitems(items):
    for item in items:
        if item.path.name in _SLOW_FILES:
            item.add_marker(pytest.mark.slow)
