"""Distributed shuffle/sort/groupby across a 3-node cluster: 1M rows move
through map/reduce exchange tasks — block bytes never materialize in the
driver (reference: _internal/planner/exchange/)."""

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 2,
                                "object_store_memory": 128 * 1024 * 1024})
    for _ in range(2):
        c.add_node(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    ray_tpu.init(address=c.address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_shuffle_1m_rows_multi_node(cluster):
    n = 1_000_000
    ds = rd.range(n, parallelism=8).random_shuffle(seed=7)
    # exact permutation: all rows survive, order differs from identity
    total = 0
    prefix = []
    for batch in ds.iter_batches(batch_size=100_000, batch_format="numpy"):
        ids = batch["id"]
        total += len(ids)
        if len(prefix) < 3:
            prefix.append(int(ids[0]))
    assert total == n
    assert prefix != sorted(prefix) or prefix[0] != 0
    # spot-check global content equality via a checksum
    s = 0
    for batch in ds.iter_batches(batch_size=200_000, batch_format="numpy"):
        s += int(batch["id"].sum())
    assert s == n * (n - 1) // 2


def test_distributed_sort_globally_ordered(cluster):
    n = 200_000
    rng = np.random.default_rng(3)
    vals = rng.permutation(n)
    ds = rd.from_items([{"v": int(v)} for v in vals]) \
        .sort("v")
    last = -1
    total = 0
    for batch in ds.iter_batches(batch_size=50_000, batch_format="numpy"):
        v = batch["v"]
        assert (np.diff(v) >= 0).all()
        assert int(v[0]) > last or total == 0
        assert int(v[0]) >= last
        last = int(v[-1])
        total += len(v)
    assert total == n and last == n - 1


def test_distributed_groupby_string_keys(cluster):
    """String keys hash with a salted per-interpreter hash() builtin; the
    exchange must partition them with a process-independent hash or the
    same key lands in multiple reduce partitions and the aggregate is
    silently wrong (one output row per key fragment)."""
    keys = [f"user-{i % 7}" for i in range(30_000)]
    ds = rd.from_items([{"k": k, "x": 1.0} for k in keys], parallelism=6)
    out = ds.groupby("k").sum("x").take_all()
    assert len(out) == 7, [r["k"] for r in out]
    got = {r["k"]: list(r.values())[1] for r in out}
    for i in range(7):
        expect = sum(1 for k in keys if k == f"user-{i}")
        assert got[f"user-{i}"] == expect


def test_distributed_groupby_agg(cluster):
    ds = rd.from_items([{"k": i % 10, "x": float(i)}
                        for i in range(100_000)])
    out = ds.groupby("k").sum("x").take_all()
    assert len(out) == 10
    got = {int(r["k"]): r["sum(x)"] if "sum(x)" in r else r.get("x_sum",
           list(r.values())[1]) for r in out}
    for k in range(10):
        expect = sum(float(i) for i in range(k, 100_000, 10))
        assert abs(got[k] - expect) < 1e-6
