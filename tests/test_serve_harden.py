"""Serve hardening: proxy-per-node, long-poll config push, gRPC ingress,
declarative YAML deploys (reference: _private/long_poll.py:177 LongPollHost,
proxy.py:558 gRPCProxy + :1153 one ProxyActor per node, serve/schema.py).
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def two_node_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes()
    yield cluster
    serve.shutdown()
    ray_tpu.shutdown()
    cluster.shutdown()


@serve.deployment
def echo(payload):
    return {"echo": payload}


@serve.deployment
class Version:
    def __init__(self, tag):
        self.tag = tag

    def __call__(self, payload):
        return self.tag


def _http_get(addr, path, payload="x"):
    req = urllib.request.Request(
        f"http://{addr}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = resp.read()
    try:
        return json.loads(body)
    except json.JSONDecodeError:
        return body.decode()     # plain-text responses (string results)


def test_proxy_per_node_and_grpc(two_node_cluster):
    serve.run(echo.bind(), name="app1", route_prefix="/")
    serve.start(http_port=18123, grpc_port=19123)

    n_nodes = len([n for n in ray_tpu.nodes() if n["alive"]])
    assert n_nodes == 2
    deadline = time.time() + 60
    while time.time() < deadline:
        proxies = serve.proxies()
        if len(proxies) >= n_nodes and all("http" in p and "grpc" in p
                                           for p in proxies.values()):
            break
        time.sleep(0.5)
    # one proxy pair on EVERY node
    assert len(proxies) == n_nodes, proxies
    http_addrs = {p["http"] for p in proxies.values()}
    grpc_addrs = {p["grpc"] for p in proxies.values()}
    assert len(http_addrs) == n_nodes     # distinct listeners
    assert len(grpc_addrs) == n_nodes

    # every node's HTTP proxy serves the app
    for node_id, addrs in proxies.items():
        out = _http_get(addrs["http"], "/", payload="hi")
        assert out == {"echo": "hi"}, (node_id, out)

    # gRPC ingress round trip on each node
    for node_id, addrs in proxies.items():
        out = serve.grpc_call(addrs["grpc"], {"k": 1}, application="app1")
        assert out == {"echo": {"k": 1}}, (node_id, out)


def test_longpoll_push_latency(two_node_cluster):
    handle = serve.run(Version.bind("v1"), name="vapp", route_prefix="/v")
    assert handle.remote("x").result(timeout=30) == "v1"
    router = handle._router
    v_before = router.version

    # DISABLE the router's polling fallback: any update it sees from here
    # on can only arrive via the controller's long-poll push
    router._last_refresh = time.monotonic() + 3600

    serve.run(Version.bind("v2"), name="vapp", route_prefix="/v")
    # wait until the new replica is actually running (replica startup is
    # not config-propagation latency)
    deadline = time.time() + 60
    while time.time() < deadline:
        dep = serve.status()["vapp"]["Version"]
        if dep["running"] >= 1 and dep["version"] > v_before:
            break
        time.sleep(0.1)
    # push-only propagation into the live handle
    t0 = time.time()
    while time.time() < t0 + 10:
        if router.version >= dep["version"]:
            break
        time.sleep(0.02)
    latency = time.time() - t0
    # propagation (push into the live router) beats the 2s poll fallback
    # by an order of magnitude; the request itself is timed separately
    # (first call to a cold replica is startup cost, not config latency)
    assert router.version >= dep["version"], (router.version, dep)
    assert latency < 1.0, f"push propagation took {latency:.2f}s"
    # redeploys are ROLLING: v1 replicas legitimately serve until the
    # roll retires them — poll for convergence (pushes keep arriving)
    deadline = time.time() + 60
    seen = None
    while time.time() < deadline:
        seen = handle.remote("x").result(timeout=30)
        if seen == "v2":
            break
        time.sleep(0.5)
    assert seen == "v2", seen


def _build_yaml_app(tag="yaml-v1"):
    return Version.bind(tag)


def test_declarative_config_deploy(two_node_cluster):
    config = {
        "http_options": {"port": 18240},
        "applications": [
            {
                "name": "yam",
                "route_prefix": "/yam",
                "import_path": "tests.test_serve_harden:_build_yaml_app",
                "args": {"tag": "from-yaml"},
                "deployments": [{"name": "Version", "num_replicas": 2}],
            }
        ],
    }
    handles = serve.deploy_from_config(config)
    assert handles[0].remote("x").result(timeout=30) == "from-yaml"
    st = serve.status()
    assert st["yam"]["Version"]["target"] == 2
    deadline = time.time() + 30
    while time.time() < deadline:
        proxies = serve.proxies()
        if proxies and all("http" in p for p in proxies.values()):
            break
        time.sleep(0.5)
    addr = next(iter(proxies.values()))["http"]
    assert _http_get(addr, "/yam", payload="q") == "from-yaml"


def test_autoscale_windowed_no_flapping():
    """Bursty load through the windowed policy: upscale happens after the
    sustained delay, momentary dips never drop replicas, and a sustained
    quiet period scales down once (reference: serve/autoscaling_policy.py
    look-back + delay semantics)."""
    import collections

    from ray_tpu.serve.controller import autoscale_decision

    auto = {"min_replicas": 1, "max_replicas": 8,
            "target_ongoing_requests": 2.0, "upscale_delay_s": 2.0,
            "downscale_delay_s": 10.0, "look_back_period_s": 4.0}
    hist = collections.deque()
    up, down, key = {}, {}, "d"
    target = 1
    targets = []
    # quiet warm-up fills the window, then load alternates 12 <-> 0 every
    # tick (1s): window-avg ~6 -> desired 3
    for t in range(4):
        target = autoscale_decision(auto, hist, 0.0, target, float(t),
                                    up, down, key)
        assert target == 1
    for t in range(4, 40):
        load = 12.0 if t % 2 == 0 else 0.0
        target = autoscale_decision(auto, hist, load, target, float(t),
                                    up, down, key)
        targets.append(target)
    # scaled up exactly once past the delay, then stayed put: no flapping
    assert target == 3, targets
    changes = sum(1 for a, b in zip(targets, targets[1:]) if a != b)
    assert changes == 1, targets
    # sustained quiet: no immediate drop (downscale delay), then one drop
    for t in range(40, 49):
        target = autoscale_decision(auto, hist, 0.0, target, float(t),
                                    up, down, key)
        assert target == 3   # inside downscale_delay_s
    for t in range(49, 60):
        target = autoscale_decision(auto, hist, 0.0, target, float(t),
                                    up, down, key)
    assert target == 1
