"""Push-based streaming shuffle (ray_tpu/data/shuffle.py).

Two tiers of coverage:

1. A hermetic fake-runtime harness (eager in-process task execution with
   pluggable completion ORDER) drives the real driver-side streaming
   logic — windowed map launch, contiguous merge-run folding, reduce
   ordering, the peak-live gauges, and seed determinism independent of
   task completion timing. These run everywhere, no cluster needed.

2. Cluster end-to-end tests (spill-backed overflow, lineage recovery of
   a killed reduce output, cross-run determinism) — gated on the
   runtime's Python floor, slow tier where multi-node.
"""

import sys

import numpy as np
import pytest

import ray_tpu
from ray_tpu.data import block as block_lib
from ray_tpu.data import exchange
from ray_tpu.data import shuffle as shuffle_lib

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")


# --------------------------------------------------------- fake runtime
class _Ref:
    __slots__ = ("val",)

    def __init__(self, val):
        self.val = val


def _unwrap(x):
    return x.val if isinstance(x, _Ref) else x


class _FakeTask:
    def __init__(self, fn, opts):
        self.fn, self.opts = fn, opts

    def options(self, **kw):
        return _FakeTask(self.fn, {**self.opts, **kw})

    def remote(self, *args, **kwargs):
        out = self.fn(*[_unwrap(a) for a in args],
                      **{k: _unwrap(v) for k, v in kwargs.items()})
        n = self.opts.get("num_returns", 1)
        if n == 1:
            return _Ref(out)
        out = list(out)
        assert len(out) == n, (len(out), n)
        return [_Ref(v) for v in out]


def _fake_remote(fn=None, **opts):
    if fn is None:
        return lambda f: _FakeTask(f, opts)
    return _FakeTask(fn, opts)


def _fake_get(refs, **_kw):
    if isinstance(refs, list):
        return [_unwrap(r) for r in refs]
    return _unwrap(refs)


def _make_fake_wait(order: str):
    """Completion-order knob: 'fifo' hands back the oldest in-flight
    task first, 'lifo' the newest — determinism must survive both."""

    def _wait(refs, num_returns=1, timeout=None):
        refs = list(refs)
        if order == "lifo":
            ready = refs[-num_returns:]
        else:
            ready = refs[:num_returns]
        rest = [r for r in refs if r not in ready]
        return ready, rest

    return _wait


@pytest.fixture(params=["fifo", "lifo"])
def fake_runtime(request, monkeypatch):
    monkeypatch.setattr(ray_tpu, "remote", _fake_remote)
    monkeypatch.setattr(ray_tpu, "get", _fake_get)
    monkeypatch.setattr(ray_tpu, "wait", _make_fake_wait(request.param))
    monkeypatch.setattr(ray_tpu, "put", lambda v: _Ref(v))
    monkeypatch.setattr(ray_tpu, "is_initialized", lambda: False)
    return request.param


def _bundles(nblocks, rows_per=50, key_mod=None):
    out = []
    for i in range(nblocks):
        ids = np.arange(i * rows_per, (i + 1) * rows_per)
        cols = {"id": ids}
        if key_mod:
            cols["k"] = ids % key_mod
        blk = block_lib.block_from_batch(cols)
        out.append((_Ref(blk), block_lib.block_metadata(blk)))
    return out


def _rows(stage, bundles, budget=None):
    out = []
    for ref, _meta in stage.execute(iter(bundles), budget):
        out.extend(block_lib.block_to_rows(_unwrap(ref)))
    return out


# ------------------------------------------------- fake-runtime coverage
def test_streaming_shuffle_permutation_deterministic(fake_runtime):
    """Same seed -> identical output ORDER, regardless of task
    completion order; output is an exact permutation of the input."""
    n_blocks, rows = 24, 50
    runs = []
    for _ in range(2):
        st = shuffle_lib.ShuffleStage("random_shuffle", seed=7)
        ids = [r["id"] for r in _rows(st, _bundles(n_blocks, rows))]
        assert not st.stats.fallback
        assert st.stats.map_tasks == n_blocks
        runs.append(ids)
    assert runs[0] == runs[1]
    assert sorted(runs[0]) == list(range(n_blocks * rows))
    assert runs[0] != sorted(runs[0])
    # a different seed permutes differently
    st2 = shuffle_lib.ShuffleStage("random_shuffle", seed=8)
    assert [r["id"] for r in _rows(st2, _bundles(n_blocks, rows))] != runs[0]


def test_peak_live_inputs_bounded(fake_runtime):
    """The memory-bound evidence: the stage never holds more than the
    in-flight window of input-block refs, no matter how many blocks
    stream through, and intermediate merges keep per-partition unmerged
    sub-block refs bounded too."""
    n_blocks = 64
    st = shuffle_lib.ShuffleStage("random_shuffle", seed=1)
    rows = _rows(st, _bundles(n_blocks, 20))
    assert len(rows) == n_blocks * 20
    g = st.stats
    assert g.input_blocks == n_blocks
    assert g.peak_live_inputs <= shuffle_lib.DEFAULT_MAX_MAPS
    assert g.peak_live_inputs < n_blocks
    assert g.merge_tasks > 0                 # runs actually folded
    total_subblocks = n_blocks * g.num_partitions
    assert g.peak_live_partials < total_subblocks
    # structural bound independent of dataset size: stuck window slots +
    # up to two partially-filled runs per partition
    assert g.peak_live_partials <= g.num_partitions * (
        shuffle_lib.DEFAULT_MAX_MAPS + 2 * shuffle_lib.DEFAULT_MERGE_FACTOR)
    assert shuffle_lib.last_shuffle_stats() is g


def test_streaming_repartition_exact_block_count(fake_runtime):
    st = shuffle_lib.ShuffleStage("repartition", num_blocks=6)
    bundles = _bundles(10, 37)
    out = list(st.execute(iter(bundles)))
    assert len(out) == 6                     # exact contract, empties kept
    rows = []
    sizes = []
    for ref, meta in out:
        blk = _unwrap(ref)
        sizes.append(blk.num_rows)
        rows.extend(block_lib.block_to_rows(blk))
    assert sorted(r["id"] for r in rows) == list(range(370))
    assert max(sizes) - min(sizes) <= 10     # round-robin balance


def test_streaming_sort_globally_ordered(fake_runtime):
    rng = np.random.default_rng(0)
    vals = rng.permutation(4000)
    bundles = []
    for chunk in np.array_split(vals, 16):
        blk = block_lib.block_from_batch({"v": chunk})
        bundles.append((_Ref(blk), block_lib.block_metadata(blk)))
    st = shuffle_lib.ShuffleStage("sort", key="v")
    got = [r["v"] for r in _rows(st, bundles)]
    assert got == list(range(4000))
    st_d = shuffle_lib.ShuffleStage("sort", key="v", descending=True)
    got_d = [r["v"] for r in _rows(st_d, bundles)]
    assert got_d == list(range(3999, -1, -1))


def test_streaming_groupby_sum(fake_runtime):
    st = shuffle_lib.ShuffleStage(
        "groupby_agg", key="k", aggs=[("id", "sum", "sum(id)")])
    rows = _rows(st, _bundles(12, 40, key_mod=5))
    assert len(rows) == 5
    got = {int(r["k"]): r["sum(id)"] for r in rows}
    n = 12 * 40
    for k in range(5):
        assert got[k] == sum(i for i in range(n) if i % 5 == k)


def test_unseeded_shuffle_still_permutes(fake_runtime):
    """seed=None must still permute (fresh per-execution entropy), not
    degenerate to map-index order within partitions."""
    st = shuffle_lib.ShuffleStage("random_shuffle", seed=None)
    ids_a = [r["id"] for r in _rows(st, _bundles(16, 40))]
    assert sorted(ids_a) == list(range(640))
    assert ids_a != sorted(ids_a)
    st_b = shuffle_lib.ShuffleStage("random_shuffle", seed=None)
    ids_b = [r["id"] for r in _rows(st_b, _bundles(16, 40))]
    assert ids_a != ids_b          # fresh entropy per execution


def test_tiny_input_falls_back_to_legacy(fake_runtime):
    st = shuffle_lib.ShuffleStage("random_shuffle", seed=3)
    rows = _rows(st, _bundles(2, 30))
    assert st.stats.fallback
    assert sorted(r["id"] for r in rows) == list(range(60))


def test_merge_factor_controls_fold_granularity(fake_runtime):
    st = shuffle_lib.ShuffleStage("random_shuffle", seed=5, merge_factor=4,
                                  num_partitions=4)
    rows = _rows(st, _bundles(32, 10))
    assert len(rows) == 320
    # 32 maps -> 8 complete runs of 4 per partition
    assert st.stats.merge_tasks == 4 * (32 // 4)


# ------------------------------------------------------ unit-level bits
def test_partition_round_robin_balance_and_empty():
    blk = block_lib.block_from_batch({"id": np.arange(10)})
    parts = exchange.partition_round_robin(blk, 3)
    assert [p.num_rows for p in parts] == [4, 3, 3]
    empty = block_lib.block_from_batch({"id": np.arange(0)})
    assert [p.num_rows for p in exchange.partition_round_robin(empty, 3)] \
        == [0, 0, 0]


def test_concat_blocks_preserves_schema_when_all_empty():
    blk = block_lib.block_from_batch({"a": np.arange(5), "b": np.arange(5)})
    empty = blk.slice(0, 0)
    out = block_lib.concat_blocks([empty, empty])
    assert out.num_rows == 0
    assert out.column_names == ["a", "b"]


def test_plurality_node_weighs_bytes(monkeypatch):
    locs = {"r1": "nodeA", "r2": "nodeB", "r3": "nodeB", "r4": None}
    monkeypatch.setattr(shuffle_lib, "object_node_ids",
                        lambda refs: [locs[r] for r in refs])
    # nodeA holds 100 bytes in one ref; nodeB holds 30 across two
    assert shuffle_lib.plurality_node(
        [("r1", 100), ("r2", 10), ("r3", 20), ("r4", 500)]) == "nodeA"
    assert shuffle_lib.plurality_node([("r4", 500)]) is None
    assert shuffle_lib.plurality_node([]) is None


def test_derived_seed_stability():
    assert shuffle_lib._derived_seed(None, 0, 3) is None
    a = shuffle_lib._derived_seed(7, 0, 3)
    assert a == shuffle_lib._derived_seed(7, 0, 3)
    assert a != shuffle_lib._derived_seed(7, 1, 3)
    assert a != shuffle_lib._derived_seed(7, 0, 4)


# --------------------------------------------------- cluster end-to-end
ROW_PAD = 8192            # bytes of payload per row


def _fat_dataset(total_bytes: int, parallelism: int = 16):
    import ray_tpu.data as rd
    n_rows = total_bytes // (ROW_PAD + 8)
    pad = "x" * ROW_PAD

    def fatten(batch):
        return {"id": batch["id"],
                "pad": np.array([pad] * len(batch["id"]), dtype=object)}

    return n_rows, rd.range(n_rows, parallelism=parallelism) \
        .map_batches(fatten)


@needs_cluster
@pytest.mark.slow
def test_shuffle_2x_store_budget_completes_via_spill():
    """Acceptance: random_shuffle on a dataset >= 2x the object-store
    budget completes, with the stage never holding all input blocks
    live (peak live-block gauge)."""
    store = 64 * 1024 * 1024
    ray_tpu.init(num_cpus=4, object_store_memory=store)
    try:
        n_rows, ds = _fat_dataset(2 * store + 16 * 1024 * 1024)
        total = 0
        checksum = 0
        for batch in ds.random_shuffle(seed=11).iter_batches(
                batch_size=4096, batch_format="numpy"):
            total += len(batch["id"])
            checksum += int(batch["id"].sum())
        assert total == n_rows
        assert checksum == n_rows * (n_rows - 1) // 2
        g = shuffle_lib.last_shuffle_stats()
        assert g is not None and not g.fallback
        assert g.peak_live_inputs < g.input_blocks
        assert g.peak_live_inputs <= shuffle_lib.DEFAULT_MAX_MAPS
    finally:
        ray_tpu.shutdown()


@needs_cluster
def test_shuffle_seed_deterministic_on_cluster():
    import ray_tpu.data as rd
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    try:
        runs = []
        for _ in range(2):
            ds = rd.range(20_000, parallelism=8).random_shuffle(seed=123)
            runs.append([r["id"] for b in ds.iter_batches(
                batch_size=5000, batch_format="numpy") for r in
                ({"id": int(v)} for v in b["id"])])
        assert runs[0] == runs[1]
        assert sorted(runs[0]) == list(range(20_000))
        assert runs[0] != sorted(runs[0])
    finally:
        ray_tpu.shutdown()


@needs_cluster
@pytest.mark.slow
def test_reduce_output_killed_mid_shuffle_recovers_via_lineage():
    """A shuffle output living only on a killed node is reconstructed
    through the map->merge->reduce lineage chain on fetch."""
    import ray_tpu.data as rd
    from ray_tpu.cluster_utils import Cluster
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 2,
                                "object_store_memory": 128 * 1024 * 1024})
    n2 = c.add_node(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    ray_tpu.init(address=c.address)
    try:
        ds = rd.range(100_000, parallelism=8).random_shuffle(seed=5)
        refs = ds.get_internal_block_refs()
        assert refs
        import time as _t
        _t.sleep(0.5)
        c.remove_node(n2)
        _t.sleep(1.0)
        total = 0
        checksum = 0
        for ref in refs:
            blk = ray_tpu.get(ref, timeout=120)
            total += blk.num_rows
            checksum += sum(blk.column("id").to_pylist())
        assert total == 100_000
        assert checksum == 100_000 * 99_999 // 2
    finally:
        ray_tpu.shutdown()
        c.shutdown()
