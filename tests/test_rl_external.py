"""External-env plane: policy server/client + ExternalPPO (reference:
rllib/env/policy_server_input.py, policy_client.py — unmanaged
simulators query the live policy over HTTP and their experience trains
the learner)."""

import threading

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import AlgorithmConfig, PolicyClient
from ray_tpu.rl.external import PolicyServer


def test_policy_server_protocol_unit():
    """Server + client round trip without a cluster: episodes record
    per-step policy outputs, episode end produces one GAE'd fragment
    with the PPO batch contract."""
    cfg = {"obs_shape": [4], "action_spec": {"type": "discrete", "n": 2},
           "hidden_sizes": (16,), "seed": 0, "gamma": 0.99,
           "lambda_": 0.95}
    server = PolicyServer(cfg, port=0)
    client = PolicyClient(server.address())
    eid = client.start_episode()
    rng = np.random.default_rng(0)
    for t in range(5):
        a = client.get_action(eid, rng.normal(size=4))
        assert a in (0, 1)
        client.log_returns(eid, 1.0)
    client.end_episode(eid, rng.normal(size=4))
    frags = server.drain()
    assert len(frags) == 1
    f = frags[0]
    assert set(f) == {"obs", "actions", "logp", "advantages",
                      "value_targets"}
    assert f["obs"].shape == (5, 4) and f["actions"].shape == (5,)
    assert np.isfinite(f["advantages"]).all()
    assert server.drain() == []          # drained exactly once
    m = server.get_metrics()
    assert m["num_episodes"] == 1
    assert m["episode_return_mean"] == pytest.approx(5.0)
    # unknown episode -> loud client-side error
    with pytest.raises(RuntimeError):
        client.get_action("nope", np.zeros(4))


@pytest.mark.slow
def test_external_ppo_cartpole(ray_start=None):
    """End-to-end: external simulator processes drive CartPole through
    the HTTP policy server; ExternalPPO must learn from that experience
    (stop reward 80 — random is ~20)."""
    import gymnasium as gym

    from ray_tpu.rl import ExternalPPO
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .training(train_batch_size=512, minibatch_size=128,
                        num_epochs=6, lr=1e-3, entropy_coeff=0.01))
    algo = ExternalPPO(config, num_servers=1)
    stop = threading.Event()

    def simulate(seed):
        client = PolicyClient(algo.addresses[0])
        env = gym.make("CartPole-v1")
        obs, _ = env.reset(seed=seed)
        eid = client.start_episode()
        while not stop.is_set():
            action = client.get_action(eid, obs)
            obs, rew, term, trunc, _ = env.step(action)
            client.log_returns(eid, rew)
            if term or trunc:
                client.end_episode(eid, obs)
                obs, _ = env.reset()
                eid = client.start_episode()

    sims = [threading.Thread(target=simulate, args=(i,), daemon=True)
            for i in range(2)]
    for t in sims:
        t.start()
    best = -np.inf
    try:
        for _ in range(40):
            r = algo.train()["episode_return_mean"]
            if r is not None:
                best = max(best, r)
            if best >= 80:
                break
    finally:
        stop.set()
        algo.stop()
        for t in sims:
            t.join(timeout=10)
        ray_tpu.shutdown()
    assert best >= 80, best
