"""Time-series metrics plane (ray_tpu/_private/metrics_ts.py) + SLO
burn-rate engine (ray_tpu/serve/slo.py): ring retention/eviction
determinism, counter-delta and histogram-delta storage, percentile
reconstruction vs exact values, query window edges, GCS handler wiring,
burn-rate transitions under synthetic pushes, pusher hardening, and the
chrome-trace counter tracks. All CPU-only, no cluster."""

import random
import sys
import threading
import time

import numpy as np
import pytest

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")

from ray_tpu._private import events
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.metrics_ts import (MetricsTimeSeries,
                                         fraction_over,
                                         percentile_from_buckets)
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util.metrics import (Histogram, counter_snapshot,
                                  gauge_snapshot, render_prometheus)


def _counter_row(name, value, tags=None):
    return counter_snapshot(name, value, tags=tags)


def _gauge_row(name, value, tags=None):
    return gauge_snapshot(name, value, tags=tags)


# ------------------------------------------------------------- ring storage
def test_counter_deltas_and_reset_detection():
    ts = MetricsTimeSeries()
    for t, v in [(0, 5.0), (2, 8.0), (4, 8.0), (6, 3.0), (8, 10.0)]:
        ts.ingest("w1", [_counter_row("c", v)], ts=100.0 + t)
    # deltas: 5 (first), 3, skip (unchanged), 3 (reset -> full value), 7
    q = ts.query("c", window_s=60, agg="sum", now=110.0)
    assert q["value"] == 18.0
    assert q["n_samples"] == 4          # the unchanged push stored nothing
    # (100, 110] excludes the first delta (left-exclusive edge): 3+3+7
    assert ts.query("c", window_s=10, agg="rate", now=110.0)["value"] \
        == pytest.approx(1.3)


def test_ring_eviction_is_deterministic_oldest_first():
    ts = MetricsTimeSeries(max_samples=4)
    for i in range(10):
        ts.ingest("w1", [_gauge_row("g", float(i))], ts=100.0 + i)
    q = ts.query("g", window_s=100, agg="series", now=200.0)
    kept = [v for _, v in q["series"][0]["samples"]]
    assert kept == [6.0, 7.0, 8.0, 9.0]     # exactly the newest 4


def test_retention_trims_old_samples():
    ts = MetricsTimeSeries(retention_s=10.0)
    ts.ingest("w1", [_gauge_row("g", 1.0)], ts=100.0)
    ts.ingest("w1", [_gauge_row("g", 2.0)], ts=120.0)   # 100.0 aged out
    s = ts.series["g"][((), "w1")]
    assert [v for _, v in s.samples] == [2.0]


def test_series_cap_drops_new_series():
    ts = MetricsTimeSeries(max_series=2)
    ts.ingest("w1", [_gauge_row("g1", 1.0), _gauge_row("g2", 1.0),
                     _gauge_row("g3", 1.0)], ts=100.0)
    assert ts.stats()["n_series"] == 2
    assert ts.stats()["dropped_series"] == 1


def test_window_edges_left_exclusive_right_inclusive():
    ts = MetricsTimeSeries()
    for t in (100.0, 102.0, 104.0):
        ts.ingest("w1", [_gauge_row("g", t)], ts=t)
    # (100, 104]: the sample AT the left edge is excluded, the right
    # edge included — two adjacent windows partition samples exactly
    q = ts.query("g", window_s=4.0, agg="series", now=104.0)
    assert [t for t, _ in q["series"][0]["samples"]] == [102.0, 104.0]
    q_prev = ts.query("g", window_s=4.0, agg="series", now=100.0)
    assert [t for t, _ in q_prev["series"][0]["samples"]] == [100.0]


def test_gauge_aggregates_across_workers():
    ts = MetricsTimeSeries()
    ts.ingest("w1", [_gauge_row("g", 2.0)], ts=100.0)
    ts.ingest("w2", [_gauge_row("g", 6.0)], ts=101.0)
    assert ts.query("g", 60, "avg", now=102.0)["value"] == 4.0
    assert ts.query("g", 60, "max", now=102.0)["value"] == 6.0
    assert ts.query("g", 60, "min", now=102.0)["value"] == 2.0
    assert ts.query("g", 60, "latest", now=102.0)["value"] == 6.0


def test_tags_filter_subset_match():
    ts = MetricsTimeSeries()
    ts.ingest("w1", [_counter_row("c", 5.0, {"zone": "a"}),
                     _counter_row("c", 7.0, {"zone": "b"})], ts=100.0)
    assert ts.query("c", 60, "sum", now=101.0)["value"] == 12.0
    assert ts.query("c", 60, "sum", tags={"zone": "a"},
                    now=101.0)["value"] == 5.0
    assert ts.query("c", 60, "sum", tags={"zone": "nope"},
                    now=101.0)["value"] is None


# -------------------------------------------------- histogram reconstruction
def test_percentile_reconstruction_against_exact():
    random.seed(7)
    ts = MetricsTimeSeries(max_samples=2000)
    h = Histogram("ttft", boundaries=[1, 2, 5, 10, 20, 50, 100, 200,
                                      500, 1000])
    vals = []
    now = 100.0
    for _ in range(40):
        for _ in range(25):
            v = random.lognormvariate(3.0, 1.0)
            vals.append(v)
            h.observe(v)
        ts.ingest("w1", [h._snapshot()], ts=now)
        now += 2.0
    arr = np.array(vals)
    bounds = h.boundaries
    for agg, q in [("p50", 50), ("p95", 95), ("p99", 99)]:
        got = ts.query("ttft", window_s=1000, agg=agg, now=now)["value"]
        exact = float(np.percentile(arr, q))
        # reconstruction is exact up to the containing bucket's width
        bucket_hi = next((b for b in bounds if b >= exact), bounds[-1])
        bucket_lo = max([0.0] + [b for b in bounds if b < exact])
        assert bucket_lo <= got <= max(bucket_hi, exact) + 1e-9, \
            (agg, got, exact)
    # mean reconstructs exactly (sum deltas / count deltas)
    assert ts.query("ttft", 1000, "avg", now=now)["value"] == \
        pytest.approx(arr.mean(), rel=1e-6)
    # frac_over within one bucket of exact
    frac = ts.query("ttft", 1000, "frac_over", threshold=50.0,
                    now=now)["value"]
    assert abs(frac - float((arr > 50).mean())) < 0.08


def test_histogram_window_isolates_old_observations():
    """Observations before the window must not leak into the windowed
    percentile: push slow requests first, fast ones later."""
    ts = MetricsTimeSeries()
    h = Histogram("lat", boundaries=[10, 100, 1000])
    now = 100.0
    for _ in range(10):
        for _ in range(20):
            h.observe(900.0)
        ts.ingest("w1", [h._snapshot()], ts=now)
        now += 2.0
    for _ in range(10):
        for _ in range(20):
            h.observe(5.0)
        ts.ingest("w1", [h._snapshot()], ts=now)
        now += 2.0
    recent = ts.query("lat", window_s=20.0, agg="p95", now=now)
    overall = ts.query("lat", window_s=1000.0, agg="p95", now=now)
    assert recent["value"] <= 10.0
    assert overall["value"] > 100.0


def test_percentile_and_fraction_helpers_edge_cases():
    assert percentile_from_buckets([10.0], [0, 0], 0.95) is None
    # all mass in the overflow bucket clamps to the top boundary
    assert percentile_from_buckets([10.0, 20.0], [0, 0, 5], 0.5) == 20.0
    # interpolation: uniform mass in (0, 10], p50 -> 5
    assert percentile_from_buckets([10.0], [10, 0], 0.5) == \
        pytest.approx(5.0)
    assert fraction_over([10.0], [10, 0], 5.0) == pytest.approx(0.5)
    assert fraction_over([10.0], [0, 10], 10.0) == 1.0


# ------------------------------------------------------------ GCS handlers
def test_gcs_report_and_query_roundtrip():
    g = GcsServer()
    h = Histogram("serve_llm_ttft_ms",
                  boundaries=[10, 50, 100, 250, 500])
    now = 1000.0
    for _ in range(20):
        for _ in range(10):
            h.observe(40.0)
        g.h_report_metrics(None, "w1", [h._snapshot()], ts=now)
        now += 2.0
    q = g.h_query_metrics(None, "serve_llm_ttft_ms", window=30,
                          agg="p95", now=now)
    assert q["value"] is not None and 10.0 <= q["value"] <= 50.0
    names = {r["name"] for r in g.h_list_metric_series(None)}
    assert "serve_llm_ttft_ms" in names
    # latest-snapshot table (the /metrics render path) still works
    assert "w1" in g.h_get_metrics(None)
    # dropping the worker clears delta baselines but keeps history
    g.h_drop_worker_metrics(None, "w1")
    q2 = g.h_query_metrics(None, "serve_llm_ttft_ms", window=30,
                           agg="p95", now=now)
    assert q2["value"] == q["value"]


def test_gcs_dump_series_gauges_for_counter_tracks():
    g = GcsServer()
    for i in range(5):
        g.h_report_metrics(None, "w1",
                           [_gauge_row("occupancy", float(i))],
                           ts=100.0 + i)
    rows = g.h_dump_metric_series(None, kinds=["gauge"], now=105.0)
    assert len(rows) == 1 and rows[0]["name"] == "occupancy"
    assert len(rows[0]["samples"]) == 5


def test_chrome_counter_tracks_from_gauge_series():
    from ray_tpu.util.tracing import task_events_to_chrome
    series = [{"name": "queue_depth", "kind": "gauge",
               "tags": {"node": "n0"}, "worker_id": "w1",
               "samples": [[10.0, 1.0], [12.0, 4.0]]}]
    out = task_events_to_chrome([], gauge_series=series)
    assert len(out) == 2
    assert all(e["ph"] == "C" and e["pid"] == "metrics" for e in out)
    assert out[0]["name"] == "queue_depth{node=n0}"
    assert out[0]["args"]["value"] == 1.0
    assert [e["ts"] for e in out] == [10.0 * 1e6, 12.0 * 1e6]
    # counter events and span events sort into one timeline
    span_rows = [{"task_id": "t", "name": "f", "state": "FINISHED",
                  "state_times": {"RUNNING": 11.0, "FINISHED": 11.5}}]
    merged = task_events_to_chrome(span_rows, gauge_series=series)
    assert [e["ts"] for e in merged] == sorted(e["ts"] for e in merged)


# ------------------------------------------------------------- SLO engine
def _fill_ttft(g, h, value, pushes, now, per_push=20):
    for _ in range(pushes):
        for _ in range(per_push):
            h.observe(value)
        g.h_report_metrics(None, "w1", [h._snapshot()], ts=now)
        now += 2.0
    return now


def test_slo_burn_rate_transitions_under_synthetic_pushes():
    from ray_tpu.serve.slo import SloConfig, SloTracker
    events.drain()
    g = GcsServer()
    h = Histogram("serve_llm_ttft_ms",
                  boundaries=[10, 50, 100, 250, 500, 1000, 2500])
    now = 1000.0
    tracker = SloTracker()
    slo = SloConfig(p95_ttft_ms=200.0, fast_window_s=30.0,
                    slow_window_s=120.0)
    clock = {"now": now}

    def query(metric, window=60.0, agg="avg", tags=None, threshold=None):
        return g.h_query_metrics(None, metric, window=window, agg=agg,
                                 tags=tags, threshold=threshold,
                                 now=clock["now"])

    # healthy: 40ms TTFT
    clock["now"] = _fill_ttft(g, h, 40.0, 30, clock["now"])
    rows = tracker.update("app", "llm", slo, query)
    assert rows[0]["objective"] == "latency"
    assert not rows[0]["violating"] and rows[0]["burn_fast"] == 0.0

    # induced load: 800ms TTFT; fast window burns first, then slow
    clock["now"] = _fill_ttft(g, h, 800.0, 5, clock["now"])
    fast_only = tracker.update("app", "llm", slo, query)[0]
    assert fast_only["burn_fast"] > 1.0
    clock["now"] = _fill_ttft(g, h, 800.0, 55, clock["now"])
    rows = tracker.update("app", "llm", slo, query)
    assert rows[0]["violating"]
    drained = [r["name"] for r in events.drain()
               if r.get("state") == "RUNNING"]
    assert "slo.violation" in drained
    # the violation is also a gauge on the metrics plane
    snap = {m["name"]: m for m in metrics_mod.registry_snapshot()}
    viol = dict((tuple(sorted(dict(k).items())), v)
                for k, v in snap["slo_violating"]["samples"])
    key = tuple(sorted({"app": "app", "deployment": "llm",
                        "objective": "latency"}.items()))
    assert viol[key] == 1.0

    # recovery: fast traffic again long enough to drain both windows
    clock["now"] = _fill_ttft(g, h, 30.0, 80, clock["now"])
    rows = tracker.update("app", "llm", slo, query)
    assert not rows[0]["violating"]
    drained = [r["name"] for r in events.drain()
               if r.get("state") == "RUNNING"]
    assert "slo.recovered" in drained
    # no repeated violation events while state is unchanged
    tracker.update("app", "llm", slo, query)
    assert "slo.violation" not in [r["name"] for r in events.drain()]


def test_slo_error_rate_objective():
    from ray_tpu.serve.slo import evaluate_slo
    g = GcsServer()
    now = 1000.0
    total = err = 0.0
    for i in range(40):
        total += 10.0
        if i >= 20:
            err += 5.0      # 50% errors in the recent half
        g.h_report_metrics(None, "w1", [
            _counter_row("serve_llm_requests_total", total),
            _counter_row("serve_llm_requests_total", err,
                         {"finish_reason": "error"}),
        ], ts=now)
        now += 2.0

    def query(metric, window=60.0, agg="avg", tags=None, threshold=None):
        return g.h_query_metrics(None, metric, window=window, agg=agg,
                                 tags=tags, threshold=threshold, now=now)

    rows = evaluate_slo({"max_error_rate": 0.05,
                         "fast_window_s": 30.0, "slow_window_s": 60.0},
                        query)
    assert rows[0]["objective"] == "error_rate"
    assert rows[0]["violating"]
    assert rows[0]["burn_fast"] > 1.0


def test_slo_no_traffic_means_no_burn():
    from ray_tpu.serve.slo import evaluate_slo

    def query(metric, window=60.0, agg="avg", tags=None, threshold=None):
        return {"value": None, "n_samples": 0}

    rows = evaluate_slo({"p95_ttft_ms": 100.0, "max_error_rate": 0.01},
                        query)
    assert len(rows) == 2
    assert all(not r["violating"] and r["burn_fast"] == 0.0 for r in rows)


# ------------------------------------------------------- pusher hardening
def test_push_interval_is_jittered_within_bounds():
    vals = {metrics_mod._push_interval() for _ in range(50)}
    assert all(1.5 <= v <= 2.5 for v in vals)
    assert len(vals) > 1        # actually jittered, not constant


def test_pusher_stop_and_resume_lifecycle():
    # force-start a pusher, stop it, confirm the thread exits, resume
    metrics_mod._ensure_pusher()
    assert metrics_mod._pusher_started
    t = next((th for th in threading.enumerate()
              if th.name == "metrics-push"), None)
    assert t is not None
    metrics_mod.stop_pusher()
    t.join(timeout=10)
    assert not t.is_alive()
    assert not metrics_mod._pusher_started
    # resume restarts only when the registry is non-empty; the suite
    # has registered metrics by now, so it restarts
    metrics_mod.resume_pusher()
    assert metrics_mod._pusher_started == bool(metrics_mod._registry)


def test_push_once_logs_first_failure_only(caplog, monkeypatch):
    import logging

    monkeypatch.setattr(metrics_mod, "_push_failures", 0)

    class _FakeRay:
        @staticmethod
        def is_initialized():
            return True

        @staticmethod
        def _get_worker():
            raise ConnectionError("gcs down")

    import sys
    monkeypatch.setitem(sys.modules, "ray_tpu", _FakeRay)
    # a metric must exist or push_once returns before contacting the GCS
    metrics_mod.Gauge("pusher_probe_gauge", "t").set(1.0)
    with caplog.at_level(logging.WARNING,
                         logger="ray_tpu.util.metrics"):
        assert metrics_mod.push_once() is False
        assert metrics_mod.push_once() is False
    warn = [r for r in caplog.records
            if "metrics push to GCS failed" in r.message]
    assert len(warn) == 1


# ------------------------------------------- daemon snapshots / prometheus
def test_daemon_snapshots_render_and_ingest():
    rows = [counter_snapshot("data_plane_bytes_in_total", 12345,
                             "bytes", {"node": "n0"}),
            gauge_snapshot("data_plane_active_conns", 3,
                           "conns", {"node": "n0"})]
    text = render_prometheus({"nm:n0": rows})
    assert 'data_plane_bytes_in_total{node="n0"} 12345.0' in text
    assert 'data_plane_active_conns{node="n0"} 3.0' in text
    ts = MetricsTimeSeries()
    ts.ingest("nm:n0", rows, ts=100.0)
    ts.ingest("nm:n0", [counter_snapshot(
        "data_plane_bytes_in_total", 22345, tags={"node": "n0"})],
        ts=102.0)
    assert ts.query("data_plane_bytes_in_total", 60, "sum",
                    now=103.0)["value"] == 22345.0
    assert ts.query("data_plane_bytes_in_total", 2, "rate",
                    now=102.0)["value"] == pytest.approx(5000.0)


# ----------------------------------------------------------- cluster tier
@pytest.fixture(scope="module")
def cluster():
    from ray_tpu.cluster_utils import Cluster
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    yield c
    c.shutdown()


@needs_cluster
def test_live_windowed_query_reconstructs_percentile(cluster):
    """Acceptance: query_metrics("serve_ttft_ms", window=30, agg="p95")
    returns a correct percentile reconstructed from histogram deltas
    pushed by a live worker process."""
    import ray_tpu
    from ray_tpu.util import state
    from ray_tpu.util.metrics import Histogram, push_once
    ray_tpu.init(address=cluster.address)
    try:
        h = Histogram("serve_ttft_ms",
                      boundaries=[10, 50, 100, 250, 500, 1000])
        # 95% of requests at ~40ms, 5% at ~400ms -> p95 in (250, 500]
        for i in range(400):
            h.observe(400.0 if i % 20 == 0 else 40.0)
        assert push_once()
        deadline = time.monotonic() + 30
        q = {}
        while time.monotonic() < deadline:
            q = state.query_metrics("serve_ttft_ms", window=30,
                                    agg="p95")
            if q.get("value") is not None:
                break
            time.sleep(0.5)
        assert q.get("value") is not None, q
        assert 100.0 < q["value"] <= 500.0, q
        exact = state.query_metrics("serve_ttft_ms", window=30,
                                    agg="avg")
        assert exact["value"] == pytest.approx(58.0, rel=0.05)
        # the new data-plane registry metrics surface too (node manager
        # pushes its own snapshots on the 2s cadence)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            names = {r["name"] for r in state.list_metric_series()}
            if "data_plane_bytes_in_total" in names:
                break
            time.sleep(0.5)
        assert "data_plane_bytes_in_total" in names
    finally:
        ray_tpu.shutdown()


@needs_cluster
def test_induced_load_produces_slo_violation_event(cluster):
    """Acceptance: a Serve deployment with an SLO, driven past its TTFT
    target, yields an slo.violation runtime event visible via
    list_runtime_events."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.util import state
    from ray_tpu.util.metrics import Histogram, push_once
    ray_tpu.init(address=cluster.address)
    try:
        @serve.deployment(slo_config={"p95_ttft_ms": 100.0,
                                      "latency_metric": "probe_ttft_ms",
                                      "fast_window_s": 10.0,
                                      "slow_window_s": 20.0})
        def noop(x):
            return x

        serve.run(noop.bind(), name="slo-probe", route_prefix=None)
        # induce load: every request blows the 100ms target
        h = Histogram("probe_ttft_ms",
                      boundaries=[10, 50, 100, 250, 500, 1000])
        deadline = time.monotonic() + 90
        seen = False
        while time.monotonic() < deadline and not seen:
            for _ in range(50):
                h.observe(400.0)
            push_once()
            rows = state.list_runtime_events(category="serve")
            seen = any(r.get("name") == "slo.violation" for r in rows)
            time.sleep(1.0)
        assert seen, "no slo.violation event reached the GCS"
        slo = serve.slo_status()
        row = slo["slo-probe"]["noop"][0]
        assert row["violating"] and row["burn_fast"] > 1.0
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def test_node_manager_observability_payload_shape():
    """The node manager's payload builder produces registry-shaped rows
    without a running node manager (the data-plane counters satellite)."""
    nm_mod = pytest.importorskip(
        "ray_tpu._private.node_manager",
        reason="node manager import needs the >=3.12 object store")
    NodeManager = nm_mod.NodeManager

    class _DS:
        bytes_in, chunks_in, active_conns = 100, 2, 1

    class _DC:
        bytes_out, chunks_out = 50, 1

    nm = NodeManager.__new__(NodeManager)      # no __init__: unit shape
    nm.node_id = "deadbeef" * 4
    nm.workers = {}
    nm.store = None
    nm._data_server = _DS()
    nm._data_client = _DC()
    nm._receiving = {}
    rows = nm._observability_metrics()
    by_name = {r["name"]: r for r in rows}
    assert by_name["data_plane_bytes_in_total"]["type"] == "counter"
    assert by_name["data_plane_bytes_in_total"]["samples"][0][1] == 100.0
    assert by_name["data_plane_active_conns"]["type"] == "gauge"
    assert by_name["data_plane_receiving"]["samples"][0][1] == 0.0
    # tags carry the node id so per-node series stay distinguishable
    assert dict(by_name["data_plane_bytes_out_total"]["samples"][0][0])[
        "node"] == nm.node_id[:12]
