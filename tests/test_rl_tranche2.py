"""RLlib tranche 2 gates: APPO, recurrent (LSTM) modules, prioritized
replay (reference: rllib/algorithms/appo/appo.py,
rllib/models/torch/recurrent_net.py,
rllib/utils/replay_buffers/prioritized_episode_buffer.py + the
tuned-example regression pattern).

Fast tier: sum-tree / buffer / unroll unit tests. Slow tier: reward-
threshold gates (APPO CartPole, APPO+LSTM on the partially-observable
StatelessCartPole, IMPALA on the built-in pixel env, DQN+prioritized)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import AlgorithmConfig, PrioritizedReplayBuffer


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


# ----------------------------------------------------------- unit tests
def test_sum_tree_prefix_find():
    from ray_tpu.rl.replay_buffer import SumTree
    t = SumTree(10)
    t.set(np.arange(10), np.arange(10, dtype=np.float64) + 1)
    assert t.total == pytest.approx(55.0)
    # cumulative bounds: [0,1) -> 0, [1,3) -> 1, ..., [45,55) -> 9
    assert t.find(np.array([0.5]))[0] == 0
    assert t.find(np.array([1.5]))[0] == 1
    assert t.find(np.array([44.9]))[0] == 8
    assert t.find(np.array([54.9]))[0] == 9
    t.set(np.array([3]), np.array([0.0]))
    assert t.total == pytest.approx(51.0)


def test_prioritized_buffer_bias_and_weights():
    buf = PrioritizedReplayBuffer(128, seed=3, alpha=1.0, beta=1.0)
    buf.add({"x": np.arange(64, dtype=np.float32)})
    # skew everything tiny except one transition
    buf.update_priorities(np.arange(64), np.full(64, 1e-6))
    buf.update_priorities(np.array([11]), np.array([50.0]))
    s = buf.sample(64)
    assert (s["indices"] == 11).mean() > 0.9
    # the over-sampled transition carries the SMALLEST weight
    others = s["weights"][s["indices"] != 11]
    if len(others):
        assert s["weights"][s["indices"] == 11].max() <= \
            others.min() + 1e-9
    # wraparound write keeps indices in range
    buf.add({"x": np.arange(100, dtype=np.float32)})
    s2 = buf.sample(32)
    assert s2["indices"].max() < 128


def test_recurrent_unroll_matches_stepwise():
    """The learner's scanned unroll must re-derive exactly the states the
    env runner saw, including mid-fragment episode resets (the
    connector state contract)."""
    import jax.numpy as jnp
    from ray_tpu.rl.rl_module import RecurrentDiscreteRLModule
    m = RecurrentDiscreteRLModule(4, 2, (32,), seed=0)
    T, B = 6, 3
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(T, B, 4)).astype(np.float32)
    dones = np.zeros((T, B), np.float32)
    dones[2, 1] = 1.0
    dones[4, 0] = 1.0
    state = m.initial_state(B)
    logits_seq, values_seq = [], []
    for t in range(T):
        state2, (lg, v) = m._step(m.params, state, jnp.asarray(obs[t]))
        logits_seq.append(np.asarray(lg))
        values_seq.append(np.asarray(v))
        mask = 1.0 - dones[t][:, None]
        state = tuple(np.asarray(s) * mask for s in state2)
    resets = np.concatenate([np.zeros((1, B), np.float32), dones[:-1]], 0)
    lg_u, v_u, _ = m._unroll(m.params, m.initial_state(B),
                             jnp.asarray(obs), jnp.asarray(resets))
    np.testing.assert_allclose(np.stack(logits_seq), np.asarray(lg_u),
                               atol=1e-5)
    np.testing.assert_allclose(np.stack(values_seq), np.asarray(v_u),
                               atol=1e-5)


def test_gaussian_recurrent_unroll_matches_stepwise():
    """Same state contract as the discrete LSTM, Gaussian head: the
    scanned unroll re-derives the runner's states across mid-fragment
    resets, and its (mean, log_std) pytree stacks time-major."""
    import jax.numpy as jnp
    from ray_tpu.rl.rl_module import RecurrentContinuousRLModule
    m = RecurrentContinuousRLModule(3, 2, (32,), seed=0)
    T, B = 6, 3
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(T, B, 3)).astype(np.float32)
    dones = np.zeros((T, B), np.float32)
    dones[2, 1] = 1.0
    dones[4, 0] = 1.0
    state = m.initial_state(B)
    means, values = [], []
    for t in range(T):
        state2, ((mean, _ls), v) = m._step(m.params, state,
                                           jnp.asarray(obs[t]))
        means.append(np.asarray(mean))
        values.append(np.asarray(v))
        mask = 1.0 - dones[t][:, None]
        state = tuple(np.asarray(s) * mask for s in state2)
    resets = np.concatenate([np.zeros((1, B), np.float32), dones[:-1]], 0)
    (mean_u, _ls_u), v_u, _ = m._unroll(m.params, m.initial_state(B),
                                        jnp.asarray(obs),
                                        jnp.asarray(resets))
    np.testing.assert_allclose(np.stack(means), np.asarray(mean_u),
                               atol=1e-5)
    np.testing.assert_allclose(np.stack(values), np.asarray(v_u),
                               atol=1e-5)


def test_gaussian_seq_logp_matches_feedforward_contract():
    """The recurrent-continuous module's (dist, actions) -> (logp,
    entropy) must agree with the feedforward ContinuousRLModule's
    logp_entropy_value semantics — both are the same diagonal
    Gaussian."""
    import jax.numpy as jnp
    from ray_tpu.rl.rl_module import (ContinuousRLModule,
                                      RecurrentContinuousRLModule,
                                      make_rl_module)
    m = make_rl_module((3,), {"type": "box", "dim": 2,
                              "low": [-1, -1], "high": [1, 1]},
                       use_lstm=True)
    assert isinstance(m, RecurrentContinuousRLModule)
    ff = ContinuousRLModule(3, 2, (16,), seed=1)
    rng = np.random.default_rng(2)
    obs = jnp.asarray(rng.normal(size=(5, 3)).astype(np.float32))
    acts = jnp.asarray(rng.normal(size=(5, 2)).astype(np.float32))
    logp_ref, ent_ref, _v = ff.logp_entropy_value(ff.params, obs, acts)
    dist, _v2 = ff.dist_values(ff.params, obs)
    logp, ent = ff.seq_logp_entropy(dist, acts)
    np.testing.assert_allclose(np.asarray(logp), np.asarray(logp_ref),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(ent), np.asarray(ent_ref),
                               atol=1e-6)
    # recurrent module shares the same static logp/entropy fn
    assert m.seq_logp_entropy is ff.seq_logp_entropy
    # clip_actions respects the action-spec bounds
    clipped = m.clip_actions(np.array([[2.0, -3.0]], np.float32))
    np.testing.assert_allclose(clipped, [[1.0, -1.0]])


def test_use_lstm_gated_to_vtrace_family(ray_start):
    """use_lstm with PPO must fail loudly at construction (the PPO
    minibatch learner is feedforward-only), and 3D obs with LSTM fail
    at module build (round-5 review findings)."""
    from ray_tpu.rl.rl_module import make_rl_module
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .training(use_lstm=True))
    with pytest.raises(ValueError, match="IMPALA or APPO"):
        config.build()   # PPO
    with pytest.raises(ValueError, match="flat observations"):
        make_rl_module((8, 8, 1), {"type": "discrete", "n": 2},
                       use_lstm=True)


def test_make_replay_buffer_factory():
    from ray_tpu.rl import ReplayBuffer, make_replay_buffer
    assert type(make_replay_buffer({"type": "uniform"}, 10)) is ReplayBuffer
    b = make_replay_buffer({"type": "prioritized", "alpha": 0.5}, 10)
    assert isinstance(b, PrioritizedReplayBuffer) and b.alpha == 0.5
    with pytest.raises(ValueError):
        make_replay_buffer({"type": "nope"}, 10)


# ------------------------------------------------------- threshold gates
def _run_algo_until(algo, stop_reward, max_iters):
    best, first = -np.inf, None
    try:
        for _ in range(max_iters):
            r = algo.train()["episode_return_mean"]
            if r is None:
                continue
            first = r if first is None else first
            best = max(best, r)
            if best >= stop_reward:
                break
    finally:
        algo.stop()
    return first, best


@pytest.mark.slow
def test_appo_cartpole_threshold(ray_start):
    """APPO gate (reference: tuned_examples/appo/cartpole_appo.py)."""
    from ray_tpu.rl import APPO
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                           rollout_fragment_length=32)
              .training(lr=1e-3, entropy_coeff=0.01, clip_param=0.3,
                        num_epochs=4, target_update_freq=2,
                        vf_loss_coeff=0.5))
    first, best = _run_algo_until(APPO(config), stop_reward=150,
                                  max_iters=90)
    assert best >= 150, (first, best)


@pytest.mark.slow
def test_appo_lstm_repeat_after_me(ray_start):
    """Recurrence gate (reference: rllib repeat_after_me_env tuned
    examples): the reward echoes the PREVIOUS observation's token, so a
    memoryless policy scores chance (~15.5 of 31) — clearing 25 requires
    the LSTM to actually carry state."""
    from ray_tpu.rl import APPO
    config = (AlgorithmConfig()
              .environment("ray_tpu/RepeatAfterMe-v0")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                           rollout_fragment_length=32)
              .training(lr=2e-3, entropy_coeff=0.003, clip_param=0.3,
                        num_epochs=4, hidden_sizes=(64,), use_lstm=True,
                        target_update_freq=2, gamma=0.9))
    first, best = _run_algo_until(APPO(config), stop_reward=25,
                                  max_iters=80)
    assert best >= 25, (first, best)


@pytest.mark.slow
def test_appo_lstm_continuous_repeat_after_me(ray_start):
    """Continuous recurrence gate: reward echoes the PREVIOUS
    observation's target value with a Box action, so a memoryless
    Gaussian policy caps at ~15.5 of 31 (action=0 vs E|target|=0.5) —
    clearing 25 requires the LSTM to carry the observation."""
    from ray_tpu.rl import APPO
    config = (AlgorithmConfig()
              .environment("ray_tpu/ContinuousRepeatAfterMe-v0")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                           rollout_fragment_length=32)
              .training(lr=2e-3, entropy_coeff=0.0, clip_param=0.3,
                        num_epochs=4, hidden_sizes=(64,), use_lstm=True,
                        target_update_freq=2, gamma=0.5))
    first, best = _run_algo_until(APPO(config), stop_reward=25,
                                  max_iters=120)
    assert best >= 25, (first, best)


@pytest.mark.slow
def test_impala_pixel_env_threshold(ray_start):
    """IMPALA conv gate on the built-in pixel env (the Atari-class
    stand-in, BASELINE 'RLlib PPO CartPole/Atari'): random play ~-0.5,
    learned policy clears +0.2."""
    from ray_tpu.rl import IMPALA
    config = (AlgorithmConfig()
              .environment("ray_tpu/GridTarget-v0")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                           rollout_fragment_length=32)
              .training(lr=1e-3, entropy_coeff=0.01, gamma=0.95,
                        num_epochs=2, vf_loss_coeff=0.5))
    first, best = _run_algo_until(IMPALA(config), stop_reward=0.2,
                                  max_iters=80)
    assert best >= 0.2, (first, best)


@pytest.mark.slow
def test_dqn_prioritized_cartpole(ray_start):
    """Prioritized-replay gate: DQN with the prioritized buffer must
    still learn CartPole (and exercises the update_priorities path on
    every grad step)."""
    from ray_tpu.rl import DQN
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(lr=1e-3, minibatch_size=64, num_epochs=4,
                        replay_buffer_config={"type": "prioritized",
                                              "alpha": 0.6, "beta": 0.4}))
    first, best = _run_algo_until(DQN(config), stop_reward=120,
                                  max_iters=50)
    assert best >= 120, (first, best)
