"""Control-plane observability: GCS hot-path handler histograms +
slow-handler spans, launch critical-path attribution, crash black boxes
(write / rotate / seal / stitch), the blackbox CLI merge, and the
metrics-pusher outage-replay fix (reference: Ray's gcs_server exports
per-handler gRPC latency, src/ray/gcs/gcs_server; event_stats.cc's
per-handler queueing stats)."""

import asyncio
import json
import os
import signal
import sys
import time

import pytest

from ray_tpu._private import blackbox, events, gcs_obs
from ray_tpu._private.gcs import GcsServer
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util.chaos import GcsRpcDelayer

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")


# ------------------------------------------------- handler instrumentation
def test_handler_histogram_accounting():
    g = GcsServer()
    wrapped = g.obs.wrap_handlers(
        {"kv_put": g.h_kv_put, "kv_get": g.h_kv_get})
    wrapped["kv_put"](None, ns="t", key=b"k", value=b"v")
    for _ in range(9):
        assert wrapped["kv_get"](None, ns="t", key=b"k") == b"v"
    st = g.obs.handlers["kv_get"]
    assert st.calls == 9
    assert sum(st.counts) == 9          # every call lands in one bucket
    assert st.inflight == 0             # fully drained
    assert st.errors == 0
    assert g.obs.inflight_total == 0
    # quantiles are monotone and bounded by the bucket ceiling
    assert 0 < st.p_quantile(0.5) <= st.p_quantile(0.99)
    # registry-shaped rows: histogram counts match, counter matches
    rows = {r["name"]: r for r in g.obs.metric_rows()}
    hist = rows["gcs_rpc_ms"]
    by_handler = {dict(s[0])["handler"]: s for s in hist["samples"]}
    assert sum(by_handler["kv_get"][1]) == 9
    assert len(by_handler["kv_get"][1]) == len(hist["boundaries"]) + 1
    calls = {dict(s[0])["handler"]: s[1]
             for s in rows["gcs_rpc_calls_total"]["samples"]}
    assert calls == {"kv_put": 1.0, "kv_get": 9.0}


def test_handler_error_accounting():
    g = GcsServer()

    def boom(conn, **kw):
        raise ValueError("nope")

    wrapped = g.obs.wrap_handlers({"boom": boom})["boom"]
    for _ in range(3):
        with pytest.raises(ValueError):
            wrapped(None)
    st = g.obs.handlers["boom"]
    assert st.calls == 3 and st.errors == 3 and st.inflight == 0
    rows = {r["name"]: r for r in g.obs.metric_rows()}
    assert rows["gcs_rpc_errors_total"]["samples"][0][1] == 3.0


def test_async_handler_observed():
    g = GcsServer()

    async def slow_echo(conn, x):
        await asyncio.sleep(0)
        return x

    wrapped = g.obs.wrap_handlers({"echo": slow_echo})["echo"]
    out = asyncio.get_event_loop_policy().new_event_loop()
    try:
        assert out.run_until_complete(wrapped(None, x=42)) == 42
    finally:
        out.close()
    st = g.obs.handlers["echo"]
    assert st.calls == 1 and st.inflight == 0


def test_streaming_handlers_not_wrapped():
    g = GcsServer()

    def stream(conn, **kw):
        pass

    stream.streaming = True
    wrapped = g.obs.wrap_handlers({"s": stream})
    assert wrapped["s"] is stream       # different calling convention


def test_slow_handler_emits_span_via_delayer(monkeypatch):
    monkeypatch.setenv("RAY_TPU_GCS_SLOW_RPC_MS", "20")
    g = GcsServer()
    g.h_kv_put(None, ns="t", key=b"k", value=b"v")
    delayer = GcsRpcDelayer("kv_get", 30.0)
    delayer.arm_local()
    try:
        assert gcs_obs.delay_for("kv_get") == 30.0
        wrapped = g.obs.wrap_handlers({"kv_get": g.h_kv_get})["kv_get"]
        loop = asyncio.get_event_loop_policy().new_event_loop()
        try:
            assert loop.run_until_complete(
                wrapped(None, ns="t", key=b"k")) == b"v"
        finally:
            loop.close()
    finally:
        GcsRpcDelayer.disarm_local()
    st = g.obs.handlers["kv_get"]
    assert st.slow == 1
    spans = g.h_list_task_events(None, kind="runtime_event",
                                 category="gcs")
    assert len(spans) == 1
    row = spans[0]
    assert row["name"] == "gcs.rpc"
    assert row["attrs"]["handler"] == "kv_get"
    assert row["attrs"]["ms"] >= 20.0
    # the delayer's env() composes with a prior spec like the other
    # chaos killers
    env = delayer.env(base={gcs_obs.DELAY_ENV: "gcs_rpc=kv_put:5"})
    assert env[gcs_obs.DELAY_ENV] == "gcs_rpc=kv_put:5,gcs_rpc=kv_get:30.0"


def test_sub_threshold_sampling(monkeypatch):
    monkeypatch.setenv("RAY_TPU_GCS_SLOW_RPC_MS", "1000")
    monkeypatch.setenv("RAY_TPU_GCS_RPC_SAMPLE_N", "5")
    g = GcsServer()
    g.h_kv_put(None, ns="t", key=b"k", value=b"v")
    wrapped = g.obs.wrap_handlers({"kv_get": g.h_kv_get})["kv_get"]
    for _ in range(10):
        wrapped(None, ns="t", key=b"k")
    spans = g.h_list_task_events(None, kind="runtime_event",
                                 category="gcs")
    # 1-in-5 sampling over 10 fast calls -> exactly 2 breadcrumbs
    assert len(spans) == 2
    assert g.obs.handlers["kv_get"].slow == 0


# ------------------------------------------------------ launch attribution
def test_launch_span_chain():
    g = GcsServer()
    ent = g._launch_begin("a" * 32, {"name": "MyActor"})
    assert ent is not None and ("a" * 32) in g.launches
    root = ent["root_span_id"]
    t0 = time.time()
    g._launch_span_row(ent, "launch.placement", t0 - 0.01, t0,
                       ent["root_span_id"], node="n1", strategy="DEFAULT")
    loop = asyncio.get_event_loop_policy().new_event_loop()
    try:
        loop.run_until_complete(g.h_launch_phase(
            None, actor_id="a" * 32, phase="worker_obtain"))
    finally:
        loop.close()
    assert g.launches["a" * 32]["phase"] == "worker_obtain"
    g._launch_finish("a" * 32, ok=True)
    assert not g.launches and len(g._launch_done) == 1
    rows = g.h_list_task_events(None, kind="runtime_event",
                                category="launch")
    by_name = {r["name"]: r for r in rows}
    assert by_name["actor.launch"]["span_id"] == root
    assert by_name["actor.launch"]["attrs"]["ok"] is True
    assert by_name["actor.launch"]["attrs"]["total_ms"] >= 0
    child = by_name["launch.placement"]
    assert child["parent_span_id"] == root
    assert child["trace_id"] == by_name["actor.launch"]["trace_id"]
    # stats pane view retires the launch into recent_launch_ms
    stats = g.h_control_plane_stats(None)
    assert stats["launches"] == []
    assert stats["launches_done"] == 1
    assert len(stats["recent_launch_ms"]) == 1


def test_launch_finish_failure_row():
    g = GcsServer()
    g._launch_begin("b" * 32, {"name": "Dead"})
    g._launch_finish("b" * 32, ok=False, error="placement group not ready")
    rows = g.h_list_task_events(None, kind="runtime_event",
                                category="launch")
    root = [r for r in rows if r["name"] == "actor.launch"][0]
    assert root["attrs"]["ok"] is False
    assert "placement" in root["attrs"]["error"]


def test_launch_trace_disabled(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LAUNCH_TRACE_ENABLED", "0")
    g = GcsServer()
    assert g._launch_begin("c" * 32, {"name": "X"}) is None
    assert not g.launches
    g._launch_finish("c" * 32, ok=True)     # no entry -> no row, no crash
    assert g.h_list_task_events(None, kind="runtime_event",
                                category="launch") == []


# ----------------------------------------------------------- black boxes
def test_blackbox_write_and_seal(tmp_path):
    path = str(tmp_path / "worker-1.bbox.ndjson")
    box = blackbox.BlackBox(path, process="worker-1", node_id="n1")
    box.record("marker", event="startup")
    box.on_event({"name": "launch.callable_init", "category": "launch",
                  "kind": "span", "start": 1.0, "end": 2.0,
                  "attrs": {"actor_id": "a1"}})
    box.seal("sigterm")
    box.seal("clean_exit")                  # idempotent: first wins
    recs = blackbox.read_box(path)
    kinds = [r["kind"] for r in recs]
    assert kinds[0] == "header"
    assert kinds[-1] == "seal"
    assert recs[-1]["reason"] == "sigterm"
    ev = [r for r in recs if r["kind"] == "event"][0]
    assert ev["name"] == "launch.callable_init"
    assert ev["attrs"] == {"actor_id": "a1"}
    seqs = [r["seq"] for r in recs]
    assert seqs == sorted(seqs)             # monotone within a box


def test_blackbox_rotation_bounded(tmp_path):
    path = str(tmp_path / "nm-1.bbox.ndjson")
    box = blackbox.BlackBox(path, max_bytes=8192, process="nm-1")
    for i in range(500):
        box.record("marker", event="tick", i=i, pad="x" * 64)
    live = os.path.getsize(path)
    rotated = os.path.getsize(path + ".1")
    assert live + rotated <= 8192 + 256     # bounded (one line of slack)
    assert rotated > 0                      # rotation actually happened
    recs = blackbox.read_box(path)
    ticks = [r["i"] for r in recs if r.get("event") == "tick"]
    assert ticks[-1] == 499                 # newest history survives
    assert ticks == sorted(ticks)
    # the fresh segment re-headers so a reader of the live file alone
    # still learns the process identity
    with open(path) as f:
        first_live = json.loads(f.readline())
    assert first_live["kind"] == "header" and first_live["rotated"]


def test_blackbox_torn_line_skipped(tmp_path):
    path = str(tmp_path / "gcs-1.bbox.ndjson")
    box = blackbox.BlackBox(path, process="gcs")
    box.record("marker", event="ok")
    with open(path, "a") as f:
        f.write('{"kind": "marker", "event": "torn-by-sig')
    recs = blackbox.read_box(path)
    assert [r for r in recs if r.get("event") == "ok"]
    assert all(r.get("event") != "torn-by-sig" for r in recs)


def test_blackbox_configure_taps_events(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_BLACKBOX_METRICS_INTERVAL_S", "0")
    blackbox.reset()
    events.drain()
    try:
        # a record made BEFORE configure must be backfilled
        events.record_instant("pre.existing", category="test")
        box = blackbox.configure(str(tmp_path), "worker-abc",
                                 node_id="n1", worker_id="w1")
        assert box is not None
        events.record_complete("launch.shell_attach", 1.0, 2.0,
                               category="launch")
        box.seal("clean_exit")
        recs = blackbox.read_box(box.path)
        names = [r.get("name") for r in recs if r["kind"] == "event"]
        assert "pre.existing" in names
        assert "launch.shell_attach" in names
        # the tap mirrors without consuming: the ring still drains
        assert any(r["name"] == "launch.shell_attach"
                   for r in events.peek())
    finally:
        blackbox.reset()
        events.drain()


def test_blackbox_disabled(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_BLACKBOX_ENABLED", "0")
    blackbox.reset()
    try:
        assert blackbox.configure(str(tmp_path), "worker-x") is None
        blackbox.record("marker", event="dropped")   # no-op, no crash
        assert blackbox.count_boxes(str(tmp_path)) == 0
    finally:
        blackbox.reset()


def test_stitch_ordering_and_clock_skew(tmp_path):
    # box A's clock runs 2s AHEAD of the GCS (offset = local - gcs = +2):
    # its raw timestamps must shift BACK to interleave correctly
    a = blackbox.BlackBox(str(tmp_path / "nm-a.bbox.ndjson"),
                          process="nm-a", clock_offset_s=2.0)
    b = blackbox.BlackBox(str(tmp_path / "nm-b.bbox.ndjson"),
                          process="nm-b", clock_offset_s=0.0)
    t = 1000.0
    a.record("marker", event="a1", ts=t + 2.5)   # gcs time t+0.5
    b.record("marker", event="b1", ts=t + 0.1)
    b.record("marker", event="b2", ts=t + 1.0)
    a.seal("clean_exit")
    # b never seals: died hard
    merged = blackbox.stitch(blackbox.scan_boxes(str(tmp_path)))
    order = [m["rec"]["event"] for m in merged["records"]
             if m["rec"].get("kind") == "marker"
             and m["rec"].get("event", "").startswith(("a", "b"))]
    assert order == ["b1", "a1", "b2"]
    by_proc = {x["process"]: x for x in merged["boxes"]}
    assert by_proc["nm-a"]["sealed"]
    assert by_proc["nm-a"]["seal_reason"] == "clean_exit"
    assert not by_proc["nm-b"]["sealed"]
    assert by_proc["nm-b"]["seal_reason"] == "none (died hard)"
    # implausible-skew clamp: a's offset exceeds the tolerance, so its
    # raw timestamps stand and a1 sorts last
    clamped = blackbox.stitch(blackbox.scan_boxes(str(tmp_path)),
                              max_skew_s=1.0)
    order = [m["rec"]["event"] for m in clamped["records"]
             if m["rec"].get("kind") == "marker"
             and m["rec"].get("event", "").startswith(("a", "b"))]
    assert order == ["b1", "b2", "a1"]


def test_blackbox_cli_merge(tmp_path, capsys):
    from ray_tpu.scripts import cli
    box = blackbox.BlackBox(str(tmp_path / "gcs-7.bbox.ndjson"),
                            process="gcs")
    box.record("marker", event="startup")
    box.seal("signal_15")

    class Args:
        paths = [str(tmp_path)]
        json = True
        limit = 0
        max_skew = 0.0

    cli.cmd_blackbox(Args())
    out = json.loads(capsys.readouterr().out)
    assert out["boxes"][0]["seal_reason"] == "signal_15"
    assert [r["rec"]["kind"] for r in out["records"]][-1] == "seal"

    Args.json = False
    cli.cmd_blackbox(Args())
    text = capsys.readouterr().out
    assert "SEALED: signal_15" in text and "gcs" in text


# --------------------------------------- metrics pusher outage buffering
class _FakeWorker:
    def __init__(self, fail: bool):
        self.fail = fail
        self.calls = []

        class Core:
            worker_id = "w-test"
            node_id = "n-test"
        self.core = Core()

    def gcs_call(self, method, **kw):
        if self.fail:
            raise ConnectionError("gcs restarting")
        self.calls.append((method, kw))


@pytest.fixture
def _isolated_registry():
    saved = dict(metrics_mod._registry)
    saved_failed = metrics_mod._failed_push
    metrics_mod._registry.clear()
    metrics_mod._failed_push = None
    yield
    metrics_mod._registry.clear()
    metrics_mod._registry.update(saved)
    metrics_mod._failed_push = saved_failed


def test_push_failure_buffers_and_replays(monkeypatch,
                                          _isolated_registry):
    import ray_tpu
    c = metrics_mod.Counter("cp_test_pushes_total", "test")
    c.inc(5)
    fake = _FakeWorker(fail=True)
    monkeypatch.setattr(ray_tpu, "is_initialized", lambda: True)
    monkeypatch.setattr(ray_tpu, "_get_worker", lambda: fake)
    assert metrics_mod.push_once() is False
    assert metrics_mod._failed_push is not None
    buf_ts, buf_payload = metrics_mod._failed_push
    assert any(r["name"] == "cp_test_pushes_total" for r in buf_payload)

    c.inc(3)
    fake.fail = False
    assert metrics_mod.push_once() is True
    assert metrics_mod._failed_push is None
    assert len(fake.calls) == 2
    # replay first, at its ORIGINAL capture time, then the live push
    replay_kw = fake.calls[0][1]
    assert replay_kw["ts"] == buf_ts
    assert replay_kw["metrics"] is buf_payload
    live_kw = fake.calls[1][1]
    assert "ts" not in live_kw
    # a second consecutive success must not re-send the old snapshot
    metrics_mod.push_once()
    assert len(fake.calls) == 3


def test_replay_reestablishes_delta_baseline(monkeypatch,
                                             _isolated_registry):
    """The reason the buffer exists: a GCS restart wipes the TS delta
    baselines, and without the replay the first post-restart push lands
    the whole cumulative history inside the current window."""
    import ray_tpu
    c = metrics_mod.Counter("cp_test_delta_total", "test")
    c.inc(100)
    fake = _FakeWorker(fail=True)
    monkeypatch.setattr(ray_tpu, "is_initialized", lambda: True)
    monkeypatch.setattr(ray_tpu, "_get_worker", lambda: fake)
    metrics_mod.push_once()                       # buffered
    # age the buffered snapshot past the query window (the outage)
    old_ts, payload = metrics_mod._failed_push
    metrics_mod._failed_push = (old_ts - 120.0, payload)
    c.inc(10)
    fake.fail = False
    assert metrics_mod.push_once() is True

    # replay both pushes into a FRESH GCS (the restart) exactly as the
    # wire saw them
    g = GcsServer()
    for method, kw in fake.calls:
        g.h_report_metrics(None, **kw)
    got = g.h_query_metrics(None, name="cp_test_delta_total",
                            window=60.0, agg="sum")
    # only the post-outage activity lands in the window — not the
    # 100-unit pre-outage history
    assert got["value"] == pytest.approx(10.0)


# ------------------------------------------------------- cluster tier
@needs_cluster
def test_nm_sigkill_mid_launch_leaves_black_box(tmp_path, monkeypatch):
    """SIGKILL a node manager while an actor launch is in flight on it;
    its black box (continuously appended — nothing runs at death) must
    survive on disk and stitch into the cross-node timeline as a
    died-hard box that still carries its final events."""
    monkeypatch.setenv("RAY_TPU_BLACKBOX_DIR", str(tmp_path))
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 2, "resources": {"head": 1}})
    victim = c.add_node(num_cpus=2, resources={"victim": 1.0})
    try:
        ray_tpu.init(address=c.address)
        c.wait_for_nodes()

        @ray_tpu.remote(resources={"victim": 0.1})
        class Slow:
            def __init__(self):
                time.sleep(30)      # still initializing when killed

            def ping(self):
                return 1

        _ = Slow.remote()           # launch lands on the victim node
        deadline = time.monotonic() + 30
        nm_tag = f"nm-{victim.node_id[:12]}"
        while time.monotonic() < deadline:
            if any(nm_tag in p for p in blackbox.scan_boxes(
                    str(tmp_path))):
                break
            time.sleep(0.2)
        os.kill(victim._local.nm_handle.proc.pid, signal.SIGKILL)
        time.sleep(1.0)
        paths = blackbox.scan_boxes(str(tmp_path))
        nm_boxes = [p for p in paths if nm_tag in p]
        assert nm_boxes, f"no black box for {nm_tag} in {paths}"
        merged = blackbox.stitch(paths)
        nm = [b for b in merged["boxes"] if b["process"] == nm_tag][0]
        assert not nm["sealed"]     # SIGKILL: nothing ran at death
        assert nm["records"] > 0
        nm_recs = [m for m in merged["records"]
                   if m["process"] == nm_tag]
        assert any(m["rec"].get("event") == "startup" for m in nm_recs)
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        c.shutdown()
