"""Tests for the native shared-memory object store.

Mirrors the coverage themes of the reference's plasma tests
(reference: src/ray/object_manager/plasma/ test suite): create/seal/get,
zero-copy reads, eviction under pressure, deferred delete, multi-process
visibility.
"""

import multiprocessing
import os

import numpy as np
import pytest

from ray_tpu._private.object_store import ObjectStoreClient


@pytest.fixture()
def store(tmp_path):
    path = "/dev/shm/raytpu_test_%d" % os.getpid()
    s = ObjectStoreClient(path, create=True, size=64 * 1024 * 1024)
    yield s
    s.close()
    os.unlink(path)


def oid(n: int) -> bytes:
    return n.to_bytes(20, "big")


def test_put_get_roundtrip(store):
    payload = b"hello world" * 1000
    assert store.put_bytes(oid(1), payload, metadata=b"meta")
    buf = store.get(oid(1))
    assert bytes(buf.data) == payload
    assert buf.metadata == b"meta"
    assert store.contains(oid(1))
    assert store.get(oid(2)) is None


def test_zero_copy_numpy(store):
    arr = np.arange(100000, dtype=np.float32)
    store.put_bytes(oid(3), arr.tobytes())
    buf = store.get(oid(3))
    out = np.frombuffer(buf.data, dtype=np.float32)
    np.testing.assert_array_equal(out, arr)


def test_duplicate_create(store):
    assert store.put_bytes(oid(4), b"x")
    assert not store.put_bytes(oid(4), b"y")


def test_create_write_seal(store):
    data, meta = store.create(oid(5), 8, 2)
    data[:] = b"abcdefgh"
    meta[:] = b"mm"
    # not visible until sealed
    assert not store.contains(oid(5))
    store.seal(oid(5))
    buf = store.get(oid(5))
    assert bytes(buf.data) == b"abcdefgh"
    assert buf.metadata == b"mm"


def test_delete_and_deferred_delete(store):
    store.put_bytes(oid(6), b"z" * 100)
    buf = store.get(oid(6))  # pinned
    store.delete(oid(6))
    # still readable through existing pin's view
    assert bytes(buf.data) == b"z" * 100
    buf.close()
    assert not store.contains(oid(6))


def test_lru_eviction(store):
    # Fill most of the 64 MiB arena with 8 MiB objects, then allocate more:
    # oldest unpinned objects must be evicted.
    blob = b"\x01" * (8 * 1024 * 1024)
    for i in range(10, 20):
        store.put_bytes(oid(i), blob)
    stats = store.stats()
    assert stats["num_evictions"] >= 1
    # most recent object is resident
    assert store.contains(oid(19))


def test_pinned_objects_not_evicted(store):
    blob = b"\x02" * (8 * 1024 * 1024)
    store.put_bytes(oid(20), blob)
    pin = store.get(oid(20))
    for i in range(21, 30):
        store.put_bytes(oid(i), blob)
    assert store.contains(oid(20))
    assert bytes(pin.data[:4]) == b"\x02\x02\x02\x02"
    pin.close()


def test_abort(store):
    store.create(oid(30), 1024)
    store.abort(oid(30))
    assert not store.contains(oid(30))
    # space reusable
    assert store.put_bytes(oid(30), b"done")


def _child_read(path, key, expected):
    c = ObjectStoreClient(path)
    buf = c.get(key)
    assert buf is not None and bytes(buf.data) == expected
    c.put_bytes(b"\x99" * 20, b"from-child")
    c.close()


def test_multiprocess_visibility(store):
    store.put_bytes(oid(40), b"shared-payload")
    ctx = multiprocessing.get_context("fork")
    p = ctx.Process(target=_child_read, args=(store.path, oid(40), b"shared-payload"))
    p.start()
    p.join(30)
    assert p.exitcode == 0
    buf = store.get(b"\x99" * 20)
    assert bytes(buf.data) == b"from-child"


def test_stats(store):
    store.put_bytes(oid(50), b"x" * 1000)
    st = store.stats()
    assert st["num_objects"] >= 1
    assert st["bytes_in_use"] >= 1000
    assert st["capacity"] > 0


def test_many_small_objects(store):
    for i in range(2000):
        store.put_bytes(oid(1000 + i), i.to_bytes(4, "big"))
    for i in range(0, 2000, 97):
        buf = store.get(oid(1000 + i))
        assert int.from_bytes(bytes(buf.data), "big") == i
