"""Tests for the native shared-memory object store.

Mirrors the coverage themes of the reference's plasma tests
(reference: src/ray/object_manager/plasma/ test suite): create/seal/get,
zero-copy reads, eviction under pressure, deferred delete, multi-process
visibility — plus the lock-striped arena paths: multi-process put/get
contention across stripes, round-robin fallback off a full home stripe,
and robust-mutex repair after a client is SIGKILLed mid-``rt_create``.
"""

import multiprocessing
import os
import time

import numpy as np
import pytest

# the store's zero-copy pin lifetime rides the PEP 688 __buffer__
# protocol — the whole module is 3.12-gated through this import
_object_store = pytest.importorskip(
    "ray_tpu._private.object_store", reason="object store requires 3.12")
ObjectStoreClient = _object_store.ObjectStoreClient

from ray_tpu.util.chaos import ShmCreateKiller  # noqa: E402


@pytest.fixture()
def store(tmp_path):
    path = "/dev/shm/raytpu_test_%d" % os.getpid()
    s = ObjectStoreClient(path, create=True, size=64 * 1024 * 1024)
    yield s
    s.close()
    os.unlink(path)


def oid(n: int) -> bytes:
    return n.to_bytes(20, "big")


def test_put_get_roundtrip(store):
    payload = b"hello world" * 1000
    assert store.put_bytes(oid(1), payload, metadata=b"meta")
    buf = store.get(oid(1))
    assert bytes(buf.data) == payload
    assert buf.metadata == b"meta"
    assert store.contains(oid(1))
    assert store.get(oid(2)) is None


def test_zero_copy_numpy(store):
    arr = np.arange(100000, dtype=np.float32)
    store.put_bytes(oid(3), arr.tobytes())
    buf = store.get(oid(3))
    out = np.frombuffer(buf.data, dtype=np.float32)
    np.testing.assert_array_equal(out, arr)


def test_duplicate_create(store):
    assert store.put_bytes(oid(4), b"x")
    assert not store.put_bytes(oid(4), b"y")


def test_create_write_seal(store):
    data, meta = store.create(oid(5), 8, 2)
    data[:] = b"abcdefgh"
    meta[:] = b"mm"
    # not visible until sealed
    assert not store.contains(oid(5))
    store.seal(oid(5))
    buf = store.get(oid(5))
    assert bytes(buf.data) == b"abcdefgh"
    assert buf.metadata == b"mm"


def test_delete_and_deferred_delete(store):
    store.put_bytes(oid(6), b"z" * 100)
    buf = store.get(oid(6))  # pinned
    store.delete(oid(6))
    # still readable through existing pin's view
    assert bytes(buf.data) == b"z" * 100
    buf.close()
    assert not store.contains(oid(6))


def test_lru_eviction(store):
    # Fill most of the 64 MiB arena with 8 MiB objects, then allocate more:
    # oldest unpinned objects must be evicted.
    blob = b"\x01" * (8 * 1024 * 1024)
    for i in range(10, 20):
        store.put_bytes(oid(i), blob)
    stats = store.stats()
    assert stats["num_evictions"] >= 1
    # most recent object is resident
    assert store.contains(oid(19))


def test_pinned_objects_not_evicted(store):
    blob = b"\x02" * (8 * 1024 * 1024)
    store.put_bytes(oid(20), blob)
    pin = store.get(oid(20))
    for i in range(21, 30):
        store.put_bytes(oid(i), blob)
    assert store.contains(oid(20))
    assert bytes(pin.data[:4]) == b"\x02\x02\x02\x02"
    pin.close()


def test_abort(store):
    store.create(oid(30), 1024)
    store.abort(oid(30))
    assert not store.contains(oid(30))
    # space reusable
    assert store.put_bytes(oid(30), b"done")


def _child_read(path, key, expected):
    c = ObjectStoreClient(path)
    buf = c.get(key)
    assert buf is not None and bytes(buf.data) == expected
    c.put_bytes(b"\x99" * 20, b"from-child")
    c.close()


def test_multiprocess_visibility(store):
    store.put_bytes(oid(40), b"shared-payload")
    ctx = multiprocessing.get_context("fork")
    p = ctx.Process(target=_child_read, args=(store.path, oid(40), b"shared-payload"))
    p.start()
    p.join(30)
    assert p.exitcode == 0
    buf = store.get(b"\x99" * 20)
    assert bytes(buf.data) == b"from-child"


def test_stats(store):
    store.put_bytes(oid(50), b"x" * 1000)
    st = store.stats()
    assert st["num_objects"] >= 1
    assert st["bytes_in_use"] >= 1000
    assert st["capacity"] > 0


def test_many_small_objects(store):
    for i in range(2000):
        store.put_bytes(oid(1000 + i), i.to_bytes(4, "big"))
    for i in range(0, 2000, 97):
        buf = store.get(oid(1000 + i))
        assert int.from_bytes(bytes(buf.data), "big") == i


# ---------------------------------------------------- lock-striped arena


@pytest.fixture()
def striped_store():
    path = "/dev/shm/raytpu_test_striped_%d" % os.getpid()
    s = ObjectStoreClient(path, create=True, size=64 * 1024 * 1024,
                          stripes=4)
    yield s
    s.close()
    os.unlink(path)


def _home_stripe(oid_bytes: bytes, nstripes: int) -> int:
    """Python mirror of hash_id/stripe_of in shm_store.cpp (test-only:
    used to construct deterministic stripe collisions; drift between the
    two shows up as test_stripe_fallback failing to provoke one)."""
    mask = (1 << 64) - 1
    a = int.from_bytes(oid_bytes[0:8], "little")
    b = int.from_bytes(oid_bytes[8:16], "little")
    c = int.from_bytes(oid_bytes[16:20], "little")
    h = a ^ ((b * 0x9E3779B97F4A7C15) & mask) ^ ((c << 17) & mask)
    h ^= h >> 33
    h = (h * 0xFF51AFD7ED558CCD) & mask
    h ^= h >> 33
    return (h >> 40) % nstripes


def test_striped_roundtrip_and_stats(striped_store):
    s = striped_store
    assert s.num_stripes() == 4
    for i in range(200):
        assert s.put_bytes(oid(5000 + i), i.to_bytes(8, "big"))
    for i in range(200):
        buf = s.get(oid(5000 + i))
        assert int.from_bytes(bytes(buf.data), "big") == i
    st = s.stats()
    assert st["num_stripes"] == 4
    assert st["num_objects"] >= 200
    assert st["poisoned"] == 0
    # per-stripe accounting sums to the aggregate
    per = [s.stripe_stats(i) for i in range(4)]
    assert sum(p["bytes_in_use"] for p in per) == st["bytes_in_use"]
    assert sum(p["capacity"] for p in per) == st["capacity"]
    # the id hash actually spreads objects over several stripes
    assert sum(1 for p in per if p["num_objects"] > 0) >= 2


def test_stripe_fallback_when_home_full(striped_store):
    s = striped_store
    # two ids with the SAME home stripe; each object fills >half a
    # 16 MiB stripe, so the second create cannot fit at home and must
    # re-home round-robin — while the first stays pinned (unevictable).
    ids = []
    n = 0
    while len(ids) < 2:
        cand = oid(42000 + n)
        n += 1
        if not ids or _home_stripe(cand, 4) == _home_stripe(ids[0], 4):
            ids.append(cand)
    big = (64 * 1024 * 1024 // 4) * 6 // 10
    pins = []
    for i in ids:
        assert s.put_bytes(i, b"\x11" * big)
        pins.append(s.get(i))
    assert s.stats()["create_fallbacks"] >= 1
    for i, pin in zip(ids, pins):
        assert s.contains(i)
        pin.close()
        s.delete(i)


def _contend_worker(path, duration, seed, q):
    c = ObjectStoreClient(path)
    payload = b"\xcd" * (4 * 1024 * 1024)
    n, errors = 0, 0
    t0 = time.perf_counter()
    i = 0
    while time.perf_counter() - t0 < duration:
        key = (seed * 1_000_000 + i).to_bytes(20, "big")
        i += 1
        try:
            if not c.put_bytes(key, payload):
                errors += 1
            buf = c.get(key)
            if buf is None:
                errors += 1
            else:
                buf.close()
            c.delete(key)
            n += 1
        except Exception:
            errors += 1
    dt = time.perf_counter() - t0
    q.put((n * len(payload) / dt, errors))
    c.close()


def test_multiprocess_put_contention():
    """ISSUE 6 acceptance: N put/get clients against one striped arena
    must aggregate at least the single-client rate (on a multi-core box;
    a 1-core host can only time-slice) with zero seal/create errors."""
    path = "/dev/shm/raytpu_test_contend_%d" % os.getpid()
    s = ObjectStoreClient(path, create=True, size=256 * 1024 * 1024,
                          stripes=4)
    ctx = multiprocessing.get_context("fork")
    try:
        duration = 0.8

        def run(n_clients, seed0):
            q = ctx.Queue()
            procs = [ctx.Process(target=_contend_worker,
                                 args=(path, duration, seed0 + k, q))
                     for k in range(n_clients)]
            for p in procs:
                p.start()
            results = [q.get(timeout=60) for _ in procs]
            for p in procs:
                p.join(30)
                assert p.exitcode == 0
            return results

        single = run(1, seed0=10)
        multi = run(4, seed0=20)
        single_rate = single[0][0]
        agg = sum(r for r, _ in multi)
        errors = single[0][1] + sum(e for _, e in multi)
        assert errors == 0, f"{errors} put/get client errors"
        min_ratio = 1.0 if (os.cpu_count() or 1) >= 2 else 0.5
        assert agg >= single_rate * min_ratio, \
            (agg, single_rate, [r for r, _ in multi])
        assert s.stats()["poisoned"] == 0
    finally:
        s.close()
        os.unlink(path)


def _chaos_put_loop(path, spec):
    # arm BEFORE the first native create: the spec is parsed once per
    # process (spawn context => fresh interpreter => fresh parse)
    os.environ[ShmCreateKiller.SPEC_ENV] = spec
    from ray_tpu._private.object_store import ObjectStoreClient as Client
    c = Client(path)
    for i in range(1000):
        try:
            c.put_bytes((7_000_000 + i).to_bytes(20, "big"), b"\xab" * 4096)
        except Exception:
            pass
    os._exit(3)  # survived 1000 puts: the injection never fired


def test_kill_mid_create_repairs_stripe(striped_store):
    """Robust-mutex chaos: a client SIGKILLed inside rt_create while
    holding a stripe mutex must not take the store down — survivors hit
    EOWNERDEAD, repair the poisoned stripe, and keep serving puts."""
    s = striped_store
    for i in range(8):
        assert s.put_bytes(oid(60000 + i), b"\x22" * 1024)
    killer = ShmCreateKiller(nth_create=3)
    ctx = multiprocessing.get_context("spawn")
    victim = ctx.Process(target=_chaos_put_loop,
                         args=(s.path, killer.spec()))
    victim.start()
    killer.assert_killed(victim)
    # stats() itself walks every stripe (seqlock -> locked fallback on the
    # stuck one), so the first poll performs the EOWNERDEAD repair
    st = s.stats()
    assert st["stripe_repairs"] >= 1
    assert st["poisoned"] == 0
    # and the arena keeps serving puts on every stripe
    for i in range(64):
        assert s.put_bytes(oid(70000 + i), b"\x33" * 2048)
        buf = s.get(oid(70000 + i))
        assert bytes(buf.data) == b"\x33" * 2048
        buf.close()
    assert s.stats()["poisoned"] == 0


# ------------------------------------------------- spanning allocation
# Objects larger than one stripe (64 MiB arena / 4 stripes = 16 MiB)
# route to the spanning path: contiguous whole stripes, one descriptor,
# whole-span eviction/repair. ISSUE 11 acceptance: put/get/pin/evict/
# crash-repair above one stripe size.

from ray_tpu.util.chaos import ShmSpanCreateKiller  # noqa: E402


def test_spanning_put_get_roundtrip(striped_store):
    s = striped_store
    blob = bytes(range(256)) * (20 * 1024 * 1024 // 256)   # 20 MiB
    assert len(blob) > s.max_alloc_bytes()
    assert s.put_bytes(oid(80001), blob, metadata=b"span-meta")
    assert s.is_span(oid(80001))
    assert s.contains(oid(80001))
    buf = s.get(oid(80001))
    assert bytes(buf.data) == blob
    assert buf.metadata == b"span-meta"
    st = s.stats()
    assert st["num_spans"] == 1
    assert st["span_creates"] >= 1
    sp = s.span_stats()
    assert sp["live_spans"] == 1
    assert sp["stripes_claimed"] == 2      # 20 MiB over 16 MiB stripes
    assert sp["span_bytes"] == len(blob) + len(b"span-meta")
    buf.close()
    s.delete(oid(80001))
    assert not s.contains(oid(80001))
    assert s.span_stats()["stripes_claimed"] == 0   # whole span returned


def test_spanning_zero_copy_numpy(striped_store):
    s = striped_store
    arr = np.arange(5 * 1024 * 1024, dtype=np.float32)     # 20 MiB
    s.put_bytes(oid(80002), arr.tobytes())
    buf = s.get(oid(80002))
    out = np.frombuffer(buf.data, dtype=np.float32)
    np.testing.assert_array_equal(out, arr)
    buf.close()


def test_spanning_pin_survives_lru_pressure(striped_store):
    """LRU pressure never half-frees a span: normal creates evict
    AROUND a pinned span; the span's bytes stay intact throughout."""
    s = striped_store
    blob = b"\x5a" * (20 * 1024 * 1024)
    assert s.put_bytes(oid(80010), blob)
    pin = s.get(oid(80010))
    # hammer well past the remaining two stripes' capacity
    for i in range(24):
        s.put_bytes(oid(80100 + i), b"\x11" * (4 * 1024 * 1024))
    assert s.contains(oid(80010))
    sp = s.span_stats()
    assert sp["live_spans"] == 1 and sp["stripes_claimed"] == 2
    assert bytes(pin.data[:8]) == b"\x5a" * 8
    assert bytes(pin.data[-8:]) == b"\x5a" * 8
    pin.close()
    s.delete(oid(80010))


def test_spanning_eviction_is_atomic(striped_store):
    """An unpinned sealed span is reclaimed WHOLE under pressure, and
    its stripes rejoin the normal allocator."""
    s = striped_store
    assert s.put_bytes(oid(80020), b"\x66" * (20 * 1024 * 1024))
    assert s.is_span(oid(80020))
    freed = s.evict(64 * 1024 * 1024)
    assert freed >= 20 * 1024 * 1024
    assert not s.contains(oid(80020))
    sp = s.span_stats()
    assert sp["live_spans"] == 0 and sp["stripes_claimed"] == 0
    assert sp["span_evictions"] >= 1
    assert s.stats()["num_spans"] == 0
    # reclaimed stripes serve normal puts again
    for i in range(8):
        assert s.put_bytes(oid(80200 + i), b"\x44" * (1024 * 1024))


def test_create_spanning_forced_and_abort(striped_store):
    """rt_create_spanning exercises span machinery with small objects;
    abort of an unsealed span returns every claimed stripe."""
    s = striped_store
    bufs = s.create_spanning(oid(80030), 4096, 4)
    assert bufs is not None
    data, meta = bufs
    data[:] = b"\x77" * 4096
    meta[:] = b"mm.."
    assert s.is_span(oid(80030))
    assert not s.contains(oid(80030))       # unsealed: not visible
    s.abort(oid(80030))
    assert not s.is_span(oid(80030))
    assert s.span_stats()["stripes_claimed"] == 0
    # duplicate detection across planes: a sealed span blocks a normal
    # create of the same id
    assert s.create_spanning(oid(80031), 1024) is not None
    s.seal(oid(80031))
    assert s.create(oid(80031), 64) is None
    s.delete(oid(80031))


def test_max_alloc_boundary_routes_exactly(striped_store):
    s = striped_store
    cap = s.max_alloc_bytes()
    assert s.put_bytes(oid(80040), b"a" * cap)
    assert not s.is_span(oid(80040))        # fits one stripe: normal path
    s.delete(oid(80040))
    assert s.put_bytes(oid(80041), b"b" * (cap + 1))
    assert s.is_span(oid(80041))            # one byte over: spanning path
    s.delete(oid(80041))


def _chaos_span_loop(path, spec):
    # arm BEFORE the first native create (spec parsed once per process)
    os.environ[ShmSpanCreateKiller.SPEC_ENV] = spec
    from ray_tpu._private.object_store import ObjectStoreClient as Client
    c = Client(path)
    try:
        c.create_spanning((8_500_000).to_bytes(20, "big"),
                          20 * 1024 * 1024, 0)
    except Exception:
        pass
    os._exit(3)  # survived the spanning create: the injection never fired


def test_kill_mid_spanning_create_repairs_whole_span(striped_store):
    """ISSUE 11 chaos: a client SIGKILLed inside span_create — span
    mutex + a member stripe's mutex held, descriptor CLAIMING — must
    leave survivors able to free/invalidate the WHOLE half-claimed span
    and keep both allocation planes serving."""
    s = striped_store
    for i in range(8):
        assert s.put_bytes(oid(81000 + i), b"\x22" * 1024)
    killer = ShmSpanCreateKiller(nth_create=1)
    ctx = multiprocessing.get_context("spawn")
    victim = ctx.Process(target=_chaos_span_loop,
                         args=(s.path, killer.spec()))
    victim.start()
    killer.assert_killed(victim)
    # the gc sweep runs both repair levels (EOWNERDEAD on span mutex +
    # poisoned member stripe)
    s.gc_unsealed(0)
    sp = s.span_stats()
    assert sp["live_spans"] == 0
    assert sp["stripes_claimed"] == 0       # nothing half-claimed leaks
    assert sp["broken_slots"] == 0
    # both planes keep serving: a fresh span and fresh normal puts
    assert s.put_bytes(oid(81100), b"\x88" * (20 * 1024 * 1024))
    assert s.is_span(oid(81100))
    buf = s.get(oid(81100))
    assert bytes(buf.data[:4]) == b"\x88" * 4
    buf.close()
    s.delete(oid(81100))
    for i in range(16):
        assert s.put_bytes(oid(81200 + i), b"\x99" * 4096)
    st = s.stats()
    assert st["poisoned"] == 0
    assert st["span_repairs"] >= 1
