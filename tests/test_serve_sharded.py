"""Multi-host (sharded) serve replicas, exercised end-to-end on CPU: one
replica = a 2-process gang joined into a single jax.distributed world
through the GCS-KV rendezvous, serving a value computed by an XLA
collective ACROSS the processes — so a correct answer proves the group
really runs as one SPMD world, not two copies (SURVEY §7.2 step 10;
reference replica lifecycle python/ray/serve/_private/deployment_state.py
has no multi-host analog — this is the TPU-native extension).

Same CI stand-in scheme as test_jax_distributed.py: CPU devices, Gloo-
backed collectives, identical code path to a real slice."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


class ShardedSum:
    """y = scale * x * sum(w) with w = [1..n_global_devices] sharded over
    every device of the GROUP's global mesh: the jnp.sum is a
    cross-process all-reduce, so each request's answer requires both
    ranks to participate."""

    def __init__(self, scale=1.0):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        assert jax.process_count() == 2, \
            f"sharded replica must span 2 processes, saw " \
            f"{jax.process_count()}"
        devs = np.array(jax.devices())
        self.n = len(devs)
        mesh = Mesh(devs, ("model",))
        dist = NamedSharding(mesh, P("model"))
        n_local = jax.local_device_count()
        rank = jax.process_index()
        local = np.arange(rank * n_local, (rank + 1) * n_local,
                          dtype=np.float32) + 1.0
        self.w = jax.make_array_from_process_local_data(
            dist, local, (self.n,))
        self.scale = float(scale)
        self._f = jax.jit(lambda x, w: x * jnp.sum(w),
                          out_shardings=NamedSharding(mesh, P()))

    def __call__(self, x):
        import jax
        y = self._f(np.float32(float(x) * self.scale), self.w)
        return float(jax.device_get(y))


def _expected(x, scale, n_devices=16):
    return scale * x * (n_devices * (n_devices + 1) / 2.0)


def test_sharded_replica_handle(ray_start):
    app = serve.deployment(ShardedSum, num_hosts=2,
                           ray_actor_options={"num_cpus": 0.5}).bind(1.0)
    handle = serve.run(app, name="sharded", route_prefix=None)
    got = handle.remote(2.0).result(timeout=120)
    assert got == pytest.approx(_expected(2.0, 1.0)), got
    # concurrent requests serialize through the SPMD lock but all answer
    results = [handle.remote(float(i)).result(timeout=120)
               for i in range(1, 4)]
    assert results == [pytest.approx(_expected(float(i), 1.0))
                       for i in range(1, 4)]
    serve.delete("sharded")


def test_sharded_replica_http_and_rolling_update(ray_start):
    """Serve a sharded model over HTTP, then roll to a new version while
    requests are in flight: zero dropped requests, every answer belongs
    to exactly one version, and the new version eventually serves."""
    app = serve.deployment(ShardedSum, num_hosts=2,
                           ray_actor_options={"num_cpus": 0.5}).bind(1.0)
    serve.run(app, name="shttp", route_prefix="/sharded",
              _http=True, http_port=18271)

    v1 = _expected(3.0, 1.0)
    v2 = _expected(3.0, 10.0)
    results, errors = [], []
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                req = urllib.request.Request(
                    "http://127.0.0.1:18271/sharded",
                    data=json.dumps(3.0).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=120) as resp:
                    results.append(float(json.loads(resp.read())))
            except Exception as e:      # pragma: no cover - failure path
                errors.append(repr(e))
            time.sleep(0.05)

    t = threading.Thread(target=hammer, daemon=True)
    t.start()
    try:
        time.sleep(2.0)
        # rolling update: same app, new init arg — the controller surges
        # a NEW 2-process group, then drains and retires the old gang
        app2 = serve.deployment(
            ShardedSum, num_hosts=2,
            ray_actor_options={"num_cpus": 0.5}).bind(10.0)
        serve.run(app2, name="shttp", route_prefix="/sharded")
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if results and results[-1] == pytest.approx(v2):
                break
            time.sleep(0.5)
    finally:
        stop.set()
        t.join(timeout=150)
    assert not errors, f"dropped requests during roll: {errors[:5]}"
    assert results, "no responses recorded"
    assert results[-1] == pytest.approx(v2), results[-5:]
    for r in results:
        assert r == pytest.approx(v1) or r == pytest.approx(v2), r
    serve.delete("shttp")


class ShardedStreamer(ShardedSum):
    """Token-streaming shape: each yielded chunk is one SPMD step (the
    jitted cross-process all-reduce), so a correct stream proves the
    ranks advance their generators in lockstep."""

    def stream(self, x):
        import jax
        for i in range(5):
            y = self._f(np.float32(float(x) + i), self.w)
            yield float(jax.device_get(y))


def test_sharded_replica_streaming(ray_start):
    """Streamed responses from a sharded gang: rank 0 yields per-step
    SPMD results; every chunk must be present, ordered, and correct."""
    app = serve.deployment(ShardedStreamer, num_hosts=2,
                           ray_actor_options={"num_cpus": 0.5}).bind(1.0)
    handle = serve.run(app, name="sstream", route_prefix=None)
    gen = handle.options(stream=True).stream.remote(2.0)
    got = [chunk for chunk in gen]
    assert got == [pytest.approx(_expected(2.0 + i, 1.0))
                   for i in range(5)], got
    # a second stream after the first completes (SPMD lock released)
    gen = handle.options(stream=True).stream.remote(0.0)
    assert [c for c in gen] == [pytest.approx(_expected(float(i), 1.0))
                                for i in range(5)]
    serve.delete("sstream")


def test_sharded_autoscaling_gangs(ray_start):
    """Autoscaling where one replica = one GANG: sustained queue depth
    on the single gang (SPMD lock serializes requests) upscales to a
    second 2-process gang; idling back down retires a whole gang."""

    class SlowShardedSum(ShardedSum):
        def __call__(self, x):
            import time as _t
            _t.sleep(0.3)       # hold the SPMD slot: queue builds
            return super().__call__(x)

    from ray_tpu.serve.api import _get_controller
    app = serve.deployment(
        SlowShardedSum, num_hosts=2,
        ray_actor_options={"num_cpus": 0.25},
        autoscaling_config={"min_replicas": 1, "max_replicas": 2,
                            "target_ongoing_requests": 1.0,
                            "upscale_delay_s": 1.0,
                            "downscale_delay_s": 4.0,
                            "look_back_period_s": 4.0},
    ).bind(1.0)
    handle = serve.run(app, name="sauto", route_prefix=None)
    ctrl = _get_controller()

    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                handle.remote(1.0).result(timeout=120)
            except Exception:
                pass

    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 120
        scaled_up = False
        while time.monotonic() < deadline:
            st = ray_tpu.get(ctrl.get_status.remote(), timeout=30)
            if st["sauto"]["SlowShardedSum"]["running"] >= 2:
                scaled_up = True
                break
            time.sleep(1.0)
        assert scaled_up, "never scaled to a second gang"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=130)
    # idle: a whole gang drains away back to min_replicas
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        st = ray_tpu.get(ctrl.get_status.remote(), timeout=30)
        if st["sauto"]["SlowShardedSum"]["running"] == 1:
            break
        time.sleep(1.0)
    assert st["sauto"]["SlowShardedSum"]["running"] == 1, st
    # the survivor still serves
    assert handle.remote(2.0).result(timeout=120) == \
        pytest.approx(_expected(2.0, 1.0))
    serve.delete("sauto")


def test_sharded_group_torn_down_with_app(ray_start):
    """Deleting the app kills every rank of the gang and releases its
    placement group — no orphaned shard actors or bundles."""
    from ray_tpu.serve.api import _get_controller

    app = serve.deployment(ShardedSum, num_hosts=2,
                           ray_actor_options={"num_cpus": 0.5}).bind(1.0)
    handle = serve.run(app, name="stear", route_prefix=None)
    assert handle.remote(1.0).result(timeout=120) == \
        pytest.approx(_expected(1.0, 1.0))
    ctrl = _get_controller()
    info = ray_tpu.get(
        ctrl.get_deployment_info.remote("stear", "ShardedSum"), timeout=30)
    (rank0,) = info["replicas"]
    serve.delete("stear")
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            ray_tpu.get(rank0.get_queue_len.remote(), timeout=5)
            time.sleep(0.5)
        except ray_tpu.ActorDiedError:
            break
    else:
        pytest.fail("rank-0 shard still alive after app delete")
