"""BAD: blocking ops in a registered h_* handler and a lock acquire in
an async def (RT001 x3)."""
import socket
import threading

_state_lock = threading.Lock()


class NodeThing:
    def __init__(self):
        self._sock = socket.socket()

    def h_fetch(self, conn, addr):
        # sync h_* handlers dispatch inline on the owner loop
        self._sock.connect(addr)              # RT001: blocking socket op
        sock = socket.create_connection(addr)  # RT001: blocking connect
        return sock

    async def h_report(self, conn):
        _state_lock.acquire()                 # RT001: blocking lock acquire
        try:
            return {"ok": True}
        finally:
            _state_lock.release()
