"""CLEAN for RT003: stores under the declared lock; unmarked methods
are out of scope; local/arg stores are not self state."""
import threading

from ray_tpu._private.markers import off_loop


class PutPath:
    def __init__(self):
        self._ref_lock = threading.Lock()
        self.count = 0

    @off_loop(lock="_ref_lock")
    def record(self, oid):
        local = oid * 2                      # locals are thread-private
        with self._ref_lock:
            self.count += 1                  # guarded RMW
            self.last = local
        return local

    def loop_side(self):
        self.count = 0                       # unmarked: loop-owned, fine
