"""BAD: blocking calls inside async bodies (RT001 x3)."""
import subprocess
import time


async def heartbeat_loop():
    while True:
        time.sleep(1.0)                       # RT001: blocks the loop


async def spawn_helper():
    subprocess.run(["true"])                  # RT001: blocking subprocess
    with open("/tmp/x") as f:                 # RT001: blocking file IO
        return f.read()
