"""CLEAN for RT002: shape math, static-arg branches, donation rebind,
early-return branches that never alias the donated buffer."""
import jax


@jax.jit
def shape_math(x):
    n = int(x.shape[0])                # shapes are static under tracing
    return x.reshape(n, -1), len(x.shape)


def make(fn):
    inner = jax.jit(fn, static_argnums=(1,))
    return inner


def branch_on_static(x, mode):
    f = jax.jit(lambda a: a, static_argnums=())
    if mode == "fast":                 # mode isn't traced here (host code)
        return f(x)
    return f(x) * 2


jit_roll = jax.jit(lambda kv: kv * 2, donate_argnums=(0,))


def decode_loop(kv, steps):
    for _ in range(steps):
        kv = jit_roll(kv)              # rebinding: the donated name is
    return kv                          # always the NEW buffer
