"""BAD: host coercion + traced branch + .item() in jitted fns (RT002)."""
import jax


@jax.jit
def decode_step(lengths, toks):
    cur = int(lengths)                 # RT002: concretizes traced value
    if toks > 0:                       # RT002: Python branch on traced arg
        return toks + cur
    return toks


def build(model):
    def sample(logits, temp):
        t = temp.item()                # RT002: .item() host sync
        return logits / t
    return jax.jit(sample)
