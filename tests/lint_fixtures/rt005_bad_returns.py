"""BAD: msgpack-unsafe handler returns (RT005)."""
import numpy as np


class Handlers:
    def h_list_nodes(self, conn):
        return {"alive": {"n1", "n2"}}       # RT005: set in the payload

    def h_count(self, conn):
        return np.int64(3)                   # RT005: numpy scalar

    async def h_locations(self, conn, oid):
        return {b"\x01\x02": "n1"}           # RT005: bytes-keyed dict

    def h_ids(self, conn, rows):
        return set(r["id"] for r in rows)    # RT005: set() constructor
