# Fixture corpus for tests/test_lint.py — deliberately buggy snippets.
# Never imported; linted as files. Kept out of the default lint paths
# (pyproject [tool.rtlint] paths = ["ray_tpu"]).
