"""BAD: unlocked self.* stores in @off_loop methods (RT003)."""
import threading

from ray_tpu._private.markers import off_loop


class PutPath:
    def __init__(self):
        self._ref_lock = threading.Lock()
        self.count = 0
        self.table = {}

    @off_loop(lock="_ref_lock")
    def record(self, oid):
        self.count += 1                      # RT003: RMW outside the lock
        self.table[oid] = self.table.get(oid, 0) + 1   # RT003: store

    @off_loop()
    def mark(self, flag):
        self.flag = flag                     # RT003: no lock even declared
