"""BAD: store under the WRONG lock in an @off_loop method (RT003)."""
import threading

from ray_tpu._private.markers import off_loop


class ArenaClient:
    def __init__(self):
        self._pins_lock = threading.Lock()
        self._other_lock = threading.Lock()
        self._pins = {}

    @off_loop(lock="_pins_lock")
    def pin(self, oid):
        with self._other_lock:               # not the declared lock
            self._pins[oid] = self._pins.get(oid, 0) + 1   # RT003
