"""BAD: donated-buffer reuse, optimizer-apply flavor (RT002).

The bug class the MPMD trainer's donation audit guards against: the
apply program donates (params, opt_state, grads) so XLA can update in
place, which makes the CALLER'S handles to those buffers invalid — a
checkpoint taken from the stale handle, or a gradient re-accumulated
into the freed buffer, reads garbage.
"""
import jax


def apply_fn(params, opt_state, grads):
    new_params = params       # stand-in for the optax update
    return new_params, opt_state


jit_apply = jax.jit(apply_fn, donate_argnums=(0, 1, 2))


def train_step(params, opt_state, grads):
    out = jit_apply(params, opt_state, grads)
    snapshot = params["w"]             # RT002: params was donated above
    grads = grads + grads              # RT002: grads was donated above
    return out, snapshot, grads
