"""BAD: donated-buffer reuse + unhashable static default (RT002)."""
import jax


def step(kv, tok):
    return kv + tok, tok


def loss(x, cfg=[1, 2]):               # noqa: B006 — deliberate
    return x * cfg[0]


jit_step = jax.jit(step, donate_argnums=(0,))
jit_loss = jax.jit(loss, static_argnums=(1,))  # RT002: mutable static default


def run(kv, tok):
    out, tok2 = jit_step(kv, tok)
    return kv.sum() + tok2             # RT002: kv was donated above
