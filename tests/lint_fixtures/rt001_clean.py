"""CLEAN for RT001: awaits, executor thunks, sync-context sleeps."""
import asyncio
import time


async def polite_loop():
    while True:
        await asyncio.sleep(1.0)              # the async way


async def offloaded_read(path):
    loop = asyncio.get_event_loop()

    def _read():                              # nested sync def: runs in
        with open(path, "rb") as f:           # the executor, not the loop
            time.sleep(0.01)
            return f.read()

    return await loop.run_in_executor(None, _read)


def worker_thread_tick():
    time.sleep(0.5)                           # sync context: fine
