"""Suppression syntax fixture: every violation here carries a pragma,
so a lint run reports zero findings but a nonzero suppressed count."""
import time


async def tick():
    # trailing same-line pragma
    time.sleep(0.01)  # rtlint: disable=RT001 — test fixture: deliberate

    # standalone pragma block binds to the next code line
    # rtlint: disable=RT001 — also deliberate
    time.sleep(0.02)


# def-line pragma covers the whole body
async def settle():  # rtlint: disable=RT001 — fixture: scope suppression
    time.sleep(0.03)
    time.sleep(0.04)
