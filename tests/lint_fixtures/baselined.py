"""Baseline fixture: one unsuppressed violation that test_lint.py
registers in a temp baseline file (with a justification), proving the
gate passes on baselined findings and fails without them."""
import time


async def legacy_block():
    time.sleep(0.5)          # known legacy finding — baselined in test
