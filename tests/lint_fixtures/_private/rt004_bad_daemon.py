"""BAD: silent broad excepts inside daemon loops (RT004)."""
import asyncio
import time


def flush_daemon(flush):
    while True:
        time.sleep(1.0)
        try:
            flush()
        except Exception:                    # RT004: swallowed every tick
            pass


async def refresh_loop(gcs):
    for attempt in range(30):
        try:
            await gcs.call("get_view")
        except:                              # RT004: bare + silent, in loop
            pass
        await asyncio.sleep(1)
