"""CLEAN for RT004: logged, counted, narrowed, or outside a loop."""
import logging
import time

logger = logging.getLogger(__name__)


def logged_daemon(flush):
    while True:
        time.sleep(1.0)
        try:
            flush()
        except Exception:
            logger.debug("flush failed", exc_info=True)   # visible


def narrowed_daemon(read):
    while True:
        try:
            read()
        except OSError:                      # narrowed type: deliberate
            pass


def one_shot(best_effort):
    try:
        best_effort()
    except Exception:                        # not in a loop: out of scope
        pass
