# RT004 is scoped to _private/ paths; these fixtures live under a
# _private/ segment so the rule applies to them.
