"""BAD: the spec-decode retrace bug — host coercion and Python control
flow on the traced accept count inside a jitted verify step (RT002).
Each distinct accept count would retrace (or just crash under jit);
accept_prefix must stay a lax cumprod/sum with a fixed-shape write."""
import jax


@jax.jit
def verify_step(drafts, verified, n_accept):
    n = int(n_accept)                  # RT002: concretizes traced count
    if n_accept > 0:                   # RT002: Python branch on traced value
        return verified[:, :n]
    return drafts


def build_accept(model):
    def accept(drafts, out, temps):
        k = out.argmax(-1).item()      # RT002: .item() host sync in trace
        return drafts[:k]
    return jax.jit(accept)
