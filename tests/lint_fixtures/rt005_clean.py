"""CLEAN for RT005: lists, str keys, coerced scalars, and non-handler
methods (out of scope)."""
import numpy as np


class Handlers:
    def h_list_nodes(self, conn):
        return {"alive": sorted(["n1", "n2"])}

    def h_count(self, conn):
        return int(np.int64(3))              # coerced at the boundary

    async def h_locations(self, conn, oid):
        return {oid.hex(): "n1"}

    def internal_set(self):                  # not an h_* handler
        return {"x", "y"}
