"""Data -> device ingest and streaming-split-into-Train (reference:
streaming_split via OutputSplitter output_splitter.py, DataConfig
train/_internal/data_config.py, ActorPoolMapOperator, resource-managed
streaming executor streaming_executor.py:48 + backpressure_policy/).
"""

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rdata
from ray_tpu.train import (DataConfig, JaxTrainer, RunConfig, ScalingConfig,
                           get_dataset_shard)


@pytest.fixture
def ray_start():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_actor_pool_map_batches(ray_start):
    class AddState:
        """Stateful UDF: construction happens once per pool actor."""

        def __init__(self, offset):
            self.offset = offset
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            return {"x": batch["x"] + self.offset}

    ds = rdata.range(64, parallelism=8).map_batches(
        lambda b: {"x": b["id"]},
    ).map_batches(AddState, fn_constructor_args=(100,), concurrency=2)
    out = sorted(r["x"] for r in ds.take_all())
    assert out == [100 + i for i in range(64)]


def test_streaming_split_equal_rows(ray_start):
    """equal=True delivers EXACTLY equal row counts (tail sliced/dropped),
    the contract lockstep SPMD consumers need."""
    # 5 blocks of 7 rows over 2 consumers: 35 rows -> 17 each, 1 dropped
    ds = rdata.range(35, parallelism=5)
    shards = ds.streaming_split(2, equal=True)
    rows = [[r["id"] for r in shard.iter_rows()] for shard in shards]
    assert len(rows[0]) == len(rows[1]) == 17
    assert not (set(rows[0]) & set(rows[1]))


def test_streaming_split_disjoint_and_complete(ray_start):
    ds = rdata.range(40, parallelism=8)
    shards = ds.streaming_split(2)
    rows = [[r["id"] for r in shard.iter_rows()] for shard in shards]
    assert rows[0] and rows[1]
    combined = sorted(rows[0] + rows[1])
    assert combined == list(range(40))
    assert not (set(rows[0]) & set(rows[1]))


def test_iter_jax_batches_device_prefetch(ray_start):
    ds = rdata.range(32, parallelism=4).map_batches(
        lambda b: {"x": b["id"].astype(np.float32)})
    got = []
    for batch in ds.iter_jax_batches(batch_size=8, device_prefetch=2):
        assert batch["x"].shape == (8,)
        got.extend(np.asarray(batch["x"]).tolist())
    assert sorted(got) == [float(i) for i in range(32)]


def test_backpressure_budget_bounds_inflight(ray_start):
    from ray_tpu.data import execution as exe
    budget = exe.ExecutionBudget(max_tasks=3)
    peak = [0]

    orig = exe.ExecutionBudget.try_acquire

    def spy(self, est, force=False):
        ok = orig(self, est, force=force)
        peak[0] = max(peak[0], self.tasks)
        return ok

    exe.ExecutionBudget.try_acquire = spy
    try:
        ds = rdata.range(64, parallelism=16).map_batches(
            lambda b: {"id": b["id"] * 2})
        out = list(exe.execute_plan(ds._stages, budget=budget))
        assert len(out) == 16
        # max_tasks plus at most one forced launch per stage
        assert peak[0] <= 3 + 2
    finally:
        exe.ExecutionBudget.try_acquire = orig


def _ingest_train_fn(config):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import ray_tpu
    from ray_tpu import train as rt
    shard = get_dataset_shard("train")
    total = 0.0
    count = 0
    for batch in shard.iter_jax_batches(batch_size=4, drop_last=False):
        total += float(batch["x"].sum())
        count += int(batch["x"].shape[0])
    tally = ray_tpu.get_actor("ingest-tally")
    ray_tpu.get(tally.add.remote(count, total), timeout=60)
    rt.report({"total": total, "count": count})


def test_trainer_dataset_ingest(ray_start):
    @ray_tpu.remote(num_cpus=0.1)
    class Tally:
        def __init__(self):
            self.count = 0
            self.total = 0.0

        def add(self, c, t):
            self.count += c
            self.total += t
            return True

        def get(self):
            return self.count, self.total

    tally = Tally.options(name="ingest-tally").remote()
    ray_tpu.get(tally.get.remote(), timeout=60)

    ds = rdata.range(24, parallelism=6).map_batches(
        lambda b: {"x": b["id"].astype(np.float32)})
    trainer = JaxTrainer(
        _ingest_train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ingest-e2e"),
        datasets={"train": ds},
        dataset_config=DataConfig(datasets_to_split="all"),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    count, total = ray_tpu.get(tally.get.remote(), timeout=60)
    # the two workers together consumed every row exactly once
    assert count == 24
    assert total == float(sum(range(24)))


def test_new_datasources(ray_start, tmp_path):
    """numpy / binary / tfrecord sources round-trip (reference:
    NumpyDatasource, BinaryDatasource, TFRecordDatasource)."""
    # .npy
    arr = np.arange(10, dtype=np.float32)
    np.save(tmp_path / "a.npy", arr)
    rows = rdata.read_numpy(str(tmp_path / "a.npy"), column="x").take_all()
    assert sorted(r["x"] for r in rows) == arr.tolist()

    # binary files
    (tmp_path / "b1.bin").write_bytes(b"hello")
    (tmp_path / "b2.bin").write_bytes(b"world!")
    rows = rdata.read_binary_files(
        [str(tmp_path / "b1.bin"), str(tmp_path / "b2.bin")],
        include_paths=True).take_all()
    assert sorted(len(r["bytes"]) for r in rows) == [5, 6]
    assert all("path" in r for r in rows)

    # tfrecords: write with our codec, read through the dataset
    from ray_tpu.data import tfrecord as tfr
    recs = [tfr.row_to_example({"label": i, "name": f"row{i}",
                                "score": [float(i), float(i) * 2]})
            for i in range(5)]
    tfr.write_records(str(tmp_path / "t.tfrecord"), recs)
    # codec round-trip sanity (incl. crc framing)
    back = [tfr.example_to_row(r) for r in
            tfr.read_records(str(tmp_path / "t.tfrecord"), validate=True)]
    assert back[2]["label"] == 2 and back[2]["name"] == "row2"
    assert back[2]["score"] == [2.0, 4.0]
    ds = rdata.read_tfrecords(str(tmp_path / "t.tfrecord"))
    rows = ds.take_all()
    assert sorted(r["label"] for r in rows) == list(range(5))


def test_tfrecord_validation_and_numpy_scalars(tmp_path):
    from ray_tpu.data import tfrecord as tfr
    recs = [tfr.row_to_example({"a": np.float32(1.5), "b": np.int64(7)})]
    path = str(tmp_path / "v.tfrecord")
    tfr.write_records(path, recs)
    (row,) = (tfr.example_to_row(r)
              for r in tfr.read_records(path, validate=True))
    assert row["a"] == 1.5 and row["b"] == 7
    # corrupt a payload byte: validated reads must fail
    blob = bytearray(open(path, "rb").read())
    blob[14] ^= 0xFF
    bad = str(tmp_path / "bad.tfrecord")
    open(bad, "wb").write(bytes(blob))
    with pytest.raises(ValueError):
        list(tfr.read_records(bad, validate=True))
