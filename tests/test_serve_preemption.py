"""Preemption-tolerant serving: burn-rate autoscaling loop, drain
semantics, and resumable streams (ROADMAP item 2, control-loop half).

Hermetic tier (no cluster, any interpreter):
- BurnRateScaler policy: sustained dual-window burn raises the replica
  target within two slow windows, an instant spike does not, idle
  replicas release after cooldown (driven against a REAL GcsServer
  metrics ring with a fake clock — the same synthetic-push harness the
  SLO tests use).
- Controller drain-deadline semantics with monkeypatched ray_tpu
  primitives: queue empties -> reaped clean; deadline expiry -> forced
  kill; a draining replica never reappears in routing tables.
- Scheduler/engine drain mode; LLMDeployment resume_tokens continuation
  (greedy-exact); the handle-side stream re-route state machine.
- Autoscaler escalating backoff + serve replica-demand export.

Cluster tier (Python >= 3.12): notice-based preemption end to end and
stream resume across a real replica kill.
"""

import itertools
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")


# --------------------------------------------------------------------------
# fakes: replica handles + ray primitives for lock-step controller tests
# --------------------------------------------------------------------------

class _FakeRef:
    _ids = itertools.count()

    def __init__(self, resolve):
        self.id = f"fakeref-{next(self._ids)}"
        self._resolve = resolve      # () -> value, may raise


class _FakeMethod:
    def __init__(self, fn):
        self._fn = fn

    def remote(self, *a, **kw):
        return _FakeRef(lambda: self._fn(*a, **kw))


class _FakeReplica:
    def __init__(self, queue_len=0, dead=False):
        self.queue_len = queue_len
        self.dead = dead
        self.drain_notices = 0

    def __getattr__(self, name):
        if name == "get_queue_len":
            return _FakeMethod(self._qlen)
        if name == "get_runtime_state":
            return _FakeMethod(
                lambda: {"queue_len": self._qlen(), "draining": False})
        if name == "begin_drain":
            return _FakeMethod(self._begin_drain)
        if name == "check_health":
            return _FakeMethod(lambda: True)
        raise AttributeError(name)

    def _qlen(self):
        if self.dead:
            raise ray_tpu.ActorDiedError("fake replica dead")
        return self.queue_len

    def _begin_drain(self):
        self.drain_notices += 1
        return True


@pytest.fixture
def fake_ray(monkeypatch):
    """Route the controller's ray_tpu.get/wait/kill through _FakeRefs."""
    killed = []

    def fake_get(obj, timeout=None):
        if isinstance(obj, list):
            return [fake_get(o, timeout=timeout) for o in obj]
        return obj._resolve()

    def fake_wait(refs, num_returns=None, timeout=None):
        return list(refs), []

    monkeypatch.setattr(ray_tpu, "get", fake_get)
    monkeypatch.setattr(ray_tpu, "wait", fake_wait)
    monkeypatch.setattr(ray_tpu, "kill", killed.append)
    return killed


@pytest.fixture
def ctrl():
    from ray_tpu.serve.controller import ServeController

    class _QuietController(ServeController):
        def _reconcile_loop(self):   # tests drive ticks by hand
            return

    c = _QuietController()
    c._stop = True
    return c


def _mk_dep(replicas, config=None, target=None):
    cfg = {"num_replicas": len(replicas),
           "graceful_shutdown_timeout_s": 5.0,
           "preempt_grace_s": 2.0,
           "resumable_streams": False}
    cfg.update(config or {})
    return {"spec": {"name": "d", "app_name": "a", "config": cfg},
            "replicas": list(replicas), "version": 0,
            "target": len(replicas) if target is None else target,
            "replica_gens": [0] * len(replicas), "gen": 0,
            # park replica construction: hermetic tests never build
            # real actors, the flag keeps _reconcile_deployment quiet
            "_creating": True}


# --------------------------------------------------------------------------
# controller drain-deadline semantics (satellite: drain tests)
# --------------------------------------------------------------------------

def test_drain_reaps_clean_once_queue_empties(ctrl, fake_ray):
    r = _FakeReplica(queue_len=2)
    dep = _mk_dep([r])
    ctrl.apps = {"a": {"d": dep}}
    with ctrl._lock:
        assert ctrl._detach_for_drain(dep, r, grace_s=30.0)
    ctrl._reap_draining(dep)
    assert fake_ray == [] and len(dep["draining"]) == 1  # busy: kept
    r.queue_len = 0
    ctrl._reap_draining(dep)
    assert fake_ray == [r]                # queue empty -> reaped clean
    assert dep["draining"] == []


def test_drain_deadline_expiry_forces_kill(ctrl, fake_ray):
    r = _FakeReplica(queue_len=3)         # never drains
    dep = _mk_dep([r])
    ctrl.apps = {"a": {"d": dep}}
    with ctrl._lock:
        ctrl._detach_for_drain(dep, r, grace_s=0.05)
    time.sleep(0.06)
    ctrl._reap_draining(dep)
    assert fake_ray == [r]                # forced kill at the deadline
    assert dep["draining"] == []


def test_dead_draining_replica_reaped_immediately(ctrl, fake_ray):
    r = _FakeReplica(queue_len=1, dead=True)
    dep = _mk_dep([r])
    ctrl.apps = {"a": {"d": dep}}
    with ctrl._lock:
        ctrl._detach_for_drain(dep, r, grace_s=60.0)
    ctrl._reap_draining(dep)
    assert fake_ray == [r]


def test_draining_replica_never_in_routing_tables(ctrl, fake_ray):
    r1, r2 = _FakeReplica(queue_len=1), _FakeReplica()
    dep = _mk_dep([r1, r2])
    ctrl.apps = {"a": {"d": dep}}
    v0 = ctrl.get_deployment_info("a", "d")["version"]
    assert ctrl.preempt_replica("a", "d", 0, grace_s=10.0)
    assert r1.drain_notices == 1          # the notice reached the replica
    info = ctrl.get_deployment_info("a", "d")
    assert info["version"] > v0           # routers are woken
    assert r1 not in info["replicas"] and r2 in info["replicas"]
    # and it stays out: subsequent tables are built from dep["replicas"]
    assert r1 not in ctrl.get_deployment_info("a", "d")["replicas"]
    assert [h for h, _ in dep["draining"]] == [r1]
    # preempting the LAST replica still detaches it (capacity dips until
    # the pre-started replacement lands — routing never sees the corpse)
    assert ctrl.preempt_replica("a", "d", 0, grace_s=10.0)
    assert ctrl.get_deployment_info("a", "d")["replicas"] == []


def test_probe_states_picks_up_self_draining_replica(ctrl, fake_ray):
    """A replica that flipped ITSELF into draining (metadata notice) is
    detached on the next reconcile tick."""
    r1, r2 = _FakeReplica(), _FakeReplica()
    dep = _mk_dep([r1, r2])
    ctrl.apps = {"a": {"d": dep}}
    probed, states = ctrl._probe_states(dep)
    assert [s["draining"] for s in states] == [False, False]
    states[0]["draining"] = True          # as the probe would report
    with ctrl._lock:
        for r, s in zip(probed, states):
            if s.get("draining"):
                ctrl._detach_for_drain(dep, r, ctrl._preempt_grace(dep))
    assert r1 not in dep["replicas"] and r2 in dep["replicas"]
    assert ctrl._preempt_grace(dep) == 2.0


# --------------------------------------------------------------------------
# burn-rate autoscaling (tentpole a)
# --------------------------------------------------------------------------

_AUTO = {"min_replicas": 1, "max_replicas": 4,
         "target_ongoing_requests": 2.0,
         "burn_upscale_hold_s": 6.0, "burn_downscale_idle_s": 60.0,
         "burn_cooldown_s": 30.0, "burn_release_threshold": 0.25}


def _rows(violating, fast=0.0, slow=0.0):
    return [{"objective": "latency", "violating": violating,
             "burn_fast": fast, "burn_slow": slow}]


def test_burn_scaler_requires_sustained_violation():
    from ray_tpu.serve.slo import BurnRateScaler
    s = BurnRateScaler()
    # one violating tick (an instant spike the multiwindow rule let
    # through) never scales: the hold gate needs 6s of it
    assert s.decide(_AUTO, _rows(True, 3.0, 1.5), 1, 0.0, now=0.0) == 1
    assert s.decide(_AUTO, _rows(False), 1, 0.0, now=2.0) == 1
    assert s.decide(_AUTO, _rows(True, 3.0, 1.5), 1, 0.0, now=4.0) == 1
    # sustained violation: hold elapses -> scale, proportional to burn
    assert s.decide(_AUTO, _rows(True, 3.0, 1.5), 1, 8.0, now=8.0) == 1
    assert s.decide(_AUTO, _rows(True, 3.0, 1.5), 1, 8.0, now=10.1) == 2
    # cooldown: still violating but no second action yet
    assert s.decide(_AUTO, _rows(True, 3.0, 1.5), 2, 8.0, now=20.0) == 2
    # past cooldown AND still sustained: next step (2 * burn 1.5 -> 3)
    assert s.decide(_AUTO, _rows(True, 3.0, 1.5), 2, 8.0, now=41.0) == 3
    # never exceeds max_replicas
    assert s.decide(_AUTO, _rows(True, 9.0, 9.0), 4, 8.0, now=100.0) == 4


def test_burn_scaler_releases_idle_after_cooldown():
    from ray_tpu.serve.slo import BurnRateScaler
    s = BurnRateScaler()
    # burn quiet + load low, but not for long enough: no release
    assert s.decide(_AUTO, _rows(False, 0.0, 0.0), 3, 0.0, now=0.0) == 3
    assert s.decide(_AUTO, _rows(False, 0.0, 0.0), 3, 0.0, now=30.0) == 3
    # idle hold (60s) elapsed -> one step down
    assert s.decide(_AUTO, _rows(False, 0.0, 0.0), 3, 0.0, now=61.0) == 2
    # cooldown separates release steps
    assert s.decide(_AUTO, _rows(False, 0.0, 0.0), 2, 0.0, now=80.0) == 2
    assert s.decide(_AUTO, _rows(False, 0.0, 0.0), 2, 0.0, now=125.0) == 1
    # floor at min_replicas
    assert s.decide(_AUTO, _rows(False, 0.0, 0.0), 1, 0.0, now=300.0) == 1


def test_burn_scaler_loaded_fleet_does_not_release():
    from ray_tpu.serve.slo import BurnRateScaler
    s = BurnRateScaler()
    # burn is quiet but per-replica load is healthy: keep capacity
    for t in range(0, 200, 2):
        assert s.decide(_AUTO, _rows(False, 0.1, 0.1), 3, 5.0,
                        now=float(t)) == 3


def test_burn_scaler_against_metrics_ring_two_slow_windows():
    """Acceptance (hermetic, fake metrics ring = a real GcsServer fed
    synthetic pushes + a fake clock): sustained dual-window burn raises
    the target within two slow windows; an instant spike lights only
    the fast window and never scales; idle releases after cooldown."""
    from ray_tpu._private.gcs import GcsServer
    from ray_tpu.serve.slo import BurnRateScaler, evaluate_slo
    from ray_tpu.util.metrics import Histogram

    slo = {"p95_ttft_ms": 200.0, "latency_metric": "churn_ttft_ms",
           "fast_window_s": 30.0, "slow_window_s": 120.0}
    auto = dict(_AUTO, burn_upscale_hold_s=4.0, burn_cooldown_s=20.0,
                burn_downscale_idle_s=40.0)

    g = GcsServer()
    h = Histogram("churn_ttft_ms",
                  boundaries=[10, 50, 100, 250, 500, 1000, 2500])
    clock = {"now": 1000.0}

    def query(metric, window=60.0, agg="avg", tags=None, threshold=None):
        return g.h_query_metrics(None, metric, window=window, agg=agg,
                                 tags=tags, threshold=threshold,
                                 now=clock["now"])

    def tick(ttft_ms, n_obs=20):
        for _ in range(n_obs):
            h.observe(ttft_ms)
        g.h_report_metrics(None, "w1", [h._snapshot()], ts=clock["now"])
        clock["now"] += 2.0
        return evaluate_slo(slo, query)

    scaler = BurnRateScaler()
    target = 1

    # healthy baseline fills both windows
    for _ in range(60):
        rows = tick(40.0)
        target = scaler.decide(auto, rows, target, 2.0, clock["now"])
    assert target == 1

    # instant spike: one bad push -> fast window burns, slow does not,
    # the multiwindow rule keeps violating False and the target flat
    rows = tick(800.0)
    assert rows[0]["burn_fast"] > 1.0 and not rows[0]["violating"]
    target = scaler.decide(auto, rows, target, 2.0, clock["now"])
    for _ in range(20):
        rows = tick(40.0)
        target = scaler.decide(auto, rows, target, 2.0, clock["now"])
    assert target == 1

    # sustained burn: every request blows the objective; the target must
    # rise within two slow windows (240 simulated seconds)
    t_bad_start = clock["now"]
    raised_at = None
    for _ in range(150):
        rows = tick(800.0)
        target = scaler.decide(auto, rows, target, 9.0, clock["now"])
        if target > 1:
            raised_at = clock["now"]
            break
    assert raised_at is not None, "sustained burn never scaled up"
    assert raised_at - t_bad_start <= 2 * 120.0
    assert rows[0]["violating"]

    # recovery + idle: traffic stops blowing the objective and the load
    # drops. The burn keeps both windows lit for a while (the scaler may
    # even step up once more — correct: the SLO is still burning), then
    # the windows drain, the idle hold elapses, and the fleet releases
    # all the way back to min_replicas, one replica per cooldown.
    released_at = None
    for _ in range(400):
        rows = tick(30.0)
        new_target = scaler.decide(auto, rows, target, 0.0, clock["now"])
        if new_target < target:
            released_at = clock["now"]
        target = new_target
        if target == 1 and released_at is not None:
            break
    assert released_at is not None, "idle replicas never released"
    assert target == 1


def test_controller_burn_autoscale_and_demand_export(ctrl):
    dep = _mk_dep([_FakeReplica()],
                  config={"autoscaling_config": dict(
                      _AUTO, burn_upscale_hold_s=0.0, burn_cooldown_s=0.0),
                      "ray_actor_options": {"num_cpus": 1.0,
                                            "num_tpus": 4.0}})
    ctrl.apps = {"a": {"d": dep}}
    with ctrl._lock:
        ctrl._burn_autoscale("a", "d", dep,
                             _rows(True, 3.0, 2.0), [1])
    assert dep["target"] == 2
    # the raised target exports as replica demand for the cluster
    # autoscaler (deficit = target - running = 1)
    demand = ctrl.get_replica_demand()
    assert demand == [{"CPU": 1.0, "TPU": 4.0}]
    # no slo rows (deployment without slo_config) -> no scaling
    with ctrl._lock:
        ctrl._burn_autoscale("a", "d", dep, None, [1])
    assert dep["target"] == 2


# --------------------------------------------------------------------------
# autoscaler: serve demand + escalating backoff (satellites)
# --------------------------------------------------------------------------

class _RecordingProvider:
    def __init__(self):
        self.created = []

    def create_node(self, node_type, resources, labels):
        nid = f"prov-{len(self.created)}"
        self.created.append(node_type)
        return nid

    def terminate_node(self, provider_node_id):
        pass

    def non_terminated_nodes(self):
        return [f"prov-{i}" for i in range(len(self.created))]


def _head_node(pending=None):
    return [{"node_id": "head", "alive": True,
             "total": {"CPU": 1.0}, "available": {"CPU": 0.0},
             "pending_demand": list(pending or [])}]


def test_autoscaler_acquires_nodes_for_serve_replica_demand():
    from ray_tpu.autoscaler.autoscaler import (Autoscaler,
                                               AutoscalerConfig,
                                               NodeTypeConfig)
    provider = _RecordingProvider()
    cfg = AutoscalerConfig(
        node_types={"tpu-host": NodeTypeConfig(
            resources={"CPU": 1.0, "TPU": 4.0}, max_workers=4)})
    demand = [{"CPU": 1.0, "TPU": 4.0}, {"CPU": 1.0, "TPU": 4.0}]
    a = Autoscaler(cfg, provider, nodes_fn=_head_node,
                   serve_demand_fn=lambda: demand)
    actions = a.step()
    # two missing replicas, one TPU host each
    assert actions["launched"] == ["tpu-host", "tpu-host"]
    # in-flight launches absorb the same demand next step: no relaunch
    assert a.step()["launched"] == []


def test_serve_demand_dedupes_against_lease_demand():
    from ray_tpu.autoscaler.autoscaler import (Autoscaler,
                                               AutoscalerConfig,
                                               NodeTypeConfig)
    provider = _RecordingProvider()
    cfg = AutoscalerConfig(
        node_types={"tpu-host": NodeTypeConfig(
            resources={"CPU": 1.0, "TPU": 4.0}, max_workers=4)})
    req = {"CPU": 1.0, "TPU": 4.0}
    a = Autoscaler(cfg, provider,
                   nodes_fn=lambda: _head_node(pending=[dict(req)]),
                   serve_demand_fn=lambda: [dict(req)])
    # the replica's lease already shows as pending node demand: one
    # launch, not two
    assert a.step()["launched"] == ["tpu-host"]


def test_serve_demand_failure_never_fails_step():
    from ray_tpu.autoscaler.autoscaler import (Autoscaler,
                                               AutoscalerConfig)

    def boom():
        raise RuntimeError("controller gone")

    a = Autoscaler(AutoscalerConfig(node_types={}), _RecordingProvider(),
                   nodes_fn=_head_node, serve_demand_fn=boom)
    assert a.step()["launched"] == []


def test_autoscaler_backoff_escalates_and_caps():
    from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
    cfg = AutoscalerConfig(node_types={}, upscale_interval_s=2.0,
                           max_backoff_s=30.0)
    a = Autoscaler(cfg, _RecordingProvider(), nodes_fn=_head_node)
    assert a._step_delay(0) == 2.0
    assert a._step_delay(1) == 4.0
    assert a._step_delay(2) == 8.0
    assert a._step_delay(4) == 30.0       # capped
    assert a._step_delay(50) == 30.0      # and never overflows


def test_autoscaler_run_counts_failures_and_backs_off():
    from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
    from ray_tpu.util import metrics as metrics_mod

    calls = []

    def bad_nodes():
        calls.append(time.monotonic())
        raise RuntimeError("gcs down")

    cfg = AutoscalerConfig(node_types={}, upscale_interval_s=0.01,
                           max_backoff_s=0.05)
    a = Autoscaler(cfg, _RecordingProvider(), nodes_fn=bad_nodes)

    def counter_value():
        for m in metrics_mod.registry_snapshot():
            if m["name"] == "autoscaler_step_failures":
                return sum(v for _, v in m["samples"])
        return 0.0

    before = counter_value()
    stop = threading.Event()
    th = threading.Thread(target=a.run, args=(stop,), daemon=True)
    th.start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and len(calls) < 5:
        time.sleep(0.01)
    stop.set()
    th.join(timeout=5)
    assert len(calls) >= 5
    assert a._consecutive_failures >= 5
    assert counter_value() - before >= 5
    # consecutive failures spaced out: later gaps reach the cap instead
    # of hot-looping at the base interval
    gaps = [b - a_ for a_, b in zip(calls, calls[1:])]
    assert max(gaps) >= 0.04


# --------------------------------------------------------------------------
# scheduler / engine drain mode (tentpole b: admission stops)
# --------------------------------------------------------------------------

def test_scheduler_drain_mode_refuses_new_finishes_queued():
    from ray_tpu.inference.scheduler import Request, Scheduler
    s = Scheduler(n_slots=2, prefill_budget=8, chunk_size=4)
    h1 = s.submit(Request(tokens=[1, 2, 3], max_new_tokens=4))
    s.begin_drain()
    assert s.draining and not s.drained()
    with pytest.raises(RuntimeError, match="draining"):
        s.submit(Request(tokens=[4, 5], max_new_tokens=4))
    # the already-queued request still admits and runs to completion
    chunks = s.plan_prefill()
    assert chunks and chunks[0].state.handle is h1
    s.prefill_done(chunks[0].state, first_token=7, now=time.monotonic())
    st = chunks[0].state
    for tok in (8, 9, 10):
        s.decode_emit(st, tok, time.monotonic())
    assert h1.tokens() == [7, 8, 9, 10]
    assert s.drained()


def _tiny_llm_config():
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig
    return TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=512, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)


def test_engine_drain_finishes_inflight_and_refuses_new():
    from ray_tpu.inference import LLMDeployment
    dep = LLMDeployment(_tiny_llm_config(), n_slots=2, max_len=256,
                        prefill_chunk=8, prefill_budget=16)
    try:
        gen = dep([1, 2, 3, 4], max_new_tokens=8)
        # direct calls yield coalesced chunks (the first is the eager
        # single-token flush); flatten for token counting
        got = [next(gen), next(gen)]
        dep.begin_drain()
        assert dep.drain_status()["draining"]
        with pytest.raises(RuntimeError, match="draining"):
            dep.engine.submit([5, 6], max_new_tokens=4)
        got.extend(gen)                   # in-flight stream completes
        assert sum(len(c) for c in got) == 8
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if dep.drain_status()["pending"] == 0:
                break
            time.sleep(0.02)
        assert dep.drain_status() == {"draining": True, "pending": 0}
    finally:
        dep.engine.stop()


def test_llm_resume_tokens_continue_exactly():
    """The resume contract: prompt + delivered tokens re-prefill (one
    chunked admission) and the continuation is greedy-identical to the
    uninterrupted stream — the exactly-once guarantee the handle's
    re-route depends on."""
    from ray_tpu.inference import LLMDeployment
    assert LLMDeployment.__serve_resumable__
    dep = LLMDeployment(_tiny_llm_config(), n_slots=2, max_len=256,
                        prefill_chunk=8, prefill_budget=16)
    try:
        full = dep.generate([1, 2, 3, 4], max_new_tokens=12)
        assert len(full) == 12
        resumed = [t for chunk in dep([1, 2, 3, 4], max_new_tokens=12,
                                      resume_tokens=full[:5])
                   for t in chunk]       # flatten coalesced chunks
        assert resumed == full[5:]
        # everything already delivered -> empty continuation, no slot
        assert list(dep([1, 2, 3, 4], max_new_tokens=12,
                        resume_tokens=full)) == []
    finally:
        dep.engine.stop()


# --------------------------------------------------------------------------
# handle: streaming re-route / resume state machine (satellite 1)
# --------------------------------------------------------------------------

class _StubGen:
    """Stands in for the core ObjectRefGenerator: yields canned items,
    then optionally dies."""

    def __init__(self, items, fail_after=None, error=None):
        self._items = list(items)
        self._i = 0
        self._fail_after = fail_after
        self._error = error
        self.closed = False

    def next(self, timeout=None):
        if self._fail_after is not None and self._i >= self._fail_after:
            raise self._error
        if self._i >= len(self._items):
            raise StopIteration
        v = self._items[self._i]
        self._i += 1
        return v

    def close(self):
        self.closed = True


def _wrap(stub, **kw):
    from ray_tpu.serve.handle import DeploymentResponseGenerator
    g = DeploymentResponseGenerator(stub, None, 0, **kw)
    g._get = lambda ref: ref      # stub items are already values
    return g


def test_stream_resume_resumable_continues_with_delivered_chunks():
    seen = {}

    def resume(delivered, chunks):
        seen["delivered"] = delivered
        seen["chunks"] = list(chunks)
        return _wrap(_StubGen([2, 3, 4])), 0

    g = _wrap(_StubGen([0, 1], fail_after=2,
                       error=ray_tpu.ActorDiedError("replica gone")),
              resume=resume, record_chunks=True)
    assert list(g) == [0, 1, 2, 3, 4]
    assert seen == {"delivered": 2, "chunks": [0, 1]}


def test_stream_resume_nonresumable_skips_delivered_chunks():
    def resume(delivered, chunks):
        assert chunks is None         # non-resumable: count-only dedupe
        return _wrap(_StubGen([0, 1, 2, 3, 4])), delivered

    g = _wrap(_StubGen([0, 1, 2], fail_after=3,
                       error=ray_tpu.ActorDiedError("replica gone")),
              resume=resume)
    # restart re-produces everything; the wrapper drops the 3 duplicates
    assert list(g) == [0, 1, 2, 3, 4]


def test_stream_resume_is_one_shot():
    def resume(delivered, chunks):
        return _wrap(_StubGen([1], fail_after=1,
                              error=ray_tpu.ActorDiedError("again"))), 0

    g = _wrap(_StubGen([0], fail_after=1,
                       error=ray_tpu.ActorDiedError("first")),
              resume=resume)
    assert next(g) == 0
    assert next(g) == 1
    with pytest.raises(ray_tpu.ActorDiedError):
        next(g)                       # second death: no second resume


def test_stream_app_errors_do_not_trigger_resume():
    def resume(delivered, chunks):
        raise AssertionError("must not re-route an application error")

    g = _wrap(_StubGen([0], fail_after=1, error=ValueError("user bug")),
              resume=resume)
    assert next(g) == 0
    with pytest.raises(ValueError, match="user bug"):
        next(g)


def test_stream_resume_surfaces_original_death_when_retry_fails():
    def resume(delivered, chunks):
        raise RuntimeError("no replicas")

    g = _wrap(_StubGen([], fail_after=0,
                       error=ray_tpu.ActorDiedError("original")),
              resume=resume)
    with pytest.raises(ray_tpu.ActorDiedError, match="original"):
        next(g)


# --------------------------------------------------------------------------
# preemption notice channel (tpu.py + replica watch)
# --------------------------------------------------------------------------

def test_check_preemption_notice_env_and_file(tmp_path, monkeypatch):
    from ray_tpu._private.accelerators import tpu as tpu_accel
    monkeypatch.delenv(tpu_accel.PREEMPT_TEST_ENV, raising=False)
    monkeypatch.delenv(tpu_accel.PREEMPT_TEST_FILE_ENV, raising=False)
    monkeypatch.setenv("RAY_TPU_DISABLE_GCE_METADATA", "1")
    assert not tpu_accel.check_preemption_notice()
    assert not tpu_accel.preemption_watch_enabled()
    marker = tmp_path / "preempt-notice"
    monkeypatch.setenv(tpu_accel.PREEMPT_TEST_FILE_ENV, str(marker))
    assert tpu_accel.preemption_watch_enabled()
    assert not tpu_accel.check_preemption_notice()
    marker.touch()
    assert tpu_accel.check_preemption_notice()
    monkeypatch.delenv(tpu_accel.PREEMPT_TEST_FILE_ENV)
    monkeypatch.setenv(tpu_accel.PREEMPT_TEST_ENV, "1")
    assert tpu_accel.check_preemption_notice()


class _DrainTracker:
    def __init__(self):
        self.drained = 0

    def __call__(self, x):
        return x

    def begin_drain(self):
        self.drained += 1

    def state(self):
        return self.drained


def test_replica_preemption_file_flips_draining(tmp_path, monkeypatch):
    import cloudpickle

    from ray_tpu.serve.replica import Replica
    marker = tmp_path / "preempt-notice"
    monkeypatch.setenv("RAY_TPU_TESTING_PREEMPT_FILE", str(marker))
    monkeypatch.setenv("RAY_TPU_PREEMPT_POLL_S", "0.02")
    r = Replica(cloudpickle.dumps(_DrainTracker), (), {}, False)
    assert r.get_runtime_state() == {"queue_len": 0, "draining": False}
    marker.touch()                    # the "notice" arrives
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if r.get_runtime_state()["draining"]:
            break
        time.sleep(0.02)
    assert r.get_runtime_state()["draining"]
    # the replica boundary now refuses new admissions (router-staleness
    # window) so the handle layer re-routes instead of erroring out
    from ray_tpu.serve.replica import ReplicaDrainingError
    with pytest.raises(ReplicaDrainingError):
        r.handle_request("state", (), {})
    # the notice reached the user callable exactly once (idempotent)
    assert r._callable.state() == 1
    r.begin_drain()
    assert r._callable.state() == 1


# --------------------------------------------------------------------------
# cluster tier: the real lifecycle (notice -> drain -> replace -> resume)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=6)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


@needs_cluster
def test_preempt_one_drains_replaces_with_zero_errors(ray_start):
    """Notice-based preemption: the in-flight stream completes on the
    draining replica, new requests land on the replacement, the client
    sees zero errors."""
    from ray_tpu.inference import LLMDeployment
    from ray_tpu.util.chaos import ServeReplicaKiller
    dep = serve.deployment(LLMDeployment, preempt_grace_s=30.0)
    serve.run(dep.bind(_tiny_llm_config(), n_slots=2, max_len=512,
                       prefill_chunk=8, prefill_budget=16),
              name="llm-preempt")
    h = serve.get_app_handle("llm-preempt")
    expected = list(h.options(stream=True).remote([1, 2, 3],
                                                  max_new_tokens=24))
    gen = h.options(stream=True).remote([1, 2, 3], max_new_tokens=24)
    got = [next(gen) for _ in range(3)]
    killer = ServeReplicaKiller("llm-preempt", "LLMDeployment")
    assert killer.preempt_one()
    got.extend(gen)                   # drained replica finishes the stream
    assert got == expected
    assert killer.wait_for_replacement(timeout_s=90, handle=h)
    # replacement serves new load; the drained replica is gone from the
    # routing table so nothing routes to the corpse
    assert list(h.options(stream=True).remote([1, 2, 3],
                                              max_new_tokens=24)) \
        == expected
    serve.delete("llm-preempt")


@needs_cluster
def test_stream_resumes_on_survivor_after_kill(ray_start):
    """Hard replica death mid-stream: the handle resubmits with
    resume_tokens and the client sees the exact greedy continuation —
    zero dropped, zero duplicated tokens."""
    from ray_tpu.inference import LLMDeployment
    from ray_tpu.util.chaos import ServeReplicaKiller
    dep = serve.deployment(LLMDeployment, num_replicas=2)
    serve.run(dep.bind(_tiny_llm_config(), n_slots=2, max_len=512,
                       prefill_chunk=8, prefill_budget=16),
              name="llm-resume")
    h = serve.get_app_handle("llm-resume")
    expected = list(h.options(stream=True).remote([5, 6, 7],
                                                  max_new_tokens=32))
    assert len(expected) == 32
    killer = ServeReplicaKiller("llm-resume", "LLMDeployment")
    gen = h.options(stream=True).remote([5, 6, 7], max_new_tokens=32)
    got = [next(gen) for _ in range(4)]
    assert killer.kill_one(prefer_busy=True)
    got.extend(gen)                   # resumes on the survivor
    assert got == expected
    assert killer.wait_for_replacement(timeout_s=90, min_running=2,
                                       handle=h)
    serve.delete("llm-resume")
