"""Tuned-examples-style regression gates: each config must hit a reward
threshold within a step budget (reference: rllib/tuned_examples/ppo/ +
rllib/tests/run_regression_tests.py — pass = stop-reward reached).

Covers the three module families: MLP/discrete (CartPole), Gaussian/
continuous (Pendulum), CNN/discrete (the built-in GridTarget pixel env).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rl import AlgorithmConfig


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield
    ray_tpu.shutdown()


def _run_until(config, stop_reward, max_iters, patience_improve=None):
    algo = config.build()
    best, first = -np.inf, None
    try:
        for i in range(max_iters):
            r = algo.train()["episode_return_mean"]
            if r is None:
                continue
            first = r if first is None else first
            best = max(best, r)
            if best >= stop_reward:
                break
    finally:
        algo.stop()
    return first, best


def test_ppo_cartpole_threshold(ray_start):
    """Discrete/MLP gate (reference: tuned_examples/ppo/cartpole_ppo.py,
    stop reward 150 on a small budget)."""
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(train_batch_size=512, minibatch_size=128,
                        num_epochs=6, lr=3e-4, entropy_coeff=0.01))
    first, best = _run_until(config, stop_reward=150, max_iters=40)
    assert best >= 150, (first, best)


def test_ppo_pendulum_continuous_threshold(ray_start):
    """Continuous/Gaussian gate (reference:
    tuned_examples/ppo/pendulum_ppo.py). Random policy averages ~-1250;
    an improving Gaussian PPO reaches -1000 quickly."""
    config = (AlgorithmConfig()
              .environment("Pendulum-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                           rollout_fragment_length=128)
              .training(train_batch_size=2048, minibatch_size=256,
                        num_epochs=10, lr=1e-3, entropy_coeff=0.0,
                        gamma=0.95, lambda_=0.95, clip_param=0.3,
                        vf_loss_coeff=0.5))
    first, best = _run_until(config, stop_reward=-1000, max_iters=45)
    assert best >= -1000, (first, best)


def test_ppo_pixel_env_conv_threshold(ray_start):
    """CNN/discrete gate on the built-in pixel env: random play averages
    about -0.5 per episode; a learned policy clears +0.2."""
    config = (AlgorithmConfig()
              .environment("ray_tpu/GridTarget-v0")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                           rollout_fragment_length=64)
              .training(train_batch_size=1024, minibatch_size=256,
                        num_epochs=6, lr=1e-3, entropy_coeff=0.01,
                        gamma=0.95))
    first, best = _run_until(config, stop_reward=0.2, max_iters=30)
    assert best >= 0.2, (first, best)


def test_sac_pendulum_threshold(ray_start):
    """SAC gate (reference: tuned_examples/sac/pendulum_sac.py) —
    off-policy continuous control; far more sample-efficient than PPO,
    so the budget is a handful of iterations."""
    from ray_tpu.rl.sac import SAC
    config = (AlgorithmConfig()
              .environment("Pendulum-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(minibatch_size=128, lr=3e-4, gamma=0.99,
                        tau=0.005, updates_per_step=1.0))
    algo = SAC(config)
    best, first = -np.inf, None
    try:
        for _ in range(45):
            r = algo.train()["episode_return_mean"]
            if r is None:
                continue
            first = r if first is None else first
            best = max(best, r)
            if best >= -900:
                break
    finally:
        algo.stop()
    assert best >= -900, (first, best)


def test_multi_learner_same_schedule(ray_start):
    """n=2 learners must run the identical epoch/minibatch schedule as
    n=1 (round-3 weakness: n>1 silently did ONE grad step per update)
    and still learn CartPole."""
    config = (AlgorithmConfig()
              .environment("CartPole-v1")
              .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                           rollout_fragment_length=64)
              .training(train_batch_size=512, minibatch_size=128,
                        num_epochs=4, lr=3e-4, entropy_coeff=0.01)
              .learners(num_learners=2))
    algo = config.build()
    try:
        result = algo.train()
        # schedule: epochs * (shard_rows // mb) applied updates
        # shard = 512/2 = 256 rows -> 2 minibatches -> 4 epochs * 2 = 8
        assert result["num_minibatch_updates"] == 8, result
        best = -np.inf
        for _ in range(14):
            r = algo.train()["episode_return_mean"]
            if r is not None:
                best = max(best, r)
        assert best > 50, best
    finally:
        algo.stop()
