"""Serve tests: deployments, scaling, composition, batching, HTTP ingress
(reference: python/ray/serve/tests/ shapes — controller+replicas on a local
cluster, hit over handle and HTTP)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


def test_basic_deployment(ray_start):
    @serve.deployment
    class Greeter:
        def __call__(self, name):
            return f"hello {name}"

    handle = serve.run(Greeter.bind(), name="greet", route_prefix=None)
    assert handle.remote("world").result(timeout=30) == "hello world"


def test_function_deployment(ray_start):
    @serve.deployment
    def double(x):
        return x * 2

    handle = serve.run(double.bind(), name="fn", route_prefix=None)
    assert handle.remote(21).result(timeout=30) == 42


def test_multi_replica_distribution(ray_start):
    @serve.deployment(num_replicas=3)
    class Which:
        def __init__(self):
            import os
            self.pid = os.getpid()

        def __call__(self, _):
            return self.pid

    handle = serve.run(Which.bind(), name="which", route_prefix=None)
    pids = {handle.remote(None).result(timeout=30) for _ in range(30)}
    assert len(pids) >= 2   # P2C spreads across replicas


def test_composition(ray_start):
    @serve.deployment
    class Preprocess:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Model:
        def __init__(self, pre):
            self.pre = pre

        def __call__(self, x):
            y = self.pre.remote(x)
            return y.result(timeout=30) * 10

    app = Model.bind(Preprocess.bind())
    handle = serve.run(app, name="composed", route_prefix=None)
    assert handle.remote(4).result(timeout=30) == 50


def test_method_calls(ray_start):
    @serve.deployment
    class Calc:
        def add(self, a, b):
            return a + b

        def mul(self, a, b):
            return a * b

    handle = serve.run(Calc.bind(), name="calc", route_prefix=None)
    assert handle.add.remote(2, 3).result(timeout=30) == 5
    assert handle.mul.remote(2, 3).result(timeout=30) == 6


def test_batching(ray_start):
    @serve.deployment
    class Batched:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
        async def handle_batch(self, items):
            return [(x, len(items)) for x in items]

        async def __call__(self, x):
            return await self.handle_batch(x)

    handle = serve.run(Batched.bind(), name="batched", route_prefix=None)
    resps = [handle.remote(i) for i in range(8)]
    outs = [r.result(timeout=30) for r in resps]
    assert [o[0] for o in outs] == list(range(8))
    assert max(o[1] for o in outs) > 1   # some calls actually batched


def test_status_and_scale_update(ray_start):
    @serve.deployment(num_replicas=1)
    class S:
        def __call__(self, _):
            return 1

    serve.run(S.bind(), name="scaled", route_prefix=None)
    st = serve.status()["scaled"]["S"]
    assert st["running"] == 1
    serve.run(S.options(num_replicas=2).bind(), name="scaled",
              route_prefix=None)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        st = serve.status()["scaled"]["S"]
        if st["running"] == 2:
            break
        time.sleep(0.3)
    assert st["running"] == 2


def test_http_ingress(ray_start):
    @serve.deployment
    class Echo:
        def __call__(self, payload):
            return {"got": payload}

    serve.run(Echo.bind(), name="http_app", route_prefix="/echo",
              _http=True, http_port=18231)
    import json
    import urllib.request
    req = urllib.request.Request(
        "http://127.0.0.1:18231/echo",
        data=json.dumps({"a": 1}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        body = json.loads(resp.read())
    assert body == {"got": {"a": 1}}


def test_model_multiplexing(ray_start):
    """Many model ids share a replica pool with per-replica LRU caches and
    sticky routing (reference: serve/multiplex.py)."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            self.loads.append(model_id)
            return {"id": model_id, "scale": int(model_id[-1])}

        async def __call__(self, x):
            model_id = serve.get_multiplexed_model_id()
            model = await self.get_model(model_id)
            return x * model["scale"]

        def load_count(self):
            return len(self.loads)

    app = MultiModel.bind()
    serve.run(app, name="mux")
    try:
        h = serve.get_app_handle("mux")
        h2 = h.options(multiplexed_model_id="m2")
        h3 = h.options(multiplexed_model_id="m3")
        assert h2.remote(10).result(timeout=60) == 20
        assert h3.remote(10).result(timeout=60) == 30
        # repeated calls hit the cached model on the same replica: total
        # loads across replicas stays at 2
        for _ in range(6):
            assert h2.remote(1).result(timeout=60) == 2
            assert h3.remote(1).result(timeout=60) == 3
        import ray_tpu
        total_loads = sum(
            ray_tpu.get(r.handle_request.remote("load_count", (), {}),
                        timeout=30)
            for r in h._router.replicas)
        assert total_loads == 2, total_loads
    finally:
        serve.delete("mux")
