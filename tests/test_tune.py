"""Tune tests: grid/random search, best-result selection, ASHA early
stopping (reference: python/ray/tune/tests/test_tune_* shapes)."""

import time

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner, grid_search


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_grid_search(ray_start):
    def trainable(config):
        tune.report(score=config["a"] * 10 + config["b"])

    tuner = Tuner(trainable,
                  param_space={"a": grid_search([1, 2, 3]),
                               "b": grid_search([0, 5])})
    results = tuner.fit()
    assert len(results) == 6
    best = results.get_best_result("score", mode="max")
    assert best.config == {"a": 3, "b": 5}
    assert best.metrics["score"] == 35


def test_random_search(ray_start):
    def trainable(config):
        tune.report(val=config["x"])

    tuner = Tuner(trainable,
                  param_space={"x": tune.uniform(0, 1)},
                  tune_config=TuneConfig(num_samples=5, seed=42))
    results = tuner.fit()
    assert len(results) == 5
    vals = [r.metrics["val"] for r in results]
    assert all(0 <= v <= 1 for v in vals)
    assert len(set(vals)) == 5


def test_asha_scheduler_unit():
    """Deterministic halving semantics, incl. reports that stride past
    rung values (first-result-at-or-past-rung evaluation)."""
    from ray_tpu.tune.schedulers import CONTINUE, STOP
    sched = ASHAScheduler(metric="acc", mode="max", max_t=100,
                          grace_period=4, reduction_factor=2)
    # two good trials seed rung 4 high (the later one leads, so both pass
    # the top-1/rf cut)
    assert sched.on_result("good1", {"training_iteration": 4,
                                     "acc": 10.0}) == CONTINUE
    assert sched.on_result("good2", {"training_iteration": 4,
                                     "acc": 11.0}) == CONTINUE
    # bad trial reporting on a stride (3, 6 — never exactly 4) must still
    # be evaluated at rung 4 and cut
    assert sched.on_result("bad", {"training_iteration": 3,
                                   "acc": 0.1}) == CONTINUE
    assert sched.on_result("bad", {"training_iteration": 6,
                                   "acc": 0.2}) == STOP
    # max_t stops unconditionally
    assert sched.on_result("good1", {"training_iteration": 100,
                                     "acc": 99.0}) == STOP


def test_asha_integration(ray_start):
    def trainable(config):
        for step in range(20):
            tune.report(acc=config["lr"] * (step + 1))
            time.sleep(0.05)

    tuner = Tuner(
        trainable,
        param_space={"lr": grid_search([0.01, 0.02, 1.0, 2.0])},
        tune_config=TuneConfig(
            scheduler=ASHAScheduler(metric="acc", mode="max", max_t=20,
                                    grace_period=4, reduction_factor=2)))
    results = tuner.fit()
    assert len(results) == 4
    best = results.get_best_result("acc", mode="max")
    assert best.config["lr"] == 2.0


def test_trial_error_captured(ray_start):
    def trainable(config):
        if config["boom"]:
            raise RuntimeError("exploded")
        tune.report(ok=1)

    tuner = Tuner(trainable,
                  param_space={"boom": grid_search([False, True])})
    results = tuner.fit()
    errs = [r for r in results if r.error]
    oks = [r for r in results if not r.error]
    assert len(errs) == 1 and "exploded" in errs[0].error
    assert len(oks) == 1 and oks[0].metrics["ok"] == 1


def test_median_stopping_rule(ray_start):
    """Bad trials stop early under the median rule."""
    from ray_tpu import tune
    from ray_tpu.tune import MedianStoppingRule

    def trainable(config):
        import time as _t
        for i in range(12):
            tune.report({"score": config["quality"] * (i + 1)})
            _t.sleep(0.05)

    tuner = tune.Tuner(
        trainable,
        param_space={"quality": tune.grid_search([0.1, 0.2, 1.0, 2.0])},
        tune_config=tune.TuneConfig(
            scheduler=MedianStoppingRule(metric="score", grace_period=3),
            max_concurrent_trials=4),
    )
    grid = tuner.fit()
    best = grid.get_best_result("score")
    assert best.config["quality"] == 2.0
    # a bottom trial must have been cut before finishing all 12 reports
    shortest = min(len(r.history) for r in grid)
    assert shortest < 12


def test_pbt_exploit_and_checkpoint(ray_start):
    """A weak PBT trial adopts a strong trial's checkpointed weight and a
    mutated config."""
    from ray_tpu import tune
    from ray_tpu.tune import PopulationBasedTraining

    def trainable(config):
        import time as _t
        ckpt = tune.get_checkpoint()
        weight = ckpt["weight"] if ckpt else 0.0
        for _ in range(20):
            weight += config["lr"]
            tune.report({"score": weight}, checkpoint={"weight": weight})
            _t.sleep(0.25)

    pbt = PopulationBasedTraining(
        metric="score", perturbation_interval=4, quantile_fraction=0.5,
        hyperparam_mutations={"lr": [0.1, 0.5, 1.0, 2.0]})
    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.01, 0.02, 1.0, 2.0])},
        tune_config=tune.TuneConfig(scheduler=pbt,
                                    max_concurrent_trials=4),
    )
    grid = tuner.fit()
    best = grid.get_best_result("score")
    assert best.metrics["score"] > 10.0   # strong configs dominate
    # every trial ends with a meaningful score: weak ones exploited into
    # high-weight checkpoints or kept compounding a strong lr
    final_scores = sorted(r.metrics.get("score", 0.0) for r in grid)
    assert final_scores[0] > 1.0, final_scores


def test_tpe_search(ray_start):
    """Native TPE beats its own random warmup on a smooth objective
    (reference: the Optuna/HyperOpt search-algorithm integrations)."""
    from ray_tpu.tune.search import TPESearch

    def objective(config):
        x = config["x"]
        bonus = 0.0 if config["kind"] == "good" else 2.0
        tune.report({"loss": (x - 3.0) ** 2 + bonus})

    space = {"x": tune.uniform(-10.0, 10.0),
             "kind": tune.choice(["good", "bad"])}
    alg = TPESearch(space, metric="loss", mode="min", n_initial=8, seed=7)
    tuner = tune.Tuner(
        objective, param_space=space,
        tune_config=tune.TuneConfig(num_samples=32, metric="loss",
                                    mode="min", search_alg=alg,
                                    max_concurrent_trials=2))
    grid = tuner.fit()
    best = grid.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] < 1.0, best.metrics
    assert best.config["kind"] == "good"
    # the model phase concentrated samples near the optimum: the best of
    # the suggested (post-warmup) trials beats the random warmup's best
    ordered = sorted(grid, key=lambda r: r.trial_id)
    warmup = ordered[:8]
    suggested = ordered[8:]
    best_warm = min(r.metrics["loss"] for r in warmup if r.metrics)
    best_sugg = min(r.metrics["loss"] for r in suggested if r.metrics)
    assert best_sugg <= best_warm + 1e-9
