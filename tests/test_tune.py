"""Tune tests: grid/random search, best-result selection, ASHA early
stopping (reference: python/ray/tune/tests/test_tune_* shapes)."""

import time

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner, grid_search


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_grid_search(ray_start):
    def trainable(config):
        tune.report(score=config["a"] * 10 + config["b"])

    tuner = Tuner(trainable,
                  param_space={"a": grid_search([1, 2, 3]),
                               "b": grid_search([0, 5])})
    results = tuner.fit()
    assert len(results) == 6
    best = results.get_best_result("score", mode="max")
    assert best.config == {"a": 3, "b": 5}
    assert best.metrics["score"] == 35


def test_random_search(ray_start):
    def trainable(config):
        tune.report(val=config["x"])

    tuner = Tuner(trainable,
                  param_space={"x": tune.uniform(0, 1)},
                  tune_config=TuneConfig(num_samples=5, seed=42))
    results = tuner.fit()
    assert len(results) == 5
    vals = [r.metrics["val"] for r in results]
    assert all(0 <= v <= 1 for v in vals)
    assert len(set(vals)) == 5


def test_asha_early_stopping(ray_start):
    def trainable(config):
        for step in range(20):
            # bad configs plateau low; good ones improve
            tune.report(acc=config["lr"] * (step + 1))
            time.sleep(0.02)

    tuner = Tuner(
        trainable,
        param_space={"lr": grid_search([0.01, 0.02, 1.0, 2.0])},
        tune_config=TuneConfig(
            scheduler=ASHAScheduler(metric="acc", mode="max", max_t=20,
                                    grace_period=4, reduction_factor=2)))
    results = tuner.fit()
    assert len(results) == 4
    best = results.get_best_result("acc", mode="max")
    assert best.config["lr"] == 2.0
    # at least one poor trial stopped early
    iters = {r.config["lr"]: len(r.history) for r in results}
    assert min(iters.values()) < 20


def test_trial_error_captured(ray_start):
    def trainable(config):
        if config["boom"]:
            raise RuntimeError("exploded")
        tune.report(ok=1)

    tuner = Tuner(trainable,
                  param_space={"boom": grid_search([False, True])})
    results = tuner.fit()
    errs = [r for r in results if r.error]
    oks = [r for r in results if not r.error]
    assert len(errs) == 1 and "exploded" in errs[0].error
    assert len(oks) == 1 and oks[0].metrics["ok"] == 1
