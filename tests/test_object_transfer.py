"""Push-based object transfer: request-push streaming, admission-controlled
pulls, and binomial-tree broadcast across a multi-node local cluster
(reference: src/ray/object_manager/pull_manager.h:52, push_manager.h:30)."""

import numpy as np
import pytest

import ray_tpu
import ray_tpu.experimental
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 1,
                                "object_store_memory": 256 * 1024 * 1024})
    workers = [c.add_node(num_cpus=1,
                          object_store_memory=256 * 1024 * 1024)
               for _ in range(3)]
    ray_tpu.init(address=c.address)
    yield c, workers
    ray_tpu.shutdown()
    c.shutdown()


def test_one_object_feeds_remote_tasks(cluster):
    """One put object consumed by tasks pinned across remote nodes: each
    node pulls (via request-push) once, every task sees the same bytes."""
    c, workers = cluster
    blob = np.arange(6_000_000, dtype=np.float64)     # 48 MB
    ref = ray_tpu.put(blob)

    @ray_tpu.remote(num_cpus=1)
    def consume(x):
        return float(x.sum()), ray_tpu.get_runtime_context()["node_id"]

    outs = ray_tpu.get([consume.remote(ref) for _ in range(3)], timeout=120)
    assert all(abs(s - float(blob.sum())) < 1e-6 for s, _ in outs)
    # the three 1-CPU tasks must have spread over the cluster
    assert len({n for _, n in outs}) >= 2


def test_broadcast_object_tree(cluster):
    """Owner-directed broadcast lands the object in every target node's
    store without any consumer task requesting it."""
    c, workers = cluster
    blob = np.ones(4_000_000, dtype=np.float64)       # 32 MB
    ref = ray_tpu.put(blob)
    import ray_tpu._private.worker as wm
    view = wm.global_worker.gcs_call("get_cluster_view")
    targets = [nid for nid in view
               if nid != wm.global_worker.core.node_id]
    assert len(targets) == 3
    ray_tpu.experimental.broadcast_object(ref, targets)

    # every target node's manager now serves the object locally
    for nid in targets:
        meta = wm.global_worker._run(
            wm.global_worker.core.pool.call(
                view[nid]["address"], "fetch_object", oid=ref.id,
                part="meta"))
        assert meta is not None and meta["data_size"] == blob.nbytes


def test_transfers_rode_the_data_plane(cluster):
    """The transfers the earlier tests performed moved their chunk bytes
    on the binary data plane, not the control RPC connection: every node
    advertises a data-plane address and the receivers' data-plane
    counters account for at least one full object's bytes."""
    c, workers = cluster
    import ray_tpu._private.worker as wm
    w = wm.global_worker
    view = w.gcs_call("get_cluster_view")
    assert all(v.get("data_plane_address") for v in view.values())
    infos = [w._run(w.core.pool.call(v["address"], "get_node_info"))
             for v in view.values()]
    stats = [i.get("data_plane") for i in infos]
    assert all(s is not None for s in stats)
    # test_broadcast_object_tree alone pushed a 32 MB object to 3 nodes
    assert sum(s["bytes_in"] for s in stats) >= 32_000_000
    assert sum(s["chunks_in"] for s in stats) > 0
    assert all(s["receiving"] == 0 for s in stats)


def test_pull_admission_bounds_inflight(cluster):
    """With a tiny admission budget, many concurrent pulls of distinct
    objects still complete (queued, not deadlocked) and memory stays
    bounded by budget + one object."""
    c, workers = cluster
    from ray_tpu._private.config import cfg
    refs = [ray_tpu.put(np.full(1_000_000, i, dtype=np.float64))
            for i in range(6)]                         # 6 x 8 MB

    @ray_tpu.remote(num_cpus=1)
    def consume_all(*xs):
        return sum(float(x[0]) for x in xs)

    # target one remote node so all six pulls land on it concurrently
    out = ray_tpu.get(consume_all.remote(*refs), timeout=120)
    assert out == sum(range(6))
