"""pip runtime environments: hashed cached venvs per spec (reference:
python/ray/_private/runtime_env/pip.py). Zero-egress CI installs a LOCAL
package instead of a PyPI one — same machinery, no network.
"""

import os
import textwrap

import pytest

import ray_tpu


@pytest.fixture
def local_pkg(tmp_path):
    pkg = tmp_path / "tinypkg"
    (pkg / "tinypkg_rt").mkdir(parents=True)
    (pkg / "tinypkg_rt" / "__init__.py").write_text(
        "MAGIC = 'runtime-env-pip-works'\n")
    (pkg / "pyproject.toml").write_text(textwrap.dedent("""
        [build-system]
        requires = ["setuptools"]
        build-backend = "setuptools.build_meta"
        [project]
        name = "tinypkg-rt"
        version = "0.0.1"
        [tool.setuptools]
        packages = ["tinypkg_rt"]
    """))
    return str(pkg)


def test_pip_runtime_env_task(local_pkg):
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"pip": [local_pkg]})
        def uses_pkg():
            import tinypkg_rt
            return tinypkg_rt.MAGIC

        assert ray_tpu.get(uses_pkg.remote(), timeout=180) == \
            "runtime-env-pip-works"

        # outside the runtime env the package must NOT be importable
        @ray_tpu.remote
        def without_pkg():
            try:
                import tinypkg_rt  # noqa: F401
                return "leaked"
            except ImportError:
                return "clean"

        assert ray_tpu.get(without_pkg.remote(), timeout=60) == "clean"
    finally:
        ray_tpu.shutdown()


def test_pip_runtime_env_actor(local_pkg):
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"pip": [local_pkg]})
        class Uses:
            def magic(self):
                import tinypkg_rt
                return tinypkg_rt.MAGIC

        a = Uses.remote()
        assert ray_tpu.get(a.magic.remote(), timeout=180) == \
            "runtime-env-pip-works"
    finally:
        ray_tpu.shutdown()
