"""Binary data plane for cross-node object transfer (data_plane.py +
node_manager.py): raw-socket chunk streaming with striping, ack-window
flow control, mid-stream abort, msgpack fallback negotiation — plus the
headline property the second socket exists for: the control plane stays
responsive (heartbeats, leases, pings) while multi-hundred-MB pushes
stream.

The unit tier drives a real DataPlaneServer/DataPlaneClient pair over
loopback against a fake node manager (plain bytearray receive regions),
so it runs on any interpreter; the cluster tier needs the Python 3.12
store runtime like every other multi-node suite."""

import asyncio
import socket
import sys
import threading
import time

import pytest

from ray_tpu._private import data_plane as dp
from ray_tpu._private.config import cfg

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")

OID = b"\x01" * 20
OID2 = b"\x02" * 20


@pytest.fixture(autouse=True)
def _small_chunks():
    """Small chunks/stripes so a few-MB unit payload exercises striping,
    windowing, and multi-chunk acks."""
    cfg.set("transfer_chunk_bytes", 128 * 1024)
    cfg.set("transfer_streams", 2)
    cfg.set("transfer_stripe_min_bytes", 64 * 1024)
    yield
    for k in ("transfer_chunk_bytes", "transfer_streams",
              "transfer_stripe_min_bytes"):
        cfg.reset(k)


class FakeNM:
    """Duck-typed stand-in for NodeManager's receive bookkeeping: the
    data-plane server only touches `_receiving`, `_finish_receive`, and
    `_abort_receive`."""

    def __init__(self):
        self._receiving = {}
        self.finished = []
        self.aborted = []
        self.relay_result = True     # or a Future to emulate relay await

    def begin(self, oid: bytes, size: int) -> bytearray:
        buf = bytearray(size)
        self._receiving[oid] = {"data": memoryview(buf), "remaining": size,
                                "relay": [], "t": time.monotonic()}
        return buf

    def _finish_receive(self, oid: bytes):
        self._receiving.pop(oid)
        self.finished.append(oid)
        return self.relay_result

    def _abort_receive(self, oid: bytes, reason: str):
        self._receiving.pop(oid, None)
        self.aborted.append((oid, reason))


async def _start_pair():
    nm = FakeNM()
    server = dp.DataPlaneServer(nm)
    addr = await server.start("127.0.0.1")
    client = dp.DataPlaneClient()
    return nm, server, addr, client


def test_stripe_ranges_cover_and_bound():
    for size in (0, 1, 100, 1 << 20, (1 << 20) + 7):
        for streams in (1, 2, 4):
            ranges = dp.stripe_ranges(size, streams, 64 * 1024)
            assert len(ranges) <= max(1, streams)
            # contiguous, complete, in order
            off = 0
            for start, length in ranges:
                assert start == off
                off += length
            assert off == max(size, 0)
    # small objects never fan out
    assert len(dp.stripe_ranges(10, 8, 64 * 1024)) == 1
    # big objects use every stream
    assert len(dp.stripe_ranges(1 << 22, 4, 64 * 1024)) == 4


def test_loopback_striped_transfer():
    """3 MB across 2 stripes of 128 KB chunks lands byte-exact in the
    receive region, with per-stripe byte counts summing to the size."""
    payload = bytes(range(256)) * (3 * 1024 * 1024 // 256)

    async def go():
        nm, server, addr, client = await _start_pair()
        try:
            buf = nm.begin(OID, len(payload))
            stripes = await client.push(addr, OID, memoryview(payload),
                                        len(payload))
            assert len(stripes) == 2
            assert sum(stripes) == len(payload)
            assert bytes(buf) == payload
            assert nm.finished == [OID]
            assert not nm._receiving
            assert server.bytes_in == len(payload)
            assert client.bytes_out == len(payload)
            assert server.chunks_in == client.chunks_out
            # pooled connections are reusable for a second transfer
            buf2 = nm.begin(OID2, len(payload))
            await client.push(addr, OID2, memoryview(payload),
                              len(payload))
            assert bytes(buf2) == payload
        finally:
            client.close()
            await server.close()

    asyncio.run(go())


def test_final_ack_waits_for_relay():
    """The completing chunk's ack resolves only after the receiver's
    relay future — the broadcast root's await covers the whole tree."""
    payload = b"x" * (256 * 1024)

    async def go():
        nm, server, addr, client = await _start_pair()
        try:
            loop = asyncio.get_event_loop()
            relay = loop.create_future()
            nm.relay_result = relay
            loop.call_later(0.3, relay.set_result, True)
            nm.begin(OID, len(payload))
            t0 = time.monotonic()
            await client.push(addr, OID, memoryview(payload), len(payload))
            assert time.monotonic() - t0 >= 0.25
        finally:
            client.close()
            await server.close()

    asyncio.run(go())


def test_push_without_receive_state_errors():
    """Chunks for an unknown/reaped oid are drained (framing stays in
    sync) and acked ABORTED — the sender must error, not silently skip."""
    payload = b"y" * (512 * 1024)

    async def go():
        nm, server, addr, client = await _start_pair()
        try:
            with pytest.raises(dp.DataPlaneError, match="aborted"):
                await client.push(addr, OID, memoryview(payload),
                                  len(payload))
            assert nm.finished == []
        finally:
            client.close()
            await server.close()

    asyncio.run(go())


def test_reap_mid_stream_aborts_sender():
    """A receive marked aborted mid-transfer (the idle-reap sweep) fails
    the push and releases the receive state exactly once."""
    payload = b"z" * (2 * 1024 * 1024)

    async def go():
        nm, server, addr, client = await _start_pair()
        try:
            st_buf = nm.begin(OID, len(payload))
            st = nm._receiving[OID]

            async def reaper():
                while server.bytes_in == 0:
                    await asyncio.sleep(0.001)
                st["aborted"] = True

            reap_task = asyncio.ensure_future(reaper())
            with pytest.raises(dp.DataPlaneError):
                await client.push(addr, OID, memoryview(payload),
                                  len(payload))
            await reap_task
            # the woken writer (or entry check) released the state
            for _ in range(100):
                if OID not in nm._receiving:
                    break
                await asyncio.sleep(0.01)
            assert OID not in nm._receiving
            assert nm.aborted and nm.aborted[0][0] == OID
            assert nm.finished == []
            del st_buf
        finally:
            client.close()
            await server.close()

    asyncio.run(go())


def test_unreachable_peer_is_unavailable():
    """No listener: DataPlaneUnavailable (zero bytes moved) so the
    caller can fall back to the msgpack path safely."""
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    async def go():
        client = dp.DataPlaneClient()
        try:
            with pytest.raises(dp.DataPlaneUnavailable):
                await client.push(f"tcp:127.0.0.1:{port}", OID,
                                  memoryview(b"abc"), 3)
        finally:
            client.close()

    asyncio.run(go())


# --------------------------------------------------------------- cluster


def _pct(samples, q=0.99):
    xs = sorted(samples)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def _ping_rtts(address, n, spacing=0.005):
    """RTTs of `n` control-plane pings over a dedicated connection in a
    dedicated event loop (so the driver's own loop contention can't
    contaminate the measurement)."""
    from ray_tpu._private import rpc

    async def go():
        conn = await rpc.connect(address, name="ping-probe")
        try:
            for _ in range(5):                       # warmup
                await conn.call("ping", timeout=30)
            rtts = []
            for _ in range(n):
                t0 = time.perf_counter()
                await conn.call("ping", timeout=30)
                rtts.append(time.perf_counter() - t0)
                await asyncio.sleep(spacing)
            return rtts
        finally:
            await conn.close()

    return asyncio.run(go())


@needs_cluster
def test_control_plane_responsive_during_bulk_transfer():
    """THE acceptance property: control-plane ping p99 to the receiving
    node manager during an active 256 MB push stays < 5x the idle p99.
    On the old path every 8 MB chunk was msgpack-decoded + copied on the
    RPC connection the pings share, head-of-line-blocking them for tens
    of ms; on the data plane the RPC socket carries only the pings."""
    import numpy as np

    import ray_tpu
    import ray_tpu.experimental
    from ray_tpu.cluster_utils import Cluster

    store = 768 * 1024 * 1024
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1,
                                      "object_store_memory": store})
    node = cluster.add_node(num_cpus=1, object_store_memory=store)
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes()
        import ray_tpu._private.worker as wm
        view = wm.global_worker.gcs_call("get_cluster_view")
        target_addr = view[node.node_id]["address"]
        assert view[node.node_id].get("data_plane_address"), \
            "node did not advertise a data plane"
        blob = np.ones(256 * 1024 * 1024, dtype=np.uint8)
        ref = ray_tpu.put(blob)

        idle = _ping_rtts(target_addr, 80)

        stop = threading.Event()
        errors = []

        def hammer():
            w = wm.global_worker
            try:
                while not stop.is_set():
                    ray_tpu.experimental.broadcast_object(
                        ref, [node.node_id])
                    w._run(w.core.node_conn.call(
                        "free_remote_object", oid=ref.id,
                        node_id=node.node_id), timeout=60)
            except Exception as e:                   # pragma: no cover
                errors.append(e)

        th = threading.Thread(target=hammer, daemon=True)
        th.start()
        time.sleep(0.5)             # transfers definitely streaming
        active = _ping_rtts(target_addr, 150)
        stop.set()
        th.join(timeout=120)
        assert not errors, errors

        idle_p99 = max(_pct(idle), 0.002)   # floor: sub-2ms p99 on a
        active_p99 = _pct(active)           # shared box is timer noise
        assert active_p99 < 5 * idle_p99, (
            f"control plane starved during bulk transfer: active p99 "
            f"{active_p99*1e3:.1f}ms vs idle p99 {idle_p99*1e3:.1f}ms")
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@needs_cluster
def test_pusher_death_mid_stripe_pull_retries():
    """Striped-transfer extension of the pusher-death reap path: the
    holder node dies mid-push, the receiver aborts the poisoned receive
    immediately (control-connection drop, not the 60s sweep), and a
    retry against the surviving holder completes the pull."""
    import numpy as np

    import ray_tpu
    import ray_tpu.experimental
    from ray_tpu.cluster_utils import Cluster

    store = 512 * 1024 * 1024
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1,
                                      "object_store_memory": store})
    n1 = cluster.add_node(num_cpus=1, object_store_memory=store)
    n2 = cluster.add_node(num_cpus=1, object_store_memory=store)
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes()
        import ray_tpu._private.worker as wm
        w = wm.global_worker
        view = w.gcs_call("get_cluster_view")
        head_id = cluster.nodes[0].node_id
        blob = np.ones(128 * 1024 * 1024, dtype=np.uint8)
        ref = ray_tpu.put(blob)
        # second holder: n1 (the node we will kill mid-push)
        ray_tpu.experimental.broadcast_object(ref, [n1.node_id])

        def pull_from(holder_id):
            return w._run(w.core.pool.call(
                view[n2.node_id]["address"], "pull_object", oid=ref.id,
                node_id=holder_id, timeout=120))

        result = {}

        def bg_pull():
            try:
                result["ok"] = pull_from(n1.node_id)
            except Exception as e:
                result["err"] = e

        th = threading.Thread(target=bg_pull, daemon=True)
        th.start()
        time.sleep(0.05)            # mid-stripe for a 128 MB object
        n1.kill()
        th.join(timeout=150)
        assert not th.is_alive(), "pull wedged after pusher death"

        if "err" in result:
            # the expected race outcome: retry on the surviving holder
            assert pull_from(head_id) is True
        meta = w._run(w.core.pool.call(
            view[n2.node_id]["address"], "fetch_object", oid=ref.id,
            part="meta", timeout=60))
        assert meta is not None and meta["data_size"] == blob.nbytes
        # no half-received state left pinning arena space
        info = w._run(w.core.pool.call(
            view[n2.node_id]["address"], "get_node_info", timeout=60))
        assert info["data_plane"]["receiving"] == 0
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@needs_cluster
def test_transfer_span_reports_stripes():
    """store.transfer flight-recorder spans carry the transport path,
    stream count, and per-stripe byte counts."""
    import numpy as np

    import ray_tpu
    import ray_tpu.experimental
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import state as state_api

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1,
                                      "object_store_memory": 256 * 1024 * 1024})
    node = cluster.add_node(num_cpus=1,
                            object_store_memory=256 * 1024 * 1024)
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes()
        blob = np.ones(32 * 1024 * 1024, dtype=np.uint8)
        ref = ray_tpu.put(blob)
        ray_tpu.experimental.broadcast_object(ref, [node.node_id])
        row = None
        for _ in range(100):        # recorder flushes on a ~1s cadence
            rows = [r for r in state_api.list_runtime_events(
                        category="store")
                    if r.get("name") == "store.transfer"]
            if rows:
                row = rows[-1]
                break
            time.sleep(0.2)
        assert row is not None, "no store.transfer span reached the GCS"
        attrs = row["attrs"]
        assert attrs["bytes"] == blob.nbytes
        assert attrs["path"] == "data_plane"
        assert attrs["streams"] >= 1
        assert sum(attrs["stripe_bytes"]) == blob.nbytes
        assert len(attrs["stripe_bytes"]) == attrs["streams"]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@needs_cluster
def test_msgpack_fallback_when_data_plane_disabled():
    """RAY_TPU_DATA_PLANE_ENABLED=0 for the whole daemon tree: no
    data-plane advertisement, transfers ride the legacy msgpack chunk
    path, and cross-node consumption still works."""
    import os

    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    os.environ["RAY_TPU_DATA_PLANE_ENABLED"] = "0"
    try:
        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 1,
                                          "object_store_memory": 256 * 1024 * 1024})
        node = cluster.add_node(num_cpus=1,
                                object_store_memory=256 * 1024 * 1024)
        ray_tpu.init(address=cluster.address)
        try:
            cluster.wait_for_nodes()
            import ray_tpu._private.worker as wm
            view = wm.global_worker.gcs_call("get_cluster_view")
            assert view[node.node_id].get("data_plane_address") is None
            blob = np.arange(2_000_000, dtype=np.float64)   # 16 MB

            @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
            def consume(x):
                return float(x.sum())

            ref = ray_tpu.put(blob)
            outs = ray_tpu.get([consume.remote(ref) for _ in range(2)],
                               timeout=120)
            assert all(abs(s - float(blob.sum())) < 1e-6 for s in outs)
            info = wm.global_worker._run(wm.global_worker.core.pool.call(
                view[node.node_id]["address"], "get_node_info",
                timeout=60))
            assert "data_plane" not in info
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()
    finally:
        os.environ.pop("RAY_TPU_DATA_PLANE_ENABLED", None)
