"""Serve operator surface: declarative deploy through the CLI
(`ray_tpu serve deploy/status/delete`) + typed protobuf servicers on the
gRPC proxy (reference: python/ray/serve/scripts.py `serve deploy`;
python/ray/serve/_private/proxy.py:558 gRPCProxy
grpc_servicer_functions)."""

import json

import pytest
import yaml

import ray_tpu
from ray_tpu import serve

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def ray_start():
    ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_typed_grpc_servicer(ray_start):
    """A hand-rolled protoc-shaped servicer registers on the proxy; rpc
    method names route to the deployment's same-named methods with typed
    payloads."""
    from ray_tpu.util.serve_grpc_demo import build_echo_app, echo_client

    serve.run(build_echo_app("svc"), name="typed", route_prefix=None)
    serve.start(
        grpc_port=0,
        grpc_servicer_functions=[
            "ray_tpu.util.serve_grpc_demo:add_EchoServicer_to_server"])
    addr = next(iter(serve.proxies().values()))["grpc"]
    assert echo_client(addr, "Echo", "hello", application="typed") \
        == "svc:hello"
    assert echo_client(addr, "Reverse", "abc", application="typed") \
        == "cba"
    serve.delete("typed")


def test_serve_cli_deploy_status_delete(ray_start, tmp_path):
    """serve deploy from YAML → status shows the app → delete removes
    it. The CLI runs in-process against the running cluster (the CLI
    functions are the product surface; process isolation is covered by
    the cluster-launcher tests)."""
    import io
    from contextlib import redirect_stdout

    from ray_tpu._private.worker import global_worker
    from ray_tpu.scripts import cli

    cfg = {
        "http_options": {"port": 18291},
        "applications": [{
            "name": "cliapp",
            "route_prefix": "/cliapp",
            "import_path": "ray_tpu.util.serve_grpc_demo:build_echo_app",
            "args": {"prefix": "cli"},
            "deployments": [{"name": "EchoDeployment",
                             "num_replicas": 2}],
        }],
    }
    cfg_path = tmp_path / "serve.yaml"
    cfg_path.write_text(yaml.safe_dump(cfg))

    class _A:
        config = str(cfg_path)
        address = global_worker.core.gcs_address

    out = io.StringIO()
    with redirect_stdout(out):
        cli.cmd_serve_deploy(_A())
    assert "deployed 1 application(s)" in out.getvalue()

    st = serve.status()
    assert st["cliapp"]["EchoDeployment"]["target"] == 2

    # HTTP ingress from the config's http_options
    import urllib.request
    req = urllib.request.Request(
        "http://127.0.0.1:18291/cliapp",
        data=json.dumps("ping").encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        body = json.loads(resp.read())
    assert body == {"echo": "ping", "prefix": "cli"}

    out = io.StringIO()
    with redirect_stdout(out):
        cli.cmd_serve_status(_A())
    parsed = json.loads(out.getvalue())
    assert "cliapp" in parsed["applications"]

    class _D:
        name = "cliapp"
        address = global_worker.core.gcs_address

    out = io.StringIO()
    with redirect_stdout(out):
        cli.cmd_serve_delete(_D())
    assert "cliapp" not in serve.status()
