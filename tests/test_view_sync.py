"""Versioned resource-view sync (reference: RaySyncer delta gossip,
src/ray/common/ray_syncer/ray_syncer.h:88): idle heartbeats carry no
resource payload, view refreshes are O(changes) deltas, and a 50-node
churn stays consistent with the full view."""

import asyncio

import pytest

import ray_tpu
from ray_tpu._private import rpc


@pytest.fixture()
def gcs_conn():
    ctx = ray_tpu.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
    import ray_tpu._private.worker as wm
    w = wm.global_worker

    def call(method, **kw):
        return w._run(w.core.gcs.call(method, **kw))

    yield call
    ray_tpu.shutdown()


def test_delta_view_churn_50_nodes(gcs_conn):
    call = gcs_conn
    # register 50 fake nodes
    for i in range(50):
        call("register_node", node_id=f"fake{i:04d}", address=f"tcp:10.0.0.{i}:1",
             object_store_address="", resources={"CPU": 8.0},
             labels={}, node_ip=f"10.0.0.{i}")
    full = call("get_cluster_view_delta", since=None)
    v0 = full["version"]
    assert sum(1 for n in full["full"] if n.startswith("fake")) == 50

    # liveness-only heartbeats (no payload): no version change, empty delta
    for i in range(50):
        call("heartbeat", node_id=f"fake{i:04d}")
    r = call("get_cluster_view_delta", since=v0)
    assert r["version"] == v0 and r["delta"] == {}

    # one node's availability changes: delta contains exactly that node
    call("heartbeat", node_id="fake0007", available={"CPU": 3.0})
    r = call("get_cluster_view_delta", since=v0)
    assert set(r["delta"]) == {"fake0007"}
    assert r["delta"]["fake0007"]["available"] == {"CPU": 3.0}
    v1 = r["version"]
    assert v1 > v0

    # repeated identical payloads don't bump the version (idle = constant)
    call("heartbeat", node_id="fake0007", available={"CPU": 3.0})
    r = call("get_cluster_view_delta", since=v1)
    assert r["delta"] == {} and r["version"] == v1

    # churn: 25 nodes change; delta tracks all, full view agrees
    for i in range(0, 50, 2):
        call("heartbeat", node_id=f"fake{i:04d}", available={"CPU": float(i)})
    r = call("get_cluster_view_delta", since=v1)
    changed = {n for n in r["delta"] if n.startswith("fake")}
    assert len(changed) == 25 or len(changed) == 24  # fake0007 may repeat
    full2 = call("get_cluster_view_delta", since=None)["full"]
    for nid, row in r["delta"].items():
        assert full2[nid]["available"] == row["available"]

    # drain marks a delta too
    call("drain_node", node_id="fake0001")
    r2 = call("get_cluster_view_delta", since=r["version"])
    assert "fake0001" in r2["delta"] and r2["delta"]["fake0001"]["draining"]
