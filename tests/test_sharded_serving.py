"""Sharded serving plane (ray_tpu/serve/sharded.py + spec_decode.py +
kv_quant.py): mesh-gang replicas with speculative decoding and int8 KV.

CPU unit tier (tier-1, any interpreter):
- greedy bit-exactness: spec-decode ON output == spec-decode OFF output
- accept/reject bookkeeping at K in {1, 4}: self-draft pins the rate at
  its 1.0 upper bound, a random-init draft lands near the floor
- int8 KV: quantize/dequantize round-trip tolerance, jnp/numpy mirror
  bit-identity, and prefix-cache HIT vs MISS greedy parity with the
  quantized block pool
- compile-once with speculation AND quantization both ON:
  decode_compile_count == 1 and exactly one verify program across
  requests of different lengths
- gang plumbing without a cluster: token digests, resume_tokens
  exactly-once, streaming protocol, GangRankKiller arming + the
  would-be SIGKILL (os.kill patched), ShellPool.checkout_many
  atomicity, digest-divergence wedging

The cluster tier (real gang attach over a Serve app, rank death
mid-decode, whole-gang drain -> shell revival -> exactly-once stream
resume) is 3.12-gated like every other cluster suite."""

import sys
import time

import numpy as np
import pytest

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")


@pytest.fixture(scope="module")
def jax_cpu():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return jax


@pytest.fixture(scope="module")
def tiny(jax_cpu):
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig, TransformerLM
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax_cpu.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


@pytest.fixture(scope="module")
def draft_cfg(jax_cpu):
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig
    return TransformerConfig(
        vocab_size=128, d_model=32, n_layers=1, n_heads=2, n_kv_heads=1,
        d_ff=64, max_seq_len=128, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)


def _replica(model, params, **kw):
    from ray_tpu.serve.sharded import ShardedEngineReplica
    base = dict(n_slots=2, max_len=64, prefill_chunk=4, prefill_budget=8,
                params_fn=lambda: params, seed=0)
    base.update(kw)
    return ShardedEngineReplica(model, **base)


PROMPT = [3, 1, 4, 1, 5, 9, 2, 6]


# ==========================================================================
# speculative decoding: greedy exactness + accept bookkeeping
# ==========================================================================

def test_spec_decode_greedy_bit_exact_vs_no_spec(tiny, draft_cfg):
    """The raw-speed multiplier must be invisible in the tokens: a
    spec-ON replica (random-init draft, so real rejections happen) and
    a spec-OFF replica produce identical greedy output."""
    _, model, params = tiny
    spec = _replica(model, params,
                    spec_decode={"draft_model": draft_cfg, "k": 4})
    base = _replica(model, params)
    for prompt, n in [(PROMPT, 24), ([7, 7, 7], 16), (list(range(20)), 8)]:
        assert spec.generate(prompt, max_new_tokens=n) == \
            base.generate(prompt, max_new_tokens=n)
    st = spec.stats()
    assert st["spec_tokens_proposed"] > 0


@pytest.mark.parametrize("k", [1, 4])
def test_spec_accept_bookkeeping_self_draft_upper_bound(tiny, k):
    """Self-draft (draft IS the target): every proposal verifies, so
    accepted == proposed and the rate sits at its 1.0 upper bound for
    any K."""
    _, model, params = tiny
    rep = _replica(model, params,
                   spec_decode={"draft_model": model.cfg, "k": k,
                                "draft_params_fn": lambda: params})
    out = rep.generate(PROMPT, max_new_tokens=24)
    assert len(out) == 24
    st = rep.stats()
    assert st["spec_tokens_proposed"] > 0
    assert st["spec_tokens_accepted"] == st["spec_tokens_proposed"]
    assert st["spec_accept_rate"] == 1.0


@pytest.mark.parametrize("k", [1, 4])
def test_spec_accept_bookkeeping_random_draft_rejects(tiny, draft_cfg, k):
    """A random-init draft disagrees with the target almost always:
    acceptance stays well below the self-draft bound and the counters
    stay consistent (accepted <= proposed, rate == accepted/proposed)."""
    _, model, params = tiny
    rep = _replica(model, params,
                   spec_decode={"draft_model": draft_cfg, "k": k,
                                "draft_seed": 3})
    rep.generate(PROMPT, max_new_tokens=24)
    st = rep.stats()
    prop, acc = st["spec_tokens_proposed"], st["spec_tokens_accepted"]
    assert prop > 0 and 0 <= acc <= prop
    assert st["spec_accept_rate"] == round(acc / prop, 4)
    assert st["spec_accept_rate"] < 1.0


# ==========================================================================
# int8 KV quantization
# ==========================================================================

def test_int8_kv_roundtrip_tolerance_and_host_mirror(jax_cpu):
    import jax.numpy as jnp

    from ray_tpu.inference.kv_quant import (dequantize_kv, dequantize_kv_np,
                                            quantize_kv, quantize_kv_np)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 16, 4, 8)).astype(np.float32)
    q, s = quantize_kv(jnp.asarray(x))
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    back = np.asarray(dequantize_kv(q, s, jnp.float32))
    # symmetric per-row int8: error bounded by half a quant step
    amax = np.abs(x).max(axis=-1, keepdims=True)
    assert np.all(np.abs(back - x) <= amax / 127 * 0.5 + 1e-7)
    # host mirrors are bit-identical to the jnp path (the disagg wire
    # re-quantizes on host; a drifting mirror would break hit parity)
    qn, sn = quantize_kv_np(x)
    np.testing.assert_array_equal(np.asarray(q), qn)
    np.testing.assert_array_equal(np.asarray(s), sn)
    np.testing.assert_array_equal(back, dequantize_kv_np(qn, sn))
    # all-zero rows must not divide by zero
    qz, sz = quantize_kv_np(np.zeros((1, 4, 2, 8), np.float32))
    assert np.all(qz == 0) and np.all(sz == 1.0)


def test_int8_slot_gain_formula():
    from ray_tpu.inference.kv_quant import slot_gain
    assert slot_gain(8, 2) == pytest.approx(2 * 8 / (8 + 4))
    assert slot_gain(128, 2) == pytest.approx(2 * 128 / 132)
    assert slot_gain(128, 4) > slot_gain(128, 2)   # fp32 baseline gains more


def test_int8_prefix_hit_greedy_parity(tiny):
    """The ISSUE gate: greedy output from an int8 prefix-cache HIT is
    bit-identical to the MISS that populated it (write-through
    quantize-and-reload on the miss path)."""
    _, model, params = tiny
    rep = _replica(model, params, kv_quant="int8", prefix_cache_slots=2)
    prompt = list(range(2, 26))             # 24 tokens = 6 full chunks
    miss = rep.generate(prompt, max_new_tokens=16)
    st0 = rep.stats()
    hit = rep.generate(prompt, max_new_tokens=16)
    st1 = rep.stats()
    assert hit == miss
    assert st1["prefix_tokens_saved"] > st0["prefix_tokens_saved"]
    assert st1["prefix_hits"] > st0["prefix_hits"]
    assert st1["kv_quant_slot_gain_vs_fp16"] > 1.0


# ==========================================================================
# compile-once with BOTH multipliers on
# ==========================================================================

def test_compile_once_spec_and_int8_together(tiny, draft_cfg):
    _, model, params = tiny
    rep = _replica(model, params, kv_quant="int8", prefix_cache_slots=2,
                   spec_decode={"draft_model": draft_cfg, "k": 4})
    base = _replica(model, params)
    for prompt, n in [(PROMPT, 20), (list(range(30)), 12), ([5], 24)]:
        assert rep.generate(prompt, max_new_tokens=n) == \
            base.generate(prompt, max_new_tokens=n)
    st = rep.stats()
    # one decode program (the fused draft+verify) and exactly one
    # verify trace across three request shapes
    assert st["decode_compile_count"] == 1
    assert st["spec_verify_compile_count"] == 1
    assert st["requests_served"] == 3


# ==========================================================================
# gang plumbing: digests, resume, streaming, chaos, shell pool
# ==========================================================================

def test_stream_digest_deterministic_across_replicas(tiny):
    """Digest agreement raw material: two same-seed replicas produce
    the same (stream_seq, blake2b) pair per stream; a different stream
    bumps the sequence and changes the digest."""
    _, model, params = tiny
    a = _replica(model, params)
    b = _replica(model, params)
    assert a.last_stream_digest() is None
    a.generate(PROMPT, max_new_tokens=12)
    b.generate(PROMPT, max_new_tokens=12)
    da, db = a.last_stream_digest(), b.last_stream_digest()
    assert da == db and da[0] == 1 and len(da[1]) == 32
    a.generate([9, 9], max_new_tokens=4)
    assert a.last_stream_digest()[0] == 2
    assert a.last_stream_digest()[1] != da[1]


def test_digest_divergence_wedges_gang(tiny):
    """ReplicaShard wedges the whole gang when any peer's stream digest
    disagrees with rank 0's — split-brain SPMD output is never served."""
    from ray_tpu.serve.sharded_replica import ReplicaShard
    _, model, params = tiny
    shard = ReplicaShard.__new__(ReplicaShard)
    shard._callable = _replica(model, params)
    shard._callable.generate(PROMPT, max_new_tokens=8)
    shard._wedged = False
    local = shard._callable.last_stream_digest()

    class _Ref:
        def __init__(self, v):
            self.v = v

    class _PeerMethod:
        def __init__(self, v):
            self.v = v

        def remote(self, *a, **k):
            return _Ref(self.v)

    class _Peer:
        def __init__(self, v):
            self.run_shard = _PeerMethod(v)

    import ray_tpu
    orig = ray_tpu.get
    ray_tpu.get = lambda refs, timeout=None: [r.v for r in refs]
    try:
        shard._peers = [_Peer(local)]
        shard._verify_stream_digest()        # agreement: no-op
        assert not shard._wedged
        shard._peers = [_Peer((local[0], "0" * 32))]
        with pytest.raises(RuntimeError, match="digest divergence"):
            shard._verify_stream_digest()
        assert shard._wedged
    finally:
        ray_tpu.get = orig


def test_resume_tokens_exactly_once(tiny, draft_cfg):
    """Severed-stream re-route: delivered tokens ride the prompt, the
    continuation is the bit-identical greedy suffix, nothing repeats."""
    _, model, params = tiny
    rep = _replica(model, params,
                   spec_decode={"draft_model": draft_cfg, "k": 4})
    out = rep.generate(PROMPT, max_new_tokens=24)
    res = rep.generate(PROMPT, max_new_tokens=24, resume_tokens=out[:10])
    assert res == out[10:]
    # fully-delivered stream: nothing left to emit
    assert rep.generate(PROMPT, max_new_tokens=24, resume_tokens=out) == []


def test_streaming_protocol_eager_first_chunk(tiny):
    _, model, params = tiny
    rep = _replica(model, params, stream_coalesce_tokens=8)
    chunks = list(rep(PROMPT, max_new_tokens=9))
    assert chunks[0] == [chunks[0][0]]      # TTFT: first token alone
    assert sum(len(c) for c in chunks) == 9
    assert [t for c in chunks for t in c] == rep.generate(
        PROMPT, max_new_tokens=9)


def test_gang_rank_killer_spec_env_and_rank0_immunity(tiny, monkeypatch):
    from ray_tpu.util.chaos import GangRankKiller
    killer = GangRankKiller(probability=1.0)
    assert killer.spec() == "gang_rank=1.0"
    env = killer.env({"A": "1", killer.SPEC_ENV: "shell_attach=0.5"})
    assert env[killer.SPEC_ENV] == "shell_attach=0.5,gang_rank=1.0"
    with pytest.raises(ValueError):
        GangRankKiller(probability=0.0)

    _, model, params = tiny
    rep = _replica(model, params)
    kills = []
    monkeypatch.setattr("os.kill", lambda pid, sig: kills.append((pid, sig)))
    killer.arm_local()
    try:
        # rank 0 never checks the hook: admission must survive chaos
        assert rep._rank == 0
        assert len(rep.generate(PROMPT, max_new_tokens=4)) == 4
        assert kills == []
        # a non-zero rank dies on its first step
        rep._rank = 1
        rep.generate(PROMPT, max_new_tokens=4)
        assert len(kills) >= 1
        import signal as _signal
        assert kills[0][1] == _signal.SIGKILL
    finally:
        rep._rank = 0
        GangRankKiller.disarm_local()


def test_shell_pool_checkout_many_is_atomic():
    from ray_tpu.serve.fleet import ShellPool

    class _Shell:
        pass

    pool = ShellPool(_Shell, size=4)
    pool.ensure()
    assert pool.idle() == 4
    assert pool.checkout_many(8) is None     # n or none: no partial gang
    assert pool.idle() == 4
    gang = pool.checkout_many(3)
    assert len(gang) == 3 and pool.idle() == 1
    assert pool.checkout_many(2) is None     # 1 idle < 2: untouched
    assert pool.idle() == 1
    assert pool.stats()["checked_out_total"] == 3


def test_drain_covers_whole_gang(tiny):
    """rank 0 owns admission, so begin_drain() on the replica drains
    the gang: the engine stops admitting and pending counts expose the
    drain progress the preemption lifecycle polls."""
    _, model, params = tiny
    rep = _replica(model, params)
    rep.generate(PROMPT, max_new_tokens=4)
    rep.begin_drain()
    st = rep.drain_status()
    assert st["draining"] and st["pending"] == 0
    with pytest.raises(RuntimeError):
        rep.generate(PROMPT, max_new_tokens=4)


def test_build_sharded_app_shape(tiny):
    from ray_tpu.serve.sharded import build_sharded_app
    app = build_sharded_app("llama-debug", num_hosts=2,
                            name="sharded-llm", n_slots=2)
    assert app.deployment.config.num_hosts == 2
    assert app.deployment.name == "sharded-llm"
    assert app.kwargs["n_slots"] == 2


# ==========================================================================
# cluster tier: real gang attach + rank-death recovery (3.12-gated)
# ==========================================================================

@pytest.fixture(scope="module")
def ray_start():
    import ray_tpu
    from ray_tpu import serve
    ctx = ray_tpu.init(num_cpus=8)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


@needs_cluster
def test_gang_attach_and_rank_death_recovery(ray_start):
    """Acceptance: a 2-host sharded deployment serves greedy streams;
    GangRankKiller SIGKILLs rank 1 mid-decode; the gang wedges, drains
    whole, revives (pre-warmed shells or cold build) and the re-routed
    stream with resume_tokens continues bit-identically."""
    from ray_tpu import serve
    from ray_tpu.serve.sharded import build_sharded_app
    from ray_tpu.util.chaos import GangRankKiller

    app = build_sharded_app(
        "llama-debug", num_hosts=2, name="sharded-acc",
        n_slots=2, max_len=64, prefill_chunk=4, prefill_budget=8)
    handle = serve.run(app, name="sharded-acc")
    try:
        ref = handle.generate.remote(PROMPT, max_new_tokens=24)
        full = ref.result(timeout=120)
        assert len(full) == 24

        killer = GangRankKiller(probability=1.0)
        import os
        os.environ[killer.SPEC_ENV] = killer.spec()
        try:
            got, err = [], None
            try:
                for chunk in handle.options(stream=True).remote(
                        PROMPT, max_new_tokens=24):
                    got.extend(chunk)
            except Exception as e:          # rank death severs the stream
                err = e
            # whichever way the race lands, what arrived is a greedy
            # prefix delivered at most once
            assert full[:len(got)] == got
        finally:
            os.environ.pop(killer.SPEC_ENV, None)

        # recovery: the controller retires the wedged gang and revives;
        # the resumed request returns exactly the missing suffix
        deadline = time.monotonic() + 180
        res = None
        while time.monotonic() < deadline:
            try:
                res = handle.generate.remote(
                    PROMPT, max_new_tokens=24,
                    resume_tokens=got).result(timeout=60)
                break
            except Exception:
                time.sleep(2)
        assert res == full[len(got):]
    finally:
        serve.delete("sharded-acc")
