"""Orbax sharded checkpoint tests on the virtual 8-device mesh: each
shard round-trips, restore honors target shardings, and training
continues bit-identically (reference counterpart: Checkpoint/Storage in
ray.train; the sharded-array path is TPU-native, SURVEY §5.4)."""

import tempfile

import jax
import numpy as np
import optax

from ray_tpu.models import MODEL_REGISTRY, TransformerLM
from ray_tpu.parallel import MeshConfig, make_mesh
from ray_tpu.parallel.train_step import make_train_fns
from ray_tpu.train.sharded_checkpoint import (abstract_like,
                                              restore_sharded, save_sharded)


def test_sharded_save_restore_roundtrip():
    cfg = MODEL_REGISTRY["llama-debug"]
    model = TransformerLM(cfg)
    mesh = make_mesh(MeshConfig(data=1, fsdp=4, seq=1, tensor=2))
    init_fn, step_fn, _ = make_train_fns(
        model, optax.adamw(1e-3), mesh, batch_shape=(8, 129))
    state = init_fn(jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 129), 0,
                              cfg.vocab_size)
    state, _ = step_fn(state, toks)

    path = tempfile.mkdtemp() + "/ckpt"
    save_sharded(state, path)
    restored = restore_sharded(path, abstract_like(state))

    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # restore places shards per the target sharding, not replicated
    a0 = jax.tree.leaves(state.params)[0]
    b0 = jax.tree.leaves(restored.params)[0]
    assert b0.sharding == a0.sharding

    _, ma = step_fn(state, toks)
    _, mb = step_fn(restored, toks)
    assert abs(float(ma["loss"]) - float(mb["loss"])) < 1e-6


def test_restore_into_different_layout():
    """A checkpoint saved under one mesh layout restores into another —
    the elastic-restart path (slice shape changed between runs)."""
    cfg = MODEL_REGISTRY["llama-debug"]
    model = TransformerLM(cfg)
    mesh_a = make_mesh(MeshConfig(data=1, fsdp=8, seq=1, tensor=1))
    init_a, _, _ = make_train_fns(model, optax.adamw(1e-3), mesh_a,
                                  batch_shape=(8, 129))
    state = init_a(jax.random.PRNGKey(0))
    path = tempfile.mkdtemp() + "/ckpt"
    save_sharded(state, path)

    mesh_b = make_mesh(MeshConfig(data=1, fsdp=2, seq=1, tensor=4))
    init_b, step_b, _ = make_train_fns(model, optax.adamw(1e-3), mesh_b,
                                       batch_shape=(8, 129))
    template = init_b(jax.random.PRNGKey(7))   # target layout
    restored = restore_sharded(path, abstract_like(template))
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    toks = jax.random.randint(jax.random.PRNGKey(1), (8, 129), 0,
                              cfg.vocab_size)
    _, m = step_b(restored, toks)
    assert 0.0 < float(m["loss"]) < 20.0
