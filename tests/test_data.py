"""Ray-Data-equivalent tests: lazy plans, streaming execution, transforms,
iteration incl. the jax device-feed path (reference:
python/ray/data/tests/test_map.py, test_iterator.py shapes)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def ray_start():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_range_count(ray_start):
    ds = rd.range(1000, parallelism=4)
    assert ds.count() == 1000
    assert ds.num_blocks() == 4


def test_map_batches(ray_start):
    ds = rd.range(100, parallelism=4).map_batches(
        lambda b: {"id": b["id"] * 2})
    got = sorted(r["id"] for r in ds.take_all())
    assert got == [i * 2 for i in range(100)]


def test_map_filter_flatmap(ray_start):
    ds = rd.range(20, parallelism=2) \
        .map(lambda r: {"v": r["id"] + 1}) \
        .filter(lambda r: r["v"] % 2 == 0) \
        .flat_map(lambda r: [{"v": r["v"]}, {"v": -r["v"]}])
    vals = sorted(r["v"] for r in ds.take_all())
    evens = [i + 1 for i in range(20) if (i + 1) % 2 == 0]
    assert vals == sorted(evens + [-v for v in evens])


def test_from_items_and_limit(ray_start):
    ds = rd.from_items([{"x": i} for i in range(50)], parallelism=5)
    assert ds.limit(7).count() == 7
    assert len(ds.take(3)) == 3


def test_repartition_and_shuffle(ray_start):
    ds = rd.range(100, parallelism=2).repartition(10)
    assert ds.num_blocks() == 10
    assert ds.count() == 100
    shuffled = rd.range(100, parallelism=4).random_shuffle(seed=0)
    vals = [r["id"] for r in shuffled.take_all()]
    assert sorted(vals) == list(range(100))
    assert vals != list(range(100))


def test_sort(ray_start):
    ds = rd.from_items([{"k": i % 7, "v": i} for i in range(30)])
    out = [r["k"] for r in ds.sort("k").take_all()]
    assert out == sorted(out)


def test_iter_batches_exact_sizes(ray_start):
    ds = rd.range(100, parallelism=7)
    batches = list(ds.iter_batches(batch_size=32, drop_last=False))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [32, 32, 32, 4]
    all_ids = np.concatenate([b["id"] for b in batches])
    assert sorted(all_ids.tolist()) == list(range(100))


def test_split(ray_start):
    shards = rd.range(90, parallelism=6).split(3)
    counts = [s.count() for s in shards]
    assert sum(counts) == 90
    assert all(c > 0 for c in counts)


def test_write_read_parquet(ray_start, tmp_path):
    ds = rd.range(64, parallelism=4).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    ds.write_parquet(str(tmp_path / "pq"))
    back = rd.read_parquet(str(tmp_path / "pq"))
    rows = back.take_all()
    assert len(rows) == 64
    assert all(r["sq"] == r["id"] ** 2 for r in rows)


def test_iter_jax_batches(ray_start):
    import jax
    ds = rd.range(64, parallelism=4)
    batches = list(ds.iter_jax_batches(batch_size=16))
    assert len(batches) == 4
    assert all(isinstance(b["id"], jax.Array) for b in batches)
    total = sum(int(b["id"].sum()) for b in batches)
    assert total == sum(range(64))


def test_iter_jax_batches_sharded(ray_start):
    import jax
    from ray_tpu.parallel import MeshConfig, make_mesh
    mesh = make_mesh(MeshConfig(data=1, fsdp=8, seq=1, tensor=1))
    ds = rd.range(64, parallelism=4)
    for b in ds.iter_jax_batches(batch_size=16, mesh=mesh):
        assert b["id"].sharding.num_devices == 8


def test_groupby_aggregates(ray_start):
    import ray_tpu.data as rd
    ds = rd.from_items([{"k": i % 3, "v": float(i)} for i in range(30)])
    out = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    expect = {}
    for i in range(30):
        expect[i % 3] = expect.get(i % 3, 0.0) + float(i)
    assert out == expect
    counts = {r["k"]: r["count()"]
              for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}
    means = {r["k"]: r["mean(v)"]
             for r in ds.groupby("k").mean("v").take_all()}
    assert abs(means[0] - expect[0] / 10) < 1e-9


def test_map_groups(ray_start):
    import ray_tpu.data as rd
    ds = rd.from_items([{"k": i % 2, "v": float(i)} for i in range(10)])

    def top1(df):
        return df.nlargest(1, "v")

    rows = ds.groupby("k").map_groups(top1).take_all()
    assert sorted(r["v"] for r in rows) == [8.0, 9.0]


def test_column_ops_and_global_aggs(ray_start):
    import ray_tpu.data as rd
    ds = rd.from_items([{"a": i, "b": 2 * i} for i in range(10)])
    ds2 = ds.add_column("c", lambda df: df["a"] + df["b"])
    row = ds2.sort("a").take(1)[0]
    assert row["c"] == 0
    assert ds2.max("c") == 27.0
    assert ds2.sum("a") == 45.0
    assert abs(ds2.mean("b") - 9.0) < 1e-9
    ds3 = ds2.drop_columns(["b"]).rename_columns({"c": "total"})
    assert sorted(ds3.take(1)[0].keys()) == ["a", "total"]
    assert ds.unique("a") == list(range(10))


def test_random_split_and_zip(ray_start):
    import ray_tpu.data as rd
    ds = rd.range(20)
    a, b = ds.random_split([0.5, 0.5], seed=0)
    assert a.count() + b.count() == 20
    z = rd.range(5).zip(rd.from_items([{"y": i * 10} for i in range(5)]))
    rows = z.sort("id").take_all()
    assert rows[2]["y"] == 20 or "y" in rows[2]


def test_preprocessors(ray_start):
    import numpy as np

    import ray_tpu.data as rd
    from ray_tpu.data.preprocessors import (Chain, LabelEncoder,
                                            MinMaxScaler, OneHotEncoder,
                                            StandardScaler)
    ds = rd.from_items([{"x": float(i), "cat": ["a", "b"][i % 2],
                         "label": ["lo", "hi"][i // 5]} for i in range(10)])
    scaled = StandardScaler(["x"]).fit_transform(ds)
    xs = np.array([r["x"] for r in scaled.take_all()])
    assert abs(xs.mean()) < 1e-9 and abs(xs.std() - 1.0) < 1e-6

    mm = MinMaxScaler(["x"]).fit_transform(ds)
    xs = np.array([r["x"] for r in mm.take_all()])
    assert xs.min() == 0.0 and xs.max() == 1.0

    enc = LabelEncoder("label").fit_transform(ds)
    labels = {r["label"] for r in enc.take_all()}
    assert labels == {0, 1}

    oh = OneHotEncoder(["cat"]).fit_transform(ds)
    r0 = oh.sort("x").take(1)[0]
    assert r0["cat_a"] == 1 and r0["cat_b"] == 0

    chain = Chain(StandardScaler(["x"]), LabelEncoder("label"))
    out = chain.fit(ds).transform(ds).take_all()
    assert {r["label"] for r in out} == {0, 1}


def test_write_json(ray_start, tmp_path):
    import json
    import os

    import ray_tpu.data as rd
    p = str(tmp_path / "out")
    rd.range(7).write_json(p)
    rows = []
    for f in sorted(os.listdir(p)):
        with open(os.path.join(p, f)) as fh:
            rows += [json.loads(l) for l in fh]
    assert sorted(r["id"] for r in rows) == list(range(7))
