"""Slice-aware gang scheduling + fail-as-a-unit restart (SURVEY §7.3:
a TPU pod slice starts, fails, and restarts as one gang; reference
resource convention: python/ray/_private/accelerators/tpu.py:334 —
pod-name + head resources; gang restart: Train FailureConfig +
BackendExecutor group restart).

CPU-hermetic: fake slice hosts carry the tpu-slice:* resources real TPU
hosts would inject.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import (Checkpoint, FailureConfig, JaxTrainer, RunConfig,
                           ScalingConfig)
from ray_tpu.train import slice as slice_lib


TOPO = "v4-16"        # 2 hosts x 4 chips


def test_slice_shape_and_pick():
    assert slice_lib.slice_shape(TOPO) == (2, 4)
    nodes = [
        {"alive": True, "total": {"TPU": 4, "tpu-slice:podA": 1},
         "available": {"TPU": 4}},
        {"alive": True, "total": {"TPU": 4, "tpu-slice:podA": 1},
         "available": {"TPU": 4}},
        {"alive": True, "total": {"TPU": 4, "tpu-slice:podB": 1},
         "available": {"TPU": 0}},     # busy
        {"alive": False, "total": {"TPU": 4, "tpu-slice:podC": 1},
         "available": {"TPU": 4}},     # dead host
        {"alive": True, "total": {"TPU": 4, "tpu-slice:podC": 1},
         "available": {"TPU": 4}},
    ]
    assert slice_lib.pick_slice(nodes, TOPO) == "tpu-slice:podA"
    assert slice_lib.pick_slice(nodes, TOPO,
                                exclude={"tpu-slice:podA"}) is None


def _gang_train_fn(config):
    import ray_tpu
    from ray_tpu import train as rt

    ckpt = rt.get_checkpoint()
    step = ckpt.to_dict()["step"] if ckpt is not None else 0
    ctx = rt.get_context()
    node = ray_tpu.get_runtime_context()["node_id"]
    progress = ray_tpu.get_actor("gang-progress")
    while step < 6:
        step += 1
        time.sleep(0.5)
        if ctx.get_world_rank() == 0:
            ray_tpu.get(progress.update.remote(step, node), timeout=30)
        rt.report({"step": step, "node": node,
                   "rank": ctx.get_world_rank()},
                  checkpoint=(Checkpoint.from_dict({"step": step})
                              if ctx.get_world_rank() == 0 else None))


def test_gang_restart_on_slice_host_death():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    pod_a = [cluster.add_node(num_cpus=2,
                              resources={"TPU": 4, "tpu-slice:podA": 1})
             for _ in range(2)]
    for _ in range(2):
        cluster.add_node(num_cpus=2,
                         resources={"TPU": 4, "tpu-slice:podB": 1})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes()   # deterministic pick: podA (sorted first)
    try:
        trainer = JaxTrainer(
            _gang_train_fn,
            scaling_config=ScalingConfig(num_workers=2, topology=TOPO),
            run_config=RunConfig(name="gang-restart",
                                 failure_config=FailureConfig(max_failures=2)),
        )
        @ray_tpu.remote(num_cpus=0.1)
        class Progress:
            def __init__(self):
                self.step = 0
                self.nodes = set()

            def update(self, step, node):
                self.step = step
                self.nodes.add(node)
                return True

            def get(self):
                return self.step, sorted(self.nodes)

        from ray_tpu.util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy
        progress = Progress.options(
            name="gang-progress",
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                cluster.nodes[0].node_id)).remote()   # not on a doomed host
        ray_tpu.get(progress.get.remote(), timeout=60)

        # kill one podA host once the gang has made real progress (a
        # checkpoint exists): the whole gang must restart from it on the
        # surviving full slice (podB)
        def _kill_after_progress():
            deadline = time.time() + 60
            while time.time() < deadline:
                step, _nodes = ray_tpu.get(progress.get.remote(),
                                           timeout=30)
                if step >= 2:
                    cluster.remove_node(pod_a[0])
                    return
                time.sleep(0.1)

        killer = threading.Thread(target=_kill_after_progress, daemon=True)
        killer.start()
        result = trainer.fit()
        killer.join(timeout=10)
        assert result.error is None, result.error
        assert result.metrics["step"] == 6
        final_step, nodes_seen = ray_tpu.get(progress.get.remote(),
                                             timeout=30)
        assert final_step == 6
        # rank-0 ran on hosts of BOTH slices across the restart
        assert len(nodes_seen) >= 2, nodes_seen
        # and the restart resumed from the checkpoint (history repeats a
        # step rather than losing all progress; rank0 history only)
        steps = [m["step"] for m in result.metrics_history]
        assert steps[-1] == 6 and min(steps) == 1
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_slice_gang_unschedulable_without_whole_slice():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2, resources={"TPU": 4, "tpu-slice:podA": 1})
    import os
    os.environ["RAY_TPU_SLICE_WAIT_TIMEOUT_S"] = "3"
    ray_tpu.init(address=cluster.address)   # only ONE podA host of two
    try:
        trainer = JaxTrainer(
            _gang_train_fn,
            scaling_config=ScalingConfig(num_workers=2, topology=TOPO),
            run_config=RunConfig(name="gang-unsched",
                                 failure_config=FailureConfig(max_failures=0)),
        )
        result = trainer.fit()
        assert result.error is not None
        assert "slice" in str(result.error)
    finally:
        import os
        os.environ.pop("RAY_TPU_SLICE_WAIT_TIMEOUT_S", None)
        ray_tpu.shutdown()
        cluster.shutdown()
