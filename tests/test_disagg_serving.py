"""Disaggregated prefill/decode serving plane (ray_tpu/serve/disagg.py
+ engine/scheduler/router/GCS extensions, ROADMAP item 1):

- chunk fingerprints + trie summaries (the cluster-routing currency)
- scheduler remote-prefill hold state
- GCS prefix_summaries publish / read / TTL-expire semantics
- router cluster longest-match vs session-hash tie-breaking
- KV payload framing round-trip
- engine KV export/import parity: greedy output bit-identical between
  remote-prefill and local-prefill paths, compile-once preserved
- deployment-level hand-off + every rung of the fallback ladder
  (including the PrefillExportKiller chaos spec)
- idle-span spill eligibility (ROADMAP item 4 leftover)

Everything above the `needs_cluster` line is CPU-pinned and
cluster-free (tier-1 on any interpreter); the cluster tier (full Serve
app, cross-replica route, prefill replica killed mid-export) is
3.12-gated."""

import sys
import time

import numpy as np
import pytest

needs_cluster = pytest.mark.skipif(
    sys.version_info < (3, 12),
    reason="cluster runtime requires Python >= 3.12 (PEP 688 store reads)")


# --------------------------------------------------------------------------
# fingerprints + trie summary (pure host code)
# --------------------------------------------------------------------------

def test_chunk_fingerprints_rolling_and_divergence():
    from ray_tpu.inference.prefix_cache import chunk_fingerprints
    toks = list(range(100, 117))            # 17 tokens, chunk 4
    fps = chunk_fingerprints(toks, 4)
    assert len(fps) == 4                    # full chunks only
    # deterministic and prefix-stable: a longer prompt sharing the
    # prefix produces the same leading fingerprints
    fps2 = chunk_fingerprints(toks + [1, 2, 3, 4, 5], 4)
    assert fps2[:4] == fps
    # divergence at chunk i changes fingerprints from i on
    other = list(toks)
    other[5] = 999                          # inside chunk 1
    fps3 = chunk_fingerprints(other, 4)
    assert fps3[0] == fps[0]
    assert fps3[1] != fps[1] and fps3[2] != fps[2]
    # admission-cap plumbing
    assert chunk_fingerprints(toks, 4, max_chunks=2) == fps[:2]
    assert chunk_fingerprints([1, 2], 4) == []


def test_trie_summary_matches_chunk_fingerprints_and_caps_topk():
    from ray_tpu.inference import RadixPrefixCache
    from ray_tpu.inference.prefix_cache import chunk_fingerprints
    c = RadixPrefixCache(4, 8)
    toks = list(range(40, 52))              # 3 chunks
    c.insert(toks)
    s = c.summary()
    assert s["chunk"] == 4 and s["blocks"] == 3
    # the summary's fingerprints ARE the prompt's path fingerprints —
    # the router-side computation matches without seeing any tokens
    assert set(s["fps"]) == set(chunk_fingerprints(toks, 4))
    # top-k keeps the most recently touched nodes
    c.insert([7, 7, 7, 7])
    m, nodes = c.match(toks + [99])         # touch the whole chain
    assert m == 12
    c.release(nodes)
    top = c.summary(top_k=3)["fps"]
    assert len(top) == 3
    assert set(top) == set(chunk_fingerprints(toks, 4))


def test_peek_and_walk_semantics():
    from ray_tpu.inference import RadixPrefixCache
    c = RadixPrefixCache(4, 8)
    toks = list(range(10, 23))              # 13 tokens = 3 full chunks
    c.insert(toks)
    lookups0, hits0 = c.lookups, c.hits
    # peek: capped like match, but no pins, no stats
    assert c.peek(toks) == 12
    assert c.peek(toks[:12]) == 8           # cap leaves the last token
    assert c.peek([99] + toks[1:]) == 0
    assert (c.lookups, c.hits) == (lookups0, hits0)
    root = c._root
    assert all(n.pins == 0 for n in root.children.values())
    # walk: uncapped up to n_chunks, PINNED, still stats-free
    nodes = c.walk(toks, 3)
    assert len(nodes) == 3
    assert all(n.pins == 1 for n in nodes)
    assert (c.lookups, c.hits) == (lookups0, hits0)
    c.release(nodes)
    assert all(n.pins == 0 for n in nodes)
    assert c.walk(toks, 2) and len(c.walk(toks, 0)) == 0


# --------------------------------------------------------------------------
# scheduler: remote-prefill hold state
# --------------------------------------------------------------------------

def _sched(n_slots=2, budget=8):
    from ray_tpu.inference import Scheduler
    return Scheduler(n_slots, budget, chunk_size=4)


def test_hold_blocks_admission_until_release():
    from ray_tpu.inference import Request
    s = _sched()
    held = s.submit(Request(tokens=np.arange(6)), hold=True)
    assert s.plan_prefill() == []           # held: not admissible
    assert not s.has_work()                 # and not spinning the loop
    assert s.release_hold(held.rid)
    assert s.has_work()
    chunks = s.plan_prefill()
    assert chunks and chunks[0].state.rid == held.rid


def test_held_request_keeps_fifo_position_but_yields_slots():
    from ray_tpu.inference import Request
    s = _sched(n_slots=1)
    held = s.submit(Request(tokens=np.arange(4)), hold=True)
    other = s.submit(Request(tokens=np.arange(4)))
    # a later arrival admits past the held head (its KV is in flight)
    chunks = s.plan_prefill()
    assert [c.state.rid for c in chunks] == [other.rid]
    # the held request is still queued, in place, and admits on release
    s.release_hold(held.rid)
    assert s._queue[0].rid == held.rid


def test_held_request_still_reaped_on_cancel_and_release_is_idempotent():
    from ray_tpu.inference import Request
    s = _sched()
    held = s.submit(Request(tokens=np.arange(4)), hold=True)
    held.cancel()
    reaped = s.reap()
    assert [st.rid for st in reaped] == [held.rid]
    assert held.finish_reason == "cancelled"
    assert s.release_hold(held.rid) is False   # already gone


# --------------------------------------------------------------------------
# GCS prefix_summaries table: publish / read / expire
# --------------------------------------------------------------------------

def test_gcs_prefix_summary_publish_read_filter_and_expire():
    from ray_tpu._private.config import cfg
    from ray_tpu._private.gcs import GcsServer
    g = GcsServer()
    assert g.h_publish_prefix_summary(None, "rep-a", [1, 2, 3], 4,
                                      blocks=3, deployment="llm")
    g.h_publish_prefix_summary(None, "rep-b", [9], 4, deployment="other")
    rows = g.h_get_prefix_summaries(None)
    assert {r["replica_id"] for r in rows} == {"rep-a", "rep-b"}
    # id + deployment filters
    assert [r["replica_id"] for r in
            g.h_get_prefix_summaries(None, ids=["rep-a"])] == ["rep-a"]
    assert [r["replica_id"] for r in
            g.h_get_prefix_summaries(None, deployment="other")] == ["rep-b"]
    # last write wins per replica
    g.h_publish_prefix_summary(None, "rep-a", [5], 4)
    (row,) = g.h_get_prefix_summaries(None, ids=["rep-a"])
    assert row["fps"] == [5]
    # fps are bounded by the top-k knob
    big = list(range(cfg.prefix_summary_top_k + 50))
    g.h_publish_prefix_summary(None, "rep-c", big, 4)
    (row,) = g.h_get_prefix_summaries(None, ids=["rep-c"])
    assert len(row["fps"]) == cfg.prefix_summary_top_k
    # expiry: rows older than the TTL vanish at read time (a dead
    # replica stops attracting routes without explicit teardown)
    g.prefix_summaries["rep-a"]["ts"] -= cfg.prefix_summary_ttl_s + 1
    assert "rep-a" not in {r["replica_id"]
                           for r in g.h_get_prefix_summaries(None)}
    assert "rep-a" not in g.prefix_summaries
    # empty/garbage publishes are refused
    assert g.h_publish_prefix_summary(None, "", [1], 4) is False


# --------------------------------------------------------------------------
# router: cluster longest-match vs session-hash tie-breaking
# --------------------------------------------------------------------------

def _router(n, chunk=4):
    import threading

    from ray_tpu.serve.handle import _Router
    r = _Router.__new__(_Router)     # skip ctor (no long-poll client)
    r.deployment_name = "d"
    r.app_name = "a"
    r.replicas = [object() for _ in range(n)]
    r.inflight = {i: 0 for i in range(n)}
    r.shared_load = {}
    r.version = 0
    r.resumable = False
    r.coalesced = False
    r.prefix_routed = True
    r.replica_ids = [f"rep-{i}" for i in range(n)]
    r._summaries = {}
    r._summary_chunk = chunk
    r._last_summary_refresh = time.monotonic() + 1e6   # never re-pull
    r.lock = threading.Lock()
    r._last_refresh = time.monotonic() + 1e6           # never refresh
    r.model_map = {}
    return r


def _set_summary(r, idx, tokens, depth, chunk=4):
    from ray_tpu.inference.prefix_cache import chunk_fingerprints
    r._summaries[f"rep-{idx}"] = set(
        chunk_fingerprints(tokens, chunk, max_chunks=depth))


def test_router_routes_to_deepest_cluster_match():
    prompt = list(range(60, 77))            # 4 full chunks of 4
    r = _router(4)
    _set_summary(r, 1, prompt, depth=1)
    _set_summary(r, 3, prompt, depth=3)
    # deepest match wins regardless of load or session hash
    r.inflight = {0: 0, 1: 0, 2: 0, 3: 99}
    for s in ("sess-a", "sess-b", ""):
        idx, _ = r.pick(session_id=s, prompt_tokens=prompt)
        assert idx == 3
        r._dec(idx)


def test_router_tie_breaks_to_session_then_least_loaded():
    import zlib
    prompt = list(range(10, 27))
    r = _router(4)
    _set_summary(r, 0, prompt, depth=2)
    _set_summary(r, 2, prompt, depth=2)
    # session whose sticky replica is among the deepest: sticky wins
    sticky2 = next(s for s in (f"s{i}" for i in range(64))
                   if zlib.crc32(str(s).encode()) % 4 == 2)
    idx, _ = r.pick(session_id=sticky2, prompt_tokens=prompt)
    assert idx == 2
    r._dec(idx)
    # session hashing OUTSIDE the winner set: least-loaded winner
    sticky1 = next(s for s in (f"s{i}" for i in range(64))
                   if zlib.crc32(str(s).encode()) % 4 == 1)
    r.inflight = {0: 5, 1: 0, 2: 0, 3: 0}
    idx, _ = r.pick(session_id=sticky1, prompt_tokens=prompt)
    assert idx == 2
    r._dec(idx)
    # no session: least-loaded winner
    r.inflight = {0: 0, 1: 0, 2: 7, 3: 0}
    idx, _ = r.pick(prompt_tokens=prompt)
    assert idx == 0


def test_router_falls_back_to_session_hash_without_match():
    prompt = list(range(30, 47))
    r = _router(4)
    # summaries exist but cover a DIFFERENT prefix -> session rung
    _set_summary(r, 1, list(range(200, 217)), depth=3)
    picks = {r.pick(session_id="sess-x", prompt_tokens=prompt)[0]
             for _ in range(6)}
    assert len(picks) == 1                  # sticky, not prefix-routed
    # avoided deepest replica falls back too
    r2 = _router(2)
    _set_summary(r2, 0, prompt, depth=2)
    idx, _ = r2.pick(prompt_tokens=prompt, avoid={0})
    assert idx == 1


def test_router_short_prompt_and_disabled_flag_skip_prefix_rung():
    r = _router(3)
    _set_summary(r, 1, list(range(8)), depth=2)
    # sub-chunk prompt: no fingerprints, session rung decides
    idx, _ = r.pick(session_id="s", prompt_tokens=[1, 2])
    assert idx in range(3)
    r._dec(idx)
    r.prefix_routed = False
    idx2, _ = r.pick(session_id="s", prompt_tokens=list(range(8)))
    assert idx2 == idx                      # same session-hash pick


# --------------------------------------------------------------------------
# KV payload framing
# --------------------------------------------------------------------------

def test_pack_unpack_roundtrip_and_zero_copy_views():
    from ray_tpu.serve.disagg import pack_kv_spans, unpack_kv_spans
    rng = np.random.RandomState(3)
    shape = (2, 1, 4, 2, 8)                 # [n_layers, 1, C, Hkv, D]
    spans = [(rng.randn(*shape).astype(np.float32),
              rng.randn(*shape).astype(np.float32)) for _ in range(3)]
    buf = pack_kv_spans(spans)
    out = unpack_kv_spans(buf)
    assert len(out) == 3
    for (k, v), (k2, v2) in zip(spans, out):
        assert np.array_equal(k, k2) and np.array_equal(v, v2)
    # memoryview input (the arena view ray_tpu.get hands back) works and
    # the arrays are views into it, not copies
    out2 = unpack_kv_spans(memoryview(buf))
    assert not out2[0][0].flags.owndata
    assert np.array_equal(out2[2][1], spans[2][1])
    assert unpack_kv_spans(pack_kv_spans([])) == []


# --------------------------------------------------------------------------
# engine: export/import parity + compile-once
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig, TransformerLM
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _engine(model, params, **kw):
    from ray_tpu.inference import EngineConfig, InferenceEngine
    cfg = dict(n_slots=2, max_len=48, prefill_chunk=4, prefill_budget=8,
               prefix_cache_slots=1)
    cfg.update(kw)
    return InferenceEngine(model, params, EngineConfig(**cfg))


def _drain(eng, handle, max_steps=300):
    for _ in range(max_steps):
        eng.step()
        if handle.finish_reason is not None:
            return handle.tokens()
    raise AssertionError("request did not finish")


def test_remote_prefill_greedy_bit_identical_and_compile_once(tiny):
    """The acceptance contract: a prompt prefilled on ANOTHER engine,
    shipped as packed KV spans and imported, produces greedy output
    bit-identical to the colocated path — with decode_compile_count
    still 1 on the importing engine."""
    from ray_tpu.serve.disagg import pack_kv_spans, unpack_kv_spans
    _, model, params = tiny
    rng = np.random.RandomState(11)
    prompt = rng.randint(0, 128, 17)
    # colocated oracle
    eng_co = _engine(model, params)
    want = _drain(eng_co, eng_co.submit(prompt, max_new_tokens=10))

    prefill = _engine(model, params, prefix_cache_slots=2)
    _drain(prefill, prefill.submit(prompt, max_new_tokens=1))
    covered, spans = prefill.export_kv_blocks(prompt)
    assert covered == 16 and len(spans) == 4
    assert prefill.kv_exports == 1

    decode = _engine(model, params, prefix_cache_slots=2)
    payload = pack_kv_spans(spans)          # the real wire framing
    imported = decode.import_kv_blocks(prompt[:covered],
                                       unpack_kv_spans(payload))
    assert imported == 16 and decode.kv_imports == 1
    h = decode.submit(prompt, max_new_tokens=10)
    got = _drain(decode, h)
    assert h.prefix_matched == 16           # admission skipped prefill
    assert got == want                      # bit-identical
    st = decode.stats()
    assert st["decode_compile_count"] == 1
    assert st["remote_prefix_tokens"] == 16
    assert decode._import_span_fn._cache_size() == 1
    assert prefill._export_span_fn._cache_size() == 1
    # a redundant import of already-cached chunks is a no-op
    assert decode.import_kv_blocks(prompt[:covered],
                                   unpack_kv_spans(payload)) == 0


def test_import_partial_prefix_and_longer_prompt_reuse(tiny):
    """An imported prefix serves LONGER prompts sharing it (cluster
    cache semantics), and a partial import still shortens prefill."""
    from ray_tpu.serve.disagg import pack_kv_spans, unpack_kv_spans
    _, model, params = tiny
    rng = np.random.RandomState(12)
    shared = rng.randint(0, 128, 12)        # 3 full chunks
    prefill = _engine(model, params, prefix_cache_slots=2)
    _drain(prefill, prefill.submit(shared, max_new_tokens=1))
    covered, spans = prefill.export_kv_blocks(shared, max_chunks=3)
    assert covered == 12
    decode = _engine(model, params, prefix_cache_slots=2)
    decode.import_kv_blocks(shared, unpack_kv_spans(
        pack_kv_spans(spans)))
    longer = np.concatenate([shared, rng.randint(0, 128, 7)])
    eng_co = _engine(model, params)
    want = _drain(eng_co, eng_co.submit(longer, max_new_tokens=8))
    h = decode.submit(longer, max_new_tokens=8)
    assert _drain(decode, h) == want
    assert h.prefix_matched == 12
    assert decode.decode_compile_count == 1


# --------------------------------------------------------------------------
# deployment tier: hand-off + fallback ladder
# --------------------------------------------------------------------------

def _mk_prefill(tiny_fixture, **kw):
    from ray_tpu.serve.disagg import PrefillLLMDeployment
    cfg, _model, params = tiny_fixture
    args = dict(n_slots=2, max_len=64, prefill_chunk=4, prefill_budget=8,
                prefix_cache_slots=2, params_fn=lambda: params)
    args.update(kw)
    return PrefillLLMDeployment(cfg, **args)


def _mk_decode(tiny_fixture, prefill, **kw):
    from ray_tpu.serve.disagg import DisaggLLMDeployment
    cfg, _model, params = tiny_fixture
    args = dict(n_slots=2, max_len=64, prefill_chunk=4, prefill_budget=8,
                prefix_cache_slots=2, params_fn=lambda: params,
                prefill=prefill)
    args.update(kw)
    return DisaggLLMDeployment(cfg, **args)


def test_disagg_deployment_handoff_end_to_end(tiny):
    from ray_tpu.inference import LLMDeployment
    cfg, _model, params = tiny
    oracle_dep = LLMDeployment(cfg, n_slots=2, max_len=64,
                               prefill_chunk=4, prefill_budget=8,
                               prefix_cache_slots=0,
                               params_fn=lambda: params)
    prefill = _mk_prefill(tiny)
    decode = _mk_decode(tiny, prefill)
    try:
        prompt = list(range(50, 67))        # 17 tokens: 4 full chunks
        want = oracle_dep.generate(prompt, max_new_tokens=10)
        got = decode.generate(prompt, max_new_tokens=10)
        assert got == want
        assert prefill.engine.kv_exports >= 1
        assert decode.engine.kv_imports == 1
        assert decode.engine.remote_prefix_tokens == 16
        assert decode.engine.decode_compile_count == 1
        # second request: local hit, no new hand-off
        assert decode.generate(prompt, max_new_tokens=10) == want
        assert decode.engine.kv_imports == 1
        # hold fully released: nothing parked in the queue
        assert decode.engine.sched.queue_depth() == 0
    finally:
        oracle_dep.engine.stop()
        prefill.engine.stop()
        decode.engine.stop()


class _BrokenPrefill:
    def prefill_export(self, tokens):
        raise RuntimeError("prefill tier unreachable")


def test_disagg_falls_back_to_local_prefill_on_handoff_failure(tiny):
    from ray_tpu.inference import LLMDeployment
    cfg, _model, params = tiny
    oracle_dep = LLMDeployment(cfg, n_slots=2, max_len=64,
                               prefill_chunk=4, prefill_budget=8,
                               prefix_cache_slots=0,
                               params_fn=lambda: params)
    decode = _mk_decode(tiny, _BrokenPrefill())
    try:
        prompt = list(range(20, 37))
        want = oracle_dep.generate(prompt, max_new_tokens=8)
        got = decode.generate(prompt, max_new_tokens=8)
        assert got == want                  # exactly-once, rung 4
        assert decode.engine.kv_imports == 0
        assert decode.engine.sched.queue_depth() == 0   # hold released
    finally:
        oracle_dep.engine.stop()
        decode.engine.stop()


def test_prefill_export_killer_spec_forces_fallback(tiny):
    """The chaos satellite: with RAY_TPU_TESTING_RPC_FAILURE=
    "prefill_export=1.0" armed, every export dies (entry or pre-return)
    and the decode tier must fall back to local prefill with identical
    output — the exception-shaped half of 'killed mid-export'."""
    from ray_tpu.inference import LLMDeployment
    from ray_tpu.util.chaos import PrefillExportKiller
    cfg, _model, params = tiny
    oracle_dep = LLMDeployment(cfg, n_slots=2, max_len=64,
                               prefill_chunk=4, prefill_budget=8,
                               prefix_cache_slots=0,
                               params_fn=lambda: params)
    prefill = _mk_prefill(tiny)
    decode = _mk_decode(tiny, prefill)
    killer = PrefillExportKiller(1.0)
    try:
        prompt = list(range(70, 87))
        want = oracle_dep.generate(prompt, max_new_tokens=8)
        killer.arm_local()
        with pytest.raises(Exception):
            prefill.prefill_export(prompt)  # the injection really fires
        got = decode.generate(prompt, max_new_tokens=8)
        assert got == want
        assert decode.engine.kv_imports == 0
    finally:
        killer.disarm_local()
        oracle_dep.engine.stop()
        prefill.engine.stop()
        decode.engine.stop()


def test_prefill_export_inline_payload_contract(tiny):
    """Outside a cluster prefill_export inlines the payload (no arena);
    the covered/chunk fields still line up with the admission cap."""
    prefill = _mk_prefill(tiny)
    try:
        prompt = list(range(90, 107))       # 17 tokens
        out = prefill.prefill_export(prompt)
        assert out["covered"] == 16 and out["chunk"] == 4
        assert "payload" in out and out.get("ref") is None
        from ray_tpu.serve.disagg import unpack_kv_spans
        assert len(unpack_kv_spans(out["payload"])) == 4
    finally:
        prefill.engine.stop()


def test_summary_publisher_noop_outside_cluster(tiny):
    """Direct instantiation (no runtime context): the publisher must
    not spawn a thread or publish anything."""
    prefill = _mk_prefill(tiny)
    try:
        pub = prefill._publisher
        assert pub._thread is None and pub.published == 0
    finally:
        prefill.engine.stop()


# --------------------------------------------------------------------------
# span spill eligibility (satellite: ROADMAP item 4 leftover)
# --------------------------------------------------------------------------

class _FakeSpanStore:
    """Duck-typed store for the node-manager span-spill sweep: spans
    with controllable age/pins/sealed state."""

    def __init__(self, spans, now=1000):
        self._spans = dict(spans)           # oid -> info dict
        self._now = now
        self.bytes_in_use = sum(s["data_size"] for s in spans.values())
        self.capacity = 100

    def list_spans(self):
        return list(self._spans)

    def object_info(self, oid):
        return self._spans.get(oid)

    def now_sec(self):
        return self._now

    def stats(self):
        return {"bytes_in_use": self.bytes_in_use,
                "capacity": self.capacity}


def _nm_with(store):
    # node_manager pulls in the native store at import time -> 3.12 only
    from ray_tpu._private.node_manager import NodeManager
    nm = NodeManager.__new__(NodeManager)
    nm.store = store
    nm.spilled = {}
    spilled = []

    def spill_one(oid, _os):
        info = store._spans.pop(oid, None)
        if info is None:
            return None
        spilled.append(oid)
        store.bytes_in_use -= info["data_size"]
        return info["data_size"]

    nm._spill_one = spill_one
    return nm, spilled


def _span(size=10, age=100, pins=0, sealed=True, now=1000):
    return {"data_size": size, "meta_size": 0, "pins": pins,
            "stripe": 0, "ctime_sec": now - age, "is_span": True,
            "sealed": sealed, "flags": 0}


@needs_cluster
def test_idle_unpinned_spans_spill_oldest_first_until_target():
    store = _FakeSpanStore({
        b"old": _span(size=40, age=500),
        b"mid": _span(size=40, age=100),
        b"new": _span(size=40, age=1),      # younger than the idle gate
        b"pin": _span(size=40, age=500, pins=1),
        b"raw": _span(size=40, age=500, sealed=False),
    })
    nm, spilled = _nm_with(store)
    # target high enough that ONE span suffices: oldest goes, rest stay
    n, freed = nm._spill_idle_spans(None, target_bytes=180)
    assert spilled == [b"old"] and n == 1 and freed == 40
    # more pressure: the next eligible span goes; pinned/unsealed/young
    # never do
    n, freed = nm._spill_idle_spans(None, target_bytes=1)
    assert spilled == [b"old", b"mid"]
    assert set(store._spans) == {b"new", b"pin", b"raw"}


@needs_cluster
def test_span_spill_noop_without_spans_or_eligible_rows():
    store = _FakeSpanStore({})
    nm, spilled = _nm_with(store)
    assert nm._spill_idle_spans(None) == (0, 0)
    store2 = _FakeSpanStore({b"pin": _span(pins=2)})
    nm2, spilled2 = _nm_with(store2)
    assert nm2._spill_idle_spans(None) == (0, 0) and spilled2 == []


@needs_cluster
def test_list_spans_filters_spanning_objects():
    pytest.importorskip("ray_tpu._private.object_store")
    import tempfile

    from ray_tpu._private.object_store import ObjectStoreClient
    with tempfile.TemporaryDirectory() as d:
        store = ObjectStoreClient(path=f"{d}/arena", size=8 << 20,
                                  create=True, stripes=2)
        try:
            oid_a = bytes(range(20))
            buf = store.create(oid_a, 128)
            store.seal(oid_a)
            oid_s = bytes(range(1, 21))
            out = store.create_spanning(oid_s, 4096)
            store.seal(oid_s)
            assert store.list_spans() == [oid_s]
            assert oid_a in store.list_objects()
        finally:
            store.close()


# --------------------------------------------------------------------------
# cluster tier (Python >= 3.12): full Serve app, cross-replica routing,
# prefill replica killed mid-export
# --------------------------------------------------------------------------

def _tiny_llm_config():
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig
    return TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=512, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)


@pytest.fixture(scope="module")
def ray_start():
    import ray_tpu
    from ray_tpu import serve
    ctx = ray_tpu.init(num_cpus=4)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


@needs_cluster
def test_disagg_serving_cross_replica_route_and_handoff(ray_start):
    """Acceptance: a request whose prefix was prefilled on a DIFFERENT
    replica is routed by cluster-wide longest match, skips local
    prefill via the KV hand-off, and yields greedy output bit-identical
    to the colocated path."""
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.disagg import build_disagg_app
    from ray_tpu._private.config import cfg
    app = build_disagg_app(
        _tiny_llm_config(), decode_replicas=2, prefill_replicas=1,
        prefill_kwargs=dict(max_len=256, prefill_chunk=8,
                            prefill_budget=32, prefix_cache_slots=4,
                            params_fn=None, seed=0),
        decode_kwargs=dict(n_slots=2, max_len=256, prefill_chunk=8,
                           prefill_budget=32, prefix_cache_slots=4,
                           seed=0))
    serve.run(app, name="llm-disagg")
    h = serve.get_app_handle("llm-disagg")
    prompt = list(range(3, 40))             # 37 tokens: 4 full chunks
    # oracle from a colocated deployment with identical seed/params
    from ray_tpu.inference import LLMDeployment
    co = serve.deployment(LLMDeployment, name="co")
    serve.run(co.bind(_tiny_llm_config(), n_slots=2, max_len=256,
                      prefill_chunk=8, prefill_budget=32, seed=0),
              name="llm-co")
    oracle = list(serve.get_app_handle("llm-co").options(
        stream=True).remote(prompt, max_new_tokens=24))

    # first request (session A) warms exactly one decode replica
    got = list(h.options(stream=True, session_id="sess-A").remote(
        prompt, max_new_tokens=24))
    assert got == oracle
    # wait for that replica's summary to publish
    deadline = time.monotonic() + 3 * cfg.prefix_summary_interval_s + 5
    rows = []
    while time.monotonic() < deadline:
        rows = ray_tpu._get_worker().gcs_call("get_prefix_summaries")
        if any(r.get("fps") for r in rows):
            break
        time.sleep(0.5)
    assert any(r.get("fps") for r in rows), rows
    # a DIFFERENT session with the same prefix must route to the warmed
    # replica by cluster-wide longest match (session hash alone would
    # spread) and still produce the oracle output
    router = h._router
    router.refresh(force=True)
    router._last_summary_refresh = 0.0
    got2 = list(h.options(stream=True, session_id="sess-B").remote(
        prompt, max_new_tokens=24))
    assert got2 == oracle
    serve.delete("llm-co")
    serve.delete("llm-disagg")


@needs_cluster
def test_prefill_replica_killed_mid_export_falls_back(ray_start):
    """Chaos satellite: kill the prefill replica while the decode tier
    depends on it — every stream must still deliver exactly-once tokens
    matching the colocated oracle (fallback ladder rung 4)."""
    from ray_tpu import serve
    from ray_tpu.serve.disagg import build_disagg_app
    from ray_tpu.util.chaos import ServeReplicaKiller
    app = build_disagg_app(
        _tiny_llm_config(), decode_replicas=1, prefill_replicas=1,
        prefill_kwargs=dict(max_len=256, prefill_chunk=8,
                            prefill_budget=32, prefix_cache_slots=4,
                            seed=0),
        decode_kwargs=dict(n_slots=2, max_len=256, prefill_chunk=8,
                           prefill_budget=32, prefix_cache_slots=4,
                           seed=0, handoff_timeout_s=5.0))
    serve.run(app, name="llm-disagg-chaos")
    h = serve.get_app_handle("llm-disagg-chaos")
    prompt = list(range(5, 42))
    oracle = list(h.options(stream=True).remote(prompt,
                                                max_new_tokens=16))
    killer = ServeReplicaKiller("llm-disagg-chaos", "prefill")
    assert killer.kill_one()
    # the very next cold prompt finds the prefill tier dead mid-cycle:
    # the hand-off rung fails and local prefill serves it exactly-once
    prompt2 = list(range(50, 87))
    got = list(h.options(stream=True).remote(prompt2, max_new_tokens=16))
    assert len(got) == 16
    assert got == list(h.options(stream=True).remote(
        prompt2, max_new_tokens=16))
    # original prompt still exact after the chaos
    assert list(h.options(stream=True).remote(
        prompt, max_new_tokens=16)) == oracle
    serve.delete("llm-disagg-chaos")
