"""Continuous-batching inference engine (ray_tpu/inference/): slot-pool
admission/eviction semantics, chunked-prefill correctness, greedy parity
with make_generate_fn, and the one-compile decode contract.

CPU-pinned and cluster-free: the engine is pure JAX + host threading, so
every test here runs in tier-1 (JAX_PLATFORMS=cpu, any Python)."""

import time

import numpy as np
import pytest


@pytest.fixture(scope="module")
def jax_cpu():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return jax


@pytest.fixture(scope="module")
def tiny(jax_cpu):
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig, TransformerLM
    cfg = TransformerConfig(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32,
        param_dtype=jnp.float32, remat=False)
    model = TransformerLM(cfg)
    params = model.init(jax_cpu.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return cfg, model, params


def _engine(model, params, **kw):
    from ray_tpu.inference import EngineConfig, InferenceEngine
    cfg = dict(n_slots=2, max_len=48, prefill_chunk=4, prefill_budget=8)
    cfg.update(kw)
    return InferenceEngine(model, params, EngineConfig(**cfg))


def _run_until(eng, cond, max_steps=300):
    for _ in range(max_steps):
        eng.step()
        if cond():
            return True
    return False


def test_mid_decode_admission(tiny):
    """A request submitted while another decodes starts (first token
    emitted) BEFORE the first finishes — the continuous-batching
    property the fixed-batch path lacks."""
    _, model, params = tiny
    eng = _engine(model, params)
    rng = np.random.RandomState(0)
    a = eng.submit(rng.randint(0, 128, 10), max_new_tokens=20)
    assert _run_until(eng, lambda: a.first_token_t is not None, 20)
    assert a.finish_reason is None
    b = eng.submit(rng.randint(0, 128, 3), max_new_tokens=4)
    assert _run_until(eng, lambda: b.first_token_t is not None, 20)
    # B started while A was still mid-decode
    assert a.finish_reason is None
    assert _run_until(eng, lambda: a.finish_reason and b.finish_reason)
    assert len(a.tokens()) == 20 and len(b.tokens()) == 4


def test_chunked_prefill_matches_one_shot_through_engine(tiny):
    """The same prompt admitted through 4-token prefill chunks and
    through one whole-prompt chunk yields identical greedy tokens."""
    _, model, params = tiny
    rng = np.random.RandomState(1)
    prompt = rng.randint(0, 128, 11)
    outs = []
    for chunk in (4, 16):     # 11 tokens: 3 chunks vs one chunk
        eng = _engine(model, params, prefill_chunk=chunk,
                      prefill_budget=16)
        h = eng.submit(prompt, max_new_tokens=8)
        assert _run_until(eng, lambda: h.finish_reason is not None)
        outs.append(h.tokens())
    assert outs[0] == outs[1]


def test_eviction_reuses_slots(tiny):
    """EOS, max-tokens and cancellation all free the slot for the next
    queued request; a single-slot engine serves a stream of requests."""
    _, model, params = tiny
    eng = _engine(model, params, n_slots=1)
    rng = np.random.RandomState(2)
    prompt = rng.randint(0, 128, 5)

    # max-tokens eviction
    h1 = eng.submit(prompt, max_new_tokens=6)
    assert _run_until(eng, lambda: h1.finish_reason is not None)
    assert h1.finish_reason == "length" and len(h1.tokens()) == 6
    assert eng.stats()["slots_free"] == 1

    # EOS eviction: re-run greedily with eos set to the 3rd token
    h2 = eng.submit(prompt, max_new_tokens=6)
    assert _run_until(eng, lambda: h2.finish_reason is not None)
    third = h2.tokens()[2]
    h3 = eng.submit(prompt, max_new_tokens=6, eos_id=int(third))
    assert _run_until(eng, lambda: h3.finish_reason is not None)
    assert h3.finish_reason == "eos" and len(h3.tokens()) == 3
    assert eng.stats()["slots_free"] == 1

    # cancellation eviction frees the slot for a queued request
    h4 = eng.submit(prompt, max_new_tokens=500)
    h5 = eng.submit(prompt, max_new_tokens=4)     # queued behind h4
    assert _run_until(eng, lambda: h4.first_token_t is not None, 20)
    assert eng.stats()["queue_depth"] == 1
    h4.cancel()
    assert _run_until(eng, lambda: h5.finish_reason is not None)
    assert h4.finish_reason == "cancelled"
    assert len(h5.tokens()) == 4
    assert eng.stats()["slots_free"] == 1 and eng.stats()["queue_depth"] == 0

    # slot-capacity eviction (prompt 5 + 43 decodes fills max_len 48)
    h6 = eng.submit(prompt, max_new_tokens=10_000)
    assert _run_until(eng, lambda: h6.finish_reason is not None)
    assert h6.finish_reason == "length"
    assert len(h6.tokens()) == 48 - len(prompt) + 1


def test_greedy_matches_make_generate_fn(tiny):
    """Greedy tokens through the engine (chunked prefill + slot-pool
    decode + shared sampling) match the one-program generator
    token-for-token."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import make_generate_fn
    from ray_tpu.parallel import MeshConfig, make_mesh
    _, model, params = tiny
    mesh = make_mesh(MeshConfig(data=1, fsdp=1, seq=1, tensor=1),
                     devices=jax.devices()[:1])
    B, P, N = 2, 10, 8
    rng = np.random.RandomState(3)
    prompts = rng.randint(0, 128, size=(B, P)).astype(np.int32)
    _, gen_fn, _ = make_generate_fn(model, mesh, batch=B, prompt_len=P,
                                    max_new_tokens=N)
    want = np.asarray(gen_fn(params, jnp.asarray(prompts),
                             jax.random.PRNGKey(7)))
    eng = _engine(model, params)
    hs = [eng.submit(prompts[i], max_new_tokens=N) for i in range(B)]
    assert _run_until(eng, lambda: all(h.finish_reason for h in hs))
    got = np.stack([h.tokens() for h in hs])
    np.testing.assert_array_equal(got, want)


def test_decode_compiles_exactly_once(tiny):
    """Across admissions, evictions, cancellations and slot reuse the
    decode step never retraces: one XLA program for the engine's life
    (the donated fixed-shape slot pool is the point of the design)."""
    _, model, params = tiny
    eng = _engine(model, params)
    rng = np.random.RandomState(4)
    # staggered mixed-length workload exercising every transition
    hs = []
    for i in range(6):
        hs.append(eng.submit(rng.randint(0, 128, 3 + 5 * (i % 3)),
                             max_new_tokens=3 + 4 * (i % 2)))
        eng.step()
        eng.step()
    hs[3].cancel()
    assert _run_until(eng, lambda: all(h.finish_reason for h in hs))
    assert eng.decode_compile_count == 1
    assert eng.prefill_compile_count == 1
    # the jit caches agree with the trace counters
    assert eng._decode_fn._cache_size() == 1


def test_deadline_expires_queued_request(tiny):
    """A request still queued past its deadline fails with
    finish_reason='deadline' instead of occupying a slot."""
    _, model, params = tiny
    eng = _engine(model, params)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, 128, 8)
    hold = [eng.submit(prompt, max_new_tokens=500) for _ in range(2)]
    for _ in range(4):
        eng.step()                       # admit the holders
    hd = eng.submit(prompt, max_new_tokens=5, deadline_s=0.05)
    time.sleep(0.1)
    eng.step()
    assert hd.finish_reason == "deadline"
    for h in hold:
        h.cancel()
    eng.step()
    assert eng.stats()["slots_free"] == 2


def test_background_loop_streams_tokens(tiny):
    """start()/stop() loop mode: tokens stream to a consumer thread as
    they are generated; stop() fails whatever is still in flight."""
    _, model, params = tiny
    eng = _engine(model, params).start()
    try:
        rng = np.random.RandomState(6)
        h = eng.submit(rng.randint(0, 128, 6), max_new_tokens=12)
        first = h.next(timeout=30)       # streams while decoding
        rest = h.tokens()
        assert isinstance(first, int) and len(rest) == 11
        assert h.finish_reason == "length"
    finally:
        eng.stop()


def test_scheduler_prefill_budget_caps_per_step_tokens(tiny):
    """plan_prefill never spends more than prefill_budget tokens per
    step, and chunks never exceed the static chunk shape."""
    from ray_tpu.inference import Scheduler
    from ray_tpu.inference.scheduler import Request
    sched = Scheduler(n_slots=4, prefill_budget=10, chunk_size=4)
    for n in (13, 9, 2):
        sched.submit(Request(tokens=np.zeros(n, np.int32)))
    seen = []
    for _ in range(6):
        chunks = sched.plan_prefill()
        if not chunks:
            break
        spent = sum(c.length for c in chunks)
        assert spent <= 10
        assert all(c.length <= 4 for c in chunks)
        seen.append(spent)
        for c in chunks:
            if c.is_last:
                sched.prefill_done(c.state, 1, time.monotonic())
            else:
                sched.advance_prefill(c.state, c.length)
    assert sum(seen) == 13 + 9 + 2
