"""Cross-node compiled-DAG channels: stages on different nodes, edge
versions flowing through node-manager-pushed mirrors (reference:
node_manager.proto:442 PushMutableObject,
experimental_mutable_object_provider.h:30, NCCL channels
torch_tensor_nccl_channel.py as the GPU analogue)."""

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode


def test_compiled_dag_two_node_pipeline():
    """A 2-node pipeline DAG: stage actors pinned to DIFFERENT nodes;
    edge versions flow through node-manager-pushed channel mirrors
    (reference: cross-node mutable objects, node_manager.proto:442
    PushMutableObject + experimental_mutable_object_provider.h:30)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import \
        NodeAffinitySchedulingStrategy

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    n2 = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes()
    try:
        head_id = cluster.nodes[0].node_id

        @ray_tpu.remote(num_cpus=0.5)
        class Stage:
            def __init__(self, k):
                self.k = k

            def apply(self, x):
                return x * 10 + self.k

            def node(self):
                return ray_tpu.get_runtime_context()["node_id"]

        s1 = Stage.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                head_id)).remote(1)
        s2 = Stage.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                n2.node_id)).remote(2)
        nodes = ray_tpu.get([s1.node.remote(), s2.node.remote()],
                            timeout=60)
        assert nodes[0] != nodes[1], "stages must live on different nodes"

        with InputNode() as inp:
            out = s2.apply.bind(s1.apply.bind(inp))
        dag = out.experimental_compile()
        try:
            # (x*10+1)*10+2
            assert dag.execute(0, timeout_s=60) == 12
            for i in range(10):
                assert dag.execute(i, timeout_s=60) == (i * 10 + 1) * 10 + 2
        finally:
            dag.teardown()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_compiled_dag_two_node_multi_consumer():
    """One producer feeds consumers on BOTH nodes; the driver (third
    reader) gets its own mirror of the terminal outputs."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import \
        NodeAffinitySchedulingStrategy

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    n2 = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes()
    try:
        head_id = cluster.nodes[0].node_id

        @ray_tpu.remote(num_cpus=0.5)
        class Node:
            def ident(self, x):
                return x

            def add(self, x, k=0):
                return x + k

        prod = Node.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(head_id))).remote()
        c_local = Node.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(head_id))).remote()
        c_remote = Node.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(n2.node_id))).remote()

        with InputNode() as inp:
            mid = prod.ident.bind(inp)
            o1 = c_local.add.bind(mid, k=100)
            o2 = c_remote.add.bind(mid, k=200)
            dag = MultiOutputNode([o1, o2]).experimental_compile()
        try:
            assert dag.execute(5, timeout_s=60) == [105, 205]
            assert dag.execute(7, timeout_s=60) == [107, 207]
        finally:
            dag.teardown()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
