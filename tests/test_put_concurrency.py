"""Off-loop put path: puts run entirely on the calling thread (caller-side
serialization + GIL-free chunked arena copies), so concurrent putters no
longer serialize behind the owner event loop.

Covers the three regressions the redesign could introduce:
  - corruption/loss under 4-thread concurrent large puts (owned-table and
    arena-allocator races),
  - a put issued from inside an actor while the worker's event loop is
    blocked (the old bridge would stall for the full block),
  - spilling under memory pressure still fires from the off-loop path.
"""

import os
import sys
import threading
import time
import zlib

import pytest

if sys.version_info < (3, 12):
    pytest.skip("ray_tpu runtime requires Python >= 3.12 (shm store "
                "zero-copy pins use the PEP 688 buffer protocol)",
                allow_module_level=True)

import numpy as np

import ray_tpu

BLOB = 8 * 1024 * 1024   # large enough for the shm + chunked-copy path


def _checksum(buf) -> int:
    return zlib.adler32(memoryview(buf))


@pytest.fixture
def ray_start():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_concurrent_puts_no_corruption_and_faster_than_serial(ray_start):
    """4 threads put distinct large blobs concurrently: every get must
    hand back byte-identical data, and the concurrent phase must not be
    slower than the same work serialized through one thread (pre-change,
    every put funneled through the one event loop with the GIL held, so
    threads could only queue)."""
    n_threads, per_thread = 4, 4
    blobs = {t: np.full(BLOB, t + 1, np.uint8) for t in range(n_threads)}
    sums = {t: _checksum(blobs[t]) for t in range(n_threads)}

    # serial baseline: same total number of puts from one thread
    t0 = time.perf_counter()
    serial_refs = [ray_tpu.put(blobs[t % n_threads])
                   for t in range(n_threads * per_thread)]
    t_serial = time.perf_counter() - t0
    del serial_refs   # free arena space before the concurrent phase
    time.sleep(0.5)   # let the loop process the frees

    results: dict = {}
    errors: list = []

    def putter(t):
        try:
            results[t] = [ray_tpu.put(blobs[t]) for _ in range(per_thread)]
        except BaseException as e:   # noqa: BLE001 — surfaced to the test
            errors.append(e)

    threads = [threading.Thread(target=putter, args=(t,))
               for t in range(n_threads)]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    t_concurrent = time.perf_counter() - t0
    assert not errors, errors
    assert all(not th.is_alive() for th in threads), "putter thread hung"

    # correctness first: every ref resolves to byte-identical data
    for t, refs in results.items():
        assert len(refs) == per_thread
        for r in refs:
            got = ray_tpu.get(r)
            assert got.nbytes == BLOB
            assert _checksum(got) == sums[t], f"thread {t} blob corrupted"

    # throughput: concurrent must beat the serialized baseline outright on
    # multi-core hosts; on a 1-core host the copies are memory-bound so we
    # only require the absence of a contention collapse
    bound = 1.0 if (os.cpu_count() or 1) >= 2 else 1.5
    assert t_concurrent < t_serial * bound, (
        f"concurrent 4-thread puts took {t_concurrent:.2f}s vs "
        f"{t_serial:.2f}s serialized (bound {bound}x) — puts are "
        "serializing again")


def test_put_from_inside_actor_while_loop_busy(ray_start):
    """A sync actor method puts a large object while the worker's own
    event loop is deliberately blocked: the put must complete without
    waiting for the loop (the old path bridged every put onto it)."""

    @ray_tpu.remote
    class Putter:
        def put_under_blocked_loop(self, block_s: float):
            from ray_tpu._private.worker import global_worker
            loop = global_worker.core.loop
            loop.call_soon_threadsafe(lambda: time.sleep(block_s))
            time.sleep(0.1)   # let the blocker occupy the loop
            arr = np.full(4 * 1024 * 1024, 7, np.uint8)
            t0 = time.perf_counter()
            ref = ray_tpu.put(arr)
            dt = time.perf_counter() - t0
            return ref, dt, _checksum(arr)

    a = Putter.remote()
    block_s = 2.0
    ref, dt, want = ray_tpu.get(
        a.put_under_blocked_loop.remote(block_s), timeout=60)
    assert dt < block_s / 2, (
        f"put inside the actor took {dt:.2f}s while the loop was blocked "
        f"for {block_s}s — it is bridging through the loop again")
    got = ray_tpu.get(ref, timeout=60)
    assert _checksum(got) == want


def test_put_spills_under_pressure_off_loop(tmp_path):
    """Memory-pressure regression for the caller-thread dispatch: filling
    the store past the watermark from a USER thread must still trigger
    the node manager's spill pass (the pressure check + blocking spill
    RPC moved off the loop with the rest of the put path)."""
    spill_uri = f"local://{tmp_path}/put-spill"
    os.environ["RAY_TPU_SPILL_URI"] = spill_uri
    try:
        ray_tpu.init(num_cpus=1, object_store_memory=64 * 1024 * 1024)
        blobs = [np.full(BLOB, i, np.uint8) for i in range(10)]
        refs = [ray_tpu.put(b) for b in blobs]    # 80 MB > 64 MB store
        deadline = time.time() + 30
        spilled = []
        root = str(tmp_path / "put-spill")
        while time.time() < deadline and not spilled:
            spilled = [f for _d, _s, fs in os.walk(root) for f in fs] \
                if os.path.isdir(root) else []
            time.sleep(0.5)
        assert spilled, "off-loop puts never triggered a spill pass"
        # every object still readable (restore path) and uncorrupted
        for i, r in enumerate(refs):
            got = ray_tpu.get(r, timeout=60)
            assert got.nbytes == BLOB
            assert _checksum(got) == _checksum(blobs[i])
    finally:
        os.environ.pop("RAY_TPU_SPILL_URI", None)
        ray_tpu.shutdown()
