"""External storage plane: URI-addressed spilling and checkpoints
(reference: python/ray/_private/external_storage.py:72 filesystem-or-S3
spill, python/ray/train/_internal/storage.py StorageContext). fsspec's
memory:// backend plays the remote filesystem — the code path is the one
gs://bucket takes on a real pod."""

import os

import numpy as np
import pytest

from ray_tpu.util import storage


def test_storage_uri_round_trip_memory_fs():
    uri = "memory://bucket/a/b/data.bin"
    storage.write_bytes(uri, b"hello-remote")
    assert storage.exists(uri)
    assert storage.read_bytes(uri) == b"hello-remote"
    assert "data.bin" in storage.listdir("memory://bucket/a/b")
    assert storage.is_remote(uri) and not storage.is_remote("/tmp/x")
    assert storage.delete(uri)
    assert not storage.exists(uri)


def test_storage_dir_round_trip(tmp_path):
    src = tmp_path / "src"
    (src / "sub").mkdir(parents=True)
    (src / "top.txt").write_bytes(b"t")
    (src / "sub" / "nested.txt").write_bytes(b"n")
    storage.upload_dir(str(src), "memory://bucket/exp1")
    dst = tmp_path / "dst"
    storage.download_dir("memory://bucket/exp1", str(dst))
    assert (dst / "top.txt").read_bytes() == b"t"
    assert (dst / "sub" / "nested.txt").read_bytes() == b"n"


def test_file_uri_single_slash_is_absolute(tmp_path, monkeypatch):
    """RFC-8089 single-slash file:/x must resolve to the absolute path,
    never a cwd-relative 'file:' directory (round-4 verdict weak #4 —
    spill blobs were silently committed under a literal 'file:' dir)."""
    monkeypatch.chdir(tmp_path)
    for form in (f"file:{tmp_path}/one/x.bin",
                 f"file://{tmp_path}/one/x.bin"):
        storage.write_bytes(form, b"abs")
        assert (tmp_path / "one" / "x.bin").read_bytes() == b"abs"
        assert not (tmp_path / "file:").exists()
        (tmp_path / "one" / "x.bin").unlink()
    assert not storage.is_remote("file:/tmp/x")
    assert storage.join("file:/a/b", "c") == "/a/b/c"


def test_validate_root_rejects_relative():
    with pytest.raises(ValueError, match="relative"):
        storage.validate_root("some/rel/path", "spill")
    # absolute locals and remote URIs pass through
    assert storage.validate_root("/abs/path") == "/abs/path"
    assert storage.validate_root("file:/abs/p") == "file:/abs/p"
    assert storage.validate_root("gs://bucket/x") == "gs://bucket/x"


def test_checkpoint_repersist_from_remote():
    """persist() of a checkpoint that already lives at a remote URI must
    materialize before tarring (tar.add reads local paths only)."""
    from ray_tpu.train import Checkpoint
    ck = Checkpoint.from_dict({"w": 7})
    uri1 = ck.persist("memory://ckpts/src", "c1")
    ck2 = Checkpoint(path=uri1)
    uri2 = ck2.persist("memory://ckpts/dst", "c2")
    assert Checkpoint(path=uri2).to_dict()["w"] == 7


def test_checkpoint_persist_restore_uri(tmp_path):
    from ray_tpu.train import Checkpoint
    ck = Checkpoint.from_dict({"w": np.arange(5), "step": 3})
    uri = ck.persist("memory://ckpts/run1", "checkpoint_000001")
    assert uri.startswith("memory://")
    restored = Checkpoint(path=uri).to_dict()
    assert restored["step"] == 3
    assert np.array_equal(restored["w"], np.arange(5))


def test_checkpoint_manager_retention_on_uri():
    from ray_tpu.train import Checkpoint
    from ray_tpu.train.checkpoint import CheckpointManager
    mgr = CheckpointManager("memory://ckpts/run2", num_to_keep=2,
                            score_attribute="acc", order="max")
    for i, acc in enumerate([0.1, 0.9, 0.5]):
        mgr.register(Checkpoint.from_dict({"i": i}), {"acc": acc})
    assert len(mgr.checkpoints) == 2
    best = mgr.best_checkpoint()
    assert best.metrics["acc"] == 0.9
    assert best.to_dict()["i"] == 1


def test_dataset_write_to_uri(tmp_path):
    """Distributed writers go through the storage plane: write_parquet
    to a local:// URI (same fsspec path as gs://) and read it back."""
    import ray_tpu
    import ray_tpu.data as rd
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        out = f"local://{tmp_path}/ds-out"
        rd.range(100, parallelism=4).write_parquet(out)
        parts = sorted((tmp_path / "ds-out").glob("*.parquet"))
        assert len(parts) == 4
        back = rd.read_parquet([str(p) for p in parts])
        assert sorted(r["id"] for r in back.take_all()) == list(range(100))
    finally:
        ray_tpu.shutdown()


def test_external_store_client_round_trip():
    """GCS store-client external impl: snapshot + address on a remote
    URI (reference: redis_store_client.h — off-node GCS state so a
    replacement GCS can restart elsewhere)."""
    from ray_tpu._private.store_client import (ExternalStoreClient,
                                               FileStoreClient,
                                               store_client_for)
    sc = store_client_for("memory://gcs-ft/clusterA")
    assert isinstance(sc, ExternalStoreClient)
    assert sc.load_snapshot() is None and sc.read_address() is None
    sc.save_snapshot(b"state-v1")
    sc.write_address("tcp:10.0.0.5:6379")
    sc2 = store_client_for("memory://gcs-ft/clusterA")
    assert sc2.load_snapshot() == b"state-v1"
    assert sc2.read_address() == "tcp:10.0.0.5:6379"
    assert isinstance(store_client_for("/tmp/x.bin"), FileStoreClient)


def test_spill_to_uri_and_restore(tmp_path):
    """Node-manager spilling through the URI backend: fill a small store
    past the watermark, assert objects land under the spill URI and come
    back transparently on get(). Uses fsspec's local:// scheme (each
    node manager is its own process, so memory:// would not be
    observable here) — local:// goes through the identical fsspec
    write/read code path as gs://, only the filesystem class differs."""
    import ray_tpu

    spill_uri = f"local://{tmp_path}/remote-spill"
    os.environ["RAY_TPU_SPILL_URI"] = spill_uri
    try:
        ray_tpu.init(num_cpus=1,
                     object_store_memory=64 * 1024 * 1024)
        blobs = [np.ones(8 * 1024 * 1024, np.uint8) * i
                 for i in range(10)]
        refs = [ray_tpu.put(b) for b in blobs]    # 80 MB > 64 MB store
        import time
        deadline = time.time() + 30
        spilled_files = []
        while time.time() < deadline:
            root = str(tmp_path / "remote-spill")
            spilled_files = [f for d, _, fs in os.walk(root) for f in fs] \
                if os.path.isdir(root) else []
            if spilled_files:
                break
            time.sleep(0.5)
        assert spilled_files, "nothing spilled to the URI target"
        # every object still readable (restore path)
        for i, r in enumerate(refs):
            got = ray_tpu.get(r)
            assert got[0] == i and got.nbytes == blobs[i].nbytes
    finally:
        os.environ.pop("RAY_TPU_SPILL_URI", None)
        ray_tpu.shutdown()
