"""Scratch: measure train-step time / MFU variants on the real chip."""
import sys, time, functools
import jax, jax.numpy as jnp
import optax

from ray_tpu.models import MODEL_REGISTRY, TransformerLM, count_params
from ray_tpu.parallel import MeshConfig, make_mesh
from ray_tpu.parallel.train_step import make_train_fns

PEAK = 197e12  # v5e bf16


def model_flops_per_step(cfg, B, L):
    # params excluding embeddings (matmul flops = 6*N*T), plus embed/unembed
    n_layer = cfg.n_layers * (
        cfg.d_model * cfg.d_model * 2                      # q, o
        + cfg.d_model * (cfg.n_kv_heads * cfg.head_dim) * 2  # k, v
        + 3 * cfg.d_model * cfg.d_ff)
    n_unembed = cfg.d_model * cfg.vocab_size
    T = B * L
    matmul = 6 * (n_layer + n_unembed) * T
    attn = cfg.n_layers * 4 * B * L * L * cfg.d_model * 3  # fwd*2mm + bwd
    if True:  # causal => half
        attn = attn / 2
    return matmul + attn


def run(name, B, L, steps=20, remat=None, attention_impl=None, warm=3):
    cfg = MODEL_REGISTRY[name]
    kw = {}
    if remat is not None:
        kw["remat"] = remat
    if attention_impl is not None:
        kw["attention_impl"] = attention_impl
    if kw:
        import dataclasses
        cfg = dataclasses.replace(cfg, **kw)
    model = TransformerLM(cfg)
    mesh = make_mesh(MeshConfig(data=1, fsdp=1), devices=jax.devices()[:1])
    init_fn, step_fn, _ = make_train_fns(model, optax.adamw(3e-4), mesh,
                                         batch_shape=(B, L + 1))
    state = init_fn(jax.random.PRNGKey(0))
    n_params = count_params(state.params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, L + 1), 0,
                                cfg.vocab_size)
    for _ in range(warm):
        state, m = step_fn(state, tokens)
    float(m["loss"])  # force full sync via host transfer
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step_fn(state, tokens)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / steps
    fl = model_flops_per_step(cfg, B, L)
    mfu = fl / dt / PEAK
    print(f"{name} B={B} L={L} remat={remat} attn={attention_impl}: "
          f"{dt*1e3:.1f} ms/step  {B*L/dt:.0f} tok/s  "
          f"params={n_params/1e6:.0f}M  MFU={mfu*100:.1f}%", flush=True)
    return dt, mfu


if __name__ == "__main__":
    for spec in sys.argv[1:]:
        # name:B:L[:remat=0][:attn=flash]
        parts = spec.split(":")
        name, B, L = parts[0], int(parts[1]), int(parts[2])
        kw = {}
        for p in parts[3:]:
            k, v = p.split("=")
            if k == "remat":
                kw["remat"] = bool(int(v))
            elif k == "attn":
                kw["attention_impl"] = v
            elif k == "steps":
                kw["steps"] = int(v)
        run(name, B, L, **kw)
